"""Native C++ kernel tests: equivalence with the numpy paths and the
mathematical properties of the space-filling curves."""

import itertools

import numpy as np
import pytest

from ramses_tpu import native
from ramses_tpu.amr import keys as kmod
from ramses_tpu.amr.hilbert import _hilbert_numpy, hilbert_key


def _grid(nbits, ndim):
    n = 1 << nbits
    ax = np.arange(n, dtype=np.int64)
    g = np.meshgrid(*([ax] * ndim), indexing="ij")
    return np.stack([x.ravel() for x in g], axis=1)


@pytest.fixture(scope="module")
def has_native():
    return native.lib() is not None


def test_native_builds(has_native):
    assert has_native, "g++ present but native library failed to build"


@pytest.mark.parametrize("ndim", [2, 3])
def test_morton_native_matches_numpy(has_native, ndim):
    if not has_native:
        pytest.skip("no native lib")
    rng = np.random.default_rng(0)
    og = rng.integers(0, 1 << 20 if ndim == 2 else 1 << 15,
                      size=(5000, ndim))
    nat = native.morton_encode(og, ndim)
    ref = kmod.encode(og[:10], ndim)   # small → numpy path
    assert np.array_equal(nat[:10], ref)


@pytest.mark.parametrize("ndim,nbits", [(2, 5), (3, 3)])
def test_hilbert_native_matches_numpy(has_native, ndim, nbits):
    if not has_native:
        pytest.skip("no native lib")
    og = _grid(nbits, ndim)
    nat = native.hilbert_encode(og, ndim, nbits)
    ref = _hilbert_numpy(og, ndim, nbits)
    assert np.array_equal(nat, ref)


@pytest.mark.parametrize("ndim,nbits", [(2, 4), (3, 3)])
def test_hilbert_bijective_and_unit_stride(ndim, nbits):
    """Keys are a bijection onto [0, 2^(ndim·nbits)) and consecutive keys
    are grid neighbours (THE Hilbert property)."""
    og = _grid(nbits, ndim)
    keys = hilbert_key(og, ndim, nbits)
    nk = 1 << (ndim * nbits)
    assert len(np.unique(keys)) == len(keys) == nk
    assert keys.min() == 0 and keys.max() == nk - 1
    order = np.argsort(keys)
    path = og[order]
    steps = np.abs(np.diff(path, axis=0))
    assert np.all(steps.sum(axis=1) == 1), "curve is not unit-stride"


def test_hilbert_locality_beats_morton():
    """Mean |Δposition| between key-consecutive cells: Hilbert = 1 by
    construction, Morton jumps across the box."""
    og = _grid(4, 2)
    hk = hilbert_key(og, 2, 4)
    mk = kmod.encode(og, 2)
    jump_h = np.abs(np.diff(og[np.argsort(hk)], axis=0)).sum(1).mean()
    jump_m = np.abs(np.diff(og[np.argsort(mk)], axis=0)).sum(1).mean()
    assert jump_h == 1.0
    assert jump_m > 1.5


def test_lookup_native_matches_numpy(has_native):
    if not has_native:
        pytest.skip("no native lib")
    rng = np.random.default_rng(3)
    keys = np.unique(rng.integers(0, 1 << 40, size=8000))
    q = np.concatenate([rng.choice(keys, 3000),
                        rng.integers(0, 1 << 40, size=3000)])
    nat = native.lookup_sorted(keys, q)
    pos = np.searchsorted(keys, q)
    pos = np.clip(pos, 0, len(keys) - 1)
    ref = np.where(keys[pos] == q, pos, -1)
    assert np.array_equal(nat, ref)


def test_neighbor_lookup_periodic(has_native):
    if not has_native:
        pytest.skip("no native lib")
    from ramses_tpu.amr.tree import Octree
    t = Octree.base(2, 4, 4)          # full 8x8 oct grid at level 4
    lev = t.levels[4]
    offs = np.array(list(itertools.product((-1, 0, 1), repeat=2)),
                    dtype=np.int64)
    out = native.neighbor_lookup(lev.keys, lev.og, 2, 8, offs)
    # complete periodic level: every neighbour exists
    assert (out >= 0).all()
    # cross-check one oct against Octree.lookup
    i = 13
    for k, off in enumerate(offs):
        cc = np.mod(lev.og[i] + off, 8)[None, :]
        assert out[i, k] == t.lookup(4, cc)[0]


def test_fallback_env(monkeypatch):
    monkeypatch.setenv("RAMSES_TPU_NATIVE", "0")
    assert native.lib() is None
    og = _grid(3, 2)
    keys = hilbert_key(og, 2, 3)      # numpy fallback still works
    assert len(np.unique(keys)) == 64
