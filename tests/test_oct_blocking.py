"""Gather-fused blocked oct sweep (amr/maps.py BlockMaps +
amr/kernels.py tile_sweep + the hierarchy wiring).

The oracle is the same invariance trick the rest of the AMR suite
uses: the blocked Morton-tile decomposition is a *layout* change, so
``oct_blocking=.true.`` must reproduce the per-oct stencil path
bitwise — same conserved state, same refinement flags, same trees —
on every configuration it is eligible for.  Map-level tests
cross-check the gathered tile values against the tree geometry
directly, and the incremental-rebuild contract (unchanged tiles are
never rebuilt) is pinned on real regrids.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from ramses_tpu.amr import maps as mapmod
from ramses_tpu.amr.hierarchy import AmrSim
from ramses_tpu.amr.tree import cell_offsets
from ramses_tpu.config import params_from_dict, params_from_string

SEDOV3D = """
&RUN_PARAMS
hydro=.true.
/
&AMR_PARAMS
levelmin={lmin}
levelmax={lmax}
boxlen=1.0
oct_blocking={blk}
/
&INIT_PARAMS
nregion=2
region_type(1)='square'
region_type(2)='point'
x_center=0.5,0.5
y_center=0.5,0.5
z_center=0.5,0.5
length_x=10.0,1.0
length_y=10.0,1.0
length_z=10.0,1.0
d_region=1.0,0.0
p_region=1e-5,0.1
/
&HYDRO_PARAMS
gamma=1.4
courant_factor=0.7
slope_type=1
riemann='{riemann}'
/
&REFINE_PARAMS
err_grad_p=0.1
/
"""


def _sedov(blk, lmin=4, lmax=5, ndim=3, dtype=None, riemann="llf"):
    p = params_from_string(
        SEDOV3D.format(lmin=lmin, lmax=lmax, blk=blk, riemann=riemann),
        ndim=ndim)
    return AmrSim(p, dtype=dtype or jnp.float64)


def _check_maps(sim):
    """Cross-check BlockMaps against the tree: every gathered slot must
    resolve to the cell its Morton key names, an interp row for its
    missing-father key, or the zero trash row."""
    from ramses_tpu.amr import keys as kmod
    nd = sim.tree.ndim
    for l, b in sim.blocks.items():
        lev = sim.tree.levels[l]
        # fabricate a cell field = its own BC-mapped Morton key; interp
        # rows get a distinct marker family, trash row a third
        u = np.full((b.ncell_pad, 1), -1.0)
        co = cell_offsets(nd)
        gc = (2 * lev.og[:, None, :] + co[None, :, :]).reshape(-1, nd)
        u[:len(gc), 0] = kmod.encode(gc, nd).astype(float)
        iv = np.full((b.ni_pad, 1), -2.0)
        iv[:b.ni, 0] = -1000.0 - np.arange(b.ni)
        src = np.concatenate([u, iv, [[-3.0]]], axis=0)
        got = src[np.asarray(b.tile_src), 0][:b.ntile]
        ck = b.slot_ckey
        exists = (sim.tree.lookup_keys(l, (ck >> nd).reshape(-1)) >= 0) \
            .reshape(ck.shape)
        assert np.array_equal(got[exists], ck[exists].astype(float)), \
            f"level {l}: existing-cell slots"
        missing = got[~exists]
        assert ((missing <= -1000.0) | (missing == -3.0)).all(), \
            f"level {l}: missing slots must be interp or trash"
        if b.ni:
            # an interp slot's row index must equal the rank of its key
            rows = (-(missing + 1000.0)).astype(int)
            onrow = missing <= -1000.0
            uniq = np.unique(ck[~exists][onrow])
            assert np.array_equal(
                rows[onrow], np.searchsorted(uniq, ck[~exists][onrow])), \
                f"level {l}: interp row ranks"
        # scatter maps invert the layout: flat cell order <-> tile slots
        nreal = lev.noct * (1 << nd)
        flat = np.arange(b.ntile_pad * (1 << (nd * (b.shift + 1)))) \
            .reshape(b.ntile_pad, -1)
        vals = flat[np.asarray(b.cell_tile)[:nreal],
                    np.asarray(b.cell_slot)[:nreal]]
        assert len(np.unique(vals)) == nreal, f"level {l}: cell scatter"


def test_block_maps_consistency():
    sim = _sedov(".true.")
    assert sim.blocks, "no blocked levels built"
    _check_maps(sim)


def test_unchanged_regrid_rebuilds_zero_blocks():
    """Steady-state regrid contract: tree untouched => every per-block
    map is reused, zero rebuilt."""
    sim = _sedov(".true.")
    assert sim.block_stats["blocks_total"] > 0
    sim.regrid()
    assert sim.block_stats["blocks_total"] > 0
    assert sim.block_stats["blocks_rebuilt"] == 0, sim.block_stats


def test_incremental_rebuild_matches_fresh():
    """After a real regrid, the prev-reusing build must equal a fresh
    build field-for-field."""
    sim = _sedov(".true.")
    for _ in range(2):
        sim.step_coarse(sim.coarse_dt())
    sim.regrid()
    shift = int(sim.params.amr.oct_block_shift)
    for l, b in sim.blocks.items():
        fresh = mapmod.build_block_maps(
            sim.tree, l, sim.bc_kinds, shift=shift,
            noct_pad=sim.maps[l].noct_pad)
        assert fresh.blocks_rebuilt == fresh.ntile
        for f in ("tile_src", "tile_ok", "interp_cell", "interp_nb",
                  "interp_sgn", "cell_tile", "cell_slot", "oct_tile",
                  "oct_slot", "tile_key", "slot_ckey"):
            a, c = getattr(b, f), getattr(fresh, f)
            assert np.array_equal(np.asarray(a), np.asarray(c)), (l, f)
        if b.tile_vsgn is not None:
            assert np.array_equal(b.tile_vsgn, fresh.tile_vsgn), l


def _parity(lmin, lmax, ndim, dtype=None, riemann="llf", nstep=2,
            with_regrid=True):
    sims = {}
    for blk in (".true.", ".false."):
        s = _sedov(blk, lmin=lmin, lmax=lmax, ndim=ndim, dtype=dtype,
                   riemann=riemann)
        if blk == ".true.":
            assert s.blocks, "no blocked levels built"
        else:
            assert not s.blocks
        for _ in range(nstep):
            s.step_coarse(s.coarse_dt())
        if with_regrid:
            s.regrid()
            s.step_coarse(s.coarse_dt())
        sims[blk] = s
    sa, sb = sims[".true."], sims[".false."]
    assert sorted(sa.levels()) == sorted(sb.levels())
    for l in sa.levels():
        # identical trees (flags parity, incl. tile_refine_flags)
        assert np.array_equal(np.asarray(sa.tree.levels[l].keys),
                              np.asarray(sb.tree.levels[l].keys)), l
        # FULL padded arrays: pad rows must stay bitwise too (the
        # sharded-vs-single suite compares them)
        ua, ub = np.asarray(sa.u[l]), np.asarray(sb.u[l])
        assert np.array_equal(ua, ub), \
            f"level {l}: maxdiff={np.abs(ua - ub).max()}"


def test_blocked_parity_3d_sedov():
    """Blocked vs per-oct stencil path: bitwise-identical state and
    trees through steps + a regrid (XLA tile fallback on CPU)."""
    _parity(4, 5, 3)


def test_blocked_parity_2d_sedov():
    _parity(4, 6, 2)


@pytest.mark.slow
def test_blocked_parity_3d_hllc_two_level_span():
    _parity(4, 6, 3, riemann="hllc")


@pytest.mark.slow
def test_blocked_parity_gravity():
    """Self-gravity run: want_flux path (phi mass-flux planes) must also
    be bitwise under blocking."""
    def blob(blk):
        groups = {
            "run_params": {"hydro": True, "poisson": True},
            "amr_params": {"levelmin": 4, "levelmax": 5, "boxlen": 1.0,
                           "oct_blocking": blk},
            "init_params": {"nregion": 2,
                            "region_type": ["square", "square"],
                            "x_center": [0.5, 0.5],
                            "y_center": [0.5, 0.5],
                            "z_center": [0.5, 0.5],
                            "length_x": [10.0, 0.25],
                            "length_y": [10.0, 0.25],
                            "length_z": [10.0, 0.25],
                            "exp_region": [10.0, 2.0],
                            "d_region": [1.0, 50.0],
                            "p_region": [10.0, 10.0]},
            "hydro_params": {"gamma": 1.4, "courant_factor": 0.5,
                             "riemann": "hllc"},
            "refine_params": {"err_grad_d": 0.2},
        }
        return AmrSim(params_from_dict(groups, ndim=3),
                      dtype=jnp.float64)

    sa, sb = blob(True), blob(False)
    assert sa.blocks and not sb.blocks
    for s in (sa, sb):
        for _ in range(2):
            s.step_coarse(s.coarse_dt())
    for l in sa.levels():
        nreal = sa.tree.levels[l].noct * 8
        assert np.array_equal(np.asarray(sa.u[l])[:nreal],
                              np.asarray(sb.u[l])[:nreal]), l


@pytest.mark.slow
def test_blocked_parity_pallas_interpret(monkeypatch):
    """The real Pallas tile kernel (interpret mode) vs the per-oct
    reference path: bitwise-identical f32 state.  Both sims run under
    FORCE_INTERPRET so the only difference is blocked vs stencil."""
    from ramses_tpu.hydro import pallas_oct
    monkeypatch.setattr(pallas_oct, "FORCE_INTERPRET", True)
    jax.clear_caches()                  # force a fresh branch choice
    try:
        sims = {}
        for blk in (".true.", ".false."):
            s = _sedov(blk, dtype=jnp.float32)
            if blk == ".true.":
                for l, b in s.blocks.items():
                    assert pallas_oct.tile_available(
                        s.cfg, b.ntile_pad, jnp.float32), (l, b.ntile_pad)
            for _ in range(2):
                s.step_coarse(s.coarse_dt())
            sims[blk] = s
        sa, sb = sims[".true."], sims[".false."]
        for l in sa.levels():
            nreal = sa.tree.levels[l].noct * 8
            assert np.array_equal(np.asarray(sa.u[l])[:nreal],
                                  np.asarray(sb.u[l])[:nreal]), l
    finally:
        jax.clear_caches()              # do not leak into other tests
