"""1D shock-tube validation vs the exact Riemann solution.

Mirrors the reference's sod-tube test (``tests/hydro/sod-tube``): same
initial states, end time, and resolution class; the oracle here is the
analytic solution (their ``sod-tube-ana.dat``) with an L1 gate.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from ramses_tpu.config import params_from_string
from ramses_tpu.driver import Simulation
from ramses_tpu.grid.uniform import totals
from tests.exact_riemann import exact_riemann

SOD = """
&RUN_PARAMS
hydro=.true.
/
&AMR_PARAMS
levelmin={lmin}
levelmax={lmin}
boxlen=1.0
/
&BOUNDARY_PARAMS
nboundary=2
ibound_min=-1,+1
ibound_max=-1,+1
bound_type= 2, 2
/
&INIT_PARAMS
nregion=2
region_type(1)='square'
region_type(2)='square'
x_center=0.25,0.75
length_x=0.5,0.5
d_region=1.0,0.125
u_region=0.0,0.0
p_region=1.0,0.1
/
&OUTPUT_PARAMS
noutput=1
tout=0.245
/
&HYDRO_PARAMS
gamma=1.4
courant_factor=0.8
slope_type={slope}
riemann='{riemann}'
/
"""



pytestmark = pytest.mark.smoke

def run_sod(riemann: str, lmin: int = 7, slope: int = 2):
    p = params_from_string(SOD.format(lmin=lmin, slope=slope,
                                      riemann=riemann), ndim=1)
    sim = Simulation(p, dtype=jnp.float64)
    sim.evolve()
    return sim


@pytest.mark.parametrize("riemann", ["hllc", "llf", "hll", "exact",
                                     "acoustic"])
def test_sod_l1(riemann):
    sim = run_sod(riemann)
    n = sim.grid.shape[0]
    x = (np.arange(n) + 0.5) / n
    rho_a, u_a, p_a = exact_riemann(1.0, 0.0, 1.0, 0.125, 0.0, 0.1,
                                    1.4, x, sim.state.t, x0=0.5)
    rho = np.asarray(sim.state.u[0])
    l1 = np.mean(np.abs(rho - rho_a))
    # second-order scheme at 128 cells: L1(rho) ~ 5e-3; LLF is more
    # diffusive.  Gates chosen ~2x above measured so regressions trip them.
    gate = {"llf": 2.5e-2, "acoustic": 1.6e-2}.get(riemann, 1.6e-2)
    assert l1 < gate, f"L1={l1:.3e} for {riemann}"
    assert sim.state.t == pytest.approx(0.245, rel=1e-10)


def test_sod_velocity_pressure():
    sim = run_sod("hllc")
    cfg = sim.cfg
    u = np.asarray(sim.state.u)
    n = sim.grid.shape[0]
    x = (np.arange(n) + 0.5) / n
    rho_a, u_a, p_a = exact_riemann(1.0, 0.0, 1.0, 0.125, 0.0, 0.1,
                                    1.4, x, sim.state.t, x0=0.5)
    vel = u[1] / u[0]
    press = (cfg.gamma - 1.0) * (u[2] - 0.5 * u[1] ** 2 / u[0])
    assert np.mean(np.abs(vel - u_a)) < 2e-2
    assert np.mean(np.abs(press - p_a)) < 1e-2


def test_conservation_periodic():
    """Mass/momentum/energy exactly conserved on a periodic box."""
    p = params_from_string(SOD.format(lmin=6, slope=2, riemann="hllc"),
                           ndim=1)
    p.boundary.nboundary = 0  # periodic
    sim = Simulation(p, dtype=jnp.float64)
    tot0 = totals(sim.state.u, sim.cfg, sim.grid.dx)
    sim.evolve()
    tot1 = totals(sim.state.u, sim.cfg, sim.grid.dx)
    assert float(tot1["mass"]) == pytest.approx(float(tot0["mass"]),
                                                rel=1e-13)
    assert float(tot1["energy"]) == pytest.approx(float(tot0["energy"]),
                                                  rel=1e-12)
