"""End-to-end driver test: dense box forms stars during an evolve run."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.config import params_from_dict
from ramses_tpu.driver import Simulation


def test_driver_forms_stars():
    groups = {
        "run_params": {"hydro": True, "pic": True},
        "amr_params": {"levelmin": 3, "levelmax": 3, "boxlen": 1.0,
                       "npartmax": 50000},
        "init_params": {"nregion": 1, "region_type": ["square"],
                        "x_center": [0.5], "y_center": [0.5],
                        "z_center": [0.5],
                        "length_x": [10.0], "length_y": [10.0],
                        "length_z": [10.0], "exp_region": [10.0],
                        "d_region": [100.0], "p_region": [10.0]},
        "hydro_params": {"gamma": 1.4, "courant_factor": 0.5,
                         "riemann": "hllc"},
        "sf_params": {"n_star": 1.0, "t_star": 0.05},
        "feedback_params": {"eta_sn": 0.1, "t_sne": 1e-6},
        "units_params": {"units_density": 1.66e-24,
                         "units_time": 3.156e13,
                         "units_length": 3.086e18},
        "output_params": {"noutput": 1, "tout": [0.02], "tend": 0.02},
    }
    p = params_from_dict(groups, ndim=3)
    sim = Simulation(p, dtype=jnp.float64)
    m0 = float(np.asarray(sim.state.u)[0].sum()) * sim.dx ** 3
    sim.evolve(chunk=4)
    act = np.asarray(sim.state.p.active)
    nstars = int(act.sum())
    assert nstars > 0, "no stars formed in a 100x-threshold box"
    m_star = float(np.asarray(sim.state.p.m)[act].sum())
    m_gas = float(np.asarray(sim.state.u)[0].sum()) * sim.dx ** 3
    # mass budget closes (SN mass returns included)
    assert np.isclose(m_gas + m_star, m0, rtol=1e-10)
    assert np.all(np.isfinite(np.asarray(sim.state.u)))
