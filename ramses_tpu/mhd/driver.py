"""MHD simulation driver: region ICs, time loop, snapshots.

The ``SOLVER=mhd`` build of the reference selected at compile time via
VPATH shadowing (SURVEY.md §1 L0); here it is a runtime solver choice.
Region ICs follow ``mhd/init_flow_fine.f90:475-596``: square regions set
[d, u, v, w, P] plus a uniform field [A_region, B_region, C_region]
(both faces, ``:529-532``).
"""

from __future__ import annotations

import time
from contextlib import nullcontext
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ramses_tpu.config import Params
from ramses_tpu.grid import boundary as bmod
from ramses_tpu.mhd import core, uniform as mu
from ramses_tpu.mhd.core import IBX, IP, MhdStatic, NCOMP
from ramses_tpu.telemetry import make_telemetry, sim_run_info
from ramses_tpu.telemetry import screen as telemetry_screen


def _region_mask(x, k, init, ndim):
    centers = [init.x_center, init.y_center, init.z_center]
    lengths = [init.length_x, init.length_y, init.length_z]
    en = float(init.exp_region[k])
    if en < 10.0:
        r = sum((2.0 * np.abs(x[d] - centers[d][k]) / lengths[d][k]) ** en
                for d in range(ndim)) ** (1.0 / en)
    else:
        r = np.maximum.reduce(
            [2.0 * np.abs(x[d] - centers[d][k]) / lengths[d][k]
             for d in range(ndim)])
    return r < 1.0


def mhd_condinit(shape, dx: float, p: Params, cfg: MhdStatic):
    """(u [nvar, *sp], bf [3, *sp]): conservative cell state + staggered
    faces from &INIT_PARAMS regions (uniform B per region)."""
    from ramses_tpu import patch
    if patch.hook("condinit") is not None:
        import warnings
        warnings.warn(
            "patch condinit hook is not applied to the MHD solver: MHD "
            "ICs need divergence-free STAGGERED face fields, which the "
            "primitive-state hook cannot provide; using &INIT_PARAMS "
            "regions instead")
    init = p.init
    ndim = cfg.ndim
    axes_c = [(np.arange(n) + 0.5) * dx for n in shape]

    q = np.zeros((cfg.nvar,) + tuple(shape))
    q[0] = cfg.smallr
    q[IP] = cfg.smallr * cfg.smallc ** 2 / cfg.gamma
    vels = [init.u_region, init.v_region, init.w_region]
    bvals = [init.A_region, init.B_region, init.C_region]

    # staggered faces: each cell's LOW face takes the owning cell's region
    # value — exactly how the reference seeds both face fields from the
    # cell's region (``mhd/init_flow_fine.f90:529-532``); evaluating at
    # face centres would leave faces that sit exactly on a region border
    # (including the domain edge) unset
    bf = np.zeros((NCOMP,) + tuple(shape))
    xc = np.meshgrid(*axes_c, indexing="ij")
    for k in range(init.nregion):
        if str(init.region_type[k]).strip() != "square":
            raise NotImplementedError("mhd ICs: square regions only")
        m = _region_mask(xc, k, init, ndim)
        q[0][m] = init.d_region[k]
        for c in range(NCOMP):
            q[1 + c][m] = vels[c][k]
            bf[c][m] = bvals[c][k]
        q[IP][m] = init.p_region[k]

    for c in range(NCOMP):
        if c < ndim:
            q[IBX + c] = 0.5 * (bf[c] + np.roll(bf[c], -1, axis=c))
        else:
            q[IBX + c] = bf[c]
    u = np.asarray(core.prim_to_cons(jnp.asarray(q), cfg))
    return u, bf


class MhdSimulation:
    """Uniform-grid MHD run (CT solver, SURVEY.md §7 stage 7)."""

    def __init__(self, params: Params, dtype=jnp.float64):
        self.params = params
        self.cfg = MhdStatic.from_params(params)
        base = [params.amr.nx, params.amr.ny, params.amr.nz][:params.ndim]
        if any(b != 1 for b in base):
            # this solver family builds cubic grids; only the hydro
            # uniform driver supports non-cubic coarse boxes
            raise NotImplementedError(
                f"MHD requires nx=ny=nz=1 (got {base})")
        lmin = params.amr.levelmin
        n = 2 ** lmin
        shape = tuple([n] * params.ndim)
        self.dx = params.amr.boxlen / n
        spec = bmod.BoundarySpec.from_params(params)
        bc_kinds = tuple((f[0].kind, f[1].kind) for f in spec.faces)
        for lo, hi in bc_kinds:
            for k in (lo, hi):
                if k not in (bmod.PERIODIC, bmod.OUTFLOW):
                    raise NotImplementedError(
                        "mhd boundaries: periodic/outflow only")
        self.grid = mu.MhdGrid(cfg=self.cfg, shape=shape, dx=self.dx,
                               bc_kinds=bc_kinds)
        u0, bf0 = mhd_condinit(shape, self.dx, params, self.cfg)
        self.u = jnp.asarray(u0, dtype=dtype)
        self.bf = jnp.asarray(bf0, dtype=dtype)
        self.t = 0.0
        self.nstep = 0
        self.iout = 1
        self.cell_updates = 0
        self.wall_s = 0.0
        self.telemetry = make_telemetry(params)
        from ramses_tpu.resilience.faultinject import FaultInjector
        from ramses_tpu.resilience.stepguard import StepGuard
        self._sguard = StepGuard.from_params(params,
                                             telemetry=self.telemetry)
        self._fault = FaultInjector.from_params(params)
        from ramses_tpu.resilience.watchdog import Watchdog
        self._wd = Watchdog.from_params(params, telemetry=self.telemetry)

    def mus_per_cell_update(self) -> float:
        return 1e6 * self.wall_s / max(self.cell_updates, 1)

    def evolve(self, tend: Optional[float] = None, chunk: int = 16,
               nstepmax: int = 10 ** 9, verbose: bool = False,
               guard=None):
        p = self.params
        tend = tend if tend is not None else (
            p.output.tout[-1] if p.output.tout else p.output.tend)
        tdtype = (jnp.float64 if jax.config.jax_enable_x64
                  else jnp.float32)
        telem = self.telemetry
        if telem.enabled:
            telem.run_info.update(sim_run_info(self))
        while self.t < tend * (1.0 - 1e-12) and self.nstep < nstepmax:
            if guard is not None and not guard.check():
                break
            n = min(chunk, nstepmax - self.nstep)
            # redo-step guard: run_steps does not donate, so plain
            # references retain the pre-window state for rollback
            prev = ((self.u, self.bf, self.t, self.nstep)
                    if self._sguard is not None else None)
            if self._fault is not None:
                n = self._fault.clamp_window(self.nstep, n)
                self._fault.maybe_nan(self)
            t0 = time.perf_counter()
            t_before = self.t
            with (self._wd.guard("step") if self._wd is not None
                    else nullcontext()):
                if self._fault is not None:
                    self._fault.maybe_hang(self.nstep)
                u, bf, t, ndone = mu.run_steps(
                    self.grid, self.u, self.bf,
                    jnp.asarray(self.t, tdtype),
                    jnp.asarray(tend, tdtype), n)
                u.block_until_ready()
                ndone = int(ndone)
            wall = time.perf_counter() - t0
            self.wall_s += wall
            self.u, self.bf, self.t = u, bf, float(t)
            self.nstep += ndone
            if self._wd is not None:
                self._wd.note(nstep=self.nstep, t=self.t)
            self.cell_updates += ndone * self.grid.ncell
            if prev is not None and not self._sguard.ok(self.t):
                ndone = self._retry_window(prev, tend, tdtype)
            if telem.enabled and ndone:
                telem.record_step(
                    self, dt=(self.t - t_before) / ndone, wall_s=wall,
                    steps=ndone, t=self.t, nstep=self.nstep,
                    chunked=ndone)
            if verbose:
                print(telemetry_screen.step_line(
                    self, dt=((self.t - t_before) / ndone
                              if ndone else None), chunk=ndone,
                    extra=f"divb={float(self.max_divb()):.2e}"))
            if ndone == 0:
                break

    def _retry_window(self, prev, tend, tdtype) -> int:
        """Redo-step ladder after a non-finite window (RAMSES redo-step):
        rollback, halve dt, escalate the 1D Riemann solver to LLF on the
        second retry, emergency-dump + abort when exhausted."""
        import dataclasses as _dc

        from ramses_tpu.resilience.stepguard import (StepGuard,
                                                     StepRetryExhausted)
        sg = self._sguard
        u0, bf0, t0, nstep0 = prev
        sg.record_trip(self)
        grid0 = self.grid
        try:
            for attempt in range(1, sg.max_retries + 1):
                self.u, self.bf, self.t = u0, bf0, t0
                self.nstep = nstep0
                escalated = attempt >= 2
                if escalated:
                    self.grid = _dc.replace(
                        grid0, cfg=_dc.replace(grid0.cfg, riemann="llf"))
                scale = 0.5 ** attempt
                sg.record_rollback(self, attempt, scale, escalated)
                tw = time.perf_counter()
                u, bf, t, ndone = mu.run_steps(
                    self.grid, u0, bf0, jnp.asarray(t0, tdtype),
                    jnp.asarray(tend, tdtype), 1, dt_scale=scale)
                u.block_until_ready()
                tf = float(t)
                if StepGuard.ok(tf):
                    ndone = int(ndone)
                    self.u, self.bf, self.t = u, bf, tf
                    self.nstep = nstep0 + ndone
                    self.cell_updates += ndone * self.grid.ncell
                    self.wall_s += time.perf_counter() - tw
                    sg.record_recovered(self, attempt)
                    return ndone
        finally:
            self.grid = grid0
        self.u, self.bf, self.t = u0, bf0, t0
        self.nstep = nstep0
        out = None
        try:
            out = self.dump(999, str(self.params.output.output_dir))
        except Exception as e:             # noqa: BLE001 - abort path
            print(f"resilience: emergency dump failed: {e}")
        sg.record_abort(self, out)
        raise StepRetryExhausted(
            f"mhd step at t={t0:.6g} still non-finite after "
            f"{sg.max_retries} retries")

    def max_divb(self):
        return jnp.max(jnp.abs(core.div_b(
            [self.bf[c] for c in range(NCOMP)],
            (self.dx,) * self.cfg.ndim, self.cfg.ndim)))

    def totals(self):
        return mu.totals(self.u, self.cfg, self.dx)

    # ------------------------------------------------------------------
    # snapshot output (reference MHD layout: B left/right columns,
    # mhd/output_hydro.f90:88-149)
    # ------------------------------------------------------------------
    def var_names(self) -> List[str]:
        dims = "xyz"
        names = ["density"]
        names += [f"velocity_{dims[d]}" for d in range(self.cfg.ndim)]
        names += [f"B_{dims[c]}_left" for c in range(3)]
        names += [f"B_{dims[c]}_right" for c in range(3)]
        names += ["pressure"]
        names += [f"scalar_{i:02d}" for i in range(self.cfg.npassive)]
        return names

    def output_vars(self) -> np.ndarray:
        """[*sp, nvar_out] float64 in var_names() order."""
        cfg = self.cfg
        u = np.asarray(self.u, dtype=np.float64)
        bf = np.asarray(self.bf, dtype=np.float64)
        rho = np.maximum(u[0], cfg.smallr)
        cols = [u[0]]
        cols += [u[1 + d] / rho for d in range(cfg.ndim)]
        b_left, b_right = [], []
        for c in range(3):
            if c < cfg.ndim:
                b_left.append(bf[c])
                br = np.roll(bf[c], -1, axis=c)
                if self.grid.bc_kinds[c][1] != bmod.PERIODIC:
                    # outflow: the wrap would import the opposite edge;
                    # replicate the local edge face instead (zero-gradient)
                    idx = [slice(None)] * cfg.ndim
                    idx[c] = -1
                    br[tuple(idx)] = bf[c][tuple(idx)]
                b_right.append(br)
            else:
                b_left.append(u[IBX + c])
                b_right.append(u[IBX + c])
        cols += b_left + b_right
        ek = 0.5 * sum(u[1 + c] ** 2 for c in range(NCOMP)) / rho
        em = 0.5 * sum((0.5 * (bl + br)) ** 2
                       for bl, br in zip(b_left, b_right))
        cols.append((cfg.gamma - 1.0) * (u[IP] - ek - em))
        for s in range(cfg.npassive):
            cols.append(u[8 + s] / rho)
        return np.stack(cols, axis=-1)

    def dump(self, iout: int = 1, base_dir: str = ".",
             namelist_path: Optional[str] = None) -> str:
        from ramses_tpu.io import snapshot as sm
        from ramses_tpu.units import units as units_fn
        params = self.params
        lmin = params.amr.levelmin
        ndim = self.cfg.ndim
        dense = self.output_vars()
        levels = sm.uniform_levels_from_dense(dense, lmin, ndim)
        snap = sm.Snapshot(
            ndim=ndim, nlevelmax=max(params.amr.levelmax, lmin),
            levels=levels, boxlen=float(params.amr.boxlen), t=float(self.t),
            gamma=self.cfg.gamma, var_names=self.var_names(),
            units=units_fn(params), levelmin=lmin, nstep=self.nstep,
            nstep_coarse=self.nstep, tout=[params.output.tend or 0.0])
        return sm.dump_all(snap, iout, base_dir,
                           namelist_path=namelist_path,
                           keep_last=int(getattr(params.output,
                                                 "checkpoint_keep", 0)))

    @classmethod
    def from_snapshot(cls, params: Params, outdir: str,
                      dtype=jnp.float64) -> "MhdSimulation":
        """Rebuild from a :meth:`dump` directory (auto-resume restore).

        The MHD columns store B as left/right face pairs: the staggered
        ``bf`` comes straight back from the left columns and the
        cell-centred field from their average, so dump→restore round
        trips exactly at file precision.  Velocity components beyond
        ndim are not written by :meth:`output_vars` and restore as zero.
        """
        from ramses_tpu.amr.tree import cell_offsets
        from ramses_tpu.io.restart import restore_tree_state
        cfg = MhdStatic.from_params(params)
        lmin = params.amr.levelmin
        tree_og, rows_lv, meta, _parts = restore_tree_state(
            outdir, cfg, lmin, to_cons=lambda q: q)   # raw output rows
        if lmin not in rows_lv:
            raise ValueError(f"snapshot has no level {lmin} data")
        ndim = cfg.ndim
        n = 1 << lmin
        og = tree_og[lmin]
        offs = cell_offsets(ndim)
        cc = (2 * og[:, None, :] + offs[None, :, :]).reshape(-1, ndim)
        rows = rows_lv[lmin]                          # [ncell, nvar_out]
        dense = np.zeros((rows.shape[1],) + (n,) * ndim)
        idx = tuple(cc[:, d] for d in range(ndim))
        for iv in range(rows.shape[1]):
            dense[iv][idx] = rows[:, iv]
        ib = 1 + ndim                                 # first B_left column
        bl = dense[ib:ib + 3]
        br = dense[ib + 3:ib + 6]
        q = np.zeros((cfg.nvar,) + (n,) * ndim)
        q[0] = dense[0]
        for d in range(ndim):
            q[1 + d] = dense[1 + d]
        for c in range(NCOMP):
            q[IBX + c] = 0.5 * (bl[c] + br[c])
        q[IP] = dense[ib + 6]
        for s in range(cfg.npassive):
            q[8 + s] = dense[ib + 7 + s]              # per-mass scalar
        sim = cls(params, dtype=dtype)
        sim.u = jnp.asarray(np.asarray(core.prim_to_cons(
            jnp.asarray(q), cfg)), dtype=dtype)
        sim.bf = jnp.asarray(bl, dtype=dtype)
        sim.t = float(meta["t"])
        sim.nstep = int(meta["nstep"])
        sim.iout = max(int(meta["iout"]), 0) + 1
        return sim
