"""f32 decomposition invariance — the north star's "bitwise-stable L1".

The claim (BASELINE.md): answers do not depend on the device
decomposition.  These tests run the SAME f32 problem on 1 device and
sharded over the 8-device virtual mesh and require exact float32
equality — they fail if any reduction (stencil gather collectives,
flux-correction scatter-adds, CIC segment sums) reorders between the
two layouts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ramses_tpu.amr.hierarchy import AmrSim
from ramses_tpu.config import load_params
from ramses_tpu.parallel.amr_sharded import ShardedAmrSim

NML = "namelists/sedov3d.nml"

needs_mesh = pytest.mark.skipif(jax.device_count() < 8,
                                reason="needs the 8-device virtual mesh")


def _params(lmin=4, lmax=5):
    p = load_params(NML, ndim=3)
    p.amr.levelmin, p.amr.levelmax = lmin, lmax
    p.refine.err_grad_d = 0.1
    p.refine.err_grad_p = 0.1
    return p


def _state_bits(sim):
    out = {}
    for l in sim.levels():
        n = sim.maps[l].noct * 2 ** sim.tree_ndim
        out[l] = np.asarray(sim.u[l])[:n].astype(np.float32)
    return out


@pytest.mark.slow
@needs_mesh
def test_amr_f32_1dev_vs_8dev_bitwise():
    """Hydro AMR with flux-correction scatter-adds: 3 coarse steps with
    regrids must agree BITWISE between layouts."""
    one = AmrSim(_params(), dtype=jnp.float32,
                 )
    eight = ShardedAmrSim(_params(), dtype=jnp.float32)
    for _ in range(3):
        one.regrid()
        eight.regrid()
        one.step_coarse(one.coarse_dt())
        eight.step_coarse(eight.coarse_dt())
    a = _state_bits(one)
    b = _state_bits(eight)
    assert set(a) == set(b)
    for l in a:
        same = a[l].view(np.uint32) == b[l].view(np.uint32)
        frac = same.mean()
        assert frac == 1.0, (
            f"level {l}: {100 * (1 - frac):.4f}% of f32 words differ "
            "between 1-device and 8-device runs (reduction reorder)")


@needs_mesh
def test_amr_pm_f32_deposit_invariance():
    """Particle CIC deposits (segment sums) must not depend on the
    mesh: compare the per-level Poisson rhs densities bitwise."""
    from ramses_tpu.pm.particles import ParticleSet

    rng = np.random.default_rng(7)
    npart = 4096
    x = rng.random((npart, 3))
    v = np.zeros((npart, 3))
    m = np.full(npart, 1.0 / npart)

    def build(cls):
        p = _params(4, 5)
        p.run.pic = True
        p.run.poisson = True
        parts = ParticleSet.make(jnp.asarray(x, jnp.float32),
                                 jnp.asarray(v, jnp.float32),
                                 jnp.asarray(m, jnp.float32))
        return cls(p, dtype=jnp.float32, particles=parts)

    one = build(AmrSim)
    eight = build(ShardedAmrSim)
    one._build_pm()
    eight._build_pm()
    for l in one.levels():
        r1 = np.asarray(one._pm_rho(l)).astype(np.float32)
        r8 = np.asarray(eight._pm_rho(l)).astype(np.float32)
        assert (r1.view(np.uint32) == r8.view(np.uint32)).all(), \
            f"level {l} deposit differs between layouts"
