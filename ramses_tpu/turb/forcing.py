"""OU forcing field in spectral space.

Reference: ``turb/turb_next_field.f90`` (OU update), the Helmholtz
projection of ``turb/turb_force_utils.f90`` (``proj_op``: solenoidal
(I - kk/k²) vs compressive kk/k² mixed by ``comp_frac``) and the power
spectra of ``calc_power_spectrum:65-102`` ('parabolic' 1-(|k|-2)²,
'power_law' |k|⁻², 'konstandin' 2-|k|).  State is the complex spectral
field [ndim, *kshape]; each update is

    f ← f·exp(-dt/T) + σ·sqrt(1-exp(-2dt/T))·N(0,1)

followed by projection and rms normalization — all fused on device.
Checkpointing mirrors ``write_turb_fields.f90``: the spectral state +
RNG key round-trips through ``.npz``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class TurbSpec:
    """&TURB_PARAMS (turb/turb_parameters.f90:36-51)."""
    enabled: bool = False
    turb_type: int = 1            # 1 driven evolving, 3 decaying
    seed: int = 0
    comp_frac: float = 1.0 / 3.0  # compressive fraction
    turb_T: float = 1.0           # autocorrelation time [code]
    turb_Ndt: int = 100           # OU updates per autocorrelation time
    turb_rms: float = 1.0         # target rms acceleration
    turb_min_rho: float = 1e-50
    spectrum: str = "parabolic"
    kmax: float = 3.0             # driving modes |k| <= kmax (box units)

    @classmethod
    def from_params(cls, p) -> "TurbSpec":
        raw = p.raw.get("turb_params", {}) if p.raw else {}

        def g(k, dflt):
            v = raw.get(k, dflt)
            return v[0] if isinstance(v, list) else v

        return cls(enabled=bool(g("turb", False)),
                   turb_type=int(g("turb_type", 1)),
                   seed=int(g("turb_seed", 0)),
                   comp_frac=float(g("comp_frac", 1.0 / 3.0)),
                   turb_T=float(g("turb_t", 1.0)),
                   turb_Ndt=int(g("turb_ndt", 100)),
                   turb_rms=float(g("turb_rms", 1.0)),
                   turb_min_rho=float(g("turb_min_rho", 1e-50)),
                   spectrum=str(g("forcing_power_spectrum", "parabolic")))


def _kgrid(shape: Sequence[int]):
    """Integer wavenumber arrays for an rfftn layout (last axis halved)."""
    ndim = len(shape)
    ks = []
    for d in range(ndim - 1):
        ks.append(np.fft.fftfreq(shape[d]) * shape[d])
    ks.append(np.fft.rfftfreq(shape[-1]) * shape[-1])
    return np.meshgrid(*ks, indexing="ij")


def _power(kmag, spec: TurbSpec):
    if spec.spectrum == "parabolic":
        p = 1.0 - (kmag - 2.0) ** 2
    elif spec.spectrum == "power_law":
        p = np.where(kmag > 0, kmag ** -2.0, 0.0)
    elif spec.spectrum == "konstandin":
        p = 2.0 - kmag
    else:
        raise ValueError(f"unknown forcing spectrum {spec.spectrum!r}")
    p = np.where((kmag >= 1.0 - 1e-9) & (kmag <= spec.kmax), p, 0.0)
    return np.maximum(p, 0.0)


class TurbForcing:
    """Driven-turbulence forcing field on an [n]*ndim grid."""

    def __init__(self, shape: Sequence[int], spec: TurbSpec,
                 key: Optional[jax.Array] = None):
        self.shape = tuple(shape)
        self.ndim = len(self.shape)
        self.spec = spec
        kk = _kgrid(self.shape)
        kmag = np.sqrt(sum(k ** 2 for k in kk))
        self.amp = jnp.asarray(np.sqrt(_power(kmag, spec)))
        kmag_safe = np.where(kmag > 0, kmag, 1.0)
        self.khat = [jnp.asarray(k / kmag_safe) for k in kk]
        self.key = (key if key is not None
                    else jax.random.PRNGKey(spec.seed))
        self.fhat = jnp.zeros((self.ndim,) + self.amp.shape,
                              dtype=jnp.complex128)
        # spin up to the stationary OU distribution (instant_turb)
        self.key, sub = jax.random.split(self.key)
        self.fhat = self._noise(sub)

    def _noise(self, key):
        """Projected, normalized random spectral field."""
        kr, ki = jax.random.split(key)
        shape = (self.ndim,) + self.amp.shape
        re = jax.random.normal(kr, shape)
        im = jax.random.normal(ki, shape)
        f = (re + 1j * im) * self.amp
        return self._project(f)

    def _project(self, f):
        """Helmholtz mix: (1-cf)·solenoidal + cf·compressive
        (``proj_op``, comp_frac weighting)."""
        cf = self.spec.comp_frac
        kdotf = sum(self.khat[d] * f[d] for d in range(self.ndim))
        comp = jnp.stack([self.khat[d] * kdotf for d in range(self.ndim)])
        sol = f - comp
        return (1.0 - cf) * sol + cf * comp

    def update(self, dt: float):
        """OU step over dt (type 3 'decaying': no noise refresh)."""
        T = self.spec.turb_T
        decay = jnp.exp(-dt / T)
        if self.spec.turb_type == 3:
            self.fhat = self.fhat * decay
            return
        self.key, sub = jax.random.split(self.key)
        noise = self._noise(sub)
        self.fhat = self.fhat * decay + noise * jnp.sqrt(
            jnp.maximum(1.0 - decay ** 2, 0.0))

    def acceleration(self):
        """Real-space acceleration [ndim, *shape], rms-normalized to
        turb_rms (``add_turb_forcing.f90`` afac scaling)."""
        acc = jnp.stack([jnp.fft.irfftn(self.fhat[d], s=self.shape)
                         for d in range(self.ndim)])
        rms = jnp.sqrt(jnp.mean(jnp.sum(acc ** 2, axis=0)))
        return acc * (self.spec.turb_rms / jnp.maximum(rms, 1e-300))

    # checkpoint (write_turb_fields.f90 / read_turb_fields.f90) ---------
    def save(self, path: str):
        np.savez(path, fhat=np.asarray(self.fhat),
                 key=np.asarray(self.key), shape=np.asarray(self.shape))

    @classmethod
    def load(cls, path: str, spec: TurbSpec) -> "TurbForcing":
        data = np.load(path)
        obj = cls(tuple(int(s) for s in data["shape"]), spec)
        obj.fhat = jnp.asarray(data["fhat"])
        obj.key = jnp.asarray(data["key"])
        return obj


def apply_forcing(u, acc, dt, min_rho: float = 1e-50):
    """Momentum/energy kick from the acceleration field
    (``add_turb_forcing.f90``): Δ(ρv) = ρ a dt,
    ΔE = (v·a ρ + ½ρa²dt) dt evaluated conservatively."""
    ndim = acc.shape[0]
    rho = jnp.maximum(u[0], min_rho)
    unew = u
    de = jnp.zeros_like(rho)
    for d in range(ndim):
        mom_old = u[1 + d]
        mom_new = mom_old + rho * acc[d] * dt
        de = de + 0.5 * (mom_new ** 2 - mom_old ** 2) / rho
        unew = unew.at[1 + d].set(mom_new)
    return unew.at[1 + ndim].add(de)
