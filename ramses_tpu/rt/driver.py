"""RT simulation driver: sources, subcycled transport+chemistry loop.

Counterpart of the reference's subcycled ``rt_step``
(``amr/amr_step.f90:594-672``) on the dense uniform grid — which is also
the ATON architecture (§2.9) without the gather/scatter: fields stay on
device, one fused program per substep, N substeps per hydro step.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ramses_tpu.rt import chem as chem_mod
from ramses_tpu.rt import m1
from ramses_tpu.rt.chem import GroupSpec

from ramses_tpu.units import C_CGS


@dataclass(frozen=True)
class RtSpec:
    """Static RT configuration (&RT_PARAMS, ``rt/rt_init.f90:151-152``)."""
    ndim: int = 3
    c_fraction: float = 0.01          # rt_c_fraction
    courant: float = 0.8              # rt_courant_factor
    otsa: bool = True
    heating: bool = True
    periodic: bool = True
    group: GroupSpec = field(default_factory=GroupSpec)
    # multigroup + helium surface (rt_parameters.f90 nGroups/X,Y):
    # SED-averaged Group3 tuple; empty → legacy single gray group
    groups3: tuple = ()
    y_he: float = 0.0
    # pure photon propagation (rt_pp / rt_freeflow): transport only,
    # no thermochemistry
    pp: bool = False

    @property
    def c_red(self) -> float:
        return self.c_fraction * C_CGS

    @property
    def full3(self) -> bool:
        """True when the multigroup/3-ion system is active."""
        return len(self.groups3) > 1 or self.y_he > 0.0

    @classmethod
    def from_params(cls, p, ndim: Optional[int] = None) -> "RtSpec":
        from ramses_tpu.rt import spectra
        r = p.rt
        bounds = list(r.rt_egy_bounds)
        if bounds and len(bounds) != int(r.rt_ngroups) + 1:
            # user-supplied but fencepost-wrong: ngroups groups need
            # ngroups+1 bin edges — error out loudly instead of
            # silently substituting the defaults
            raise ValueError(
                f"rt_egy_bounds has {len(bounds)} values; "
                f"rt_ngroups={int(r.rt_ngroups)} needs "
                f"{int(r.rt_ngroups) + 1} bin edges "
                f"(rt/rt_parameters.f90 group bounds)")
        if not bounds:
            bounds = list(spectra.DEFAULT_BOUNDS[:int(r.rt_ngroups)]) \
                + [spectra.DEFAULT_BOUNDS[-1]]
        groups3 = spectra.blackbody_groups(float(r.rt_t_star), bounds)
        return cls(ndim=ndim or p.ndim,
                   c_fraction=float(r.rt_c_fraction),
                   courant=float(r.rt_courant_factor),
                   otsa=bool(r.rt_otsa),
                   periodic=not bool(r.rt_is_outflow_bound),
                   groups3=groups3,
                   y_he=float(r.rt_y_he),
                   pp=bool(r.rt_pp) or bool(r.rt_freeflow))


class RtSim:
    """Standalone RT problem on a uniform grid (cgs units).

    Legacy mode (default spec): single gray group, H-only chemistry —
    ``N``/``F``/``x`` are plain per-cell arrays.  With
    ``spec.full3`` (multigroup and/or helium): ``N`` gains a leading
    group axis, ``F`` becomes [ng, ndim, …], and the chemistry runs the
    3-ion ladder (``xHe2``/``xHe3`` join ``x``)."""

    def __init__(self, shape: Sequence[int], dx: float, spec: RtSpec,
                 nH, T=None, xHII=None):
        self.shape = tuple(shape)
        self.dx = float(dx)
        self.spec = spec
        ndim = spec.ndim
        assert len(self.shape) == ndim
        self.nH = jnp.asarray(nH, jnp.float64)
        self.T = (jnp.asarray(T, jnp.float64) if T is not None
                  else jnp.full(self.shape, 100.0))
        self.x = (jnp.asarray(xHII, jnp.float64) if xHII is not None
                  else jnp.full(self.shape, 1.2e-3))
        if spec.full3:
            ng = len(spec.groups3)
            self.N = jnp.full((ng,) + self.shape, m1.SMALL_NP)
            self.F = jnp.zeros((ng, ndim) + self.shape)
            self.xHe2 = jnp.full(self.shape, 1e-6)
            self.xHe3 = jnp.full(self.shape, 1e-8)
            self.src = jnp.zeros((ng,) + self.shape)
        else:
            self.N = jnp.full(self.shape, m1.SMALL_NP)
            self.F = jnp.zeros((ndim,) + self.shape)
            self.src = jnp.zeros(self.shape)
        # flux (beam) source field: allocated lazily on the first
        # DIRECTED point_source so beam-free runs don't carry and
        # integrate an all-zeros (ng, ndim, *shape) array every substep
        self.src_F = None
        self.t = 0.0
        self._step_fn = None
        # RtSim is built from arrays, not Params, so telemetry attaches
        # explicitly: ``sim.telemetry = make_telemetry(params)`` (or a
        # host driver shares its recorder); default is the no-op NULL
        from ramses_tpu.telemetry import NULL
        self.telemetry = NULL

    @property
    def nHe(self):
        """Helium number density from the mass fractions (X = 1 - Y)."""
        y = self.spec.y_he
        return self.nH * (y / (4.0 * max(1.0 - y, 1e-10)))

    def point_source(self, pos: Sequence[float], ndot: float,
                     direction: Optional[Sequence[float]] = None):
        """Add a point source of ``ndot`` photons/s (one-cell injection,
        the reference's cloud-smoothed stellar injection reduced);
        multigroup sources split by the SED's photon-count shares.
        ``direction``: optional unit vector making the source a BEAM —
        photons inject with streaming flux F = c_red·N·n̂ (the
        rt_u/v/w_source directed sources of rad_beams.nml)."""
        idx = tuple(int(p / self.dx) for p in pos)
        vol = self.dx ** self.spec.ndim
        src = np.array(self.src)
        nd = self.spec.ndim
        if direction is not None and self.src_F is None:
            shape = ((len(self.spec.groups3), nd) + self.shape
                     if self.spec.full3 else (nd,) + self.shape)
            self.src_F = jnp.zeros(shape)
            self._step_fn = None          # recompile with the beam term
        srcF = (np.array(self.src_F) if self.src_F is not None
                else None)
        if self.spec.full3:
            for g, grp in enumerate(self.spec.groups3):
                src[(g,) + idx] += grp.frac * ndot / vol
                if direction is not None:
                    for d in range(nd):
                        srcF[(g, d) + idx] += (self.spec.c_red * grp.frac
                                               * ndot / vol
                                               * float(direction[d]))
        else:
            src[idx] += ndot / vol
            if direction is not None:
                for d in range(nd):
                    srcF[(d,) + idx] += (self.spec.c_red * ndot / vol
                                         * float(direction[d]))
        self.src = jnp.asarray(src)
        if srcF is not None:
            self.src_F = jnp.asarray(srcF)

    def _build_step(self):
        spec = self.spec
        dx = self.dx
        has_beam = self.src_F is not None

        if not spec.full3:
            @partial(jax.jit, static_argnames=("nsub",))
            def run(N, F, x, xh2, xh3, T, nH, nHe, src, src_F, dt_sub,
                    nsub: int):
                def body(carry, _):
                    N, F, x, T = carry
                    N = N + dt_sub * src
                    if has_beam:
                        F = F + dt_sub * src_F
                    N, F = m1.transport_step(N, F, dt_sub, dx, spec.c_red,
                                             spec.ndim, spec.periodic)
                    if not spec.pp:      # rt_pp: free-flowing photons
                        N, x, T = chem_mod.chem_step(
                            N, x, T, nH, dt_sub, spec.c_red, spec.group,
                            spec.otsa, heating=spec.heating)
                    return (N, F, x, T), None
                (N, F, x, T), _ = jax.lax.scan(body, (N, F, x, T), None,
                                               length=nsub)
                return N, F, x, xh2, xh3, T
            return run

        groups = spec.groups3
        ng = len(groups)

        @partial(jax.jit, static_argnames=("nsub",))
        def run(N, F, x, xh2, xh3, T, nH, nHe, src, src_F, dt_sub,
                nsub: int):
            def body(carry, _):
                N, F, x, xh2, xh3, T = carry
                N = N + dt_sub * src
                if has_beam:
                    F = F + dt_sub * src_F
                Ns, Fs = [], []
                for g in range(ng):          # per-group GLF transport
                    Ng, Fg = m1.transport_step(
                        N[g], F[g], dt_sub, dx, spec.c_red, spec.ndim,
                        spec.periodic)
                    Ns.append(Ng)
                    Fs.append(Fg)
                if spec.pp:
                    Ns = list(Ns)
                else:
                    Ns, (x, xh2, xh3), T = chem_mod.chem_step_3ion(
                        Ns, (x, xh2, xh3), T, nH, nHe, dt_sub,
                        spec.c_red, groups, spec.otsa,
                        heating=spec.heating)
                return (jnp.stack(Ns), jnp.stack(Fs), x, xh2, xh3,
                        T), None
            (N, F, x, xh2, xh3, T), _ = jax.lax.scan(
                body, (N, F, x, xh2, xh3, T), None, length=nsub)
            return N, F, x, xh2, xh3, T
        return run

    def advance(self, dt: float):
        """Advance physical time dt with RT-courant substeps."""
        if self._step_fn is None:
            self._step_fn = self._build_step()
        dt_c = m1.rt_courant_dt(self.dx, self.spec.c_red,
                                self.spec.courant)
        nsub = max(1, int(np.ceil(dt / dt_c)))
        dt_sub = dt / nsub
        xh2 = getattr(self, "xHe2", jnp.zeros(self.shape))
        xh3 = getattr(self, "xHe3", jnp.zeros(self.shape))
        srcF = (self.src_F if self.src_F is not None
                else jnp.asarray(0.0))
        out = self._step_fn(self.N, self.F, self.x, xh2, xh3, self.T,
                            self.nH, self.nHe, self.src, srcF,
                            jnp.asarray(dt_sub), nsub)
        self.N, self.F, self.x, xh2, xh3, self.T = out
        if self.spec.full3:
            self.xHe2, self.xHe3 = xh2, xh3
        self.t += dt
        if self.telemetry.enabled:
            # substep census only — photon/ionization totals sync the
            # device, so they stay in the amortized rt_stats audit
            self.telemetry.record_event(
                "rt_advance", t=float(self.t), dt=float(dt),
                nsub=int(nsub), dt_sub=float(dt_sub))

    # diagnostics ------------------------------------------------------
    def ionized_volume(self) -> float:
        """V_ion = Σ x dV — the Stromgren-sphere measure."""
        return float(jnp.sum(self.x) * self.dx ** self.spec.ndim)

    def photon_total(self) -> float:
        return float(jnp.sum(self.N) * self.dx ** self.spec.ndim)


def stromgren_radius(ndot: float, nH: float, T: float = 1e4) -> float:
    """Classical Stromgren radius [cm] for a pure-H medium."""
    aB = float(chem_mod.alpha_B(jnp.asarray(T)))
    return (3.0 * ndot / (4.0 * np.pi * aB * nH ** 2)) ** (1.0 / 3.0)
