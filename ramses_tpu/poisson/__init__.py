"""Gravity: Poisson solvers, force computation, analytic fields.

TPU-native replacement of the reference ``poisson/`` layer (SURVEY.md §2.6):
the per-AMR-level masked multigrid becomes dense whole-grid cycles under
jit, the CG fallback keeps the reference's ``cg_levelmin`` escape hatch,
and a periodic FFT solve (exact for the discrete 7-point operator) is the
TPU-idiomatic fast path the Fortran never had.
"""
