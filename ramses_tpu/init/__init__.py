from ramses_tpu.init.regions import region_condinit, condinit  # noqa: F401
