"""Host-resident octree topology.

Replaces the reference's pointer-based fully-threaded tree
(``son/father/nbor`` arrays + per-(level,cpu) linked lists,
``amr/amr_commons.f90:54-75``) with one sorted Morton-key array per level:
an oct at level ``l`` is identified by its integer coordinates on the
``2^(l-1)``-per-dim oct grid (cells are ``2^l`` per dim), membership and
neighbour lookup are ``np.searchsorted`` on the sorted keys, and "linked
list order" is simply array order.  Levels below ``levelmin`` are implicitly
fully refined (the reference's coarse levels 1..levelmin-1 exist only as
scaffolding; ours don't exist at all).

Conventions:
  * level ``l`` cell grid: ``2^l`` cells per dim over the unit box
    (``levelmin=7`` ⇒ 128³ base cells, matching the reference).
  * oct at level ``l`` has oct coords ``og ∈ [0, 2^(l-1))^ndim``; its 2^ndim
    cells have cell coords ``2*og + c, c ∈ {0,1}^ndim``.
  * cell offset index within an oct: ``off = c_x * 2^(ndim-1) + ... + c_z``
    (x slowest), matching a row-major reshape to ``[2]*ndim`` cell axes.
    (The reference uses x-fastest ``ind_son=1+ix+2*iy+4*iz``; ours matches
    numpy/XLA reshape order instead.)
  * flat cell index at a level: ``oct_index * 2^ndim + off``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List

import numpy as np

from ramses_tpu.amr import keys as kmod


@dataclass
class OctLevel:
    """Sorted oct set of one level."""
    lvl: int
    keys: np.ndarray          # [noct] int64 Morton keys, sorted ascending
    og: np.ndarray            # [noct, ndim] int64 oct coords (decoded)

    @property
    def noct(self) -> int:
        return len(self.keys)


class Octree:
    """Per-level sorted oct sets for levels levelmin..levelmax.

    ``root``: coarse root-cell counts per dim (``nx, ny, nz`` of
    &AMR_PARAMS; ``amr/init_amr.f90:37-60`` builds the tree over this
    arbitrary coarse grid).  Level ``l`` then has ``root[d]·2^l`` cells
    along dim ``d`` (cubic cells; the domain extent is
    ``root[d]·boxlen``), reducing to the single-cube 2^l layout for
    the default all-ones root."""

    def __init__(self, ndim: int, levelmin: int, levelmax: int,
                 root=None):
        self.ndim = ndim
        self.levelmin = levelmin
        self.levelmax = levelmax
        self.root = tuple(int(r) for r in
                          (root if root is not None else (1,) * ndim))
        self.levels: Dict[int, OctLevel] = {}

    def cell_dims(self, lvl: int):
        """Cells per dim at level ``lvl``."""
        return tuple(r << lvl for r in self.root)

    def oct_dims(self, lvl: int):
        """Octs per dim at level ``lvl``."""
        return tuple(r << (lvl - 1) for r in self.root)

    @classmethod
    def base(cls, ndim: int, levelmin: int, levelmax: int,
             root=None) -> "Octree":
        """Complete base level (the reference's fully-refined levelmin)."""
        t = cls(ndim, levelmin, levelmax, root=root)
        axes = [np.arange(n, dtype=np.int64)
                for n in t.oct_dims(levelmin)]
        grids = np.meshgrid(*axes, indexing="ij")
        og = np.stack([g.ravel() for g in grids], axis=1)
        t.set_level(levelmin, og)
        return t

    def set_level(self, lvl: int, og: np.ndarray) -> None:
        og = np.asarray(og, dtype=np.int64).reshape(-1, self.ndim)
        ks = kmod.encode(og, self.ndim)
        order = np.argsort(ks, kind="stable")
        self.levels[lvl] = OctLevel(lvl, ks[order], og[order])

    def has(self, lvl: int) -> bool:
        return lvl in self.levels and self.levels[lvl].noct > 0

    def noct(self, lvl: int) -> int:
        return self.levels[lvl].noct if lvl in self.levels else 0

    @property
    def finest(self) -> int:
        """Finest level actually populated."""
        lv = self.levelmin
        for l in range(self.levelmin, self.levelmax + 1):
            if self.has(l):
                lv = l
        return lv

    def lookup(self, lvl: int, og: np.ndarray) -> np.ndarray:
        """Oct indices for coords ``og [n, ndim]``; -1 where absent."""
        if not self.has(lvl):
            return np.full(len(og), -1, dtype=np.int64)
        lev = self.levels[lvl]
        ks = kmod.encode(np.asarray(og, dtype=np.int64), self.ndim)
        pos = np.searchsorted(lev.keys, ks)
        pos = np.clip(pos, 0, lev.noct - 1)
        hit = lev.keys[pos] == ks
        return np.where(hit, pos, -1)

    def lookup_keys(self, lvl: int, ks: np.ndarray) -> np.ndarray:
        """Oct indices for Morton keys; -1 where absent."""
        if not self.has(lvl):
            return np.full(len(ks), -1, dtype=np.int64)
        lev = self.levels[lvl]
        if len(ks) >= 4096:
            from ramses_tpu import native
            nat = native.lookup_sorted(lev.keys, ks)
            if nat is not None:
                return nat
        pos = np.searchsorted(lev.keys, ks)
        pos = np.clip(pos, 0, lev.noct - 1)
        hit = lev.keys[pos] == ks
        return np.where(hit, pos, -1)

    def cell_coords(self, lvl: int) -> np.ndarray:
        """Global cell coords of every cell of the level, flat-cell order:
        ``[noct * 2^ndim, ndim]``."""
        lev = self.levels[lvl]
        offs = cell_offsets(self.ndim)                   # [2^d, ndim]
        return (2 * lev.og[:, None, :] + offs[None, :, :]).reshape(
            -1, self.ndim)

    def cell_centers(self, lvl: int, boxlen: float = 1.0) -> np.ndarray:
        """Physical cell-centre coords ``[ncell, ndim]`` in [0, boxlen]."""
        dx = boxlen / (1 << lvl)
        return (self.cell_coords(lvl) + 0.5) * dx

    def son_parent_cells(self, lvl: int) -> np.ndarray:
        """Flat lvl-cell index covered by each lvl+1 oct (tree order),
        -1 where the parent oct is missing (2:1 violation)."""
        og1 = self.levels[lvl + 1].og
        f_oct = self.lookup(lvl, og1 >> 1)
        off = np.zeros(len(og1), dtype=np.int64)
        for d in range(self.ndim):
            off = off * 2 + (og1[:, d] & 1)
        return np.where(f_oct >= 0, f_oct * (1 << self.ndim) + off, -1)

    def refined_mask(self, lvl: int) -> np.ndarray:
        """Bool [ncell_flat]: cell has a son oct at lvl+1.

        Built from the fine level's oct coords (each lvl+1 oct marks
        exactly one lvl cell): O(noct(lvl+1)), not O(ncell(lvl))."""
        ncell = self.noct(lvl) * (1 << self.ndim)
        out = np.zeros(ncell, dtype=bool)
        if not self.has(lvl + 1):
            return out
        rows = self.son_parent_cells(lvl)
        out[rows[rows >= 0]] = True
        return out


def cell_offsets(ndim: int) -> np.ndarray:
    """[2^ndim, ndim] cell offsets in flat-cell order (x slowest)."""
    offs = np.indices((2,) * ndim).reshape(ndim, -1).T
    return offs.astype(np.int64)


def map_coords(cc: np.ndarray, lvl: int, bc_kinds: List[tuple],
               ndim: int, dims=None):
    """Map (possibly out-of-domain) cell coords to in-domain coords per the
    physical boundaries (``amr/physical_boundaries.f90`` semantics realized
    as index mapping instead of ghost regions).

    ``bc_kinds[d] = (low_kind, high_kind)`` with kinds from
    ``grid.boundary``: 0 periodic, 1 reflecting, 2 outflow.
    ``dims``: per-dim cell counts (``tree.cell_dims(lvl)``); defaults
    to the single-cube ``2^lvl`` everywhere.
    Returns (mapped coords, reflect_mask [n, ndim] bool — True where the
    coordinate was mirrored an odd number of times, i.e. velocity component
    d must be sign-flipped).
    """
    out = cc.copy()
    refl = np.zeros(cc.shape, dtype=bool)
    for d in range(ndim):
        n = (1 << lvl) if dims is None else int(dims[d])
        lo, hi = bc_kinds[d]
        x = out[:, d]
        if lo == 0 and hi == 0:            # periodic
            out[:, d] = np.mod(x, n)
        else:
            # reflecting: mirror about the wall; outflow: clamp (zero-grad)
            below = x < 0
            above = x >= n
            if lo == 1:
                out[:, d] = np.where(below, -1 - x, out[:, d])
                refl[:, d] |= below
            elif lo != 0:
                out[:, d] = np.where(below, 0, out[:, d])
            if hi == 1:
                x2 = out[:, d]
                out[:, d] = np.where(above, 2 * n - 1 - x2, out[:, d])
                refl[:, d] |= above
            elif hi != 0:
                out[:, d] = np.where(above, n - 1, out[:, d])
            # mixed periodic on one side only: clamp handles the remainder
            out[:, d] = np.clip(out[:, d], 0, n - 1)
    return out, refl
