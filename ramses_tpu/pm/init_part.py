"""Particle + baryon initial conditions from cosmological IC files.

Reference: ``pm/init_part.f90`` (grafic displacement initialization,
Gadget import) and ``hydro/init_flow_fine.f90`` (baryon fields from
``ic_deltab``/``ic_velb*``).

Conventions bridged here (code units: box = 1, conformal time τ in
1/H0, supercomoving velocities v_code = dx/dτ):

* grafic velocities are PROPER PECULIAR km/s at ``astart``:
      v_code = v_kms · a / (H0 · L_box[Mpc])
* the Zel'dovich growing mode gives the comoving displacement
      ψ_box = v_code / (f(Ω) · hexp)          (hexp = a²H/H0)
  so particles start at x = q + ψ with velocity v_code — exactly the
  ``init_part.f90`` displacement construction in our unit system.
* Gadget positions are kpc/h comoving, velocities km/s·√a (internal).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ramses_tpu.io import gadget as gadget_io
from ramses_tpu.io import grafic as grafic_io
from ramses_tpu.pm.cosmology import Cosmology, dadt


def fpeebl(a: float, om: float, ov: float, ok: float) -> float:
    """Linear growth rate f = dlnD/dlna (``init_time.f90`` fpeebl):
    the Ωm(a)^(5/9) fit, exact for EdS."""
    h2 = om / a ** 3 + ov + ok / a ** 2
    om_a = (om / a ** 3) / h2
    return om_a ** (5.0 / 9.0)


def particles_from_grafic(dirname: str, cosmo: Cosmology,
                          omega_b: Optional[float] = None):
    """(x [n,3], v [n,3], m [n]) in code units from a grafic level
    directory — the DM side of ``init_part.f90``.

    Masses sum to (1 − Ωb/Ωm): matter mean density is 1 in
    supercomoving units and baryons carry their share in the gas.
    """
    hdr, fields = grafic_io.read_grafic_dir(dirname)
    a = hdr.astart
    om, ov = hdr.omega_m, hdr.omega_v
    ok = 1.0 - om - ov
    n1, n2, n3 = hdr.np1, hdr.np2, hdr.np3
    L = hdr.boxlen_mpc
    h0 = hdr.h0
    # v_kms → code velocity (dx/dτ, box units, τ in 1/H0)
    v_scale = a / (h0 * L)
    f_om = fpeebl(a, om, ov, ok)
    hexp = a * dadt(a, om, ov, ok)                   # a²H/H0
    q = np.stack(np.meshgrid(
        (np.arange(n1) + 0.5) / n1, (np.arange(n2) + 0.5) / n2,
        (np.arange(n3) + 0.5) / n3, indexing="ij"), axis=-1)
    v = np.stack([fields[f].astype(np.float64) * v_scale
                  for f in grafic_io.FIELDS_DM], axis=-1)
    psi = v / (f_om * hexp)                          # comoving, box units
    x = np.mod(q + psi, 1.0).reshape(-1, 3)
    v = v.reshape(-1, 3)
    fb = (omega_b if omega_b is not None else 0.0) / om
    mass = np.full(len(x), (1.0 - fb) / len(x))
    return x, v, mass, hdr


def baryons_from_grafic(dirname: str, cosmo: Cosmology, gamma: float,
                        omega_b: float, t2_start: float = 1e-8):
    """Conservative gas state [nvar=5, n,n,n] in supercomoving units
    from ``ic_deltab``/``ic_velb*`` (``init_flow_fine.f90`` cosmo
    branch): ρ = (Ωb/Ωm)(1+δ), momentum from the baryon velocities,
    a small uniform initial temperature."""
    hdr, fields = grafic_io.read_grafic_dir(dirname)
    if "ic_deltab" not in fields:
        raise FileNotFoundError(f"{dirname}: no ic_deltab (baryons)")
    a = hdr.astart
    v_scale = a / (hdr.h0 * hdr.boxlen_mpc)
    fb = omega_b / hdr.omega_m
    rho = fb * (1.0 + fields["ic_deltab"].astype(np.float64))
    vel = [fields[f].astype(np.float64) * v_scale
           for f in ("ic_velbx", "ic_velby", "ic_velbz")]
    p = t2_start * rho                                # cold start
    e = p / (gamma - 1.0) + 0.5 * rho * sum(vc * vc for vc in vel)
    return np.stack([rho] + [rho * vc for vc in vel] + [e]), hdr


def particles_from_gadget(path: str, cosmo: Cosmology):
    """(x [n,3], v [n,3], m [n]) in code units from a Gadget-1 file
    (``pm/init_part.f90`` 'gadget' branch)."""
    hdr, pos, vel, _ids = gadget_io.read_gadget(path)
    if hdr.boxsize <= 0:
        raise ValueError("gadget: BoxSize missing")
    a = hdr.time
    x = np.mod(pos / hdr.boxsize, 1.0)
    # internal velocity u = v_pec/sqrt(a) → v_pec = u·sqrt(a) km/s;
    # box length kpc/h → Mpc: L = boxsize/1000/h
    L_mpc = hdr.boxsize / 1000.0 / hdr.hubble
    h0 = 100.0 * hdr.hubble
    v = vel * np.sqrt(a) * a / (h0 * L_mpc)
    # equal masses normalized to total matter = 1 (DM-only import)
    m = np.full(len(x), 1.0 / len(x))
    return x, v, m, hdr
