"""Test configuration: CPU backend with 8 virtual devices.

Tests run on a virtual 8-device CPU mesh (the 'mpirun -np N on one host'
trick of the reference suite, ``tests/run_test_suite.sh:78-82``) with
float64 enabled so correctness oracles are precision-limited by the
algorithm, not the backend.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ramses_tpu.platform import force_cpu_mesh  # noqa: E402

force_cpu_mesh(8)

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Drop compiled executables after each test module.

    A full-suite run accumulates thousands of XLA:CPU executables in
    one process; past ~130 tests the NEXT compile can segfault inside
    ``backend_compile_and_load`` (reproduced twice at the same test).
    Per-module cache clearing bounds the in-process compiler state;
    each module recompiles its own programs anyway.
    """
    yield
    jax.clear_caches()
