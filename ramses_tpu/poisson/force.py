"""Gravitational acceleration from the potential.

``force_fine → gradient_phi`` (``poisson/force_fine.f90:5,199``): the
reference uses a 5-point, 4th-order finite-difference gradient with
coefficients a=0.5*4/3/dx, b=0.25*1/3/dx — i.e.
``dphi/dx = [8(phi_{+1}-phi_{-1}) - (phi_{+2}-phi_{-2})] / (12 dx)`` —
and f = -grad(phi).
"""

from __future__ import annotations

import jax.numpy as jnp


def gradient_phi(phi, dx: float):
    """4th-order central gradient, periodic wrap.  Returns [ndim, *sp]."""
    a = 2.0 / (3.0 * dx)
    b = 1.0 / (12.0 * dx)
    comps = []
    for ax in range(phi.ndim):
        d1 = jnp.roll(phi, -1, axis=ax) - jnp.roll(phi, 1, axis=ax)
        d2 = jnp.roll(phi, -2, axis=ax) - jnp.roll(phi, 2, axis=ax)
        comps.append(a * d1 - b * d2)
    return jnp.stack(comps)


def force(phi, dx: float):
    """f = -grad(phi), shape [ndim, *spatial]."""
    return -gradient_phi(phi, dx)
