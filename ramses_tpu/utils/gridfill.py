"""Leaf-cell → dense-grid block fill (shared host helper).

Used by the movie engine's live-AMR frames and available to analysis
tools: leaves at the target level scatter with ONE vectorized
fancy-index assignment (they are the vast majority on a deep
hierarchy); only the few coarser leaves loop to paint their 2^Δl
blocks.  (``utils/post.amr2cube`` keeps its own weighted accumulation
because it also volume-averages leaves FINER than the target level.)
"""

from __future__ import annotations

import numpy as np


def leaves_to_dense(pos: np.ndarray, levels: np.ndarray,
                    vals: np.ndarray, lmax: int,
                    boxlen: float) -> np.ndarray:
    """Dense [k, (2^lmax)^nd] grid from leaf centres/levels/values.

    ``pos`` [n, nd] cell centres in [0, boxlen); ``levels`` [n] the
    leaf's level (<= lmax); ``vals`` [n, k] per-leaf values,
    block-constant over each leaf's footprint.
    """
    n = 1 << lmax
    nd = pos.shape[1]
    k = vals.shape[1]
    out = np.zeros((k,) + (n,) * nd)
    levels = np.asarray(levels)
    for l in np.unique(levels):
        sel = levels == l
        span = 1 << (lmax - int(l))
        dxl = boxlen / (1 << int(l))
        i0 = np.clip(((pos[sel] - 0.5 * dxl) / boxlen * n)
                     .round().astype(int), 0, n - span)
        v = vals[sel]
        if span == 1:
            idx = tuple(i0[:, d] for d in range(nd))
            out[(slice(None),) + idx] = v.T
        else:
            for j in range(len(v)):
                sl = tuple(slice(i0[j, d], i0[j, d] + span)
                           for d in range(nd))
                out[(slice(None),) + sl] = v[j].reshape(
                    (-1,) + (1,) * nd)
    return out
