// CPU baseline proxy: 3D MUSCL-Hancock unsplit hydro, HLLC Riemann.
//
// The reference (tatary/ramses) cannot be compiled in this image (no
// Fortran compiler), so this file re-creates the algorithmic cost of its
// hot kernel — hydro/umuscl.f90: ctoprim (:861) -> uslope minmod (:970,
// slope_type=1) -> trace3d predictor (:483) -> cmpflxm/riemann per
// direction (:714) — as plain optimized C++ on a uniform grid, the same
// sedov3d levelmin=levelmax configuration that is BASELINE.md config 1.
// Measured mus-per-cell-update from this program stands in for the
// reference's self-instrumented `mus/pt` (amr/adaptive_loop.f90:204-212).
//
// Build: g++ -O3 -march=native -funroll-loops -o muscl3d muscl3d.cc
// Run:   ./muscl3d [N] [nsteps]   -> one JSON line on stdout
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <chrono>
#include <vector>

static const double GAMMA = 1.4;
static const double SMALLR = 1e-10, SMALLC = 1e-10;

struct Grid {
  int n;          // cells per side (interior)
  int s;          // stride with 2-ghost pad
  std::vector<double> u;  // [5][s^3] conservative: rho, mom xyz, E
  Grid(int n_) : n(n_), s(n_ + 4), u(5 * (size_t)(n_ + 4) * (n_ + 4) * (n_ + 4)) {}
  inline size_t idx(int v, int i, int j, int k) const {
    return ((size_t)v * s + i) * s * s + (size_t)j * s + k;
  }
};

static inline double minmod(double a, double b) {
  // slope_type=1 minmod limiter (hydro/umuscl.f90:970 dsgn/dlim branch)
  if (a * b <= 0.0) return 0.0;
  double sa = std::fabs(a), sb = std::fabs(b);
  return (a > 0 ? 1.0 : -1.0) * (sa < sb ? sa : sb);
}

// One unsplit MUSCL-Hancock step over the interior; periodic ghosts.
// prim layout per cell: rho, vx, vy, vz, p.
static void step(Grid &g, double dt) {
  const int n = g.n, s = g.s;
  const size_t nc = (size_t)s * s * s;
  static std::vector<double> q, dqx, dqy, dqz, flux;
  q.resize(5 * nc); dqx.resize(5 * nc); dqy.resize(5 * nc); dqz.resize(5 * nc);
  flux.resize(5 * nc * 3);
  const double dx = 1.0 / n, dtdx = dt / dx;

  // periodic ghost fill (2 wide) on conservative state
  for (int v = 0; v < 5; v++)
    for (int i = 0; i < s; i++)
      for (int j = 0; j < s; j++)
        for (int k = 0; k < s; k++) {
          int ii = (i - 2 + n) % n + 2, jj = (j - 2 + n) % n + 2,
              kk = (k - 2 + n) % n + 2;
          if (ii != i || jj != j || kk != k)
            g.u[g.idx(v, i, j, k)] = g.u[g.idx(v, ii, jj, kk)];
        }

  // ctoprim
  for (size_t c = 0; c < nc; c++) {
    double rho = g.u[0 * nc + c]; rho = rho > SMALLR ? rho : SMALLR;
    double inv = 1.0 / rho;
    double vx = g.u[1 * nc + c] * inv, vy = g.u[2 * nc + c] * inv,
           vz = g.u[3 * nc + c] * inv;
    double ek = 0.5 * rho * (vx * vx + vy * vy + vz * vz);
    double p = (GAMMA - 1.0) * (g.u[4 * nc + c] - ek);
    p = p > SMALLR * SMALLC ? p : SMALLR * SMALLC;
    q[0 * nc + c] = rho; q[1 * nc + c] = vx; q[2 * nc + c] = vy;
    q[3 * nc + c] = vz; q[4 * nc + c] = p;
  }

  // uslope: minmod limited central differences in each direction
  const size_t di = (size_t)s * s, dj = s, dk = 1;
  for (int v = 0; v < 5; v++)
    for (int i = 1; i < s - 1; i++)
      for (int j = 1; j < s - 1; j++)
        for (int k = 1; k < s - 1; k++) {
          size_t c = ((size_t)i) * di + (size_t)j * dj + k, b = (size_t)v * nc + c;
          dqx[b] = minmod(q[b + di] - q[b], q[b] - q[b - di]);
          dqy[b] = minmod(q[b + dj] - q[b], q[b] - q[b - dj]);
          dqz[b] = minmod(q[b + dk] - q[b], q[b] - q[b - dk]);
        }

  // trace3d: half-dt predictor in primitive variables, then per-face
  // HLLC flux (cmpflxm).  For each direction, reconstruct L/R states at
  // the face from the predicted cell states.
  auto hllc = [&](const double qL[5], const double qR[5], int d, double F[5]) {
    // rotate so velocity component d is the normal one
    int iv = 1 + d;
    double rl = qL[0], ul = qL[iv], pl = qL[4];
    double rr = qR[0], ur = qR[iv], pr = qR[4];
    double cl = std::sqrt(GAMMA * pl / rl), cr = std::sqrt(GAMMA * pr / rr);
    double sl = (ul - cl < ur - cr) ? ul - cl : ur - cr;
    double sr = (ul + cl > ur + cr) ? ul + cl : ur + cr;
    double sm = (pr - pl + rl * ul * (sl - ul) - rr * ur * (sr - ur)) /
                (rl * (sl - ul) - rr * (sr - ur) + 1e-300);
    const double *qs; double rs, us, ps, ss;
    if (sm >= 0) { qs = qL; rs = rl; us = ul; ps = pl; ss = sl; }
    else         { qs = qR; rs = rr; us = ur; ps = pr; ss = sr; }
    double pstar = ps + rs * (ss - us) * (sm - us);
    double rstar = rs * (ss - us) / (ss - sm + 1e-300);
    double e = ps / (GAMMA - 1.0) +
               0.5 * rs * (qs[1] * qs[1] + qs[2] * qs[2] + qs[3] * qs[3]);
    double estar = ((ss - us) * e - ps * us + pstar * sm) / (ss - sm + 1e-300);
    double ro, uo, po, eo, vo[3] = {qs[1], qs[2], qs[3]};
    if ((sm >= 0 && sl >= 0) || (sm < 0 && sr <= 0)) {
      ro = rs; uo = us; po = ps; eo = e;
    } else {
      ro = rstar; uo = sm; po = pstar; eo = estar;
    }
    vo[d] = uo;
    F[0] = ro * uo;
    F[1] = ro * uo * vo[0] + (d == 0 ? po : 0);
    F[2] = ro * uo * vo[1] + (d == 1 ? po : 0);
    F[3] = ro * uo * vo[2] + (d == 2 ? po : 0);
    F[4] = (eo + po) * uo;
  };

  const size_t dstep[3] = {di, dj, dk};
  for (int i = 1; i < s - 1; i++)
    for (int j = 1; j < s - 1; j++)
      for (int k = 1; k < s - 1; k++) {
        size_t c = ((size_t)i) * di + (size_t)j * dj + k;
        // predictor: q^{n+1/2} = q - dt/2 (A dq) summed over directions
        for (int d = 0; d < 3; d++) {
          size_t dd = dstep[d];
          // left state: cell c predicted, +half slope in d
          double qL[5], qR[5];
          for (int side = 0; side < 2; side++) {
            size_t cc = side == 0 ? c - dd : c;
            double *dst = side == 0 ? qL : qR;
            double r = q[0 * nc + cc], vx = q[1 * nc + cc],
                   vy = q[2 * nc + cc], vz = q[3 * nc + cc], p = q[4 * nc + cc];
            double drx = dqx[0 * nc + cc], dux = dqx[1 * nc + cc],
                   dvx = dqx[2 * nc + cc], dwx = dqx[3 * nc + cc],
                   dpx = dqx[4 * nc + cc];
            double dry = dqy[0 * nc + cc], duy = dqy[1 * nc + cc],
                   dvy = dqy[2 * nc + cc], dwy = dqy[3 * nc + cc],
                   dpy = dqy[4 * nc + cc];
            double drz = dqz[0 * nc + cc], duz = dqz[1 * nc + cc],
                   dvz = dqz[2 * nc + cc], dwz = dqz[3 * nc + cc],
                   dpz = dqz[4 * nc + cc];
            // source terms (trace3d, hydro/umuscl.f90:483): primitive
            // evolution r' = -(u r_x + r u_x) - ... etc., half dt
            double sr0 = -(vx * drx + vy * dry + vz * drz)
                         - (dux + dvy + dwz) * r;
            double su0 = -(vx * dux + vy * duy + vz * duz) - dpx / r;
            double sv0 = -(vx * dvx + vy * dvy + vz * dvz) - dpy / r;
            double sw0 = -(vx * dwx + vy * dwy + vz * dwz) - dpz / r;
            double sp0 = -(vx * dpx + vy * dpy + vz * dpz)
                         - (dux + dvy + dwz) * GAMMA * p;
            double half = 0.5 * dtdx;
            double rp = r + half * sr0, up = vx + half * su0,
                   vp = vy + half * sv0, wp = vz + half * sw0,
                   pp = p + half * sp0;
            // interpolate to the face: +/- half slope along d
            double sgn = side == 0 ? 0.5 : -0.5;
            const double *dq = d == 0 ? &dqx[0] : d == 1 ? &dqy[0] : &dqz[0];
            dst[0] = rp + sgn * dq[0 * nc + cc];
            dst[1] = up + sgn * dq[1 * nc + cc];
            dst[2] = vp + sgn * dq[2 * nc + cc];
            dst[3] = wp + sgn * dq[3 * nc + cc];
            dst[4] = pp + sgn * dq[4 * nc + cc];
            if (dst[0] < SMALLR) dst[0] = SMALLR;
            if (dst[4] < SMALLR * SMALLC) dst[4] = SMALLR * SMALLC;
          }
          hllc(qL, qR, d, &flux[(d * 5) * nc + c]);
        }
      }

  // conservative update: u -= dtdx * (F_{i+1} - F_i) per direction
  for (int v = 0; v < 5; v++)
    for (int i = 2; i < 2 + n; i++)
      for (int j = 2; j < 2 + n; j++)
        for (int k = 2; k < 2 + n; k++) {
          size_t c = ((size_t)i) * di + (size_t)j * dj + k;
          double d0 = flux[(0 * 5 + v) * nc + c + di] - flux[(0 * 5 + v) * nc + c];
          double d1 = flux[(1 * 5 + v) * nc + c + dj] - flux[(1 * 5 + v) * nc + c];
          double d2 = flux[(2 * 5 + v) * nc + c + dk] - flux[(2 * 5 + v) * nc + c];
          g.u[(size_t)v * nc + c] -= dtdx * (d0 + d1 + d2);
        }
}

int main(int argc, char **argv) {
  int n = argc > 1 ? atoi(argv[1]) : 128;
  int nsteps = argc > 2 ? atoi(argv[2]) : 5;
  Grid g(n);
  const size_t nc = (size_t)g.s * g.s * g.s;
  // sedov-like ICs: cold uniform medium + central energy point
  for (int i = 2; i < 2 + n; i++)
    for (int j = 2; j < 2 + n; j++)
      for (int k = 2; k < 2 + n; k++) {
        size_t c = g.idx(0, i, j, k);
        g.u[c] = 1.0;
        g.u[4 * nc + (c - 0)] = 1e-5 / (GAMMA - 1.0);
      }
  int m = 2 + n / 2;
  g.u[g.idx(4, m, m, m)] = 0.4 * n * n * n / (GAMMA - 1.0) * 1e-5 + 1.0;

  // warm-up step (first touch, page faults)
  step(g, 1e-6);
  auto t0 = std::chrono::steady_clock::now();
  for (int it = 0; it < nsteps; it++) step(g, 1e-6);
  auto t1 = std::chrono::steady_clock::now();
  double wall = std::chrono::duration<double>(t1 - t0).count();
  double updates = (double)n * n * n * nsteps;
  printf("{\"proxy\": \"muscl3d-hllc\", \"n\": %d, \"steps\": %d, "
         "\"wall_s\": %.4f, \"mus_per_cell_update\": %.4f, "
         "\"cell_updates_per_sec\": %.3e}\n",
         n, nsteps, wall, 1e6 * wall / updates, updates / wall);
  return 0;
}
