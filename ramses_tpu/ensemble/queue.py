"""File-backed submit/claim/complete job queue for the run service.

The queue is a directory with one JSON record per job, and a job's
lifecycle IS its location: ``queued/`` -> ``running/`` -> ``done/`` or
``failed/`` (plus ``parked/``, where the poison-config circuit breaker
— :mod:`ramses_tpu.ensemble.breaker` — sidelines jobs whose frozen
config keeps killing workers).  Every transition is a single
``os.rename`` on the same filesystem, so claiming is atomic — two
workers racing for one job see exactly one rename succeed and one
``FileNotFoundError`` (the AMT task-queue scheduling shape,
arXiv:2412.15518, reduced to POSIX).

Claims are **fenced**: every claim (and every stale reclaim) bumps a
monotone ``fence`` generation token in the record, and every
worker-side write — heartbeat, ``complete()``/``fail()``/``requeue()``
— re-reads the on-disk record and refuses to proceed when its token is
stale (:class:`FenceLost`).  A worker that stalls past the staleness
timeout and then *recovers* (a zombie) therefore cannot double-complete
a job another worker already took over: its late writes are refused and
logged as ``stage="fenced"`` ``failure_log`` entries on the record.

Liveness is a **content heartbeat**, not an mtime: the worker writes a
``<id>.json.hb`` sidecar carrying (fence, a worker-local monotone
sequence counter, wall time), and :func:`reclaim_stale` judges
staleness by *observing the sequence counter stand still* on its own
monotonic clock — clock skew between hosts (or a skewed wall stamp)
cannot false-trip a reclaim by itself.  A record with no heartbeat at
all falls back to the record mtime, the pre-fencing signal.  Results
(telemetry JSONL + checkpoints) land under ``results/<job>/``.
"""

from __future__ import annotations

import itertools
import json
import os
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

STATES = ("queued", "running", "done", "failed", "parked")

#: heartbeat sidecar suffix (rides next to the running record; never
#: matches the ``*.json`` record filters)
HB_SUFFIX = ".hb"


class FenceLost(RuntimeError):
    """A worker-side queue write was refused because the claim's
    fencing token no longer matches the on-disk record — the job was
    reclaimed (and possibly re-claimed) while this worker stalled.
    The worker must abandon the job: it owns neither the record nor
    the right to complete/fail/requeue it."""


@dataclass
class Job:
    """A claimed (or inspected) job: its id, current record path and
    parsed record dict."""
    id: str
    path: str
    record: Dict[str, Any]

    @property
    def state(self) -> str:
        return os.path.basename(os.path.dirname(self.path))

    @property
    def fence(self) -> int:
        """The fencing token this claim holds (the in-memory record is
        the claim-time snapshot; reclaims bump only the on-disk one)."""
        return int(self.record.get("fence", 0) or 0)


def _dirs(queue_dir: str) -> Dict[str, str]:
    return {s: os.path.join(queue_dir, s) for s in STATES}


def init_queue(queue_dir: str) -> str:
    for d in _dirs(queue_dir).values():
        os.makedirs(d, exist_ok=True)
    os.makedirs(os.path.join(queue_dir, "results"), exist_ok=True)
    return queue_dir


def results_dir(queue_dir: str, job_id: str) -> str:
    return os.path.join(queue_dir, "results", job_id)


def _write_record(path: str, record: Dict[str, Any]) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def submit(queue_dir: str, namelist: str,
           sweeps: Optional[Dict[str, List[Any]]] = None,
           solver: str = "", ndim: int = 3, dtype: str = "float32",
           job_id: str = "", meta: Optional[Dict[str, Any]] = None,
           kind: str = "run") -> str:
    """Enqueue a job: ``namelist`` is the full namelist *text* (the
    record is self-contained — workers need no shared checkout), plus
    optional explicit per-member ``sweeps``.  ``kind`` dispatches the
    worker-side handler first-class — ``"run"`` (forward ensemble,
    default) or ``"calibrate"`` (gradient-descent calibration,
    ramses_tpu/diff) — instead of being sniffed from the payload.
    Returns the job id."""
    init_queue(queue_dir)
    if kind not in ("run", "calibrate"):
        raise ValueError(f"unknown job kind {kind!r}")
    if not job_id:
        job_id = f"job-{time.time_ns():020d}-{os.getpid()}"
    path = os.path.join(queue_dir, "queued", job_id + ".json")
    if os.path.exists(path):
        raise FileExistsError(f"job id '{job_id}' already queued")
    from ramses_tpu.obs.trace import new_trace_id
    record = {
        "id": job_id, "kind": kind, "namelist": namelist,
        "sweeps": dict(sweeps or {}), "solver": solver,
        "ndim": int(ndim), "dtype": dtype,
        "submitted_unix": time.time(), "attempts": 0,
        # fencing generation: bumped by every claim and every stale
        # reclaim; a worker holding an older token has lost the job
        "fence": 0,
        # end-to-end correlation id (ramses_tpu/obs/trace): stamped
        # here once, then propagated into every telemetry record,
        # failure_log entry and checkpoint manifest this job produces
        "trace_id": new_trace_id(),
        "meta": dict(meta or {})}
    # frozen-config fingerprint: the poison-config circuit breaker
    # (ensemble/breaker) keys cross-worker failure counting on it
    try:
        from ramses_tpu.ensemble.breaker import config_fingerprint
        record["config_fp"] = config_fingerprint(record)
    except Exception:
        pass
    # submit-time cost stamp (members x cells x steps + shard clamps):
    # the currency plan_gang bin-packs on.  Strictly best-effort — an
    # unparseable namelist submits unstamped and schedules as a small
    # FIFO job (the failure then surfaces on the worker, with a log).
    try:
        from ramses_tpu.ensemble.meshplan import stamp_cost
        cost = stamp_cost(namelist, ndim=int(ndim), sweeps=sweeps,
                          solver=solver, kind=kind)
        if cost is not None:
            record["cost"] = cost
    except Exception:
        pass
    _write_record(path, record)
    return job_id


def job_kind(record: Dict[str, Any]) -> str:
    """The job's dispatch kind; records written before the field existed
    default to ``"run"``."""
    return str(record.get("kind") or "run")


def claim(queue_dir: str, worker: str = "",
          job_id: str = "") -> Optional[Job]:
    """Atomically claim the oldest *eligible* queued job (rename into
    ``running/``), bump its attempt count and fencing token, stamp the
    claim time and write the first content heartbeat.  Returns None
    when the queue is empty; racing workers each get a distinct job or
    None.  A record inside its requeue-backoff window
    (``not_before_unix`` in the future) is skipped by the FIFO scan so
    a failing job cannot thundering-herd the fleet.  ``job_id`` claims
    that specific job instead of the FIFO head — the gang scheduler
    plans from a :func:`peek_queued` snapshot and then claims each
    planned job by id, dropping any it loses to a racing worker."""
    dirs = _dirs(queue_dir)
    worker = worker or f"{os.uname().nodename}:{os.getpid()}"
    if job_id:
        names = [job_id + ".json"]
    else:
        try:
            names = sorted(n for n in os.listdir(dirs["queued"])
                           if n.endswith(".json"))
        except FileNotFoundError:
            return None
    now = time.time()
    for name in names:
        src = os.path.join(dirs["queued"], name)
        dst = os.path.join(dirs["running"], name)
        if not job_id:
            # backoff eligibility pre-read (tolerant: a record renamed
            # or half-written under us is simply someone else's)
            try:
                with open(src) as f:
                    rec0 = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            if float(rec0.get("not_before_unix") or 0.0) > now:
                continue               # still in its backoff window
        try:
            os.rename(src, dst)        # the atomic claim
        except OSError:
            continue                   # another worker won this one
        with open(dst) as f:
            record = json.load(f)
        record["attempts"] = int(record.get("attempts", 0)) + 1
        # fenced claim: the new generation token; every write this
        # worker makes on behalf of the job carries (and re-verifies)
        # it, so a reclaimed predecessor cannot finish over us
        record["fence"] = int(record.get("fence", 0)) + 1
        record["worker"] = worker
        record["claimed_unix"] = time.time()
        record.pop("not_before_unix", None)
        _write_record(dst, record)
        job = Job(id=record["id"], path=dst, record=record)
        heartbeat(job)                 # claim goes live immediately
        return job
    return None


def peek_queued(queue_dir: str) -> List[Dict[str, Any]]:
    """Snapshot the queued records in FIFO (file-name = submit) order
    without claiming anything — the gang scheduler's planning input.
    Records that vanish or fail to parse mid-listing are skipped (a
    racing worker claimed them, or a submit is mid-flight)."""
    dirs = _dirs(queue_dir)
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(n for n in os.listdir(dirs["queued"])
                       if n.endswith(".json"))
    except (FileNotFoundError, NotADirectoryError):
        return out
    for name in names:
        try:
            with open(os.path.join(dirs["queued"], name)) as f:
                out.append(json.load(f))
        except (OSError, json.JSONDecodeError):
            continue
    return out


def _is_exclusive(record: Dict[str, Any]) -> bool:
    """Mesh-wide jobs drain the gang and run alone: a cost stamp with
    ``exclusive`` (per-member cells above the pack budget), or a
    non-``run`` kind (calibrate drives its own optimizer loop and
    shares no chunk cadence to gang on)."""
    cost = record.get("cost") or {}
    return bool(cost.get("exclusive")) or job_kind(record) != "run"


def plan_gang(records: List[Dict[str, Any]], ndev: int,
              order: str = "cost", now: Optional[float] = None,
              starve_s: float = 600.0
              ) -> List[Tuple[Dict[str, Any], int]]:
    """Pure gang-scheduling decision: which queued jobs to claim next
    and how many devices each gets.  No filesystem, no jax — the unit-
    testable core of the cost-aware serve loop.

    ``records`` is a FIFO-ordered :func:`peek_queued` snapshot;
    ``ndev`` the local device count.  Returns ``[(record, nshard),
    ...]`` whose nshards sum to at most ``ndev``.

    ``order="cost"`` (the default claim order):

    * an *exclusive* job (cost stamp says mesh-wide, or a calibrate)
      that has waited longer than ``starve_s`` preempts everything —
      the starvation bound: bin-packed small jobs can only overtake a
      big job for so long;
    * otherwise small jobs are greedily bin-packed cost-ascending
      (cheapest first — they drain soonest, keeping queue latency
      low), each granted its ``min_shards`` first and leftover devices
      spread round-robin up to ``min(max_shards, members)``;
    * with no packable small jobs, the oldest exclusive job takes the
      whole mesh.

    ``order="fifo"`` is the fallback knob: strictly the head job, all
    devices — the pre-scheduler behavior."""
    if not records:
        return []
    ndev = max(1, int(ndev))
    if order == "fifo":
        return [(records[0], ndev)]
    if order != "cost":
        raise ValueError(f"unknown claim order {order!r}")
    now = time.time() if now is None else float(now)
    exclusive = [r for r in records if _is_exclusive(r)]
    small = [r for r in records if not _is_exclusive(r)]
    starving = [r for r in exclusive
                if now - float(r.get("submitted_unix", now))
                >= float(starve_s)]
    if starving:
        return [(starving[0], ndev)]
    if not small:
        return [(exclusive[0], ndev)] if exclusive else []
    small = sorted(small, key=lambda r: int(
        (r.get("cost") or {}).get("cost") or 0))
    gang: List[List[Any]] = []
    avail = ndev

    def _clamps(rec):
        c = rec.get("cost") or {}
        lo = max(1, int(c.get("min_shards") or 1))
        hi = int(c.get("max_shards") or 0) or ndev
        # packed replicas cannot exceed the member count — extra
        # devices would idle, so leave them for the next job
        hi = min(hi, max(1, int(c.get("members") or 1)))
        return lo, max(lo, hi)

    for rec in small:
        lo, _hi = _clamps(rec)
        if lo > avail:
            continue                   # next gang, once devices free
        gang.append([rec, lo])
        avail -= lo
        if avail <= 0:
            break
    if not gang:
        return [(exclusive[0], ndev)] if exclusive else []
    grew = True
    while avail > 0 and grew:
        grew = False
        for entry in gang:
            if avail <= 0:
                break
            _lo, hi = _clamps(entry[0])
            if entry[1] < hi:
                entry[1] += 1
                avail -= 1
                grew = True
    return [(rec, int(n)) for rec, n in gang]


# ---------------------------------------------------------------------
# fenced heartbeats
# ---------------------------------------------------------------------

#: worker-local monotone heartbeat sequence — the progression signal
#: reclaim observes; shared across this process's claims on purpose
#: (any advance proves the worker's host thread is alive)
_hb_seq = itertools.count(1)


def _hb_path(job_path: str) -> str:
    return job_path + HB_SUFFIX


def _read_hb(job_path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(_hb_path(job_path)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _unlink_hb(job_path: str) -> None:
    try:
        os.unlink(_hb_path(job_path))
    except OSError:
        pass


def _is_enospc(err: BaseException) -> bool:
    import errno
    return isinstance(err, OSError) and err.errno == errno.ENOSPC


def heartbeat(job: Job) -> None:
    """Refresh the worker liveness signal: a fence-checked *content*
    record (``<id>.json.hb``) carrying this process's monotone
    sequence counter plus wall time — :func:`reclaim_stale` keys on
    the counter standing still under its own clock, so neither
    host-to-host clock skew nor a biased wall stamp can fake liveness
    or death by itself.  Raises :class:`FenceLost` when the on-disk
    record's fencing token no longer matches this claim (the job was
    reclaimed while the worker stalled) — the one place a zombie
    worker reliably discovers it must abandon the job."""
    _check_fence(job, "heartbeat")
    skew = 0.0
    try:
        from ramses_tpu.resilience.faultinject import heartbeat_skew
        skew = heartbeat_skew()
    except Exception:
        pass
    rec = {"job": job.id, "fence": job.fence, "seq": next(_hb_seq),
           "wall_unix": time.time() + skew,
           "mono_s": time.monotonic(),
           "worker": str(job.record.get("worker", ""))}
    try:
        _write_record(_hb_path(job.path), rec)
    except OSError as e:
        if not _is_enospc(e):
            raise
        # full disk degrades, never crashes the worker: fall back to
        # the zero-byte mtime bump so liveness survives ENOSPC
        try:
            os.utime(job.path)
        except OSError:
            pass


def _age_heartbeat(job_path: str, by_s: float) -> None:
    """Test/ops helper: make a running record's heartbeat look
    ``by_s`` seconds old — content wall stamp AND file mtimes — the
    simulation of a worker that died that long ago."""
    hbp = _hb_path(job_path)
    try:
        with open(hbp) as f:
            hb = json.load(f)
        hb["wall_unix"] = float(hb.get("wall_unix", time.time())) - by_s
        _write_record(hbp, hb)
    except (OSError, json.JSONDecodeError):
        pass
    old = time.time() - by_s
    for p in (job_path, hbp):
        try:
            os.utime(p, (old, old))
        except OSError:
            pass


def _check_fence(job: Job, op: str, telemetry=None) -> None:
    """Verify this claim still owns the record: the on-disk fencing
    token must equal the claim's.  On mismatch the refusal is made
    durable — a ``stage="fenced"`` entry lands in the canonical
    record's ``failure_log`` wherever the record now lives — and
    :class:`FenceLost` is raised."""
    try:
        with open(job.path) as f:
            disk = json.load(f)
        ok = int(disk.get("fence", 0) or 0) == job.fence
    except (OSError, json.JSONDecodeError):
        ok = False
    if ok:
        return
    queue_dir = os.path.dirname(os.path.dirname(job.path))
    cur = job_status(queue_dir, job.id)
    if cur is not None:
        where = (f"record now in {cur.state}/ at fence "
                 f"{cur.record.get('fence', '?')}")
    else:
        where = "record gone"
    msg = (f"fenced write refused: {op} by "
           f"{job.record.get('worker', '?')} holds fence "
           f"{job.fence}; {where}")
    if cur is not None:
        cur.record.setdefault("failure_log", []).append({
            "error": msg, "stage": "fenced",
            "kind": job_kind(cur.record),
            "attempt": int(job.record.get("attempts", 0)),
            "worker": str(job.record.get("worker", "")),
            "trace_id": str(cur.record.get("trace_id", "")),
            "time_unix": time.time()})
        try:
            _write_record(cur.path, cur.record)
        except OSError:
            pass
    _emit(telemetry, "queue_fenced", job=job.id, op=op,
          fence=job.fence, worker=str(job.record.get("worker", "")),
          trace_id=str(job.record.get("trace_id", "")))
    raise FenceLost(msg)


def _backoff_delay(attempts: int, base_s: float,
                   cap_s: float = 60.0) -> float:
    """Jittered exponential requeue backoff: attempt 1 -> ~base,
    doubling, capped; the jitter (0.5x..1x) decorrelates a fleet of
    workers eyeing the same bounced job."""
    if base_s <= 0.0:
        return 0.0
    import random
    raw = min(float(cap_s), float(base_s)
              * (2.0 ** max(0, int(attempts) - 1)))
    return raw * (0.5 + 0.5 * random.random())


def _log_failure(record: Dict[str, Any], error: str,
                 stage: str) -> None:
    """Append one attempt's failure to the record's ``failure_log``.
    The log rides the record file through every requeue/reclaim, so a
    job that bounced across three workers arrives in ``failed/`` with
    the full history instead of only the last error."""
    record.setdefault("failure_log", []).append({
        "error": str(error), "stage": stage,
        "kind": job_kind(record),
        "attempt": int(record.get("attempts", 0)),
        "worker": record.get("worker", ""),
        "trace_id": record.get("trace_id", ""),
        "time_unix": time.time()})
    record["error"] = str(error)


def _emit(telemetry, kind: str, **fields) -> None:
    if telemetry is not None:
        try:
            telemetry.record_event(kind, **fields)
        except Exception:
            pass


def _breaker_note(job: Job, stage: str, failed: bool,
                  telemetry=None) -> None:
    """Feed the poison-config circuit breaker (best-effort): worker-
    attributable failures only — stale reclaims, drains and fenced
    refusals say nothing about the config."""
    if stage in ("stale", "drain", "fenced"):
        return
    try:
        from ramses_tpu.ensemble import breaker as _bk
        queue_dir = os.path.dirname(os.path.dirname(job.path))
        _bk.record_failure(queue_dir, job.record, stage,
                           telemetry=telemetry)
    except Exception:
        pass


def complete(job: Job, result: Optional[Dict[str, Any]] = None) -> str:
    """running -> done, folding ``result`` (artifact paths, final t/
    nstep) into the record.  Fence-checked: a reclaimed zombie's late
    ``complete()`` raises :class:`FenceLost` instead of producing a
    second ``done/`` entry.  A success half-opens nothing — it CLOSES
    any matching poison-config breaker and releases parked twins."""
    dst = _finish(job, "done", result=result)
    try:
        from ramses_tpu.ensemble import breaker as _bk
        queue_dir = os.path.dirname(os.path.dirname(dst))
        _bk.on_success(queue_dir, job.record)
    except Exception:
        pass
    return dst


def fail(job: Job, error: str = "",
         result: Optional[Dict[str, Any]] = None,
         telemetry=None, stage: str = "fail") -> str:
    """running -> failed with the error appended to the accumulated
    ``failure_log`` (and recorded as the headline ``error``).
    ``stage`` labels the log entry — the serve loop passes ``"hang"``
    for deadline-killed jobs so the classification survives in the
    record.  Fence-checked like :func:`complete`."""
    if error:
        _log_failure(job.record, error, stage)
    _emit(telemetry, "queue_fail", job=job.id,
          trace_id=job.record.get("trace_id", ""),
          attempts=int(job.record.get("attempts", 0)), error=error,
          stage=stage)
    dst = _finish(job, "failed", result=result, error=error)
    _breaker_note(job, stage, failed=True, telemetry=telemetry)
    return dst


def requeue(job: Job, error: str = "", telemetry=None,
            stage: str = "requeue", backoff_base_s: float = 0.0,
            backoff_cap_s: float = 60.0,
            count_attempt: bool = True) -> str:
    """running -> queued (a failed attempt with attempts remaining);
    the attempt count stays — :func:`claim` bumps it on the next
    worker.  The attempt's error is appended to ``failure_log``, which
    survives the requeue because it lives in the record file.
    ``stage`` labels the entry (``"hang"`` for kill-and-requeue,
    ``"drain"`` for a SIGTERM graceful drain).

    ``backoff_base_s > 0`` stamps a jittered-exponential
    ``not_before_unix`` eligibility gate into the record so a job that
    keeps bouncing does not thundering-herd the fleet's claim scans.
    ``count_attempt=False`` refunds the claim's attempt bump (a drain
    is the worker's fault, not the job's).  Fence-checked."""
    _check_fence(job, "requeue", telemetry=telemetry)
    if error:
        _log_failure(job.record, error, stage)
    if not count_attempt:
        job.record["attempts"] = max(
            0, int(job.record.get("attempts", 0)) - 1)
    delay = 0.0
    if stage != "drain":
        delay = _backoff_delay(int(job.record.get("attempts", 0)),
                               backoff_base_s, backoff_cap_s)
    if delay > 0.0:
        job.record["not_before_unix"] = time.time() + delay
    else:
        job.record.pop("not_before_unix", None)
    _emit(telemetry, "queue_requeue", job=job.id,
          trace_id=job.record.get("trace_id", ""),
          attempts=int(job.record.get("attempts", 0)), error=error,
          stage=stage, backoff_s=round(delay, 3))
    _write_record(job.path, job.record)
    hb_of = job.path
    dst = os.path.join(os.path.dirname(os.path.dirname(job.path)),
                       "queued", os.path.basename(job.path))
    os.rename(job.path, dst)
    _unlink_hb(hb_of)
    job.path = dst
    if stage not in ("drain",):
        _breaker_note(job, stage, failed=False, telemetry=telemetry)
    return dst


def _finish(job: Job, state: str, result=None, error: str = "") -> str:
    _check_fence(job, state)
    job.record["finished_unix"] = time.time()
    if result:
        job.record["result"] = result
    if error:
        job.record["error"] = error
    _write_record(job.path, job.record)
    hb_of = job.path
    dst = os.path.join(os.path.dirname(os.path.dirname(job.path)),
                       state, os.path.basename(job.path))
    os.rename(job.path, dst)
    _unlink_hb(hb_of)
    job.path = dst
    return dst


# ---------------------------------------------------------------------
# stale reclaim: fencing token + heartbeat progression as authority
# ---------------------------------------------------------------------

#: observer-side heartbeat progression cache:
#: (queue_dir, job, fence, seq) -> monotonic time first observed.
#: Staleness = the SAME (fence, seq) observed for stale_s of the
#: observer's own clock — immune to writer-side clock skew.
_hb_observed: Dict[Tuple[str, str, int, int], float] = {}


def _hb_age(queue_dir: str, path: str, record: Dict[str, Any],
            now: float, now_mono: float,
            current_keys: set) -> Optional[float]:
    """Effective heartbeat age of one running record, or None when the
    record vanished under us.  Authority order:

    1. a content heartbeat whose fence matches the record: the larger
       of (a) observer-clock age since its (fence, seq) was first
       seen and (b) the heartbeat's own claimed age — counted only as
       far as BOTH its wall stamp and its file mtime agree (min of
       the two), so a skewed wall stamp alone — or a skewed
       filesystem clock alone — cannot fake death, while a worker
       dead since before this observer started is still condemned;
    2. a heartbeat with a MISMATCHED fence is a dead claim: infinite
       age (the token was already superseded — nothing live holds it);
    3. no heartbeat at all: the record mtime, the legacy signal.
    """
    hb = _read_hb(path)
    fence = int(record.get("fence", 0) or 0)
    if hb is not None and int(hb.get("fence", -1)) != fence:
        return float("inf")            # superseded token: dead claim
    if hb is not None:
        key = (queue_dir, str(record.get("id", "")), fence,
               int(hb.get("seq", 0)))
        current_keys.add(key)
        _hb_observed.setdefault(key, now_mono)
        wall_age = max(0.0, now - float(hb.get("wall_unix", now)))
        try:
            mtime_age = max(0.0, now - os.path.getmtime(
                _hb_path(path)))
        except OSError:
            mtime_age = 0.0
        return max(now_mono - _hb_observed[key],
                   min(wall_age, mtime_age))
    try:
        return now - os.path.getmtime(path)
    except OSError:
        return None                    # finished/reclaimed under us


def _reclaim_one(queue_dir: str, name: str, record: Dict[str, Any],
                 age: float, max_attempts: int, now: float,
                 backoff_base_s: float = 0.0,
                 backoff_cap_s: float = 60.0) -> Optional[str]:
    """Move one stale running record out: bump the fencing token (the
    zombie's is now refused everywhere), requeue or fail by attempt
    budget, stamp the reclaim backoff.  Returns the destination state
    or None when a racing caller won the rename."""
    dirs = _dirs(queue_dir)
    path = os.path.join(dirs["running"], name)
    attempts = int(record.get("attempts", 0))
    state = "queued" if attempts < max_attempts else "failed"
    _log_failure(record, f"stale worker (no heartbeat progress for "
                 f"{age:.0f}s, attempt {attempts})", "stale")
    if state == "queued":
        # the stale note is bookkeeping, not the job's verdict
        record.pop("error", None)
        delay = _backoff_delay(attempts, backoff_base_s, backoff_cap_s)
        if delay > 0.0:
            record["not_before_unix"] = now + delay
    record["reclaimed_unix"] = now
    # fence the dead claim out: every write the zombie attempts from
    # here on compares its token against this bumped generation
    record["fence"] = int(record.get("fence", 0)) + 1
    dst = os.path.join(dirs[state], name)
    try:
        _write_record(path, record)
        os.rename(path, dst)
    except OSError:
        return None
    _unlink_hb(path)
    return state


def reclaim_stale(queue_dir: str, stale_s: float = 300.0,
                  max_attempts: int = 3, log=print,
                  telemetry=None, backoff_base_s: float = 0.0,
                  backoff_cap_s: float = 60.0) -> int:
    """Requeue running jobs whose heartbeat has made no progress for
    ``stale_s`` (a dead/preempted worker); jobs already at
    ``max_attempts`` go to ``failed/`` instead.  Returns the number of
    records moved.

    The authority is the **fencing token + heartbeat content**, not an
    mtime: a claim whose token was superseded is reclaimed on sight; a
    live claim is one whose heartbeat *sequence counter advances* —
    judged on the observer's own monotonic clock (see
    :func:`_hb_age`), so clock skew cannot false-trip a reclaim, and a
    zombie that later resumes is refused by the bumped token anyway.
    Safe to call concurrently — the rename either succeeds for exactly
    one caller or raises and is skipped."""
    dirs = _dirs(queue_dir)
    qabs = os.path.abspath(queue_dir)
    now = time.time()
    now_mono = time.monotonic()
    moved = 0
    current_keys: set = set()
    try:
        names = sorted(n for n in os.listdir(dirs["running"])
                       if n.endswith(".json"))
    except FileNotFoundError:
        return 0
    for name in names:
        path = os.path.join(dirs["running"], name)
        try:
            with open(path) as f:
                record = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue                   # finished/reclaimed under us
        age = _hb_age(qabs, path, record, now, now_mono, current_keys)
        if age is None or age < stale_s:
            continue
        attempts = int(record.get("attempts", 0))
        state = _reclaim_one(qabs, name, record, age, max_attempts,
                             now, backoff_base_s=backoff_base_s,
                             backoff_cap_s=backoff_cap_s)
        if state is None:
            continue
        moved += 1
        _emit(telemetry, "queue_reclaim", job=record.get("id", name),
              trace_id=record.get("trace_id", ""),
              attempts=attempts, to=state,
              fence=int(record.get("fence", 0)),
              heartbeat_age_s=round(min(age, 1e12), 1))
        if log is not None:
            log(f"queue: reclaimed {record.get('id', name)} -> {state} "
                f"(heartbeat {age:.0f}s stale, attempt {attempts}, "
                f"fence -> {int(record.get('fence', 0))})")
    # drop observations for keys no longer current (job finished,
    # moved, or its heartbeat advanced) so the cache stays bounded
    for key in [k for k in _hb_observed
                if k[0] == qabs and k not in current_keys]:
        del _hb_observed[key]
    return moved


def unpark(queue_dir: str, job_id: str, note: str = "") -> bool:
    """parked -> queued (breaker half-open probe release / operator
    reset / fsck repair of an orphaned park).  Clears the backoff gate
    so the released job is immediately claimable.  Returns False when
    the job is not parked (raced away)."""
    src = os.path.join(queue_dir, "parked", job_id + ".json")
    try:
        with open(src) as f:
            record = json.load(f)
    except (OSError, json.JSONDecodeError):
        return False
    record.pop("not_before_unix", None)
    record.pop("parked_by", None)
    if note:
        record.setdefault("failure_log", []).append({
            "error": note, "stage": "unpark",
            "kind": job_kind(record),
            "attempt": int(record.get("attempts", 0)),
            "worker": "", "trace_id": record.get("trace_id", ""),
            "time_unix": time.time()})
    dst = os.path.join(queue_dir, "queued", job_id + ".json")
    try:
        _write_record(src, record)
        os.rename(src, dst)
    except OSError:
        return False
    return True


def job_status(queue_dir: str, job_id: str) -> Optional[Job]:
    """Find a job in any state dir (None when unknown).  Tolerates a
    record being renamed between the existence check and the read (a
    racing claim/finish) by moving on to the next state dir."""
    for state, d in _dirs(queue_dir).items():
        path = os.path.join(d, job_id + ".json")
        try:
            with open(path) as f:
                return Job(id=job_id, path=path, record=json.load(f))
        except (OSError, json.JSONDecodeError):
            continue
    return None


def queue_counts(queue_dir: str) -> Dict[str, int]:
    out = {}
    for state, d in _dirs(queue_dir).items():
        try:
            out[state] = len([n for n in os.listdir(d)
                              if n.endswith(".json")])
        except (FileNotFoundError, NotADirectoryError):
            out[state] = 0
    return out
