"""RT ↔ hydro coupling on the uniform grid.

The in-driver role of the reference's ``rt_step`` call chain
(``amr/amr_step.f90:594-672``: rho/T from the hydro state → subcycled
M1 transport + thermochemistry → photoheated temperature written back
into the gas energy).  Unit bridging follows ``amr/units.f90`` /
``rt/rt_init.f90``: the RT system runs in cgs, the gas in user units.
"""

from __future__ import annotations


import jax.numpy as jnp
import numpy as np

from ramses_tpu.rt.driver import RtSim, RtSpec
from ramses_tpu.units import X_frac, mH


class RtCoupled:
    """Owns an :class:`RtSim` whose density/temperature track the gas."""

    def __init__(self, params, grid, un, u0):
        self.params = params
        self.grid = grid
        self.un = un
        spec = RtSpec.from_params(params)
        self.spec = spec
        x_frac = 1.0 - spec.y_he if spec.y_he > 0 else X_frac
        self.x_frac = x_frac
        dx_cgs = grid.dx * un.scale_l
        nH = np.asarray(u0[0], np.float64) * un.scale_d * x_frac / mH
        self.sim = RtSim(grid.shape, dx_cgs, spec, nH,
                         T=self._gas_T(u0))
        r = params.rt
        # photon-budget bookkeeping for rt_stats: total registered
        # source rate [photons/s] and cumulative injected count
        self._ndot_total = 0.0
        self._injected = 0.0
        if float(r.rt_ndot) > 0.0:
            # rt_src_pos is in box-fraction units → cgs position
            pos = [float(v) * dx_cgs * grid.shape[d]
                   for d, v in enumerate(r.rt_src_pos[:spec.ndim])]
            self.sim.point_source(pos, float(r.rt_ndot))
            self._ndot_total += float(r.rt_ndot)
        # rt_nsource point/beam list (rad_beams.nml usage): per-source
        # box-unit centres, photons/s rates, optional beam direction
        for k in range(int(r.rt_nsource)):
            stype = (r.rt_source_type[k]
                     if k < len(r.rt_source_type) else "point")
            if str(stype).strip("'\" ") != "point":
                raise NotImplementedError(
                    f"rt_source_type={stype!r}: only 'point' sources "
                    "are wired (shells/squares via &RT_REGIONS role)")
            cen = [r.rt_src_x_center, r.rt_src_y_center,
                   r.rt_src_z_center]
            pos = [(float(cen[d][k]) if k < len(cen[d]) else 0.0)
                   * dx_cgs * grid.shape[d] for d in range(spec.ndim)]
            uvw = [r.rt_u_source, r.rt_v_source, r.rt_w_source]
            direction = None
            if any(k < len(uvw[d]) and float(uvw[d][k]) != 0.0
                   for d in range(spec.ndim)):
                direction = [float(uvw[d][k]) if k < len(uvw[d]) else 0.0
                             for d in range(spec.ndim)]
            rate = (float(r.rt_n_source[k])
                    if k < len(r.rt_n_source) else 0.0)
            self.sim.point_source(pos, rate, direction=direction)
            self._ndot_total += rate

    # ------------------------------------------------------------------
    def rt_stats(self, sim=None) -> dict:
        """Photon-budget stats (the reference's ``output_rt_stats``
        role): live photon count vs cumulative injected."""
        tot = self.sim.photon_total()
        inj = float(self._injected)
        return {"photons": tot, "injected": inj,
                "ratio": (tot / inj) if inj > 0.0 else 0.0}

    def _mu(self):
        """Mean molecular weight from the current ion state."""
        x = np.asarray(self.sim.x, np.float64)
        y = self.spec.y_he
        if y > 0:
            xh2 = np.asarray(self.sim.xHe2, np.float64)
            xh3 = np.asarray(self.sim.xHe3, np.float64)
            denom = (1.0 - y) * (1.0 + x) + 0.25 * y * (1.0 + xh2
                                                        + 2.0 * xh3)
        else:
            denom = (1.0 + x)
        return 1.0 / np.maximum(denom, 1e-10)

    def _gas_T(self, u):
        """Temperature [K] from the conservative gas state."""
        cfg = self.grid.cfg
        rho = np.maximum(np.asarray(u[0], np.float64), cfg.smallr)
        mom2 = sum(np.asarray(u[1 + d], np.float64) ** 2
                   for d in range(cfg.ndim))
        eint = np.asarray(u[cfg.ndim + 1], np.float64) - 0.5 * mom2 / rho
        p = (cfg.gamma - 1.0) * np.maximum(eint, 1e-300)
        t2 = p / rho * self.un.scale_T2          # T/mu
        mu = self._mu() if hasattr(self, "sim") else 1.0   # neutral H
        return np.maximum(t2 * mu, 0.1)

    def advance(self, u, dt_code: float):
        """Advance RT by ``dt_code`` (user units) against the current
        gas and return the gas state with the photoheated energy."""
        cfg = self.grid.cfg
        un = self.un
        # refresh density + temperature from the (possibly moved) gas
        rho = np.maximum(np.asarray(u[0], np.float64), cfg.smallr)
        self.sim.nH = jnp.asarray(rho * un.scale_d * self.x_frac / mH)
        self.sim.T = jnp.asarray(self._gas_T(u))
        dt_cgs = float(dt_code) * un.scale_t
        self._injected += self._ndot_total * dt_cgs
        self.sim.advance(dt_cgs)
        if not self.spec.heating:
            return u
        # write the updated temperature back into the gas energy
        T_new = np.asarray(self.sim.T, np.float64)
        mu = self._mu()
        p_code = rho * (T_new / mu) / un.scale_T2
        mom2 = sum(np.asarray(u[1 + d], np.float64) ** 2
                   for d in range(cfg.ndim))
        e_new = p_code / (cfg.gamma - 1.0) + 0.5 * mom2 / rho
        return u.at[cfg.ndim + 1].set(jnp.asarray(e_new, u.dtype))
