"""AMR self-gravity tests: map/operator sanity, refined-patch accuracy
against the dense fine solve, point-mass force law, coupled dynamics."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.amr.hierarchy import AmrSim
from ramses_tpu.amr.maps import build_gravity_maps
from ramses_tpu.amr.tree import Octree
from ramses_tpu.config import params_from_dict
from ramses_tpu.poisson import amr_solve as gs
from ramses_tpu.poisson.solver import fft_solve


def test_gravity_maps_complete_level():
    """Complete periodic base level: no ghosts, Laplacian exact on
    linear and quadratic fields."""
    t = Octree.base(2, 4, 4)
    g = build_gravity_maps(t, 4, [(0, 0), (0, 0)])
    assert g.ng == 0
    n = 16
    dx = 1.0 / n
    cc = t.cell_centers(4)
    # linear field has zero Laplacian away from the periodic wrap
    phi_lin = jnp.asarray(cc[:, 0])
    pad = g.ncell_pad - g.ncell
    phi_lin = jnp.concatenate([phi_lin, jnp.zeros(pad)])
    ghosts = jnp.zeros((g.ng_pad,))
    lap = np.asarray(gs.laplacian(phi_lin, ghosts, jnp.asarray(g.nb),
                                  dx, jnp.asarray(g.valid_cell), 2))
    interior = (cc[:, 0] > 2 * dx) & (cc[:, 0] < 1 - 2 * dx)
    assert np.abs(lap[:g.ncell][interior]).max() < 1e-9
    # sin field: Δ sin(2πx) = −(2π)² sin(2πx) to O(h²)
    phi_sin = jnp.concatenate([jnp.asarray(np.sin(2 * np.pi * cc[:, 0])),
                               jnp.zeros(pad)])
    lap = np.asarray(gs.laplacian(phi_sin, ghosts, jnp.asarray(g.nb),
                                  dx, jnp.asarray(g.valid_cell), 2))
    expect = -(2 * np.pi) ** 2 * np.sin(2 * np.pi * cc[:, 0])
    assert np.allclose(lap[:g.ncell], expect, atol=0.5)


def test_cg_matches_fft_on_complete_level():
    """CG on the base level reproduces the exact FFT solution."""
    t = Octree.base(2, 4, 4)
    g = build_gravity_maps(t, 4, [(0, 0), (0, 0)])
    n = 16
    dx = 1.0 / n
    cc = t.cell_coords(4)
    rng = np.random.default_rng(0)
    rho_d = rng.standard_normal((n, n))
    rho_d -= rho_d.mean()
    phi_d = np.asarray(fft_solve(jnp.asarray(rho_d), dx))
    rhs = jnp.zeros((g.ncell_pad,))
    rhs = rhs.at[jnp.arange(g.ncell)].set(
        jnp.asarray(rho_d[cc[:, 0], cc[:, 1]]))
    ghosts = jnp.zeros((g.ng_pad,))
    phi = np.asarray(gs.cg_level(rhs, ghosts, jnp.asarray(g.nb), dx,
                                 jnp.asarray(g.valid_cell), 2, iters=400))
    got = phi[:g.ncell] - phi[:g.ncell].mean()
    want = phi_d[cc[:, 0], cc[:, 1]]
    want = want - want.mean()
    assert np.abs(got - want).max() < 2e-5 * np.abs(want).max()


def _blob_params(lmin=4, lmax=5, ndim=2, d0=50.0):
    groups = {
        "run_params": {"hydro": True, "poisson": True},
        "amr_params": {"levelmin": lmin, "levelmax": lmax, "boxlen": 1.0},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.5, 0.5], "y_center": [0.5, 0.5],
                        "z_center": [0.5, 0.5],
                        "length_x": [10.0, 0.25], "length_y": [10.0, 0.25],
                        "length_z": [10.0, 0.25],
                        "exp_region": [10.0, 2.0],
                        "d_region": [1.0, d0],
                        "p_region": [10.0, 10.0]},
        "hydro_params": {"gamma": 1.4, "courant_factor": 0.5,
                         "riemann": "hllc"},
        "refine_params": {"err_grad_d": 0.2},
        "output_params": {"tend": 0.01},
    }
    return params_from_dict(groups, ndim=ndim)


def test_refined_patch_force_matches_dense():
    """Force on the refined patch ≈ the dense fine-grid solve."""
    p = _blob_params(lmin=4, lmax=5, ndim=2)
    sim = AmrSim(p, dtype=jnp.float64)
    assert sim.tree.has(5), "blob did not trigger refinement"
    sim.solve_gravity()

    # dense reference at the fine resolution
    n = 32
    dx = 1.0 / n
    dense = np.full((n, n), 1.0)
    xc = (np.arange(n) + 0.5) * dx
    X, Y = np.meshgrid(xc, xc, indexing="ij")
    r = np.sqrt(((X - 0.5) / 0.125) ** 2 + ((Y - 0.5) / 0.125) ** 2)
    dense[r < 1.0] = 50.0
    rhs = 4 * np.pi * (dense - dense.mean())
    phi_d = np.asarray(fft_solve(jnp.asarray(rhs), dx))
    fx_d = -(np.roll(phi_d, -1, 0) - np.roll(phi_d, 1, 0)) / (2 * dx)

    m = sim.maps[5]
    cc = sim.tree.cell_coords(5)
    f_amr = np.asarray(sim.fg[5])[:m.noct * 4]
    # compare where the patch is interior (2 fine cells from its edge)
    lab = np.zeros((n, n), dtype=bool)
    lab[tuple(cc.T)] = True
    interior = lab.copy()
    for d in range(2):
        for s in (-1, 1):
            for _ in range(1):
                interior &= np.roll(lab, s * 2, axis=d)
    sel = interior[tuple(cc.T)]
    got = f_amr[sel, 0]
    want = fx_d[tuple(cc[sel].T)]
    scale = np.abs(fx_d).max()
    assert np.abs(got - want).max() < 0.05 * scale, \
        f"max err {np.abs(got - want).max():.3e} vs scale {scale:.3e}"


def test_point_mass_force_law_3d():
    """Central concentration: radial force ~ GM/r² outside it."""
    p = _blob_params(lmin=4, lmax=4, ndim=3, d0=1000.0)
    sim = AmrSim(p, dtype=jnp.float64)
    sim.solve_gravity()
    m = sim.maps[4]
    cc = sim.tree.cell_centers(4)
    f = np.asarray(sim.fg[4])[:m.noct * 8]
    rvec = cc - 0.5
    r = np.sqrt((rvec ** 2).sum(1))
    fr = -(f * rvec).sum(1) / np.maximum(r, 1e-12)   # inward positive
    u0 = np.asarray(sim.u[4])[:m.noct * 8, 0]
    mass_c = ((u0 - 1.0) * sim.dx(4) ** 3).sum()     # excess blob mass
    shell = (r > 0.2) & (r < 0.3)
    want = mass_c / r[shell] ** 2                    # G=1 user units
    got = fr[shell]
    # periodic images + finite blob: ~15% band
    assert np.median(np.abs(got / want - 1.0)) < 0.2


def test_amr_gravity_dynamics_smoke():
    """Coupled run: dense blob starts infalling; everything finite."""
    p = _blob_params(lmin=3, lmax=4, ndim=2, d0=100.0)
    sim = AmrSim(p, dtype=jnp.float64)
    sim.evolve(0.02)
    assert sim.nstep > 0
    for l in sim.levels():
        assert np.all(np.isfinite(np.asarray(sim.u[l])))
    # inward momentum near the blob edge: radial velocity < 0 on average
    l = sim.lmin
    m = sim.maps[l]
    cc = sim.tree.cell_centers(l)
    u = np.asarray(sim.u[l])[:m.noct * 4]
    rvec = cc - 0.5
    r = np.sqrt((rvec ** 2).sum(1))
    vr = ((u[:, 1:3] / u[:, 0:1]) * rvec).sum(1) / np.maximum(r, 1e-12)
    ring = (r > 0.15) & (r < 0.35)
    assert vr[ring].mean() < 0.0

def test_pcg_convergence_control_and_iters():
    """pcg_level: residual-targeted iteration, matches plain CG, and the
    two-level preconditioner converges in (many) fewer iterations than
    the tolerance cap."""
    p = _blob_params(lmin=4, lmax=5, ndim=2)
    sim = AmrSim(p, dtype=jnp.float64)
    sim.solve_gravity()
    assert 5 in sim.poisson_iters
    nit = int(sim.poisson_iters[5])
    assert 0 < nit < 200, nit

    # same system via the two solvers agrees
    m = sim.maps[5]
    d = sim.dev[5]
    from ramses_tpu.amr import kernels as K
    from ramses_tpu.amr.hierarchy import _Cfg1
    rho = sim.u[5][:, 0]
    mtot = float(sim.totals()[0])
    rhs = 4 * np.pi * (rho - mtot)
    ghosts = K.interp_cells(sim.phi[4][:, None], d["g_cell"], d["g_gnb"],
                            d["g_sgn"].astype(sim.phi[4].dtype),
                            _Cfg1(2), itype=1)[:, 0]
    dx = jnp.asarray(sim.dx(5), rhs.dtype)
    phi_cg = gs.cg_level(rhs, ghosts, d["g_nb"], dx, d["g_valid"], 2,
                         iters=400)
    phi_pcg, nit2 = gs.pcg_level(rhs, ghosts, d["g_nb"], d["g_octnb"],
                                 dx, d["g_valid"], 2, tol=1e-10,
                                 iters=400)
    scale = float(jnp.abs(phi_cg).max())
    assert float(jnp.abs(phi_pcg - phi_cg).max()) < 1e-6 * scale


def test_mg_ladder_preconditioner():
    """The masked-multigrid ladder (``multigrid_fine``'s level ladder
    as a PCG preconditioner): lattices coarsen the masked domain with
    consistent parent maps, the preconditioned solve matches plain CG,
    and it converges in no more iterations than the two-level variant
    from the same cold start."""

    # a large complete periodic level gives a deep ladder
    t = Octree.base(2, 6, 6)
    g = build_gravity_maps(t, 6, [(0, 0), (0, 0)])
    assert len(g.mg) >= 2                  # 32^2 octs -> 16^2 -> 8^2...
    prev_n = t.noct(6)
    for nb_j, par_j, n_j in g.mg:
        n_pad = nb_j.shape[0]
        assert n_j < prev_n and n_j <= n_pad   # strict coarsening
        assert nb_j.shape == (n_pad, 2, 2)
        assert (par_j[:prev_n] < n_j).all()
        # periodic complete lattice: every REAL row's neighbour exists;
        # padded rows are all-sentinel
        assert (nb_j[:n_j] < n_j).all()
        assert (nb_j[n_j:] == n_pad).all()
        prev_n = n_j

    n = 64
    dx = 1.0 / n
    cc = t.cell_coords(6)
    rng = np.random.default_rng(1)
    rho_d = rng.standard_normal((n, n))
    rho_d -= rho_d.mean()
    rhs = jnp.zeros((g.ncell_pad,)).at[jnp.arange(g.ncell)].set(
        jnp.asarray(rho_d[cc[:, 0], cc[:, 1]]))
    ghosts = jnp.zeros((g.ng_pad,))
    mg_dev = tuple((jnp.asarray(nb_j), jnp.asarray(par_j))
                   for nb_j, par_j, _ in g.mg)
    common = (rhs, ghosts, jnp.asarray(g.nb), jnp.asarray(g.oct_nb),
              dx, jnp.asarray(g.valid_cell), 2)
    phi_mg, it_mg = gs.pcg_level(*common, tol=1e-8, iters=400,
                                 mg=mg_dev)
    phi_2l, it_2l = gs.pcg_level(*common, tol=1e-8, iters=400, mg=())
    phi_cg = gs.cg_level(rhs, ghosts, jnp.asarray(g.nb), dx,
                         jnp.asarray(g.valid_cell), 2, iters=800)

    def centered(a):
        a = np.asarray(a)[:g.ncell]
        return a - a.mean()

    ref = centered(phi_cg)
    assert np.abs(centered(phi_mg) - ref).max() < 1e-6 * np.abs(ref).max()
    assert int(it_mg) <= int(it_2l)
    assert int(it_mg) < 400


def test_mg_ladder_masked_nonperiodic():
    """The ladder on a MASKED (disc-shaped) partial level with
    non-periodic walls: sentinel neighbours outside the mask/box,
    sentinel parents on padded rows, and the preconditioned solve
    still matches plain CG."""

    # disc-shaped refined patch at level 6 inside an outflow box
    t = Octree.base(2, 5, 6)
    og5 = t.levels[5].og
    cen = (og5 + 0.5) / 32.0
    sel = ((cen - 0.5) ** 2).sum(1) < 0.3 ** 2
    og6 = (2 * og5[sel][:, None, :]
           + np.indices((2, 2)).reshape(2, -1).T[None, :, :]
           ).reshape(-1, 2)
    t.set_level(6, og6)
    bc = [(2, 2), (2, 2)]                        # outflow walls
    g = build_gravity_maps(t, 6, bc)
    assert len(g.mg) >= 1
    noct = t.noct(6)
    prev_n = noct
    for nb_j, par_j, n_j in g.mg:
        n_pad = nb_j.shape[0]
        assert n_j <= n_pad and n_j < prev_n
        # masked domain: some neighbours must be sentinels
        assert (nb_j[:n_j] == n_pad).any()
        assert (nb_j <= n_pad).all() and (par_j <= n_pad).all()
        # padded nb rows are all-sentinel
        assert (nb_j[n_j:] == n_pad).all()
        prev_n = n_j

    dx = 1.0 / 64
    rng = np.random.default_rng(2)
    rhs = jnp.zeros((g.ncell_pad,)).at[jnp.arange(g.ncell)].set(
        jnp.asarray(rng.standard_normal(g.ncell)))
    ghosts = jnp.zeros((g.ng_pad,))
    mg_dev = tuple((jnp.asarray(nb_j), jnp.asarray(par_j))
                   for nb_j, par_j, _ in g.mg)
    common = (rhs, ghosts, jnp.asarray(g.nb), jnp.asarray(g.oct_nb),
              dx, jnp.asarray(g.valid_cell), 2)
    phi_mg, it_mg = gs.pcg_level(*common, tol=1e-9, iters=500,
                                 mg=mg_dev)
    phi_cg = gs.cg_level(rhs, ghosts, jnp.asarray(g.nb), dx,
                         jnp.asarray(g.valid_cell), 2, iters=1000)
    a = np.asarray(phi_mg)[:g.ncell]
    b = np.asarray(phi_cg)[:g.ncell]
    assert np.abs(a - b).max() < 1e-6 * max(np.abs(b).max(), 1e-300)
    assert 0 < int(it_mg) < 500
