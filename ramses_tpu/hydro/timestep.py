"""CFL timestep (``cmpdt``, hydro/godunov_utils.f90:5-125).

Computes the per-cell Courant-limited dt including the reference's
gravity-strength correction factor, reduced with ``jnp.min`` (the
MPI_ALLREDUCE(MIN) of ``hydro/courant_fine.f90:140`` becomes a mesh
``pmin`` in the sharded path).
"""

from __future__ import annotations

import jax.numpy as jnp

from ramses_tpu.hydro.core import HydroStatic


def cell_dt(u, grav, dx: float, cfg: HydroStatic):
    """Per-cell Courant-limited dt (shape = spatial shape of ``u``).

    ``u``: [nvar, *sp]; ``grav``: list of ndim accel arrays or None;
    ``dx``: cell size (scalar — cubic cells, as the reference assumes).
    """
    r = jnp.maximum(u[0], cfg.smallr)
    inv_r = 1.0 / r
    vels = [u[1 + d] * inv_r for d in range(cfg.ndim)]
    eint = u[cfg.ndim + 1] - 0.5 * r * sum(v * v for v in vels)
    for n in range(cfg.nener):
        eint = eint - u[2 + cfg.ndim + n]
    p = jnp.maximum((cfg.gamma - 1.0) * eint, r * cfg.smallp)
    c2 = cfg.gamma * p
    for n in range(cfg.nener):
        c2 = c2 + cfg.gamma_rad[n] * (cfg.gamma_rad[n] - 1.0) * u[2 + cfg.ndim + n]
    c = jnp.sqrt(c2 * inv_r)

    # wave speed: ndim*c + sum |v| (godunov_utils.f90:88-97)
    ws = float(cfg.ndim) * c
    for v in vels:
        ws = ws + jnp.abs(v)

    # gravity strength ratio (godunov_utils.f90:100-110)
    if grav is not None:
        gnorm = sum(jnp.abs(g) for g in grav)
    else:
        gnorm = jnp.zeros_like(ws)
    ratio = jnp.maximum(gnorm * dx / ws ** 2, 1e-4)

    cf = cfg.courant_factor
    return dx / ws * (jnp.sqrt(1.0 + 2.0 * cf * ratio) - 1.0) / ratio


def compute_dt(u, grav, dx: float, cfg: HydroStatic):
    """Max allowed dt over a (sub)grid: min of :func:`cell_dt`, capped by
    the reference's ``dtmax`` guard."""
    dtmax = cfg.courant_factor * dx / cfg.smallc
    return jnp.minimum(dtmax, jnp.min(cell_dt(u, grav, dx, cfg)))
