"""Utilities: profiling timers, map-making post-processing tools."""
