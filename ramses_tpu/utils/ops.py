"""Operational hygiene: signal-triggered dumps, walltime watchdog,
clean-stop file, the per-step screen block, and memory accounting.

Reference behaviours reproduced:
  * ``amr/ramses.f90:17-48`` — trap signals, dump a valid snapshot,
    exit cleanly.
  * ``amr/adaptive_loop.f90:216-226`` — walltime watchdog: when the
    remaining allocation can't fit another coarse step, dump + stop.
  * ``amr/adaptive_loop.f90:199-214`` + ``amr/memory.f90`` — the
    per-``ncontrol`` screen block: step, time, dt, mesh census, µs/pt,
    memory high-water mark.
  * clean_stop: the reference stops when ``stop_run`` appears in the
    run directory (the operator's brake).
"""

from __future__ import annotations

import os
import signal
import time
from typing import Optional

import numpy as np


def rss_mb() -> float:
    """Resident set size [MiB] (the reference's getmem RSS probe)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) / 1024.0
    except OSError:
        pass
    return 0.0


def device_mb() -> float:
    """Total bytes of live device arrays [MiB]."""
    import jax
    try:
        return sum(int(np.prod(a.shape)) * a.dtype.itemsize
                   for a in jax.live_arrays()) / 2 ** 20
    except Exception:
        return 0.0


class OpsGuard:
    """Attachable run guard: call :meth:`check` once per coarse step.

    Returns False when the run must stop (walltime exhausted or the
    clean-stop file appeared); fires a snapshot dump first.  SIGUSR1
    requests an immediate snapshot without stopping; SIGTERM/SIGINT
    request dump-and-stop.
    """

    def __init__(self, sim, base_dir: str = ".",
                 walltime_s: Optional[float] = None,
                 stop_file: str = "stop_run",
                 install_signals: bool = True,
                 nan_check: Optional[bool] = None,
                 dumper=None):
        self.sim = sim
        self.base_dir = base_dir
        self.walltime_s = walltime_s
        self.stop_file = stop_file
        # queued async snapshots must hit disk (manifests finalized)
        # before a SIGTERM/walltime stop returns the allocation
        self.dumper = dumper if dumper is not None \
            else getattr(sim, "dumper", None)
        # NaN trap (&RUN_PARAMS debug_nan; SURVEY.md §5.2): cheap dt
        # check every step, full-state audit at the conservation cadence
        if nan_check is None:
            nan_check = bool(getattr(
                getattr(getattr(sim, "params", None), "run", None),
                "debug_nan", False))
        self.nan_check = nan_check
        self.t0 = time.perf_counter()
        self._dump_requested = False
        self._stop_requested = False
        self._iout = 900               # emergency outputs: high numbers
        self._max_rss = 0.0
        self._step_wall = self.t0
        self._nblock = 0
        self._ncheck = 0
        # conservation audit cadence: totals() downloads the whole
        # device state, so amortize it over screen blocks
        self.cons_every = 10
        if install_signals:
            signal.signal(signal.SIGUSR1, self._on_dump)
            signal.signal(signal.SIGTERM, self._on_stop)
            signal.signal(signal.SIGINT, self._on_stop)

    # -- signal handlers ------------------------------------------------
    def _on_dump(self, _sig, _frm):
        self._dump_requested = True

    def _on_stop(self, sig, _frm):
        if self._stop_requested and sig == signal.SIGINT:
            # second Ctrl-C: the run is stuck inside a step (compile or
            # hung device call) and will never reach the next check();
            # escalate to the default KeyboardInterrupt
            signal.signal(signal.SIGINT, signal.default_int_handler)
            raise KeyboardInterrupt
        self._stop_requested = True

    def _dump(self) -> Optional[str]:
        from contextlib import nullcontext

        # io_deadline_s: snapshot writes run under the sim's watchdog
        # (a wedged filesystem hangs a run as surely as a wedged device)
        wd = getattr(self.sim, "_wd", None)
        try:
            with (wd.guard("io") if wd is not None else nullcontext()):
                out = self.sim.dump(self._iout, self.base_dir)
            self._iout += 1
            return out
        except Exception as e:          # keep the run alive on IO issues
            print(f"ops: emergency dump failed: {e}")
            return None

    # -- per-step hook --------------------------------------------------
    def _nan_trapped(self) -> Optional[str]:
        """Reason string when the state went unphysical, else None:
        cheap dt probe every step (non-finite OR non-positive once the
        run is under way — a dt that collapsed to zero stalls the run
        as surely as a NaN), full leaf audit (a whole-device download)
        amortized to every ``cons_every``-th check."""
        dt = float(getattr(self.sim, "dt_old", 0.0))
        if not np.isfinite(dt):
            return "nonfinite_dt"
        if dt <= 0.0 and int(getattr(self.sim, "nstep", 0)) > 0:
            return "nonpositive_dt"
        self._ncheck += 1
        if self._ncheck % max(self.cons_every, 1) == 0 \
                and hasattr(self.sim, "totals"):
            if not np.isfinite(np.asarray(self.sim.totals())).all():
                return "nonfinite_totals"
        return None

    def _record_fault(self, reason: str):
        tel = getattr(self.sim, "telemetry", None)
        if tel is not None:
            try:
                tel.record_event(
                    "fault", reason=reason,
                    nstep=int(getattr(self.sim, "nstep", 0)),
                    t=float(getattr(self.sim, "t", 0.0)),
                    dt=float(getattr(self.sim, "dt_old", 0.0)))
            except Exception:
                pass

    def _drain_dumper(self):
        """Flush queued async snapshots before a stop returns; report
        writer failures into telemetry + screen rather than raising
        past the clean-shutdown path."""
        if self.dumper is None:
            return
        for e in self.dumper.drain():
            print(f"ops: async snapshot write failed during stop: {e}")
            tel = getattr(self.sim, "telemetry", None)
            if tel is not None:
                try:
                    tel.record_event("io_error", error=repr(e))
                except Exception:
                    pass

    def check(self) -> bool:
        self._max_rss = max(self._max_rss, rss_mb())
        fault = getattr(self.sim, "_fault", None)
        if fault is not None:
            fault.maybe_signal(int(getattr(self.sim, "nstep", 0)))
        if self.nan_check:
            reason = self._nan_trapped()
            if reason is not None:
                self._record_fault(reason)
                out = self._dump()
                print("ops: NaN TRAP: unphysical state detected "
                      f"({reason}, step "
                      f"{getattr(self.sim, 'nstep', '?')}); crash "
                      f"snapshot -> {out}")
                self._drain_dumper()
                return False
        if self._dump_requested:
            self._dump_requested = False
            out = self._dump()
            print(f"ops: SIGUSR1 snapshot -> {out}")
        if self._stop_requested:
            out = self._dump()
            print(f"ops: stop signal: snapshot -> {out}")
            self._drain_dumper()
            return False
        if os.path.exists(os.path.join(self.base_dir, self.stop_file)):
            out = self._dump()
            print(f"ops: {self.stop_file} found: snapshot -> {out}")
            self._drain_dumper()
            return False
        if self.walltime_s is not None:
            used = time.perf_counter() - self.t0
            # leave room for one more step (reference: 2x the mean step)
            last = time.perf_counter() - self._step_wall
            if used + 2.0 * last > self.walltime_s:
                out = self._dump()
                print(f"ops: walltime watchdog: snapshot -> {out}")
                self._drain_dumper()
                return False
        self._step_wall = time.perf_counter()
        return True

    # -- screen block ---------------------------------------------------
    def run_guarded(self, evolve):
        """Run ``evolve()`` under the jit-level NaN trap: with
        ``jax_debug_nans`` on, a NaN raises FloatingPointError from
        INSIDE the compiled step — before any per-step :meth:`check` —
        so catch it here, write the promised crash snapshot, and
        re-raise with the producing-op traceback intact."""
        try:
            evolve()
        except FloatingPointError:
            out = self._dump()
            print(f"ops: NaN TRAP (jit raise): crash snapshot -> {out}")
            raise


    def screen_block(self, extra: str = "") -> str:
        """The reference's per-ncontrol control line — formatting lives
        in the telemetry screen sink (:mod:`ramses_tpu.telemetry.
        screen`); this wrapper keeps the guard's amortized-audit
        cadence and RSS high-water state."""
        from ramses_tpu.telemetry import screen as tscreen
        self._nblock += 1
        audit = (self._nblock - 1) % max(self.cons_every, 1) == 0
        return tscreen.control_block(self.sim, max_rss=self._max_rss,
                                     audit=audit, extra=extra)
