"""Trace correlation: one id joins a job's whole history.

A ``trace_id`` is stamped onto the job record at submit time and rides
the record file through claim/requeue/stale-reclaim/complete; the
worker binds it into the job's telemetry recorder (every JSONL record),
every ``failure_log`` entry, the bench heartbeat sidecars and the
checkpoint manifest meta — so one grep (or ``tools/trace_report.py``)
joins submit -> claim -> telemetry -> failure -> artifact across
however many workers the job bounced through.

Deliberately stdlib-only and leaf-level: ``ensemble/queue.py`` (which
must stay jax-free) imports this at submit time, and the bench parent
reads the same env contract without importing ramses_tpu at all.
"""

from __future__ import annotations

import os
import uuid

#: env override: a driving process (bench parent, CI harness) exports
#: this so every child it launches lands under ONE pre-known trace id
ENV_VAR = "RAMSES_TRACE_ID"


def new_trace_id() -> str:
    """A 16-byte random hex id (W3C trace-id width).  :data:`ENV_VAR`
    wins when set, so a parent can pre-correlate its children."""
    return os.environ.get(ENV_VAR, "").strip() or uuid.uuid4().hex


def worker_id() -> str:
    """``host:pid`` — the worker identity stamped beside trace ids."""
    return f"{os.uname().nodename}:{os.getpid()}"
