"""grafic cosmological initial-condition files.

Reference readers: ``amr/init_time.f90:303-414`` (init_file — scans
``initfile(ilevel)`` directories for ``ic_deltab``/``ic_velc*``/
``ic_velb*`` planes), ``hydro/init_flow_fine.f90`` (baryon fields) and
``pm/init_part.f90`` (dark-matter displacements).  Format (grafic1/2,
Fortran unformatted):

  record 1: np1, np2, np3 (int32), dx (float32, comoving Mpc),
            x1o, x2o, x3o (float32 offsets, Mpc),
            astart, omega_m, omega_v, h0 (float32)
  then np3 records, each one (np1, np2) float32 plane.

Velocities are proper peculiar velocities in km/s at ``astart``;
``ic_deltab`` is the density contrast δ.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

from ramses_tpu.io.fortran import read_record as _read_record
from ramses_tpu.io.fortran import write_record as _write_record


@dataclass
class GraficHeader:
    np1: int
    np2: int
    np3: int
    dx: float          # comoving Mpc
    x1o: float = 0.0
    x2o: float = 0.0
    x3o: float = 0.0
    astart: float = 0.01
    omega_m: float = 1.0
    omega_v: float = 0.0
    h0: float = 70.0   # km/s/Mpc

    @property
    def boxlen_mpc(self) -> float:
        return self.np1 * self.dx


def read_grafic(path: str) -> Tuple[GraficHeader, np.ndarray]:
    """One grafic plane file → (header, field [np1, np2, np3])."""
    with open(path, "rb") as f:
        hdr_raw = _read_record(f)
        np1, np2, np3 = struct.unpack("<iii", hdr_raw[:12])
        floats = np.frombuffer(hdr_raw[12:12 + 8 * 4], dtype="<f4")
        hdr = GraficHeader(np1, np2, np3, float(floats[0]),
                           float(floats[1]), float(floats[2]),
                           float(floats[3]), float(floats[4]),
                           float(floats[5]), float(floats[6]),
                           float(floats[7]))
        out = np.empty((np1, np2, np3), dtype=np.float32)
        for k in range(np3):
            plane = np.frombuffer(_read_record(f), dtype="<f4")
            # planes are (np2, np1) row-major in the file (x fastest)
            out[:, :, k] = plane.reshape(np2, np1).T
    return hdr, out


def write_grafic(path: str, hdr: GraficHeader, field: np.ndarray):
    """Write one plane file (inverse of :func:`read_grafic`)."""
    assert field.shape == (hdr.np1, hdr.np2, hdr.np3)
    with open(path, "wb") as f:
        payload = struct.pack("<iii", hdr.np1, hdr.np2, hdr.np3)
        payload += np.asarray(
            [hdr.dx, hdr.x1o, hdr.x2o, hdr.x3o, hdr.astart,
             hdr.omega_m, hdr.omega_v, hdr.h0], dtype="<f4").tobytes()
        _write_record(f, payload)
        for k in range(hdr.np3):
            _write_record(f, np.ascontiguousarray(
                field[:, :, k].T, dtype="<f4").tobytes())


FIELDS_DM = ("ic_velcx", "ic_velcy", "ic_velcz")
FIELDS_BARYON = ("ic_deltab", "ic_velbx", "ic_velby", "ic_velbz")


def read_grafic_dir(dirname: str) -> Tuple[GraficHeader,
                                           Dict[str, np.ndarray]]:
    """Load every present IC field of one level directory
    (``init_time.f90:330-378`` scans the same names)."""
    fields: Dict[str, np.ndarray] = {}
    hdr: Optional[GraficHeader] = None
    for name in FIELDS_DM + FIELDS_BARYON:
        p = os.path.join(dirname, name)
        if not os.path.exists(p):
            continue
        h, arr = read_grafic(p)
        if hdr is None:
            hdr = h
        elif (h.np1, h.np2, h.np3) != (hdr.np1, hdr.np2, hdr.np3):
            raise IOError(f"grafic: inconsistent dimensions in {name}")
        fields[name] = arr
    if hdr is None:
        raise FileNotFoundError(f"no grafic files in {dirname}")
    return hdr, fields


def write_zeldovich_ics(dirname: str, delta: np.ndarray, hdr: GraficHeader,
                        fpeebl: float, baryons: bool = True):
    """Generate a self-consistent grafic set from a density contrast
    field δ at ``astart``: Zel'dovich displacement ψ = ∇∇⁻²δ and proper
    peculiar velocities v = f·H(a)·a·ψ (km/s) — the standard growing
    mode (test/IC-generation utility; the inverse of what
    :func:`ramses_tpu.pm.init_part.particles_from_grafic` applies)."""
    os.makedirs(dirname, exist_ok=True)
    n = delta.shape[0]
    kf = np.fft.fftfreq(n, d=1.0 / n)
    kx, ky, kz = np.meshgrid(kf, kf, kf, indexing="ij")
    k2 = kx ** 2 + ky ** 2 + kz ** 2
    k2[0, 0, 0] = 1.0
    dhat = np.fft.fftn(delta)
    # δ = -∇·ψ  →  ψ_hat = +i k/|k|² δ_hat with k_phys = 2π m / L
    # (m integer modes): ψ[Mpc] = ifft(+i m/|m|² δ_hat) · L/2π
    a = hdr.astart
    om, ov = hdr.omega_m, hdr.omega_v
    ok = 1.0 - om - ov
    h_a = hdr.h0 * np.sqrt(om / a ** 3 + ov + ok / a ** 2)  # km/s/Mpc
    vfac = fpeebl * h_a * a                         # km/s per Mpc of ψ
    vels = []
    for kc in (kx, ky, kz):
        psi = np.real(np.fft.ifftn(1j * kc / k2 * dhat)) \
            * (hdr.boxlen_mpc / (2.0 * np.pi))      # comoving Mpc
        vels.append((psi * vfac).astype(np.float32))
    write_grafic(os.path.join(dirname, "ic_deltab"), hdr,
                 delta.astype(np.float32))
    for nm, v in zip(("ic_velcx", "ic_velcy", "ic_velcz"), vels):
        write_grafic(os.path.join(dirname, nm), hdr, v)
    if baryons:
        for nm, v in zip(("ic_velbx", "ic_velby", "ic_velbz"), vels):
            write_grafic(os.path.join(dirname, nm), hdr, v)
