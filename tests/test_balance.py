"""Cost-weighted Hilbert load balancing (``parallel/balance.py`` — the
``load_balance.f90``/``cost_weighting`` role on the sharded AMR path).

Oracles:
  * the capacity-constrained weighted cuts are feasible and balanced to
    one-oct granularity;
  * layouts are pure row permutations: a forced rebalance must leave
    the evolved physics identical to the identity-layout run (single
    device exercises every remap with zero communication effects);
  * the same with self-gravity + particles (gravity maps, PM deposit
    maps, migration under layouts);
  * a refinement ladder piled into one corner octant on the 8-device
    mesh triggers a natural rebalance, the per-device summed cost lands
    within the padding bound at every level, explicit ppermute halo
    schedules run on a >=4k-oct partial level, and mesh-of-8 ==
    mesh-of-1 on the evolved state;
  * the rebalance is observable: measured imbalance drops and the
    screen block reports it.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ramses_tpu.amr.hierarchy import AmrSim
from ramses_tpu.config import params_from_dict, params_from_string
from ramses_tpu.parallel import balance
from ramses_tpu.parallel.amr_sharded import ShardedAmrSim
from ramses_tpu.pm.particles import ParticleSet


# ---------------------------------------------------------------- unit

@pytest.mark.smoke
def test_balanced_cuts_uniform():
    w = np.ones(64)
    counts = balance.balanced_cuts(w, 8, 8)
    assert counts.sum() == 64 and (counts == 8).all()


@pytest.mark.smoke
def test_balanced_cuts_skewed_within_capacity():
    rng = np.random.default_rng(0)
    w = rng.uniform(0.5, 1.5, 100)
    w[:10] *= 50.0                       # heavy head
    counts = balance.balanced_cuts(w, 8, 16)
    assert counts.sum() == 100 and (counts <= 16).all() and (counts >= 0).all()
    # per-device cost within one max-weight of the ideal share wherever
    # the capacity clamp is not binding
    cuts = np.concatenate([[0], np.cumsum(counts)])
    per = np.array([w[a:b].sum() for a, b in zip(cuts[:-1], cuts[1:])])
    free = counts < 16
    assert (per[free] <= w.sum() / 8 + w.max() + 1e-12).all()


@pytest.mark.smoke
def test_balanced_cuts_exact_capacity_and_infeasible():
    counts = balance.balanced_cuts(np.ones(24), 3, 8)
    assert (counts == 8).all()
    with pytest.raises(ValueError):
        balance.balanced_cuts(np.ones(25), 3, 8)


@pytest.mark.smoke
def test_make_layout_roundtrip_and_remap_sentinels():
    rng = np.random.default_rng(1)
    order = rng.permutation(21).astype(np.int64)
    counts = balance.balanced_cuts(np.ones(21)[order], 4, 6)
    lay = balance.make_layout(order, counts, 24, 4)
    # inverse relation, per-segment placement
    assert (lay.row_oct[lay.oct_row] == np.arange(21)).all()
    for d in range(4):
        seg = lay.row_oct[d * 6:(d + 1) * 6]
        n = int(lay.counts[d])
        assert (seg[:n] >= 0).all() and (seg[n:] == -1).all()
    # value remaps: real indices move, sentinels pass through
    v = np.array([0, 20, -1, 21, 100], dtype=np.int32)
    r = balance.remap_octs(v, lay)
    assert r[0] == lay.oct_row[0] and r[1] == lay.oct_row[20]
    assert r[2] == -1 and r[3] == 21 and r[4] == 100
    ttd = 4
    c = np.array([0, 5, 21 * ttd - 1, 21 * ttd, -1], dtype=np.int32)
    rc = balance.remap_cells(c, lay, ttd)
    assert rc[0] == lay.oct_row[0] * ttd
    assert rc[1] == lay.oct_row[1] * ttd + 1
    assert rc[2] == lay.oct_row[20] * ttd + ttd - 1
    assert rc[3] == 21 * ttd and rc[4] == -1


# ------------------------------------------------------- invariance

def _sedov_groups(lb, lmin=3, lmax=5):
    g = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": lmin, "levelmax": lmax, "boxlen": 1.0,
                       "load_balance": lb},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.25, 0.75], "y_center": [0.5, 0.5],
                        "length_x": [0.5, 0.5], "length_y": [10.0, 10.0],
                        "exp_region": [10.0, 10.0],
                        "d_region": [1.0, 0.125],
                        "p_region": [1.0, 0.1]},
        "hydro_params": {"gamma": 1.4, "courant_factor": 0.8,
                         "riemann": "hllc", "slope_type": 1},
        "refine_params": {"err_grad_d": 0.05, "err_grad_p": 0.05},
        "output_params": {"tend": 0.05},
    }
    return {k: dict(v) for k, v in g.items()}


def _cmp_state(sim_a, sim_b, rtol, atol):
    for l in sim_a.levels():
        a = sim_a.tree_order_cells(np.asarray(sim_a.u[l]), l)
        b = sim_b.tree_order_cells(np.asarray(sim_b.u[l]), l)
        np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                                   err_msg=f"lvl {l}")


@pytest.mark.slow
def test_forced_layout_single_device_invariance():
    """A forced Hilbert relayout is a pure row permutation: the evolved
    run must match the identity-layout run to roundoff, and the screen
    block must report the rebalance."""
    from ramses_tpu.utils.ops import OpsGuard

    sim0 = AmrSim(params_from_dict(_sedov_groups(False), ndim=2),
                  dtype=jnp.float64)
    sim1 = AmrSim(params_from_dict(_sedov_groups(True), ndim=2),
                  dtype=jnp.float64)
    sim0.evolve(0.015)
    sim1.evolve(0.015)
    sim1.request_rebalance()
    sim1.regrid()
    assert sim1.layouts, "forced rebalance adopted no layout"
    assert sim1._rebalance_count == 1
    # a layout level's real rows are scattered: [:noct] slicing invalid
    l = max(sim1.layouts)
    assert not np.array_equal(sim1.layouts[l].oct_row,
                              np.arange(sim1.layouts[l].noct))
    line = OpsGuard(sim1, install_signals=False).screen_block()
    assert " lb[" in line and "nreb=1" in line and "imb=" in line
    sim0.evolve(0.03)
    sim1.evolve(0.03)
    assert sim0.nstep == sim1.nstep
    for l in sim0.levels():
        assert sim0.tree.noct(l) == sim1.tree.noct(l)
    np.testing.assert_allclose(np.asarray(sim0.totals()),
                               np.asarray(sim1.totals()), rtol=1e-12)
    _cmp_state(sim0, sim1, rtol=1e-11, atol=1e-12)


@pytest.mark.slow          # ~13s; nightly tier on the 1-core box
def test_forced_layout_gravity_pm_invariance():
    """Layout transform correctness through the gravity maps (nb /
    ghost / mg ladder) and PM deposit maps: particles + CG self-gravity
    evolve identically under a forced relayout."""
    def _params(lb):
        txt = "\n".join([
            "&RUN_PARAMS", "hydro=.true.", "poisson=.true.",
            "pic=.true.", "/",
            "&AMR_PARAMS", "levelmin=3", "levelmax=5", "boxlen=1.0",
            f"load_balance={'.true.' if lb else '.false.'}",
            "load_balance_threshold=1.05", "cost_weight_part=0.5", "/",
            "&POISSON_PARAMS", "solver='cg'", "epsilon=1e-12", "/",
            "&INIT_PARAMS", "nregion=1", "region_type(1)='square'",
            "d_region=1.0", "p_region=1.0", "/",
            "&HYDRO_PARAMS", "riemann='hllc'", "courant_factor=0.5", "/",
            "&REFINE_PARAMS", "x_refine=0,0,0.25,0.25",
            "y_refine=0,0,0.25,0.25", "r_refine=-1,-1,0.2,0.2",
            "exp_refine=10,10,10,10", "/",
        ])
        return params_from_string(txt, ndim=2)

    # the new &AMR_PARAMS keys parse from namelist text
    p1 = _params(True)
    assert p1.amr.load_balance is True
    assert p1.amr.load_balance_threshold == 1.05
    assert p1.amr.cost_weight_part == 0.5

    rng = np.random.default_rng(7)
    x0 = np.concatenate([rng.uniform(0.05, 0.45, (48, 2)),
                         rng.uniform(0.0, 1.0, (16, 2))])
    v0 = rng.uniform(-0.05, 0.05, (64, 2))
    ps = ParticleSet.make(x0, v0, np.full(64, 1.0 / 64))
    sim0 = AmrSim(_params(False), dtype=jnp.float64,
                  particles=jax.device_put(ps))
    sim1 = AmrSim(p1, dtype=jnp.float64, particles=jax.device_put(ps))
    sim0.evolve(0.02, nstepmax=2)
    sim1.evolve(0.02, nstepmax=2)
    sim1.request_rebalance()
    sim1.regrid()
    sim0.regrid()
    assert sim1.layouts
    # equalize gravity warm-start state: the layout change cold-starts
    # sim1's solver (phi/fg pruned by design) — clear sim0's too so the
    # dt paths see the same inputs
    for s in (sim0, sim1):
        s.phi.clear()
        s.fg.clear()
        s._dt_cache = None
    for _ in range(4):
        sim0.step_coarse(sim0.coarse_dt())
        sim1.step_coarse(sim1.coarse_dt())
    np.testing.assert_allclose(np.asarray(sim0.totals()),
                               np.asarray(sim1.totals()),
                               rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(np.asarray(sim0.p.x),
                               np.asarray(sim1.p.x),
                               rtol=1e-9, atol=1e-11)
    _cmp_state(sim0, sim1, rtol=1e-8, atol=1e-10)


# -------------------------------------------------- sharded, skewed

def _skew_groups(lb, lmin=5, lmax=8):
    g = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": lmin, "levelmax": lmax, "boxlen": 1.0,
                       "load_balance": lb},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.3, 0.8], "y_center": [0.3, 0.8],
                        "length_x": [0.4, 0.6], "length_y": [0.4, 0.6],
                        "exp_region": [2.0, 2.0],
                        "d_region": [1.0, 0.25],
                        "p_region": [1.0, 0.2]},
        "hydro_params": {"gamma": 1.4, "courant_factor": 0.8,
                         "riemann": "hllc", "slope_type": 1},
        # geometric-only refinement: a sup-norm box in one corner at
        # every level -> a deterministic ladder piled into one octant
        "refine_params": {"r_refine": [-1.0] * (lmin - 1)
                          + [0.56] * (lmax - lmin),
                          "x_refine": [0.0] * (lmax - 1),
                          "y_refine": [0.0] * (lmax - 1),
                          "exp_refine": [10.0] * (lmax - 1)},
        "output_params": {"tend": 1.0},
    }
    return {k: dict(v) for k, v in g.items()}


@pytest.mark.slow
def test_skewed_tree_sharded_rebalances_and_matches_single_device():
    """The acceptance scenario: refinement piled into one corner octant
    on the 8-device mesh.  The natural (threshold) rebalance must fire,
    per-device summed cost must land within one-oct granularity of the
    ideal share at every level, the explicit ppermute halo schedules
    must run on a >=4k-oct partial level, and the evolved state must
    match the single-device run."""
    assert len(jax.devices()) >= 8
    LMIN, LMAX = 5, 8
    sim1 = AmrSim(params_from_dict(_skew_groups(False), ndim=2),
                  dtype=jnp.float64)
    sim8 = ShardedAmrSim(params_from_dict(_skew_groups(True), ndim=2),
                         devices=jax.devices()[:8], dtype=jnp.float64,
                         explicit_comm=True)
    for _ in range(LMAX - LMIN):
        sim1.regrid()
        sim8.regrid()
    assert ({l: sim1.tree.noct(l) for l in sim1.levels()}
            == {l: sim8.tree.noct(l) for l in sim8.levels()})
    # the finest level is partial and big enough to matter
    noct = sim8.tree.noct(LMAX)
    assert noct >= 4096
    assert noct < int(np.prod(sim8.tree.oct_dims(LMAX)))
    # the natural rebalance fired (blind row splits of a Morton-packed
    # corner put nearly everything on the first devices)
    assert sim8._rebalance_count >= 1 and sim8.layouts
    assert sim8.balance_stats is not None
    # explicit ppermute schedules exist for every partial level
    for l in range(LMIN + 1, LMAX + 1):
        assert l in sim8._comm_specs, l
    # per-device summed cost within one-oct granularity of the ideal
    # share at every level (the bucket-padding bound)
    for l in sim8.levels():
        w = balance.oct_costs(sim8, l)
        lay = sim8.layouts.get(l)
        cap = (lay.noct_pad if lay is not None
               else sim8._noct_pad(l, len(w))) // sim8.ndev
        rows = lay.oct_row if lay is not None else np.arange(len(w))
        per = np.bincount(rows // cap, weights=w, minlength=sim8.ndev)
        assert per.max() <= w.sum() / sim8.ndev + w.max() + 1e-9, l
    # observable: the adopted layouts beat the identity split
    imb_identity = balance.measure(sim8, {}).imbalance
    imb_balanced = balance.measure(sim8).imbalance
    assert imb_balanced < imb_identity
    assert sim8.balance_stats.imbalance == pytest.approx(imb_balanced)
    # mesh-of-8 == mesh-of-1 on the evolved state
    sim1.step_coarse(sim1.coarse_dt())
    sim8.step_coarse(sim8.coarse_dt())
    np.testing.assert_allclose(np.asarray(sim1.totals()),
                               np.asarray(sim8.totals()), rtol=1e-12)
    _cmp_state(sim1, sim8, rtol=1e-11, atol=1e-12)
