"""Parity of the fused Pallas MUSCL kernel vs the XLA reference path.

Runs the kernel in Pallas interpreter mode on the CPU test backend, so
the TPU code path's algorithm is covered by CI without TPU hardware
(``pallas_muscl.fused_step_padded(interpret=True)``).  The oracle is the
whole-grid XLA pipeline (``grid.uniform.step`` internals) that the TPU
kernel replaces — both implement ``hydro/umuscl.f90:22-171``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ramses_tpu.grid import boundary as bmod
from ramses_tpu.hydro import muscl, pallas_muscl as pk
from ramses_tpu.hydro.core import HydroStatic
from ramses_tpu.config import Params

SHAPE = (16, 16, 128)

# the fused kernel's overlapping x/y halo windows need the Element
# block-indexing mode; jax releases without it can't run the kernel
# even in interpreter mode (production gates it off the same way in
# pallas_muscl.kernel_available)
pytestmark = pytest.mark.skipif(
    pk.Element is None,
    reason="pl.Element block mode absent from this jax release")


def _cfg(riemann="llf", slope_type=1):
    p = Params(ndim=3)
    p.hydro.riemann = riemann
    p.hydro.slope_type = slope_type
    return HydroStatic.from_params(p)


def _state(cfg, seed=0):
    rng = np.random.default_rng(seed)
    r = 1.0 + 0.3 * rng.random(SHAPE)
    v = 0.2 * rng.standard_normal((3,) + SHAPE)
    p_ = 0.5 + 0.2 * rng.random(SHAPE)
    e = p_ / (cfg.gamma - 1.0) + 0.5 * r * (v ** 2).sum(axis=0)
    u = np.stack([r, r * v[0], r * v[1], r * v[2], e])
    return jnp.asarray(u, jnp.float32)


def _xla_step(u, dt, cfg, bc, dx):
    up = bmod.pad(u, bc, cfg, muscl.NGHOST)
    flux, _ = muscl.unsplit(up, None, dt, (dx,) * 3, cfg)
    un = muscl.apply_fluxes(up, flux, cfg)
    return bmod.unpad(un, 3, muscl.NGHOST)


@pytest.mark.smoke
@pytest.mark.parametrize("riemann", ["llf", "hllc"])
def test_fused_step_matches_xla(riemann):
    cfg = _cfg(riemann)
    bc = bmod.BoundarySpec.periodic(3)
    kinds = tuple((lo.kind, hi.kind) for lo, hi in bc.faces)
    assert pk.supports(cfg, SHAPE, kinds, jnp.float32)
    u = _state(cfg)
    dx = 1.0 / SHAPE[0]
    dt = jnp.asarray(1e-3, jnp.float32)
    ref = _xla_step(u, dt, cfg, bc, dx)
    up, _ = pk.pad_xy(u, bc, cfg)
    got = pk.fused_step_padded(up, dt, cfg, dx, SHAPE, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_fused_step_reflecting_xy():
    cfg = _cfg("llf")
    refl = bmod.FaceBC(kind=bmod.REFLECTING)
    per = bmod.FaceBC()
    bc = bmod.BoundarySpec(faces=((refl, refl), (refl, refl), (per, per)))
    kinds = tuple((lo.kind, hi.kind) for lo, hi in bc.faces)
    assert pk.supports(cfg, SHAPE, kinds, jnp.float32)
    u = _state(cfg, seed=3)
    dx = 1.0 / SHAPE[0]
    dt = jnp.asarray(5e-4, jnp.float32)
    ref = _xla_step(u, dt, cfg, bc, dx)
    up, _ = pk.pad_xy(u, bc, cfg)
    got = pk.fused_step_padded(up, dt, cfg, dx, SHAPE, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_fused_step_masked_matches_dense_sweep():
    """Refined-face flux zeroing (the AMR dense path's mask input)."""
    from ramses_tpu.amr import kernels as K

    cfg = _cfg("llf")
    bc = bmod.BoundarySpec.periodic(3)
    u = _state(cfg, seed=7)
    dx = 1.0 / SHAPE[0]
    dt = jnp.asarray(5e-4, jnp.float32)
    rng = np.random.default_rng(11)
    ok = jnp.asarray(rng.random(SHAPE) < 0.1)

    # XLA oracle: the masked branch of dense_sweep
    up = bmod.pad(u, bc, cfg, muscl.NGHOST)
    flux, _ = muscl.unsplit(up, None, dt, (dx,) * 3, cfg)
    okp = ok
    for d in range(3):
        padw = [(muscl.NGHOST, muscl.NGHOST) if d2 == d else (0, 0)
                for d2 in range(3)]
        okp = jnp.pad(okp, padw, mode="wrap")
    masked = [flux[d] * (~(okp | jnp.roll(okp, 1, axis=d)))[None]
              .astype(flux.dtype) for d in range(3)]
    un = muscl.apply_fluxes(up, jnp.stack(masked), cfg)
    ref = bmod.unpad(un, 3, muscl.NGHOST)

    upad, okpad = pk.pad_xy(u, bc, cfg, ok=ok)
    got = pk.fused_step_padded(upad, dt, cfg, dx, SHAPE, ok_pad=okpad,
                               interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_fused_courant_matches_compute_dt():
    from ramses_tpu.hydro.timestep import compute_dt

    cfg = _cfg("llf")
    bc = bmod.BoundarySpec.periodic(3)
    u = _state(cfg, seed=5)
    dx = 1.0 / SHAPE[0]
    dt = jnp.asarray(1e-3, jnp.float32)
    up, _ = pk.pad_xy(u, bc, cfg)
    un, crt = pk.fused_step_padded(up, dt, cfg, dx, SHAPE, courant=True,
                                   interpret=True)
    dtmax = cfg.courant_factor * dx / cfg.smallc
    want = float(compute_dt(un.astype(jnp.float32), None, dx, cfg))
    got = float(jnp.minimum(dtmax, crt[0, 0]))
    # (sqrt(1+2*cf*ratio)-1)/ratio cancels catastrophically in f32
    # (~1e-3 relative); cell_dt evaluates it per-cell in the array dtype
    # while the kernel folds it into one scalar — allow that spread
    assert got == pytest.approx(want, rel=3e-3)


# ---------------------------------------------------------------------------
# oct-batch kernel (pallas_oct): partial-level AMR sweeps
# ---------------------------------------------------------------------------

def _row_state(cfg, n, seed=0):
    """[n, nvar] physically-valid random conservative rows."""
    rng = np.random.default_rng(seed)
    r = 1.0 + 0.3 * rng.random(n)
    v = 0.2 * rng.standard_normal((3, n))
    p_ = 0.5 + 0.2 * rng.random(n)
    e = p_ / (cfg.gamma - 1.0) + 0.5 * r * (v ** 2).sum(axis=0)
    return jnp.asarray(np.stack([r, r * v[0], r * v[1], r * v[2], e],
                                axis=1), jnp.float32)


@pytest.mark.smoke
@pytest.mark.parametrize("riemann", ["llf", "hllc"])
def test_oct_sweep_matches_level_sweep(riemann, monkeypatch):
    """Drive kernels.level_sweep itself twice — pallas branch forced on
    (interpreter mode) vs forced off (XLA) — so the REAL production
    dispatch is what is pinned, not a replica of it."""
    from ramses_tpu.amr import kernels as K
    from ramses_tpu.hydro import pallas_oct

    cfg = _cfg(riemann)
    noct, ni_pad = 128, 256
    ncell_pad = noct * 8
    rng = np.random.default_rng(5)
    u_flat = _row_state(cfg, ncell_pad, seed=21)
    interp = _row_state(cfg, ni_pad, seed=22)
    nrows = ncell_pad + ni_pad + 1          # + trash row
    sten = jnp.asarray(rng.integers(0, nrows, (noct, 216)), jnp.int32)
    ok = jnp.asarray(rng.random((noct, 216)) < 0.15)
    dt = jnp.asarray(2e-4, jnp.float32)
    dx = 1.0 / 64

    def run():
        jax.clear_caches()                  # force a fresh branch choice
        du, corr, phi = K.level_sweep(u_flat, interp, sten, None, ok,
                                      None, dt, dx, cfg, ret_flux=True)
        return np.asarray(du), np.asarray(corr), np.asarray(phi)

    monkeypatch.setattr(pallas_oct, "FORCE_INTERPRET", True)
    assert pallas_oct.available(cfg, noct, jnp.float32)
    du_k, corr_k, phi_k = run()
    monkeypatch.setattr(pallas_oct, "FORCE_INTERPRET", False)
    monkeypatch.setattr(pallas_oct, "DISABLED", True)
    assert not pallas_oct.available(cfg, noct, jnp.float32)
    du_x, corr_x, phi_x = run()
    jax.clear_caches()                      # do not leak into other tests
    np.testing.assert_allclose(du_k, du_x, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(corr_k, corr_x, rtol=2e-5, atol=2e-6)
    # MC-tracer face-flux capture parity (want_flux kernel output)
    np.testing.assert_allclose(phi_k, phi_x, rtol=2e-5, atol=2e-6)


def test_fused_step_want_flux_matches_xla_dense_sweep():
    """The dense kernel's MC-tracer face-flux capture (want_flux)
    matches the XLA dense_sweep's ret_flux output."""
    import ramses_tpu.hydro.pallas_muscl as pk
    from ramses_tpu.amr import kernels as K
    from ramses_tpu.grid.boundary import BoundarySpec

    cfg = _cfg("hllc")
    shape = (16, 16, 128)
    bc = BoundarySpec.periodic(3)
    rng = np.random.default_rng(9)
    nvar = 5
    r = 1.0 + 0.3 * rng.random(shape)
    v = 0.2 * rng.standard_normal((3,) + shape)
    p_ = 0.5 + 0.2 * rng.random(shape)
    e = p_ / (cfg.gamma - 1.0) + 0.5 * r * (v ** 2).sum(axis=0)
    ud = jnp.asarray(np.stack([r, r * v[0], r * v[1], r * v[2], e]),
                     jnp.float32)
    ok = jnp.asarray(rng.random(shape) < 0.1)
    dt = jnp.asarray(1e-3, jnp.float32)
    dx = 1.0 / shape[0]
    # kernel path (interpreter mode)
    up, okp = pk.pad_xy(ud, bc, cfg, ok=ok)
    un_k, phi_k = pk.fused_step_padded(up, dt, cfg, dx, shape,
                                       ok_pad=okp, interpret=True,
                                       want_flux=True)
    # XLA path through dense_sweep itself (identity layout: feed a
    # flat array whose maps come from a tiny complete-level tree is
    # overkill — compare against level-free dense formulation):
    from ramses_tpu.grid import boundary as bmod
    from ramses_tpu.hydro import muscl
    up2 = bmod.pad(ud, bc, cfg, muscl.NGHOST, dx=dx)
    flux, _tmp = muscl.unsplit(up2, None, dt, (dx,) * 3, cfg)
    okp2 = ok
    for d in range(3):
        padw = [(muscl.NGHOST, muscl.NGHOST) if d2 == d else (0, 0)
                for d2 in range(3)]
        okp2 = jnp.pad(okp2, padw, mode="wrap")
    masked = []
    for d in range(3):
        keep = ~(okp2 | jnp.roll(okp2, 1, axis=d))
        masked.append(flux[d] * keep[None].astype(flux.dtype))
    g = muscl.NGHOST
    for d in range(3):
        f0 = masked[d][0]
        lo_ix = tuple(slice(g, g + shape[dd]) for dd in range(3))
        hi_ix = tuple(slice(g + 1, g + 1 + shape[dd]) if dd == d
                      else slice(g, g + shape[dd]) for dd in range(3))
        np.testing.assert_allclose(np.asarray(phi_k[d, 0]),
                                   np.asarray(f0[lo_ix]),
                                   rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(np.asarray(phi_k[d, 1]),
                                   np.asarray(f0[hi_ix]),
                                   rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("want_flux", [False, True])
def test_fused_step_shard_relabel_parity(want_flux):
    """Per-shard relabeled entry == unrelabeled interior kernel.

    ``shard_axes`` gates to TPU, so drive ``fused_step_shard`` directly
    in interpreter mode: original axis 0 (extent 128) takes the lane
    role, axes 1/2 carry NG ghost slabs — the relabel the slab path
    produces when z was cut first.  Tolerance (not bitwise): the
    relabeled kernel sweeps directions in relabeled order.
    """
    from ramses_tpu.amr import kernels as K

    cfg = _cfg("hllc")
    loc = (128, 16, 16)
    axes = (1, 2, 0)
    rng = np.random.default_rng(7)
    r = 1.0 + 0.3 * rng.random(loc)
    v = 0.2 * rng.standard_normal((3,) + loc)
    p_ = 0.5 + 0.2 * rng.random(loc)
    e = p_ / (cfg.gamma - 1.0) + 0.5 * r * (v ** 2).sum(axis=0)
    u = jnp.asarray(np.stack([r, r * v[0], r * v[1], r * v[2], e]),
                    jnp.float32)
    okf = jnp.asarray(rng.random(loc) < 0.1, jnp.float32)
    dt = jnp.asarray(1e-3, jnp.float32)
    dx = 1.0 / loc[0]
    g = muscl.NGHOST
    # shard-path block: ghosts on axes[0]/axes[1] only, lane axis bare
    up, okp = u, okf
    for ax in axes[:2]:
        padw = [(g, g) if d == 1 + ax else (0, 0) for d in range(4)]
        up = jnp.pad(up, padw, mode="wrap")
        okp = jnp.pad(okp, [w for w in padw[1:]], mode="wrap")
    out_k = pk.fused_step_shard(up, okp, dt, cfg, dx, loc, axes,
                                want_flux=want_flux, interpret=True)
    # reference: fully ghost-padded unrelabeled interior kernel
    upf, okpf = u, okf
    for ax in range(3):
        padw = [(g, g) if d == 1 + ax else (0, 0) for d in range(4)]
        upf = jnp.pad(upf, padw, mode="wrap")
        okpf = jnp.pad(okpf, [w for w in padw[1:]], mode="wrap")
    out_r = K.dense_interior_update(upf, okpf, dt, dx, loc, cfg,
                                    ret_flux=want_flux)
    du_k = out_k[0] if want_flux else out_k
    du_r = out_r[0] if want_flux else out_r
    np.testing.assert_allclose(np.asarray(du_k), np.asarray(du_r),
                               rtol=2e-5, atol=2e-6)
    if want_flux:
        np.testing.assert_allclose(np.asarray(out_k[1]),
                                   np.asarray(out_r[1]),
                                   rtol=2e-5, atol=2e-6)
