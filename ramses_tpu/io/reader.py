"""Snapshot reader: parses ``output_NNNNN/`` back into arrays.

Record-walking counterpart of :mod:`ramses_tpu.io.snapshot` (the restart
path of the reference, ``amr/init_amr.f90`` / ``hydro/init_hydro.f90:137+``),
and the basis of the test oracle: :func:`leaf_cells` reproduces what the
reference's ``visu_ramses.load_snapshot`` extracts (leaf cells with
level/x/y/z/dx + primitive variables).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List

import numpy as np

from ramses_tpu.io import fortran as frt

_KIND_DTYPES = {"d": np.float64, "f": np.float32, "i": np.int32,
                "q": np.int64, "b": np.int8, "h": np.int16}


def read_descriptor(path: str) -> List[tuple]:
    out = []
    with open(path) as f:
        for line in f:
            if line.startswith("#"):
                continue
            parts = [p.strip() for p in line.split(",")]
            if len(parts) >= 3:
                out.append((parts[1], parts[2]))
    return out


def read_info(path: str) -> dict:
    info = {}
    with open(path) as f:
        for line in f:
            if "=" not in line:
                continue
            k, v = line.split("=", 1)
            k, v = k.strip(), v.strip()
            try:
                info[k] = int(v)
            except ValueError:
                try:
                    info[k] = float(v)
                except ValueError:
                    info[k] = v
    return info


@dataclass
class AmrFileData:
    header: dict
    # per level: dict with ind_grid, xg [n, ndim], son [n, 2^d] (ref order)
    levels: Dict[int, dict] = field(default_factory=dict)


def read_amr_file(path: str) -> AmrFileData:
    with open(path, "rb") as f:
        h = {}
        h["ncpu"] = frt.read_int(f)
        h["ndim"] = frt.read_int(f)
        h["nx"], h["ny"], h["nz"] = frt.read_ints(f)
        h["nlevelmax"] = frt.read_int(f)
        h["ngridmax"] = frt.read_int(f)
        h["nboundary"] = frt.read_int(f)
        h["ngrid_current"] = frt.read_int(f)
        h["boxlen"] = float(frt.read_reals(f)[0])
        h["noutput"], h["iout"], h["ifout"] = frt.read_ints(f)
        h["tout"] = frt.read_reals(f)
        h["aout"] = frt.read_reals(f)
        h["t"] = float(frt.read_reals(f)[0])
        h["dtold"] = frt.read_reals(f)
        h["dtnew"] = frt.read_reals(f)
        h["nstep"], h["nstep_coarse"] = frt.read_ints(f)
        frt.read_reals(f)                       # einit, mass_tot_0, rho_tot
        h["cosmo"] = tuple(frt.read_reals(f))
        aexp_rec = frt.read_reals(f)
        h["aexp"] = float(aexp_rec[0])
        frt.read_reals(f)                       # mass_sph
        ncpu, nlev = h["ncpu"], h["nlevelmax"]
        h["headl"] = frt.read_ints(f).reshape(nlev, ncpu).T
        h["taill"] = frt.read_ints(f).reshape(nlev, ncpu).T
        h["numbl"] = frt.read_ints(f).reshape(nlev, ncpu).T
        frt.read_ints(f)                        # numbtot
        if h["nboundary"] > 0:
            frt.read_ints(f)
            frt.read_ints(f)
            h["numbb"] = frt.read_ints(f).reshape(nlev, -1).T
        frt.read_ints(f)                        # free list
        h["ordering"] = frt.read_str(f)
        if h["ordering"] == "bisection":
            for _ in range(5):
                frt.skip_record(f)
        else:
            h["bound_key"] = frt.read_reals(f)
        ncoarse = h["nx"] * h["ny"] * h["nz"]
        h["son_coarse"] = frt.read_ints(f)
        frt.read_ints(f)                        # flag1 coarse
        frt.read_ints(f)                        # cpu_map coarse

        ndim = h["ndim"]
        twotondim = 1 << ndim
        twondim = 2 * ndim
        data = AmrFileData(header=h)
        for l in range(1, nlev + 1):
            ncache = int(h["numbl"][:, l - 1].sum())
            if h["nboundary"] > 0:
                ncache_b = int(h["numbb"][:, l - 1].sum())
            else:
                ncache_b = 0
            if ncache + ncache_b == 0:
                continue
            ind_grid = frt.read_ints(f)
            frt.read_ints(f)                    # next
            frt.read_ints(f)                    # prev
            xg = np.stack([frt.read_reals(f) for _ in range(ndim)], axis=1)
            frt.read_ints(f)                    # father
            for _ in range(twondim):
                frt.read_ints(f)                # nbor
            son = np.stack([frt.read_ints(f) for _ in range(twotondim)],
                           axis=1)
            for _ in range(2 * twotondim):
                frt.read_ints(f)                # cpu_map, flag1
            data.levels[l] = dict(ind_grid=ind_grid, xg=xg, son=son)
        return data


def read_hydro_file(path: str) -> dict:
    with open(path, "rb") as f:
        ncpu = frt.read_int(f)
        nvar = frt.read_int(f)
        ndim = frt.read_int(f)
        nlevelmax = frt.read_int(f)
        nboundary = frt.read_int(f)
        gamma = float(frt.read_reals(f)[0])
        twotondim = 1 << ndim
        levels: Dict[int, np.ndarray] = {}
        for l in range(1, nlevelmax + 1):
            for ib in range(ncpu + nboundary):
                ilevel = frt.read_int(f)
                ncache = frt.read_int(f)
                if ncache == 0:
                    continue
                arr = np.empty((ncache, twotondim, nvar))
                for ind in range(twotondim):
                    for ivar in range(nvar):
                        arr[:, ind, ivar] = frt.read_reals(f)
                if ib < ncpu:
                    levels.setdefault(l, []).append(arr)
        for l in list(levels):
            levels[l] = np.concatenate(levels[l], axis=0)
        return dict(ncpu=ncpu, nvar=nvar, ndim=ndim, nlevelmax=nlevelmax,
                    gamma=gamma, levels=levels)


def read_grav_file(path: str) -> dict:
    with open(path, "rb") as f:
        ncpu = frt.read_int(f)
        nvar = frt.read_int(f)
        nlevelmax = frt.read_int(f)
        nboundary = frt.read_int(f)
        levels: Dict[int, np.ndarray] = {}
        twotondim = None
        for l in range(1, nlevelmax + 1):
            for ib in range(ncpu + nboundary):
                ilevel = frt.read_int(f)
                ncache = frt.read_int(f)
                if ncache == 0:
                    continue
                if twotondim is None:
                    # nvar = ndim + 1 ⇒ ndim ⇒ 2^ndim
                    twotondim = 1 << (nvar - 1)
                arr = np.empty((ncache, twotondim, nvar))
                for ind in range(twotondim):
                    for ivar in range(nvar):
                        arr[:, ind, ivar] = frt.read_reals(f)
                levels.setdefault(l, []).append(arr)
        for l in list(levels):
            levels[l] = np.concatenate(levels[l], axis=0)
        return dict(ncpu=ncpu, nvar=nvar, levels=levels)


def read_part_file(path: str, fields: List[tuple]) -> dict:
    with open(path, "rb") as f:
        ncpu = frt.read_int(f)
        ndim = frt.read_int(f)
        npart = frt.read_int(f)
        frt.read_ints(f)                        # localseed
        nstar = frt.read_int(f)
        mstar = float(frt.read_reals(f)[0])
        mstar_lost = float(frt.read_reals(f)[0])
        nsink = frt.read_int(f)
        out = dict(ncpu=ncpu, ndim=ndim, npart=npart, nstar_tot=nstar,
                   mstar_tot=mstar, mstar_lost=mstar_lost, nsink=nsink)
        for name, kind in fields:
            out[name] = frt.read_array(f, _KIND_DTYPES[kind])
        return out


def load_snapshot(outdir: str, read_grav: bool = False) -> dict:
    """Load a full snapshot directory (all cpu files)."""
    suffix = os.path.basename(outdir.rstrip("/")).split("_")[-1]
    info = read_info(os.path.join(outdir, f"info_{suffix}.txt"))
    ncpu = info["ncpu"]
    amr = []
    hyd = []
    grav = []
    for icpu in range(1, ncpu + 1):
        amr.append(read_amr_file(
            os.path.join(outdir, f"amr_{suffix}.out{icpu:05d}")))
        hyd.append(read_hydro_file(
            os.path.join(outdir, f"hydro_{suffix}.out{icpu:05d}")))
        gpath = os.path.join(outdir, f"grav_{suffix}.out{icpu:05d}")
        if read_grav and os.path.exists(gpath):
            grav.append(read_grav_file(gpath))
    var_names = [n for n, _ in read_descriptor(
        os.path.join(outdir, "hydro_file_descriptor.txt"))]
    snap = dict(info=info, amr=amr, hydro=hyd, grav=grav,
                var_names=var_names)
    pdesc = os.path.join(outdir, "part_file_descriptor.txt")
    if os.path.exists(pdesc):
        fields = read_descriptor(pdesc)
        parts = [read_part_file(
            os.path.join(outdir, f"part_{suffix}.out{icpu:05d}"), fields)
            for icpu in range(1, ncpu + 1)]
        snap["part"] = parts
        snap["part_fields"] = fields
    return snap


def leaf_cells(snap: dict) -> dict:
    """Leaf-cell table: the quantity ``visu_ramses.load_snapshot`` builds
    (cells where son==0 or level==levelmax) with positions in user units."""
    info = snap["info"]
    ndim = snap["amr"][0].header["ndim"]
    nlevelmax = snap["amr"][0].header["nlevelmax"]
    boxlen = snap["amr"][0].header["boxlen"]
    var_names = snap["var_names"]
    cols: Dict[str, List[np.ndarray]] = {k: [] for k in
                                         var_names + ["level", "dx"]
                                         + ["xyz"[d] for d in range(ndim)]}
    for amr, hyd in zip(snap["amr"], snap["hydro"]):
        for l, lev in amr.levels.items():
            if l not in hyd["levels"]:
                continue
            vals = hyd["levels"][l]               # [n, 2^d, nvar]
            son = lev["son"]
            xg = lev["xg"]
            dxc = 0.5 ** l
            n, ttd = son.shape
            for ind in range(ttd):
                leaf = ~((son[:, ind] > 0) & (l < nlevelmax))
                if not leaf.any():
                    continue
                # ref ind → cell offsets, x fastest
                cx = ind & 1
                cy = (ind >> 1) & 1 if ndim > 1 else 0
                cz = (ind >> 2) & 1 if ndim > 2 else 0
                offs = [cx, cy, cz][:ndim]
                for d in range(ndim):
                    x = xg[leaf, d] + (offs[d] - 0.5) * dxc
                    cols["xyz"[d]].append(x * boxlen)
                cols["level"].append(np.full(leaf.sum(), l))
                cols["dx"].append(np.full(leaf.sum(), dxc * boxlen))
                for iv, nm in enumerate(var_names):
                    cols[nm].append(vals[leaf, ind, iv])
    return {k: np.concatenate(v) if v else np.empty(0)
            for k, v in cols.items()}
