"""Gravity primitives shared by the coupled steppers.

The per-step sequence itself lives in :mod:`ramses_tpu.pm.coupling`
(``pm_hydro_step`` — one stepper for every physics combination, like the
reference's single ``amr_step``).  This module holds the pieces:

- :class:`GravitySpec` — static config of the solve
- :func:`solve_phi` / :func:`gravity_field` — Poisson RHS + solve + force
  (``Lap(phi) = fourpi*(rho - mean)``, ``fourpi = 4*pi`` in code units or
  ``1.5*omega_m*aexp`` under supercomoving cosmology,
  ``poisson/multigrid_fine_commons.f90:1082-1112``)
- :func:`kick` — momentum kick at fixed internal energy
  (``hydro/synchro_hydro_fine.f90:56-141``)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax.numpy as jnp

from ramses_tpu.grid import boundary as bmod
from ramses_tpu.hydro import muscl
from ramses_tpu.hydro.core import HydroStatic
from ramses_tpu.poisson import force as fmod
from ramses_tpu.poisson import solver as smod
from ramses_tpu.poisson.gravana import cell_centers, gravana


@dataclass(frozen=True)
class GravitySpec:
    """Static gravity configuration (jit-static argument)."""
    enabled: bool = False
    gravity_type: int = 0               # 0: self-gravity; >0: analytic
    gravity_params: Tuple[float, ...] = ()
    solver: str = "fft"                  # fft | mg | cg
    epsilon: float = 1e-4                # &POISSON_PARAMS epsilon
    ncycle: int = 10                     # MG V-cycle cap (MAXITER=10)
    cg_iters: int = 150
    boxlen: float = 1.0
    fourpi: float = 4.0 * 3.14159265358979323846  # rhs factor (cosmo varies)
    # False: any non-periodic face → isolated multipole-Dirichlet solve
    # (poisson/boundary_potential.f90 path, poisson/isolated.py)
    periodic: bool = True

    @classmethod
    def from_params(cls, p) -> "GravitySpec":
        if not p.run.poisson:
            return cls(enabled=False)
        # solver selection: the reference uses MG below cg_levelmin and CG
        # at/above it (amr/amr_step.f90:250-258); our uniform-grid default
        # is the exact FFT solve, overridable via &POISSON_PARAMS solver=.
        raw = p.raw.get("poisson_params", {}) if p.raw else {}
        default = "cg" if p.poisson.cg_levelmin <= p.amr.levelmin else "fft"
        solver = str(raw.get("solver", default)).strip("'\" ").lower()
        return cls(enabled=True,
                   gravity_type=int(p.poisson.gravity_type),
                   gravity_params=tuple(float(v)
                                        for v in p.poisson.gravity_params),
                   epsilon=float(p.poisson.epsilon),
                   solver=solver,
                   boxlen=float(p.amr.boxlen),
                   periodic=_all_periodic(
                       bmod.BoundarySpec.from_params(p)))


def solve_phi(spec: GravitySpec, rho, dx: float, fourpi=None):
    """Potential of the density contrast (zero-mean rhs, periodic).

    ``fourpi`` may be a traced override of the static rhs factor — the
    cosmological ``1.5*omega_m*aexp`` varies in time
    (``poisson/multigrid_fine_commons.f90:1087-1088``)."""
    factor = spec.fourpi if fourpi is None else fourpi
    rhs = factor * (rho - jnp.mean(rho))
    if spec.solver == "fft":
        return smod.fft_solve(rhs, dx)
    if spec.solver == "mg":
        return smod.mg_solve(rhs, dx, ncycle=spec.ncycle)
    if spec.solver == "cg":
        return smod.cg_solve(rhs, dx, iters=spec.cg_iters, tol=spec.epsilon)
    raise ValueError(spec.solver)


def gravity_field(spec: GravitySpec, rho, dx: float, fourpi=None):
    """Acceleration [ndim, *sp]: analytic model or self-gravity solve."""
    if spec.gravity_type > 0:
        x = cell_centers(rho.shape, dx, dtype=rho.dtype)
        return gravana(x, spec.gravity_type, spec.gravity_params,
                       spec.boxlen)
    if not spec.periodic:
        from ramses_tpu.poisson.isolated import (grad_isolated,
                                                 isolated_solve)
        factor = spec.fourpi if fourpi is None else fourpi
        phi, gh = isolated_solve(rho, dx, factor, iters=spec.cg_iters,
                                 tol=spec.epsilon)
        return grad_isolated(phi, gh, dx)
    phi = solve_phi(spec, rho, dx, fourpi)
    return fmod.force(phi, dx)


def kick(u, f, dteff, cfg: HydroStatic):
    """Momentum kick at fixed internal energy (synchydrofine1)."""
    r = jnp.maximum(u[0], cfg.smallr)
    ekin_old = sum(0.5 * u[1 + d] ** 2 for d in range(cfg.ndim)) / r
    mom = [u[1 + d] + r * f[d] * dteff for d in range(cfg.ndim)]
    ekin_new = sum(0.5 * m * m for m in mom) / r
    e = u[cfg.ndim + 1] - ekin_old + ekin_new
    return jnp.concatenate(
        [u[0:1], jnp.stack(mom), e[None], u[cfg.ndim + 2:]], axis=0)


def _all_periodic(bc: bmod.BoundarySpec) -> bool:
    return all(f.kind == bmod.PERIODIC for pair in bc.faces for f in pair)


def _pad_force(f, ndim: int, mode: str, ng: int = muscl.NGHOST):
    """Ghost-pad the force field (wrap for periodic, edge otherwise)."""
    pads = [(0, 0)] * (f.ndim - ndim) + [(ng, ng)] * ndim
    return jnp.pad(f, pads, mode=mode)
