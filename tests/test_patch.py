"""User patch overlay — the runtime equivalent of the reference's
compile-time PATCH= VPATH shadowing (``bin/Makefile:153-160``)."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu import patch
from ramses_tpu.config import params_from_dict



pytestmark = pytest.mark.smoke

@pytest.fixture(autouse=True)
def _clean_patch():
    patch.clear()
    yield
    patch.clear()


def _base_groups(**extra):
    g = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 4, "levelmax": 4, "boxlen": 1.0},
        "init_params": {"nregion": 1, "region_type": ["square"],
                        "x_center": [0.5], "length_x": [10.0],
                        "exp_region": [10.0],
                        "d_region": [1.0], "p_region": [1.0]},
        "hydro_params": {"gamma": 1.4, "courant_factor": 0.5},
        "output_params": {"tend": 0.01},
    }
    g.update(extra)
    return g


PATCH_SRC = '''
import numpy as np

def condinit(x, dx, params, cfg):
    """Linear density ramp along x — not expressible as regions."""
    q = np.zeros((cfg.nvar,) + x[0].shape)
    q[0] = 1.0 + x[0]
    q[cfg.ndim + 1] = 2.5
    return q

def gravana(x, gravity_type, gravity_params, boxlen):
    import jax.numpy as jnp
    g = jnp.zeros_like(x)
    return g.at[0].set(-3.0)          # uniform -x acceleration

def source(sim, dt):
    sim._patch_calls = getattr(sim, "_patch_calls", 0) + 1
'''


def test_install_from_file_and_hooks(tmp_path):
    pf = tmp_path / "mypatch.py"
    pf.write_text(PATCH_SRC)
    patch.install(str(pf))
    assert patch.hook("condinit") is not None
    assert patch.hook("gravana") is not None
    assert patch.hook("source") is not None
    assert patch.hook("boundana") is None
    patch.clear()
    assert patch.hook("condinit") is None


def test_namelist_patch_reconciliation(tmp_path):
    """A second sim with a different (or no) namelist patch must not
    inherit the first one's hooks; explicit installs win."""
    from ramses_tpu.driver import Simulation
    pf = tmp_path / "a.py"
    pf.write_text(PATCH_SRC)
    p1 = params_from_dict(_base_groups(), ndim=1)
    p1.run.patch = str(pf)
    Simulation(p1, dtype=jnp.float64)
    assert patch.hook("condinit") is not None
    # second sim, no patch: hooks cleared
    p2 = params_from_dict(_base_groups(), ndim=1)
    sim2 = Simulation(p2, dtype=jnp.float64)
    assert patch.hook("condinit") is None
    rho = np.asarray(sim2.state.u)[0]
    np.testing.assert_allclose(rho, 1.0)      # stock region ICs
    # explicit install survives a namelist-less sim
    patch.install(str(pf))
    Simulation(params_from_dict(_base_groups(), ndim=1),
               dtype=jnp.float64)
    assert patch.hook("condinit") is not None


def test_rhd_condinit_hook(tmp_path):
    """The patch condinit also shadows the rhd solver's IC path."""
    from ramses_tpu.rhd.core import RhdStatic
    from ramses_tpu.rhd.driver import rhd_condinit
    pf = tmp_path / "rhdpatch.py"
    pf.write_text("""
import numpy as np

def condinit(x, dx, params, cfg):
    q = np.zeros((cfg.nvar,) + x[0].shape)
    q[0] = 2.0 + x[0]
    q[4] = 0.5
    return q
""")
    patch.install(str(pf))
    p = params_from_dict(_base_groups(), ndim=1)
    cfg = RhdStatic(ndim=1)
    u = rhd_condinit((8,), 1.0 / 8, p, cfg)
    # D = rho*Gamma = rho at rest: the ramp survives the conversion
    x = (np.arange(8) + 0.5) / 8
    np.testing.assert_allclose(u[0], 2.0 + x, rtol=1e-12)


def test_condinit_hook_replaces_regions(tmp_path):
    from ramses_tpu.driver import Simulation
    pf = tmp_path / "mypatch.py"
    pf.write_text(PATCH_SRC)
    p = params_from_dict(_base_groups(), ndim=1)
    p.run.patch = str(pf)
    sim = Simulation(p, dtype=jnp.float64)
    rho = np.asarray(sim.state.u)[0]
    x = (np.arange(16) + 0.5) / 16
    np.testing.assert_allclose(rho, 1.0 + x, rtol=1e-6)


def test_gravana_hook(tmp_path):
    from ramses_tpu.poisson.coupling import GravitySpec, gravity_field
    pf = tmp_path / "mypatch.py"
    pf.write_text(PATCH_SRC)
    patch.install(str(pf))
    spec = GravitySpec(enabled=True, gravity_type=1,
                       gravity_params=(9.9,))
    f = gravity_field(spec, jnp.ones((8, 8)), 1.0 / 8)
    assert float(f[0][0, 0]) == -3.0          # hook, not the 9.9 const


def test_source_hook_called_amr(tmp_path):
    from ramses_tpu.amr.hierarchy import AmrSim
    pf = tmp_path / "mypatch.py"
    pf.write_text(PATCH_SRC)
    g = _base_groups()
    g["run_params"]["patch"] = str(pf)
    p = params_from_dict(g, ndim=1)
    sim = AmrSim(p, dtype=jnp.float64)
    sim.evolve(0.01, nstepmax=4)
    assert getattr(sim, "_patch_calls", 0) == sim.nstep


def test_cli_patch_flag(tmp_path):
    import ramses_tpu.__main__ as main_mod
    pf = tmp_path / "mypatch.py"
    pf.write_text(PATCH_SRC)
    nml = tmp_path / "run.nml"
    nml.write_text(f"""
&RUN_PARAMS
hydro=.true.
nstepmax=2
/
&AMR_PARAMS
levelmin=4
levelmax=4
boxlen=1.0
/
&INIT_PARAMS
nregion=1
region_type='square'
x_center=0.5
length_x=10.0
exp_region=10.0
d_region=1.0
p_region=1.0
/
&HYDRO_PARAMS
gamma=1.4
/
&OUTPUT_PARAMS
tend=0.005
output_dir='{tmp_path}'
/
""")
    assert main_mod.main([str(nml), "--ndim", "1", "--dtype", "float64",
                          "--patch", str(pf)]) == 0


def test_boundana_position_dependent():
    """A boundana hook declaring ``x`` receives ghost-cell coordinates
    and imposes a per-cell inflow profile (hydro/boundana.f90:45)."""
    import jax.numpy as jnp

    from ramses_tpu import patch
    from ramses_tpu.grid import boundary as bmod
    from ramses_tpu.hydro.core import HydroStatic
    from ramses_tpu.config import Params

    p = Params(ndim=2)
    cfg = HydroStatic.from_params(p)

    def boundana(d, side, cfg, x=None):
        # density ramp along y on the low-x face; constant elsewhere
        rho = 1.0 + x[1] if d == 0 and side == 0 else jnp.ones_like(x[0])
        return (rho, jnp.zeros_like(rho), jnp.zeros_like(rho),
                jnp.full_like(rho, 2.5))

    inflow = bmod.FaceBC(bmod.INFLOW, (1.0, 0.0, 0.0, 2.5))
    per = bmod.FaceBC()
    spec = bmod.BoundarySpec(faces=((inflow, per), (per, per)))
    n = 8
    dx = 1.0 / n
    u = jnp.ones((4, n, n))
    u = u.at[3].set(2.5 / (cfg.gamma - 1.0))
    import types
    mod = types.SimpleNamespace(boundana=boundana)
    try:
        patch.install(mod)
        up = bmod.pad(u, spec, cfg, 2, dx=dx)
    finally:
        patch.clear()
    # low-x ghosts carry the y ramp: rho(y) = 1 + (j+0.5)*dx
    ys = (np.arange(n) + 0.5) * dx
    np.testing.assert_allclose(np.asarray(up[0, 0, 2:-2]), 1.0 + ys,
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(up[0, 1, 2:-2]), 1.0 + ys,
                               rtol=1e-6)
    # energy ghosts: pure thermal at P=2.5
    np.testing.assert_allclose(np.asarray(up[3, 0, 2:-2]),
                               2.5 / (cfg.gamma - 1.0), rtol=1e-6)


def test_boundana_transverse_coordinates_after_padding():
    """An inflow profile on a HIGHER dim's face sees transverse
    coordinates consistent with the already-padded lower dims (the
    y-face ghost block includes x ghosts at negative x)."""
    import jax.numpy as jnp

    from ramses_tpu import patch
    from ramses_tpu.grid import boundary as bmod
    from ramses_tpu.hydro.core import HydroStatic
    from ramses_tpu.config import Params

    p = Params(ndim=2)
    cfg = HydroStatic.from_params(p)
    seen = {}

    def boundana(d, side, cfg, x=None):
        seen[(d, side)] = tuple(np.asarray(c) for c in x)
        rho = 1.0 + x[0]               # x-dependent on the y-face
        return (rho, jnp.zeros_like(rho), jnp.zeros_like(rho),
                jnp.full_like(rho, 2.5))

    inflow = bmod.FaceBC(bmod.INFLOW, (1.0, 0.0, 0.0, 2.5))
    per = bmod.FaceBC()
    spec = bmod.BoundarySpec(faces=((per, per), (inflow, per)))
    n = 8
    dx = 1.0 / n
    u = jnp.ones((4, n, n))
    import types
    try:
        patch.install(types.SimpleNamespace(boundana=boundana))
        up = bmod.pad(u, spec, cfg, 2, dx=dx)
    finally:
        patch.clear()
    xcoords = seen[(1, 0)][0]
    # the y-face ghost block spans the PADDED x axis: its first two x
    # rows are the x-ghost columns at negative coordinates
    assert xcoords.shape == (n + 4, 2)
    np.testing.assert_allclose(xcoords[0, 0], -1.5 * dx)
    np.testing.assert_allclose(xcoords[2, 0], 0.5 * dx)
    # and the imposed density follows 1 + x at the INTERIOR columns
    np.testing.assert_allclose(np.asarray(up[0, 2:-2, 0]),
                               1.0 + (np.arange(n) + 0.5) * dx,
                               rtol=1e-6)
