"""Canonical lowered step-chain programs the rule engine audits.

Generalizes ``telemetry/hlo.py lower_fused_step`` into an enumerator:
each driver family (uniform hydro, blocked/stencil AMR hydro, MHD CT,
RHD, RT-coupled, and — when the process has >1 device — the
row-sharded mesh) is built from a small canonical namelist on the CPU
backend and LOWERED only (trace, no compile, no execution past the
IC build), so the full enumeration costs seconds and the audited
StableHLO is exactly what a production run of that family would
compile.

Per-program ``meta`` carries the rule inputs: configured dtype bits
(``f64-leak``), donation expectation (``donation-miss``), partition
count (``nondeterministic-scatter``), and the gather budget
(``gather-blowup`` — budgets are the measured canonical-tree counts
with ~50% headroom, so a formulation regression trips the budget
while ordinary tree drift does not).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

# Canonical 2D Sedov used by the hydro AMR programs: two partial
# levels, small enough that the full build-and-lower is ~seconds on
# one CPU core.
SEDOV2D = """
&RUN_PARAMS
hydro=.true.
/
&AMR_PARAMS
levelmin=4
levelmax=5
boxlen=1.0
oct_blocking={blk}
/
&INIT_PARAMS
nregion=2
region_type(1)='square'
region_type(2)='point'
x_center=0.5,0.5
y_center=0.5,0.5
length_x=10.0,1.0
length_y=10.0,1.0
d_region=1.0,0.0
p_region=1e-5,0.1
/
&HYDRO_PARAMS
gamma=1.4
riemann='llf'
/
&REFINE_PARAMS
err_grad_p=0.1
/
"""

# gathered-element budgets of the canonical trees (measured on the
# seed lowering x ~1.5 headroom; a duplicated-batch regression is a
# >=2x jump, far past the headroom)
GATHER_BUDGETS = {
    "hydro_amr": 200_000,
    "mhd_amr": 800_000,
    "rhd_amr": 40_000,
    "rt_amr": 120_000,
    "hydro_amr_sharded": 400_000,
}


@dataclass
class Program:
    """One lowered program under audit."""
    name: str
    family: str                    # hydro | mhd | rhd | rt | uniform
    text: str
    meta: Dict[str, Any] = field(default_factory=dict)


def _dtype_bits(dtype) -> int:
    import jax.numpy as jnp
    return int(jnp.dtype(dtype).itemsize) * 8


def _from_sim(name: str, family: str, sim, text: Optional[str] = None,
              **meta) -> Program:
    from ramses_tpu.telemetry import hlo
    meta.setdefault("dtype_bits", _dtype_bits(sim.dtype))
    meta.setdefault("expect_donation", True)
    if name in GATHER_BUDGETS:
        meta.setdefault("gather_budget_elems", GATHER_BUDGETS[name])
    return Program(name=name, family=family,
                   text=text or hlo.lower_fused_step(sim), meta=meta)


def sim_program(sim, name: Optional[str] = None,
                text: Optional[str] = None) -> Program:
    """Audit-ready :class:`Program` for an already-built sim's fused
    step — the telemetry run-header hook (``analysis_findings``)
    audits the exact program the run measures through this.  Pass
    ``text`` when the caller already holds the lowering (the run
    header lowers once for the gather inventory and reuses it)."""
    family = "mhd" if hasattr(sim, "bfs") else "hydro"
    return _from_sim(name or type(sim).__name__, family, sim,
                     text=text)


# -- builders ---------------------------------------------------------
def _build_uniform() -> Program:
    import jax.numpy as jnp

    from ramses_tpu.config import params_from_string
    from ramses_tpu.driver import Simulation
    from ramses_tpu.grid.uniform import run_steps

    nml = "\n".join([
        "&RUN_PARAMS", "hydro=.true.", "/",
        "&AMR_PARAMS", "levelmin=5", "levelmax=5", "boxlen=1.0", "/",
        "&INIT_PARAMS", "nregion=1", "region_type(1)='square'",
        "d_region=1.0", "p_region=1.0", "/",
        "&OUTPUT_PARAMS", "tend=0.1", "/",
    ])
    sim = Simulation(params_from_string(nml, ndim=2),
                     dtype=jnp.float32)
    u = sim.state.u
    z = jnp.zeros((), u.dtype)
    text = run_steps.lower(sim.grid, u, z, z + 0.1, 4).as_text()
    # run_steps deliberately does NOT donate (the redo-step guard
    # retains the pre-window state) — expect_donation stays False
    return Program(name="hydro_uniform", family="uniform", text=text,
                   meta={"dtype_bits": 32, "expect_donation": False})


def _build_hydro_amr() -> Program:
    import jax.numpy as jnp

    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.config import params_from_string

    sim = AmrSim(params_from_string(SEDOV2D.format(blk=".true."),
                                    ndim=2), dtype=jnp.float32)
    # no ratio gate here: on the tiny 2D canonical tree the blocked
    # formulation gathers ~1.1x MORE than the stencil one (thin tiles,
    # low occupancy) — blocking pays off on deep 3D trees, which is
    # where the >=2x ratio gate lives (test_hlo_inventory slow tier,
    # through check_gather_ratio).  The budget is the gate here.
    return _from_sim("hydro_amr", "hydro", sim)


def _repo_path(rel: str) -> str:
    import os

    import ramses_tpu
    root = os.path.dirname(os.path.dirname(
        os.path.abspath(ramses_tpu.__file__)))
    return os.path.join(root, rel)


def _build_mhd_amr() -> Program:
    import jax.numpy as jnp

    from ramses_tpu.config import load_params
    from ramses_tpu.mhd.amr import MhdAmrSim

    p = load_params(_repo_path("namelists/tube_mhd.nml"), ndim=2)
    p.amr.levelmin, p.amr.levelmax = 4, 5
    p.refine.err_grad_d = 0.05
    p.refine.err_grad_p = 0.05
    sim = MhdAmrSim(p, dtype=jnp.float32)
    return _from_sim("mhd_amr", "mhd", sim)


def _build_rhd_amr() -> Program:
    import jax.numpy as jnp

    from ramses_tpu.config import params_from_dict
    from ramses_tpu.rhd.amr import RhdAmrSim

    groups = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 4, "levelmax": 5, "boxlen": 1.0},
        "boundary_params": {"nboundary": 2,
                            "ibound_min": [-1, 1],
                            "ibound_max": [-1, 1],
                            "bound_type": [2, 2]},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.25, 0.75],
                        "length_x": [0.5, 0.5],
                        "exp_region": [10.0, 10.0],
                        "d_region": [10.0, 1.0],
                        "p_region": [13.33, 1e-2]},
        "hydro_params": {"gamma": 5.0 / 3.0, "slope_type": 1},
        "refine_params": {"err_grad_d": 0.05, "err_grad_p": 0.05},
        "output_params": {"tend": 0.35},
    }
    sim = RhdAmrSim(params_from_dict(groups, ndim=1),
                    dtype=jnp.float32)
    return _from_sim("rhd_amr", "rhd", sim)


def _build_rt_amr() -> Program:
    import jax.numpy as jnp

    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.config import params_from_dict

    groups = {
        "run_params": {"hydro": True, "rt": True},
        "amr_params": {"levelmin": 3, "levelmax": 4, "boxlen": 1.0},
        "init_params": {"nregion": 1, "region_type": ["square"],
                        "x_center": [0.5], "y_center": [0.5],
                        "z_center": [0.5],
                        "length_x": [10.0], "length_y": [10.0],
                        "length_z": [10.0], "exp_region": [10.0],
                        "d_region": [1.0], "p_region": [1e-4]},
        "hydro_params": {"gamma": 5.0 / 3.0},
        "refine_params": {"err_grad_d": 0.05},
        "rt_params": {"rt_ndot": 1e48, "rt_c_fraction": 1e-4,
                      "rt_src_pos": [0.5, 0.5, 0.5],
                      "rt_otsa": True},
        "units_params": {"units_density": 1.66e-24,
                         "units_time": 3.15e13,
                         "units_length": 3.08e18},
        "output_params": {"tend": 0.01},
    }
    sim = AmrSim(params_from_dict(groups, ndim=3), dtype=jnp.float32)
    return _from_sim("rt_amr", "rt", sim)


def _build_hydro_amr_sharded() -> Optional[Program]:
    import jax
    import jax.numpy as jnp

    if jax.device_count() < 2:
        return None
    from ramses_tpu.config import params_from_string
    from ramses_tpu.parallel.amr_sharded import ShardedAmrSim

    # default GSPMD mode on purpose (explicit_comm=False): this is the
    # shape a plain multi-device run compiles, and it KEEPS one accepted
    # nondeterministic-scatter finding — the blocked tile sweep folds
    # the partial level's coarse corrections through a scatter-add the
    # partitioner may reassociate.  The explicit_comm=True schedule
    # routes that fold deterministically (amr_comm.sweep_correct_
    # explicit) and is opted into per run, not audited here.
    sim = ShardedAmrSim(
        params_from_string(SEDOV2D.format(blk=".true."), ndim=2),
        devices=jax.devices(), dtype=jnp.float32)
    return _from_sim("hydro_amr_sharded", "hydro", sim,
                     partitioned=True)


BUILDERS: Dict[str, Callable[[], Optional[Program]]] = {
    "hydro_uniform": _build_uniform,
    "hydro_amr": _build_hydro_amr,
    "mhd_amr": _build_mhd_amr,
    "rhd_amr": _build_rhd_amr,
    "rt_amr": _build_rt_amr,
    "hydro_amr_sharded": _build_hydro_amr_sharded,
}


def build_programs(names: Optional[List[str]] = None) -> List[Program]:
    """Build and lower the requested canonical programs (all by
    default; builders whose preconditions fail — e.g. the sharded
    program on a 1-device process — return None and are skipped).

    Builds run with x64 disabled regardless of the host config:
    production runs f32/i32, and the test suite's global
    ``jax_enable_x64`` would otherwise drag weak-typed python floats
    into the canonical lowerings as f64 select/multiply chains —
    exactly what ``f64-leak`` flags, but as a host-environment
    artifact rather than a program property."""
    from jax.experimental import disable_x64
    out: List[Program] = []
    with disable_x64():
        for name, build in BUILDERS.items():
            if names is not None and name not in names:
                continue
            prog = build()
            if prog is not None:
                out.append(prog)
    return out
