"""Non-equilibrium hydrogen photochemistry + photoheating.

The ``rt/rt_cooling_module.f90`` capability, reduced to the gray
single-group hydrogen system (multi-group/He structure slots in along the
same axes): per cell and substep, implicitly coupled updates of

  photon density:  dN/dt = -c σ n_HI N                (absorption)
  ionized fraction: dx/dt = (Γ + β(T) n_e) (1-x) - α(T) n_e x
  temperature:      photoheating e_γ per ionization, recombination +
                    collisional-ionization cooling

with on-the-spot approximation (case-B recombination, ``rt_otsa``).
Rates are the standard published fits (Cen 1992; Hui & Gnedin 1997).
All quantities cgs; the update is one fused elementwise program.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ramses_tpu.units import kB

EV = 1.602177e-12
# canonical ionization thresholds [eV] — shared with rt.spectra
ION_EV = (13.5984, 24.5874, 54.4178)     # HI, HeI, HeII
E_ION_HI = ION_EV[0] * EV


@dataclass(frozen=True)
class GroupSpec:
    """Gray photon group (the reference's per-group SED-averaged
    cross-sections/energies, ``rt/rt_spectra.f90``)."""
    sigma: float = 3.0e-18       # cm^2, HI-ionization-weighted
    e_photon: float = 18.85 * EV  # mean photon energy (1e5 K blackbody)


def alpha_B(T):
    """Case-B recombination [cm^3/s] (Hui & Gnedin 1997 fit)."""
    lam = 2.0 * 157807.0 / jnp.maximum(T, 1.0)
    return 2.753e-14 * lam ** 1.5 / (1.0 + (lam / 2.74) ** 0.407) ** 2.242


def alpha_A(T):
    lam = 2.0 * 157807.0 / jnp.maximum(T, 1.0)
    return 1.269e-13 * lam ** 1.503 / (1.0 + (lam / 0.522) ** 0.47) ** 1.923


def beta_ci(T):
    """Collisional ionization [cm^3/s] (Cen 1992)."""
    T = jnp.maximum(T, 1.0)
    return (5.85e-11 * jnp.sqrt(T) * jnp.exp(-157809.1 / T)
            / (1.0 + jnp.sqrt(T / 1e5)))


def cool_rec_B(T):
    """Case-B recombination cooling [erg cm^3/s]."""
    lam = 2.0 * 157807.0 / jnp.maximum(T, 1.0)
    return (3.435e-30 * T * lam ** 1.97
            / (1.0 + (lam / 2.25) ** 0.376) ** 3.72)


def alpha_B_HeII(T):
    """Case-B He+ recombination [cm^3/s] (Hui & Gnedin 1997)."""
    lam = 2.0 * 285335.0 / jnp.maximum(T, 1.0)
    return 1.26e-14 * lam ** 0.75


def alpha_B_HeIII(T):
    """Case-B He++ recombination: hydrogenic Z=2 scaling of HG97."""
    lam = 2.0 * 631515.0 / jnp.maximum(T, 1.0)
    return 2.0 * 2.753e-14 * lam ** 1.5 \
        / (1.0 + (lam / 2.74) ** 0.407) ** 2.242


def beta_ci_HeI(T):
    T = jnp.maximum(T, 1.0)
    return (2.38e-11 * jnp.sqrt(T) * jnp.exp(-285335.4 / T)
            / (1.0 + jnp.sqrt(T / 1e5)))


def beta_ci_HeII(T):
    T = jnp.maximum(T, 1.0)
    return (5.68e-12 * jnp.sqrt(T) * jnp.exp(-631515.0 / T)
            / (1.0 + jnp.sqrt(T / 1e5)))


E_ION = tuple(e * EV for e in ION_EV)


def chem_step_3ion(Ns, xs, T, nH, nHe, dt, c_red, groups,
                   otsa: bool = True, niter: int = 5,
                   heating: bool = True, uv=None):
    """Multigroup, 3-ion (HII, HeII, HeIII) implicit chemistry substep —
    the ``rt_cooling_module.f90`` system with helium.

    ``Ns``: list of per-group photon densities; ``xs`` = (xHII, xHeII,
    xHeIII) fractional abundances (of H and He respectively); ``groups``:
    :class:`ramses_tpu.rt.spectra.Group3` tuple.  ``uv``: optional
    homogeneous UV background (``rt_UV_hom``) as (gamma[3] 1/s,
    heat[3] erg/s) per HI/HeI/HeII atom.  Returns (Ns', xs', T').
    """
    xH0, xHe20, xHe30 = [jnp.clip(x, 1e-10, 1.0 - 1e-10) for x in xs]
    xH, xHe2, xHe3 = xH0, xHe20, xHe30
    aH = alpha_B(T) if otsa else alpha_A(T)
    aHe2 = alpha_B_HeII(T)
    aHe3 = alpha_B_HeIII(T)

    def densities(xH, xHe2, xHe3):
        nHI = nH * (1.0 - xH)
        nHeI = nHe * jnp.clip(1.0 - xHe2 - xHe3, 1e-10, 1.0)
        nHeII = nHe * xHe2
        ne = nH * xH + nHe * (xHe2 + 2.0 * xHe3)
        return nHI, nHeI, nHeII, ne

    for _ in range(niter):
        nHI, nHeI, nHeII, ne = densities(xH, xHe2, xHe3)
        # implicit absorption per group at fixed ion densities
        Gam = [jnp.zeros_like(T) for _ in range(3)]
        N_new = []
        for g, N in zip(groups, Ns):
            tau = (g.sigmaN[0] * nHI + g.sigmaN[1] * nHeI
                   + g.sigmaN[2] * nHeII)
            Np = N / (1.0 + dt * c_red * tau)
            N_new.append(Np)
            for sp in range(3):
                Gam[sp] = Gam[sp] + c_red * g.sigmaN[sp] * Np
        if uv is not None:
            for sp in range(3):
                Gam[sp] = Gam[sp] + uv[0][sp]
        # H: (Γ + β ne)(1-x) = α ne x — implicit from the FIXED initial
        # state, rates refined at the current guess (see chem_step)
        creH = Gam[0] + beta_ci(T) * ne
        xH = jnp.clip((xH0 + dt * creH) / (1.0 + dt * (creH + aH * ne)),
                      1e-10, 1.0 - 1e-10)
        # He ladder: HeI→HeII (Γ1+β ne), HeII→HeIII (Γ2+β ne),
        # HeIII→HeII (α3 ne), HeII→HeI (α2 ne); linearized implicit
        cre1 = Gam[1] + beta_ci_HeI(T) * ne
        cre2 = Gam[2] + beta_ci_HeII(T) * ne
        xHeI = jnp.clip(1.0 - xHe2 - xHe3, 1e-10, 1.0)
        xHe2 = jnp.clip(
            (xHe20 + dt * (cre1 * xHeI + aHe3 * ne * xHe3))
            / (1.0 + dt * (cre2 + aHe2 * ne)), 1e-10, 1.0)
        xHe3 = jnp.clip((xHe30 + dt * cre2 * xHe2)
                        / (1.0 + dt * aHe3 * ne), 1e-10, 1.0)
        s = xHe2 + xHe3
        over = s > 1.0 - 1e-10
        xHe2 = jnp.where(over, xHe2 / s * (1.0 - 1e-10), xHe2)
        xHe3 = jnp.where(over, xHe3 / s * (1.0 - 1e-10), xHe3)

    nHI, nHeI, nHeII, ne = densities(xH, xHe2, xHe3)
    N_out = []
    heat = jnp.zeros_like(T)
    for g, N in zip(groups, Ns):
        tau_sp = [g.sigmaN[0] * nHI, g.sigmaN[1] * nHeI,
                  g.sigmaN[2] * nHeII]
        tau = tau_sp[0] + tau_sp[1] + tau_sp[2]
        Np = N / (1.0 + dt * c_red * tau)
        N_out.append(Np)
        if heating:
            absorbed = jnp.maximum(N - Np, 0.0) / dt
            frac = [t / jnp.maximum(tau, 1e-300) for t in tau_sp]
            for sp in range(3):
                heat = heat + absorbed * frac[sp] * jnp.maximum(
                    g.e_photon - E_ION[sp], 0.0)
    if heating:
        if uv is not None:
            heat = heat + (uv[1][0] * nHI + uv[1][1] * nHeI
                           + uv[1][2] * nHeII)
        cool = (cool_rec_B(T) * ne * nH * xH
                + 1.55e-26 * T ** 0.3647 * ne * nHeII)   # He+ rec (Cen92)
        ntot = nH * (1.0 + xH) + nHe * (1.0 + xHe2 + 2.0 * xHe3)
        dT = dt * (heat - cool) / (1.5 * kB * jnp.maximum(ntot, 1e-30))
        T = jnp.maximum(T + dT, 1.0)
    return N_out, (xH, xHe2, xHe3), T


def chem_step(N, xHII, T, nH, dt, c_red, group: GroupSpec,
              otsa: bool = True, niter: int = 5, heating: bool = True,
              uv=None):
    """One implicitly-coupled chemistry substep.  Returns (N', x', T').

    Sequential implicit sweep (the reference's cell-wise iteration,
    ``rt_cooling_module`` order absorption → ionization → thermal),
    fixed-point iterated ``niter`` times for the x↔ne coupling.
    """
    x0 = jnp.clip(xHII, 1e-10, 1.0 - 1e-10)
    x = x0
    alpha = alpha_B(T) if otsa else alpha_A(T)

    # fixed-point refinement of the IMPLICIT update: rates evaluate at
    # the current guess, but the step always starts from x0 (iterating
    # the update itself would compound niter timesteps of ionization)
    for _ in range(niter):
        nHI = nH * (1.0 - x)
        # implicit absorption at fixed nHI
        N_new = N / (1.0 + dt * c_red * group.sigma * nHI)
        gamma = c_red * group.sigma * N_new         # photoionizations/s/atom
        if uv is not None:
            gamma = gamma + uv[0][0]
        ne = nH * x
        cre = gamma + beta_ci(T) * ne
        dst = alpha * ne
        x = jnp.clip((x0 + dt * cre) / (1.0 + dt * (cre + dst)),
                     1e-10, 1.0 - 1e-10)

    nHI = nH * (1.0 - x)
    N_out = N / (1.0 + dt * c_red * group.sigma * nHI)
    # photons actually absorbed per volume
    absorbed = jnp.maximum(N - N_out, 0.0)

    if heating:
        ne = nH * x
        heat = absorbed / dt * (group.e_photon - E_ION_HI)
        if uv is not None:
            heat = heat + uv[1][0] * nHI
        cool = cool_rec_B(T) * ne * nH * x
        ntot = nH * (1.0 + x)                        # H + electrons
        dT = dt * (heat - cool) / (1.5 * kB * jnp.maximum(ntot, 1e-30))
        T = jnp.maximum(T + dT, 1.0)
    return N_out, x, T
