// CPU baseline proxy: 3D geometric multigrid V-cycle for the Poisson
// equation, red-black Gauss-Seidel smoothing.
//
// Mirrors the algorithmic cost of the reference's per-level multigrid —
// poisson/multigrid_fine_fine.f90: gauss_seidel_mg_fine (:332, red/black
// x2 pre + x2 post), cmp_residual_mg_fine (:147), restrict_residual_fine
// (:457), interpolate_and_correct_fine (:596) — driven by the V-cycle of
// multigrid_fine_commons.f90:25-305.  Reports V-cycles/sec on a uniform
// grid; the reference cannot be compiled here (no Fortran compiler), so
// this proxy is the measured stand-in for its "multigrid iters/sec".
//
// Build: g++ -O3 -march=native -funroll-loops -o mg3d mg3d.cc
// Run:   ./mg3d [N] [ncycles]   -> one JSON line on stdout
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <chrono>
#include <cstring>
#include <vector>

struct Level {
  int n;
  std::vector<double> phi, rhs, res;
  Level(int n_) : n(n_), phi((size_t)n_ * n_ * n_), rhs(phi.size()),
                  res(phi.size()) {}
  inline size_t id(int i, int j, int k) const {
    return ((size_t)i * n + j) * n + k;
  }
};

// periodic index
static inline int pw(int i, int n) { return (i + n) % n; }

static void smooth(Level &L, int color, double dx2) {
  const int n = L.n;
  for (int i = 0; i < n; i++)
    for (int j = 0; j < n; j++)
      for (int k = 0; k < n; k++) {
        if (((i + j + k) & 1) != color) continue;
        double nb = L.phi[L.id(pw(i - 1, n), j, k)] +
                    L.phi[L.id(pw(i + 1, n), j, k)] +
                    L.phi[L.id(i, pw(j - 1, n), k)] +
                    L.phi[L.id(i, pw(j + 1, n), k)] +
                    L.phi[L.id(i, j, pw(k - 1, n))] +
                    L.phi[L.id(i, j, pw(k + 1, n))];
        L.phi[L.id(i, j, k)] = (nb - dx2 * L.rhs[L.id(i, j, k)]) / 6.0;
      }
}

static void residual(Level &L, double dx2) {
  const int n = L.n;
  for (int i = 0; i < n; i++)
    for (int j = 0; j < n; j++)
      for (int k = 0; k < n; k++) {
        double nb = L.phi[L.id(pw(i - 1, n), j, k)] +
                    L.phi[L.id(pw(i + 1, n), j, k)] +
                    L.phi[L.id(i, pw(j - 1, n), k)] +
                    L.phi[L.id(i, pw(j + 1, n), k)] +
                    L.phi[L.id(i, j, pw(k - 1, n))] +
                    L.phi[L.id(i, j, pw(k + 1, n))];
        L.res[L.id(i, j, k)] =
            L.rhs[L.id(i, j, k)] - (nb - 6.0 * L.phi[L.id(i, j, k)]) / dx2;
      }
}

static void vcycle(std::vector<Level> &levels, int l, double dx) {
  Level &L = levels[l];
  double dx2 = dx * dx;
  smooth(L, 0, dx2); smooth(L, 1, dx2);
  smooth(L, 0, dx2); smooth(L, 1, dx2);
  if (l + 1 < (int)levels.size()) {
    residual(L, dx2);
    Level &C = levels[l + 1];
    std::memset(C.phi.data(), 0, C.phi.size() * sizeof(double));
    const int cn = C.n;
    for (int i = 0; i < cn; i++)
      for (int j = 0; j < cn; j++)
        for (int k = 0; k < cn; k++) {
          double sum = 0;
          for (int a = 0; a < 2; a++)
            for (int b = 0; b < 2; b++)
              for (int c = 0; c < 2; c++)
                sum += L.res[L.id(2 * i + a, 2 * j + b, 2 * k + c)];
          C.rhs[C.id(i, j, k)] = sum / 8.0;
        }
    vcycle(levels, l + 1, 2 * dx);
    for (int i = 0; i < cn; i++)
      for (int j = 0; j < cn; j++)
        for (int k = 0; k < cn; k++) {
          double corr = C.phi[C.id(i, j, k)];
          for (int a = 0; a < 2; a++)
            for (int b = 0; b < 2; b++)
              for (int c = 0; c < 2; c++)
                L.phi[L.id(2 * i + a, 2 * j + b, 2 * k + c)] += corr;
        }
  }
  smooth(L, 0, dx2); smooth(L, 1, dx2);
  smooth(L, 0, dx2); smooth(L, 1, dx2);
}

int main(int argc, char **argv) {
  int n = argc > 1 ? atoi(argv[1]) : 128;
  int ncyc = argc > 2 ? atoi(argv[2]) : 10;
  std::vector<Level> levels;
  for (int m = n; m >= 4; m /= 2) levels.emplace_back(m);
  Level &F = levels[0];
  // point-mass style rhs (p-pointmass3.nml analogue): delta sources,
  // zero-mean for periodic solvability
  double mean = 3.0 / ((double)n * n * n);
  for (size_t c = 0; c < F.rhs.size(); c++) F.rhs[c] = -mean;
  F.rhs[F.id(n / 2, n / 2, n / 2)] += 1.0;
  F.rhs[F.id(n / 4, n / 2, n / 2)] += 1.0;
  F.rhs[F.id(3 * n / 4, n / 2, n / 2)] += 1.0;
  double dx = 1.0 / n;

  vcycle(levels, 0, dx);  // warm-up
  auto t0 = std::chrono::steady_clock::now();
  for (int it = 0; it < ncyc; it++) vcycle(levels, 0, dx);
  auto t1 = std::chrono::steady_clock::now();
  double wall = std::chrono::duration<double>(t1 - t0).count();
  residual(F, dx * dx);
  double rn = 0;
  for (double r : F.res) rn += r * r;
  printf("{\"proxy\": \"mg3d-vcycle\", \"n\": %d, \"cycles\": %d, "
         "\"wall_s\": %.4f, \"vcycles_per_sec\": %.4f, "
         "\"resnorm\": %.3e}\n",
         n, ncyc, wall, ncyc / wall, std::sqrt(rn));
  return 0;
}
