"""Snapshot post-processing: amr2map / part2map equivalents.

The reference ships 56 standalone f90 analysis programs (``utils/f90``,
SURVEY.md §2.11); the two workhorses project AMR snapshots
(``amr2map``) and particle snapshots (``part2map``) to 2D maps.  These
read our ``output_NNNNN`` directories through :mod:`ramses_tpu.io.reader`
and write the movie frame format.

CLI:  ``python -m ramses_tpu.utils.maps amr2map output_00001 out.map
      --var density --dir z --nx 256``
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional

import numpy as np

from ramses_tpu.io import reader as rdr
from ramses_tpu.io.movie import write_frame


def amr2map(outdir: str, var: str = "density", axis: int = 2,
            nx: int = 256, kind: str = "mean") -> np.ndarray:
    """Project leaf cells onto a 2D grid (mass/volume-weighted)."""
    snap = rdr.load_snapshot(outdir)
    cells = rdr.leaf_cells(snap)
    ndim = snap["info"]["ndim"]
    boxlen = snap["amr"][0].header["boxlen"]
    axes2d = [d for d in range(ndim) if d != axis][:2]
    if ndim == 1:
        axes2d = [0]
    vals = cells[var]
    dx = cells["dx"]
    w = dx ** ndim                     # volume weight
    if kind == "max":
        grid = np.full((nx,) * min(len(axes2d), 2), -np.inf)
    else:
        grid = np.zeros((nx,) * min(len(axes2d), 2))
        wsum = np.zeros_like(grid)
    coords = [np.clip((cells["xyz"[d]] / boxlen * nx).astype(int),
                      0, nx - 1) for d in axes2d]
    idx = tuple(coords)
    if kind == "max":
        np.maximum.at(grid, idx, vals)
        grid[np.isneginf(grid)] = 0.0
        return grid
    np.add.at(grid, idx, vals * w)
    np.add.at(wsum, idx, w)
    return grid / np.maximum(wsum, 1e-300)


def part2map(outdir: str, axis: int = 2, nx: int = 256) -> np.ndarray:
    """Mass-weighted particle surface density map."""
    snap = rdr.load_snapshot(outdir)
    if "part" not in snap:
        raise FileNotFoundError(f"no particle files in {outdir}")
    part = snap["part"][0]
    ndim = snap["info"]["ndim"]
    boxlen = snap["amr"][0].header["boxlen"]
    axes2d = [d for d in range(ndim) if d != axis][:2]
    grid = np.zeros((nx,) * min(len(axes2d), 2))
    coords = [np.clip((part[f"position_{'xyz'[d]}"] / boxlen * nx)
                      .astype(int), 0, nx - 1) for d in axes2d]
    np.add.at(grid, tuple(coords), part["mass"])
    return grid * (nx / boxlen) ** len(axes2d)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ramses_tpu.utils.maps")
    ap.add_argument("tool", choices=["amr2map", "part2map"])
    ap.add_argument("outdir")
    ap.add_argument("mapfile")
    ap.add_argument("--var", default="density")
    ap.add_argument("--dir", default="z", choices=["x", "y", "z"])
    ap.add_argument("--nx", type=int, default=256)
    ap.add_argument("--kind", default="mean",
                    choices=["mean", "max"])
    args = ap.parse_args(argv)
    axis = "xyz".index(args.dir)
    if args.tool == "amr2map":
        m = amr2map(args.outdir, var=args.var, axis=axis, nx=args.nx,
                    kind=args.kind)
    else:
        m = part2map(args.outdir, axis=axis, nx=args.nx)
    write_frame(args.mapfile, m)
    print(f"{args.tool}: {m.shape} map -> {args.mapfile} "
          f"(min {m.min():.4e} max {m.max():.4e})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
