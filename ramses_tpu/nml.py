"""Fortran-namelist parser.

Runtime configuration in the reference is a Fortran namelist file passed as
the first CLI argument (``amr/read_params.f90:51-70``).  This module parses
that format so every production/test ``.nml`` in the reference's
``namelist/`` and ``tests/`` trees drives this framework unchanged.

Supported syntax (everything the reference's 24 production namelists use):
  * ``&GROUP ... /`` blocks, case-insensitive group & key names
  * scalars: int, float (``1d-3``/``1e-3``/``.5``), ``.true.``/``.false.``,
    quoted strings ('...' or "...")
  * comma-separated value lists, Fortran repeat counts (``10*1``, ``3*1,2``)
  * indexed assignment ``key(3)=...`` (1-based, as in Fortran)
  * ``!`` comments
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Tuple, Union

Scalar = Union[int, float, bool, str]

_GROUP_RE = re.compile(r"&(\w+)")
_KEY_RE = re.compile(r"^\s*(\w+)\s*(?:\(\s*(\d+)\s*\))?\s*=\s*(.*)$", re.S)
_TRUE = (".true.", "t", ".t.")
_FALSE = (".false.", "f", ".f.")


def _strip_comment(line: str) -> str:
    """Remove a trailing ``!`` comment, respecting quoted strings."""
    out = []
    quote = None
    for ch in line:
        if quote:
            out.append(ch)
            if ch == quote:
                quote = None
        elif ch in "'\"":
            quote = ch
            out.append(ch)
        elif ch == "!":
            break
        else:
            out.append(ch)
    return "".join(out)


def _parse_scalar(tok: str) -> Scalar:
    tok = tok.strip()
    if not tok:
        return ""
    if (tok[0] == "'" and tok[-1] == "'") or (tok[0] == '"' and tok[-1] == '"'):
        return tok[1:-1]
    low = tok.lower()
    if low in _TRUE:
        return True
    if low in _FALSE:
        return False
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        # Fortran doubles use d/D as the exponent marker.
        return float(low.replace("d", "e"))
    except ValueError:
        return tok  # bare string (RAMSES allows unquoted strings rarely)


def _split_values(rhs: str) -> List[str]:
    """Split a namelist RHS on commas, respecting quotes."""
    toks, cur, quote = [], [], None
    for ch in rhs:
        if quote:
            cur.append(ch)
            if ch == quote:
                quote = None
        elif ch in "'\"":
            quote = ch
            cur.append(ch)
        elif ch == ",":
            toks.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    last = "".join(cur).strip()
    if last:
        toks.append(last)
    return [t for t in toks if t != ""]


def _parse_values(rhs: str) -> List[Scalar]:
    vals: List[Scalar] = []
    for tok in _split_values(rhs):
        m = re.match(r"^(\d+)\*(.+)$", tok)
        if m and "'" not in tok and '"' not in tok:
            vals.extend([_parse_scalar(m.group(2))] * int(m.group(1)))
        else:
            vals.append(_parse_scalar(tok))
    return vals


def parse_nml(text: str) -> Dict[str, Dict[str, Any]]:
    """Parse namelist text into ``{group: {key: scalar | list | {index: v}}}``.

    Indexed assignments are returned as ``{1-based-index: value-list}`` dicts
    so the consumer can densify with its own defaults.
    """
    groups: Dict[str, Dict[str, Any]] = {}
    current: Dict[str, Any] | None = None
    pending_key: Tuple[str, int | None] | None = None

    for raw in text.splitlines():
        line = _strip_comment(raw).strip()
        if not line:
            continue
        if current is None:
            m = _GROUP_RE.match(line)
            if m:
                name = m.group(1).lower()
                current = groups.setdefault(name, {})
                line = line[m.end():].strip()
                if not line:
                    continue
            else:
                continue  # prose outside groups (e.g. header comments)
        # inside a group
        while line:
            if line.startswith("/") or line.lower().startswith("&end"):
                current = None
                pending_key = None
                break
            m = _KEY_RE.match(line)
            if m:
                key = m.group(1).lower()
                idx = int(m.group(2)) if m.group(2) else None
                rhs = m.group(3).strip()
                # a terminating '/' may share the line
                end = False
                if rhs.endswith("/"):
                    rhs, end = rhs[:-1].rstrip(), True
                vals = _parse_values(rhs)
                _store(current, key, idx, vals)
                pending_key = (key, idx)
                if end:
                    current = None
                    pending_key = None
                break
            # continuation line: extra values for the previous key
            if pending_key is not None:
                end = False
                if line.endswith("/"):
                    line, end = line[:-1].rstrip(), True
                if line:
                    key, idx = pending_key
                    _store(current, key, idx, _parse_values(line), extend=True)
                if end:
                    current = None
                    pending_key = None
            break
    return groups


def _store(group: Dict[str, Any], key: str, idx: int | None,
           vals: List[Scalar], extend: bool = False) -> None:
    if idx is not None:
        slot = group.setdefault(key, {})
        if not isinstance(slot, dict):
            slot = {1: slot if isinstance(slot, list) else [slot]}
            group[key] = slot
        if extend and idx in slot:
            slot[idx] = slot[idx] + vals
        else:
            slot[idx] = vals
        return
    if extend and key in group:
        prev = group[key] if isinstance(group[key], list) else [group[key]]
        group[key] = prev + vals
        return
    group[key] = vals[0] if len(vals) == 1 else vals


def load_nml(path: str) -> Dict[str, Dict[str, Any]]:
    with open(path) as f:
        return parse_nml(f.read())


def densify(value: Any, n: int, default: Scalar) -> List[Scalar]:
    """Expand a parsed namelist value into a length-``n`` list.

    Handles scalars, short lists (padded with ``default``), and
    ``{1-based-index: [values]}`` dicts from indexed assignment.
    """
    out: List[Scalar] = [default] * n
    if value is None:
        return out
    if isinstance(value, dict):
        for idx, vals in value.items():
            vlist = vals if isinstance(vals, list) else [vals]
            for j, v in enumerate(vlist):
                if 0 <= idx - 1 + j < n:
                    out[idx - 1 + j] = v
        return out
    if not isinstance(value, list):
        value = [value]
    for j, v in enumerate(value[:n]):
        out[j] = v
    return out
