"""Uniform-grid SRHD stepper: MUSCL-Hancock + relativistic HLL.

Mirrors the MHD/hydro uniform pipelines: primitive TVD slopes,
conservative Hancock half-step, HLL interface fluxes with the
Mignone-Bodo wave-speed bounds, roll-stencil conservative update.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ramses_tpu.grid import boundary as bmod
from ramses_tpu.hydro import muscl as hmuscl
from ramses_tpu.rhd import core
from ramses_tpu.rhd.core import RhdStatic

NGHOST = 2


@dataclass(frozen=True)
class RhdGrid:
    cfg: RhdStatic
    shape: Tuple[int, ...]
    dx: float
    bc_kinds: Tuple[Tuple[int, int], ...]

    @property
    def ncell(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n


def _pad(a, ndim, bc_kinds, ng=NGHOST):
    for d in range(ndim):
        ax = a.ndim - ndim + d
        lo, hi = bc_kinds[d]
        n = a.shape[ax]

        def take(s0, s1):
            idx = [slice(None)] * a.ndim
            idx[ax] = slice(s0, s1)
            return a[tuple(idx)]

        def ghost(kind, side):
            if kind == bmod.PERIODIC:
                return take(n - ng, n) if side == 0 else take(0, ng)
            edge = take(0, 1) if side == 0 else take(n - 1, n)
            reps = [1] * a.ndim
            reps[ax] = ng
            return jnp.tile(edge, reps)

        a = jnp.concatenate([ghost(lo, 0), a, ghost(hi, 1)], axis=ax)
    return a


def _unpad(a, ndim, ng=NGHOST):
    idx = [slice(None)] * a.ndim
    for d in range(ndim):
        ax = a.ndim - ndim + d
        idx[ax] = slice(ng, a.shape[ax] - ng)
    return a[tuple(idx)]


def _hll(ql, qr, d: int, cfg: RhdStatic):
    lm_l, lp_l = core.wave_speeds(ql, d, cfg)
    lm_r, lp_r = core.wave_speeds(qr, d, cfg)
    SL = jnp.minimum(jnp.minimum(lm_l, lm_r), 0.0)
    SR = jnp.maximum(jnp.maximum(lp_l, lp_r), 0.0)
    fl = core.flux_along(ql, d, cfg)
    fr = core.flux_along(qr, d, cfg)
    ul = core.prim_to_cons(ql, cfg)
    ur = core.prim_to_cons(qr, cfg)
    den = SR - SL + 1e-30
    return (SR * fl - SL * fr + SL * SR * (ur - ul)) / den


def step(grid: RhdGrid, u, dt):
    """One SRHD step on the conservative state [nvar, *sp]."""
    cfg = grid.cfg
    nd = cfg.ndim
    dx = grid.dx

    up = _pad(u, nd, grid.bc_kinds)
    q = core.cons_to_prim(up, cfg)
    dq = list(hmuscl.uslope(q, cfg))

    du_half = jnp.zeros_like(up)
    face_q = []
    for d in range(nd):
        q_hi = q + 0.5 * dq[d]
        q_lo = q - 0.5 * dq[d]
        f_hi = core.flux_along(q_hi, d, cfg)
        f_lo = core.flux_along(q_lo, d, cfg)
        du_half = du_half - (0.5 * dt / dx) * (f_hi - f_lo)
        face_q.append((q_lo, q_hi))

    un = up
    for d in range(nd):
        ax = q.ndim - nd + d
        q_lo, q_hi = face_q[d]
        ul_c = core.prim_to_cons(q_hi, cfg) + du_half
        ur_c = core.prim_to_cons(q_lo, cfg) + du_half
        ql = core.cons_to_prim(jnp.roll(ul_c, 1, axis=ax), cfg)
        qr = core.cons_to_prim(ur_c, cfg)
        fg = _hll(ql, qr, d, cfg)
        un = un + (dt / dx) * (fg - jnp.roll(fg, -1, axis=ax))
    return _unpad(un, nd)


@partial(jax.jit, static_argnames=("grid",))
def cfl_dt(grid: RhdGrid, u):
    cfg = grid.cfg
    q = core.cons_to_prim(u, cfg)
    rate = 0.0
    for d in range(cfg.ndim):
        lm, lp = core.wave_speeds(q, d, cfg)
        rate = rate + jnp.maximum(jnp.abs(lm), jnp.abs(lp)) / grid.dx
    return cfg.courant_factor / jnp.max(rate)


_jit_step = jax.jit(step, static_argnames=("grid",))


@partial(jax.jit, static_argnames=("grid", "nsteps", "dt_scale"))
def run_steps(grid: RhdGrid, u, t, tend, nsteps: int,
              dt_scale: float = 1.0):
    # dt_scale < 1: redo-step retry at reduced Courant dt
    def body(carry, _):
        u, t, ndone = carry
        dt = cfl_dt(grid, u) * dt_scale
        dt = jnp.minimum(dt, jnp.maximum(tend - t, 0.0))
        active = t < tend
        un = step(grid, u, jnp.where(active, dt, 0.0))
        u = jnp.where(active, un, u)
        t = jnp.where(active, t + dt, t)
        ndone = ndone + jnp.where(active, 1, 0)
        return (u, t, ndone), None

    (u, t, ndone), _ = jax.lax.scan(body, (u, t, jnp.array(0)), None,
                                    length=nsteps)
    return u, t, ndone


@partial(jax.jit,
         static_argnames=("grid", "nsteps", "dt_scale", "summarize"))
def run_steps_batch(grid: RhdGrid, u, t, tend, nsteps: int,
                    dt_scale: float = 1.0, summarize: bool = False):
    """:func:`run_steps` vmapped over a leading ensemble axis
    (``u[B, nvar, *sp]``, ``t/tend[B]``) — cf. the hydro
    ``grid/uniform.run_steps_batch``.  Per-member completion is the
    in-scan ``t < tend`` mask; returns per-member ``ndone``, plus the
    per-member guard summary ``[B, 3]`` when ``summarize`` (columns:
    finite flag, D total, tau total)."""
    def solo(u_, t_, tend_):
        return run_steps(grid, u_, t_, tend_, nsteps, dt_scale=dt_scale)
    u, t, ndone = jax.vmap(solo)(u, t, tend)
    if summarize:
        from ramses_tpu.grid.uniform import batch_summary
        return u, t, ndone, batch_summary(u, grid.cfg.ndim, grid.dx,
                                          grid.cfg.ndim + 1)
    return u, t, ndone


def lorentz_refine_flags(u, cfg: RhdStatic, err: float = 0.1):
    """Lorentz-factor gradient refinement criterion (the rhd
    hydro_flag analogue)."""
    q = core.cons_to_prim(u, cfg)
    lor = core.lorentz(q)
    flag = jnp.zeros(lor.shape, dtype=bool)
    for d in range(cfg.ndim):
        dl = jnp.abs(jnp.roll(lor, -1, axis=d) - lor) / lor
        flag |= (dl > err) | (jnp.roll(dl, 1, axis=d) > err)
    return flag
