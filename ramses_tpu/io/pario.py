"""Elastic per-host sharded checkpoints — the ``pario`` role.

The reference bounds checkpoint write concurrency with the
``IOGROUPSIZE`` token ring (``amr/output_amr.f90:256-260,395-400``) and
evolved a dedicated I/O-server process family (``pario/io_loop.f90``).
The TPU-native equivalent: every host writes exactly the shard rows it
already holds (``jax.Array.addressable_shards`` — no cross-host gather,
no device→single-host funnel), one validated shard directory per
writer.  An optional ``io_group_size`` bounds write concurrency on
BOTH axes: within a process it is a semaphore over the ``split_hosts``
writer threads, and across processes the hosts write in
``io_group_size`` staggered waves (wave = ``process_index %
io_group_size``) with a global device barrier between waves — so at
most ``ceil(process_count / io_group_size)`` hosts stream to the
filesystem at once, the IOGROUPSIZE contract.

Format 2 (``pario_NNNNN/``) — elastic and pod-true:

  manifest.json        global manifest (resilience/checkpoint.py):
                       top-level file hashes + a ``shards`` table
                       sealing every shard manifest's SHA-256
  tree.npz             process-0 payload: per-level oct coords, run
                       scalars (t/nstep/dt), load-balance layout
                       permutations, host-replicated sink/tracer/turb
                       state
  shard_SSSSS/         one per writer (shard = process*split + group)
    manifest.json      schema-1 manifest over the shard payload, meta
                       carrying row intervals, oct/particle counts and
                       the Hilbert-order key range per array
    data.npz           this writer's row blocks — gas levels AND
                       particle lanes ({name}_r{i}/{name}_d{i}/
                       {name}_n keys, uncompressed: zlib would
                       serialize the concurrent writers on CPU time)

Two-phase commit: every writer stages its shard dirs inside
``pario_NNNNN.tmp/`` (payload → shard manifest → validate →
``os.replace``), then all hosts meet at a deadline-watchdogged barrier
(``Watchdog.guard("io")``) and process 0 seals the set — validating
every shard, writing the GLOBAL manifest, and renaming the staging dir
into place.  A host that dies or hangs mid-dump leaves only the
``.tmp`` staging dir, whose name never matches the checkpoint
scanner's all-digits suffix — it can NEVER scan as a valid checkpoint
— and the surviving hosts' guarded barrier raises ``HangDetected``,
aborts the commit, and falls through so the pod is not wedged.

Restore is mesh-shape-elastic: the reader validates each shard
(full-hash), assembles the global hierarchy from every valid shard —
or any subset whose row intervals still cover each level — and places
the rows onto the CURRENT process/device mesh, so a dump from 8
devices restores onto 4 or 1 and vice versa.  A corrupt shard is
quarantined (``shard_X.quarantined``), which invalidates the global
manifest, so ``resolve_restart_dir`` falls back to the next-oldest
globally-valid checkpoint exactly as it does for whole-checkpoint rot.
Format-1 dumps (``manifest.npz`` + ``host_*.npz``) remain readable.

On a single-host CPU mesh the "hosts" degenerate to one process; the
writer pool still exercises the per-shard decomposition, the commit
protocol, and the restore-side reassembly, which is what the
mesh-level contract needs.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import warnings
from typing import Dict, Optional

import numpy as np

#: elastic sharded layout (shard dirs + global manifest.json); format 1
#: is the legacy manifest.npz + host_*.npz layout, still restorable
PARIO_FORMAT = 2

_PART_FIELDS = ("x", "v", "m", "active", "idp", "family", "tp", "zp",
                "flags")


class CorruptShardError(RuntimeError):
    """A pario checkpoint failed restore-side validation (torn shard
    payload, torn tree payload, or a surviving-shard subset that no
    longer covers the hierarchy).  ``AmrSim.from_checkpoint_dir``
    catches this and falls back to the next-oldest globally-valid
    checkpoint."""


def _unpersisted_state(sim, nproc: int = 1) -> list:
    """Names of populated state layers a pario dump does NOT persist.

    Format 2 persists particles/sinks/tracers/turb on every process
    count (``nproc=1`` semantics), so only radiation is lost there.
    The ``nproc > 1`` branch describes legacy FORMAT-1 multi-process
    dumps, which stayed gas-only — the v1 restore path still uses it
    to warn about what an old dump never carried.
    """
    out = []
    if int(nproc) > 1:
        p = getattr(sim, "p", None)
        if p is not None and int(np.sum(np.asarray(p.active))) > 0:
            out.append("particles")
        if getattr(sim, "sinks", None) is not None:
            out.append("sinks")
        tx = getattr(sim, "tracer_x", None)
        if tx is not None and len(tx) > 0:
            out.append("tracers")
    if getattr(sim, "rt_amr", None) is not None:
        out.append("radiation")
    return out


def _host_state_payload(sim) -> Dict[str, np.ndarray]:
    """Host-replicated non-gas state riding ``tree.npz`` (process 0
    writes it): sink census, tracer positions/ids, and the
    driven-turbulence OU field + RNG key.  Particle lanes are sharded
    device state and ride the shard payloads instead."""
    out: Dict[str, np.ndarray] = {}
    s = getattr(sim, "sinks", None)
    if s is not None:
        for f in ("x", "v", "m", "tform", "idp"):
            out[f"sink_{f}"] = np.asarray(getattr(s, f))
        out["sink_next_id"] = np.asarray(int(s.next_id))
    tx = getattr(sim, "tracer_x", None)
    if tx is not None:
        out["tracer_x"] = np.asarray(tx)
        tid = getattr(sim, "tracer_id", None)
        if tid is not None:
            out["tracer_id"] = np.asarray(tid)
    tb = getattr(sim, "turb", None)
    if tb is not None:
        out["turb_fhat"] = np.asarray(tb.fhat)
        out["turb_key"] = np.asarray(tb.key)
    return out


def _restore_host_state(sim, man) -> None:
    """Re-attach the :func:`_host_state_payload` layers from a loaded
    npz mapping onto a freshly-built sim."""
    import jax.numpy as jnp

    if "sink_x" in man.files:
        from ramses_tpu.pm.sinks import SinkSet
        sim.sinks = SinkSet(
            x=np.asarray(man["sink_x"]), v=np.asarray(man["sink_v"]),
            m=np.asarray(man["sink_m"]),
            tform=np.asarray(man["sink_tform"]),
            idp=np.asarray(man["sink_idp"]),
            next_id=int(man["sink_next_id"]))
    if "tracer_x" in man.files:
        sim.tracer_x = np.asarray(man["tracer_x"])
        if "tracer_id" in man.files:
            sim.tracer_id = np.asarray(man["tracer_id"])
    if "turb_fhat" in man.files and getattr(sim, "turb", None) \
            is not None:
        # OU spectral field + RNG key: the restored forcing continues
        # the dumped realization instead of re-seeding
        sim.turb.fhat = jnp.asarray(man["turb_fhat"])
        sim.turb.key = jnp.asarray(man["turb_key"])


def _attach_particles(sim, lanes: Dict[str, np.ndarray],
                      params) -> None:
    """Rebuild the ParticleSet from reassembled full padded lanes (so
    a restore keeps the exact lane layout and headroom —
    bitwise-identical PM restarts)."""
    import jax.numpy as jnp

    if "x" not in lanes:
        return
    from ramses_tpu.pm.particles import ParticleSet
    sim.p = ParticleSet(**{f: jnp.asarray(lanes[f])
                           for f in _PART_FIELDS})
    run = getattr(params, "run", None)
    if bool(getattr(run, "pic", False)):
        sim.pic = True


def _level_arrays(sim) -> Dict[str, object]:
    """Name → sharded device array for everything that must ride the
    checkpoint (solver family decides: hydro u; MHD adds faces).

    Under &AMR_PARAMS offload, a parked level rides as an
    ``offload.HostBuffer``: ``_shard_blocks`` stages it through
    ``np.asarray`` (zero-copy ``__array__``), so dumping a parked
    hierarchy reads host staging directly — no device round-trip."""
    arrs = {f"u{l}": sim.u[l] for l in sim.levels()}
    bf = getattr(sim, "bf", None)
    if isinstance(bf, dict):
        arrs.update({f"bf{l}": bf[l] for l in sim.levels() if l in bf})
    return arrs


def _particle_arrays(sim) -> Dict[str, object]:
    """Name → particle lane array (full padded lanes; sharded or
    replicated placement decides the shard row intervals)."""
    p = getattr(sim, "p", None)
    if p is None:
        return {}
    return {f"part_{f}": getattr(p, f) for f in _PART_FIELDS}


def _host_wave(me: int, group: int) -> int:
    """The wave in which process ``me`` writes its shards: waves are
    keyed on ``process_index % io_group_size``, so wave ``w`` holds
    every ``ceil(nproc/group)``-th process — bounded filesystem fan-in
    per wave, ``group`` waves total."""
    return int(me) % max(1, int(group))


def _barrier(tag: str) -> None:
    """Cross-host barrier (no-op single-process)."""
    import jax
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(tag)


def _shard_blocks(arrs: Dict[str, object], ngrp: int):
    """Partition this process's addressable shards of every array into
    ``ngrp`` writer groups.  Returns per-group ``{key: array}`` payload
    dicts (the ``{name}_r{i}/_d{i}/_n`` block scheme) and per-group
    row-interval metadata ``{name: [[r0, nrows], ...]}``.  Replicated
    arrays (every device holds the full rows) are deduplicated to one
    block — all writers would stage identical bytes."""
    blocks = [dict() for _ in range(ngrp)]
    counts = [dict() for _ in range(ngrp)]
    rows = [dict() for _ in range(ngrp)]
    for name, a in arrs.items():
        if hasattr(a, "addressable_shards"):
            shards = list(a.addressable_shards)
            seen = set()
            parts = []
            for s in shards:
                r0 = int(s.index[0].start or 0) if s.index else 0
                if r0 in seen:
                    continue            # replicated placement
                seen.add(r0)
                parts.append((r0, s.data))
        else:
            parts = [(0, a)]
        for k, (r0, data) in enumerate(parts):
            g = k * ngrp // max(len(parts), 1)
            i = counts[g].get(name, 0)
            counts[g][name] = i + 1
            d = np.asarray(data)
            blocks[g][f"{name}_r{i}"] = np.asarray([r0], dtype=np.int64)
            blocks[g][f"{name}_d{i}"] = d
            rows[g].setdefault(name, []).append([int(r0), int(len(d))])
    for g in range(ngrp):
        for name, n in counts[g].items():
            blocks[g][f"{name}_n"] = np.asarray([n], dtype=np.int64)
    return blocks, rows


def _shard_meta(sim, sidx: int, me: int, rows: Dict[str, list],
                iout: int) -> Dict[str, object]:
    """Per-shard manifest meta: row intervals, oct/particle counts and
    the Hilbert-order key range per array — everything the elastic
    reader and the offline scrubber need without opening the payload."""
    ttd = 2 ** int(sim.cfg.ndim)
    octs = {}
    npart = 0
    key_range = {}
    for name, ivs in rows.items():
        lo = min(r0 for r0, _n in ivs)
        hi = max(r0 + n for r0, n in ivs)
        key_range[name] = [int(lo), int(hi)]
        tot = sum(n for _r0, n in ivs)
        if name.startswith("u"):
            octs[name[1:]] = int(tot // ttd)
        elif name == "part_x":
            npart = int(tot)
    return {"kind": "pario_shard", "format": PARIO_FORMAT,
            "shard": int(sidx), "process": int(me), "iout": int(iout),
            "nstep": int(sim.nstep), "rows": rows, "octs": octs,
            "npart": npart, "key_range": key_range}


def _commit_pario(stage: str, final: str, meta: Dict[str, object],
                  nshard: int, telemetry=None, log=print
                  ) -> Optional[str]:
    """Phase 2, process 0 only: validate the full shard set, seal the
    global manifest, atomically rename the staging dir into place.
    Returns the final path, or None when the commit must be aborted
    (missing/torn shard) — an aborted commit leaves only the ``.tmp``
    staging dir, which no scanner ever selects."""
    from ramses_tpu.resilience import checkpoint as ckpt

    def abort(reason: str) -> None:
        if log is not None:
            log(f"pario: commit of {os.path.basename(final)} aborted: "
                f"{reason}")
        if telemetry is not None:
            telemetry.record_event("io_degraded", reason="commit_abort",
                                   detail=reason, path=stage)

    expected = {f"shard_{i:05d}" for i in range(int(nshard))}
    present = {n for n in os.listdir(stage)
               if n.startswith("shard_")
               and os.path.isdir(os.path.join(stage, n))}
    # shard dirs beyond the expected set are leftovers of a dead dump
    # attempt on a larger mesh — an elastic resume writes fewer shards
    for extra in sorted(present - expected):
        shutil.rmtree(os.path.join(stage, extra), ignore_errors=True)
    missing = sorted(expected - present)
    if missing:
        abort(f"missing {missing[0]} ({len(missing)} of {nshard})")
        return None
    rows_total: Dict[str, int] = {}
    npart = 0
    for name in sorted(expected):
        sdir = os.path.join(stage, name)
        # size-only validation: each writer already full-hash-validated
        # its own staged bytes in phase 1; re-hashing every shard here
        # would serialize the whole dump through process 0's CPU
        ok, reason = ckpt.validate_checkpoint(sdir, verify_hash=False)
        if not ok:
            abort(f"{name}: {reason}")
            return None
        smeta = ckpt.read_manifest_meta(sdir)
        for nm, ivs in (smeta.get("rows") or {}).items():
            for r0, n in ivs:
                rows_total[nm] = max(rows_total.get(nm, 0),
                                     int(r0) + int(n))
        npart = max(npart, int(smeta.get("npart", 0) or 0))
    meta = dict(meta, nshard=int(nshard), rows_total=rows_total)
    ckpt.write_global_manifest(stage, meta=meta)
    if os.path.isdir(final):
        shutil.rmtree(final)
    os.replace(stage, final)
    try:
        fd = os.open(os.path.dirname(os.path.abspath(final)),
                     os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass                          # e.g. non-fsyncable mount
    return final


def dump_pario(sim, iout: int, base_dir: str = ".",
               io_group_size: Optional[int] = None,
               split_hosts: Optional[int] = None) -> str:
    """Write an elastic sharded checkpoint of ``sim`` (AmrSim or
    ShardedAmrSim) under the two-phase commit protocol.  Returns the
    committed ``pario_NNNNN`` path, or the ``.tmp`` staging path when
    the commit was aborted (hung barrier, missing shard, injected
    death on another host) — the staging path never scans as a
    checkpoint, so an aborted dump degrades to "no new checkpoint",
    never to a torn one.

    ``io_group_size`` bounds write concurrency (None = all at once) on
    both axes: a per-process semaphore over the ``split_hosts`` writer
    threads, and — on a multi-process run — cross-host staggering into
    ``io_group_size`` waves with a global barrier between waves.
    Every process walks the same wave schedule, which makes the
    barrier a collective.

    ``split_hosts``: partition this process's shards into that many
    shard dirs written CONCURRENTLY — on a real pod every process is
    one writer already; on a single-host test mesh this exercises the
    same per-shard decomposition and commit protocol."""
    import jax

    from ramses_tpu.resilience import checkpoint as ckpt
    from ramses_tpu.resilience.watchdog import HangDetected

    final = os.path.join(base_dir, f"pario_{iout:05d}")
    stage = final + ".tmp"
    nproc = jax.process_count()
    me = jax.process_index()
    nstep = int(sim.nstep)
    tel = getattr(sim, "telemetry", None)
    inj = getattr(sim, "_fault", None)
    wd = getattr(sim, "_wd", None)

    # stale staging left by a dump that died mid-commit.  Dumps are
    # collective and serialized in the run loop, so ANY pario_*.tmp for
    # a different iout is a dead attempt — clean it, it is observable
    # I/O degradation.  For OUR OWN stage the marker disambiguates: it
    # records which nstep staged it — a DIFFERENT nstep means a dead
    # attempt, the SAME nstep means concurrent writers of this very
    # dump (keep it; a deterministic resume that replays the exact
    # dump also lands here, and the writers below replace their own
    # shard dirs in place).
    marker = os.path.join(stage, f".staged_nstep_{nstep}")
    if me == 0:
        stale = [os.path.join(base_dir, n)
                 for n in sorted(os.listdir(base_dir or "."))
                 if n.startswith("pario_") and n.endswith(".tmp")
                 and os.path.join(base_dir, n) != stage]
        if os.path.isdir(stage) and not os.path.exists(marker):
            stale.append(stage)
        for s in stale:
            if tel is not None:
                tel.record_event("io_degraded", reason="stale_stage",
                                 path=s, iout=int(iout))
            shutil.rmtree(s, ignore_errors=True)
    _barrier(f"pario_{iout:05d}_stage")
    os.makedirs(stage, exist_ok=True)
    with open(marker, "w"):
        pass

    # structured telemetry for any layer the fat checkpoint still
    # cannot persist (radiation) — the gas-only multi-process era is
    # over, so this is an event, not a warning
    lost = _unpersisted_state(sim, nproc=1)
    if lost and tel is not None:
        tel.record_event("io_degraded", reason="unpersisted",
                         layers=lost, iout=int(iout), path=final)

    # phase 0: process 0 stages the tree payload + run scalars +
    # host-replicated extras (these now persist on EVERY process count)
    if me == 0:
        tree_payload = {}
        for l in sim.levels():
            tree_payload[f"og{l}"] = sim.tree.levels[l].og
        # load-balance layouts: rows in the shard payloads are in the
        # dump sim's (possibly Hilbert-rebalanced) row order — persist
        # the oct_row permutation so restore can return them to tree
        # order before re-decomposing onto the current mesh
        for l, lay in getattr(sim, "layouts", {}).items():
            tree_payload[f"octrow{l}"] = np.asarray(lay.oct_row,
                                                    np.int64)
        dtc = getattr(sim, "_dt_cache", None)
        np.savez(os.path.join(stage, "tree.npz"),
                 levels=np.asarray(sim.levels()),
                 ndim=sim.cfg.ndim, root=np.asarray(sim.tree.root),
                 levelmin=sim.lmin, levelmax=sim.lmax,
                 t=float(sim.t), nstep=nstep,
                 dt_old=float(getattr(sim, "dt_old", 0.0)),
                 dtnew=float(dtc) if dtc is not None else 0.0,
                 nproc=nproc, **tree_payload,
                 **_host_state_payload(sim))

    # phase 1: partition this process's shards into writer groups and
    # stage each as a validated shard dir
    ngrp = max(1, int(split_hosts or 1))
    arrs = dict(_level_arrays(sim))
    arrs.update(_particle_arrays(sim))
    blocks, rows = _shard_blocks(arrs, ngrp)

    sem = threading.Semaphore(io_group_size or max(ngrp, 1))
    errs = []

    def write_shard(g):
        with sem:
            try:
                sidx = me * ngrp + g
                sdir = os.path.join(stage, f"shard_{sidx:05d}")
                part = sdir + ".partial"
                if os.path.isdir(part):
                    shutil.rmtree(part)
                os.makedirs(part)
                np.savez(os.path.join(part, "data.npz"), **blocks[g])
                ckpt.write_manifest(
                    part, meta=_shard_meta(sim, sidx, me, rows[g],
                                           iout))
                ok, reason = ckpt.validate_checkpoint(
                    part, verify_hash=False)
                if not ok:
                    raise RuntimeError(
                        f"pario: staged shard {sidx} failed "
                        f"validation: {reason}")
                if inj is not None:
                    # torn@K:shard=J corrupts the payload AFTER the
                    # manifest is staged — exactly the window where
                    # only full-hash validation can convict the shard
                    inj.maybe_torn(part, sidx, nstep)
                if os.path.isdir(sdir):
                    # dead same-nstep attempt staged this shard (a
                    # deterministic resume replays the exact dump) —
                    # rename over a non-empty dir would ENOTEMPTY
                    shutil.rmtree(sdir)
                os.replace(part, sdir)
            except Exception as e:      # surface on the main thread
                errs.append(e)

    def write_all():
        threads = [threading.Thread(target=write_shard, args=(g,))
                   for g in range(ngrp)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        if errs:
            raise errs[0]

    group = int(io_group_size or 0)
    if group > 0 and nproc > 1:
        # cross-host wave staggering: wave w writes while the others
        # wait at the barrier; min(group, nproc) waves covers every
        # residue class that actually occurs
        mine = _host_wave(me, group)
        for w in range(min(group, nproc)):
            if mine == w:
                write_all()
            _barrier(f"pario_{iout:05d}_wave_{w}")
    else:
        write_all()

    if inj is not None:
        # die@K:host=J: this process exits hard after staging its
        # shards but before the commit barrier — the mid-commit host
        # death the two-phase protocol must survive
        inj.maybe_die(nstep, host=me)

    # phase 2: deadline-watchdogged commit barrier + global seal.  A
    # host that died above never reaches the barrier; the survivors'
    # io deadline expires, HangDetected lands here, and the dump
    # kills-and-falls-through with the commit aborted.
    committed = None
    meta = {"kind": "pario", "format": PARIO_FORMAT, "iout": int(iout),
            "nstep": nstep, "t": float(sim.t), "nproc": int(nproc),
            "ndev": int(getattr(sim, "ndev", 1))}
    try:
        if wd is not None:
            with wd.guard("io"):
                _barrier(f"pario_{iout:05d}_commit")
                if me == 0:
                    committed = _commit_pario(stage, final, meta,
                                              nproc * ngrp,
                                              telemetry=tel)
                _barrier(f"pario_{iout:05d}_committed")
        else:
            _barrier(f"pario_{iout:05d}_commit")
            if me == 0:
                committed = _commit_pario(stage, final, meta,
                                          nproc * ngrp, telemetry=tel)
            _barrier(f"pario_{iout:05d}_committed")
    except HangDetected as e:
        if tel is not None:
            tel.record_event("io_degraded", reason="commit_abort",
                             detail=str(e), path=stage)
        print(f" pario: commit barrier hung ({e}); abandoning "
              f"checkpoint {iout}, run continues", flush=True)
        return stage
    if me != 0:
        committed = final if os.path.isdir(final) else None
    return committed if committed is not None else stage


def restore_pario(cls, params, outdir: str, dtype=None, devices=None,
                  log=print, **kw):
    """Rebuild a sim of class ``cls`` from a ``pario_NNNNN`` directory
    onto the CURRENT process/device mesh (write on 8, restore on 4 or
    1, and vice versa).  Format-2 restores validate every shard with
    full hashes first: a corrupt shard is quarantined and — unless the
    surviving shards still cover every level's rows —
    :class:`CorruptShardError` is raised so the caller falls back to
    the next-oldest globally-valid checkpoint."""
    if not os.path.isfile(os.path.join(outdir, "manifest.json")) \
            and os.path.isfile(os.path.join(outdir, "manifest.npz")):
        return _restore_pario_v1(cls, params, outdir, dtype=dtype,
                                 devices=devices, **kw)

    import jax
    import jax.numpy as jnp

    from ramses_tpu.amr.tree import Octree
    from ramses_tpu.parallel import balance
    from ramses_tpu.resilience import checkpoint as ckpt

    with open(os.path.join(outdir, "manifest.json")) as f:
        gman = json.load(f)
    meta = dict(gman.get("meta") or {})
    shards = dict(gman.get("shards") or {})

    run = getattr(params, "run", None)
    if not bool(getattr(run, "elastic_restore", True)):
        cur = int(jax.process_count())
        dumped = int(meta.get("nproc", 1))
        if cur != dumped:
            raise RuntimeError(
                f"pario: checkpoint written on {dumped} processes, "
                f"current run has {cur} and elastic_restore=.false.")

    # per-shard full-hash validation with quarantine-and-fall-back
    ok_shards: Dict[str, dict] = {}
    bad = []
    for name, ent in sorted(shards.items()):
        ok, reason = ckpt.validate_shard(outdir, name, ent,
                                         verify_hash=True)
        if ok:
            ok_shards[name] = ent
        else:
            bad.append((name, reason))
    if bad:
        rows_total = {nm: int(v)
                      for nm, v in (meta.get("rows_total") or
                                    {}).items()}
        covered = bool(rows_total)
        for nm, tot in rows_total.items():
            ivs = [iv for ent in ok_shards.values()
                   for iv in (ent.get("rows") or {}).get(nm, [])]
            if not balance.ranges_cover(ivs, tot)[0]:
                covered = False
                break
        for name, reason in bad:
            ckpt.quarantine_shard(outdir, name, reason, log=log)
        if not covered:
            raise CorruptShardError(
                f"{os.path.basename(outdir)}: "
                f"{'; '.join(f'{n}: {r}' for n, r in bad)} and the "
                "surviving shards do not cover the hierarchy")
        if log is not None:
            log(f"pario: restoring {os.path.basename(outdir)} from "
                f"{len(ok_shards)}/{len(shards)} shards (full row "
                f"coverage; quarantined: "
                f"{', '.join(n for n, _ in bad)})")

    try:
        man = np.load(os.path.join(outdir, "tree.npz"))
    except Exception as e:              # torn top-level payload
        raise CorruptShardError(
            f"{os.path.basename(outdir)}: tree payload unreadable "
            f"({e})") from e
    levels = [int(l) for l in man["levels"]]
    tree = Octree(int(man["ndim"]), int(man["levelmin"]),
                  int(man["levelmax"]),
                  root=(man["root"] if "root" in man.files else None))
    for l in levels:
        tree.set_level(l, man[f"og{l}"])
    if devices is not None:
        kw["devices"] = devices
    sim = cls(params, dtype=dtype or jnp.float32, init_tree=tree, **kw)

    # gather row blocks from every valid shard payload
    per_name: Dict[str, list] = {}
    for name in sorted(ok_shards):
        z = np.load(os.path.join(outdir, name, "data.npz"))
        names = {k[:-2] for k in z.files if k.endswith("_n")}
        for nm in names:
            nsh = int(z[f"{nm}_n"][0])
            for k in range(nsh):
                per_name.setdefault(nm, []).append(
                    (int(z[f"{nm}_r{k}"][0]), z[f"{nm}_d{k}"]))

    ttd = 2 ** int(man["ndim"])
    for l in levels:
        orow = (np.asarray(man[f"octrow{l}"], np.int64)
                if f"octrow{l}" in man.files else None)
        for prefix, target in (("u", "u"), ("bf", "bf")):
            name = f"{prefix}{l}"
            if name not in per_name:
                continue
            tgt = getattr(sim, target, None)
            if tgt is None or l not in tgt:
                continue
            cur = np.asarray(tgt[l])
            # reassemble at the DUMP's row extent first: a rebalanced
            # dump scatters real rows across its whole bucket, and the
            # dump's bucket may exceed this mesh's (hysteresis state
            # isn't persisted) — clipping to cur.shape up front would
            # drop real cells
            ext = max((r0 + len(data) for r0, data in per_name[name]),
                      default=0)
            if orow is not None:
                ext = max(ext, (int(orow.max()) + 1) * ttd)
            dbuf = np.zeros((ext,) + cur.shape[1:], cur.dtype)
            for r0, data in per_name[name]:
                dbuf[r0:r0 + len(data)] = data
            if orow is not None:
                # dump rows are in the dump sim's rebalanced layout:
                # oct i lives at cell rows [orow[i]*ttd, ...) — gather
                # back to tree order (the fresh sim starts identity)
                idx = (orow[:, None] * ttd
                       + np.arange(ttd)[None, :]).reshape(-1)
                dbuf = dbuf[idx]
            buf = np.zeros(cur.shape, cur.dtype)
            n = min(len(dbuf), len(buf))
            buf[:n] = dbuf[:n]
            tgt[l] = sim._place(jnp.asarray(buf, buf.dtype), "cells")

    # particle lanes: reassemble the full padded lane arrays from the
    # shard row intervals, whatever mesh wrote them
    lanes: Dict[str, np.ndarray] = {}
    for f in _PART_FIELDS:
        nm = f"part_{f}"
        if nm not in per_name:
            continue
        ext = max(r0 + len(d) for r0, d in per_name[nm])
        d0 = per_name[nm][0][1]
        dbuf = np.zeros((ext,) + d0.shape[1:], d0.dtype)
        for r0, d in per_name[nm]:
            dbuf[r0:r0 + len(d)] = d
        lanes[f] = dbuf
    _attach_particles(sim, lanes, params)
    _restore_host_state(sim, man)

    lost = _unpersisted_state(sim, nproc=1)
    if lost:
        warnings.warn(
            f"restore_pario: restored run carries {'/'.join(lost)} "
            "state that was NOT in the checkpoint — those layers are "
            "fresh from ICs, not the dumped run.", stacklevel=2)
    sim.t = float(man["t"])
    sim.nstep = int(man["nstep"])
    sim.dt_old = float(man["dt_old"])
    dtn = float(man["dtnew"]) if "dtnew" in man.files else 0.0
    # pending next-step dt: restore takes the same next step a
    # continuous run would (dt hysteresis rides the manifest)
    sim._dt_cache = dtn if dtn > 0.0 else None
    # mesh-shape elasticity, part 2: the rows were re-PLACED onto the
    # current mesh above; when cost-weighted balancing is enabled, ask
    # the next regrid to re-cut the Hilbert layouts against the
    # current device count too (the dump's cuts were for its mesh)
    if balance.enabled(sim):
        sim.request_rebalance()
    return sim


# ---- legacy format 1 (manifest.npz + host_*.npz) ---------------------


def _restore_extra_state(sim, man, params) -> None:
    """Format-1 extras: particles rode the process-0 manifest (single
    process only); sinks/tracers/turb likewise."""
    import jax.numpy as jnp

    if "part_x" in man.files:
        from ramses_tpu.pm.particles import ParticleSet
        sim.p = ParticleSet(
            **{f: jnp.asarray(man[f"part_{f}"])
               for f in _PART_FIELDS})
        run = getattr(params, "run", None)
        if bool(getattr(run, "pic", False)):
            sim.pic = True
    _restore_host_state(sim, man)


def _restore_pario_v1(cls, params, outdir: str, dtype=None,
                      devices=None, **kw):
    """Reader for legacy format-1 dumps: ``manifest.npz`` carries the
    tree + extras, ``host_*.npz`` the row blocks.  Kept so checkpoints
    written before the elastic format remain restorable."""
    import glob as globmod

    import jax.numpy as jnp

    from ramses_tpu.amr.tree import Octree

    man = np.load(os.path.join(outdir, "manifest.npz"))
    levels = [int(l) for l in man["levels"]]
    tree = Octree(int(man["ndim"]), int(man["levelmin"]),
                  int(man["levelmax"]),
                  root=(man["root"] if "root" in man.files else None))
    for l in levels:
        tree.set_level(l, man[f"og{l}"])
    if devices is not None:
        kw["devices"] = devices
    sim = cls(params, dtype=dtype or jnp.float32, init_tree=tree, **kw)

    per_name: Dict[str, list] = {}
    for f in sorted(globmod.glob(os.path.join(outdir, "host_*.npz"))):
        z = np.load(f)
        names = {k[:-2] for k in z.files if k.endswith("_n")}
        for name in names:
            nsh = int(z[f"{name}_n"][0])
            for k in range(nsh):
                per_name.setdefault(name, []).append(
                    (int(z[f"{name}_r{k}"][0]), z[f"{name}_d{k}"]))
    ttd = 2 ** int(man["ndim"])
    for l in levels:
        orow = (np.asarray(man[f"octrow{l}"], np.int64)
                if f"octrow{l}" in man.files else None)
        for prefix, target in (("u", "u"), ("bf", "bf")):
            name = f"{prefix}{l}"
            if name not in per_name:
                continue
            tgt = getattr(sim, target, None)
            if tgt is None or l not in tgt:
                continue
            cur = np.asarray(tgt[l])
            ext = max((r0 + len(data) for r0, data in per_name[name]),
                      default=0)
            if orow is not None:
                ext = max(ext, (int(orow.max()) + 1) * ttd)
            dbuf = np.zeros((ext,) + cur.shape[1:], cur.dtype)
            for r0, data in per_name[name]:
                dbuf[r0:r0 + len(data)] = data
            if orow is not None:
                idx = (orow[:, None] * ttd
                       + np.arange(ttd)[None, :]).reshape(-1)
                dbuf = dbuf[idx]
            buf = np.zeros(cur.shape, cur.dtype)
            n = min(len(dbuf), len(buf))
            buf[:n] = dbuf[:n]
            tgt[l] = sim._place(jnp.asarray(buf, buf.dtype), "cells")
    _restore_extra_state(sim, man, params)
    dump_nproc = int(man["nproc"]) if "nproc" in man.files else 1
    lost = _unpersisted_state(sim, nproc=dump_nproc)
    if lost:
        warnings.warn(
            f"restore_pario: restored run carries {'/'.join(lost)} "
            "state that was NOT in the checkpoint — those layers are "
            "fresh from ICs, not the dumped run.", stacklevel=2)
    sim.t = float(man["t"])
    sim.nstep = int(man["nstep"])
    sim.dt_old = float(man["dt_old"])
    dtn = float(man["dtnew"]) if "dtnew" in man.files else 0.0
    sim._dt_cache = dtn if dtn > 0.0 else None
    return sim
