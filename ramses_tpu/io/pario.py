"""Per-host concurrent sharded checkpoints — the ``pario`` role.

The reference bounds checkpoint write concurrency with the
``IOGROUPSIZE`` token ring (``amr/output_amr.f90:256-260,395-400``) and
evolved a dedicated I/O-server process family (``pario/io_loop.f90``).
The TPU-native equivalent: every host writes exactly the shard rows it
already holds (``jax.Array.addressable_shards`` — no cross-host gather,
no device→single-host funnel), one file set per host.  An optional
``io_group_size`` bounds write concurrency on BOTH axes: within a
process it is a semaphore over the ``split_hosts`` writer threads, and
across processes the hosts write in ``io_group_size`` staggered waves
(wave = ``process_index % io_group_size``) with a global device barrier
between waves — so at most ``ceil(process_count / io_group_size)``
hosts stream to the filesystem at once, the IOGROUPSIZE contract.
Restore reads whichever file sets exist and
re-places rows onto the CURRENT mesh, so a dump from N hosts restores
onto any device count — the same any-count contract as the
reference-format snapshot path (``io/snapshot.py``), which remains the
interoperable format; this one is the fast fat-checkpoint path.

Layout of ``pario_NNNNN/``:
  manifest.npz       — tree (per-level oct coords), t/nstep/meta,
                       per-level row counts, the writer list
  host_HHHHH.npz     — this host's row blocks: for each level, the
                       global [row0, row1) interval per shard and the
                       raw rows (uncompressed: zlib would serialize
                       the concurrent writers on CPU time)

On a single-host CPU mesh the "hosts" degenerate to one process; the
writer pool still exercises the per-shard decomposition and the
restore-side reassembly, which is what the mesh-level contract needs.
"""

from __future__ import annotations

import os
import threading
import warnings
from typing import Dict, Optional

import numpy as np


def _unpersisted_state(sim, nproc: int = 1) -> list:
    """Names of populated state layers pario does NOT checkpoint.

    Single-process dumps ride particles/sinks/tracers/turb state on the
    manifest (see :func:`_extra_state_payload`), so only radiation is
    lost there.  Multi-process dumps stay gas-only for those layers —
    the particle arrays are sharded device state and the manifest is a
    process-0 artifact — so a dump of a run carrying any of these loses
    that state on restore; the reference-format snapshot path
    (io/snapshot.py) persists them.
    """
    out = []
    if int(nproc) > 1:
        p = getattr(sim, "p", None)
        if p is not None and int(np.sum(np.asarray(p.active))) > 0:
            out.append("particles")
        if getattr(sim, "sinks", None) is not None:
            out.append("sinks")
        tx = getattr(sim, "tracer_x", None)
        if tx is not None and len(tx) > 0:
            out.append("tracers")
    if getattr(sim, "rt_amr", None) is not None:
        out.append("radiation")
    return out


def _extra_state_payload(sim) -> Dict[str, np.ndarray]:
    """Non-gas state riding the single-process manifest: full padded
    particle lanes (so a restore keeps the exact lane layout and
    headroom — bitwise-identical PM restarts), host sink/tracer
    arrays, and the driven-turbulence OU field + RNG key."""
    out: Dict[str, np.ndarray] = {}
    p = getattr(sim, "p", None)
    if p is not None:
        for f in ("x", "v", "m", "active", "idp", "family",
                  "tp", "zp", "flags"):
            out[f"part_{f}"] = np.asarray(getattr(p, f))
    s = getattr(sim, "sinks", None)
    if s is not None:
        for f in ("x", "v", "m", "tform", "idp"):
            out[f"sink_{f}"] = np.asarray(getattr(s, f))
        out["sink_next_id"] = np.asarray(int(s.next_id))
    tx = getattr(sim, "tracer_x", None)
    if tx is not None:
        out["tracer_x"] = np.asarray(tx)
        tid = getattr(sim, "tracer_id", None)
        if tid is not None:
            out["tracer_id"] = np.asarray(tid)
    tb = getattr(sim, "turb", None)
    if tb is not None:
        out["turb_fhat"] = np.asarray(tb.fhat)
        out["turb_key"] = np.asarray(tb.key)
    return out


def _restore_extra_state(sim, man, params) -> None:
    """Re-attach the :func:`_extra_state_payload` layers from a loaded
    manifest onto a freshly-built sim."""
    import jax.numpy as jnp

    if "part_x" in man.files:
        from ramses_tpu.pm.particles import ParticleSet
        sim.p = ParticleSet(
            **{f: jnp.asarray(man[f"part_{f}"])
               for f in ("x", "v", "m", "active", "idp", "family",
                         "tp", "zp", "flags")})
        run = getattr(params, "run", None)
        if bool(getattr(run, "pic", False)):
            sim.pic = True
    if "sink_x" in man.files:
        from ramses_tpu.pm.sinks import SinkSet
        sim.sinks = SinkSet(
            x=np.asarray(man["sink_x"]), v=np.asarray(man["sink_v"]),
            m=np.asarray(man["sink_m"]),
            tform=np.asarray(man["sink_tform"]),
            idp=np.asarray(man["sink_idp"]),
            next_id=int(man["sink_next_id"]))
    if "tracer_x" in man.files:
        sim.tracer_x = np.asarray(man["tracer_x"])
        if "tracer_id" in man.files:
            sim.tracer_id = np.asarray(man["tracer_id"])
    if "turb_fhat" in man.files and getattr(sim, "turb", None) \
            is not None:
        # OU spectral field + RNG key: the restored forcing continues
        # the dumped realization instead of re-seeding
        sim.turb.fhat = jnp.asarray(man["turb_fhat"])
        sim.turb.key = jnp.asarray(man["turb_key"])


def _level_arrays(sim) -> Dict[str, object]:
    """Name → sharded device array for everything that must ride the
    checkpoint (solver family decides: hydro u; MHD adds faces)."""
    arrs = {f"u{l}": sim.u[l] for l in sim.levels()}
    bf = getattr(sim, "bf", None)
    if isinstance(bf, dict):
        arrs.update({f"bf{l}": bf[l] for l in sim.levels() if l in bf})
    return arrs


def _host_wave(me: int, group: int) -> int:
    """The wave in which process ``me`` writes its host files: waves
    are keyed on ``process_index % io_group_size``, so wave ``w`` holds
    every ``ceil(nproc/group)``-th process — bounded filesystem fan-in
    per wave, ``group`` waves total."""
    return int(me) % max(1, int(group))


def _barrier(tag: str) -> None:
    """Cross-host barrier between write waves (no-op single-process)."""
    import jax
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(tag)


def dump_pario(sim, iout: int, base_dir: str = ".",
               io_group_size: Optional[int] = None,
               split_hosts: Optional[int] = None) -> str:
    """Write a per-host sharded checkpoint of ``sim`` (AmrSim or
    ShardedAmrSim).  Each process writes only its addressable shards
    — one writer thread per host file.

    ``io_group_size`` bounds write concurrency (None = all at once) on
    both axes: a per-process semaphore over the ``split_hosts`` writer
    threads, and — on a multi-process run — cross-host staggering into
    ``io_group_size`` waves (wave = ``process_index % io_group_size``)
    with a global barrier between waves, so at most
    ``ceil(process_count/io_group_size)`` hosts hit the filesystem
    simultaneously.  Every process walks the same wave schedule, which
    makes the barrier a collective.

    ``split_hosts``: partition this process's shards into that many
    host files written CONCURRENTLY — on a real pod every process is
    one writer already; on a single-host test mesh this exercises the
    same per-host decomposition and writer concurrency.

    Single-process runs get the atomic-checkpoint treatment (stage to
    ``pario_NNNNN.tmp/`` + ``manifest.json`` + rename); multi-process
    runs write in place because the rename would race the other hosts'
    writers — there the npz manifest from process 0 remains the only
    completeness signal."""
    import jax

    from ramses_tpu.resilience import checkpoint as ckpt

    final = os.path.join(base_dir, f"pario_{iout:05d}")
    nproc = jax.process_count()
    atomic = nproc == 1
    if atomic:
        out = final + ".tmp"
        if os.path.isdir(out):
            import shutil
            shutil.rmtree(out)
        os.makedirs(out)
    else:
        out = final
        os.makedirs(out, exist_ok=True)
    arrs = _level_arrays(sim)
    me = jax.process_index()

    lost = _unpersisted_state(sim, nproc=nproc)
    if lost:
        warnings.warn(
            f"dump_pario: run carries {'/'.join(lost)} state that the "
            "pario fat-checkpoint does NOT persist here; a restore "
            "re-creates it from ICs.  Use sim.dump() (reference-format "
            "snapshots) for full-state checkpoints.",
            stacklevel=2)

    # manifest: host tree + run meta (process 0 writes it)
    if me == 0:
        tree_payload = {}
        for l in sim.levels():
            tree_payload[f"og{l}"] = sim.tree.levels[l].og
        # load-balance layouts: rows in the host files are in the dump
        # sim's (possibly Hilbert-rebalanced) row order — persist the
        # oct_row permutation so restore can return them to tree order
        for l, lay in getattr(sim, "layouts", {}).items():
            tree_payload[f"octrow{l}"] = np.asarray(lay.oct_row,
                                                    np.int64)
        dtc = getattr(sim, "_dt_cache", None)
        # single-process: non-gas layers (particles/sinks/tracers/turb)
        # ride the manifest — multi-process particle state is sharded
        # across hosts and stays on the snapshot path (see
        # _unpersisted_state)
        extra = _extra_state_payload(sim) if nproc == 1 else {}
        np.savez(os.path.join(out, "manifest.npz"),
                 levels=np.asarray(sim.levels()),
                 ndim=sim.cfg.ndim, root=np.asarray(sim.tree.root),
                 levelmin=sim.lmin, levelmax=sim.lmax,
                 t=float(sim.t), nstep=int(sim.nstep),
                 dt_old=float(getattr(sim, "dt_old", 0.0)),
                 dtnew=float(dtc) if dtc is not None else 0.0,
                 nproc=nproc, **tree_payload, **extra)

    # partition this process's shards into host groups (by device)
    ngrp = max(1, int(split_hosts or 1))
    grp_blocks = [dict() for _ in range(ngrp)]
    grp_counts = [dict() for _ in range(ngrp)]
    for name, a in arrs.items():
        shards = list(a.addressable_shards)
        for k, s in enumerate(shards):
            g = k * ngrp // max(len(shards), 1)
            i = grp_counts[g].get(name, 0)
            grp_counts[g][name] = i + 1
            r0 = s.index[0].start or 0
            grp_blocks[g][f"{name}_r{i}"] = np.asarray([r0],
                                                       dtype=np.int64)
            grp_blocks[g][f"{name}_d{i}"] = np.asarray(s.data)
    for g in range(ngrp):
        for name, n in grp_counts[g].items():
            grp_blocks[g][f"{name}_n"] = np.asarray([n], dtype=np.int64)

    sem = threading.Semaphore(io_group_size or max(nproc * ngrp, 1))
    errs = []

    def write(g):
        with sem:
            try:
                np.savez(os.path.join(out,
                                      f"host_{me * ngrp + g:05d}.npz"),
                         **grp_blocks[g])
            except Exception as e:          # surface on the main thread
                errs.append(e)

    def write_all():
        threads = [threading.Thread(target=write, args=(g,))
                   for g in range(ngrp)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        if errs:
            raise errs[0]

    group = int(io_group_size or 0)
    if group > 0 and nproc > 1:
        # cross-host wave staggering: wave w writes while the others
        # wait at the barrier; min(group, nproc) waves covers every
        # residue class that actually occurs
        mine = _host_wave(me, group)
        for w in range(min(group, nproc)):
            if mine == w:
                write_all()
            _barrier(f"pario_{iout:05d}_wave_{w}")
    else:
        write_all()
    if atomic:
        out = ckpt.finalize_checkpoint(out, final, meta={
            "kind": "pario", "iout": int(iout),
            "nstep": int(sim.nstep), "t": float(sim.t)})
    return out


def restore_pario(cls, params, outdir: str, dtype=None, devices=None,
                  **kw):
    """Rebuild a sim of class ``cls`` from a ``pario_NNNNN`` directory
    onto the CURRENT device count.  Reads every host file set present,
    reassembles global row arrays, and places them level by level."""
    import glob as globmod

    import jax.numpy as jnp

    from ramses_tpu.amr.tree import Octree

    man = np.load(os.path.join(outdir, "manifest.npz"))
    levels = [int(l) for l in man["levels"]]
    tree = Octree(int(man["ndim"]), int(man["levelmin"]),
                  int(man["levelmax"]),
                  root=(man["root"] if "root" in man.files else None))
    for l in levels:
        tree.set_level(l, man[f"og{l}"])
    if devices is not None:
        kw["devices"] = devices
    sim = cls(params, dtype=dtype or jnp.float32, init_tree=tree, **kw)

    # gather row blocks from every host file
    per_name: Dict[str, list] = {}
    for f in sorted(globmod.glob(os.path.join(outdir, "host_*.npz"))):
        z = np.load(f)
        names = {k[:-2] for k in z.files if k.endswith("_n")}
        for name in names:
            nsh = int(z[f"{name}_n"][0])
            for k in range(nsh):
                per_name.setdefault(name, []).append(
                    (int(z[f"{name}_r{k}"][0]), z[f"{name}_d{k}"]))
    ttd = 2 ** int(man["ndim"])
    for l in levels:
        orow = (np.asarray(man[f"octrow{l}"], np.int64)
                if f"octrow{l}" in man.files else None)
        for prefix, target in (("u", "u"), ("bf", "bf")):
            name = f"{prefix}{l}"
            if name not in per_name:
                continue
            tgt = getattr(sim, target, None)
            if tgt is None or l not in tgt:
                continue
            cur = np.asarray(tgt[l])
            # reassemble at the DUMP's row extent first: a rebalanced
            # dump scatters real rows across its whole bucket, and the
            # dump's bucket may exceed this mesh's (hysteresis state
            # isn't persisted) — clipping to cur.shape up front would
            # drop real cells
            ext = max((r0 + len(data) for r0, data in per_name[name]),
                      default=0)
            if orow is not None:
                ext = max(ext, (int(orow.max()) + 1) * ttd)
            dbuf = np.zeros((ext,) + cur.shape[1:], cur.dtype)
            for r0, data in per_name[name]:
                dbuf[r0:r0 + len(data)] = data
            if orow is not None:
                # dump rows are in the dump sim's rebalanced layout:
                # oct i lives at cell rows [orow[i]*ttd, ...) — gather
                # back to tree order (the fresh sim starts identity)
                idx = (orow[:, None] * ttd
                       + np.arange(ttd)[None, :]).reshape(-1)
                dbuf = dbuf[idx]
            buf = np.zeros(cur.shape, cur.dtype)
            n = min(len(dbuf), len(buf))
            buf[:n] = dbuf[:n]
            tgt[l] = sim._place(jnp.asarray(buf, buf.dtype), "cells")
    _restore_extra_state(sim, man, params)
    dump_nproc = int(man["nproc"]) if "nproc" in man.files else 1
    lost = _unpersisted_state(sim, nproc=dump_nproc)
    if lost:
        warnings.warn(
            f"restore_pario: restored run carries {'/'.join(lost)} "
            "state that was NOT in the checkpoint — those layers are "
            "fresh from ICs, not the dumped run.", stacklevel=2)
    sim.t = float(man["t"])
    sim.nstep = int(man["nstep"])
    sim.dt_old = float(man["dt_old"])
    dtn = float(man["dtnew"]) if "dtnew" in man.files else 0.0
    # pending next-step dt: restore takes the same next step a
    # continuous run would (dt hysteresis rides the manifest)
    sim._dt_cache = dtn if dtn > 0.0 else None
    return sim
