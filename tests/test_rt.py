"""Radiative transfer tests.

Anchors: free-streaming propagation speed, photon conservation,
absorption↔ionization bookkeeping, and the classical Stromgren-sphere
expansion against the analytic solution — the reference's stromgren2d
oracle in analytic form (SURVEY.md §4).
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.rt import chem as chem_mod
from ramses_tpu.rt import m1
from ramses_tpu.rt.chem import GroupSpec
from ramses_tpu.rt.driver import C_CGS, RtSim, RtSpec, stromgren_radius


def test_free_streaming_speed_1d():
    """A photon front must advance at the reduced speed of light."""
    spec = RtSpec(ndim=1, c_fraction=1e-4, heating=False)
    n = 256
    dx = 1.0e14
    sim = RtSim((n,), dx, spec, nH=np.full(n, 1e-30))  # no absorption
    N0 = np.zeros(n)
    N0[:8] = 1.0
    sim.N = jnp.asarray(N0)
    sim.F = jnp.asarray(N0[None, :] * spec.c_red)      # beaming right
    t = 100 * dx / spec.c_red
    sim.advance(t)
    N = np.asarray(sim.N)
    # half-max front position (GLF smears the 1% contour): the slab's
    # leading edge started at cell 7 and travelled ~100 cells
    front = np.max(np.where(N > 0.5 * N.max())[0])
    assert 90 <= front <= 118
    # photons conserved (periodic, no absorption)
    assert np.isclose(N.sum(), N0.sum(), rtol=1e-10)


def test_m1_closure_limits():
    N = jnp.asarray([1.0, 1.0])
    # free streaming: |F| = cN → P = N n n
    F = [jnp.asarray([1.0, 0.0])]
    P = m1.eddington(N, F, 1.0, 1)
    assert np.isclose(float(P[0][0][0]), 1.0, atol=1e-10)  # f=1: chi=1
    assert np.isclose(float(P[0][0][1]), 1.0 / 3.0, atol=1e-10)  # f=0


def test_absorption_ionization_balance():
    """Photons removed == ionizations performed (no recombination at
    T→0 limit over a short step)."""
    nH = jnp.full((16,), 1e-3)
    N = jnp.full((16,), 1e-6)
    T = jnp.full((16,), 1e2)
    x0 = jnp.full((16,), 1e-6)
    dt = 1e8
    c_red = 1e-3 * C_CGS
    g = GroupSpec()
    N1, x1, T1 = chem_mod.chem_step(N, x0, T, nH, dt, c_red, g,
                                    heating=False)
    absorbed = float((N - N1).sum())
    ionized = float((nH * (x1 - x0)).sum())
    assert absorbed > 0
    assert np.isclose(absorbed, ionized, rtol=0.05)


@pytest.mark.smoke
def test_stromgren_sphere_3d():
    """Ionized volume approaches the analytic Stromgren value."""
    nH0 = 1e-3           # cm^-3
    ndot = 5e48          # photons/s
    T0 = 1e4
    rs = stromgren_radius(ndot, nH0, T0)
    box = 4.0 * rs
    n = 32
    dx = box / n
    spec = RtSpec(ndim=3, c_fraction=1e-3, heating=False, periodic=False)
    sim = RtSim((n,) * 3, dx, spec, nH=np.full((n,) * 3, nH0),
                T=np.full((n,) * 3, T0))
    sim.point_source((box / 2,) * 3, ndot)
    # equilibrium photon balance fixes ∫x²dV = V_S exactly (recombination
    # ∝ x²); ∫x dV would overcount the GLF-diffused front.  Run 3 t_rec.
    aB = float(chem_mod.alpha_B(jnp.asarray(T0)))
    t_rec = 1.0 / (aB * nH0)
    v2_hist = []
    for _ in range(9):
        sim.advance(0.5 * t_rec)
        x = np.asarray(sim.x)
        v2_hist.append(float((x ** 2).sum()) * dx ** 3)
    v_s = 4.0 / 3.0 * np.pi * rs ** 3
    assert 0.88 < v2_hist[-1] / v_s < 1.05, \
        f"x²-volume/V_S = {v2_hist[-1] / v_s:.3f}"
    assert all(b >= a * 0.999 for a, b in zip(v2_hist, v2_hist[1:]))
    # interior ionized, exterior neutral
    x = np.asarray(sim.x)
    c = n // 2
    assert x[c, c, c] > 0.99
    assert x[0, 0, 0] < 0.05


def test_photoheating_raises_temperature():
    nH0 = 1e-3
    ndot = 1e49
    n = 16
    rs = stromgren_radius(ndot, nH0)
    dx = 2 * rs / n
    spec = RtSpec(ndim=2, c_fraction=1e-3, heating=True, periodic=False)
    sim = RtSim((n, n), dx, spec, nH=np.full((n, n), nH0),
                T=np.full((n, n), 100.0))
    sim.point_source((rs, rs), ndot)
    aB = float(chem_mod.alpha_B(jnp.asarray(1e4)))
    sim.advance(0.3 / (aB * nH0))
    T = np.asarray(sim.T)
    c = n // 2
    assert T[c, c] > 5e3           # photoheated toward ~1e4 K
    assert np.all(np.isfinite(T))


def test_photon_conservation_with_source():
    """Without absorption, injected photons are exactly accounted."""
    spec = RtSpec(ndim=2, c_fraction=1e-3, heating=False, periodic=True)
    n = 32
    dx = 3e15
    sim = RtSim((n, n), dx, spec, nH=np.full((n, n), 1e-30))
    ndot = 1e50
    sim.point_source((n * dx / 2, n * dx / 2), ndot)
    dt = 20 * m1.rt_courant_dt(dx, spec.c_red)
    sim.advance(dt)
    expected = ndot * sim.t
    assert np.isclose(sim.photon_total(), expected, rtol=1e-6)
