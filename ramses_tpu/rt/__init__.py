"""M1-closure radiative transfer (SURVEY.md §2.5, §2.9).

The ``rt/`` module equivalent — photon density + flux per group advected
with the M1 Eddington closure at a reduced speed of light, coupled to
non-equilibrium hydrogen photochemistry and photoheating — and at the
same time the ATON replacement: the whole solve is one dense fused device
program on the uniform grid (the reference's GPU offload pattern,
gather → device step × N → scatter, §2.9, is simply our normal execution
model).
"""
