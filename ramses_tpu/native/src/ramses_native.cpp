// Native host-side kernels for the tree/metadata passes.
//
// The reference keeps its hot host-side machinery in compiled code
// (Fortran tree walks amr/nbors_utils.f90, C++/CUDA atonlib, pario
// transfer.c); these are the equivalents for our host core: space-filling
// curve keys, batched ordered lookups, and neighbour index-map
// construction — the build_comm-shaped passes that run after each
// refinement (SURVEY.md §7).
//
// Hilbert indices use John Skilling's public-domain transpose algorithm
// ("Programming the Hilbert curve", AIP Conf. Proc. 707, 381 (2004)) —
// an independent, cleaner formulation of what amr/hilbert.f90 implements
// with per-dimension state machines.

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------- Morton
static inline uint64_t spread2(uint64_t x) {
    x &= 0xFFFFFFFFull;
    x = (x | (x << 16)) & 0x0000FFFF0000FFFFull;
    x = (x | (x << 8))  & 0x00FF00FF00FF00FFull;
    x = (x | (x << 4))  & 0x0F0F0F0F0F0F0F0Full;
    x = (x | (x << 2))  & 0x3333333333333333ull;
    x = (x | (x << 1))  & 0x5555555555555555ull;
    return x;
}

static inline uint64_t spread3(uint64_t x) {
    x &= 0x1FFFFFull;
    x = (x | (x << 32)) & 0x1F00000000FFFFull;
    x = (x | (x << 16)) & 0x1F0000FF0000FFull;
    x = (x | (x << 8))  & 0x100F00F00F00F00Full;
    x = (x | (x << 4))  & 0x10C30C30C30C30C3ull;
    x = (x | (x << 2))  & 0x1249249249249249ull;
    return x;
}

void morton_encode(const int64_t* og, int64_t n, int ndim, int64_t* out) {
    if (ndim == 1) {
        memcpy(out, og, sizeof(int64_t) * (size_t)n);
    } else if (ndim == 2) {
        for (int64_t i = 0; i < n; i++)
            out[i] = (int64_t)(spread2((uint64_t)og[2 * i])
                               | (spread2((uint64_t)og[2 * i + 1]) << 1));
    } else {
        for (int64_t i = 0; i < n; i++)
            out[i] = (int64_t)(spread3((uint64_t)og[3 * i])
                               | (spread3((uint64_t)og[3 * i + 1]) << 1)
                               | (spread3((uint64_t)og[3 * i + 2]) << 2));
    }
}

// ---------------------------------------------------------------- Hilbert
// Skilling (2004): AxesToTranspose + bit interleave of the transpose.
static inline uint64_t hilbert_key_one(uint64_t* X, int b, int n) {
    uint64_t M = 1ull << (b - 1), P, Q, t;
    // Inverse undo
    for (Q = M; Q > 1; Q >>= 1) {
        P = Q - 1;
        for (int i = 0; i < n; i++) {
            if (X[i] & Q) X[0] ^= P;
            else { t = (X[0] ^ X[i]) & P; X[0] ^= t; X[i] ^= t; }
        }
    }
    // Gray encode
    for (int i = 1; i < n; i++) X[i] ^= X[i - 1];
    t = 0;
    for (Q = M; Q > 1; Q >>= 1)
        if (X[n - 1] & Q) t ^= Q - 1;
    for (int i = 0; i < n; i++) X[i] ^= t;
    // interleave transpose bits, x-bit most significant per group
    uint64_t key = 0;
    for (int j = b - 1; j >= 0; j--)
        for (int i = 0; i < n; i++)
            key = (key << 1) | ((X[i] >> j) & 1ull);
    return key;
}

void hilbert_encode(const int64_t* og, int64_t n, int ndim, int nbits,
                    uint64_t* out) {
    uint64_t X[3];
    for (int64_t i = 0; i < n; i++) {
        for (int d = 0; d < ndim; d++)
            X[d] = (uint64_t)og[i * ndim + d];
        out[i] = hilbert_key_one(X, nbits, ndim);
    }
}

// ------------------------------------------------------------- searching
void searchsorted_i64(const int64_t* sorted, int64_t m, const int64_t* q,
                      int64_t n, int64_t* out) {
    for (int64_t i = 0; i < n; i++) {
        int64_t lo = 0, hi = m;
        int64_t v = q[i];
        while (lo < hi) {
            int64_t mid = (lo + hi) >> 1;
            if (sorted[mid] < v) lo = mid + 1;
            else hi = mid;
        }
        out[i] = lo;
    }
}

// lookup: position where sorted[pos]==q, else -1
void lookup_i64(const int64_t* sorted, int64_t m, const int64_t* q,
                int64_t n, int64_t* out) {
    for (int64_t i = 0; i < n; i++) {
        int64_t lo = 0, hi = m;
        int64_t v = q[i];
        while (lo < hi) {
            int64_t mid = (lo + hi) >> 1;
            if (sorted[mid] < v) lo = mid + 1;
            else hi = mid;
        }
        out[i] = (lo < m && sorted[lo] == v) ? lo : -1;
    }
}

// ------------------------------------------------- neighbour index maps
// For each oct (og[i]) and each offset (offs[k]), find the index of the
// oct at og[i]+offs[k] (periodic wrap at level_size) in the sorted key
// array; -1 if absent.  This is the kernel of build_level_maps — the
// get3cubefather equivalent (amr/nbors_utils.f90:5).
void neighbor_lookup(const int64_t* keys_sorted, const int64_t* og,
                     int64_t noct, int ndim, int64_t level_size,
                     const int64_t* offs, int64_t nofs, int64_t* out) {
    uint64_t tmp[3];
    for (int64_t i = 0; i < noct; i++) {
        for (int64_t k = 0; k < nofs; k++) {
            // wrapped neighbour coordinates → Morton key
            for (int d = 0; d < ndim; d++) {
                int64_t c = og[i * ndim + d] + offs[k * ndim + d];
                c %= level_size;
                if (c < 0) c += level_size;
                tmp[d] = (uint64_t)c;
            }
            uint64_t key;
            if (ndim == 1) key = tmp[0];
            else if (ndim == 2)
                key = spread2(tmp[0]) | (spread2(tmp[1]) << 1);
            else
                key = spread3(tmp[0]) | (spread3(tmp[1]) << 1)
                    | (spread3(tmp[2]) << 2);
            // binary search
            int64_t lo = 0, hi = noct;
            int64_t v = (int64_t)key;
            while (lo < hi) {
                int64_t mid = (lo + hi) >> 1;
                if (keys_sorted[mid] < v) lo = mid + 1;
                else hi = mid;
            }
            out[i * nofs + k] =
                (lo < noct && keys_sorted[lo] == v) ? lo : -1;
        }
    }
}

}  // extern "C"
