"""Uniform-grid constrained-transport MHD stepper.

The ``mag_unsplit`` pipeline (``mhd/umuscl.f90``, 2,844 LoC of
nvector-batched stencils) re-designed as whole-grid fused XLA ops:

  ctoprim → TVD slopes → conservative Hancock half-step predictor →
  per-direction HLLD/HLL/LLF face fluxes → Gardiner-Stone arithmetic
  edge-EMF averaging → induction update of the staggered field
  (``mhd/godunov_fine.f90:960-973``'s B += curl(EMF)) → conservative update.

div(B) is zero to machine precision by construction (staggered curl), the
property the reference maintains with face-B pairs + EMF arrays
(``mhd/godunov_fine.f90:565``).
"""

from __future__ import annotations

from dataclasses import dataclass, replace as dreplace
from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from ramses_tpu.grid import boundary as bmod
from ramses_tpu.hydro import muscl as hmuscl
from ramses_tpu.mhd import core, riemann as rsolve
from ramses_tpu.mhd.core import IBX, IP, MhdStatic, NCOMP

NGHOST = 2


@dataclass(frozen=True)
class MhdGrid:
    cfg: MhdStatic
    shape: Tuple[int, ...]
    dx: float
    bc_kinds: Tuple[Tuple[int, int], ...]   # per-dim (low, high) kinds

    @property
    def ncell(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n


def _axis(ndim: int, d: int, a) -> int:
    return a.ndim - ndim + d


def _pad(a, ndim: int, bc_kinds, ng: int = NGHOST, flip_comp: int = -1):
    """Ghost-pad the trailing ndim axes.  Periodic wrap or outflow edge
    replication (the two kinds the MHD path supports; reflecting walls
    need face-field mirroring — not yet wired)."""
    for d in range(ndim):
        ax = a.ndim - ndim + d
        lo, hi = bc_kinds[d]
        n = a.shape[ax]

        def take(s0, s1):
            idx = [slice(None)] * a.ndim
            idx[ax] = slice(s0, s1)
            return a[tuple(idx)]

        def ghost(kind, side):
            if kind == bmod.PERIODIC:
                return take(n - ng, n) if side == 0 else take(0, ng)
            # outflow: replicate edge
            edge = take(0, 1) if side == 0 else take(n - 1, n)
            reps = [1] * a.ndim
            reps[ax] = ng
            return jnp.tile(edge, reps)

        a = jnp.concatenate([ghost(lo, 0), a, ghost(hi, 1)], axis=ax)
    return a


def _unpad(a, ndim: int, ng: int = NGHOST):
    idx = [slice(None)] * a.ndim
    for d in range(ndim):
        ax = a.ndim - ndim + d
        idx[ax] = slice(ng, a.shape[ax] - ng)
    return a[tuple(idx)]


def _slopes(q, cfg: MhdStatic):
    """The hydro TVD limiter bank applied to the MHD primitive stack —
    ``uslope`` only reads ndim/slope_type/slope_theta, which MhdStatic
    provides with identical semantics."""
    return list(hmuscl.uslope(q, cfg))


def _rot_perm(cfg: MhdStatic, d: int):
    t1, t2 = (d + 1) % 3, (d + 2) % 3
    perm = [0, 1 + d, 1 + t1, 1 + t2, IP, IBX + d, IBX + t1, IBX + t2]
    perm += list(range(8, cfg.nvar))
    return perm


def ct_core(up, bfp, dt, dx: Sequence[float], cfg: MhdStatic,
            bax: int = 0, bn_faces=None, flux_mask=None,
            emf_override=None):
    """The CT MUSCL-Hancock pipeline on already-assembled arrays.

    ``up`` [nvar, *sp(, batch…)] cell conservative with B slots ALREADY
    cell-centered; ``bfp`` list of NCOMP low-face arrays (same spatial
    shape).  ``bax`` = number of trailing batch axes (0 for the uniform
    grid, 1 for the AMR per-oct stencil batch).  ``bn_faces``: optional
    override of the low-face normal fields fed to the Riemann solver
    (the AMR path prefers stored fine values on shared coarse-fine
    faces).  ``flux_mask``: optional per-dim keep factors (0 at refined
    faces, ``godunov_fine.f90:718`` semantics) applied to the CELL
    update and the returned fluxes but NOT to the EMF corner average —
    the fine region's state is restriction-overwritten while its edge
    EMFs stay whole-level consistent.  Spatial shifts are ``jnp.roll``
    — callers guarantee enough ghost/stencil margin that
    wrap-contaminated entries are never read from the region they keep.

    Returns (un, bfn_list, fluxes, e_edges) where ``e_edges[(d1,d2)]``
    is the final corner EMF field of that staggered pair (the quantity
    the AMR coarse-fine matching averages, ``mhd/godunov_fine.f90:826``).
    """
    nd = cfg.ndim

    def ax_(d, a):
        return a.ndim - nd - bax + d

    q = core.ctoprim(up, cfg)
    # the slope bank infers spatial axes from cfg: flag the batch axis
    scfg = dreplace(cfg, trailing_batch=True) if bax else cfg
    dq = _slopes(q, scfg)

    # conservative Hancock half-step: the cell's own reconstructed faces
    du_half = jnp.zeros_like(up)
    face_q = []
    for d in range(nd):
        q_hi = q + 0.5 * dq[d]
        q_lo = q - 0.5 * dq[d]
        f_hi = core.flux_along(q_hi, d, cfg)
        f_lo = core.flux_along(q_lo, d, cfg)
        du_half = du_half - (0.5 * dt / dx[d]) * (f_hi - f_lo)
        face_q.append((q_lo, q_hi))

    # half-dt prediction of the staggered field (edge-averaged cell EMFs),
    # so the Riemann normal field is time-centred like its other inputs —
    # the role of the reference's induction terms in trace3d
    # (``mhd/umuscl.f90`` magnetic predictor)
    base_faces = bn_faces if bn_faces is not None else bfp
    bf_half = [base_faces[c] for c in range(NCOMP)]
    for d1 in range(nd):
        for d2 in range(d1 + 1, nd):
            ax1 = ax_(d1, bfp[d1])
            ax2 = ax_(d2, bfp[d1])
            sig = 1.0 if (d1, d2) in ((0, 1), (1, 2), (2, 0)) else -1.0
            v1, v2 = q[1 + d1], q[1 + d2]
            b1, b2 = q[IBX + d1], q[IBX + d2]
            e_c0 = sig * (v2 * b1 - v1 * b2)
            e_edge0 = 0.25 * (e_c0 + jnp.roll(e_c0, 1, axis=ax1)
                              + jnp.roll(e_c0, 1, axis=ax2)
                              + jnp.roll(jnp.roll(e_c0, 1, axis=ax1),
                                         1, axis=ax2))
            bf_half[d1] = bf_half[d1] - sig * (0.5 * dt / dx[d2]) * (
                jnp.roll(e_edge0, -1, axis=ax2) - e_edge0)
            bf_half[d2] = bf_half[d2] + sig * (0.5 * dt / dx[d1]) * (
                jnp.roll(e_edge0, -1, axis=ax1) - e_edge0)

    fluxes = []
    for d in range(nd):
        ax = ax_(d, q)
        q_lo, q_hi = face_q[d]
        ul_c = core.prim_to_cons(q_hi, cfg) + du_half    # this cell's hi face
        ur_c = core.prim_to_cons(q_lo, cfg) + du_half    # this cell's lo face
        ql = core.ctoprim(jnp.roll(ul_c, 1, axis=ax), cfg)
        qr = core.ctoprim(ur_c, cfg)
        # static per-row stack, not a gather with an index array: the
        # Pallas CT kernel traces this body and may not close over
        # constants, and XLA folds the stack to the same copies anyway
        perm = _rot_perm(cfg, d)
        ql_r = jnp.stack([ql[i] for i in perm])
        qr_r = jnp.stack([qr[i] for i in perm])
        bn = bf_half[d]                # staggered, half-dt predicted
        fg = rsolve.solve(ql_r, qr_r, bn, cfg)
        # scatter to state layout
        out = [None] * cfg.nvar
        t1, t2 = (d + 1) % 3, (d + 2) % 3
        out[0] = fg[0]
        out[1 + d], out[1 + t1], out[1 + t2] = fg[1], fg[2], fg[3]
        out[IP] = fg[4]
        out[IBX + d], out[IBX + t1], out[IBX + t2] = fg[5], fg[6], fg[7]
        for s in range(cfg.npassive):
            out[8 + s] = fg[8 + s]
        fluxes.append(jnp.stack(out))

    # conservative update of cell state (staggered B rows excluded)
    if flux_mask is not None:
        fl_cell = [fluxes[d] * flux_mask[d][None] for d in range(nd)]
    else:
        fl_cell = fluxes
    un = up
    for d in range(nd):
        ax = ax_(d, up)
        un = un + (dt / dx[d]) * (fl_cell[d]
                                  - jnp.roll(fl_cell[d], -1, axis=ax))
    # half-step primitives for the cell-centered EMF reference
    q_half = core.ctoprim(up + du_half, cfg)

    # CT induction on staggered components.  The base is the SAME
    # face-value selection the Riemann solver saw (bn_faces): on the AMR
    # stencil path this keeps every cell's own (lo, hi) pair evolving
    # from its own stored values, so per-cell divB is preserved exactly
    # even where duplicated faces disagree across a coarse-fine seam.
    bfn = [base_faces[c] for c in range(NCOMP)]
    e_edges = {}
    use2d = cfg.riemann2d != "average" and nd >= 2
    for d1 in range(nd):
        for d2 in range(d1 + 1, nd):
            # axes on the scalar (no component dim) EMF arrays
            ax1 = ax_(d1, bfp[d1])
            ax2 = ax_(d2, bfp[d1])
            # face EMFs: E_e on d1-faces and d2-faces
            sig = 1.0 if (d1, d2) in ((0, 1), (1, 2), (2, 0)) else -1.0
            if use2d:
                # 2D corner Riemann upwinding (cmp_mag_flx,
                # mhd/umuscl.f90:1453): half-dt-evolved corner states
                # of the four cells around each edge.  Reconstruction
                # happens in PRIMITIVE space around the half-evolved
                # cell state (the reference's trace does the same) — a
                # conservative round-trip would divide momentum by the
                # floored density when the diagonal slope sum overshoots
                # at a strong contact, exploding the corner velocities.
                from ramses_tpu.mhd import riemann2d as r2d
                pfloor = cfg.smallr * cfg.smallc ** 2
                qcorner = {}
                for s1 in (-1.0, 1.0):
                    for s2 in (-1.0, 1.0):
                        qc = q_half + 0.5 * (s1 * dq[d1] + s2 * dq[d2])
                        qc = qc.at[0].set(jnp.maximum(qc[0], cfg.smallr))
                        qc = qc.at[IP].set(jnp.maximum(qc[IP], pfloor))
                        qcorner[(s1, s2)] = qc
                dorth = 3 - d1 - d2

                def comp(qc, *rolls):
                    for ax in rolls:
                        qc = jnp.roll(qc, 1, axis=ax)
                    return (qc[0], qc[IP], qc[1 + d1], qc[1 + d2],
                            qc[1 + dorth], qc[IBX + dorth])

                qax1, qax2 = ax_(d1, q), ax_(d2, q)
                states = {
                    ("R", "T"): comp(qcorner[(-1.0, -1.0)]),
                    ("L", "T"): comp(qcorner[(1.0, -1.0)], qax1),
                    ("R", "B"): comp(qcorner[(-1.0, 1.0)], qax2),
                    ("L", "B"): comp(qcorner[(1.0, 1.0)], qax1, qax2),
                }
                A_T = bf_half[d1]
                A_B = jnp.roll(bf_half[d1], 1, axis=ax2)
                B_R = bf_half[d2]
                B_L = jnp.roll(bf_half[d2], 1, axis=ax1)
                eps = r2d.corner_emf(states, A_T, A_B, B_R, B_L, cfg)
                e_edge = -sig * eps
            else:
                # F_d1(B_d2) = -sig*E_e ; F_d2(B_d1) = +sig*E_e
                e_f1 = -sig * fluxes[d1][IBX + d2]       # (lo d1, ctr d2)
                e_f2 = sig * fluxes[d2][IBX + d1]        # (ctr d1, lo d2)
                # cell-centered reference EMF from half-step state
                v1, v2 = q_half[1 + d1], q_half[1 + d2]
                b1, b2 = q_half[IBX + d1], q_half[IBX + d2]
                e_c = sig * (v2 * b1 - v1 * b2)          # E_e = -(v×B)_e
                # Gardiner & Stone (2005) arithmetic corner average
                e_edge = (0.5 * (e_f1 + jnp.roll(e_f1, 1, axis=ax2)
                                 + e_f2 + jnp.roll(e_f2, 1, axis=ax1))
                          - 0.25 * (e_c + jnp.roll(e_c, 1, axis=ax1)
                                    + jnp.roll(e_c, 1, axis=ax2)
                                    + jnp.roll(jnp.roll(e_c, 1,
                                                        axis=ax1),
                                               1, axis=ax2)))
            if emf_override is not None and (d1, d2) in emf_override:
                # coarse-fine EMF matching (godunov_fine.f90:826-973):
                # edges covered by a refined oct take the time-averaged
                # fine EMF, so the coarse induction lands EXACTLY on the
                # restriction of the fine faces
                msk, vals = emf_override[(d1, d2)]
                e_edge = jnp.where(msk, vals.astype(e_edge.dtype), e_edge)
            e_edges[(d1, d2)] = e_edge
            # dB_d1/dt = -sig * dE_e/d_d2 ; dB_d2/dt = +sig * dE_e/d_d1
            bfn[d1] = bfn[d1] - sig * (dt / dx[d2]) * (
                jnp.roll(e_edge, -1, axis=ax2) - e_edge)
            bfn[d2] = bfn[d2] + sig * (dt / dx[d1]) * (
                jnp.roll(e_edge, -1, axis=ax1) - e_edge)

    # degenerate (cell-centered) components advance with the conservative
    # flux update; without this they would be frozen at their ICs
    for c in range(nd, NCOMP):
        bfn[c] = un[IBX + c]
    # refresh cell-centered staggered B components from the new faces
    bc_new = []
    for c in range(min(nd, NCOMP)):
        b = bfn[c]
        bc_new.append(0.5 * (b + jnp.roll(b, -1, axis=ax_(c, b))))
    for c in range(min(nd, NCOMP)):
        un = un.at[IBX + c].set(bc_new[c])
    return un, bfn, fl_cell, e_edges


def step_padded(cfg: MhdStatic, dx: Sequence[float], up, bfp_ext, dt,
                okp=None, ovr=None):
    """The CT step on ALREADY ghost-assembled arrays — the single
    pipeline behind :func:`step` (global pad), the slab-sharded advance
    (:func:`ramses_tpu.parallel.dense_slab.mhd_ct_slab`, halo-exchanged
    ghosts) and the single-block Pallas kernel
    (:mod:`ramses_tpu.mhd.pallas_ct`).

    ``up`` [nvar, \\*sp+2·ng] padded cell conservative with the RAW
    (uncentered) B slots — the face-average centering happens here;
    ``bfp_ext`` [NCOMP, \\*sp+2·(ng+1)] low faces padded one layer
    deeper (the centred average must be valid in every padded cell);
    ``okp`` optional padded bool refined mask [\\*sp+2·ng]; ``ovr``
    optional dict (d1,d2) → (padded mask, padded values) on the padded
    corner lattice.  Returns the PADDED (un, bfn_list) — callers
    unpad."""
    nd = cfg.ndim
    trim = tuple([slice(None)] + [slice(1, -1)] * nd)
    bfp = bfp_ext[trim]
    bc = []
    for c in range(NCOMP):
        b = bfp_ext[c]
        lo = b[tuple(slice(1, -1) for _ in range(nd))]
        if c < nd:
            hi_idx = [slice(1, -1)] * nd
            hi_idx[c] = slice(2, None)      # neighbour's low face = high face
            bc.append(0.5 * (lo + b[tuple(hi_idx)]))
        else:
            bc.append(lo)
    up = up.at[IBX:IBX + NCOMP].set(jnp.stack(bc))

    flux_mask = None
    if okp is not None:
        flux_mask = []
        for d in range(nd):
            ax = okp.ndim - nd + d
            keep = ~(okp | jnp.roll(okp, 1, axis=ax))
            flux_mask.append(keep.astype(up.dtype))
    un, bfn, _fluxes, _e = ct_core(up, [bfp[c] for c in range(NCOMP)],
                                   dt, dx, cfg, flux_mask=flux_mask,
                                   emf_override=ovr)
    return un, bfn


def step(grid: MhdGrid, u, bf, dt, ok=None, emf_override=None):
    """One CT MUSCL-Hancock step.  ``u`` [nvar, *sp] cell conservative
    (B slots cell-centered, derived), ``bf`` [3, *sp] staggered low-face
    field.  ``ok``: optional refined-cell mask — faces touching a
    refined cell get zero cell-state flux (AMR complete-level path).
    ``emf_override``: dict (d1,d2) → (mask, values) on the ACTIVE grid's
    cell-corner lattice — coarse-fine EMF matching.
    Returns (u', bf')."""
    cfg = grid.cfg
    nd = cfg.ndim
    dx = (grid.dx,) * nd
    ng = NGHOST

    up = _pad(u, nd, grid.bc_kinds)
    # faces get one extra ghost layer so the cell-centred average is valid
    # in EVERY padded cell (a rolled average would wrap garbage into the
    # outermost ghosts and contaminate boundary-face slopes)
    bfp_ext = _pad(bf, nd, grid.bc_kinds, ng + 1)
    okp = None
    if ok is not None:
        okp = _pad(ok[None], nd, grid.bc_kinds)[0]
    ovr = None
    if emf_override is not None:
        ovr = {}
        for pair, (msk, vals) in emf_override.items():
            ovr[pair] = (_pad(msk[None], nd, grid.bc_kinds)[0],
                         _pad(vals[None], nd, grid.bc_kinds)[0])
    un, bfn = step_padded(cfg, dx, up, bfp_ext, dt, okp=okp, ovr=ovr)
    u_out = _unpad(un, nd)
    bf_out = jnp.stack([_unpad(b, nd) for b in bfn])
    return u_out, bf_out


@partial(jax.jit, static_argnames=("grid",))
def cfl_dt(grid: MhdGrid, u, bf):
    cfg = grid.cfg
    nd = cfg.ndim
    bc = core.cell_center_b([bf[c] for c in range(NCOMP)], nd)
    uu = u.at[IBX:IBX + NCOMP].set(jnp.stack(bc))
    q = core.ctoprim(uu, cfg)
    rate = 0.0
    for d in range(nd):
        cf = core.fast_speed(q, d, cfg)
        rate = rate + (jnp.abs(q[1 + d]) + cf) / grid.dx
    return cfg.courant_factor / jnp.max(rate)


_jit_step = jax.jit(step, static_argnames=("grid",))


@partial(jax.jit, static_argnames=("grid", "nsteps", "dt_scale"))
def run_steps(grid: MhdGrid, u, bf, t, tend, nsteps: int,
              dt_scale: float = 1.0):
    """Advance up to nsteps entirely on device (cf. hydro run_steps).
    ``dt_scale < 1``: redo-step retry at reduced Courant dt."""
    def body(carry, _):
        u, bf, t, ndone = carry
        dt = cfl_dt(grid, u, bf) * dt_scale
        dt = jnp.minimum(dt, jnp.maximum(tend - t, 0.0))
        active = t < tend
        un, bfn = step(grid, u, bf, jnp.where(active, dt, 0.0))
        u = jnp.where(active, un, u)
        bf = jnp.where(active, bfn, bf)
        t = jnp.where(active, t + dt, t)
        ndone = ndone + jnp.where(active, 1, 0)
        return (u, bf, t, ndone), None

    (u, bf, t, ndone), _ = jax.lax.scan(
        body, (u, bf, t, jnp.array(0)), None, length=nsteps)
    return u, bf, t, ndone


@partial(jax.jit,
         static_argnames=("grid", "nsteps", "dt_scale", "summarize"))
def run_steps_batch(grid: MhdGrid, u, bf, t, tend, nsteps: int,
                    dt_scale: float = 1.0, summarize: bool = False):
    """:func:`run_steps` vmapped over a leading ensemble axis
    (``u[B, nvar, *sp]``, ``bf[B, 3, *sp]``, ``t/tend[B]``) — cf. the
    hydro ``grid/uniform.run_steps_batch``.  Per-member completion is
    the in-scan ``t < tend`` mask; returns per-member ``ndone``, plus
    the per-member guard summary ``[B, 3]`` when ``summarize``."""
    def solo(u_, bf_, t_, tend_):
        return run_steps(grid, u_, bf_, t_, tend_, nsteps,
                         dt_scale=dt_scale)
    u, bf, t, ndone = jax.vmap(solo)(u, bf, t, tend)
    if summarize:
        from ramses_tpu.grid.uniform import batch_summary
        return u, bf, t, ndone, batch_summary(
            u, grid.cfg.ndim, grid.dx, IP, bf=bf)
    return u, bf, t, ndone


def totals(u, cfg: MhdStatic, dx: float):
    vol = dx ** cfg.ndim
    return {"mass": jnp.sum(u[0]) * vol,
            "energy": jnp.sum(u[IP]) * vol,
            "momentum": [jnp.sum(u[1 + c]) * vol for c in range(NCOMP)]}
