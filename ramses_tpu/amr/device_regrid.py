"""Device-resident regrid migration.

The host reference path (``maps.build_prolong_maps`` +
``hierarchy._migrate_level``) rebuilds per-level numpy row tables on
every changed-tree regrid — the r04-instrumented trace showed that host
work (migrate 9.3 s of a 94 s run) dominating once the sweep itself went
fast.  This module derives the same survivor-copy and new-oct
prolongation maps *on device* with one jitted kernel per level, straight
from the (already sorted) Morton key arrays:

* survivors: a binary search of the new level's keys in the old level's
  sorted keys (``Octree.lookup_keys`` is exactly this on host);
* father cells: a level-l oct key IS its father cell's level-(l-1)
  Morton key, and the covering oct key is ``key >> ndim`` — no
  coordinate decode needed;
* child offsets within the father oct: the bit-reversed low ``ndim``
  bits of the key (the host ``f_off = f_off*2 + (coords[:, d] & 1)``
  fold, since coordinate parities are the low interleaved key bits);
* father neighbours: a jnp port of ``keys.decode``/``encode`` and
  ``tree.map_coords`` (same mask ladders, same reflect/clip semantics),
  then the same binary search.

Selection is by ``where`` over values the host path would gather from
identical rows, and ``kernels.interp_cells`` is elementwise per request
row, so the migrated ``u`` is bitwise identical to the host path (pinned
by tests/test_oct_blocking.py).

Integer width: with jax x64 enabled the port mirrors the host 64-bit
mask ladders (coords to 21 bits/dim in 3D); without it the kernel runs
the standard 32-bit ladders, valid while ``ndim * coord_bits`` fits an
int32 — :func:`keys_fit` gates, and the hierarchy falls back to the
host path beyond.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ramses_tpu.amr import kernels as K
from ramses_tpu.amr.tree import cell_offsets

# spread-mask ladders keyed (ndim, wide): premask + (shift, mask) steps,
# mirroring amr/keys.py bit-for-bit in the 64-bit case and the standard
# 32-bit Morton ladders otherwise; compact runs the same table in
# reverse (see _compact)
_TABS = {
    (2, True): (0xFFFFFFFF,
                ((16, 0x0000FFFF0000FFFF), (8, 0x00FF00FF00FF00FF),
                 (4, 0x0F0F0F0F0F0F0F0F), (2, 0x3333333333333333),
                 (1, 0x5555555555555555))),
    (3, True): (0x1FFFFF,
                ((32, 0x1F00000000FFFF), (16, 0x1F0000FF0000FF),
                 (8, 0x100F00F00F00F00F), (4, 0x10C30C30C30C30C3),
                 (2, 0x1249249249249249))),
    (2, False): (0xFFFF,
                 ((8, 0x00FF00FF), (4, 0x0F0F0F0F),
                  (2, 0x33333333), (1, 0x55555555))),
    (3, False): (0x3FF,
                 ((16, 0xFF0000FF), (8, 0x0F00F00F),
                  (4, 0xC30C30C3), (2, 0x49249249))),
}


def _x64() -> bool:
    return bool(jax.config.jax_enable_x64)


def key_dtype():
    """Device integer dtype for Morton keys (int64 under x64)."""
    return jnp.int64 if _x64() else jnp.int32


def keys_fit(ndim: int, lvl: int, root=None) -> bool:
    """Can every key/coord this level needs fit the device key dtype?"""
    root = tuple(root or ()) or (1,) * ndim
    n = max(root[:ndim]) << max(lvl - 1, 0)    # cells/dim at lvl-1
    bits = max(int(n - 1).bit_length(), 1)
    if _x64():
        return bits <= {1: 62, 2: 31, 3: 20}[ndim]
    return bits <= {1: 30, 2: 15, 3: 10}[ndim]


def _sent(dtype) -> int:
    return int(np.iinfo(np.dtype(dtype.name if hasattr(dtype, "name")
                                 else dtype)).max)


def upload_keys(keys: np.ndarray, pad: int):
    """Sorted level keys padded to ``pad`` with the max-int sentinel
    (keeps the array sorted; sentinel never equals a real key under
    :func:`keys_fit`)."""
    dt = np.int64 if _x64() else np.int32
    out = np.full(pad, np.iinfo(dt).max, dtype=dt)
    n = min(len(keys), pad)
    out[:n] = keys[:n]
    return jnp.asarray(out)


def _spread(x, ndim: int, wide: bool):
    pre, tab = _TABS[(ndim, wide)]
    x = x & jnp.asarray(pre, x.dtype)
    for s, m in tab:
        x = (x | (x << s)) & jnp.asarray(m, x.dtype)
    return x


def _compact(x, ndim: int, wide: bool):
    pre, tab = _TABS[(ndim, wide)]
    x = x & jnp.asarray(tab[-1][1], x.dtype)
    for i in range(len(tab) - 1, 0, -1):
        x = (x | (x >> tab[i][0])) & jnp.asarray(tab[i - 1][1], x.dtype)
    return (x | (x >> tab[0][0])) & jnp.asarray(pre, x.dtype)


def _encode(c, ndim: int):
    """jnp port of keys.encode: coords [n, ndim] → keys [n]."""
    if ndim == 1:
        return c[:, 0]
    sdt = c.dtype
    udt = jnp.uint64 if sdt == jnp.int64 else jnp.uint32
    k = _spread(c[:, 0].astype(udt), ndim, sdt == jnp.int64)
    for d in range(1, ndim):
        k = k | (_spread(c[:, d].astype(udt), ndim,
                         sdt == jnp.int64) << d)
    return k.astype(sdt)


def _decode(k, ndim: int):
    """jnp port of keys.decode: keys [n] → coords [n, ndim]."""
    if ndim == 1:
        return k[:, None]
    sdt = k.dtype
    udt = jnp.uint64 if sdt == jnp.int64 else jnp.uint32
    ku = k.astype(udt)
    return jnp.stack([_compact(ku >> d, ndim,
                               sdt == jnp.int64).astype(sdt)
                      for d in range(ndim)], axis=1)


def _bitrev_low(k, ndim: int):
    """Child slot within the father oct: the host ``f_off*2 +
    (coords[:, d] & 1)`` fold over ascending d, read straight off the
    low interleaved key bits."""
    off = jnp.zeros_like(k)
    for d in range(ndim):
        off = off * 2 + ((k >> d) & 1)
    return off


def _map_coords(cc, bc_kinds, dims, ndim: int):
    """jnp port of tree.map_coords (static bc kinds / dims): mapped
    coords plus the per-dim 'crossed a reflecting face' flags."""
    outs, refls = [], []
    for d in range(ndim):
        n = int(dims[d])
        lo, hi = bc_kinds[d]
        x = cc[:, d]
        if lo == 0 and hi == 0:
            outs.append(jnp.mod(x, n))
            refls.append(jnp.zeros(x.shape, bool))
            continue
        below, above = x < 0, x >= n
        r = jnp.zeros(x.shape, bool)
        if lo == 1:
            x = jnp.where(below, -1 - x, x)
            r = r | below
        elif lo != 0:
            x = jnp.where(below, 0, x)
        if hi == 1:
            x = jnp.where(above, 2 * n - 1 - x, x)
            r = r | above
        elif hi != 0:
            x = jnp.where(above, n - 1, x)
        outs.append(jnp.clip(x, 0, n - 1))
        refls.append(r)
    return jnp.stack(outs, axis=1), jnp.stack(refls, axis=1)


def _find(sorted_keys, ks):
    """(clipped position, exact-hit) of ``ks`` in a sorted key array —
    the device half of ``Octree.lookup_keys``."""
    pos = jnp.searchsorted(sorted_keys, ks)
    pos = jnp.clip(pos, 0, sorted_keys.shape[0] - 1)
    return pos, sorted_keys[pos] == ks


def migrate_level(old_u, u_coarse, new_keys, old_keys, coarse_keys,
                  ncell_pad: int, ndim: int, bc_kinds: tuple,
                  dims: tuple, cfg, itype: int):
    """One level's regrid migration with maps derived on device.

    ``new_keys``/``old_keys``/``coarse_keys`` are sentinel-padded sorted
    key arrays (:func:`upload_keys`) of the new level, the old level and
    the new coarser level; ``dims`` are the lvl-1 cell counts per dim.
    Returns the migrated [ncell_pad, nvar] batch, bitwise identical to
    ``build_prolong_maps`` + ``_migrate_level``.

    Host-parked state (``offload.HostBuffer``, &AMR_PARAMS offload)
    composes: parked operands are fetched here, outside the jit, so the
    traced program always sees device arrays.
    """
    from ramses_tpu.amr.offload import as_device
    return _migrate_level_jit(as_device(old_u), as_device(u_coarse),
                              new_keys, old_keys, coarse_keys, ncell_pad,
                              ndim, bc_kinds, dims, cfg, itype)


@partial(jax.jit, static_argnames=("ncell_pad", "ndim", "bc_kinds",
                                   "dims", "cfg", "itype"))
def _migrate_level_jit(old_u, u_coarse, new_keys, old_keys, coarse_keys,
                       ncell_pad: int, ndim: int, bc_kinds: tuple,
                       dims: tuple, cfg, itype: int):
    ttd = 1 << ndim
    sent = _sent(new_keys.dtype)
    valid = new_keys < sent                       # real (non-pad) octs
    pos, kept = _find(old_keys, new_keys)
    kept = kept & valid
    f_pos, _ = _find(coarse_keys, new_keys >> ndim)
    f_cell = f_pos * ttd + _bitrev_low(new_keys, ndim)
    og = _decode(new_keys, ndim)                  # cell coords at lvl-1
    nb = []
    for d in range(ndim):
        cols = []
        for s in (-1, +1):
            nc = og.at[:, d].add(s)
            ncm, nrefl = _map_coords(nc, bc_kinds, dims, ndim)
            nkey = _encode(ncm, ndim)
            n_pos, found = _find(coarse_keys, nkey >> ndim)
            bad = ~found | nrefl.any(axis=1)
            cols.append(jnp.where(bad, f_cell,
                                  n_pos * ttd + _bitrev_low(nkey, ndim)))
        nb.append(jnp.stack(cols, axis=1))
    nb = jnp.stack(nb, axis=1)                    # [noct_pad, ndim, 2]

    rows = jnp.arange(ncell_pad)
    oi, j = rows // ttd, rows % ttd
    sgn_tab = jnp.asarray((cell_offsets(ndim) * 2 - 1).astype(np.float64),
                          dtype=u_coarse.dtype)   # [2^d, ndim]
    vals = K.interp_cells(u_coarse, f_cell[oi], nb[oi], sgn_tab[j], cfg,
                          itype=itype)
    copied = old_u[pos[oi] * ttd + j]
    return jnp.where(kept[oi][:, None], copied.astype(old_u.dtype),
                     jnp.where(valid[oi][:, None],
                               vals.astype(old_u.dtype), 0))
