"""Jitted device kernels for the AMR hydro sweep.

One level-step = interp (buffer prolongation) → stencil gather → unsplit
MUSCL-Hancock → refined-face flux zeroing → conservative update + coarse
flux-correction scatter, the whole of ``godfine1``
(``hydro/godunov_fine.f90:486-910``) as a single fused XLA program over the
level's oct batch instead of nvector chunks.
"""

from __future__ import annotations

from dataclasses import replace as dreplace
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ramses_tpu.amr import bitperm
from ramses_tpu.hydro import muscl
from ramses_tpu.hydro.core import HydroStatic
from ramses_tpu.hydro.timestep import cell_dt


def pow2_cube(shape) -> bool:
    """True when every dim equals the same power of two — the complete
    cubic-level case where flat↔dense is a bit permutation
    (:mod:`ramses_tpu.amr.bitperm`) instead of an index gather."""
    s0 = shape[0]
    return (s0 & (s0 - 1)) == 0 and all(s == s0 for s in shape)


def rows_to_dense(rows, inv_perm, shape):
    """Flat-order rows ``[ncell(+pad), *trailing]`` → dense
    ``[*shape, *trailing]``.  Bit-permutation transpose on cubic
    power-of-two levels (no gather — the TPU fast path); index gather
    through ``inv_perm`` otherwise."""
    if pow2_cube(shape):
        return bitperm.flat_to_dense(rows, shape[0].bit_length() - 1,
                                     len(shape))
    if inv_perm is None:
        raise ValueError(f"non-cubic complete level {shape} needs an "
                         "inv_perm index map")
    return rows[inv_perm].reshape(shape + rows.shape[1:])


def dense_to_rows(dense, perm, shape):
    """Dense ``[*shape, *trailing]`` → flat-order rows (inverse of
    :func:`rows_to_dense`)."""
    nd = len(shape)
    if pow2_cube(shape):
        return bitperm.dense_to_flat(dense, shape[0].bit_length() - 1, nd)
    if perm is None:
        raise ValueError(f"non-cubic complete level {shape} needs a "
                         "perm index map")
    ncell = 1
    for s in shape:
        ncell *= s
    return dense.reshape((ncell,) + dense.shape[nd:])[perm]


def _unsplit_fn(cfg):
    """Physics dispatch: the cfg class selects the sweep kernel family
    (hydro default; ``physics="rhd"`` → the SRHD set with the same
    low-face dt/dx-scaled flux convention)."""
    if getattr(cfg, "physics", "hydro") == "rhd":
        from ramses_tpu.rhd import sweeps
        return sweeps.unsplit
    return muscl.unsplit


def _cell_dt_fn(cfg):
    if getattr(cfg, "physics", "hydro") == "rhd":
        from ramses_tpu.rhd import sweeps
        return sweeps.cell_dt
    return cell_dt


def _flags_fn(cfg):
    if getattr(cfg, "physics", "hydro") == "rhd":
        from ramses_tpu.rhd import sweeps
        return sweeps.grad_flags
    return _grad_flags


@partial(jax.jit, static_argnames=("cfg", "itype"))
def interp_cells(u_coarse, cell_idx, nb_idx, sgn, cfg: HydroStatic,
                 itype: int = 1):
    """Prolongation values for requested fine cells.

    ``interpol_hydro`` with interpol_var=0 (conservative variables,
    ``hydro/interpol_hydro.f90:268-391``): fine = a0 + Σ_d w_d·(±0.5) with
    w from the chosen limiter on the father's face-neighbour differences.

    u_coarse: [ncell, nvar]; cell_idx: [ni]; nb_idx: [ni, ndim, 2];
    sgn: [ni, ndim] ±1.  Returns [ni, nvar].
    """
    a0 = u_coarse[cell_idx]                            # [ni, nvar]
    out = a0
    if itype == 0:
        return out
    for d in range(cfg.ndim):
        al = u_coarse[nb_idx[:, d, 0]]
        ar = u_coarse[nb_idx[:, d, 1]]
        dl = 0.5 * (a0 - al)                           # halved differences
        dr = 0.5 * (ar - a0)                           # (compute_limiter_minmod)
        if itype == 1:
            w = jnp.where(dl * dr <= 0.0, 0.0,
                          jnp.sign(dr) * jnp.minimum(jnp.abs(dl),
                                                     jnp.abs(dr)))
        elif itype == 3:
            w = 0.25 * (ar - al)                       # unlimited central
        else:  # itype 2: per-dim monotonized central (the reference's
            # corner-coupled limiter is approximated dimension-by-dimension)
            dc = 0.25 * (ar - al)
            lim = jnp.minimum(2.0 * jnp.abs(dl), 2.0 * jnp.abs(dr))
            w = jnp.where(dl * dr <= 0.0, 0.0,
                          jnp.sign(dc) * jnp.minimum(jnp.abs(dc), lim))
        out = out + w * (0.5 * sgn[:, d:d + 1])
    return out


def _gather_uloc(u_flat, interp_vals, stencil_src, vsgn, cfg: HydroStatic):
    """Build [nvar, 6^d..., noct] stencil batch from flat cells + interps.

    The oct axis is minor-most on purpose: TPU layouts tile the two
    minor dims to (8, 128), so a [..., 6, 6] minor layout would pad
    ~28x in HBM while [..., 6, noct] pads ~1.3x.
    """
    trash = jnp.zeros((1, cfg.nvar), u_flat.dtype)
    src = jnp.concatenate([u_flat, interp_vals, trash], axis=0)
    srcT = src.T                                       # [nvar, nrows]
    ul = srcT[:, stencil_src]                          # [nvar, noct, 6^d]
    if vsgn is not None:
        # reflecting boundaries: flip mirrored velocity components
        for d in range(cfg.ndim):
            flip = ((vsgn >> d) & 1).astype(u_flat.dtype)  # [noct, 6^d]
            s = 1.0 - 2.0 * flip
            ul = ul.at[1 + d].multiply(s)
    noct = ul.shape[1]
    ul = jnp.swapaxes(ul, 1, 2)                        # [nvar, 6^d, noct]
    return ul.reshape((cfg.nvar,) + (6,) * cfg.ndim + (noct,))


def _flat_cells(blk, ndim: int):
    """[2..., noct] per-cell block → flat [noct*2^d] row order."""
    noct = blk.shape[-1]
    return jnp.transpose(
        blk, (ndim,) + tuple(range(ndim))).reshape(noct * 2 ** ndim)


@partial(jax.jit, static_argnames=("cfg", "dx", "ret_flux"))
def level_sweep(u_flat, interp_vals, stencil_src, vsgn, ok_ref, gloc,
                dt, dx: float, cfg: HydroStatic, ret_flux: bool = False):
    """Full godfine1 for one level.

    Returns (du_flat [ncell, nvar], corr [noct, ndim, 2, nvar]) where
    corr[:, d, side] is the summed boundary flux (already ×dt/dx) to be
    scattered ∓/2^ndim into unrefined coarse neighbours.

    ``ret_flux``: additionally return the per-cell signed mass flux
    ``phi [ncell, ndim, 2]`` at each cell's (low, high) face — the MC
    gas-tracer capture of ``godunov_fine.f90:685-715`` (fluxes already
    ×dt/dx, refined faces zeroed) — served by BOTH branches (the
    Pallas kernel emits it as a third output).
    """
    ndim, nvar = cfg.ndim, cfg.nvar
    bcfg = dreplace(cfg, trailing_batch=True)
    uloc = _gather_uloc(u_flat, interp_vals, stencil_src, vsgn, cfg)
    noct = uloc.shape[-1]
    # [noct, 6^d] → [6..., noct]
    okl = ok_ref.T.reshape((6,) * ndim + (noct,))

    from ramses_tpu.hydro import pallas_oct
    if gloc is None and pallas_oct.available(cfg, noct, u_flat.dtype):
        # fused TPU oct-batch kernel (same physics, VMEM-resident);
        # self-gravity rides as the hierarchy's separate traced
        # half-kick, so gloc is None on every production path
        out_k = pallas_oct.oct_sweep(
            uloc, okl.astype(uloc.dtype), dt, cfg, dx,
            want_flux=ret_flux)
        du_k, corr_k = out_k[0], out_k[1]
        du_flat = jnp.transpose(
            du_k, (ndim + 1,) + tuple(range(1, ndim + 1)) + (0,)
        ).reshape(noct * 2 ** ndim, nvar)
        corr_out = jnp.transpose(corr_k, (3, 1, 2, 0))
        if not ret_flux:
            return du_flat, corr_out
        # phi [3, 2, 2,2,2, N] → flat [ncell, ndim, 2]
        phi_k = jnp.transpose(out_k[2], (5, 2, 3, 4, 0, 1)).reshape(
            noct * 2 ** ndim, ndim, 2)
        return du_flat, corr_out, phi_k

    flux, tmp = _unsplit_fn(cfg)(uloc, gloc, dt, (dx,) * ndim, bcfg)
    # flux[d]: [nvar, 6..., noct], defined at the LOW face of each cell.

    # Reset flux along direction at refined interfaces
    # (hydro/godunov_fine.f90:718-747): a face is zeroed when either
    # adjacent cell is refined — its contribution comes from level+1;
    # the reference zeroes the tmp (divu/eint-flux) faces the same way.
    fluxes = []
    tmps = []
    for d in range(ndim):
        keep = ~(okl | jnp.roll(okl, 1, axis=d))       # [6..., noct]
        fluxes.append(flux[d] * keep[None].astype(flux.dtype))
        if tmp is not None:
            tmps.append(tmp[d] * keep[None].astype(flux.dtype))
    # conservative update over the whole block (outer cells hold
    # wrapped garbage the interior never consumes), then the optional
    # dual-energy fix, then the interior extraction
    un_blk = muscl.apply_fluxes(uloc, jnp.stack(fluxes), bcfg)
    if tmp is not None and (cfg.pressure_fix or cfg.nener):
        un_blk = muscl.dual_energy_fix(uloc, un_blk, jnp.stack(tmps),
                                       dt, (dx,) * ndim, bcfg)
    interior = (slice(None),) + tuple(slice(2, 4) for _ in range(ndim))
    du = un_blk[interior] - uloc[interior]
    # [nvar, 2..., noct] → flat [noct*2^d, nvar]
    du_flat = jnp.transpose(
        du, (ndim + 1,) + tuple(range(1, ndim + 1)) + (0,)
    ).reshape(noct * 2 ** ndim, nvar)

    # boundary fluxes for the coarse correction: low face idx 2, high idx 4
    corr = []
    for d in range(ndim):
        f = fluxes[d]
        idx_lo = [slice(None)]
        idx_hi = [slice(None)]
        for d2 in range(ndim):
            if d2 == d:
                idx_lo.append(2)
                idx_hi.append(4)
            else:
                idx_lo.append(slice(2, 4))
                idx_hi.append(slice(2, 4))
        red = tuple(range(1, 1 + ndim - 1))
        lo = f[tuple(idx_lo)].sum(axis=red) if ndim > 1 else f[tuple(idx_lo)]
        hi = f[tuple(idx_hi)].sum(axis=red) if ndim > 1 else f[tuple(idx_hi)]
        corr.append(jnp.stack([lo, hi], axis=-1))      # [nvar, noct, 2]
    corr = jnp.stack(corr, axis=-2)                    # [nvar, noct, ndim, 2]
    corr = jnp.moveaxis(corr, 0, -1)                   # [noct, ndim, 2, nvar]
    if not ret_flux:
        return du_flat, corr
    # per-cell (low, high) face mass flux: cell at stencil position i
    # along d has its low face flux at index i, high face at i+1
    phis = []
    for d in range(ndim):
        f0 = fluxes[d][0]                              # [6..., noct] mass
        lo_ix = tuple(slice(2, 4) for _ in range(ndim))
        hi_ix = tuple(slice(3, 5) if dd == d else slice(2, 4)
                      for dd in range(ndim))
        phis.append(jnp.stack([_flat_cells(f0[lo_ix], ndim),
                               _flat_cells(f0[hi_ix], ndim)], axis=-1))
    phi = jnp.stack(phis, axis=-2)                     # [ncell, ndim, 2]
    return du_flat, corr, phi


# ---------------------------------------------------------------------------
# Blocked Morton tile sweep (gather-fused oct path)
# ---------------------------------------------------------------------------

_NG = 2                                   # tile halo width (MUSCL stencil)


def _gather_utile(u_flat, interp_vals, tile_src, tile_vsgn,
                  cfg: HydroStatic, td: int):
    """Compact blocked gather: [nvar, td..., ntile] from flat cells +
    interps — the gather-fused replacement for :func:`_gather_uloc`'s
    ~(3^d)x-duplicated per-oct stencil batch.  Each Morton-aligned tile
    holds its interior cells once plus a 2-cell halo, so HBM gather
    traffic scales with tile volume instead of stencil volume."""
    trash = jnp.zeros((1, cfg.nvar), u_flat.dtype)
    src = jnp.concatenate([u_flat, interp_vals, trash], axis=0)
    srcT = src.T                                       # [nvar, nrows]
    ut = srcT[:, tile_src]                             # [nvar, ntile, td^d]
    if tile_vsgn is not None:
        for d in range(cfg.ndim):
            flip = ((tile_vsgn >> d) & 1).astype(u_flat.dtype)
            ut = ut.at[1 + d].multiply(1.0 - 2.0 * flip)
    ntile = ut.shape[1]
    ut = jnp.swapaxes(ut, 1, 2)                        # [nvar, td^d, ntile]
    return ut.reshape((cfg.nvar,) + (td,) * cfg.ndim + (ntile,))


def _face_planes(fl, d, ndim: int, c: int):
    """Per-oct-face flux planes of masked flux ``fl`` [nvar, td..., ntile]
    along d: [nvar, c//2+1, c...(transverse, increasing-dim order),
    ntile] — positions _NG + 2k, transverse interior."""
    idx = [slice(None)]
    for dd in range(ndim):
        idx.append(slice(_NG, _NG + c + 1, 2) if dd == d
                   else slice(_NG, _NG + c))
    return jnp.moveaxis(fl[tuple(idx)], 1 + d, 1)


def _mass_planes(f0, d, ndim: int, c: int):
    """All c+1 per-cell-face planes of the mass flux ``f0``
    [td..., ntile] along d: [c+1, c...(transverse), ntile]."""
    idx = []
    for dd in range(ndim):
        idx.append(slice(_NG, _NG + c + 1) if dd == d
                   else slice(_NG, _NG + c))
    return jnp.moveaxis(f0[tuple(idx)], d, 0)


def _corr_from_planes(planes, d, ndim: int, c: int):
    """Per-oct boundary-flux sums from face planes: (lo, hi), each
    [nvar, (c//2)^ndim, ntile] flattened in global dim order — the same
    [nvar, 2, 2, ...] transverse reduction as :func:`level_sweep`."""
    o = c // 2
    nvar, ntile = planes.shape[0], planes.shape[-1]
    shape = [nvar, o + 1] + [o, 2] * (ndim - 1) + [ntile]
    g = planes.reshape(shape)
    cell_axes = [3 + 2 * i for i in range(ndim - 1)]
    g = jnp.moveaxis(g, cell_axes, tuple(range(1, ndim)))
    red = tuple(range(1, 1 + ndim - 1))
    s = g.sum(axis=red) if ndim > 1 else g
    # s: [nvar, o+1 (planes along d), o transverse dims..., ntile];
    # restore global dim order before flattening to oct slots
    def _oct_rows(x):
        x = jnp.moveaxis(x, 1, 1 + d)
        return x.reshape(nvar, o ** ndim, ntile)
    lo = jax.lax.slice_in_dim(s, 0, o, axis=1)
    hi = jax.lax.slice_in_dim(s, 1, o + 1, axis=1)
    return _oct_rows(lo), _oct_rows(hi)


@partial(jax.jit, static_argnames=("cfg", "dx", "shift", "ret_flux",
                                   "pallas_ok"))
def tile_sweep(u_flat, interp_vals, tile_src, tile_vsgn, tile_ok,
               cell_tile, cell_slot, oct_tile, oct_slot,
               dt, dx: float, cfg: HydroStatic, shift: int,
               ret_flux: bool = False, pallas_ok: bool = True):
    """Full godfine1 for one blocked partial level — the gather-fused
    replacement for :func:`level_sweep` (same return convention:
    du_flat [ncell, nvar], corr [noct, ndim, 2, nvar] [, phi
    [ncell, ndim, 2]]).  The 6^d-duplicated stencil batch is never
    materialized: the sweep runs on the compact [nvar, td..., ntile]
    tile batch (Pallas kernel on TPU, trailing-batch XLA fallback
    elsewhere), and du/corr/phi are reordered back to flat rows with
    small per-cell/per-oct gathers.

    ``pallas_ok=False`` forces the XLA tile formulation regardless of
    :func:`~ramses_tpu.hydro.pallas_oct.tile_available` — row-sharded
    meshes use it so GSPMD can partition the sweep (the two
    formulations are pinned bitwise-identical by tests)."""
    ndim, nvar = cfg.ndim, cfg.nvar
    c = 1 << (shift + 1)
    td = c + 2 * _NG
    ut = _gather_utile(u_flat, interp_vals, tile_src, tile_vsgn, cfg, td)
    ntile = ut.shape[-1]
    okl = tile_ok.T.reshape((td,) * ndim + (ntile,))

    from ramses_tpu.hydro import pallas_oct
    if pallas_ok and pallas_oct.tile_available(cfg, ntile, u_flat.dtype):
        out_k = pallas_oct.tile_sweep(ut, okl.astype(ut.dtype), dt, cfg,
                                      dx, shift, want_flux=ret_flux)
        du_t, corrp = out_k[0], out_k[1]
        planes = [corrp[:, d] for d in range(ndim)]
        mass = ([out_k[2][d] for d in range(ndim)] if ret_flux else None)
    else:
        bcfg = dreplace(cfg, trailing_batch=True)
        flux, tmp = _unsplit_fn(cfg)(ut, None, dt, (dx,) * ndim, bcfg)
        fluxes = []
        tmps = []
        for d in range(ndim):
            keep = ~(okl | jnp.roll(okl, 1, axis=d))
            fluxes.append(flux[d] * keep[None].astype(flux.dtype))
            if tmp is not None:
                tmps.append(tmp[d] * keep[None].astype(flux.dtype))
        un_blk = muscl.apply_fluxes(ut, jnp.stack(fluxes), bcfg)
        if tmp is not None and (cfg.pressure_fix or cfg.nener):
            un_blk = muscl.dual_energy_fix(ut, un_blk, jnp.stack(tmps),
                                           dt, (dx,) * ndim, bcfg)
        interior = (slice(None),) + (slice(_NG, _NG + c),) * ndim
        du_t = un_blk[interior] - ut[interior]
        planes = [_face_planes(fluxes[d], d, ndim, c) for d in range(ndim)]
        mass = ([_mass_planes(fluxes[d][0], d, ndim, c)
                 for d in range(ndim)] if ret_flux else None)

    # interior update → flat rows.  Pad cell rows carry slot c^d /
    # tile 0 (maps.py), which flattens one past the interior batch —
    # an appended zero column — so they come out exactly 0 with no
    # masking on the real-row dataflow.
    flat_idx = cell_slot * ntile + cell_tile
    du_src = jnp.concatenate(
        [du_t.reshape((nvar, c ** ndim * ntile)),
         jnp.zeros((nvar, 1), du_t.dtype)], axis=1)
    du_flat = du_src[:, flat_idx].T                    # [ncell_pad, nvar]

    # boundary fluxes → per-oct corr rows
    corr = []
    for d in range(ndim):
        lo, hi = _corr_from_planes(planes[d], d, ndim, c)
        lo_g = lo[:, oct_slot, oct_tile]
        hi_g = hi[:, oct_slot, oct_tile]
        corr.append(jnp.stack([lo_g, hi_g], axis=-1))  # [nvar, noct, 2]
    corr = jnp.stack(corr, axis=-2)                    # [nvar, noct, nd, 2]
    corr = jnp.moveaxis(corr, 0, -1)                   # [noct, nd, 2, nvar]
    if not ret_flux:
        return du_flat, corr

    # per-cell (low, high) face mass flux
    def _cell_rows(x, d):
        x = jnp.moveaxis(x, 0, d)                      # [c..., ntile]
        xf = jnp.concatenate([x.reshape(c ** ndim * ntile),
                              jnp.zeros((1,), x.dtype)])
        return xf[flat_idx]
    phis = []
    for d in range(ndim):
        phis.append(jnp.stack([_cell_rows(mass[d][:c], d),
                               _cell_rows(mass[d][1:c + 1], d)], axis=-1))
    phi = jnp.stack(phis, axis=-2)                     # [ncell, ndim, 2]
    return du_flat, corr, phi


@partial(jax.jit, static_argnames=("cfg", "err_grad", "floors", "shift"))
def tile_refine_flags(u_flat, interp_vals, tile_src, tile_vsgn,
                      cell_tile, cell_slot,
                      err_grad: Tuple[float, float, float],
                      floors: Tuple[float, float, float],
                      cfg: HydroStatic, shift: int):
    """Blocked-gather variant of :func:`refine_flags`: evaluates the same
    gradient criteria on the compact tile batch (the shared gather of
    the blocked sweep) and reorders to flat-cell rows [noct_pad, 2^d]."""
    nd = cfg.ndim
    c = 1 << (shift + 1)
    td = c + 2 * _NG
    ut = _gather_utile(u_flat, interp_vals, tile_src, tile_vsgn, cfg, td)
    ntile = ut.shape[-1]
    ok = _flags_fn(cfg)(ut, err_grad, floors, spatial0=0, cfg=cfg)
    interior = (slice(_NG, _NG + c),) * nd
    okc = jnp.concatenate([ok[interior].reshape(c ** nd * ntile),
                           jnp.zeros((1,), ok.dtype)])
    rows = okc[cell_slot * ntile + cell_tile]          # [ncell_pad]
    return rows.reshape(len(cell_slot) // 2 ** nd, 2 ** nd)


def dense_interior_update(up, okp, dt, dx: float, shape: Tuple[int, ...],
                          cfg: HydroStatic, ret_flux: bool = False):
    """Padded-halo interior update shared by the global-view dense sweep
    and the per-shard slab path (:mod:`ramses_tpu.parallel.dense_slab`).

    ``up``: ``[nvar, *(shape + 2*NGHOST)]`` ghost-padded state; ``okp``:
    optional refined-cell mask over the same padded box, ALREADY in the
    state dtype (1.0 = refined) — faces touching a refined cell get zero
    flux.  Returns ``du [nvar, *shape]`` (+ ``phi [*shape, ndim, 2]``
    per-cell (low, high) dt/dx-scaled face mass fluxes when
    ``ret_flux``).
    """
    from ramses_tpu.grid import boundary as bmod

    nd = cfg.ndim
    flux, tmp = _unsplit_fn(cfg)(up, None, dt, (dx,) * nd, cfg)
    if okp is not None:
        masked = []
        masked_tmp = []
        for d in range(nd):
            # arithmetic (1-ok)(1-ok_roll) instead of pred ~(ok|roll):
            # the pred→f32 convert of the bit-permuted mask is exactly
            # the op the SPMD partitioner could only reshard by full
            # rematerialization (MULTICHIP_r05 tail)
            keep = (1.0 - okp) * (1.0 - jnp.roll(okp, 1, axis=d))
            masked.append(flux[d] * keep[None])
            if tmp is not None:
                masked_tmp.append(tmp[d] * keep[None])
        flux = jnp.stack(masked)
        if tmp is not None:
            tmp = jnp.stack(masked_tmp)
    un = muscl.apply_fluxes(up, flux, cfg)
    if tmp is not None and (cfg.pressure_fix or cfg.nener):
        un = muscl.dual_energy_fix(up, un, tmp, dt, (dx,) * nd, cfg)
    du = bmod.unpad(un, nd, muscl.NGHOST) - bmod.unpad(up, nd,
                                                       muscl.NGHOST)
    if not ret_flux:
        return du
    g = muscl.NGHOST
    phis = []
    for d in range(nd):
        f0 = flux[d][0]                                # [*padded] mass
        lo_ix = tuple(slice(g, g + shape[dd]) for dd in range(nd))
        hi_ix = tuple(slice(g + 1, g + 1 + shape[dd]) if dd == d
                      else slice(g, g + shape[dd]) for dd in range(nd))
        phis.append(jnp.stack([f0[lo_ix], f0[hi_ix]], axis=-1))
    return du, jnp.stack(phis, axis=-2)                # [*shape, ndim, 2]


def pad_ok_dense(ok_dense, shape: Tuple[int, ...], bc, dtype, ng: int):
    """Dense-ravel refined mask → ghost-padded arithmetic mask in the
    state dtype (the convert happens BEFORE the pad/bit-permuted views,
    on the cleanly row-sharded array)."""
    okp = ok_dense.astype(dtype).reshape(shape)
    for d in range(len(shape)):
        mode = "wrap" if bc.faces[d][0].kind == 0 else "edge"
        padw = [(ng, ng) if d2 == d else (0, 0)
                for d2 in range(len(shape))]
        okp = jnp.pad(okp, padw, mode=mode)
    return okp


@partial(jax.jit, static_argnames=("cfg", "shape", "bc", "dx", "ret_flux"))
def dense_sweep(u_flat, inv_perm, perm, ok_dense, dt, dx: float,
                shape: Tuple[int, ...], bc, cfg: HydroStatic,
                ret_flux: bool = False):
    """Sweep for a COMPLETE level (covers the whole box) as a dense grid.

    The 6^d stencil gather duplicates each cell ~3^d times and its
    [..., 6, 6] minors tile terribly on TPU; a complete level needs
    neither ghost interpolation nor coarse corrections, so it runs the
    roll-based uniform kernel instead (``grid/uniform.py`` path) with
    refined-face flux zeroing.  Returns du over the flat level rows.

    ``ret_flux``: additionally return ``phi [ncell, ndim, 2]`` — the
    per-cell (low, high) face mass flux ×dt/dx in flat row order (MC
    gas-tracer capture) — served by BOTH branches (the fused kernel
    emits it as a second output).
    """
    from ramses_tpu.grid import boundary as bmod
    from ramses_tpu.hydro import pallas_muscl as pk

    nd, nvar = cfg.ndim, cfg.nvar
    ncell = 1
    for s in shape:
        ncell *= s
    ud = rows_to_dense(u_flat, inv_perm, shape)        # [*shape, nvar]
    ud = jnp.moveaxis(ud, -1, 0)                       # [nvar, *shape]
    if pk.kernel_available(cfg, shape, bc.faces, ud.dtype):
        # fused TPU kernel path (same physics, VMEM-resident pipeline);
        # refined-face flux zeroing rides in as the mask input, the
        # MC-tracer face-flux capture as a second kernel output
        ok = ok_dense.reshape(shape) if ok_dense is not None else None
        up, okp = pk.pad_xy(ud, bc, cfg, ok=ok)
        if ret_flux:
            un, phid = pk.fused_step_padded(up, dt, cfg, dx, shape,
                                            ok_pad=okp, want_flux=True)
        else:
            un = pk.fused_step_padded(up, dt, cfg, dx, shape, ok_pad=okp)
        du_rows = dense_to_rows(jnp.moveaxis(un - ud, 0, -1), perm, shape)
        if u_flat.shape[0] > ncell:
            du_rows = jnp.zeros_like(u_flat).at[:ncell].set(du_rows)
        if not ret_flux:
            return du_rows
        # phid [3, 2, *shape] → flat rows [ncell, ndim, 2]
        phi = dense_to_rows(jnp.moveaxis(phid, (0, 1), (-2, -1)),
                            perm, shape)
        if u_flat.shape[0] > ncell:
            phi = jnp.zeros((u_flat.shape[0], nd, 2),
                            phi.dtype).at[:ncell].set(phi)
        return du_rows, phi
    up = bmod.pad(ud, bc, cfg, muscl.NGHOST, dx=dx)
    okp = (pad_ok_dense(ok_dense, shape, bc, up.dtype, muscl.NGHOST)
           if ok_dense is not None else None)
    out = dense_interior_update(up, okp, dt, dx, shape, cfg,
                                ret_flux=ret_flux)
    du_dense = out[0] if ret_flux else out             # [nvar, *shape]
    du_rows = dense_to_rows(jnp.moveaxis(du_dense, 0, -1), perm, shape)
    if u_flat.shape[0] > ncell:
        du_rows = jnp.zeros_like(u_flat).at[:ncell].set(du_rows)
    if not ret_flux:
        return du_rows
    phi = dense_to_rows(out[1], perm, shape)           # [ncell, ndim, 2]
    if u_flat.shape[0] > ncell:
        phi = jnp.zeros((u_flat.shape[0], nd, 2),
                        phi.dtype).at[:ncell].set(phi)
    return du_rows, phi


@partial(jax.jit, static_argnames=("cfg", "shape", "bc", "err_grad",
                                   "floors", "dx"))
def dense_refine_flags(u_flat, inv_perm, perm,
                       err_grad: Tuple[float, float, float],
                       floors: Tuple[float, float, float],
                       shape: Tuple[int, ...], bc, cfg: HydroStatic,
                       dx: float = None):
    """Gradient refinement criteria for a complete level on the dense
    grid (same semantics as :func:`refine_flags`)."""
    from ramses_tpu.grid import boundary as bmod

    nd, nvar = cfg.ndim, cfg.nvar
    ncell = 1
    for s in shape:
        ncell *= s
    ud = jnp.moveaxis(rows_to_dense(u_flat, inv_perm, shape), -1, 0)
    up = bmod.pad(ud, bc, cfg, 1, dx=dx)
    ok = _flags_fn(cfg)(up, err_grad, floors, spatial0=0, cfg=cfg)
    ok = ok[tuple(slice(1, -1) for _ in range(nd))]    # interior
    flags_flat = dense_to_rows(ok, perm, shape)        # flat cell order
    return flags_flat.reshape(ncell // 2 ** nd, 2 ** nd)


@partial(jax.jit, static_argnames=("cfg",))
def scatter_corrections(unew_coarse, corr, corr_idx, cfg: HydroStatic):
    """Scatter ∓flux/2^ndim into unrefined coarse neighbour cells
    (``hydro/godunov_fine.f90:795-910``).  corr_idx == -1 → dropped."""
    ndim = cfg.ndim
    w = 1.0 / (2 ** ndim)
    idx = corr_idx.reshape(-1)                         # [noct*ndim*2]
    valid = idx >= 0
    safe = jnp.where(valid, idx, 0)
    # side 0 (low face of the fine oct = high face of the coarse cell): -F
    # side 1: +F   (u += F_low - F_high seen from the coarse cell)
    sign = jnp.tile(jnp.array([-1.0, 1.0], unew_coarse.dtype),
                    corr_idx.shape[0] * ndim)
    vals = corr.reshape(-1, cfg.nvar) * (w * sign * valid)[:, None]
    return unew_coarse.at[safe].add(vals.astype(unew_coarse.dtype))


@partial(jax.jit, static_argnames=("cfg",))
def scatter_corr_flux(phi_coarse, corr, corr_idx, cfg: HydroStatic):
    """Fold the fine level's boundary mass fluxes into the coarse
    neighbours' face slots of the MC-tracer capture ``phi``.

    A fine oct's faces coincide with its parent cell's faces, so the
    low-side corr value IS the mass flux through the unrefined coarse
    neighbour's HIGH face (and vice versa), scaled 1/2^ndim into coarse
    Δρ units exactly like :func:`scatter_corrections`.  The coarse
    sweep zeroed those faces (refined-adjacent), so this is the only
    writer."""
    ndim = cfg.ndim
    w = 1.0 / (2 ** ndim)
    for d in range(ndim):
        for side, slot in ((0, 1), (1, 0)):
            idx = corr_idx[:, d, side]
            valid = idx >= 0
            safe = jnp.where(valid, idx, 0)
            vals = corr[:, d, side, 0] * w * valid
            phi_coarse = phi_coarse.at[safe, d, slot].add(
                vals.astype(phi_coarse.dtype))
    return phi_coarse


@partial(jax.jit, static_argnames=("cfg",))
def restrict_upload(u_level, u_fine, ref_cell, son_oct, cfg: HydroStatic):
    """upload_fine: overwrite refined cells with the mean of their son
    oct's cells (``hydro/interpol_hydro.f90:5-100``)."""
    ndim = cfg.ndim
    twotondim = 2 ** ndim
    valid = ref_cell >= 0
    safe_cell = jnp.where(valid, ref_cell, 0)
    rows = (son_oct[:, None] * twotondim
            + jnp.arange(twotondim)[None, :])          # [nref, 2^d]
    mean = u_fine[rows].mean(axis=1)                   # [nref, nvar]
    cur = u_level[safe_cell]
    vals = jnp.where(valid[:, None], mean, cur)
    return u_level.at[safe_cell].set(vals.astype(u_level.dtype))


@partial(jax.jit, static_argnames=("cfg",))
def level_courant(u_flat, valid_cell, dx: float, cfg: HydroStatic,
                  fg=None):
    """Min CFL dt over the level's (valid) cells — ``courant_fine``.

    ``fg`` [ncell, ndim]: gravitational acceleration; enables the
    gravity-strength dt correction of ``cmpdt``
    (``hydro/godunov_utils.f90:100-110``) that keeps a collapsing
    self-gravitating cell from outrunning its own kick."""
    u = jnp.moveaxis(u_flat, -1, 0)                    # [nvar, ncell]
    grav = ([fg[:, d] for d in range(cfg.ndim)]
            if fg is not None else None)
    dtc = _cell_dt_fn(cfg)(u, grav, dx, cfg)
    dtc = jnp.where(valid_cell, dtc, jnp.inf)
    return jnp.minimum(cfg.courant_factor * dx / cfg.smallc, jnp.min(dtc))


@partial(jax.jit, static_argnames=("cfg", "err_grad", "floors"))
def refine_flags(u_flat, interp_vals, stencil_src, vsgn,
                 err_grad: Tuple[float, float, float],
                 floors: Tuple[float, float, float],
                 cfg: HydroStatic):
    """Per-cell gradient refinement criteria — ``hydro_refine``
    (``hydro/godunov_utils.f90:125-260``): relative two-sided differences
    of ρ, P, and Mach-normalized velocity over the 3^d neighbourhood.

    Returns bool flags [noct, 2^d] in flat-cell order.
    """
    uloc = _gather_uloc(u_flat, interp_vals, stencil_src, vsgn, cfg)
    nd = cfg.ndim
    # fields below are [6..., noct]: spatial axes 0..nd-1, oct axis last
    ok = _flags_fn(cfg)(uloc, err_grad, floors, spatial0=0, cfg=cfg)
    interior = tuple(slice(2, 4) for _ in range(nd))
    okc = ok[interior]                                 # [2..., noct]
    okc = jnp.moveaxis(okc, -1, 0)                     # [noct, 2...]
    return okc.reshape(okc.shape[0], 2 ** nd)


def two_sided_rel_err(f, floor, nd: int, spatial0: int):
    """Max-over-directions relative two-sided difference — the error
    metric of ``hydro_refine`` (``hydro/godunov_utils.f90:152-210``),
    shared by the hydro and SRHD flag kernels."""
    err = jnp.zeros_like(f)
    for d in range(nd):
        ax = spatial0 + d
        fl = jnp.roll(f, 1, axis=ax)
        fr = jnp.roll(f, -1, axis=ax)
        e1 = jnp.abs(fr - f) / (jnp.abs(fr) + jnp.abs(f) + floor)
        e2 = jnp.abs(f - fl) / (jnp.abs(f) + jnp.abs(fl) + floor)
        err = jnp.maximum(err, 2.0 * jnp.maximum(e1, e2))
    return err


def _grad_flags(uloc, err_grad, floors, spatial0: int, cfg: HydroStatic):
    """Shared gradient-criteria evaluation; ``uloc`` is [nvar, ...] with
    spatial axes starting at ``spatial0`` of the per-field arrays."""
    nd = cfg.ndim
    r = jnp.maximum(uloc[0], cfg.smallr)
    vels = [uloc[1 + d] / r for d in range(nd)]
    ek = sum(0.5 * r * v * v for v in vels)
    p = (cfg.gamma - 1.0) * (uloc[nd + 1] - ek)
    ok = jnp.zeros_like(r, dtype=bool)
    egd, egu, egp = err_grad
    fld, flu, flp = floors

    def two_sided(f, floor):
        return two_sided_rel_err(f, floor, nd, spatial0)

    if egd >= 0.0:
        ok = ok | (two_sided(r, fld) > egd)
    if egp >= 0.0:
        ok = ok | (two_sided(p, flp) > egp)
    if egu >= 0.0:
        c = jnp.sqrt(jnp.maximum(cfg.gamma * p / r, flu ** 2))
        for d in range(nd):
            v = vels[d]
            err = jnp.zeros_like(v)
            for dd in range(nd):
                ax = spatial0 + dd
                vl, vr = jnp.roll(v, 1, axis=ax), jnp.roll(v, -1, axis=ax)
                cl, cr = jnp.roll(c, 1, axis=ax), jnp.roll(c, -1, axis=ax)
                e1 = jnp.abs(vr - v) / (cr + c + jnp.abs(vr) + jnp.abs(v)
                                        + flu)
                e2 = jnp.abs(v - vl) / (c + cl + jnp.abs(v) + jnp.abs(vl)
                                        + flu)
                err = jnp.maximum(err, 2.0 * jnp.maximum(e1, e2))
            ok = ok | (err > egu)
    return ok
