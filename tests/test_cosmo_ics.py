"""Cosmological IC pipeline: grafic/Gadget readers, Zel'dovich particle
initialization, and linear growth through the PM solvers.

Oracle strategy (SURVEY.md §4 style): the IC writers are exact inverses
of the readers (round-trip bitwise); the physics oracle is linear
perturbation theory — in an EdS universe a single-mode density
perturbation must grow with D(a) ∝ a through the full PM + gravity
stack (``pm/init_part.f90`` + ``amr/init_time.f90`` conventions).
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from ramses_tpu.config import Params
from ramses_tpu.io import gadget as gio
from ramses_tpu.io import grafic as gf
from ramses_tpu.pm import init_part as ip
from ramses_tpu.pm.cosmology import Cosmology


def test_grafic_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    hdr = gf.GraficHeader(8, 8, 8, dx=1.5, astart=0.02, omega_m=1.0,
                          omega_v=0.0, h0=70.0)
    field = rng.standard_normal((8, 8, 8)).astype(np.float32)
    p = str(tmp_path / "ic_deltab")
    gf.write_grafic(p, hdr, field)
    h2, f2 = gf.read_grafic(p)
    assert (h2.np1, h2.np2, h2.np3) == (8, 8, 8)
    assert h2.dx == pytest.approx(1.5)
    assert h2.astart == pytest.approx(0.02)
    np.testing.assert_array_equal(f2, field)


def test_gadget_roundtrip(tmp_path):
    rng = np.random.default_rng(1)
    n = 64
    hdr = gio.GadgetHeader(npart=(0, n, 0, 0, 0, 0),
                           mass=(0, 0.1, 0, 0, 0, 0), time=0.05,
                           redshift=19.0, boxsize=10000.0, omega0=1.0,
                           omega_l=0.0, hubble=0.7)
    pos = rng.random((n, 3)) * 10000.0
    vel = rng.standard_normal((n, 3)) * 100.0
    ids = np.arange(n, dtype=np.uint32)
    p = str(tmp_path / "ic_gadget")
    gio.write_gadget(p, hdr, pos, vel, ids)
    h2, pos2, vel2, ids2 = gio.read_gadget(p)
    assert h2.boxsize == pytest.approx(10000.0)
    assert h2.time == pytest.approx(0.05)
    np.testing.assert_allclose(pos2, pos, rtol=1e-6)
    np.testing.assert_allclose(vel2, vel, rtol=1e-5, atol=1e-4)
    np.testing.assert_array_equal(ids2, ids)
    x, v, m, _ = ip.particles_from_gadget(p, None)
    assert x.shape == (n, 3) and (x >= 0).all() and (x < 1).all()
    assert m.sum() == pytest.approx(1.0)


def _single_mode_ics(dirname, n=32, amp=0.01, astart=0.02):
    """grafic directory holding δ = amp·cos(2πx) + matched Zel'dovich
    velocities (EdS)."""
    x = (np.arange(n) + 0.5) / n
    delta = (amp * np.cos(2 * np.pi * x))[:, None, None] \
        * np.ones((1, n, n))
    hdr = gf.GraficHeader(n, n, n, dx=100.0 / n, astart=astart,
                          omega_m=1.0, omega_v=0.0, h0=70.0)
    f = ip.fpeebl(astart, 1.0, 0.0, 0.0)
    gf.write_zeldovich_ics(dirname, delta, hdr, f)
    return hdr


def _cosmo_params(n_level, lmax=None, initdir=""):
    p = Params(ndim=3)
    p.run.cosmo = True
    p.run.pic = True
    p.run.poisson = True
    p.run.hydro = False
    p.amr.levelmin = n_level
    p.amr.levelmax = lmax if lmax is not None else n_level
    p.amr.boxlen = 1.0
    p.init.filetype = "grafic"
    p.init.initfile = [initdir]
    p.init.aexp_ini = 0.02
    p.raw = {"cosmo_params": {"omega_m": 1.0, "omega_l": 0.0,
                              "omega_b": 0.0, "h0": 70.0, "aexp": 0.02,
                              "boxlen_ini": 100.0}}
    return p


def _mode_amplitude(rho, n):
    """Amplitude of the cos(2πx) mode of a deposited density field."""
    prof = np.asarray(rho).mean(axis=(1, 2))
    x = (np.arange(n) + 0.5) / n
    return 2.0 * np.mean(prof * np.cos(2 * np.pi * x))


def test_zeldovich_particles_match_delta(tmp_path):
    """Depositing the displaced particles recovers δ at astart."""
    from ramses_tpu.pm import particles as pmod

    d = str(tmp_path / "ics")
    _single_mode_ics(d, n=32, amp=0.01)
    cosmo = Cosmology(omega_m=1.0, omega_l=0.0, omega_k=0.0,
                      aexp_ini=0.02)
    x, v, m, hdr = ip.particles_from_grafic(d, cosmo)
    assert len(x) == 32 ** 3
    assert m.sum() == pytest.approx(1.0)
    p = pmod.ParticleSet.make(jnp.asarray(x), jnp.asarray(v),
                              jnp.asarray(m))
    rho = pmod.deposit_cic(p, (32, 32, 32), 1.0 / 32)
    amp = _mode_amplitude(rho, 32)
    assert amp == pytest.approx(0.01, rel=0.05)


def test_linear_growth_uniform_pm(tmp_path):
    """EdS single mode grows as D ∝ a through the full PM stack."""
    from ramses_tpu.driver import Simulation
    from ramses_tpu.pm import particles as pmod

    d = str(tmp_path / "ics")
    n = 32
    _single_mode_ics(d, n=n, amp=0.01)
    p = _cosmo_params(5, initdir=d)
    a_end = 0.06
    tau_end = float(Cosmology.from_params(p).tau_of_aexp(a_end))
    p.output.tout = [tau_end]
    p.output.noutput = 1
    sim = Simulation(p, dtype=jnp.float64)
    sim.evolve(chunk=8)
    aexp = float(sim.cosmo.aexp_of_tau(sim.state.t))
    assert aexp == pytest.approx(a_end, rel=1e-2)
    rho = pmod.deposit_cic(sim.state.p, (n, n, n), 1.0 / n)
    amp = _mode_amplitude(rho, n)
    growth = amp / 0.01
    assert growth == pytest.approx(a_end / 0.02, rel=0.12)


@pytest.mark.slow
def test_cosmo_amr_growth(tmp_path):
    """The same oracle through the AMR driver (hierarchy PM + cosmo
    supercomoving stepping + m_refine quasi-Lagrangian criterion)."""
    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.driver import load_cosmo_ics
    from ramses_tpu.hydro.core import HydroStatic
    from ramses_tpu.pm import particles as pmod

    d = str(tmp_path / "ics")
    n = 16
    _single_mode_ics(d, n=n, amp=0.02)
    p = _cosmo_params(4, lmax=5, initdir=d)
    p.run.hydro = True           # AMR driver carries a gas field
    p.refine.m_refine = [4.0] * 10
    cosmo = Cosmology.from_params(p)
    parts, dense = load_cosmo_ics(p, cosmo, HydroStatic.from_params(p),
                                  (n, n, n))
    assert dense is None or dense.shape[1:] == (n, n, n)
    sim = AmrSim(p, dtype=jnp.float64, particles=parts,
                 init_dense_u=dense)
    assert sim.cosmo is not None
    a0 = sim.aexp_now()
    assert a0 == pytest.approx(0.02, rel=0.05)
    amp0 = _mode_amplitude(pmod.deposit_cic(sim.p, (n, n, n), 1.0 / n), n)
    a_end = 0.05
    tau_end = float(sim.cosmo.tau_of_aexp(a_end))
    sim.evolve(tau_end, nstepmax=400)
    assert sim.aexp_now() == pytest.approx(a_end, rel=0.02)
    rho = pmod.deposit_cic(sim.p, (n, n, n), 1.0 / n)
    growth = _mode_amplitude(rho, n) / amp0
    assert growth == pytest.approx(a_end / 0.02, rel=0.2)


def test_grafic_tools_roundtrip(tmp_path):
    """degrade/extract/center over a synthetic grafic set: block means,
    window offsets in the header, periodic recentering."""
    from ramses_tpu.io import grafic as gr
    from ramses_tpu.utils.grafic_tools import center, degrade, extract, main

    rng = np.random.default_rng(5)
    n = 16
    hdr = gr.GraficHeader(n, n, n, dx=0.5, astart=0.02, omega_m=0.3,
                          omega_v=0.7, h0=70.0)
    indir = tmp_path / "ic"
    indir.mkdir()
    fields = {}
    for name in ("ic_deltab", "ic_velcx"):
        arr = rng.standard_normal((n, n, n)).astype(np.float32)
        gr.write_grafic(str(indir / name), hdr, arr)
        fields[name] = arr

    deg = tmp_path / "deg"
    assert degrade(str(indir), str(deg)) == 2
    h2, small = gr.read_grafic(str(deg / "ic_deltab"))
    assert small.shape == (8, 8, 8) and h2.dx == 1.0
    want = fields["ic_deltab"].reshape(8, 2, 8, 2, 8, 2).mean((1, 3, 5))
    np.testing.assert_allclose(small, want, rtol=1e-6)

    ext = tmp_path / "ext"
    assert extract(str(indir), str(ext), (4, 0, 2), (8, 8, 8)) == 2
    h3, sub = gr.read_grafic(str(ext / "ic_velcx"))
    np.testing.assert_array_equal(sub, fields["ic_velcx"][4:12, 0:8,
                                                          2:10])
    assert h3.x1o == hdr.x1o + 4 * hdr.dx and h3.x3o == 2 * hdr.dx

    cen = tmp_path / "cen"
    assert center(str(indir), str(cen), (0.0, 0.0, 0.0)) == 2
    _h4, rolled = gr.read_grafic(str(cen / "ic_deltab"))
    np.testing.assert_array_equal(rolled[8, 8, 8],
                                  fields["ic_deltab"][0, 0, 0])
    # CLI smoke
    assert main(["degrade", str(indir), str(tmp_path / "d2")]) == 0


@pytest.mark.slow
def test_lightcone_emission_during_cosmo_run(tmp_path, monkeypatch):
    """&RUN_PARAMS lightcone: each coarse step emits the comoving shell
    swept since the previous one (amr/light_cone.f90 output_cone role);
    shells chain without gaps and carry velocities + emission epochs."""
    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.driver import load_cosmo_ics
    from ramses_tpu.hydro.core import HydroStatic

    d = str(tmp_path / "ics")
    n = 16
    _single_mode_ics(d, n=n, amp=0.02)
    p = _cosmo_params(4, lmax=4, initdir=d)
    p.run.hydro = True
    p.run.lightcone = True
    p.lightcone.zmax_cone = 1000.0          # the whole run emits
    p.lightcone.thetay_cone = 90.0          # full sky
    p.lightcone.thetaz_cone = 90.0
    p.output.output_dir = str(tmp_path)
    cosmo = Cosmology.from_params(p)
    parts, dense = load_cosmo_ics(p, cosmo, HydroStatic.from_params(p),
                                  (n, n, n))
    sim = AmrSim(p, dtype=jnp.float64, particles=parts,
                 init_dense_u=dense)
    tau_end = float(sim.cosmo.tau_of_aexp(0.03))
    sim.evolve(tau_end, nstepmax=6)
    import glob
    cones = sorted(glob.glob(str(tmp_path / "cone_*.npz")))
    assert len(cones) >= 2
    r_ranges = []
    for c in cones:
        z = np.load(c)
        assert z["pos"].shape == z["vel"].shape
        assert len(z["r"]) == len(z["a_emit"]) == len(z["pos"])
        # emission epochs are earlier for more distant particles
        if len(z["r"]) > 3:
            o = np.argsort(z["r"])
            assert z["a_emit"][o][0] >= z["a_emit"][o][-1] - 1e-12
        r_ranges.append((z["r"].min(), z["r"].max()))
    # consecutive shells tile the lookback distance (later steps emit
    # NEARER shells), with no overlap beyond roundoff
    for (lo1, hi1), (lo0, hi0) in zip(r_ranges[1:], r_ranges[:-1]):
        assert hi1 <= lo0 + 1e-8


@pytest.mark.slow
@pytest.mark.parametrize("name", ["mergertree.nml", "cosmo_gal.nml"])
def test_shipped_cosmo_namelists_run_through_cli(name, tmp_path,
                                                 monkeypatch):
    """The grafic-IC production namelists (mergertree.nml DM-only +
    clumpfind/unbinding/mergertree chain; cosmo_gal.nml hydro + SF +
    feedback + cooling) run through the CLI against generated ICs —
    the same coverage contract as test_namelist_suite for the
    self-contained configs (cosmo.nml's siblings)."""
    import os
    import re

    from ramses_tpu.__main__ import main

    nmldir = os.path.join(os.path.dirname(__file__), "..", "namelists")
    txt = open(os.path.join(nmldir, name)).read()
    # shrink to the CPU-host budget: 16^3 ICs, 2 coarse steps
    txt = re.sub(r"levelmin=\d+", "levelmin=4", txt)
    txt = re.sub(r"levelmax=\d+", "levelmax=5", txt)
    txt = txt.replace("&RUN_PARAMS", "&RUN_PARAMS\nnstepmax=2", 1)
    txt = re.sub(r"aout=[0-9.,]+", "aout=1.0", txt)
    txt = re.sub(r"noutput=\d+", "noutput=1", txt)
    dst = str(tmp_path / name)
    open(dst, "w").write(txt)
    _single_mode_ics(str(tmp_path / "grafic_files"), n=16, amp=0.02)
    monkeypatch.chdir(tmp_path)
    assert main([dst, "--ndim", "3", "--dtype", "float64"]) == 0
    outs = [d for d in os.listdir(tmp_path) if d.startswith("output_")]
    assert outs, f"{name}: no snapshot written"
    if name == "mergertree.nml":
        # the in-run clump pass left its table next to the snapshot
        files = os.listdir(os.path.join(tmp_path, outs[0]))
        assert any(f.startswith("clump_") for f in files), files
