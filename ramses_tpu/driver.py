"""Simulation driver: config → initial state → time loop → outputs.

The equivalent of ``program ramses → adaptive_loop`` (``amr/ramses.f90:13``,
``amr/adaptive_loop.f90:79-230``) for the single-level path: host keeps
wall-clock/output bookkeeping; device advances in fused multi-step chunks.
"""

from __future__ import annotations

import time
from contextlib import nullcontext
from dataclasses import dataclass
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ramses_tpu.config import Params, load_params
from ramses_tpu.grid import boundary as bmod
from ramses_tpu.grid.uniform import UniformGrid, run_steps, step
from ramses_tpu.hydro.core import HydroStatic
from ramses_tpu.init.regions import condinit
from ramses_tpu.pm.coupling import PMSpec, run_steps_pm, total_density
from ramses_tpu.pm.cosmology import Cosmology
from ramses_tpu.pm.particles import ParticleSet
from ramses_tpu.poisson.coupling import GravitySpec, gravity_field
from ramses_tpu.telemetry import make_telemetry, sim_run_info
from ramses_tpu.telemetry import screen as telemetry_screen


@dataclass
class SimState:
    u: jax.Array
    t: float = 0.0
    nstep: int = 0
    dt: float = 0.0
    iout: int = 1  # next output slot (1-based, like the reference)
    f: Optional[jax.Array] = None  # gravity field [ndim, *sp] (poisson)
    p: Optional[ParticleSet] = None
    dt_old: float = 0.0            # previous step (split particle kick)


def load_cosmo_ics(params, cosmo, cfg, shape):
    """(ParticleSet, gas u [nvar, *shape] | None) from the namelist's
    ``initfile``/``filetype`` (``amr/init_time.f90:303-414`` init_file)."""
    from ramses_tpu.pm import init_part as ip

    path = params.init.initfile[0]
    want_gas = bool(params.run.hydro)
    if params.init.filetype == "grafic":
        x, v, m, ghdr = ip.particles_from_grafic(
            path, cosmo, omega_b=(cosmo.omega_b if want_gas else None))
        u0 = None
        if want_gas:
            dense, _ = ip.baryons_from_grafic(path, cosmo, cfg.gamma,
                                              cosmo.omega_b)
            if dense.shape[1:] != tuple(shape):
                raise ValueError(
                    f"grafic grid {dense.shape[1:]} != run grid {shape} "
                    "(levelmin must match the IC resolution)")
            u0 = np.zeros((cfg.nvar,) + tuple(shape))
            u0[:dense.shape[0]] = dense
        if abs(ghdr.astart - cosmo.aexp_ini) > 1e-3 * ghdr.astart:
            import warnings
            warnings.warn(f"grafic astart={ghdr.astart} != namelist "
                          f"aexp_ini={cosmo.aexp_ini}; file wins for "
                          "displacements, namelist for the time axis")
    else:
        x, v, m, _ = ip.particles_from_gadget(path, cosmo)
        u0 = None
    p = ParticleSet.make(jnp.asarray(x), jnp.asarray(v), jnp.asarray(m))
    return p, u0


class Simulation:
    """Single-level simulation (SURVEY.md §7 stage 2).

    Resolution is ``2**levelmin`` per dimension scaled by nx/ny/nz coarse
    cells, cell size ``boxlen / 2**levelmin`` in user units — matching the
    reference's fully-refined base mesh.
    """

    def __init__(self, params: Params, dtype=jnp.float32,
                 particles: Optional[ParticleSet] = None):
        from ramses_tpu import patch
        patch.maybe_install_from_params(params)
        self.params = params
        if getattr(params.hydro, "difmag", 0.0):
            import warnings
            warnings.warn("HYDRO_PARAMS difmag requested but not yet "
                          "implemented in this solver; running without.")
        self.cfg = HydroStatic.from_params(params)
        lmin = params.amr.levelmin
        n = 2 ** lmin
        base = [params.amr.nx, params.amr.ny, params.amr.nz][:params.ndim]
        shape = tuple(b * n for b in base)
        self.dx = params.amr.boxlen / n
        self.bc = bmod.BoundarySpec.from_params(params)
        self.grid = UniformGrid(cfg=self.cfg, shape=shape, dx=self.dx,
                                bc=self.bc)
        self.pspec = PMSpec.from_params(params)
        self.cosmo = (Cosmology.from_params(params) if params.run.cosmo
                      else None)
        # SF/sink specs early: the particle-lane budget below needs to
        # know whether the run keeps creating particles
        from ramses_tpu.pm.sinks import SinkSet, SinkSpec
        from ramses_tpu.pm.star_formation import SfSpec
        self.sf_spec = SfSpec.from_params(params)
        self.sink_spec = SinkSpec.from_params(params)
        # cosmological IC files (grafic/gadget): particles + baryons
        # (init_part.f90 / init_flow_fine.f90 'file' branches)
        u0 = None
        if (self.cosmo is not None and params.init.initfile
                and params.init.filetype in ("grafic", "gadget")
                and particles is None):
            particles, u0 = load_cosmo_ics(params, self.cosmo, self.cfg,
                                           shape)
        if u0 is None:
            u0 = condinit(shape, self.dx, params, self.cfg)
        self.state = SimState(u=jnp.asarray(u0, dtype=dtype))
        if self.pspec.enabled:
            from ramses_tpu.pm.particles import lane_headroom
            # pic without IC particles: an empty set whose lane budget
            # must leave room for SF/sink creation (a 1-lane set would
            # silently drop every new star)
            grows = self.sf_spec.enabled or self.sink_spec.enabled
            self.state.p = particles if particles is not None else \
                ParticleSet.make(jnp.zeros((0, params.ndim)),
                                 jnp.zeros((0, params.ndim)),
                                 jnp.zeros((0,)),
                                 nmax=lane_headroom(params, grows) or 1)
        self.gspec = GravitySpec.from_params(params)
        box_periodic = all(f.kind == bmod.PERIODIC
                           for pair in self.bc.faces for f in pair)
        if not box_periodic:
            if self.pspec.enabled:
                # the uniform PM stepper (pm/coupling.run_steps_pm)
                # wraps drift and CIC indices periodically — an open box
                # would teleport escapers to the far wall (gravity on or
                # off makes no difference to the drift)
                raise NotImplementedError(
                    "uniform-grid particles require a periodic box; "
                    "use the AMR driver for open-box PM runs")
            if self.cosmo is not None:
                raise NotImplementedError(
                    "cosmology requires a periodic box")
            if self.gspec.enabled and self.gspec.gravity_type == 0 \
                    and any(f.kind == bmod.REFLECTING
                            for pair in self.bc.faces for f in pair):
                raise NotImplementedError(
                    "self-gravity with reflecting walls is unsupported "
                    "(isolated solve covers outflow/inflow boxes)")
        if self.gspec.enabled:
            # initial force so the first -0.5dt "un-kick" cancels exactly
            # (the reference's nstep==0 save_phi_old, amr/amr_step.f90:260);
            # cosmology solves with the supercomoving source coefficient
            # 1.5*omega_m*aexp, not 4pi
            rho0 = total_density(self.pspec, self.state.u, self.state.p,
                                 shape, self.dx)
            fourpi0 = (1.5 * self.cosmo.omega_m * self.cosmo.aexp_ini
                       if self.cosmo is not None else None)
            self.state.f = gravity_field(self.gspec, rho0, self.dx, fourpi0)
        elif self.pspec.enabled or self.cosmo is not None:
            fdt = (jnp.float64 if jax.config.jax_enable_x64
                   else jnp.float32)
            self.state.f = jnp.zeros((params.ndim,) + shape, fdt)
        if self.cosmo is not None:
            self.state.t = self.cosmo.tau_ini
            # aexp-ladder outputs: convert aout -> conformal time
            if params.output.aout:
                taus = [float(self.cosmo.tau_of_aexp(a))
                        for a in params.output.aout
                        if a <= 1.0]
                params.output.tout = sorted(set(params.output.tout + taus))
                params.output.noutput = len(params.output.tout)
        # cooling microphysics (&COOLING_PARAMS → tables at this epoch)
        self.cool_tables = None
        self.cool_spec = None
        if params.cooling.cooling:
            from ramses_tpu.hydro.cooling import CoolingSpec, build_tables
            from ramses_tpu.units import units as units_fn
            un = units_fn(params, cosmo=self.cosmo,
                          aexp=(self.cosmo.aexp_ini if self.cosmo else 1.0))
            self.cool_spec = CoolingSpec.from_params(params, un)
            c = params.cooling
            self.cool_tables = build_tables(
                aexp=(self.cosmo.aexp_ini if self.cosmo else 1.0),
                J21=float(c.J21), a_spec=float(c.a_spec),
                z_reion=float(c.z_reion),
                haardt_madau=bool(c.haardt_madau))
            if (self.pspec.enabled or self.gspec.enabled
                    or self.cosmo is not None):
                import warnings
                warnings.warn("cooling is wired into the pure-hydro path "
                              "only for now; gravity/PM runs ignore it")
        # star formation / feedback / sinks (coarse-step cadence passes)
        from ramses_tpu.units import units as units_fn
        self.units = units_fn(params, cosmo=self.cosmo,
                              aexp=(self.cosmo.aexp_ini if self.cosmo
                                    else 1.0))
        self.sinks = (SinkSet.empty(params.ndim)
                      if self.sink_spec.enabled else None)
        self._sf_rng = np.random.default_rng(1234)
        self._next_star_id = 1
        # turbulence forcing (&TURB_PARAMS)
        from ramses_tpu.turb.forcing import TurbForcing, TurbSpec
        self.turb_spec = TurbSpec.from_params(params)
        self.turb = (TurbForcing(shape, self.turb_spec)
                     if self.turb_spec.enabled else None)
        # radiative transfer in the driver (rt=.true.): subcycled M1 +
        # thermochemistry against the live gas (amr_step.f90:594-672)
        self.rt = None
        if params.run.rt:
            from ramses_tpu.rt.coupling import RtCoupled
            from ramses_tpu.units import units as units_fn
            self.rt = RtCoupled(params, self.grid,
                                units_fn(params, cosmo=self.cosmo),
                                self.state.u)
        if self.sf_spec.enabled and not self.pspec.enabled:
            import dataclasses as _dc
            self.pspec = _dc.replace(self.pspec, enabled=True)
            if self.state.p is None:
                from ramses_tpu.pm.particles import lane_headroom
                self.state.p = ParticleSet.make(
                    jnp.zeros((0, params.ndim)),
                    jnp.zeros((0, params.ndim)), jnp.zeros((0,)),
                    nmax=lane_headroom(params, True))
        # &MOVIE_PARAMS on-the-fly frames (amr/movie.f90)
        from ramses_tpu.io.movie import MovieWriter
        self.movie, self.movie_imov = MovieWriter.from_params(params)
        if self.movie is not None:
            self._movie_next = 0
        self.output_times = list(params.output.tout[:params.output.noutput])
        self.on_output: Optional[Callable] = None
        # perf accounting (mus/pt of adaptive_loop.f90:204-212)
        self.cell_updates = 0
        self.wall_s = 0.0
        # structured run telemetry (&OUTPUT_PARAMS telemetry=; the
        # shared no-op NULL when off — zero-overhead contract)
        self.telemetry = make_telemetry(params)
        # in-run fault recovery (&RUN_PARAMS max_step_retries) + the
        # deterministic fault-injection harness (fault_inject)
        from ramses_tpu.resilience.faultinject import FaultInjector
        from ramses_tpu.resilience.stepguard import StepGuard
        self._sguard = StepGuard.from_params(params,
                                             telemetry=self.telemetry)
        self._fault = FaultInjector.from_params(params)
        # hang watchdog (&RUN_PARAMS *_deadline_s): None when every
        # deadline is unset — evolve() then skips the guard entirely
        from ramses_tpu.resilience.watchdog import Watchdog
        self._wd = Watchdog.from_params(params, telemetry=self.telemetry)

    @property
    def nstep(self) -> int:
        return int(self.state.nstep)

    @property
    def t(self) -> float:
        return float(self.state.t)

    @property
    def tend(self) -> float:
        if self.output_times:
            return self.output_times[-1]
        return float("inf")

    def evolve(self, chunk: int = 16, verbose: bool = False, guard=None):
        """Run to the final output time, firing outputs on the way.
        ``guard``: optional :class:`ramses_tpu.utils.ops.OpsGuard`
        (signal dumps, stop_run file, walltime watchdog)."""
        st = self.state
        nstepmax = self.params.run.nstepmax
        telem = self.telemetry
        if telem.enabled:
            telem.run_info.update(sim_run_info(self))
        from ramses_tpu import patch
        if patch.hook("source") is not None:
            # the source hook is documented at coarse-step cadence
            # (patch.py): fused multi-step chunks would hand it one
            # aggregated ~chunk*dt — run step-at-a-time instead
            chunk = 1
        # Time is integrated in f64 (f32 if x64 is disabled) regardless of
        # the state dtype: with a bf16 state, t += dt would stall once
        # dt < eps(t) and the run would spin to nstepmax.
        tdtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        for tout in self.output_times[st.iout - 1:]:
            # sign-safe tolerance: cosmology runs in (negative) conformal
            # time, so a relative factor on tout would flip direction
            ttol = 1e-12 * (abs(tout) + 1.0)
            while st.t < tout - ttol and st.nstep < nstepmax:
                if guard is not None and not guard.check():
                    return st
                n = min(chunk, nstepmax - st.nstep)
                if self.movie is not None:
                    # fused chunks may not run past the movie cadence
                    # (frames sample at chunk boundaries)
                    n = min(n, self.movie_imov)
                if self._fault is not None:
                    # pending step-indexed faults must land exactly at
                    # their target step, not at a chunk boundary
                    n = self._fault.clamp_window(int(st.nstep), n)
                t_before = st.t
                if self.rt is not None and self.params.run.static:
                    # frozen gas: pure RT evolution to the output time
                    # (the reference's static Stromgren tests)
                    st.u = self.rt.advance(st.u, tout - st.t)
                    st.t = tout
                    st.nstep += 1
                    if self.movie is not None \
                            and st.nstep >= self._movie_next:
                        self.movie.emit(self)
                        self._movie_next = st.nstep + self.movie_imov
                    continue
                # redo-step guard: on the plain-hydro dispatch (no
                # donation — these are live references, not copies) the
                # pre-step state is retained so a non-finite window can
                # roll back; pm/cool scans expose no dt_scale hook and
                # rely on OpsGuard's trap instead
                plain = not (self.pspec.enabled or self.gspec.enabled
                             or self.cosmo is not None
                             or self.cool_tables is not None)
                prev = ((st.u, st.t, st.nstep, st.dt_old)
                        if self._sguard is not None and plain else None)
                if self._fault is not None:
                    self._fault.maybe_nan(self)
                t0 = time.perf_counter()
                hist = None
                # the whole dispatch + blocking fetch runs under the
                # step deadline (first window: compile deadline) —
                # nullcontext when the watchdog is off keeps this path
                # fetch-identical to the unguarded one
                with (self._wd.guard("step") if self._wd is not None
                        else nullcontext()):
                    if self._fault is not None:
                        self._fault.maybe_hang(int(st.nstep))
                    if (self.pspec.enabled or self.gspec.enabled
                            or self.cosmo is not None):
                        u, st.p, st.f, t, dt_old, ndone = run_steps_pm(
                            self.grid, self.gspec, self.pspec, st.u,
                            st.p, st.f, jnp.asarray(st.t, tdtype),
                            jnp.asarray(tout, tdtype),
                            jnp.asarray(st.dt_old, tdtype), n,
                            cosmo=self.cosmo)
                        st.dt_old = float(dt_old)
                    elif self.cool_tables is not None:
                        from ramses_tpu.grid.uniform import run_steps_cool
                        u, t, ndone = run_steps_cool(
                            self.grid, st.u, jnp.asarray(st.t, tdtype),
                            jnp.asarray(tout, tdtype), n,
                            self.cool_tables, self.cool_spec)
                    elif telem.enabled:
                        # instrumented run: the scan additionally stacks
                        # per-step (t, dt) so the event log gets one
                        # record per coarse step from this single
                        # summary fetch — the chunk stays one device
                        # program
                        u, t, ndone, hist = run_steps(
                            self.grid, st.u, jnp.asarray(st.t, tdtype),
                            jnp.asarray(tout, tdtype), n, trace=True)
                    else:
                        u, t, ndone = run_steps(
                            self.grid, st.u, jnp.asarray(st.t, tdtype),
                            jnp.asarray(tout, tdtype), n)
                    u.block_until_ready()
                    ndone = int(ndone)
                wall = time.perf_counter() - t0
                self.wall_s += wall
                st.u, st.t, st.nstep = u, float(t), st.nstep + ndone
                if self._wd is not None:
                    self._wd.note(nstep=st.nstep, t=st.t)
                self.cell_updates += ndone * self.grid.ncell
                if prev is not None and not self._sguard.ok(st.t):
                    # non-finite window: roll back and redo at halved
                    # dt (raises StepRetryExhausted after the ladder)
                    ndone = self._retry_window(prev, tout, tdtype)
                    hist = None
                if telem.enabled and ndone:
                    if hist is not None:
                        ts, dts = jax.device_get(hist)
                        telem.record_chunk(self, ts[:ndone], dts[:ndone],
                                           ndone, wall,
                                           nstep_end=st.nstep)
                    else:
                        # pm/cool scans don't expose per-step history:
                        # one aggregate record per dispatch
                        telem.record_step(
                            self, dt=(st.t - t_before) / ndone,
                            wall_s=wall, steps=ndone, t=st.t,
                            nstep=st.nstep, chunked=ndone)
                self._source_passes(st.t - t_before)
                if self.rt is not None and st.t > t_before:
                    st.u = self.rt.advance(st.u, st.t - t_before)
                if self.movie is not None \
                        and st.nstep >= self._movie_next:
                    self.movie.emit(self)
                    self._movie_next = st.nstep + self.movie_imov
                if verbose:
                    print(telemetry_screen.step_line(
                        self, dt=((st.t - t_before) / ndone
                                  if ndone else None), chunk=ndone))
                if ndone == 0:
                    break
            if st.t < tout - ttol:
                break  # budget exhausted before this output time: no dump
            if self.on_output is not None:
                self.on_output(self, st.iout)
            st.iout += 1
        return st

    def _source_passes(self, dt_chunk: float):
        """Coarse-step-cadence source terms: star formation, SN feedback,
        sink creation/accretion/merging/motion (``amr_step`` order
        ``:369-380,493,549-567``)."""
        if dt_chunk <= 0.0:
            return
        st = self.state
        if self.turb is not None:
            from ramses_tpu.turb.forcing import apply_forcing
            self.turb.update(dt_chunk)
            acc = self.turb.acceleration()
            st.u = apply_forcing(st.u, acc, dt_chunk,
                                 self.turb_spec.turb_min_rho)
        if self.sf_spec.enabled:
            from ramses_tpu.pm.star_formation import (kinetic_feedback,
                                                      star_formation,
                                                      thermal_feedback)
            u_np = np.asarray(st.u, dtype=np.float64)
            u_np, p2, self._next_star_id = star_formation(
                u_np, st.p, self._sf_rng, self.sf_spec, self.units,
                self.dx, st.t, dt_chunk, self._next_star_id)
            # f_w > 0 selects the mass-loaded kinetic wind scheme
            # (feedback.f90's f_w branch); otherwise thermal dumps
            if self.sf_spec.f_w > 0:
                u_np, p2 = kinetic_feedback(u_np, p2, self.sf_spec,
                                            self.units, self.dx, st.t,
                                            bc=self.bc)
            else:
                u_np, p2 = thermal_feedback(u_np, p2, self.sf_spec,
                                            self.units, self.dx, st.t)
            st.u = jnp.asarray(u_np, st.u.dtype)
            st.p = p2
        if self.sinks is not None:
            from ramses_tpu.pm.sinks import (accrete, create_sinks,
                                             drift_kick, merge_sinks)
            u_np = np.asarray(st.u, dtype=np.float64)
            u_np, self.sinks = create_sinks(
                u_np, self.sinks, self.sink_spec, self.units, self.dx,
                st.t, self.cfg.gamma)
            u_np, self.sinks = accrete(
                u_np, self.sinks, self.sink_spec, self.units, self.dx,
                dt_chunk, self.cfg.gamma)
            self.sinks = merge_sinks(self.sinks, self.sink_spec, self.dx)
            self.sinks = drift_kick(self.sinks, st.f, self.dx, dt_chunk,
                                    self.params.amr.boxlen,
                                    spec=self.sink_spec,
                                    units=self.units)
            st.u = jnp.asarray(u_np, st.u.dtype)
        from ramses_tpu import patch
        user_source = patch.hook("source")
        if user_source is not None:
            # AFTER the stock passes, like the AMR driver — a hook that
            # post-processes this step's SF/feedback sees the same state
            # in both drivers
            user_source(self, dt_chunk)

    def _retry_window(self, prev, tout, tdtype) -> int:
        """Redo-step ladder for a non-finite fused window: restore the
        retained pre-step state, retry ONE step at halved dt (halving
        again per attempt), escalating the Riemann solver to diffusive
        LLF from the second attempt; emergency-dump the last clean
        state and raise :class:`StepRetryExhausted` when the ladder is
        spent.  Returns the number of steps recovered (for the
        telemetry aggregate record)."""
        import dataclasses as _dc

        from ramses_tpu.resilience.stepguard import (StepGuard,
                                                     StepRetryExhausted)
        sg = self._sguard
        st = self.state
        u0, t0, nstep0, dt_old0 = prev
        sg.record_trip(self)
        grid0 = self.grid
        try:
            for attempt in range(1, sg.max_retries + 1):
                st.u, st.t, st.nstep, st.dt_old = u0, t0, nstep0, dt_old0
                escalated = attempt >= 2
                if escalated:
                    self.grid = _dc.replace(
                        grid0, cfg=_dc.replace(grid0.cfg, riemann="llf"))
                scale = 0.5 ** attempt
                sg.record_rollback(self, attempt, scale, escalated)
                tw0 = time.perf_counter()
                with (self._wd.guard("step") if self._wd is not None
                        else nullcontext()):
                    u, t, ndone = run_steps(
                        self.grid, u0, jnp.asarray(t0, tdtype),
                        jnp.asarray(tout, tdtype), 1, dt_scale=scale)
                    u.block_until_ready()
                    tf = float(t)
                if StepGuard.ok(tf):
                    st.u, st.t, st.nstep = u, tf, nstep0 + int(ndone)
                    self.cell_updates += int(ndone) * self.grid.ncell
                    self.wall_s += time.perf_counter() - tw0
                    sg.record_recovered(self, attempt)
                    return int(ndone)
        finally:
            self.grid = grid0     # escalation is per-retry, not sticky
        st.u, st.t, st.nstep, st.dt_old = u0, t0, nstep0, dt_old0
        out = None
        try:
            out = self.dump(999, self.params.output.output_dir)
        except Exception as e:    # the abort itself must not be masked
            print(f"resilience: emergency dump failed: {e}")
        sg.record_abort(self, out)
        raise StepRetryExhausted(
            f"step {nstep0} non-finite after {sg.max_retries} retries "
            f"(t={t0:.6g}); last clean state dumped to {out}")

    def mus_per_cell_update(self) -> float:
        return 1e6 * self.wall_s / max(self.cell_updates, 1)

    def totals(self):
        """Conservation audit (``check_cons``) over the active grid."""
        from ramses_tpu.grid.uniform import totals as _totals
        return _totals(self.state.u, self.cfg, self.dx)

    # ------------------------------------------------------------------
    # snapshot / restart (SURVEY.md §3.4, §5.4)
    # ------------------------------------------------------------------
    def dump(self, iout: Optional[int] = None, base_dir: Optional[str] = None,
             namelist_path: Optional[str] = None) -> str:
        """Write a reference-format ``output_NNNNN/`` snapshot."""
        import os

        from ramses_tpu.io import snapshot as snapmod
        with (self._wd.guard("io") if self._wd is not None
                else nullcontext()):
            iout = iout if iout is not None else self.state.iout
            snap = snapmod.snapshot_from_uniform(self, iout)
            base = base_dir or self.params.output.output_dir
            extra = None
            if self.turb is not None:
                # the OU spectral state + RNG key ride in every snapshot
                # (``turb/write_turb_fields.f90``) so a driven-turbulence
                # restart continues the SAME forcing realization instead
                # of silently re-seeding; staged alongside the file set
                # so it lands under the checkpoint manifest, not after
                # the rename
                extra = os.path.join(base,
                                     f"output_{iout:05d}.extras.tmp")
                os.makedirs(extra, exist_ok=True)
                self.turb.save(os.path.join(extra, "turb_fields.npz"))
            if getattr(self.params.output, "savegadget", False) \
                    and self.state.p is not None:
                # &OUTPUT_PARAMS savegadget: each particle output also
                # lands as a Gadget SnapFormat=1 file, staged into the
                # extras dir so it rides the checkpoint manifest
                from ramses_tpu.io.gadget import dump_gadget_particles
                if extra is None:
                    extra = os.path.join(
                        base, f"output_{iout:05d}.extras.tmp")
                    os.makedirs(extra, exist_ok=True)
                dump_gadget_particles(
                    os.path.join(extra, f"gadget_{iout:05d}.dat"),
                    self.state.p, boxlen=self.params.amr.boxlen,
                    time=self.state.t)
            return snapmod.dump_all(
                snap, iout, base, namelist_path=namelist_path,
                extra_dir=extra,
                keep_last=int(getattr(self.params.output,
                                      "checkpoint_keep", 0)))

    @classmethod
    def from_snapshot(cls, params: Params, outdir: str,
                      dtype=jnp.float32) -> "Simulation":
        """Resume from a snapshot directory (``nrestart`` path)."""
        from ramses_tpu.io.restart import restore_particles, restore_uniform
        from ramses_tpu.pm.particles import lane_headroom
        from ramses_tpu.pm.sinks import SinkSpec
        from ramses_tpu.pm.star_formation import SfSpec
        cfg = HydroStatic.from_params(params)
        dense, meta, parts = restore_uniform(outdir, params, cfg)
        # particle-creating runs need free lanes after the restart too
        grows = (SfSpec.from_params(params).enabled
                 or SinkSpec.from_params(params).enabled)
        p = (restore_particles(parts, params.ndim,
                               nmax=lane_headroom(params, grows))
             if parts else None)
        sim = cls(params, dtype=dtype, particles=p)
        if p is not None:
            # new star ids must not collide with restored particles'
            sim._next_star_id = int(np.asarray(p.idp).max()) + 1
        sim.state.u = jnp.asarray(dense, dtype=dtype)
        sim.state.t = float(meta["t"])
        sim.state.nstep = int(meta["nstep"])
        iout_meta = int(meta["iout"])
        if iout_meta < 900:
            sim.state.iout = max(iout_meta, 1) + 1
        else:
            # emergency checkpoint (OpsGuard 900+, StepGuard 999): its
            # iout is NOT an output-schedule index — derive the next
            # pending output from the restored time so the resumed
            # evolve() continues the tout schedule instead of indexing
            # past its end
            sim.state.iout = 1 + sum(
                1 for tt in sim.output_times
                if sim.state.t >= tt - 1e-12 * (abs(tt) + 1.0))
        if sim.turb is not None:
            import os

            from ramses_tpu.turb.forcing import TurbForcing
            tpath = os.path.join(outdir, "turb_fields.npz")
            if os.path.exists(tpath):
                # restore the OU field + RNG key (read_turb_fields.f90):
                # the restarted run reproduces the continuous run's
                # forcing sequence bitwise
                sim.turb = TurbForcing.load(tpath, sim.turb_spec)
            else:
                import warnings
                warnings.warn(f"no turb_fields.npz in {outdir}: the "
                              "forcing re-seeds from turb_seed and the "
                              "restart will not reproduce the original "
                              "driving sequence")
        if sim.gspec.enabled:
            rho = total_density(sim.pspec, sim.state.u, sim.state.p,
                                sim.grid.shape, sim.dx)
            # supercomoving source uses aexp AT the restored time, not
            # aexp_ini — restart must continue the original trajectory
            fourpi = (1.5 * sim.cosmo.omega_m
                      * float(sim.cosmo.aexp_of_tau(sim.state.t))
                      if sim.cosmo is not None else None)
            sim.state.f = gravity_field(sim.gspec, rho, sim.dx, fourpi)
        return sim


def run_namelist(path: str, ndim: int = 3, dtype=jnp.float32,
                 verbose: bool = False,
                 max_attempts: int = 1) -> Simulation:
    """Build-and-evolve from a namelist.  With ``max_attempts > 1`` or
    ``&RUN_PARAMS auto_resume``/``nrestart=-1`` the run is supervised:
    an interrupted attempt resumes from the newest manifest-valid
    checkpoint with exponential backoff between attempts.

    ``&ENSEMBLE_PARAMS nmember > 1`` dispatches to the batched
    ensemble engine instead (one compiled program advances every
    member) and returns the :class:`~ramses_tpu.ensemble.batch.
    EnsembleEngine` in place of a :class:`Simulation`."""
    params = load_params(path, ndim=ndim)
    if params.ensemble.nmember > 1:
        from ramses_tpu.ensemble.batch import EnsembleEngine, EnsembleSpec
        spec = EnsembleSpec.from_params(params)
        return EnsembleEngine(spec, dtype=dtype).run(verbose=verbose)
    supervised = (max_attempts > 1 or params.run.auto_resume
                  or params.run.nrestart == -1)
    if supervised:
        from ramses_tpu.resilience import supervisor as rsup

        def build(restart):
            if restart is not None:
                return Simulation.from_snapshot(params, restart,
                                                dtype=dtype)
            return Simulation(params, dtype=dtype)

        return rsup.supervise(build,
                              lambda sim: sim.evolve(verbose=verbose),
                              params,
                              base_dir=params.output.output_dir,
                              max_attempts=max(2, int(max_attempts)))
    sim = Simulation(params, dtype=dtype)
    sim.evolve(verbose=verbose)
    return sim
