"""Two-level parallelism: map a job onto the local device mesh.

A worker's mesh can be filled two ways (ROADMAP item 3(b)), and the
:class:`MeshPlan` is the explicit record of which one a job got:

* **packed** — a *small* job (per-member cell count within
  ``&ENSEMBLE_PARAMS pack_cell_budget``) shards the leading member axis
  of each vmapped sub-batch over a replica mesh axis
  (:func:`ramses_tpu.parallel.mesh.replica_mesh`).  Members are data-
  parallel — no cross-member collectives exist in the batched step
  chain — so GSPMD partitions the one compiled program into B/R-member
  per-device replicas with zero communication, and the per-member
  ``t < tend`` in-scan mask becomes per-replica completion masking for
  free.
* **slab** — a *mesh-wide* job (per-member cells above the budget)
  streams members one at a time through the explicit slab pipeline on
  the full assigned mesh (:func:`ramses_tpu.parallel.halo.
  run_steps_halo` — 1-D leading-axis decomposition, ring halo
  exchange, ``lax.pmin`` CFL).

``plan_for`` chooses between them from the namelist alone;
``stamp_cost`` is the submit-time cost model the queue scheduler
bin-packs on — the job-level analogue of the per-oct cost model in
:mod:`ramses_tpu.parallel.balance` (cost = members x cells x steps,
arXiv:2412.15518's work-placement currency).

Plans are JSON-serializable (devices are recorded as indices into
``jax.devices()``) so a checkpoint can record the packing it was
written under while restoring under any other — the state arrays are
saved host-global, which makes every ensemble checkpoint elastic
across packings by construction.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ramses_tpu.config import Params, params_from_string


@dataclass(frozen=True)
class MeshPlan:
    """How one job lands on the local mesh.

    ``mode``: ``"single"`` (one device, the pre-composition behavior),
    ``"packed"`` (member vmap sharded over per-device replicas) or
    ``"slab"`` (members stream over the full-mesh slab pipeline).
    ``device_ids`` index into ``jax.devices()``; empty means device 0.
    """
    mode: str = "single"
    device_ids: Tuple[int, ...] = ()
    # packed: cap on replicas (0 = len(device_ids)); the engine picks
    # the largest divisor of each sub-batch size within the cap so the
    # member axis shards evenly
    max_replicas: int = 0

    def __post_init__(self):
        if self.mode not in ("single", "packed", "slab"):
            raise ValueError(f"unknown MeshPlan mode {self.mode!r}")

    @property
    def n_devices(self) -> int:
        return max(1, len(self.device_ids))

    def devices(self) -> list:
        """Resolve the device ids against the live backend."""
        import jax
        devs = jax.devices()
        if not self.device_ids:
            return [devs[0]]
        return [devs[i] for i in self.device_ids]

    def describe(self) -> Dict[str, Any]:
        """JSON-ready summary for telemetry / checkpoint manifests."""
        return {"mode": self.mode, "devices": self.n_devices,
                "device_ids": list(self.device_ids),
                "max_replicas": int(self.max_replicas)}

    @classmethod
    def single(cls) -> "MeshPlan":
        return cls()

    @classmethod
    def packed(cls, device_ids: Sequence[int],
               max_replicas: int = 0) -> "MeshPlan":
        return cls(mode="packed", device_ids=tuple(device_ids),
                   max_replicas=int(max_replicas))

    @classmethod
    def slab(cls, device_ids: Sequence[int]) -> "MeshPlan":
        return cls(mode="slab", device_ids=tuple(device_ids))


def member_cells(params: Params) -> int:
    """Estimated per-member cell count: the uniform base grid, times a
    worst-case refinement factor for AMR namelists (every level fully
    refined — an upper bound, which is the right direction for a
    budget check)."""
    a = params.amr
    n = 2 ** a.levelmin
    base = [a.nx, a.ny, a.nz][:params.ndim]
    cells = 1
    for b in base:
        cells *= b * n
    depth = max(0, int(a.levelmax) - int(a.levelmin))
    return cells * (2 ** (params.ndim * depth))


def slab_eligible(params: Params, n_devices: int,
                  solver: str = "") -> bool:
    """Can this namelist's members run on the explicit uniform slab
    pipeline over ``n_devices``?  Mirrors ``parallel/halo._check``:
    hydro without cooling, fully periodic, leading axis divisible into
    shards at least one stencil halo thick — plus the ensemble
    engine's own uniform-only scope."""
    from ramses_tpu.ensemble.batch import solver_from_params
    from ramses_tpu.grid import boundary as bmod
    from ramses_tpu.hydro import muscl

    if n_devices <= 1:
        return False
    solver = solver or solver_from_params(params)
    if solver != "hydro" or params.cooling.cooling:
        return False
    a = params.amr
    if a.levelmax > a.levelmin:
        return False
    r = params.run
    if r.poisson or r.pic or r.cosmo or r.rt or r.patch:
        return False
    spec_bc = bmod.BoundarySpec.from_params(params)
    if any(f[0].kind != 0 or f[1].kind != 0 for f in spec_bc.faces):
        return False
    nx = a.nx * 2 ** a.levelmin
    return nx % n_devices == 0 and nx // n_devices >= muscl.NGHOST


def plan_for(params: Params, nmember: int,
             device_ids: Optional[Sequence[int]] = None,
             n_devices: Optional[int] = None,
             solver: str = "") -> MeshPlan:
    """Choose the packing for a job on an assigned device set.

    ``device_ids`` (or just ``n_devices`` for the local mesh prefix)
    names the submesh the scheduler granted.  Small jobs pack; a job
    over the cell budget goes mesh-wide on the slab pipeline when
    eligible, and falls back to a single device otherwise (the
    pre-composition behavior — correct, just not sharded)."""
    if device_ids is None:
        if n_devices is None:
            import jax
            n_devices = len(jax.devices())
        device_ids = tuple(range(n_devices))
    device_ids = tuple(device_ids)
    if len(device_ids) <= 1:
        return MeshPlan.single()
    e = params.ensemble
    budget = int(e.pack_cell_budget)
    if budget > 0 and member_cells(params) > budget:
        if slab_eligible(params, len(device_ids), solver=solver):
            return MeshPlan.slab(device_ids)
        return MeshPlan.single()
    return MeshPlan.packed(device_ids,
                           max_replicas=int(e.pack_max_replicas))


def largest_divisor(b: int, cap: int) -> int:
    """Largest divisor of ``b`` that is <= ``cap`` — the replica count
    a B-member sub-batch shards evenly over."""
    cap = max(1, min(int(cap), int(b)))
    for r in range(cap, 0, -1):
        if b % r == 0:
            return r
    return 1


# ---------------------------------------------------------------------
# submit-time cost stamp (queue scheduling currency)
# ---------------------------------------------------------------------
#: cap on the steps term so an unbounded nstepmax (the 1e6 default)
#: still yields finite, comparable costs
_STEP_CAP = 10 ** 6


def stamp_cost(namelist: str, ndim: int = 3,
               sweeps: Optional[Dict[str, List[Any]]] = None,
               solver: str = "", kind: str = "run"
               ) -> Optional[Dict[str, Any]]:
    """Estimate ``(members x cells x steps)`` plus shard clamps for a
    job record at submit time.  Returns None when the namelist does
    not parse into a costable config — the scheduler treats an
    unstamped record as a small FIFO job, so stamping is strictly
    best-effort."""
    try:
        params = params_from_string(namelist, ndim=ndim)
        e = params.ensemble
        nm = int(e.nmember) or \
            (max(len(v) for v in sweeps.values()) if sweeps else 1)
        cells = member_cells(params)
        steps = min(max(1, int(params.run.nstepmax)), _STEP_CAP)
        exclusive = bool(int(e.pack_cell_budget) > 0
                         and cells > int(e.pack_cell_budget)
                         and kind == "run")
        max_shards = int(e.max_shards)
        if not max_shards and params.amr.levelmax > params.amr.levelmin:
            from ramses_tpu.parallel.dense_slab import max_slab_devices
            max_shards = max_slab_devices(int(params.amr.levelmax),
                                          params.ndim)
        return {"members": nm, "cells": int(cells),
                "steps": int(steps),
                "cost": int(nm) * int(cells) * int(steps),
                "min_shards": int(e.min_shards),
                "max_shards": max_shards, "exclusive": exclusive}
    except Exception:
        return None
