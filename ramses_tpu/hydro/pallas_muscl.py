"""Fused MUSCL-Hancock TPU kernel (Pallas).

The whole unsplit update — ``ctoprim → uslope → trace3d → cmpflxm →
riemann → conservative update`` (``hydro/umuscl.f90:22-171``) — as ONE
Pallas kernel.  The XLA formulation in :mod:`ramses_tpu.hydro.muscl`
materializes ~60 grid-sized intermediates per step (~85 GB of HBM traffic
at 256³); here every intermediate lives in VMEM and HBM sees exactly one
read of the (haloed) state and one write of the update, the traffic the
algorithm actually requires.

Blocking: the grid is tiled over (x, y); each program sees the FULL z
extent (z is the TPU lane dimension — keeping it whole makes the minor
dims perfectly tiled and gives the z-direction stencil for free via lane
rotates).  x/y halos (2 cells) come from overlapping `pl.Element` windows
into a pre-padded array; z wraps periodically inside the kernel with
``jnp.roll`` (non-periodic z falls back to the XLA path).

Scope: ndim=3, nener=0, npassive=0, scheme=muscl, slope_type∈{1,2,8},
riemann∈{llf, hllc}.  Everything else falls back to
:func:`ramses_tpu.hydro.muscl.unsplit` (bit-identical physics, slower).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
try:  # Element block-indexing mode is absent from older jax releases
    from jax._src.pallas.core import Element
except ImportError:         # pragma: no cover - depends on jax version
    Element = None
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams → CompilerParams between releases
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")

from ramses_tpu.hydro.core import HydroStatic

NG = 2  # ghost cells per side (matches muscl.NGHOST)

# Read once at import: jit caches are keyed on static args, not the
# environment, so a post-import toggle would silently hit stale caches.
DISABLED = bool(__import__("os").environ.get("RAMSES_NO_PALLAS"))


def kernel_available(cfg: HydroStatic, shape, bc_faces, dtype) -> bool:
    """Full availability gate: env kill-switch, TPU backend, single
    device (the kernel has no GSPMD partitioning rule — sharded runs
    must keep the XLA solver so the SPMD partitioner can insert halo
    collectives), and configuration coverage."""
    if DISABLED or Element is None:
        return False
    if jax.default_backend() != "tpu" or jax.device_count() != 1:
        return False
    kinds = tuple((lo.kind, hi.kind) for lo, hi in bc_faces)
    return supports(cfg, shape, kinds, dtype)


def supports(cfg: HydroStatic, shape, bc_kinds, dtype) -> bool:
    """True when the fused kernel covers this configuration.

    ``bc_kinds``: per-dim (low, high) boundary kinds (grid.boundary codes).
    """
    if getattr(cfg, "physics", "hydro") != "hydro":
        return False
    if cfg.ndim != 3 or cfg.nener != 0 or cfg.npassive != 0:
        return False
    if cfg.scheme != "muscl" or cfg.slope_type not in (1, 2, 8):
        return False
    if cfg.pressure_fix:
        return False
    if cfg.riemann not in ("llf", "hllc"):
        return False
    if tuple(bc_kinds[2]) != (0, 0):  # z handled by in-kernel periodic roll
        return False
    for d in (0, 1):                  # x/y pad: periodic/reflect/outflow
        if any(k not in (0, 1, 2) for k in bc_kinds[d]):
            return False
    if dtype not in (jnp.float32, jnp.dtype("float32")):
        return False
    nx, ny, nz = shape
    if nz % 128 != 0 or nz > 1024:    # lane dim whole + VMEM budget
        return False
    bx, by = _pick_block(shape)
    return bx is not None and by is not None


WY = 16  # y window: by + 4-cell halo, padded to the 8-sublane rule
BY = 8   # y tile


def _pick_block(shape) -> Tuple[Optional[int], Optional[int]]:
    """x/y tile sizes, sized to the VMEM budget.

    Mosaic requires the last two block dims divisible by (8, 128): z is
    always the full extent (lane dim); y uses a fixed 8-cell tile read
    through a 16-cell window (2 halo + 2 junk per side); x is a free
    (untiled) dim so its window is exactly bx+4.
    """
    nx, ny, nz = shape
    if ny % BY:
        return None, None
    # per-variable block bytes ~ (bx+4)*WY*nz*4; ~45 live variables.
    budget = 11 * 1024 * 1024 // (45 * 4 * nz * WY)     # cap on bx+4
    for bx in (32, 16, 8, 4):
        if nx % bx == 0 and (bx + 2 * NG) <= budget:
            return bx, BY
    return None, None


def _slopes(ql, q, qr, st: int, theta: float):
    """TVD slope of one variable given (left, centre, right) neighbours."""
    dl = q - ql
    dr = qr - q
    dcen = 0.5 * (dl + dr)
    if st in (1, 2):
        f = float(st)
        slop = f * jnp.minimum(jnp.abs(dl), jnp.abs(dr))
    else:                              # generalized minmod (theta)
        slop = theta * jnp.minimum(jnp.abs(dl), jnp.abs(dr))
    dlim = jnp.where(dl * dr <= 0.0, 0.0, slop)
    return jnp.sign(dcen) * jnp.minimum(dlim, jnp.abs(dcen))


def _roll(a, shift: int, axis: int):
    return jnp.roll(a, shift, axis=axis)


def _llf_flux(ql, qr, d: int, cfg: HydroStatic):
    """LLF flux of one face set; ql/qr are 5-tuples (r, vx, vy, vz, p) with
    density/pressure already floored.  Returns 5-tuple of state-layout
    fluxes (mass, mom_x, mom_y, mom_z, energy)."""
    g = cfg.gamma
    entho = 1.0 / (g - 1.0)
    rl, pl_ = ql[0], ql[4]
    rr, pr_ = qr[0], qr[4]
    ul, ur = ql[1 + d], qr[1 + d]
    cl = jnp.sqrt(jnp.maximum(g * pl_ / rl, cfg.smallc ** 2))
    cr = jnp.sqrt(jnp.maximum(g * pr_ / rr, cfg.smallc ** 2))
    cmax = jnp.maximum(jnp.abs(ul) + cl, jnp.abs(ur) + cr)

    def cons_flux(q5, un):
        r, p = q5[0], q5[4]
        ek = 0.5 * r * (q5[1] * q5[1] + q5[2] * q5[2] + q5[3] * q5[3])
        et = p * entho + ek
        ucons = (r, r * q5[1], r * q5[2], r * q5[3], et)
        f = [r * un * q5[1 + c] for c in range(3)]
        f[d] = f[d] + p
        return ucons, (r * un, f[0], f[1], f[2], un * (et + p))

    uL, fL = cons_flux(ql, ul)
    uR, fR = cons_flux(qr, ur)
    return tuple(0.5 * (fl + fr - cmax * (ur_ - ul_))
                 for fl, fr, ul_, ur_ in zip(fL, fR, uL, uR))


def _hllc_flux(ql, qr, d: int, cfg: HydroStatic):
    """HLLC with Toro sampling (``riemann_hllc``, godunov_utils.f90:988),
    specialized to nener=0/npassive=0, state-layout output."""
    g = cfg.gamma
    entho = 1.0 / (g - 1.0)
    rl, pl_ = ql[0], ql[4]
    rr, pr_ = qr[0], qr[4]
    ul, ur = ql[1 + d], qr[1 + d]
    ekl = 0.5 * rl * (ql[1] * ql[1] + ql[2] * ql[2] + ql[3] * ql[3])
    ekr = 0.5 * rr * (qr[1] * qr[1] + qr[2] * qr[2] + qr[3] * qr[3])
    etotl = pl_ * entho + ekl
    etotr = pr_ * entho + ekr
    cfastl = jnp.sqrt(jnp.maximum(g * pl_ / rl, cfg.smallc ** 2))
    cfastr = jnp.sqrt(jnp.maximum(g * pr_ / rr, cfg.smallc ** 2))
    SL = jnp.minimum(ul, ur) - jnp.maximum(cfastl, cfastr)
    SR = jnp.maximum(ul, ur) + jnp.maximum(cfastl, cfastr)
    rcl = rl * (ul - SL)
    rcr = rr * (SR - ur)
    ustar = (rcr * ur + rcl * ul + (pl_ - pr_)) / (rcr + rcl)
    pstar = (rcr * pl_ + rcl * pr_ + rcl * rcr * (ul - ur)) / (rcr + rcl)
    rstarl = rl * (SL - ul) / (SL - ustar)
    etotstarl = ((SL - ul) * etotl - pl_ * ul + pstar * ustar) / (SL - ustar)
    rstarr = rr * (SR - ur) / (SR - ustar)
    etotstarr = ((SR - ur) * etotr - pr_ * ur + pstar * ustar) / (SR - ustar)

    def sel(a_l, a_sl, a_sr, a_r):
        return jnp.where(SL > 0.0, a_l,
               jnp.where(ustar > 0.0, a_sl,
               jnp.where(SR > 0.0, a_sr, a_r)))

    ro = sel(rl, rstarl, rstarr, rr)
    uo = sel(ul, ustar, ustar, ur)
    po = sel(pl_, pstar, pstar, pr_)
    etoto = sel(etotl, etotstarl, etotstarr, etotr)
    left = ustar > 0.0
    fmass = ro * uo
    f = [None] * 5
    f[0] = fmass
    f[4] = (etoto + po) * uo
    for c in range(3):
        if c == d:
            f[1 + c] = fmass * uo + po
        else:
            f[1 + c] = fmass * jnp.where(left, ql[1 + c], qr[1 + c])
    return tuple(f)


def _make_kernel(cfg: HydroStatic, dx: float, bx: int, by: int,
                 masked: bool, courant: bool, want_flux: bool = False):
    """Kernel body closure; refs: u_pad [5, bx+4, WY, nz] window,
    (ok [bx+4, WY, nz] window,) dt [1,1] SMEM → out [5, bx, by, nz]
    (+ per-block courant dt min [1, 1] SMEM when ``courant``)
    (+ phi [3, 2, bx, by, nz] per-cell (low, high) dt/dx-scaled face
    MASS fluxes when ``want_flux`` — the MC-tracer capture)."""
    st = cfg.slope_type
    theta = float(getattr(cfg, "slope_theta", 1.5))
    solver = _llf_flux if cfg.riemann == "llf" else _hllc_flux
    sx = slice(NG, NG + bx)
    sy = slice(NG, NG + by)

    def kernel(*refs):
        i = 1
        u_ref = refs[0]
        ok_ref = refs[i] if masked else None
        i += int(masked)
        dt_ref = refs[i]
        out_ref = refs[i + 1]
        i += 2
        crt_ref = refs[i] if courant else None
        i += int(courant)
        phi_ref = refs[i] if want_flux else None
        dt = dt_ref[0, 0]
        # ---- ctoprim (umuscl.f90:861-967) ----
        r = jnp.maximum(u_ref[0], cfg.smallr)
        ir = 1.0 / r
        v = [u_ref[1] * ir, u_ref[2] * ir, u_ref[3] * ir]
        ek = 0.5 * (v[0] * v[0] + v[1] * v[1] + v[2] * v[2])
        eint = jnp.maximum(u_ref[4] * ir - ek, cfg.smalle)
        p = (cfg.gamma - 1.0) * r * eint
        q = (r, v[0], v[1], v[2], p)
        # ---- uslope: dq[d][comp] ----
        dq = []
        for d in range(3):
            qm1 = tuple(_roll(c, 1, d) for c in q)
            qp1 = tuple(_roll(c, -1, d) for c in q)
            dq.append(tuple(_slopes(a, b, c, st, theta)
                            for a, b, c in zip(qm1, q, qp1)))
        # ---- trace3d source terms (umuscl.f90:176-714) ----
        divv = dq[0][1] + dq[1][2] + dq[2][3]
        adv = lambda comp: (v[0] * dq[0][comp] + v[1] * dq[1][comp]
                            + v[2] * dq[2][comp])
        sr0 = -adv(0) - divv * r
        sp0 = -adv(4) - divv * cfg.gamma * p
        sv0 = [-adv(1 + j) - dq[j][4] * ir for j in range(3)]
        dtdx2 = 0.5 * dt / dx

        if masked:
            # 0/1 mask already in the state dtype (see pad_xy): Mosaic
            # supports neither i1 vector rolls nor u8->f32 casts here
            okf = ok_ref[:]

        # ---- per-direction face flux + conservative update ----
        du = [None] * 5
        for d in range(3):
            def face_state(sgn):
                rho = r + sgn * 0.5 * dq[d][0] + sr0 * dtdx2
                rho = jnp.where(rho < cfg.smallr, r, rho)
                vs = [v[j] + sgn * 0.5 * dq[d][1 + j] + sv0[j] * dtdx2
                      for j in range(3)]
                pp = p + sgn * 0.5 * dq[d][4] + sp0 * dtdx2
                return (rho, vs[0], vs[1], vs[2], pp)
            qm = face_state(+1.0)     # high-side face state
            qp = face_state(-1.0)     # low-side face state
            # face i between cells i-1, i: left = qm(i-1), right = qp(i)
            ql5 = tuple(_roll(c, 1, d) for c in qm)
            qr5 = qp
            # floors (riemann.py _prims)
            ql5 = (jnp.maximum(ql5[0], cfg.smallr), ql5[1], ql5[2], ql5[3],
                   jnp.maximum(ql5[4], ql5[0] * cfg.smallp))
            qr5 = (jnp.maximum(qr5[0], cfg.smallr), qr5[1], qr5[2], qr5[3],
                   jnp.maximum(qr5[4], qr5[0] * cfg.smallp))
            flux = solver(ql5, qr5, d, cfg)
            if masked:
                # face kept iff neither adjacent cell is refined:
                # (1-ok_i)(1-ok_{i-1}) — pure arithmetic, no i1 vectors
                keepf = (1.0 - okf) * (1.0 - _roll(okf, 1, d))
                flux = tuple(f * keepf for f in flux)
            scale = dt / dx
            if want_flux:
                phi_ref[d, 0] = (flux[0] * scale)[sx, sy, :]
                phi_ref[d, 1] = (_roll(flux[0], -1, d) * scale)[sx, sy, :]
            for c in range(5):
                contrib = (flux[c] - _roll(flux[c], -1, d)) * scale
                du[c] = contrib if du[c] is None else du[c] + contrib
        # write updated interior (x/y halo dropped; z has no halo)
        un = [(u_ref[c] + du[c])[sx, sy, :] for c in range(5)]
        for c in range(5):
            out_ref[c] = un[c]
        if courant:
            # per-block Courant min of the UPDATED state (``cmpdt``,
            # godunov_utils.f90:5-125 with gravity off) — the next step's
            # dt comes out of the same kernel launch for free.
            r2 = jnp.maximum(un[0], cfg.smallr)
            ir2 = 1.0 / r2
            v2 = [un[1] * ir2, un[2] * ir2, un[3] * ir2]
            ek2 = 0.5 * r2 * (v2[0] * v2[0] + v2[1] * v2[1]
                              + v2[2] * v2[2])
            p2 = jnp.maximum((cfg.gamma - 1.0) * (un[4] - ek2),
                             r2 * cfg.smallp)
            c2 = jnp.sqrt(cfg.gamma * p2 * ir2)
            ws = 3.0 * c2 + jnp.abs(v2[0]) + jnp.abs(v2[1]) + jnp.abs(v2[2])
            ratio = 1e-4                      # gravity-off strength ratio
            cf = cfg.courant_factor
            fac = (jnp.sqrt(1.0 + 2.0 * cf * ratio) - 1.0) / ratio
            local = jnp.min(dx / ws) * fac
            # TPU grid steps run sequentially on the core: accumulate the
            # global min into the single shared (1,1) SMEM output.
            first = jnp.logical_and(pl.program_id(0) == 0,
                                    pl.program_id(1) == 0)

            @pl.when(first)
            def _():
                crt_ref[0, 0] = local

            @pl.when(jnp.logical_not(first))
            def _():
                crt_ref[0, 0] = jnp.minimum(crt_ref[0, 0], local)

    return kernel


@partial(jax.jit,
         static_argnames=("cfg", "dx", "shape", "courant", "interpret",
                          "want_flux"))
def fused_step_padded(u_pad, dt, cfg: HydroStatic, dx: float,
                      shape: Tuple[int, int, int],
                      ok_pad: Optional[jnp.ndarray] = None,
                      courant: bool = False, interpret: bool = False,
                      want_flux: bool = False):
    """Run the fused kernel on an x/y-ghost-padded state.

    u_pad: [5, nx+4, ny+8, nz] from :func:`pad_xy` (x: 2-cell ghosts
    both sides; y: 2-cell ghosts + 4 junk rows at the high end so the
    16-cell y windows stay in bounds); ok_pad: optional refined-cell
    mask, same spatial shape — faces touching a refined cell get zero
    flux (``godunov_fine.f90:718``).  Returns the UPDATED active grid
    [5, nx, ny, nz].
    """
    nx, ny, nz = shape
    bx, by = _pick_block(shape)
    dt2 = jnp.asarray(dt, u_pad.dtype).reshape(1, 1)
    kern = _make_kernel(cfg, dx, bx, by, ok_pad is not None, courant,
                        want_flux)
    in_specs = [
        pl.BlockSpec(
            (Element(5), Element(bx + 2 * NG), Element(WY), Element(nz)),
            lambda i, j: (0, i * bx, j * by, 0),
            memory_space=pltpu.VMEM),
    ]
    args = [u_pad]
    if ok_pad is not None:
        in_specs.append(pl.BlockSpec(
            (Element(bx + 2 * NG), Element(WY), Element(nz)),
            lambda i, j: (i * bx, j * by, 0),
            memory_space=pltpu.VMEM))
        args.append(ok_pad)
    in_specs.append(pl.BlockSpec((1, 1), lambda i, j: (0, 0),
                                 memory_space=pltpu.SMEM))
    args.append(dt2)
    out_specs = [pl.BlockSpec((5, bx, by, nz), lambda i, j: (0, i, j, 0),
                              memory_space=pltpu.VMEM)]
    out_shape = [jax.ShapeDtypeStruct((5, nx, ny, nz), u_pad.dtype)]
    if courant:
        out_specs.append(pl.BlockSpec((1, 1), lambda i, j: (0, 0),
                                      memory_space=pltpu.SMEM))
        out_shape.append(jax.ShapeDtypeStruct((1, 1), u_pad.dtype))
    if want_flux:
        out_specs.append(pl.BlockSpec(
            (3, 2, bx, by, nz), lambda i, j: (0, 0, i, j, 0),
            memory_space=pltpu.VMEM))
        out_shape.append(
            jax.ShapeDtypeStruct((3, 2, nx, ny, nz), u_pad.dtype))
    if len(out_specs) == 1:
        out_specs, out_shape = out_specs[0], out_shape[0]
    else:
        out_specs, out_shape = tuple(out_specs), tuple(out_shape)
    return pl.pallas_call(
        kern,
        grid=(nx // bx, ny // by),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,           # CPU parity tests
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
    )(*args)


def shard_axes(cfg: HydroStatic, loc, cut, dtype):
    """Axis relabel for a PER-SHARD fused-kernel call, or None.

    The kernel wants its lane ("z") axis whole, periodic, %128 and
    uncut by the slab decomposition (the in-kernel roll would otherwise
    wrap inside one shard).  A slab cut always takes z first
    (amr/bitperm.py), so the per-shard call picks any UNCUT axis whose
    local extent fits the lane rules and relabels it to the kernel's z,
    permuting the momentum components to match.  Returns ``(a0, a1,
    az)``: the original axes taking the kernel's (x, y, z) roles.
    Unlike :func:`kernel_available` this gate has no single-device
    requirement — inside ``shard_map`` the kernel runs on the local
    block, so no GSPMD partitioning rule is needed.
    """
    if DISABLED or Element is None:
        return None
    if jax.default_backend() != "tpu":
        return None
    if getattr(cfg, "physics", "hydro") != "hydro" or cfg.ndim != 3:
        return None
    if cfg.nener != 0 or cfg.npassive != 0 or cfg.scheme != "muscl" \
            or cfg.slope_type not in (1, 2, 8) or cfg.pressure_fix \
            or cfg.riemann not in ("llf", "hllc"):
        return None
    if dtype not in (jnp.float32, jnp.dtype("float32")):
        return None
    for az in (2, 1, 0):
        if cut[az]:
            continue
        nz = loc[az]
        if nz % 128 or nz > 1024:
            continue
        a0, a1 = (d for d in range(3) if d != az)
        bx, by = _pick_block((loc[a0], loc[a1], nz))
        if bx is not None:
            return (a0, a1, az)
    return None


def fused_step_shard(up, okp, dt, cfg: HydroStatic, dx: float,
                     loc: Tuple[int, int, int], axes: Tuple[int, int, int],
                     want_flux: bool = False, interpret: bool = False):
    """Per-shard fused kernel on a halo-extended local box.

    ``up``: [5, *ext] in ORIGINAL axis order with NG ghost slabs on
    ``axes[0]``/``axes[1]`` and the bare local extent on the lane axis
    ``axes[2]`` (handled by the in-kernel periodic roll — valid because
    the slab gate guarantees that axis is uncut).  ``okp``: optional
    refined mask in the state dtype over the same extended box.
    Returns ``du [5, *loc]`` (+ ``phi [*loc, 3, 2]`` when
    ``want_flux``), both in original axis/component order — the same
    contract as :func:`ramses_tpu.amr.kernels.dense_interior_update`.

    NOTE: the relabeled kernel applies the directional sweeps in
    relabeled order, so it is NOT bitwise against the unrelabeled
    global kernel (float accumulation order differs); shard-invariance
    bitwise pins hold on the XLA path (CPU tests), the pallas shard
    path is tolerance-pinned.
    """
    a0, a1, az = axes
    vp = (0, 1 + a0, 1 + a1, 1 + az, 4)
    ivp = (0, 1 + axes.index(0), 1 + axes.index(1), 1 + axes.index(2), 4)
    sp = (0, 1 + a0, 1 + a1, 1 + az)               # relabel transpose
    isp = (0, 1 + axes.index(0), 1 + axes.index(1), 1 + axes.index(2))
    ur = jnp.transpose(up, sp)[jnp.asarray(vp)]
    # y window slack: 4 junk rows at the high end (values never used)
    ur = jnp.pad(ur, ((0, 0), (0, 0), (0, WY - BY - NG * 2), (0, 0)),
                 mode="edge")
    okr = None
    if okp is not None:
        okr = jnp.transpose(okp, (a0, a1, az))
        okr = jnp.pad(okr, ((0, 0), (0, WY - BY - NG * 2), (0, 0)),
                      mode="edge")
    shape_rel = (loc[a0], loc[a1], loc[az])
    out = fused_step_padded(ur, dt, cfg, dx, shape_rel, ok_pad=okr,
                            want_flux=want_flux, interpret=interpret)
    un = out[0] if want_flux else out
    du = un - ur[:, NG:-NG, NG:NG + shape_rel[1], :]
    du = jnp.transpose(du[jnp.asarray(ivp)], isp)
    if not want_flux:
        return du
    phis = []
    for d in range(3):
        f = out[1][axes.index(d)]                  # [2, *rel spatial]
        f = jnp.transpose(f, (0,) + tuple(1 + axes.index(dd)
                                          for dd in range(3)))
        phis.append(jnp.moveaxis(f, 0, -1))        # [*loc, 2]
    return du, jnp.stack(phis, axis=-2)            # [*loc, 3, 2]


def pad_xy(u, bc, cfg: HydroStatic, ok=None):
    """Ghost-pad x (2/2) and y (2 low / 6 high — window slack) only;
    z periodic is handled in-kernel."""
    up = _pad_leading2(u, bc, cfg)
    if ok is None:
        return up, None
    # ship the mask in the STATE dtype: Mosaic supports neither i1
    # vector rolls nor u8->f32 casts inside the kernel
    okp = _pad_leading2(ok[None].astype(u.dtype), bc, cfg)[0]
    return up, okp


def _pad_leading2(u, bc, cfg: HydroStatic):
    """Pad spatial axes 1,2 of [C, nx, ny, nz] per the x/y BCs."""
    for d in range(2):
        ax = 1 + d
        lo_bc, hi_bc = bc.faces[d]
        n = u.shape[ax]

        def take(a, b, step=1):
            idx = [slice(None)] * u.ndim
            idx[ax] = slice(a, b, step)
            return u[tuple(idx)]

        def ghost(fbc, side, ng):
            if fbc.kind == 0:                          # periodic
                if side == 0:
                    return take(n - ng, n)
                g = take(0, NG)
                if ng == NG:
                    return g
                # junk rows beyond the true ghosts: repeat (finite values)
                reps = [1] * u.ndim
                reps[ax] = (ng + NG - 1) // NG
                return jnp.tile(g, reps)[tuple(
                    slice(0, ng) if a == ax else slice(None)
                    for a in range(u.ndim))]
            if fbc.kind == 1:                          # reflecting
                g = take(0, ng) if side == 0 else take(n - ng, n)
                g = jnp.flip(g, axis=ax)
                if u.shape[0] == cfg.nvar:             # state: flip mom_d
                    sgn = jnp.ones((u.shape[0],), u.dtype).at[1 + d].set(-1)
                    g = g * sgn.reshape(-1, 1, 1, 1)
                return g
            # outflow / inflow approximated by edge copy for the kernel
            edge = take(0, 1) if side == 0 else take(n - 1, n)
            reps = [1] * u.ndim
            reps[ax] = ng
            return jnp.tile(edge, reps)

        hi_ng = NG if d == 0 else WY - BY - NG         # y: +4 junk rows
        u = jnp.concatenate([ghost(lo_bc, 0, NG), u, ghost(hi_bc, 1, hi_ng)],
                            axis=ax)
    return u
