"""Backend selection helpers.

The deployment image's ``sitecustomize`` registers a TPU-tunnel ("axon")
PJRT plugin in every interpreter and forces ``jax_platforms="axon,cpu"``
through ``jax.config`` — overriding the ``JAX_PLATFORMS`` environment
variable.  Anything that must run on a virtual multi-device CPU mesh
(the reference suite's same-host multi-rank trick,
``tests/run_test_suite.sh:78-82``) has to force the CPU platform back
*before the first backend is instantiated*.  This module is the single
home for that workaround; ``tests/conftest.py`` and
``__graft_entry__.dryrun_multichip`` both use it.
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"

# persistent-compile-cache hit/miss counters (best-effort, via
# jax.monitoring): "hits" counts executables served from the on-disk
# cache, "compiles" counts every pass through the backend-compile
# timer — which wraps ``compile_or_get_cached`` and so fires on disk
# hits too (the load is timed like a compile).  Misses are therefore
# derived as ``compiles - hits``: both counters are monotone and fire
# exactly once per compile request, so deltas stay consistent even
# when the cache engages midway through a process.  Surfaced in the
# telemetry run header so worker cold-start economics are observable.
_CACHE_STATS = {"hits": 0, "compiles": 0, "dir": ""}
_cache_listener_installed = False


def _install_cache_listener():
    global _cache_listener_installed
    if _cache_listener_installed:
        return
    try:
        from jax import monitoring

        def _on_event(name, **kw):
            if "persistent_cache_hit" in name \
                    or ("compilation_cache" in name and "hit" in name
                        and "requests" not in name):
                _CACHE_STATS["hits"] += 1

        def _on_duration(name, secs, **kw):
            if name.endswith("backend_compile_duration"):
                _CACHE_STATS["compiles"] += 1

        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
        _cache_listener_installed = True
    except Exception:      # monitoring API drift must not kill a run
        pass


def compile_cache_stats() -> dict:
    """Snapshot of {hits, misses, dir, ...} for telemetry headers.

    ``misses`` = compile requests not served from disk (real backend
    compiles); with no cache engaged that is every compile."""
    s = dict(_CACHE_STATS)
    s["misses"] = max(0, s["compiles"] - s["hits"])
    return s


def setup_compile_cache(params) -> str:
    """Point the persistent compilation cache at an explicit directory.

    ``&RUN_PARAMS compile_cache_dir`` (env fallback
    ``RAMSES_COMPILE_CACHE``) — called from ``__main__`` and the
    ensemble service BEFORE the first trace, so a known namelist
    cold-starts in O(load) instead of O(compile).  Unlike the
    package-import default (:func:`enable_compile_cache`) an explicit
    directory is honored on every backend, including CPU-forced runs —
    the operator asked for it by name.  Returns the directory in
    effect ("" when unset).  Best-effort: an unwritable path warns and
    leaves the run uncached rather than failing it.
    """
    path = str(getattr(getattr(params, "run", params),
                       "compile_cache_dir", "") or "").strip()
    if not path:
        path = os.environ.get("RAMSES_COMPILE_CACHE", "").strip()
    if not path:
        return ""
    path = os.path.expanduser(path)
    try:
        import jax
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache every entry: the point is O(load) worker cold-start,
        # and the fused AMR programs the growth phase re-traces are
        # individually small but numerous
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          0)
        # JAX-level executable cache only (see enable_compile_cache):
        # the XLA:CPU AOT cache keys on exact host machine features
        jax.config.update("jax_persistent_cache_enable_xla_caches",
                          "none")
        _CACHE_STATS["dir"] = path
        _install_cache_listener()
        return path
    except Exception as e:
        import warnings
        warnings.warn(f"compile_cache_dir={path!r} not usable: {e}")
        return ""


def enable_compile_cache():
    """Point JAX's persistent compilation cache at a durable directory.

    The AMR growth phase recompiles its fused programs whenever a level
    crosses a padding bucket; each TPU compile costs seconds to tens of
    seconds while the device work itself is milliseconds (the reference
    pays zero — Fortran compiles once at build time).  The persistent
    cache makes every recompile after the first sighting of a shape a
    disk hit instead.  Called from ``ramses_tpu/__init__``; disable with
    ``RAMSES_NO_XLA_CACHE=1``, relocate with ``RAMSES_XLA_CACHE_DIR``.
    Best-effort: a read-only filesystem must not break the solver.
    """
    if os.environ.get("RAMSES_NO_XLA_CACHE"):
        return
    # CPU-forced runs (tests, the driver's dryrun, verify checks) skip
    # the cache: XLA:CPU executables are AOT machine code whose
    # feature-set check warns on every load (and can in principle
    # SIGILL), polluting driver artifacts.  TPU is where recompiles
    # cost tens of seconds, and TPU runs never force JAX_PLATFORMS.
    if os.environ.get("JAX_PLATFORMS", "").strip().lower().startswith("cpu"):
        return
    path = os.environ.get(
        "RAMSES_XLA_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "ramses_tpu_xla"))
    try:
        import jax
        if getattr(jax.config, "jax_compilation_cache_dir", None):
            return                 # respect the host app's own cache
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        # JAX-level executable cache only: the XLA:CPU AOT cache keys on
        # exact host machine features and warns (worse: may SIGILL) when
        # they drift between processes; the TPU win comes from the
        # executable cache alone
        jax.config.update("jax_persistent_cache_enable_xla_caches", "none")
    except Exception:
        pass


def force_cpu_mesh(n_devices: int):
    """Force the CPU backend with ``n_devices`` virtual devices.

    Safe to call more than once with the same count.  Raises if a JAX
    backend was already initialized on a different platform or with
    fewer devices — a loud failure instead of a silently-smaller mesh.
    Returns the first ``n_devices`` devices.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    flag = f"{_COUNT_FLAG}={n_devices}"
    if _COUNT_FLAG in flags:
        flags = re.sub(rf"{_COUNT_FLAG}=\d+", flag, flags)
    else:
        flags = (flags + " " + flag).strip()
    os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")
    # authoritative cache kill for CPU-forced processes: package import
    # may have enabled the persistent cache before this call (the
    # JAX_PLATFORMS guard in enable_compile_cache only covers runs that
    # exported the variable before importing ramses_tpu), and XLA:CPU
    # cache entries are AOT machine code (load warnings / SIGILL risk)
    jax.config.update("jax_compilation_cache_dir", None)
    devices = jax.devices()
    if devices[0].platform != "cpu":
        raise RuntimeError(
            f"CPU platform could not be forced: backend already "
            f"initialized on {devices[0].platform!r}. Call force_cpu_mesh "
            f"before any other jax use in the process.")
    if len(devices) < n_devices:
        raise RuntimeError(
            f"requested {n_devices} virtual CPU devices but the backend "
            f"has {len(devices)}; it was initialized before XLA_FLAGS "
            f"could be updated.")
    return devices[:n_devices]
