"""Explicit halo-exchange backend: shard_map + ppermute slab pipeline.

The global-view path (:mod:`ramses_tpu.parallel.sharded`) leaves halo
communication to XLA's SPMD partitioner.  This module is the EXPLICIT
formulation of the reference's two-sided message schedule
(``amr/virtual_boundaries.f90:373-533`` ``make_virtual_fine``): the
state lives as per-device blocks under ``jax.shard_map``, each step
sends the ``NGHOST``-deep boundary slabs to the ring neighbours with
``lax.ppermute`` (ICI neighbour exchange — the collective actually
generated for MPI_Isend/Irecv pairs on a torus), pads the remaining
axes locally, and runs the unchanged MUSCL kernels on the interior.
The CFL reduction is a ``lax.pmin`` over the mesh axis (P7).

Why keep both: the GSPMD path is the idiomatic TPU formulation and
lets the compiler fuse; this path pins the communication schedule —
deterministic slab order, no partitioner heuristics — and is the
template for hand-scheduled overlap when profiles demand it.  The two
must agree bitwise on periodic boxes (asserted in
``tests/test_halo.py``).

Scope: fully periodic boxes, 1-D decomposition over the leading
spatial axis — the Hilbert-order row decomposition every other sharded
path uses (P1).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ramses_tpu.grid import boundary as bmod
from ramses_tpu.grid.uniform import UniformGrid
from ramses_tpu.hydro import muscl
from ramses_tpu.hydro.timestep import compute_dt

AXIS = "hx"          # mesh axis name of the slab decomposition


def make_halo_mesh(devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (AXIS,))


def _check(grid: UniformGrid, mesh: Mesh):
    n = mesh.shape[AXIS]
    if any(f[0].kind != 0 or f[1].kind != 0 for f in grid.bc.faces):
        raise NotImplementedError(
            "halo backend: fully periodic boxes only (physical "
            "boundary slabs stay on the GSPMD path)")
    if grid.shape[0] % n:
        raise ValueError(
            f"leading axis {grid.shape[0]} not divisible by the "
            f"{n}-device mesh")
    if grid.shape[0] // n < muscl.NGHOST:
        raise ValueError("shard thinner than the stencil halo")


def _exchange(u_loc, ng: int):
    """Ring exchange of the leading-spatial-axis boundary slabs.

    ``u_loc``: [nvar, nx_loc, ...].  Returns the block extended to
    ``nx_loc + 2*ng`` — each device's low ghost slab is its left
    neighbour's high interior slab and vice versa (periodic ring, so
    device 0's left neighbour is device n-1: the wrap IS the physical
    periodic boundary)."""
    # jax.lax.axis_size is absent from older jax releases; psum of a
    # unit weight is the portable spelling
    n = int(jax.lax.psum(1, AXIS))
    fwd = [(i, (i + 1) % n) for i in range(n)]    # data moves +x
    bwd = [(i, (i - 1) % n) for i in range(n)]    # data moves -x
    lo_ghost = jax.lax.ppermute(u_loc[:, -ng:], AXIS, fwd)
    hi_ghost = jax.lax.ppermute(u_loc[:, :ng], AXIS, bwd)
    return jnp.concatenate([lo_ghost, u_loc, hi_ghost], axis=1)


def _pad_rest(u_ext, ndim: int, ng: int):
    """Periodic-wrap padding of the non-decomposed spatial axes."""
    pads = [(0, 0), (0, 0)] + [(ng, ng)] * (ndim - 1)
    return jnp.pad(u_ext, pads, mode="wrap")


def _local_step(u_loc, dt, grid: UniformGrid):
    cfg = grid.cfg
    ng = muscl.NGHOST
    up = _pad_rest(_exchange(u_loc, ng), cfg.ndim, ng)
    flux, tmp = muscl.unsplit(up, None, dt, (grid.dx,) * cfg.ndim, cfg)
    un = muscl.apply_fluxes(up, flux, cfg)
    if cfg.pressure_fix or cfg.nener:
        un = muscl.dual_energy_fix(up, un, tmp, dt,
                                   (grid.dx,) * cfg.ndim, cfg)
    return bmod.unpad(un, cfg.ndim, ng)


@lru_cache(maxsize=None)
def _build_run(grid: UniformGrid, mesh: Mesh, nsteps: int):
    try:
        shard_map = jax.shard_map                 # jax >= 0.8
    except AttributeError:
        from jax.experimental.shard_map import shard_map

    cfg = grid.cfg

    def shard_body(u_loc, t, tend):
        def body(carry, _):
            u_loc, t, ndone = carry
            dt_loc = compute_dt(u_loc, None, grid.dx, cfg)
            dt = jax.lax.pmin(dt_loc, AXIS)
            dt = jnp.minimum(dt, jnp.maximum(tend - t, 0.0))
            active = t < tend
            un = _local_step(u_loc, jnp.where(active, dt, 0.0)
                             .astype(u_loc.dtype), grid)
            u_loc = jnp.where(active, un, u_loc)
            t = jnp.where(active, t + dt, t)
            ndone = ndone + jnp.where(active, 1, 0)
            return (u_loc, t, ndone), None

        # seed the step counter FROM t: older shard_map tracks a fresh
        # constant's replication as unknown, and the scan carry check
        # then rejects the (known-replicated) output counter
        ndone0 = (t - t).astype(jnp.int32)
        (u_loc, t, ndone), _ = jax.lax.scan(
            body, (u_loc, t, ndone0), None, length=nsteps)
        return u_loc, t, ndone

    return jax.jit(shard_map(shard_body, mesh=mesh,
                             in_specs=(P(None, AXIS), P(), P()),
                             out_specs=(P(None, AXIS), P(), P())))


def run_steps_halo(grid: UniformGrid, mesh: Mesh, u, t, tend,
                   nsteps: int):
    """``run_steps`` with the explicit slab pipeline: the whole window
    is ONE shard_map program; every step does two ppermutes + one
    pmin.  Returns (u, t, n_done) like the global-view version."""
    _check(grid, mesh)
    u = jax.device_put(u, NamedSharding(mesh, P(None, AXIS)))
    return _build_run(grid, mesh, nsteps)(u, jnp.asarray(t),
                                          jnp.asarray(tend))
