"""Snapshot I/O tests.

Three layers, mirroring the reference's oracle design (SURVEY.md §4):
record-level roundtrips, full dump/load/leaf-cell extraction, and an
independent byte-offset walk that reproduces the arithmetic of the
reference checker (``tests/visu/visu_ramses.py:120-310``) to prove our
files match the ``output_amr.f90`` record layout byte for byte.
"""

import io
import os
import struct

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.config import load_params, params_from_dict
from ramses_tpu.io import fortran as frt
from ramses_tpu.io import reader as rdr
from ramses_tpu.io import snapshot as snap


def test_fortran_record_roundtrip():
    buf = io.BytesIO()
    a = np.arange(7, dtype=np.int32)
    b = np.linspace(0, 1, 5)
    frt.write_record(buf, a)
    frt.write_record(buf, b)
    frt.write_ints(buf, 3, 4, 5)
    frt.write_str(buf, "hilbert", 128)
    buf.seek(0)
    assert np.array_equal(frt.read_ints(buf), a)
    assert np.allclose(frt.read_reals(buf), b)
    assert np.array_equal(frt.read_ints(buf), [3, 4, 5])
    assert frt.read_str(buf) == "hilbert"


def _sod_params(ndim=2, lmin=4, lmax=None):
    groups = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": lmin, "levelmax": lmax or lmin,
                       "boxlen": 1.0},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.25, 0.75], "y_center": [0.5, 0.5],
                        "z_center": [0.5, 0.5],
                        "length_x": [0.5, 0.5], "length_y": [10.0, 10.0],
                        "length_z": [10.0, 10.0],
                        "exp_region": [10.0, 10.0],
                        "d_region": [1.0, 0.125],
                        "p_region": [1.0, 0.1]},
        "hydro_params": {"gamma": 1.4, "courant_factor": 0.8,
                         "riemann": "hllc", "slope_type": 1},
        "refine_params": {"err_grad_d": 0.05, "err_grad_p": 0.05},
        "output_params": {"noutput": 1, "tout": [0.1], "tend": 0.1},
    }
    return params_from_dict(groups, ndim=ndim)


def _uniform_sim(ndim=2, lmin=4):
    from ramses_tpu.driver import Simulation
    p = _sod_params(ndim=ndim, lmin=lmin)
    sim = Simulation(p, dtype=jnp.float64)
    sim.output_times = [0.05]
    return sim


def test_uniform_dump_and_leaf_cells(tmp_path):
    sim = _uniform_sim(ndim=2, lmin=4)
    sim.evolve()
    out = sim.dump(iout=1, base_dir=str(tmp_path))
    assert os.path.isdir(out)
    s = rdr.load_snapshot(out)
    assert s["info"]["ncpu"] == 1
    assert s["info"]["ndim"] == 2
    cells = rdr.leaf_cells(s)
    n = 16
    assert len(cells["density"]) == n * n
    # mass conservation: sum rho*dx^2 equals device total
    mass_snap = np.sum(cells["density"] * cells["dx"] ** 2)
    u = np.asarray(sim.state.u)
    mass_dev = u[0].sum() * sim.dx ** 2
    assert np.isclose(mass_snap, mass_dev, rtol=1e-12)
    # positions are cell centers
    xs = np.sort(np.unique(np.round(cells["x"], 12)))
    assert np.allclose(xs, (np.arange(n) + 0.5) / n)
    # velocity is primitive (u = mom/rho)
    i = np.argmax(cells["density"])


def test_scaffold_hierarchy_complete(tmp_path):
    """Every level 1..levelmin is present, fully refined below levelmin."""
    sim = _uniform_sim(ndim=2, lmin=3)
    out = sim.dump(iout=1, base_dir=str(tmp_path))
    amr = rdr.read_amr_file(os.path.join(out, "amr_00001.out00001"))
    for l in range(1, 4):
        assert l in amr.levels
        assert len(amr.levels[l]["ind_grid"]) == 4 ** (l - 1)
    assert np.all(amr.levels[1]["son"] > 0)
    assert np.all(amr.levels[2]["son"] > 0)
    assert np.all(amr.levels[3]["son"] == 0)
    # son ids of level l point into level l+1's id range
    ids2 = amr.levels[2]["ind_grid"]
    assert set(amr.levels[1]["son"].ravel()) == set(ids2)


def _visu_style_walk(amr_path, ncpu, levelmax, ndim):
    """Byte-offset walk replicating the reference oracle's arithmetic
    (``tests/visu/visu_ramses.py:144-310``) for the single-cpu case.
    Returns (nx, noutput, ngridlevel, xg_by_level, son_by_level)."""
    with open(amr_path, "rb") as f:
        content = f.read()

    def offset(ninteg, nlines, nfloat, nstrin=0, nquadr=0):
        return 4 * ninteg + 8 * (nlines + nfloat) + nstrin + nquadr * 16

    # nx, ny, nz at ninteg=2, nlines=2
    o = offset(2, 2, 0) + 4
    nx, ny, nz = struct.unpack("3i", content[o:o + 12])
    ncoarse = nx * ny * nz
    # nboundary at ninteg=7, nlines=5
    o = offset(7, 5, 0) + 4
    nboundary = struct.unpack("i", content[o:o + 4])[0]
    # noutput at ninteg=9, nfloat=1, nlines=8
    o = offset(9, 8, 1) + 4
    noutput = struct.unpack("i", content[o:o + 4])[0]
    # numbl at ninteg=14+2*ncpu*lmax, nfloat=18+2*noutput+2*lmax, nlines=21
    ninteg = 14 + 2 * ncpu * levelmax
    nfloat = 18 + 2 * noutput + 2 * levelmax
    o = offset(ninteg, 21, nfloat) + 4
    ngridlevel = np.asarray(struct.unpack(
        "%ii" % (ncpu * levelmax),
        content[o:o + 4 * ncpu * levelmax])).reshape(levelmax, ncpu).T
    # bound-key record size
    ninteg = 14 + 3 * ncpu * levelmax + 10 * levelmax + 5
    nlines = 21 + 2 + 3 * min(1, nboundary) + 1 + 1
    o = offset(ninteg, nlines, nfloat, nstrin=128)
    key_size = struct.unpack("i", content[o:o + 4])[0]

    ninteg1 = (14 + 3 * ncpu * levelmax + 10 * levelmax + 5 + 3 * ncoarse)
    nfloat1 = 18 + 2 * noutput + 2 * levelmax
    nlines1 = 21 + 2 + 3 * min(1, nboundary) + 1 + 1 + 1 + 3
    nstrin1 = 128 + key_size

    twotondim = 2 ** ndim
    xg_by_level, son_by_level = {}, {}
    for ilevel in range(levelmax):
        ninteg_a, nfloat_a = ninteg1, nfloat1
        nlines_a, nstrin_a = nlines1, nstrin1
        for j in range(nboundary + ncpu):
            ncache = ngridlevel[j, ilevel]
            if ncache > 0:
                # xg records
                ninteg = ninteg_a + ncache * 3
                nlines = nlines_a + 3
                xg = np.zeros((ncache, ndim))
                for n in range(ndim):
                    o = offset(ninteg, nlines,
                               nfloat_a + n * (ncache + 1), nstrin_a) + 4
                    xg[:, n] = struct.unpack(
                        "%id" % ncache, content[o:o + 8 * ncache])
                # son records
                ninteg = ninteg_a + ncache * (4 + 2 * ndim)
                nfloat = nfloat_a + ncache * ndim
                nlines = nlines_a + 4 + 3 * ndim
                son = np.zeros((ncache, twotondim), dtype=np.int32)
                for ind in range(twotondim):
                    o = offset(ninteg + ind * ncache, nlines + ind,
                               nfloat, nstrin_a) + 4
                    son[:, ind] = struct.unpack(
                        "%ii" % ncache, content[o:o + 4 * ncache])
                xg_by_level[ilevel + 1] = xg
                son_by_level[ilevel + 1] = son
                ninteg_a += ncache * (4 + 3 * twotondim + 2 * ndim)
                nfloat_a += ncache * ndim
                nlines_a += 4 + 3 * twotondim + 3 * ndim
        ninteg1, nfloat1 = ninteg_a, nfloat_a
        nlines1, nstrin1 = nlines_a, nstrin_a
    return nx, noutput, ngridlevel, xg_by_level, son_by_level


def test_oracle_byte_offsets(tmp_path):
    """Our amr file parses identically through the reference oracle's
    byte-offset arithmetic and through our record reader."""
    sim = _uniform_sim(ndim=3, lmin=3)
    out = sim.dump(iout=1, base_dir=str(tmp_path))
    path = os.path.join(out, "amr_00001.out00001")
    ours = rdr.read_amr_file(path)
    h = ours.header
    nx, noutput, ngridlevel, xg_lv, son_lv = _visu_style_walk(
        path, h["ncpu"], h["nlevelmax"], h["ndim"])
    assert nx == h["nx"]
    assert noutput == h["noutput"]
    assert np.array_equal(ngridlevel, h["numbl"])
    for l, lev in ours.levels.items():
        assert np.allclose(xg_lv[l], lev["xg"])
        assert np.array_equal(son_lv[l], lev["son"])


def test_hydro_file_primitive_vars(tmp_path):
    sim = _uniform_sim(ndim=2, lmin=4)
    out = sim.dump(iout=1, base_dir=str(tmp_path))
    s = rdr.load_snapshot(out)
    cells = rdr.leaf_cells(s)
    assert s["var_names"] == ["density", "velocity_x", "velocity_y",
                              "pressure"]
    # initial sod state: left density 1, right 0.125; pressure 1 / 0.1
    left = cells["x"] < 0.5
    assert np.allclose(cells["density"][left], 1.0)
    assert np.allclose(cells["density"][~left], 0.125)
    assert np.allclose(cells["pressure"][left], 1.0)
    assert np.allclose(cells["pressure"][~left], 0.1)


def test_amr_dump_and_leaf_cells(tmp_path):
    from ramses_tpu.amr.hierarchy import AmrSim
    p = _sod_params(ndim=2, lmin=3, lmax=5)
    sim = AmrSim(p, dtype=jnp.float64)
    sim.evolve(0.02)
    out = sim.dump(iout=1, base_dir=str(tmp_path))
    s = rdr.load_snapshot(out)
    cells = rdr.leaf_cells(s)
    assert len(cells["density"]) == sim.ncell_leaf()
    # leaf volume tiles the box exactly
    assert np.isclose(np.sum(cells["dx"] ** 2), 1.0, rtol=1e-12)
    # conserved mass matches the sim's own audit
    mass = np.sum(cells["density"] * cells["dx"] ** 2)
    assert np.isclose(mass, sim.totals()[0], rtol=1e-12)
    assert cells["level"].max() == 5
    assert cells["level"].min() >= 3


def test_restart_uniform_roundtrip(tmp_path):
    from ramses_tpu.driver import Simulation
    sim = _uniform_sim(ndim=2, lmin=4)
    sim.evolve()
    out = sim.dump(iout=1, base_dir=str(tmp_path))
    p2 = _sod_params(ndim=2, lmin=4)
    sim2 = Simulation.from_snapshot(p2, out, dtype=jnp.float64)
    assert np.isclose(sim2.state.t, sim.state.t)
    assert sim2.state.nstep == sim.state.nstep
    # conservative state reproduced to writer/reader roundtrip precision
    assert np.allclose(np.asarray(sim2.state.u), np.asarray(sim.state.u),
                       rtol=1e-13, atol=1e-13)
    # and it keeps evolving
    sim2.output_times = [0.08]
    sim2.state.iout = 1
    sim2.evolve()
    assert sim2.state.t > sim.state.t


def test_restart_amr_roundtrip(tmp_path):
    from ramses_tpu.amr.hierarchy import AmrSim
    p = _sod_params(ndim=2, lmin=3, lmax=5)
    sim = AmrSim(p, dtype=jnp.float64)
    sim.evolve(0.02)
    out = sim.dump(iout=1, base_dir=str(tmp_path))
    sim2 = AmrSim.from_snapshot(_sod_params(ndim=2, lmin=3, lmax=5), out,
                                dtype=jnp.float64)
    assert np.isclose(sim2.t, sim.t)
    for l in sim.levels():
        assert sim2.tree.noct(l) == sim.tree.noct(l)
        nc = sim.maps[l].noct * 4
        assert np.allclose(np.asarray(sim2.u[l])[:nc],
                           np.asarray(sim.u[l])[:nc],
                           rtol=1e-13, atol=1e-13)
    sim2.evolve(0.03)
    assert sim2.t > sim.t


def test_particle_file_roundtrip(tmp_path):
    from ramses_tpu.pm.particles import ParticleSet
    rng = np.random.default_rng(7)
    n = 100
    x = rng.random((n, 3))
    v = rng.standard_normal((n, 3))
    m = rng.random(n)
    ps = ParticleSet.make(x, v, m)
    sim = _uniform_sim(ndim=3, lmin=3)
    sim.state.p = ps
    out = sim.dump(iout=2, base_dir=str(tmp_path))
    s = rdr.load_snapshot(out)
    assert "part" in s
    part = s["part"][0]
    assert part["npart"] == n
    assert np.allclose(part["position_x"], x[:, 0])
    assert np.allclose(part["velocity_z"], v[:, 2])
    assert np.allclose(part["mass"], m)
    assert np.array_equal(part["identity"], np.arange(1, n + 1))
    # header family counts
    with open(os.path.join(out, "header_00002.txt")) as f:
        lines = f.readlines()
    fams = dict(line.split() for line in lines[1:-2])
    assert int(fams["DM"]) == n


def test_restart_particles(tmp_path):
    from ramses_tpu.driver import Simulation
    from ramses_tpu.io.restart import restore_particles
    from ramses_tpu.io import reader
    rng = np.random.default_rng(3)
    from ramses_tpu.pm.particles import ParticleSet
    n = 17
    ps = ParticleSet.make(rng.random((n, 3)), rng.standard_normal((n, 3)),
                          rng.random(n))
    sim = _uniform_sim(ndim=3, lmin=3)
    sim.state.p = ps
    out = sim.dump(iout=1, base_dir=str(tmp_path))
    s = reader.load_snapshot(out)
    part = s["part"][0]
    ps2 = restore_particles(part, 3)
    assert np.allclose(np.asarray(ps2.x), np.asarray(ps.x))
    assert np.allclose(np.asarray(ps2.v), np.asarray(ps.v))
    assert np.allclose(np.asarray(ps2.m), np.asarray(ps.m))


ORACLE_PATH = "/root/reference/tests/visu/visu_ramses.py"


def _load_oracle():
    """Import the reference suite's snapshot parser verbatim."""
    import importlib.util
    import os
    if not os.path.exists(ORACLE_PATH):
        pytest.skip("reference oracle not available")
    spec = importlib.util.spec_from_file_location("visu_ramses",
                                                  ORACLE_PATH)
    visu = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(visu)
    return visu


@pytest.mark.smoke
def test_reference_oracle_reads_our_snapshot(tmp_path, monkeypatch):
    """Execute the REFERENCE's own snapshot parser
    (``/root/reference/tests/visu/visu_ramses.py`` load_snapshot, run
    verbatim) against a dumped output directory — the byte-compat claim
    certified by the upstream oracle itself, not a re-implementation."""
    import jax.numpy as jnp

    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.config import params_from_dict

    visu = _load_oracle()
    g = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 3, "levelmax": 4, "boxlen": 1.0},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.5, 0.5], "y_center": [0.5, 0.5],
                        "z_center": [0.5, 0.5],
                        "length_x": [10.0, 0.25], "length_y": [10.0, 0.25],
                        "length_z": [10.0, 0.25],
                        "exp_region": [10.0, 2.0],
                        "d_region": [1.0, 8.0], "p_region": [0.1, 4.0]},
        "hydro_params": {"gamma": 1.4},
        "refine_params": {"err_grad_d": 0.2},
        "output_params": {"tend": 0.01},
    }
    sim = AmrSim(params_from_dict(g, ndim=3), dtype=jnp.float64)
    sim.evolve(0.004, nstepmax=2)
    sim.dump(1, str(tmp_path))

    monkeypatch.chdir(tmp_path)                # oracle reads from CWD
    data = visu.load_snapshot(1)
    d = data["data"]
    # cell census matches the live hierarchy's leaf count
    assert d["ncells"] == sim.ncell_leaf()
    # conservation: oracle-parsed mass == live totals
    m_oracle = float((d["density"] * d["dx"] ** 3).sum())
    assert np.isclose(m_oracle, sim.totals()[0], rtol=1e-12)
    # geometry: positions in-box, dx consistent with levels
    for ax in "xyz":
        assert (d[ax] > 0).all() and (d[ax] < 1).all()
    assert set(np.round(np.log2(1.0 / d["dx"])).astype(int)) \
        <= set(sim.levels())
    # energy column round-trips through the primitive conversion
    vel2 = d["velocity_x"] ** 2 + d["velocity_y"] ** 2 \
        + d["velocity_z"] ** 2
    e_oracle = float(((d["pressure"] / 0.4
                       + 0.5 * d["density"] * vel2)
                      * d["dx"] ** 3).sum())
    assert np.isclose(e_oracle, sim.totals()[4], rtol=1e-12)


def test_reference_oracle_reads_sink_csv(tmp_path, monkeypatch):
    """The oracle's sink/stellar CSV readers parse our companions."""
    import jax.numpy as jnp

    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.config import params_from_dict

    visu = _load_oracle()
    g = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 3, "levelmax": 4, "boxlen": 1.0},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.5, 0.5], "y_center": [0.5, 0.5],
                        "z_center": [0.5, 0.5],
                        "length_x": [10.0, 0.3], "length_y": [10.0, 0.3],
                        "length_z": [10.0, 0.3],
                        "exp_region": [10.0, 2.0],
                        "d_region": [0.1, 100.0],
                        "p_region": [0.05, 1.0]},
        "hydro_params": {"gamma": 5.0 / 3.0},
        "refine_params": {"err_grad_d": 0.3},
        "sink_params": {"create_sinks": True, "n_sink": 10.0,
                        "accretion_scheme": "threshold", "c_acc": 0.2},
        "stellar_params": {"stellar_msink_th": 0.002, "lt_t0": 1.0,
                           "sn_e_ref": 0.0},
        "units_params": {"units_density": 1.66e-24,
                         "units_time": 3.15e13,
                         "units_length": 3.08e18},
        "output_params": {"tend": 0.02},
    }
    sim = AmrSim(params_from_dict(g, ndim=3), dtype=jnp.float64)
    sim.evolve(0.01, nstepmax=3)
    assert sim.sinks.n > 0 and sim.stellar.n > 0
    sim.dump(1, str(tmp_path))

    monkeypatch.chdir(tmp_path)
    data = visu.load_snapshot(1)
    assert data["sinks"]["nsinks"] == sim.sinks.n
    np.testing.assert_allclose(np.sort(data["sinks"]["msink"]),
                               np.sort(sim.sinks.m), rtol=1e-9)
    assert data["stellars"]["nstellars"] == sim.stellar.n
    np.testing.assert_allclose(np.sort(data["stellars"]["mstellar"]),
                               np.sort(sim.stellar.m), rtol=1e-9)


@pytest.mark.smoke
def test_noncubic_box_roundtrip(tmp_path):
    """A 2x1x1 coarse grid round-trips snapshot -> restart (VERDICT r3
    item 8: arbitrary coarse dims, ref amr/init_amr.f90:37-60)."""
    from ramses_tpu.driver import Simulation

    p = load_params("namelists/sedov3d.nml", ndim=3)
    p.amr.levelmin = p.amr.levelmax = 4
    p.amr.nx = 2
    p.run.nstepmax = 3
    sim = Simulation(p)
    assert sim.grid.shape == (32, 16, 16)
    sim.evolve()
    out = sim.dump(iout=1, base_dir=str(tmp_path))
    # header carries the coarse dims; level-1 oct grid is 2x1x1
    from ramses_tpu.io import reader as rdr
    snap = rdr.load_snapshot(out)
    h = snap["amr"][0].header
    assert (h["nx"], h["ny"], h["nz"]) == (2, 1, 1)
    xg1 = snap["amr"][0].levels[1]["xg"]
    assert len(xg1) == 2 and xg1[:, 0].max() > 1.0   # two roots along x
    back = Simulation.from_snapshot(p, out)
    np.testing.assert_allclose(np.asarray(back.state.u),
                               np.asarray(sim.state.u),
                               rtol=1e-6, atol=1e-9)
    assert back.state.t == pytest.approx(sim.state.t)
    # evolving the restart works (boundary wrap across the long axis)
    back.params.run.nstepmax = back.state.nstep + 2
    back.evolve()
    assert np.isfinite(np.asarray(back.state.u)).all()


def test_noncubic_box_amr_gates_unsupported_physics():
    # plain hydro AMR now RUNS on non-cubic roots (tests/test_amr.py
    # TestNonCubicAmr); the unported physics layers must refuse loudly
    p = load_params("namelists/sedov3d.nml", ndim=3)
    p.amr.levelmin, p.amr.levelmax = 4, 5
    p.amr.ny = 3
    p.run.pic = True
    from ramses_tpu.amr.hierarchy import AmrSim
    with pytest.raises(NotImplementedError, match="non-cubic"):
        AmrSim(p)
