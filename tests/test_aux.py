"""Auxiliary subsystem tests: timers, movie frames, map tools, lightcone."""

import time

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.io.movie import MovieWriter, project, read_frame, write_frame
from ramses_tpu.pm.lightcone import cone_selection
from ramses_tpu.utils.maps import amr2map, main as maps_main, part2map
from ramses_tpu.utils.timers import Timers


def test_timers_accumulate():
    tm = Timers()
    tm.timer("a")
    time.sleep(0.02)
    tm.timer("b")
    time.sleep(0.01)
    tm.stop()
    assert tm.acc["a"] >= 0.015
    assert tm.acc["b"] >= 0.005
    rep = tm.output_timer()
    assert "a" in rep and "total" in rep


def test_timer_section():
    tm = Timers()
    tm.timer("outer")
    with tm.section("inner"):
        time.sleep(0.01)
    time.sleep(0.005)
    tm.stop()
    assert tm.acc["inner"] >= 0.008
    assert tm.acc["outer"] >= 0.003


def test_frame_roundtrip(tmp_path):
    data = np.arange(12.0).reshape(3, 4)
    p = str(tmp_path / "f.map")
    write_frame(p, data, t=1.5, bounds=(0, 1, 0, 2))
    fr = read_frame(p)
    assert fr["t"] == 1.5
    assert fr["bounds"] == (0, 1, 0, 2)
    assert np.allclose(fr["data"], data)


def test_frame_version_tag(tmp_path):
    """New frames carry the .map layout version in the header; legacy
    5-double headers read back as version 0 (their non-square frames
    are orientation-ambiguous — the shape convention predates the tag)."""
    from ramses_tpu.io import fortran as frt
    from ramses_tpu.io.movie import MAP_FORMAT_VERSION

    p = str(tmp_path / "v1.map")
    write_frame(p, np.arange(12.0).reshape(3, 4))
    assert read_frame(p)["version"] == MAP_FORMAT_VERSION == 1

    legacy = str(tmp_path / "v0.map")
    arr = np.arange(12.0).reshape(3, 4).astype(np.float32)
    with open(legacy, "wb") as f:
        frt.write_record(f, np.asarray([2.5, 0, 1, 0, 1],
                                       dtype=np.float64))
        frt.write_record(f, np.asarray(arr.shape, dtype=np.int32))
        frt.write_record(f, arr.T.ravel())
    fr = read_frame(legacy)
    assert fr["version"] == 0 and fr["t"] == 2.5
    assert np.allclose(fr["data"], arr)


def test_frame_shape_sanity_check(tmp_path):
    """A frame whose data record disagrees with its shape record fails
    loudly instead of reshaping garbage."""
    from ramses_tpu.io import fortran as frt

    bad = str(tmp_path / "bad.map")
    with open(bad, "wb") as f:
        frt.write_record(f, np.asarray([0.0, 0, 1, 0, 1, 1.0],
                                       dtype=np.float64))
        frt.write_record(f, np.asarray([3, 4], dtype=np.int32))
        frt.write_record(f, np.zeros(7, dtype=np.float32))  # != 3*4
    with pytest.raises(ValueError, match="nw\\*nh"):
        read_frame(bad)


def test_project_kinds():
    f = jnp.asarray(np.arange(27.0).reshape(3, 3, 3))
    assert np.allclose(np.asarray(project(f, 0, "sum")),
                       np.asarray(f).sum(0))
    assert np.allclose(np.asarray(project(f, 2, "max")),
                       np.asarray(f).max(2))
    assert np.allclose(np.asarray(project(f, 1, "slice")),
                       np.asarray(f)[:, 1, :])


def _sod_sim(tmp_path):
    from ramses_tpu.config import params_from_dict
    from ramses_tpu.driver import Simulation
    groups = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 4, "levelmax": 4, "boxlen": 1.0},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.25, 0.75], "y_center": [0.5, 0.5],
                        "length_x": [0.5, 0.5], "length_y": [10.0, 10.0],
                        "exp_region": [10.0, 10.0],
                        "d_region": [1.0, 0.125],
                        "p_region": [1.0, 0.1]},
        "hydro_params": {"riemann": "hllc"},
        "output_params": {"noutput": 1, "tout": [0.05], "tend": 0.05},
    }
    return Simulation(params_from_dict(groups, ndim=2), dtype=jnp.float64)


def test_movie_writer(tmp_path):
    sim = _sod_sim(tmp_path)
    mw = MovieWriter(str(tmp_path / "movie"), fields=("density",
                                                      "pressure"))
    paths = mw.emit(sim)
    assert len(paths) == 2
    fr = read_frame(paths[0])
    assert fr["data"].shape == (16, 16)
    assert np.isclose(fr["data"].max(), 1.0, atol=1e-5)


def test_amr2map_and_cli(tmp_path):
    sim = _sod_sim(tmp_path)
    out = sim.dump(iout=1, base_dir=str(tmp_path))
    m = amr2map(out, var="density", axis=2, nx=16)
    assert m.shape == (16, 16)
    # left half dense, right half light
    assert np.isclose(m[2, 8], 1.0, atol=1e-6)
    assert np.isclose(m[13, 8], 0.125, atol=1e-6)
    # CLI end-to-end
    mapfile = str(tmp_path / "d.map")
    assert maps_main(["amr2map", out, mapfile, "--nx", "16"]) == 0
    fr = read_frame(mapfile)
    assert fr["data"].shape == (16, 16)


def test_part2map(tmp_path):
    from ramses_tpu.pm.particles import ParticleSet
    sim = _sod_sim(tmp_path)
    rng = np.random.default_rng(0)
    n = 50
    sim.state.p = ParticleSet.make(
        np.column_stack([np.full(n, 0.3), rng.uniform(0, 1, n)]),
        np.zeros((n, 2)), np.full(n, 2.0))
    out = sim.dump(iout=2, base_dir=str(tmp_path))
    m = part2map(out, axis=2, nx=8)
    # all mass lands in column x≈0.3 → bin 2
    assert np.isclose(m[2].sum(), 100.0 * 8 ** 2, rtol=1e-12)
    assert m[5].sum() == 0.0


def test_cone_selection_shell():
    rng = np.random.default_rng(1)
    x = rng.uniform(0, 1, (5000, 3))
    pos, r, idx = cone_selection(x, obs=(0.5, 0.5, 0.5), r1=0.6, r2=1.1,
                                 boxlen=1.0)
    assert (r >= 0.6).all() and (r < 1.1).all()
    # shell volume fraction sanity: V = 4π/3 (r2³−r1³)
    vol = 4 * np.pi / 3 * (1.1 ** 3 - 0.6 ** 3)
    assert abs(len(r) / 5000 / vol - 1.0) < 0.1
    # opening angle restricts the count
    pos2, r2_, _ = cone_selection(x, obs=(0.5, 0.5, 0.5), r1=0.6, r2=1.1,
                                  opening=np.pi / 8)
    assert 0 < len(r2_) < len(r)
    mu = pos2[:, 2] / r2_
    assert (mu >= np.cos(np.pi / 8) - 1e-12).all()


def test_movie_multicamera_zoom(tmp_path):
    """NMOV cameras: per-camera axis/shader/zoom window, one movieN/
    directory each (amr/movie.f90 proj_axis + xcentre/deltax_frame)."""
    import numpy as np

    from ramses_tpu.io.movie import Camera, MovieWriter, read_frame

    class FakeSim:
        pass

    from ramses_tpu.config import params_from_dict
    p = params_from_dict({
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 4, "levelmax": 4, "boxlen": 1.0},
        "init_params": {"nregion": 1, "region_type": ["square"],
                        "x_center": [0.5], "y_center": [0.5],
                        "z_center": [0.5],
                        "length_x": [10.0], "length_y": [10.0],
                        "length_z": [10.0], "exp_region": [10.0],
                        "d_region": [1.0], "p_region": [1.0]},
        "hydro_params": {"gamma": 1.4},
        "output_params": {"tend": 1.0}}, ndim=3)
    from ramses_tpu.hydro.core import HydroStatic

    n = 16
    u = np.zeros((5, n, n, n))
    u[0] = 1.0
    u[0, 8:12, 8:12, :] += np.arange(n)       # x/y column, z gradient
    u[4] = 2.5
    sim = FakeSim()

    class St:
        pass

    sim.state = St()
    sim.state.u = u
    sim.state.t = 0.25
    sim.cfg = HydroStatic.from_params(p)
    sim.params = p

    cams = [Camera(axis=2, kind="max"),
            Camera(axis=0, kind="mean",
                   center=(0.5, 0.6, 0.5), delta=(1.0, 0.5, 0.5))]
    mw = MovieWriter(str(tmp_path / "mov"), fields=("density",),
                     cameras=cams)
    paths = mw.emit(sim)
    assert len(paths) == 2
    f1 = read_frame(paths[0])
    assert f1["data"].shape == (n, n)
    assert f1["t"] == 0.25
    f2 = read_frame(paths[1])                 # zoomed camera: cropped
    assert f2["data"].shape == (8, 8)
    assert "movie1" in paths[0] and "movie2" in paths[1]


def test_movie_emit_amr(tmp_path):
    """Live-AMR frames: leaves block-fill the finest grid."""
    import numpy as np

    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.config import params_from_dict
    from ramses_tpu.io.movie import MovieWriter, read_frame

    g = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 4, "levelmax": 5, "boxlen": 1.0},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.5, 0.5], "y_center": [0.5, 0.5],
                        "z_center": [0.5, 0.5],
                        "length_x": [10.0, 0.25], "length_y": [10.0, 0.25],
                        "length_z": [10.0, 0.25],
                        "exp_region": [10.0, 2.0],
                        "d_region": [1.0, 10.0], "p_region": [0.1, 5.0]},
        "hydro_params": {"gamma": 5.0 / 3.0},
        "refine_params": {"err_grad_d": 0.2},
        "output_params": {"tend": 0.01},
    }
    import jax.numpy as jnp
    sim = AmrSim(params_from_dict(g, ndim=3), dtype=jnp.float64)
    mw = MovieWriter(str(tmp_path / "mov"), fields=("density",))
    paths = mw.emit_amr(sim)
    fr = read_frame(paths[0])
    assert fr["data"].shape == (32, 32)
    c = fr["data"][16, 16]
    assert c > fr["data"][2, 2]               # blob visible


def test_movie_params_wiring(tmp_path):
    """&MOVIE_PARAMS drives on-the-fly frames from the namelist in both
    drivers (movie=.true., proj_axis cameras, imov cadence)."""

    import jax.numpy as jnp
    import numpy as np

    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.config import params_from_dict
    from ramses_tpu.driver import Simulation

    g = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 4, "levelmax": 4, "boxlen": 2.0},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [1.0, 1.0], "y_center": [1.0, 1.0],
                        "z_center": [1.0, 1.0],
                        "length_x": [20.0, 0.5], "length_y": [20.0, 0.5],
                        "length_z": [20.0, 0.5],
                        "exp_region": [10.0, 2.0],
                        "d_region": [1.0, 10.0], "p_region": [0.1, 5.0]},
        "hydro_params": {"gamma": 5.0 / 3.0},
        "movie_params": {"movie": True, "proj_axis": "zx", "imov": 1,
                         "movie_vars_txt": ["density"]},
        "output_params": {"tend": 0.01,
                          "output_dir": str(tmp_path)},
    }
    sim = Simulation(params_from_dict({k: dict(v) for k, v in g.items()},
                                      ndim=3), dtype=jnp.float64)
    assert sim.movie is not None and len(sim.movie.cameras) == 2
    sim.evolve()
    cam1 = tmp_path / "movie" / "movie1"
    assert len(list(cam1.glob("density_*.map"))) >= 1
    # default windows cover the WHOLE boxlen=2 grid (box fractions)
    from ramses_tpu.io.movie import read_frame
    fr = read_frame(str(sorted(cam1.glob("density_*.map"))[0]))
    assert fr["data"].shape == (16, 16)
    assert fr["data"].max() > 5.0          # blob visible, not a corner

    g["amr_params"]["levelmax"] = 5
    g["refine_params"] = {"err_grad_d": 0.2}
    g["output_params"]["output_dir"] = str(tmp_path / "amr")
    sim2 = AmrSim(params_from_dict(g, ndim=3), dtype=jnp.float64)
    sim2.evolve(0.005, nstepmax=2)
    cam1a = tmp_path / "amr" / "movie" / "movie1"
    assert len(list(cam1a.glob("density_*.map"))) >= 1


def test_lightcone_rotation():
    """Narrow-cone observer rotation: the rotated frame's opening cut
    selects the particles the unrotated frame sees along the rotated
    axis (light_cone.f90 compute_rotation_matrix)."""
    import numpy as np

    from ramses_tpu.pm.lightcone import cone_selection, rotation_matrix

    rng = np.random.default_rng(0)
    x = rng.uniform(0, 1, (2000, 3))
    obs = [0.5, 0.5, 0.5]
    R = rotation_matrix(thetay=np.pi / 2)     # rotated z' = -x
    # opening cone along z in the ROTATED frame == along -x unrotated
    px, pr, pi = cone_selection(x, obs, 0.05, 0.45, opening=0.3,
                                rotation=R)
    qx, qr, qi = cone_selection(x, obs, 0.05, 0.45, opening=0.3,
                                axis=(-1.0, 0, 0))
    assert set(pi.tolist()) == set(qi.tolist())
    np.testing.assert_allclose(np.sort(pr), np.sort(qr), rtol=1e-12)


def test_movie_shader_bank(tmp_path):
    """Extended shader bank (amr/movie.f90 i_mv_*): speed field,
    varmin/varmax exclusion, smoothing, and particle-deposition maps."""
    import jax.numpy as jnp

    from ramses_tpu.io.movie import Camera, MovieWriter, read_frame
    from ramses_tpu.pm.particles import FAM_DM, FAM_STAR

    n = 16
    u = np.zeros((5, n, n, n))
    u[0] = 1.0
    u[0, :, :, :8] = 5.0               # dense half (z < 0.5)
    u[1] = 2.0                         # mom_x: v = 2 (light), 0.4 (dense)
    u[4] = 10.0
    # varmin=1 keeps only the fast (light) cells in the projection
    cam = Camera(axis=2, kind="mean", varmin=1.0)
    mw = MovieWriter(str(tmp_path / "m"), fields=("speed", "dm",
                                                  "stars"),
                     cameras=[cam])

    class P:
        x = np.array([[0.25, 0.25, 0.5], [0.75, 0.75, 0.5]])
        m = np.array([3.0, 7.0])
        family = np.array([FAM_DM, FAM_STAR], dtype=np.int8)
        active = np.array([True, True])

    class Sim:
        class state:
            u = jnp.asarray(np.ones((5, n, n, n)))
            t = 0.0
            p = P()
        cfg = type("C", (), {"gamma": 1.4, "nvar": 5, "ndim": 3,
                             "nener": 0})()

    Sim.state.u = jnp.asarray(u)
    paths = mw.emit(Sim())
    frames = {p.split("/")[-1].split("_")[0]: read_frame(p)
              for p in paths}
    # speed: 2 in light cells, 0.4 in dense cells; varmin=1 excludes
    # the dense half -> masked mass-weighted mean = 2.0
    np.testing.assert_allclose(frames["speed"]["data"], 2.0, rtol=1e-6)
    # particle surface densities integrate to the family masses
    px = (1.0 / n) ** 2
    assert frames["dm"]["data"].sum() * px == pytest.approx(3.0)
    assert frames["stars"]["data"].sum() * px == pytest.approx(7.0)
    # smoothing conserves the map integral
    cam2 = Camera(axis=2, kind="sum", smooth=2.0)
    mw2 = MovieWriter(str(tmp_path / "m2"), fields=("density",),
                      cameras=[cam2])
    paths2 = mw2.emit(Sim())
    f2 = read_frame(paths2[0])
    assert f2["data"].sum() == pytest.approx(
        np.asarray(Sim.state.u)[0].sum(axis=2).sum(), rel=1e-5)
