"""Exact Riemann problem solution for test oracles (Toro ch. 4).

Independent analytic reference — NOT the solver under test — used to
validate shock-tube runs the same way the reference suite ships
``sod-tube-ana.dat`` analytic curves.
"""

from __future__ import annotations

import numpy as np
from scipy.optimize import brentq


def exact_riemann(rl, ul, pl, rr, ur, pr, gamma, x, t, x0=0.5):
    """Sample the exact solution of a 1D Riemann problem at positions x."""
    cl = np.sqrt(gamma * pl / rl)
    cr = np.sqrt(gamma * pr / rr)
    g1 = (gamma - 1.0) / (2.0 * gamma)
    g2 = (gamma + 1.0) / (2.0 * gamma)
    g3 = 2.0 * gamma / (gamma - 1.0)
    g4 = 2.0 / (gamma - 1.0)
    g5 = 2.0 / (gamma + 1.0)
    g6 = (gamma - 1.0) / (gamma + 1.0)
    g7 = (gamma - 1.0) / 2.0

    def fK(p, rK, pK, cK):
        if p > pK:  # shock
            aK = g5 / rK
            bK = g6 * pK
            return (p - pK) * np.sqrt(aK / (p + bK))
        return g4 * cK * ((p / pK) ** g1 - 1.0)  # rarefaction

    def f(p):
        return fK(p, rl, pl, cl) + fK(p, rr, pr, cr) + (ur - ul)

    pstar = brentq(f, 1e-12, 10.0 * max(pl, pr))
    ustar = 0.5 * (ul + ur) + 0.5 * (fK(pstar, rr, pr, cr)
                                     - fK(pstar, rl, pl, cl))

    rho = np.empty_like(x)
    u = np.empty_like(x)
    p = np.empty_like(x)
    s = (x - x0) / max(t, 1e-300)

    for i, si in enumerate(s):
        if si <= ustar:  # left of contact
            if pstar > pl:  # left shock
                sL = ul - cl * np.sqrt(g2 * pstar / pl + g1)
                if si < sL:
                    rho[i], u[i], p[i] = rl, ul, pl
                else:
                    rho[i] = rl * ((pstar / pl + g6) / (g6 * pstar / pl + 1))
                    u[i], p[i] = ustar, pstar
            else:  # left rarefaction
                shead = ul - cl
                cstar = cl * (pstar / pl) ** g1
                stail = ustar - cstar
                if si < shead:
                    rho[i], u[i], p[i] = rl, ul, pl
                elif si > stail:
                    rho[i] = rl * (pstar / pl) ** (1.0 / gamma)
                    u[i], p[i] = ustar, pstar
                else:
                    u[i] = g5 * (cl + g7 * ul + si)
                    c = g5 * (cl + g7 * (ul - si))
                    rho[i] = rl * (c / cl) ** g4
                    p[i] = pl * (c / cl) ** g3
        else:  # right of contact
            if pstar > pr:  # right shock
                sR = ur + cr * np.sqrt(g2 * pstar / pr + g1)
                if si > sR:
                    rho[i], u[i], p[i] = rr, ur, pr
                else:
                    rho[i] = rr * ((pstar / pr + g6) / (g6 * pstar / pr + 1))
                    u[i], p[i] = ustar, pstar
            else:  # right rarefaction
                shead = ur + cr
                cstar = cr * (pstar / pr) ** g1
                stail = ustar + cstar
                if si > shead:
                    rho[i], u[i], p[i] = rr, ur, pr
                elif si < stail:
                    rho[i] = rr * (pstar / pr) ** (1.0 / gamma)
                    u[i], p[i] = ustar, pstar
                else:
                    u[i] = g5 * (-cr + g7 * ur + si)
                    c = g5 * (cr - g7 * (ur - si))
                    rho[i] = rr * (c / cr) ** g4
                    p[i] = pr * (c / cr) ** g3
    return rho, u, p
