"""Sink particles: creation, accretion, merging, motion.

Capability core of ``pm/sink_particle.f90`` (3,010 LoC): density-threshold
creation at local maxima (the clump-finder-seeded path reduces to this on
a uniform grid), Bondi and threshold accretion (``grow_sink:575``,
``accrete_sink:722``), pairwise merging, leapfrog motion in the gas
gravity field.  Sinks are few (≤ thousands): all bookkeeping is host
numpy; only the gas-side mass removal touches device arrays.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from ramses_tpu.units import C_CGS, Units, factG_in_cgs


@dataclass(frozen=True)
class SinkSpec:
    """&SINK_PARAMS subset (pm/read_sink_feedback_params.f90)."""
    enabled: bool = False
    n_sink: float = 1e10           # creation threshold [H/cc]
    accretion_scheme: str = "bondi"   # bondi | threshold | none
    c_acc: float = 0.75            # threshold-accretion fraction
    r_acc_cells: float = 2.0       # accretion radius in cells
    merging_cells: float = 2.0     # merge radius in cells
    nsinkmax: int = 1000
    # AGN thermal feedback (``pm/sink_particle.f90`` agn branch /
    # Teyssier+11): E = eps_c * eps_r * dM c^2 dumped into the host
    # cell; the radiated eps_r share never reaches the sink mass
    agn: bool = False
    eps_r: float = 0.1             # radiative efficiency
    eps_c: float = 0.15            # coupling efficiency
    # direct sink-sink N^2 gravity during the drift (the reference's
    # ``direct_force_sink`` smbh option)
    direct_force: bool = False
    # cloud sampling: accretion samples a lattice of cloud points
    # within radius 0.5*ir_cloud*dx_min (``create_cloud_from_sink``,
    # pm/sink_particle.f90:131); 1 = host cell only
    ir_cloud: int = 4

    @classmethod
    def from_params(cls, p) -> "SinkSpec":
        raw = p.raw.get("sink_params", {}) if p.raw else {}

        def g(k, dflt):
            v = raw.get(k, dflt)
            return v[0] if isinstance(v, list) else v

        return cls(enabled=bool(g("create_sinks", False)),
                   n_sink=float(g("n_sink", 1e10)),
                   accretion_scheme=str(g("accretion_scheme", "bondi")),
                   c_acc=float(g("c_acc", 0.75)),
                   r_acc_cells=float(g("r_acc_cells", 2.0)),
                   merging_cells=float(g("merging_cells", 2.0)),
                   nsinkmax=int(g("nsinkmax", 1000)),
                   agn=bool(g("agn", False)),
                   eps_r=float(g("eps_r", 0.1)),
                   eps_c=float(g("eps_c", 0.15)),
                   direct_force=bool(g("direct_force", False)),
                   ir_cloud=int(g("ir_cloud", 4)))


def cloud_offsets(ndim: int, ir_cloud: int, dx: float) -> np.ndarray:
    """Cloud-point offsets: a dx/2-spaced lattice inside radius
    ``0.5*ir_cloud*dx`` (the reference's sink cloud particles,
    ``create_cloud_from_sink`` — equal-weight points that let the
    accretion kernel resolve the Bondi radius instead of sampling one
    host cell).  Always includes the centre point."""
    if ir_cloud <= 1:
        return np.zeros((1, ndim))
    half = 0.5 * dx
    r = 0.5 * ir_cloud * dx
    k = int(np.floor(r / half))
    ax = np.arange(-k, k + 1) * half
    grids = np.meshgrid(*([ax] * ndim), indexing="ij")
    pts = np.stack([g.ravel() for g in grids], axis=1)
    return pts[(pts ** 2).sum(axis=1) <= r * r + 1e-12]


@dataclass
class SinkSet:
    """SoA sink arrays (host)."""
    x: np.ndarray          # [n, ndim]
    v: np.ndarray          # [n, ndim]
    m: np.ndarray          # [n]
    tform: np.ndarray      # [n]
    idp: np.ndarray        # [n]
    next_id: int = 1

    @classmethod
    def empty(cls, ndim: int) -> "SinkSet":
        return cls(x=np.zeros((0, ndim)), v=np.zeros((0, ndim)),
                   m=np.zeros(0), tform=np.zeros(0),
                   idp=np.zeros(0, dtype=np.int64))

    @property
    def n(self) -> int:
        return len(self.m)


def create_sinks(u, sinks: SinkSet, spec: SinkSpec, units: Units,
                 dx: float, t: float, gamma: float):
    """Threshold creation (``create_sink:6``): cells above n_sink that are
    local density maxima and farther than the merge radius from existing
    sinks convert their excess gas into a new sink."""
    u = np.array(u)
    ndim = u.ndim - 1
    vol = dx ** ndim
    rho = u[0]
    nH = rho * units.scale_nH
    d_thr = spec.n_sink / units.scale_nH
    cand = nH > spec.n_sink
    if not cand.any() or sinks.n >= spec.nsinkmax:
        return u, sinks

    # local maximum over the 3^ndim neighbourhood (periodic)
    is_max = np.ones_like(cand)
    for d in range(ndim):
        for s in (-1, 1):
            is_max &= rho >= np.roll(rho, s, axis=d)
    cand &= is_max
    idx = np.argwhere(cand)
    if len(idx) == 0:
        return u, sinks

    xnew = (idx + 0.5) * dx
    # respect exclusion radius around existing sinks
    if sinks.n:
        d2 = ((xnew[:, None, :] - sinks.x[None, :, :]) ** 2).sum(-1)
        ok = (d2 > (spec.merging_cells * dx) ** 2).all(axis=1)
        idx, xnew = idx[ok], xnew[ok]
    room = spec.nsinkmax - sinks.n
    idx, xnew = idx[:room], xnew[:room]
    if len(idx) == 0:
        return u, sinks

    cells = tuple(idx.T)
    dm_rho = np.maximum(rho[cells] - d_thr, 0.0)
    mnew = dm_rho * vol
    vel = np.stack([u[1 + d][cells] / rho[cells] for d in range(ndim)],
                   axis=1)
    frac = 1.0 - dm_rho / rho[cells]
    for iv in range(u.shape[0]):
        u[iv][cells] = u[iv][cells] * frac

    sinks = SinkSet(
        x=np.concatenate([sinks.x, xnew]),
        v=np.concatenate([sinks.v, vel]),
        m=np.concatenate([sinks.m, mnew]),
        tform=np.concatenate([sinks.tform, np.full(len(idx), t)]),
        idp=np.concatenate([sinks.idp, sinks.next_id
                            + np.arange(len(idx), dtype=np.int64)]),
        next_id=sinks.next_id + len(idx))
    return u, sinks


def accrete(u, sinks: SinkSet, spec: SinkSpec, units: Units, dx: float,
            dt: float, gamma: float):
    """Accretion onto sinks (``grow_sink:575``, ``accrete_sink:722``).

    bondi:     mdot = 4π G² M² ρ / (c_s² + v_rel²)^{3/2}
    threshold: remove c_acc of the gas above n_sink in the host cell
    Both capped at 90% of the host cell's gas.
    """
    if sinks.n == 0 or spec.accretion_scheme == "none":
        return u, sinks
    u = np.array(u)
    ndim = u.ndim - 1
    vol = dx ** ndim
    shape = u.shape[1:]
    cells = tuple(np.clip((sinks.x[:, d] / dx).astype(np.int64), 0,
                          shape[d] - 1) for d in range(ndim))
    rho = u[0][cells]
    vgas = np.stack([u[1 + d][cells] / np.maximum(rho, 1e-300)
                     for d in range(ndim)], axis=1)
    ek = 0.5 * (np.stack([u[1 + d][cells] for d in range(ndim)], axis=1)
                ** 2).sum(1) / np.maximum(rho, 1e-300)
    press = (gamma - 1.0) * (u[1 + ndim][cells] - ek)
    cs2 = gamma * np.maximum(press, 1e-300) / np.maximum(rho, 1e-300)

    if spec.accretion_scheme == "bondi":
        # G in code units: G_code = G_cgs * scale_d * scale_t^2
        g_code = factG_in_cgs * units.scale_d * units.scale_t ** 2
        vrel2 = ((sinks.v - vgas) ** 2).sum(1)
        mdot = (4 * np.pi * g_code ** 2 * sinks.m ** 2 * rho
                / np.maximum(cs2 + vrel2, 1e-300) ** 1.5)
        dm = np.minimum(mdot * dt, 0.9 * rho * vol)
    else:  # threshold
        d_thr = spec.n_sink / units.scale_nH
        dm = np.minimum(spec.c_acc * np.maximum(rho - d_thr, 0.0) * vol,
                        0.9 * rho * vol)

    dm_rho = dm / vol
    frac = 1.0 - dm_rho / np.maximum(rho, 1e-300)
    # conservative momentum transfer: sink absorbs gas momentum
    mom_g = np.stack([u[1 + d][cells] for d in range(ndim)], axis=1)
    p_acc = mom_g * (dm_rho / np.maximum(rho, 1e-300))[:, None] * vol
    for iv in range(u.shape[0]):
        np.multiply.at(u[iv], cells, frac)
    m_gain = dm
    if spec.agn:
        # AGN thermal dump: eps_r of the accreted rest mass radiates,
        # eps_c of that couples to the host cell's gas energy
        e_agn, m_gain = agn_energy(dm, spec, units)
        np.add.at(u[1 + ndim], cells, e_agn / vol)
    newm = sinks.m + m_gain
    sinks.v = (sinks.v * sinks.m[:, None] + p_acc) \
        / np.maximum(newm, 1e-300)[:, None]
    sinks.m = newm
    return u, sinks


def agn_energy(dm: np.ndarray, spec: SinkSpec, units: Units):
    """(coupled AGN energy [code], sink mass gain) for accreted gas
    ``dm`` — the Teyssier+11 thermal quasar mode: L = eps_r dM c²,
    a fraction eps_c heats the host cell, the radiated share never
    reaches the sink (``pm/sink_particle.f90`` AGN branch)."""
    c_code = C_CGS / units.scale_v
    e_agn = spec.eps_c * spec.eps_r * dm * c_code ** 2
    return e_agn, (1.0 - spec.eps_r) * dm


def sink_sink_accel(sinks: SinkSet, g_code: float, soft: float,
                    boxlen: Optional[float] = None) -> np.ndarray:
    """Direct N² sink-sink gravitational acceleration with Plummer
    softening (``direct_force_sink``; N is tiny, so the all-pairs
    host loop is free).  ``boxlen`` applies the minimum-image
    convention — positions are stored wrapped, so a pair straddling a
    periodic face must attract ACROSS it."""
    x = sinks.x
    dxij = x[None, :, :] - x[:, None, :]          # [i, j, ndim]
    if boxlen is not None:
        dxij = dxij - boxlen * np.round(dxij / boxlen)
    r2 = (dxij ** 2).sum(-1) + soft ** 2
    np.fill_diagonal(r2, 1.0)
    w = g_code * sinks.m[None, :] / r2 ** 1.5
    np.fill_diagonal(w, 0.0)
    return (w[:, :, None] * dxij).sum(axis=1)


def direct_force_kick(sinks: SinkSet, units: Units, dx: float,
                      dt: float, boxlen: Optional[float]) -> SinkSet:
    """Apply the sink-sink N² kick (shared by the uniform and AMR
    drift paths; softening = dx/2 at the force resolution)."""
    if sinks.n < 2:
        return sinks
    g_code = factG_in_cgs * units.scale_d * units.scale_t ** 2
    sinks.v = sinks.v + sink_sink_accel(sinks, g_code, 0.5 * dx,
                                        boxlen=boxlen) * dt
    return sinks


def merge_sinks(sinks: SinkSet, spec: SinkSpec, dx: float) -> SinkSet:
    """Pairwise merge within the merge radius, conserving mass/momentum."""
    n = sinks.n
    if n < 2:
        return sinks
    alive = np.ones(n, dtype=bool)
    r2 = (spec.merging_cells * dx) ** 2
    order = np.argsort(-sinks.m)            # heaviest survives
    for a in order:
        if not alive[a]:
            continue
        d2 = ((sinks.x - sinks.x[a]) ** 2).sum(1)
        near = alive & (d2 < r2)
        near[a] = False
        if near.any():
            mt = sinks.m[a] + sinks.m[near].sum()
            sinks.x[a] = (sinks.x[a] * sinks.m[a]
                          + (sinks.x[near] * sinks.m[near, None]).sum(0)) / mt
            sinks.v[a] = (sinks.v[a] * sinks.m[a]
                          + (sinks.v[near] * sinks.m[near, None]).sum(0)) / mt
            sinks.m[a] = mt
            alive[near] = False
    return SinkSet(x=sinks.x[alive], v=sinks.v[alive], m=sinks.m[alive],
                   tform=sinks.tform[alive], idp=sinks.idp[alive],
                   next_id=sinks.next_id)


def drift_kick(sinks: SinkSet, f_field, dx: float, dt: float,
               boxlen: float, spec: Optional[SinkSpec] = None,
               units: Optional[Units] = None) -> SinkSet:
    """Leapfrog sink motion in the gas gravity field (NGP gather),
    plus the optional direct sink-sink N² force."""
    if sinks.n == 0:
        return sinks
    if f_field is not None:
        f = np.asarray(f_field)
        ndim = sinks.x.shape[1]
        shape = f.shape[1:]
        cells = tuple(np.clip((sinks.x[:, d] / dx).astype(np.int64), 0,
                              shape[d] - 1) for d in range(ndim))
        acc = np.stack([f[d][cells] for d in range(ndim)], axis=1)
        sinks.v = sinks.v + acc * dt
    if spec is not None and spec.direct_force and units is not None:
        sinks = direct_force_kick(sinks, units, dx, dt, boxlen)
    sinks.x = np.mod(sinks.x + sinks.v * dt, boxlen)
    return sinks
