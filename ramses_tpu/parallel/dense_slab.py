"""Explicit slab-sharded dense sweep for COMPLETE levels.

The global-view :func:`ramses_tpu.amr.kernels.dense_sweep` hands the
flat↔dense bit-permutation transpose to XLA's SPMD partitioner; on a
multi-chip mesh the partitioner cannot follow the bit-interleaved
reshape and falls back to "involuntary full rematerialization" — the
whole base grid is gathered to every chip and re-split each coarse
step (MULTICHIP_r05 tail).  This module is the EXPLICIT formulation:
the complete level's row batch stays sharded ``P("oct")`` exactly as
it already is, and a ``shard_map`` body does per device

1. a SHARD-LOCAL bit-permutation (:func:`ramses_tpu.amr.bitperm.
   flat_to_dense_slab`): a contiguous flat row chunk IS an axis-aligned
   dense sub-box (the top ``log2(ndev)`` flat bits are the most
   significant coordinate bits, z-major), so each chip converts only
   the rows it owns — no cross-chip gather exists;
2. a ring ``lax.ppermute`` halo exchange per cut axis (the pipeline
   proven in :mod:`ramses_tpu.parallel.halo`), sequenced axis-by-axis
   over the progressively extended block so corner ghosts fill with
   their true global values; uncut axes wrap locally;
3. the unchanged padded-interior kernel
   (:func:`ramses_tpu.amr.kernels.dense_interior_update`) on the local
   box — per-cell arithmetic identical to the global path, so mesh-of-1
   and mesh-of-N agree BITWISE (asserted in tests/test_dense_slab.py);
4. the inverse shard-local bit-permutation back to flat rows.

Geometry: the cut degenerates to z-slabs for 2 devices, (z, y) pencils
for 4, and octants for 8 — always aligned with oct boundaries.  Scope:
fully periodic cubic power-of-two levels with unpadded row batches and
a power-of-two device count; everything else falls back to the
global-view sweep (kept bitwise-pinned as the single-device reference).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ramses_tpu.amr import bitperm
from ramses_tpu.hydro import muscl
from ramses_tpu.parallel.mesh import OCT_AXIS


def _shard_map():
    try:
        return jax.shard_map                          # jax >= 0.8
    except AttributeError:
        from jax.experimental.shard_map import shard_map
        return shard_map


class SlabSpec(NamedTuple):
    """Static (hashable) description of one complete level's slab
    decomposition — rides inside ``FusedSpec`` as part of the jit key."""
    lvl: int
    ndim: int
    mbits: int             # log2(ndev): top flat bits = device index
    mesh: Mesh             # the 1-D "oct" mesh the rows shard over
    grid: Tuple[int, ...]  # device grid extent per axis (prod = ndev)
    loc: Tuple[int, ...]   # local dense sub-box shape per device
    # per-axis ppermute schedules ((fwd, bwd) pairs of (src, dst)
    # tuples) for cut axes; None = uncut (local periodic wrap)
    perms: tuple


def build_slab_spec(mesh: Mesh, lvl: int, ndim: int,
                    shape: Tuple[int, ...], ncell_pad: int,
                    bc_kinds) -> Optional[SlabSpec]:
    """SlabSpec for a complete level, or None when the level must keep
    the global-view path (non-periodic, non-cubic, padded rows, or a
    non-power-of-two / single-device mesh)."""
    if tuple(mesh.axis_names) != (OCT_AXIS,):
        return None
    ndev = int(mesh.devices.size)
    if ndev <= 1 or ndev & (ndev - 1):
        return None
    if tuple(shape) != (1 << lvl,) * ndim:
        return None
    ncell = (1 << lvl) ** ndim
    if ncell_pad != ncell:
        return None
    mbits = ndev.bit_length() - 1
    if mbits > ndim * (lvl - 1):
        return None
    if any(k != 0 for lohi in bc_kinds for k in lohi):
        return None                                   # periodic only
    gb = bitperm.grid_bits(lvl, ndim, mbits)
    grid = tuple(1 << b for b in gb)
    loc = bitperm.slab_shape(lvl, ndim, mbits)
    if any(loc[d] < muscl.NGHOST for d in range(ndim)):
        return None                                   # shard < stencil
    coords = bitperm.chunk_coords(lvl, ndim, mbits)
    dev_of = {g: D for D, g in enumerate(coords)}
    perms = []
    for d in range(ndim):
        if grid[d] == 1:
            perms.append(None)
            continue
        fwd = []
        bwd = []
        for D, g in enumerate(coords):
            up = list(g)
            dn = list(g)
            up[d] = (g[d] + 1) % grid[d]
            dn[d] = (g[d] - 1) % grid[d]
            fwd.append((D, dev_of[tuple(up)]))
            bwd.append((D, dev_of[tuple(dn)]))
        perms.append((tuple(fwd), tuple(bwd)))
    return SlabSpec(lvl=lvl, ndim=ndim, mbits=mbits, mesh=mesh,
                    grid=grid, loc=loc, perms=tuple(perms))


def _take(a, ax: int, sl: slice):
    idx = [slice(None)] * a.ndim
    idx[ax] = sl
    return a[tuple(idx)]


def halo_extend(a, spec: SlabSpec, ng: int, spatial0: int,
                axes=None):
    """Extend the local dense block by ``ng`` ghost cells on every
    spatial axis (axes ``spatial0 .. spatial0+ndim-1``): ring ppermute
    slabs on cut axes, local periodic wrap on uncut ones.  Later axes
    exchange the already-extended block, so corner ghosts carry their
    exact global-periodic values.  ``axes``: optional subset of the
    original spatial axes to extend (the pallas shard path leaves its
    lane axis bare for the in-kernel periodic roll)."""
    for d in range(spec.ndim):
        if axes is not None and d not in axes:
            continue
        ax = spatial0 + d
        if spec.perms[d] is None:
            pads = [(0, 0)] * a.ndim
            pads[ax] = (ng, ng)
            a = jnp.pad(a, pads, mode="wrap")
        else:
            fwd, bwd = spec.perms[d]
            lo = jax.lax.ppermute(_take(a, ax, slice(-ng, None)),
                                  OCT_AXIS, list(fwd))
            hi = jax.lax.ppermute(_take(a, ax, slice(0, ng)),
                                  OCT_AXIS, list(bwd))
            a = jnp.concatenate([lo, a, hi], axis=ax)
    return a


def dense_apply_slab(rows, spec: SlabSpec, local_fn, ng: int,
                     out_ndim: Optional[int] = None):
    """Generic slab engine: flat rows → per-shard dense sub-box →
    ``ng``-deep halo extension → ``local_fn(extended) -> [*loc,
    *trailing_out]`` → flat rows.  ``local_fn`` sees the block with the
    spatial axes LEADING (trailing feature axes untouched) and must
    return the un-extended local box.  ``out_ndim``: rank of the
    returned rows array (defaults to the input rank)."""
    sm = _shard_map()
    nd = spec.ndim

    def body(r_loc):
        dense = bitperm.flat_to_dense_slab(r_loc, spec.lvl, nd,
                                           spec.mbits)
        out = local_fn(halo_extend(dense, spec, ng, 0))
        return bitperm.dense_to_flat_slab(out, spec.lvl, nd, spec.mbits)

    in_spec = P(OCT_AXIS, *([None] * (rows.ndim - 1)))
    out_rank = out_ndim if out_ndim is not None else rows.ndim
    out_spec = P(OCT_AXIS, *([None] * (out_rank - 1)))
    return sm(body, mesh=spec.mesh, in_specs=(in_spec,),
              out_specs=out_spec)(rows)


def dense_sweep_slab(u_flat, ok_flat, dt, dx: float, spec: SlabSpec,
                     cfg, ret_flux: bool = False):
    """Slab-sharded complete-level hydro sweep — the explicit-comm
    formulation of :func:`ramses_tpu.amr.kernels.dense_sweep` (same
    physics, bitwise-identical du/phi).  ``ok_flat``: flat-row refined
    mask or None; ``dt`` traced scalar.  Returns du rows (+ phi rows
    when ``ret_flux``), sharded like the input."""
    from ramses_tpu.amr import kernels as K
    from ramses_tpu.hydro import pallas_muscl as pk

    sm = _shard_map()
    nd = spec.ndim
    ng = muscl.NGHOST
    masked = ok_flat is not None
    # per-shard fused TPU kernel: relabel an uncut %128 axis to the
    # kernel lane role; None (e.g. every CPU run, or all axes cut)
    # takes the shared XLA interior update
    cut = tuple(p is not None for p in spec.perms)
    kaxes = (pk.shard_axes(cfg, spec.loc, cut, u_flat.dtype)
             if nd == 3 else None)

    def body(u_loc, ok_loc, dt_):
        ud = bitperm.flat_to_dense_slab(u_loc, spec.lvl, nd, spec.mbits)
        ext = None if kaxes is None else kaxes[:2]
        up = halo_extend(jnp.moveaxis(ud, -1, 0), spec, ng, 1, axes=ext)
        okp = None
        if masked:
            # convert on the flat rows (clean shard-local op), halo the
            # arithmetic mask exactly like the state
            okd = bitperm.flat_to_dense_slab(
                ok_loc.astype(u_loc.dtype), spec.lvl, nd, spec.mbits)
            okp = halo_extend(okd, spec, ng, 0, axes=ext)
        if kaxes is not None:
            out = pk.fused_step_shard(up, okp, dt_, cfg, dx, spec.loc,
                                      kaxes, want_flux=ret_flux)
        else:
            out = K.dense_interior_update(up, okp, dt_, dx, spec.loc,
                                          cfg, ret_flux=ret_flux)
        du = out[0] if ret_flux else out
        du_rows = bitperm.dense_to_flat_slab(
            jnp.moveaxis(du, 0, -1), spec.lvl, nd, spec.mbits)
        if not ret_flux:
            return du_rows
        phi_rows = bitperm.dense_to_flat_slab(out[1], spec.lvl, nd,
                                              spec.mbits)
        return du_rows, phi_rows

    ok_in = P(OCT_AXIS) if masked else P()
    out_specs = ((P(OCT_AXIS, None), P(OCT_AXIS, None, None))
                 if ret_flux else P(OCT_AXIS, None))
    if not masked:
        # shard_map needs a concrete operand for every spec slot
        ok_flat = jnp.zeros((), u_flat.dtype)
    return sm(body, mesh=spec.mesh,
              in_specs=(P(OCT_AXIS, None), ok_in, P()),
              out_specs=out_specs)(u_flat, ok_flat, dt)


def dense_flags_slab(u_flat, spec: SlabSpec, flags_fn, twotondim: int):
    """Slab-sharded complete-level refinement flags: ``flags_fn`` maps
    the 1-ghost-extended local block ``[nvar, *loc+2]`` to a bool grid
    of the same spatial shape (the shared ``_grad_flags`` family); the
    interior is sliced here.  Returns ``[noct, 2^ndim]`` flags rows."""
    nd = spec.ndim

    def local_fn(dense_ext):
        ok = flags_fn(jnp.moveaxis(dense_ext, -1, 0))
        return ok[tuple(slice(1, -1) for _ in range(nd))]

    flags = dense_apply_slab(u_flat, spec, local_fn, ng=1, out_ndim=1)
    return flags.reshape(flags.shape[0] // twotondim, twotondim)
