"""Per-level gather/scatter index maps (host, numpy).

These are the TPU equivalents of the reference's per-step tree walks: the
6^ndim stencil gather of ``godfine1`` (``hydro/godunov_fine.f90:553-676``),
the buffer-cell interpolation requests (``:583-593``), the coarse-level
flux-correction targets (``nbor(ind_grid, 2*idim-1/2)``, ``:795-910``), and
the leaf→father restriction of ``upload_fine`` (``hydro/interpol_hydro.f90:5``).
Where the reference re-walks the tree for every nvector batch every step,
we materialize int32 index maps once per regrid (the ``build_comm``
amortization pattern, ``amr/virtual_boundaries.f90:1286``) and the per-step
work becomes pure XLA gathers/scatter-adds.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from ramses_tpu.amr import keys as kmod
from ramses_tpu.amr.tree import Octree, cell_offsets, map_coords


def bucket(n: int, lo: int = 16) -> int:
    """Pad count to power-of-2 buckets to bound jit recompiles
    (SURVEY.md §7 hard part 2)."""
    if n <= lo:
        return lo
    return 1 << int(np.ceil(np.log2(n)))


@dataclass
class LevelMaps:
    """All index maps of one level (numpy; hierarchy moves them to device)."""
    lvl: int
    noct: int
    noct_pad: int
    ni: int
    ni_pad: int
    # gather: src row for each stencil cell, into
    # concat(cells [ncell_pad], interp [ni_pad], trash [1])
    stencil_src: np.ndarray          # [noct_pad, 6^d] int32
    vsgn: Optional[np.ndarray]       # [noct_pad, 6^d] uint8 bitmask, or None
    ok_ref: np.ndarray               # [noct_pad, 6^d] bool: cell refined
    # interpolation requests (absent at levelmin: ni=0)
    interp_cell: np.ndarray          # [ni_pad] int32 flat cell idx at lvl-1
    interp_nb: np.ndarray            # [ni_pad, ndim, 2] int32 (left,right)
    interp_sgn: np.ndarray           # [ni_pad, ndim] int8 (±1 child offset)
    # coarse flux-correction targets (absent at levelmin)
    corr_idx: np.ndarray             # [noct_pad, ndim, 2] int32, -1 invalid
    # restriction (upload_fine) from lvl+1 into this level
    nref: int
    nref_pad: int
    ref_cell: np.ndarray             # [nref_pad] int32 flat cell idx, -1 pad
    son_oct: np.ndarray              # [nref_pad] int32 oct idx at lvl+1
    valid_oct: np.ndarray            # [noct_pad] bool
    # COMPLETE level (covers the whole box, e.g. the base level): the
    # sweep runs dense (roll-based uniform kernel) instead of through the
    # 6^d stencil gather — stencil/interp/corr maps above are then empty.
    complete: bool = False
    perm: Optional[np.ndarray] = None      # [ncell] flat row → dense ravel
    inv_perm: Optional[np.ndarray] = None  # [ncell] dense ravel → flat row
    ok_dense: Optional[np.ndarray] = None  # [ncell] bool refined, dense order
    # same mask in FLAT row order (shardable over contiguous row chunks
    # for the slab-sharded dense path, parallel/dense_slab.py)
    ok_flat: Optional[np.ndarray] = None   # [ncell] bool refined, flat order

    @property
    def ndim(self) -> int:
        return self.interp_sgn.shape[1]

    @property
    def ncell_pad(self) -> int:
        return self.noct_pad * 2 ** self.ndim


def stencil_offsets(ndim: int) -> np.ndarray:
    """[6^ndim, ndim] stencil offsets in row-major order, range 0..5
    (stencil cell coords = 2*og - 2 + offset)."""
    return np.indices((6,) * ndim).reshape(ndim, -1).T.astype(np.int64)


def _pad_rows(a: np.ndarray, n: int, fill=0) -> np.ndarray:
    out = np.full((n,) + a.shape[1:], fill, dtype=a.dtype)
    out[:len(a)] = a
    return out


def _restriction_maps(tree: Octree, lvl: int):
    """upload_fine source/target maps: (nref, nref_pad, ref_cell, son_oct,
    refined_mask-or-None).

    Built from the FINE level's oct list (every lvl+1 oct covers exactly
    one lvl cell), O(noct(lvl+1)) instead of a lookup over every lvl
    cell — the regrid hot path."""
    if not tree.has(lvl + 1):
        return 0, 8, np.full(8, -1, dtype=np.int32), \
            np.zeros(8, dtype=np.int32), None
    ndim = tree.ndim
    twotondim = 1 << ndim
    ref_all = tree.son_parent_cells(lvl)       # flat lvl cell per son oct
    son_all = np.nonzero(ref_all >= 0)[0]
    ref_idx = ref_all[son_all]
    order = np.argsort(ref_idx, kind="stable")  # deterministic map order
    ref_idx = ref_idx[order]
    son = son_all[order]                        # son octs in tree order
    nref = len(ref_idx)
    nref_pad = bucket(nref, 8)
    rmask = np.zeros(tree.noct(lvl) * twotondim, dtype=bool)
    rmask[ref_idx] = True
    return nref, nref_pad, _pad_rows(ref_idx.astype(np.int32), nref_pad, -1), \
        _pad_rows(son.astype(np.int32), nref_pad), rmask


def _interp_requests(tree: Octree, lvl: int, uniq_keys: np.ndarray,
                     bc_kinds: List[tuple]):
    """Coarse-cell interpolation maps for a sorted list of unique missing
    fine-cell Morton keys: (interp_cell, interp_nb, interp_sgn).

    Shared by the 6^d stencil maps and the blocked tile maps so the two
    gather paths interpolate bitwise-identical ghost values."""
    ndim = tree.ndim
    twotondim = 1 << ndim
    ucoords = kmod.decode(uniq_keys, ndim)             # fine cell coords
    ni = len(uniq_keys)
    ccoarse = ucoords >> 1                             # cell coords at lvl-1
    f_oct = tree.lookup(lvl - 1, ccoarse >> 1)
    if (f_oct < 0).any():
        raise RuntimeError(
            f"2:1 gradedness violated at level {lvl}: "
            f"{int((f_oct < 0).sum())} missing father octs")
    f_off = np.zeros(ni, dtype=np.int64)
    for d in range(ndim):
        f_off = f_off * 2 + (ccoarse[:, d] & 1)
    interp_cell = (f_oct * twotondim + f_off).astype(np.int32)
    interp_sgn = ((ucoords & 1) * 2 - 1).astype(np.int8)
    interp_nb = np.empty((ni, ndim, 2), dtype=np.int32)
    for d in range(ndim):
        for side, s in ((0, -1), (1, +1)):
            nc = ccoarse.copy()
            nc[:, d] += s
            ncm, nrefl = map_coords(nc, lvl - 1, bc_kinds, ndim,
                                    dims=tree.cell_dims(lvl - 1))
            n_oct = tree.lookup(lvl - 1, ncm >> 1)
            n_off = np.zeros(ni, dtype=np.int64)
            for d2 in range(ndim):
                n_off = n_off * 2 + (ncm[:, d2] & 1)
            flat = n_oct * twotondim + n_off
            # neighbour absent at lvl-1 (grade transition) or mirrored:
            # fall back to the centre cell (zero slope contribution) —
            # the reference walks up the tree instead
            # (amr/nbors_utils.f90:404); this degrades to 1st order
            # locally, which the minmod limiter tolerates.
            bad = (n_oct < 0) | nrefl.any(axis=1)
            interp_nb[:, d, side] = np.where(bad, interp_cell,
                                             flat).astype(np.int32)
    return interp_cell, interp_nb, interp_sgn


def build_level_maps(tree: Octree, lvl: int, bc_kinds: List[tuple],
                     noct_pad: Optional[int] = None) -> LevelMaps:
    ndim = tree.ndim
    twotondim = 1 << ndim
    lev = tree.levels[lvl]
    noct = lev.noct
    noct_pad = noct_pad or bucket(noct)
    ncell_pad = noct_pad * twotondim
    if noct == int(np.prod(tree.oct_dims(lvl))):
        return _build_complete_level_maps(tree, lvl, noct, noct_pad)
    soff = stencil_offsets(ndim)                       # [6^d, ndim]
    ns = len(soff)

    # --- stencil cell coords, BC-mapped ---
    fc = (2 * lev.og[:, None, :] - 2 + soff[None, :, :]).reshape(-1, ndim)
    mapped, refl = map_coords(fc, lvl, bc_kinds, ndim,
                              dims=tree.cell_dims(lvl))
    oc = mapped >> 1
    off = np.zeros(len(mapped), dtype=np.int64)
    for d in range(ndim):
        off = off * 2 + (mapped[:, d] & 1)
    oct_idx = tree.lookup(lvl, oc)
    exists = oct_idx >= 0

    # refined flag (``ok`` of godfine1): does the stencil cell have a son?
    if tree.has(lvl + 1):
        ok = tree.lookup(lvl + 1, mapped) >= 0
        ok &= exists
    else:
        ok = np.zeros(len(mapped), dtype=bool)

    # --- interpolation requests for missing stencil cells ---
    miss = ~exists
    if lvl > tree.levelmin and miss.any():
        miss_keys = kmod.encode(mapped[miss], ndim)
        uniq_keys, inv = np.unique(miss_keys, return_inverse=True)
        ni = len(uniq_keys)
        interp_cell, interp_nb, interp_sgn = _interp_requests(
            tree, lvl, uniq_keys, bc_kinds)
    else:
        ni = 0
        inv = None
        interp_cell = np.zeros(0, dtype=np.int32)
        interp_sgn = np.zeros((0, ndim), dtype=np.int8)
        interp_nb = np.zeros((0, ndim, 2), dtype=np.int32)

    ni_pad = bucket(ni, 8) if ni > 0 else 8
    trash = ncell_pad + ni_pad

    src = np.full(len(mapped), trash, dtype=np.int64)
    src[exists] = oct_idx[exists] * twotondim + off[exists]
    if ni > 0:
        src[miss] = ncell_pad + inv

    stencil_src = np.full((noct_pad, ns), trash, dtype=np.int32)
    stencil_src[:noct] = src.reshape(noct, ns).astype(np.int32)
    ok_ref = np.zeros((noct_pad, ns), dtype=bool)
    ok_ref[:noct] = ok.reshape(noct, ns)

    # velocity sign-flip bitmask for reflecting boundaries
    if refl.any():
        bits = np.zeros(len(mapped), dtype=np.uint8)
        for d in range(ndim):
            bits |= (refl[:, d].astype(np.uint8) << d)
        vsgn = np.zeros((noct_pad, ns), dtype=np.uint8)
        vsgn[:noct] = bits.reshape(noct, ns)
    else:
        vsgn = None

    # pad interp arrays
    def _pad(a, n, fill=0):
        out = np.full((n,) + a.shape[1:], fill, dtype=a.dtype)
        out[:len(a)] = a
        return out
    interp_cell = _pad(interp_cell, ni_pad)
    interp_nb = _pad(interp_nb, ni_pad)
    interp_sgn = _pad(interp_sgn, ni_pad, 1)

    # --- coarse flux-correction targets ---
    corr_idx = np.full((noct_pad, ndim, 2), -1, dtype=np.int32)
    if lvl > tree.levelmin:
        for d in range(ndim):
            for side, s in ((0, -1), (1, +1)):
                nc = lev.og.copy()                     # father cell coords
                nc[:, d] += s
                inb = nc[:, d]
                in_domain = np.ones(noct, dtype=bool)
                lo, hi = bc_kinds[d]
                n_l1 = tree.cell_dims(lvl - 1)[d]
                if lo == 0 and hi == 0:
                    nc[:, d] = np.mod(inb, n_l1)
                else:
                    # non-periodic: out-of-domain faces get no correction
                    in_domain = (inb >= 0) & (inb < n_l1)
                    nc[:, d] = np.clip(inb, 0, n_l1 - 1)
                # target must be a coarse leaf: no oct at lvl covering it
                covered = tree.lookup(lvl, nc) >= 0
                f_oct = tree.lookup(lvl - 1, nc >> 1)
                f_off = np.zeros(noct, dtype=np.int64)
                for d2 in range(ndim):
                    f_off = f_off * 2 + (nc[:, d2] & 1)
                flat = f_oct * twotondim + f_off
                valid = in_domain & ~covered & (f_oct >= 0)
                corr_idx[:noct, d, side] = np.where(valid, flat,
                                                    -1).astype(np.int32)

    # --- restriction map (upload_fine at this level) ---
    nref, nref_pad, ref_cell, son_oct, _rm = _restriction_maps(tree, lvl)

    valid_oct = np.zeros(noct_pad, dtype=bool)
    valid_oct[:noct] = True

    return LevelMaps(lvl=lvl, noct=noct, noct_pad=noct_pad, ni=ni,
                     ni_pad=ni_pad, stencil_src=stencil_src, vsgn=vsgn,
                     ok_ref=ok_ref, interp_cell=interp_cell,
                     interp_nb=interp_nb, interp_sgn=interp_sgn,
                     corr_idx=corr_idx, nref=nref, nref_pad=nref_pad,
                     ref_cell=ref_cell, son_oct=son_oct,
                     valid_oct=valid_oct)


def _build_complete_level_maps(tree: Octree, lvl: int, noct: int,
                               noct_pad: int) -> LevelMaps:
    """Maps for a level that covers the whole box: dense permutation +
    restriction only.  The stencil gather, ghost interpolation, and
    coarse flux correction are structurally absent — the sweep runs on
    the dense grid with physical boundaries, and every coarse parent
    cell is refined so corrections to lvl-1 all drop."""
    ndim = tree.ndim
    twotondim = 1 << ndim
    ncell = noct * twotondim
    dims = tree.cell_dims(lvl)
    cc = tree.cell_coords(lvl)
    perm = np.ravel_multi_index(
        tuple(cc[:, d] for d in range(ndim)), dims)
    inv_perm = np.empty(ncell, dtype=np.int64)
    inv_perm[perm] = np.arange(ncell)

    nref, nref_pad, ref_cell, son_oct, rmask = _restriction_maps(tree, lvl)
    if rmask is not None:
        ok_dense = np.zeros(ncell, dtype=bool)
        ok_dense[perm] = rmask
    else:
        ok_dense = None
    ok_flat = rmask

    valid_oct = np.zeros(noct_pad, dtype=bool)
    valid_oct[:noct] = True
    return LevelMaps(
        lvl=lvl, noct=noct, noct_pad=noct_pad, ni=0, ni_pad=8,
        stencil_src=np.zeros((0, 0), dtype=np.int32), vsgn=None,
        ok_ref=np.zeros((0, 0), dtype=bool),
        interp_cell=np.zeros(8, dtype=np.int32),
        interp_nb=np.zeros((8, ndim, 2), dtype=np.int32),
        interp_sgn=np.ones((8, ndim), dtype=np.int8),
        corr_idx=np.full((noct_pad, ndim, 2), -1, dtype=np.int32),
        nref=nref, nref_pad=nref_pad, ref_cell=ref_cell, son_oct=son_oct,
        valid_oct=valid_oct, complete=True,
        perm=perm.astype(np.int64), inv_perm=inv_perm, ok_dense=ok_dense,
        ok_flat=ok_flat)


def refresh_restriction(m: LevelMaps, tree: Octree) -> LevelMaps:
    """New LevelMaps with only the lvl+1-dependent parts rebuilt
    (restriction targets + dense refined mask) — used when a COMPLETE
    level's own oct set is unchanged across a regrid."""
    from dataclasses import replace

    nref, nref_pad, ref_cell, son_oct, rmask = _restriction_maps(tree,
                                                                 m.lvl)
    ok_dense = None
    if rmask is not None and m.perm is not None:
        ok_dense = np.zeros(len(m.perm), dtype=bool)
        ok_dense[m.perm] = rmask
    return replace(m, nref=nref, nref_pad=nref_pad, ref_cell=ref_cell,
                   son_oct=son_oct, ok_dense=ok_dense, ok_flat=rmask)


# ---------------------------------------------------------------------------
# Blocked Morton tile maps (gather-fused oct sweep)
# ---------------------------------------------------------------------------

NGHOST_TILE = 2       # MUSCL-Hancock halo width (slopes at ±1 need ±2)


def _flat_off_table(ndim: int) -> np.ndarray:
    """Morton low-bit pattern (x at bit 0) → flat cell offset (x slowest)."""
    n = 1 << ndim
    out = np.zeros(n, dtype=np.int64)
    for m in range(n):
        f = 0
        for d in range(ndim):
            f = f * 2 + ((m >> d) & 1)
        out[m] = f
    return out


@dataclass
class BlockMaps:
    """Morton-aligned oct-tile maps for the gather-fused partial sweep.

    Octs are grouped into aligned cubes of ``2**shift`` octs per side.
    Because the per-level oct list is Morton-sorted, every tile is a
    contiguous oct range and all of a tile's cells live in one dense
    ``td^ndim`` box (``2**(shift+1)`` interior cells per side plus a
    2-cell halo).  ``tile_src`` replaces the per-oct 6^ndim stencil
    gather of :class:`LevelMaps`: one compact row per tile slot instead
    of a ~(3^ndim)x duplicated per-oct batch, so the sweep's HBM gather
    traffic scales with tile volume, not stencil volume.
    """
    lvl: int
    shift: int                       # octs per tile side = 2**shift
    ntile: int
    ntile_pad: int
    ni: int
    ni_pad: int
    # gather: src row per tile slot into concat(cells, interp, trash)
    tile_src: np.ndarray             # [ntile_pad, td^d] int32
    tile_vsgn: Optional[np.ndarray]  # [ntile_pad, td^d] uint8, or None
    tile_ok: np.ndarray              # [ntile_pad, td^d] bool (cell refined)
    # interpolation requests (same semantics as LevelMaps)
    interp_cell: np.ndarray          # [ni_pad] int32
    interp_nb: np.ndarray            # [ni_pad, ndim, 2] int32
    interp_sgn: np.ndarray           # [ni_pad, ndim] int8
    # scatter-back maps (kernel tile outputs → flat rows / per-oct corr)
    cell_tile: np.ndarray            # [ncell_pad] int32 tile of each row
    cell_slot: np.ndarray            # [ncell_pad] int32 interior C^d slot
    oct_tile: np.ndarray             # [noct_pad] int32
    oct_slot: np.ndarray             # [noct_pad] int32 tile-local oct slot
    # incremental-rebuild state: per-tile slot geometry is a pure
    # function of (tile prefix, bc, level dims) — reusable across
    # regrids for every tile whose Morton prefix survives
    tile_key: np.ndarray             # [ntile] int64 prefixes, sorted
    slot_ckey: np.ndarray            # [ntile, td^d] int64 mapped cell key
    slot_vbits: Optional[np.ndarray]  # [ntile, td^d] uint8, or None
    noct: int = 0
    noct_pad: int = 0
    blocks_rebuilt: int = 0          # tiles whose geometry was re-derived

    @property
    def ndim(self) -> int:
        return self.interp_sgn.shape[1]

    @property
    def td(self) -> int:
        return (1 << (self.shift + 1)) + 2 * NGHOST_TILE

    @property
    def ncell_pad(self) -> int:
        return self.noct_pad * 2 ** self.ndim


def _shift0(a: np.ndarray, s: int, ax: int) -> np.ndarray:
    """Zero-padded shift of ``a`` by ``s`` along ``ax``."""
    b = np.zeros_like(a)
    n = a.shape[ax]
    src = [slice(None)] * a.ndim
    dst = [slice(None)] * a.ndim
    if s > 0:
        dst[ax], src[ax] = slice(s, n), slice(0, n - s)
    else:
        dst[ax], src[ax] = slice(0, n + s), slice(-s, n)
    b[tuple(dst)] = a[tuple(src)]
    return b


def _dilate2(mask: np.ndarray, ndim: int) -> np.ndarray:
    """Chebyshev-radius-2 binary dilation over the tile axes (1..ndim) —
    the MUSCL-Hancock influence radius of a cell."""
    out = mask
    for ax in range(1, ndim + 1):
        m = out
        for s in (1, 2):
            out = out | _shift0(m, s, ax) | _shift0(m, -s, ax)
    return out


def _tile_geometry(tree: Octree, lvl: int, tile_key: np.ndarray,
                   shift: int, bc_kinds: List[tuple]):
    """Tree-independent slot geometry of each tile: the BC-mapped cell
    Morton key and reflection bitmask for every td^ndim slot."""
    ndim = tree.ndim
    td = (1 << (shift + 1)) + 2 * NGHOST_TILE
    nslot = td ** ndim
    # tile origin in cell coords: decode the prefix back to oct coords
    org = kmod.decode(tile_key << (ndim * shift), ndim) * 2
    loc = np.indices((td,) * ndim).reshape(ndim, -1).T  # [nslot, ndim]
    gc = (org[:, None, :] + loc[None, :, :]
          - NGHOST_TILE).reshape(-1, ndim)
    mapped, refl = map_coords(gc, lvl, bc_kinds, ndim,
                              dims=tree.cell_dims(lvl))
    ckey = kmod.encode(mapped, ndim).reshape(len(tile_key), nslot)
    if refl.any():
        bits = np.zeros(len(gc), dtype=np.uint8)
        for d in range(ndim):
            bits |= (refl[:, d].astype(np.uint8) << d)
        vbits = bits.reshape(len(tile_key), nslot)
    else:
        vbits = None
    return ckey, vbits


def build_block_maps(tree: Octree, lvl: int, bc_kinds: List[tuple],
                     shift: int = 2, noct_pad: Optional[int] = None,
                     prev: Optional[BlockMaps] = None) -> BlockMaps:
    """Blocked tile maps for a partial level; with ``prev`` from the last
    regrid, slot geometry is re-derived only for tiles whose Morton
    prefix is new (``blocks_rebuilt`` counts them)."""
    ndim = tree.ndim
    twotondim = 1 << ndim
    lev = tree.levels[lvl]
    noct = lev.noct
    noct_pad = noct_pad or bucket(noct)
    ncell_pad = noct_pad * twotondim
    c = 1 << (shift + 1)
    td = c + 2 * NGHOST_TILE
    nslot = td ** ndim

    tile_key, oct_tile_r = np.unique(lev.keys >> (ndim * shift),
                                     return_inverse=True)
    ntile = len(tile_key)
    ntile_pad = bucket(ntile, 8)

    reuse = (prev is not None and prev.shift == shift
             and prev.lvl == lvl and len(prev.tile_key) > 0)
    if reuse:
        pos = np.searchsorted(prev.tile_key, tile_key)
        pos = np.clip(pos, 0, len(prev.tile_key) - 1)
        hit = prev.tile_key[pos] == tile_key
        new = ~hit
        slot_ckey = np.empty((ntile, nslot), dtype=np.int64)
        slot_ckey[hit] = prev.slot_ckey[pos[hit]]
        vb_new = None
        if new.any():
            ck_new, vb_new = _tile_geometry(tree, lvl, tile_key[new],
                                            shift, bc_kinds)
            slot_ckey[new] = ck_new
        if prev.slot_vbits is None and vb_new is None:
            slot_vbits = None
        else:
            slot_vbits = np.zeros((ntile, nslot), dtype=np.uint8)
            if prev.slot_vbits is not None:
                slot_vbits[hit] = prev.slot_vbits[pos[hit]]
            if vb_new is not None:
                slot_vbits[new] = vb_new
        rebuilt = int(new.sum())
    else:
        slot_ckey, slot_vbits = _tile_geometry(tree, lvl, tile_key,
                                               shift, bc_kinds)
        rebuilt = ntile

    # --- tree-dependent lookups (vectorized over all slots) ---
    ck = slot_ckey.reshape(-1)
    oct_idx = tree.lookup_keys(lvl, ck >> ndim)
    foff = _flat_off_table(ndim)[ck & (twotondim - 1)]
    exists = oct_idx >= 0
    if tree.has(lvl + 1):
        # the slot's cell key at lvl IS its covering oct key at lvl+1
        ok = tree.lookup_keys(lvl + 1, ck) >= 0
        ok &= exists
    else:
        ok = np.zeros(len(ck), dtype=bool)

    # Sparse tiles have holes/halo slots arbitrarily far from any real
    # oct — their fathers need not exist (2:1 gradedness only covers the
    # 1-oct neighbourhood), and their values cannot influence any kept
    # output (du/corr/phi read at most 2 cells from an existing oct).
    # Interpolate only the slots inside that influence radius; the rest
    # read the zero trash row.
    near = _dilate2(exists.reshape((ntile,) + (td,) * ndim),
                    ndim).reshape(-1)
    miss = near & ~exists
    if lvl > tree.levelmin and miss.any():
        uniq_keys, inv = np.unique(ck[miss], return_inverse=True)
        ni = len(uniq_keys)
        interp_cell, interp_nb, interp_sgn = _interp_requests(
            tree, lvl, uniq_keys, bc_kinds)
    else:
        ni = 0
        inv = None
        interp_cell = np.zeros(0, dtype=np.int32)
        interp_sgn = np.zeros((0, ndim), dtype=np.int8)
        interp_nb = np.zeros((0, ndim, 2), dtype=np.int32)
    ni_pad = bucket(ni, 8) if ni > 0 else 8
    trash = ncell_pad + ni_pad

    src = np.full(len(ck), trash, dtype=np.int64)
    src[exists] = oct_idx[exists] * twotondim + foff[exists]
    if ni > 0:
        src[miss] = ncell_pad + inv
    tile_src = np.full((ntile_pad, nslot), trash, dtype=np.int32)
    tile_src[:ntile] = src.reshape(ntile, nslot).astype(np.int32)
    tile_ok = np.zeros((ntile_pad, nslot), dtype=bool)
    tile_ok[:ntile] = ok.reshape(ntile, nslot)
    if slot_vbits is not None and slot_vbits.any():
        tile_vsgn = np.zeros((ntile_pad, nslot), dtype=np.uint8)
        tile_vsgn[:ntile] = slot_vbits
    else:
        tile_vsgn = None

    interp_cell = _pad_rows(interp_cell, ni_pad)
    interp_nb = _pad_rows(interp_nb, ni_pad)
    interp_sgn = _pad_rows(interp_sgn, ni_pad, 1)

    # per-oct scatter map: tile + tile-local oct slot (d=0 slowest)
    a = lev.og & ((1 << shift) - 1)
    oslot = np.zeros(noct, dtype=np.int64)
    for d in range(ndim):
        oslot = oslot * (1 << shift) + a[:, d]
    oct_tile = np.zeros(noct_pad, dtype=np.int32)
    oct_slot = np.zeros(noct_pad, dtype=np.int32)
    oct_tile[:noct] = oct_tile_r
    oct_slot[:noct] = oslot

    # per-cell scatter map: tile + interior C^d slot
    co = cell_offsets(ndim)
    gc = 2 * lev.og[:, None, :] + co[None, :, :]       # [noct, 2^d, ndim]
    lc = gc - 2 * ((lev.og >> shift) << shift)[:, None, :]
    cslot = np.zeros((noct, twotondim), dtype=np.int64)
    for d in range(ndim):
        cslot = cslot * c + lc[:, :, d]
    # pad rows must come out exactly zero (level_sweep zeroes them via
    # its ok masks, and the sharded-vs-single suites compare full
    # padded arrays): slot c^d flattens one past the interior batch,
    # where the kernels' reorder gathers an appended zero column
    cell_tile = np.zeros(ncell_pad, dtype=np.int32)
    cell_slot = np.full(ncell_pad, c ** ndim, dtype=np.int32)
    cell_tile[:noct * twotondim] = np.repeat(oct_tile_r, twotondim)
    cell_slot[:noct * twotondim] = cslot.reshape(-1)

    return BlockMaps(lvl=lvl, shift=shift, ntile=ntile,
                     ntile_pad=ntile_pad, ni=ni, ni_pad=ni_pad,
                     tile_src=tile_src, tile_vsgn=tile_vsgn,
                     tile_ok=tile_ok, interp_cell=interp_cell,
                     interp_nb=interp_nb, interp_sgn=interp_sgn,
                     cell_tile=cell_tile, cell_slot=cell_slot,
                     oct_tile=oct_tile, oct_slot=oct_slot,
                     tile_key=tile_key, slot_ckey=slot_ckey,
                     slot_vbits=slot_vbits, noct=noct,
                     noct_pad=noct_pad, blocks_rebuilt=rebuilt)


def build_prolong_maps(tree_new: Octree, tree_old: Octree, lvl: int,
                       bc_kinds: List[tuple]
                       ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                  np.ndarray, np.ndarray]:
    """Maps to fill level ``lvl`` of the new tree from old data.

    Returns (copy_dst, copy_src, new_father_cell, new_nb, new_sgn):
      * copy_dst/copy_src: oct indices new←old for octs that survived;
      * for brand-new octs: father-cell interpolation request against the
        NEW lvl-1 state (``make_grid_fine``, ``amr/refine_utils.f90:590``),
        one request per (new oct, child cell) in flat-cell order.
    """
    ndim = tree_new.ndim
    twotondim = 1 << ndim
    newlev = tree_new.levels[lvl]
    old_idx = tree_old.lookup_keys(lvl, newlev.keys) if tree_old.has(lvl) \
        else np.full(newlev.noct, -1, dtype=np.int64)
    kept = old_idx >= 0
    copy_dst = np.nonzero(kept)[0].astype(np.int32)
    copy_src = old_idx[kept].astype(np.int32)

    new_octs = np.nonzero(~kept)[0]
    nnew = len(new_octs)
    father = newlev.og[new_octs]                       # cell coords at lvl-1
    f_oct = tree_new.lookup(lvl - 1, father >> 1)
    if nnew and (f_oct < 0).any():
        raise RuntimeError("prolongation: father oct missing")
    f_off = np.zeros(nnew, dtype=np.int64)
    for d in range(ndim):
        f_off = f_off * 2 + (father[:, d] & 1)
    f_cell = (f_oct * twotondim + f_off).astype(np.int32)
    nb = np.empty((nnew, ndim, 2), dtype=np.int32)
    for d in range(ndim):
        for side, s in ((0, -1), (1, +1)):
            nc = father.copy()
            nc[:, d] += s
            ncm, nrefl = map_coords(nc, lvl - 1, bc_kinds, ndim,
                                    dims=tree_new.cell_dims(lvl - 1))
            n_oct = tree_new.lookup(lvl - 1, ncm >> 1)
            n_off = np.zeros(nnew, dtype=np.int64)
            for d2 in range(ndim):
                n_off = n_off * 2 + (ncm[:, d2] & 1)
            bad = (n_oct < 0) | nrefl.any(axis=1)
            nb[:, d, side] = np.where(
                bad, f_cell, n_oct * twotondim + n_off).astype(np.int32)
    return copy_dst, copy_src, new_octs.astype(np.int32), f_cell, nb


@dataclass
class GravityMaps:
    """Face-neighbour maps for the per-level Poisson solve
    (``poisson/multigrid_fine_*`` machinery reduced to index maps).

    ``nb[:, d, side]`` rows index concat(φ_cells [ncell_pad],
    ghosts [ng_pad], zero [1]); ghosts are fine cells whose neighbour
    lives on the coarser level (the Dirichlet BC ring of
    ``make_fine_bc_rhs``), filled by interpolating coarse φ.
    """
    lvl: int
    ncell: int
    ncell_pad: int
    ng: int
    ng_pad: int
    nb: np.ndarray           # [ncell_pad, ndim, 2] int32
    g_cell: np.ndarray       # [ng_pad] int32 coarse flat cell
    g_nb: np.ndarray         # [ng_pad, ndim, 2] int32 coarse neighbours
    g_sgn: np.ndarray        # [ng_pad, ndim] int8 child offset signs
    valid_cell: np.ndarray   # [ncell_pad] bool
    # oct-lattice adjacency (the level's own coarse grid, spacing 2*dx):
    # rows index concat(octs [noct_pad], zero [1]) — the coarse half of
    # the two-level preconditioner (multigrid_fine's coarse MG levels)
    oct_nb: Optional[np.ndarray] = None   # [noct_pad, ndim, 2] int32
    # deeper coarsened lattices of the SAME masked domain — the full
    # masked-multigrid ladder (multigrid_fine's levels below ifinelevel)
    # as tuple of (nb [n_j, ndim, 2], par_prev [n_{j-1}|noct_pad], n_j)
    mg: tuple = ()


def build_mg_lattices(og: np.ndarray, lvl: int, bc_kinds: List[tuple],
                      noct: int, noct_pad: int,
                      min_n: int = 32, root=None) -> tuple:
    """Coarsened lattices of a partial level's oct set for the masked
    multigrid V-cycle (``poisson/multigrid_fine_fine.f90`` level
    ladder): depth ``j`` holds the unique ``og >> j`` coords with
    face-neighbour maps (sentinel ``n_j`` = outside the mask, Dirichlet
    zero for the error equation) and the parent map from depth ``j-1``
    (depth 0 = the oct lattice itself, padded rows -> sentinel).
    Coarsening stops at ``min_n`` cells or a one-cell-wide box."""
    ndim = og.shape[1]
    root = tuple(root or (1,) * ndim)
    out = []
    prev_coords = og[:noct]
    prev_pad = noct_pad
    j = 1
    while True:
        shift = lvl - 1 - j
        sides = tuple(r << max(shift, 0) for r in root)
        # stop once another halving would merge ROOT cells (shift < 1):
        # the lattice below the root grid has no consistent topology
        if len(prev_coords) <= min_n or shift < 1:
            break
        coords = prev_coords >> 1
        keys = kmod.encode(coords, ndim)
        ukeys, inv = np.unique(keys, return_inverse=True)
        n = len(ukeys)
        if n == len(prev_coords):      # no coarsening progress: stop
            break
        ucoords = kmod.decode(ukeys, ndim)
        # bucket-padded shapes: jit signatures of the Poisson solve
        # stay stable across regrids (sentinel = n_pad, the zeros row)
        n_pad = bucket(n, 64)
        par = np.full(prev_pad, n_pad, dtype=np.int32)   # pads drop
        par[:len(inv)] = inv
        nb = np.full((n_pad, ndim, 2), n_pad, dtype=np.int32)
        for d in range(ndim):
            lo_k, hi_k = bc_kinds[d]
            for s_i, s in ((0, -1), (1, +1)):
                q = ucoords.copy()
                q[:, d] += s
                if lo_k == 0 and hi_k == 0:
                    q[:, d] = np.mod(q[:, d], sides[d])
                    inside = np.ones(n, dtype=bool)
                else:
                    inside = (q[:, d] >= 0) & (q[:, d] < sides[d])
                    q[:, d] = np.clip(q[:, d], 0, sides[d] - 1)
                qk = kmod.encode(q, ndim)
                pos = np.searchsorted(ukeys, qk)
                pos = np.clip(pos, 0, n - 1)
                hit = (ukeys[pos] == qk) & inside
                nb[:n, d, s_i] = np.where(hit, pos, n_pad).astype(
                    np.int32)
        out.append((nb, par, n))
        prev_coords = ucoords
        prev_pad = n_pad
        j += 1
    return tuple(out)


def build_gravity_maps(tree: Octree, lvl: int, bc_kinds: List[tuple],
                       noct_pad: Optional[int] = None) -> GravityMaps:
    """Build the 2·ndim face-neighbour map of a level's cells with
    coarse-ghost requests where the neighbour is unrefined."""
    ndim = tree.ndim
    twotondim = 1 << ndim
    lev = tree.levels[lvl]
    noct = lev.noct
    noct_pad = noct_pad or bucket(noct)
    ncell = noct * twotondim
    ncell_pad = noct_pad * twotondim

    cc = tree.cell_coords(lvl)                    # [ncell, ndim]
    nb_rows = np.zeros((ncell, ndim, 2), dtype=np.int64)
    miss_coords = []
    miss_where = []
    for d in range(ndim):
        for side, s in ((0, -1), (1, +1)):
            nc = cc.copy()
            nc[:, d] += s
            ncm, _refl = map_coords(nc, lvl, bc_kinds, ndim,
                                    dims=tree.cell_dims(lvl))
            oct_idx = tree.lookup(lvl, ncm >> 1)
            off = np.zeros(len(ncm), dtype=np.int64)
            for d2 in range(ndim):
                off = off * 2 + (ncm[:, d2] & 1)
            flat = oct_idx * twotondim + off
            ok = oct_idx >= 0
            nb_rows[:, d, side] = np.where(ok, flat, -1)
            if (~ok).any():
                miss_coords.append(ncm[~ok])
                miss_where.append((d, side, np.where(~ok)[0]))

    # unique ghost cells
    if miss_coords:
        allmiss = np.concatenate(miss_coords)
        keys = kmod.encode(allmiss, ndim)
        uniq, inv = np.unique(keys, return_inverse=True)
        ucoords = kmod.decode(uniq, ndim)
        ng = len(uniq)
        # interp requests from lvl-1 (same construction as hydro ghosts)
        ccoarse = ucoords >> 1
        f_oct = tree.lookup(lvl - 1, ccoarse >> 1)
        if (f_oct < 0).any():
            raise RuntimeError(f"gradedness violated at level {lvl}")
        f_off = np.zeros(ng, dtype=np.int64)
        for d in range(ndim):
            f_off = f_off * 2 + (ccoarse[:, d] & 1)
        g_cell = (f_oct * twotondim + f_off).astype(np.int32)
        g_sgn = ((ucoords & 1) * 2 - 1).astype(np.int8)
        g_nb = np.empty((ng, ndim, 2), dtype=np.int32)
        for d in range(ndim):
            for side, s in ((0, -1), (1, +1)):
                nc2 = ccoarse.copy()
                nc2[:, d] += s
                ncm2, nrefl = map_coords(nc2, lvl - 1, bc_kinds, ndim,
                                         dims=tree.cell_dims(lvl - 1))
                n_oct = tree.lookup(lvl - 1, ncm2 >> 1)
                n_off = np.zeros(ng, dtype=np.int64)
                for d2 in range(ndim):
                    n_off = n_off * 2 + (ncm2[:, d2] & 1)
                flat2 = n_oct * twotondim + n_off
                bad = (n_oct < 0) | nrefl.any(axis=1)
                g_nb[:, d, side] = np.where(bad, g_cell,
                                            flat2).astype(np.int32)
        # patch nb_rows with ghost slots
        pos = 0
        for chunk, (d, side, rows) in zip(miss_coords, miss_where):
            n = len(chunk)
            nb_rows[rows, d, side] = ncell_pad + inv[pos:pos + n]
            pos += n
    else:
        ng = 0
        g_cell = np.zeros(0, dtype=np.int32)
        g_sgn = np.zeros((0, ndim), dtype=np.int8)
        g_nb = np.zeros((0, ndim, 2), dtype=np.int32)

    ng_pad = bucket(ng, 8) if ng > 0 else 8
    zero_row = ncell_pad + ng_pad
    nb_rows[nb_rows < 0] = zero_row

    def _padg(a, n, fill=0):
        out = np.full((n,) + a.shape[1:], fill, dtype=a.dtype)
        out[:len(a)] = a
        return out

    nb = np.full((ncell_pad, ndim, 2), zero_row, dtype=np.int64)
    nb[:ncell] = nb_rows
    valid = np.zeros(ncell_pad, dtype=bool)
    valid[:ncell] = True

    # oct-lattice adjacency for the coarse preconditioner level
    oct_nb = np.full((noct_pad, ndim, 2), noct_pad, dtype=np.int32)
    for d in range(ndim):
        n_oct_lat = tree.oct_dims(lvl)[d]
        lo_k, hi_k = bc_kinds[d]
        for side, s in ((0, -1), (1, +1)):
            oc = lev.og.copy()
            oc[:, d] += s
            if lo_k == 0 and hi_k == 0:
                oc[:, d] = np.mod(oc[:, d], n_oct_lat)
                inside = np.ones(noct, dtype=bool)
            else:
                inside = (oc[:, d] >= 0) & (oc[:, d] < n_oct_lat)
                oc[:, d] = np.clip(oc[:, d], 0, n_oct_lat - 1)
            idx = tree.lookup(lvl, oc)
            found = (idx >= 0) & inside
            oct_nb[:noct, d, side] = np.where(found, idx,
                                              noct_pad).astype(np.int32)

    return GravityMaps(
        lvl=lvl, ncell=ncell, ncell_pad=ncell_pad, ng=ng, ng_pad=ng_pad,
        nb=nb.astype(np.int32),
        g_cell=_padg(g_cell, ng_pad), g_nb=_padg(g_nb, ng_pad),
        g_sgn=_padg(g_sgn, ng_pad), valid_cell=valid, oct_nb=oct_nb,
        mg=build_mg_lattices(lev.og, lvl, bc_kinds, noct,
                             noct_pad, root=tree.root))
