#!/usr/bin/env python
"""Component-level device-time breakdown of the steady-state AMR step.

The VERDICT-r04 mandate: find the measured 678x per-cell-update overhead
of the AMR path vs the uniform kernel WITH A MEASUREMENT, not a guess.
This tool times each device kernel of the fused coarse step in
isolation, at the exact live shapes of the bench configuration
(sedov3d levelmin=7 levelmax=9 by default), plus the candidate
conversions (index-gather vs bit-permutation transpose) side by side,
the blocked Morton-tile sweep vs the 6^3 stencil sweep, the regrid
sub-phases (flag/maps/migrate/upload), and the static HLO
gather-element inventory of the fused step.

Results land in a machine-readable JSON file (``PROF_JSON``, default
``PROF_AMR.json`` next to the repo root), rewritten ATOMICALLY after
every probe — a deadline-killed run leaves a classified partial capture
(``completed: false``, ``probe_errors``), never an empty one.  The
``##PROF##`` stdout line carries the same object.

Hang-proofing (the PR 7 ladder): run WITHOUT ``PROF_CHILD`` and the
parent re-executes itself as a killed-on-deadline subprocess
(``PROF_DEADLINE_S``, default 900) and classifies the outcome — rc 87
(watchdog hard-exit) and timeouts read the partial JSON back and stamp
``classification: "hang"`` plus the probe in flight.  Inside the child
every probe runs under a :class:`ramses_tpu.resilience.watchdog.
Watchdog` step guard (``PROF_PROBE_DEADLINE_S``, default 120 when
deadlines are armed): a wedged probe raises HangDetected (recorded,
remaining probes still run) and a truly uninterruptible one hard-exits
87 for the parent to classify.  ``bench.py`` runs the same probes as
the ``profile_amr`` sub under its own subprocess isolation.

Optionally wraps 3 steady-state steps in a ``jax.profiler.trace``
(PROFILE_TRACE_DIR env) for op-level inspection where the tensorboard
profile plugin exists.

Env: PROF_LMIN, PROF_LMAX, PROF_WARM, PROF_REPS, PROF_JSON,
PROF_DEADLINE_S, PROF_PROBE_DEADLINE_S, PROF_CHILD, PROFILE_TRACE_DIR.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MARKER = "##PROF##"


def timeit(fn, reps, sync):
    """Median-free simple wall: warm once (compile), sync, run reps,
    sync; returns seconds per call."""
    out = fn()
    sync(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    sync(out)
    return (time.perf_counter() - t0) / reps


def _sync(x):
    """Hard sync: host-fetch one element of every leaf (block_until_ready
    alone can return early over a tunneled device)."""
    import jax
    leaves = jax.tree_util.tree_leaves(x)
    jax.device_get([l.ravel()[:1] for l in leaves if hasattr(l, "ravel")])


def _json_path():
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.environ.get("PROF_JSON", os.path.join(here, "PROF_AMR.json"))


def _write_json(res):
    """Atomic incremental emission: the capture on disk is always a
    valid JSON object, partial or complete."""
    path = _json_path()
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(res, f, indent=1, default=str)
        os.replace(tmp, path)
    except OSError:
        pass


def collect(hb=lambda *a, **k: None, emit=None):
    """Run every probe, returning the result dict.  ``hb(phase)`` marks
    progress (bench.py heartbeat); ``emit(res)`` is called after every
    probe with the partial result (defaults to the atomic PROF_JSON
    write)."""
    import jax
    import jax.numpy as jnp

    from ramses_tpu.amr import bitperm
    from ramses_tpu.amr import kernels as K
    from ramses_tpu.amr.hierarchy import (AmrSim, _fused_coarse_step,
                                          _fused_courant)
    from ramses_tpu.config import load_params
    from ramses_tpu.utils.timers import NullTimers, Timers

    if emit is None:
        emit = _write_json

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    lmin = int(os.environ.get("PROF_LMIN", "7"))
    lmax = int(os.environ.get("PROF_LMAX", "9"))
    warm = int(os.environ.get("PROF_WARM", "15"))
    reps = int(os.environ.get("PROF_REPS", "10"))
    params = load_params(os.path.join(here, "namelists", "sedov3d.nml"),
                        ndim=3)
    params.amr.levelmin, params.amr.levelmax = lmin, lmax
    params.refine.err_grad_d = 0.1
    params.refine.err_grad_p = 0.1

    t = {}
    res = {"device": str(jax.devices()[0].platform),
           "reps": reps, "completed": False, "timings_s": t,
           "probe_errors": {}}

    # watchdog around every probe: armed when the parent mode or the
    # caller set a probe deadline — an interruptible wedge is recorded
    # and skipped, an uninterruptible one hard-exits HANG_EXIT_CODE
    dl = float(os.environ.get("PROF_PROBE_DEADLINE_S", "0") or 0.0)
    wd = None
    HangDetected = ()
    if dl > 0.0:
        from ramses_tpu.resilience import watchdog as wmod
        HangDetected = wmod.HangDetected
        wd = wmod.Watchdog(step_deadline_s=dl, hard_exit=True)
        wd._warmed = True              # no separate compile budget here

    def probe(name, fn):
        """One guarded probe; failures/hangs become probe_errors
        entries instead of killing the capture."""
        res["probe"] = name
        try:
            if wd is not None:
                with wd.guard("step"):
                    fn()
            else:
                fn()
        except HangDetected as e:      # soft-interrupted wedge
            res["probe_errors"][name] = f"hang: {e}"
        except Exception as e:         # noqa: BLE001 - capture survives
            res["probe_errors"][name] = repr(e)
        hb(name)
        emit(res)

    state = {}

    def p_init():
        sim = AmrSim(params, dtype=jnp.float32)
        # no telemetry here, so the sim defaults to NullTimers; install
        # a draining accumulator so the warm-up's changed-tree regrids
        # leave a growth-phase sub-phase breakdown for p_regrid
        sim.timers = Timers(sync=sim.drain)
        sim.evolve(1e9, nstepmax=warm)      # develop the blast + compile
        sim.timers.stop()
        state["growth_acc"] = dict(sim.timers.acc)
        sim.timers = NullTimers()   # don't let drains skew later probes
        sim.regrid_interval = 0             # freeze the tree
        state["sim"] = sim
        state["spec"] = sim._fused_spec()
        state["dt"] = jnp.asarray(sim.coarse_dt(), sim.dtype)
        res["octs_per_level"] = {str(l): sim.tree.noct(l)
                                 for l in sim.levels()}
        res["levels"] = list(sim.levels())
        res["blocked_levels"] = sorted(sim.blocks)
        res["block_stats"] = dict(sim.block_stats)
        res["tile_occupancy"] = {
            str(l): round(b.noct / (b.ntile * (1 << (3 * b.shift))), 4)
            for l, b in sim.blocks.items()}
    probe("init", p_init)
    if "sim" not in state:
        res["error"] = ("init probe failed: "
                        + str(res["probe_errors"].get("init")))
        emit(res)
        return res
    sim, spec, dt = state["sim"], state["spec"], state["dt"]

    # --- static HLO gather inventory of the fused step ---------------
    def p_hlo():
        from ramses_tpu.analysis import engine as aeng
        from ramses_tpu.telemetry import hlo as hmod
        txt = hmod.lower_fused_step(sim)
        inv = hmod.gather_inventory(txt)
        res["hlo_gather_elems"] = sum(n for n, _ in inv)
        res["hlo_gather_ops"] = len(inv)
        # unbaselined static-analysis findings of the same lowering
        res["analysis_findings"] = aeng.audit_sim(sim, text=txt)
    probe("hlo_inventory", p_hlo)

    # --- full fused coarse step (the steady-state unit of work) ------
    def p_step():
        # the step jit donates its state argument, so thread the
        # returned state through exactly like the evolve loop does
        def _step():
            out = _fused_coarse_step(sim.u, sim.dev, {}, dt, spec, None)
            sim.u = out[0]
            return out
        t["fused_coarse_step"] = timeit(_step, reps, _sync)
    probe("fused_coarse_step", p_step)

    # --- per-component, exact live shapes ----------------------------
    lb = sim.lmin
    d = sim.dev[lb]
    u0 = sim.u[lb]
    shape = (1 << lb,) * sim.cfg.ndim
    ncell = shape[0] ** sim.cfg.ndim

    def p_dense():
        t["dense_sweep_base"] = timeit(
            lambda: K.dense_sweep(u0, d.get("inv_perm"), d.get("perm"),
                                  d["ok_dense"], dt, sim.dx(lb), shape,
                                  sim.bspec, sim.cfg), reps, _sync)
    probe("dense_sweep_base", p_dense)

    def p_conv():
        # conversions: bit-permutation transpose vs index gather
        f2d = jax.jit(lambda u: bitperm.flat_to_dense(u, lb, 3))
        d2f = jax.jit(lambda ud: bitperm.dense_to_flat(ud, lb, 3))
        ud = f2d(u0)
        state["ud"] = ud
        t["flat_to_dense_bitperm"] = timeit(lambda: f2d(u0), reps, _sync)
        t["dense_to_flat_bitperm"] = timeit(lambda: d2f(ud), reps, _sync)
        m = sim.maps[lb]
        inv_perm = jnp.asarray(m.inv_perm)
        perm = jnp.asarray(m.perm)
        gat = jax.jit(lambda u, i: u[i])
        t["flat_to_dense_gather"] = timeit(lambda: gat(u0, inv_perm),
                                           reps, _sync)
        rows = u0[:ncell]
        t["dense_to_flat_gather"] = timeit(lambda: gat(rows, perm), reps,
                                           _sync)
    probe("conversions", p_conv)

    def p_pallas_dense():
        # pure dense kernel (what the uniform bench runs per 128^3)
        from ramses_tpu.hydro import pallas_muscl as pk
        if not pk.kernel_available(sim.cfg, shape, sim.bspec.faces,
                                   u0.dtype) or "ud" not in state:
            return
        ok = (d["ok_dense"].reshape(shape)
              if d.get("ok_dense") is not None else None)
        udm = jnp.moveaxis(state["ud"], -1, 0)

        @jax.jit
        def dense_kernel(udm):
            up, okp = pk.pad_xy(udm, sim.bspec, sim.cfg, ok=ok)
            return pk.fused_step_padded(up, dt, sim.cfg, sim.dx(lb),
                                        shape, ok_pad=okp)
        t["pallas_dense_kernel"] = timeit(lambda: dense_kernel(udm),
                                          reps, _sync)
    probe("pallas_dense_kernel", p_pallas_dense)

    def p_levels():
        for l in sim.levels():
            if sim.maps[l].complete:
                continue
            dl_ = sim.dev[l]
            itp = K.interp_cells(sim.u[l - 1], dl_["interp_cell"],
                                 dl_["interp_nb"], dl_["interp_sgn"],
                                 sim.cfg, itype=spec.itype)
            t[f"interp_cells_L{l}"] = timeit(
                lambda: K.interp_cells(sim.u[l - 1], dl_["interp_cell"],
                                       dl_["interp_nb"],
                                       dl_["interp_sgn"],
                                       sim.cfg, itype=spec.itype), reps,
                _sync)
            t[f"level_sweep_L{l}"] = timeit(
                lambda: K.level_sweep(sim.u[l], itp, dl_["stencil_src"],
                                      dl_["vsgn"], dl_["ok_ref"], None,
                                      dt, sim.dx(l), sim.cfg), reps,
                _sync)
            if l in sim.blocks:
                # the gather-fused blocked sweep, same level/shapes —
                # side-by-side with the 6^3 stencil sweep above
                bi = K.interp_cells(
                    sim.u[l - 1], dl_["b_interp_cell"],
                    dl_["b_interp_nb"], dl_["b_interp_sgn"], sim.cfg,
                    itype=spec.itype)
                t[f"tile_sweep_L{l}"] = timeit(
                    lambda: K.tile_sweep(
                        sim.u[l], bi, dl_["tile_src"], dl_["tile_vsgn"],
                        dl_["tile_ok"], dl_["cell_tile"],
                        dl_["cell_slot"], dl_["oct_tile"],
                        dl_["oct_slot"], dt, sim.dx(l), sim.cfg,
                        sim.blocks[l].shift), reps, _sync)
            t[f"scatter_corr_L{l}"] = timeit(
                lambda: K.scatter_corrections(
                    sim.u[l - 1],
                    jnp.zeros((sim.maps[l].noct_pad, 3, 2,
                               sim.cfg.nvar), sim.dtype),
                    dl_["corr_idx"], sim.cfg),
                reps, _sync)
    probe("level_kernels", p_levels)

    def p_restrict():
        t["restrict_upload_base"] = timeit(
            lambda: K.restrict_upload(sim.u[lb], sim.u[lb + 1],
                                      d["ref_cell"], d["son_oct"],
                                      sim.cfg),
            reps, _sync) if sim.tree.has(lb + 1) else None
    probe("restrict_upload", p_restrict)

    def p_courant():
        t["fused_courant"] = timeit(
            lambda: _fused_courant(sim.u, sim.dev, spec), reps, _sync)
    probe("fused_courant", p_courant)

    def p_regrid():
        # regrid sub-phases (flag/maps/migrate/upload): instrumented
        # timers with a device drain at each section switch, plus the
        # incremental-rebuild counters — steady state (unchanged tree)
        # must rebuild ZERO per-block maps
        saved = sim.timers
        sim.timers = Timers(sync=sim.drain)
        for _ in range(3):
            sim.regrid()
        sim.timers.stop()
        res["regrid_phase_s"] = {
            k: round(v, 4) for k, v in sim.timers.acc.items()
            if k.startswith("regrid")}
        # the steady-state loop above short-circuits after balance, so
        # maps/migrate/upload come from the growth-phase accumulator
        # captured during the warm-up evolve (changed-tree regrids)
        res["regrid_phase_growth_s"] = {
            k: round(v, 4) for k, v in state["growth_acc"].items()
            if k.startswith("regrid")}
        res["regrid_block_stats"] = dict(sim.block_stats)
        sim.timers = saved
    probe("regrid_phases", p_regrid)

    def p_steady():
        # steady-state chunk throughput (the bench's steady_state
        # number); warm with the SAME step count so the canonical chunk
        # decomposition is fully compiled before the timed window
        nss = 8
        sim.evolve(1e9, nstepmax=sim.nstep + nss)
        sim.drain()
        ttd = 2 ** sim.cfg.ndim
        upd = sum(sim.tree.noct(l) * ttd * 2 ** (l - sim.lmin)
                  for l in sim.levels())
        t0 = time.perf_counter()
        sim.evolve(1e9, nstepmax=sim.nstep + nss)
        sim.drain()
        wss = time.perf_counter() - t0
        res["steady_state_cell_updates_per_sec"] = nss * upd / wss
        res["steady_state_s_per_coarse_step"] = wss / nss
        res["updates_per_coarse_step"] = upd
    probe("steady_state", p_steady)

    def p_offload():
        # segmented out-of-core step (amr/offload.py) on the same
        # frozen tree: per-step wall with inactive levels cycling
        # through host parks — side-by-side with fused_coarse_step
        # above, the segmentation + transfer overhead is the delta;
        # the residency counters land under res["offload"]
        from ramses_tpu.amr.offload import OffloadEngine
        eng = OffloadEngine("on")
        why = eng.ineligible_reason(sim)
        if why is not None:
            res["offload"] = {"skipped": why}
            return
        dtf = float(sim.coarse_dt())
        spec_now = sim._fused_spec()

        def _ostep():
            sim.u, sim._dt_cache = eng.run_step(sim, dtf, spec_now)
            return sim.u[sim.lmin]
        t["offload_step"] = timeit(_ostep, max(3, reps // 2), _sync)
        res["offload"] = dict(eng.last_step_stats or {})
        eng.unpark_all(sim)       # later probes expect device arrays
        sim._dt_cache = None
    probe("offload_step", p_offload)

    def p_trace():
        tdir = os.environ.get("PROFILE_TRACE_DIR")
        if tdir:
            with jax.profiler.trace(tdir):
                sim.evolve(1e9, nstepmax=sim.nstep + 3)
                sim.drain()
            res["trace_dir"] = tdir
    probe("profiler_trace", p_trace)

    res["timings_s"] = {k: (round(v, 6) if v is not None else None)
                        for k, v in t.items()}
    res.pop("probe", None)
    res["completed"] = True
    if not res["probe_errors"]:
        res.pop("probe_errors")
    emit(res)
    return res


def _parent():
    """Re-execute as a killed-on-deadline child; classify the outcome
    and always print a ##PROF## line (partial on hang/crash)."""
    deadline = float(os.environ.get("PROF_DEADLINE_S", "900"))
    env = dict(os.environ, PROF_CHILD="1")
    env.setdefault("PROF_PROBE_DEADLINE_S",
                   str(min(120.0, max(30.0, deadline / 6.0))))
    try:
        os.path.exists(_json_path()) and os.remove(_json_path())
    except OSError:
        pass
    rc = None
    try:
        r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           env=env, timeout=deadline,
                           capture_output=True, text=True)
        rc = r.returncode
        for line in reversed(r.stdout.splitlines()):
            if line.startswith(MARKER):
                print(line, flush=True)
                return 0
    except subprocess.TimeoutExpired:
        rc = "timeout"
    # no marker: classify from the partial JSON the child left behind
    try:
        with open(_json_path()) as f:
            res = json.load(f)
    except (OSError, ValueError):
        res = {"completed": False}
    res["classification"] = ("hang" if rc in (87, "timeout")
                             else "crash")
    res["child_rc"] = rc
    if not res.get("completed"):
        res.setdefault("probe_at_exit", res.get("probe"))
    _write_json(res)
    print(MARKER + json.dumps(res, default=str), flush=True)
    return 0


def main():
    if os.environ.get("PROF_CHILD") or os.environ.get("PROF_INPROC"):
        res = collect()
        print(MARKER + json.dumps(res, default=str), flush=True)
        return 0
    return _parent()


if __name__ == "__main__":
    sys.exit(main())
