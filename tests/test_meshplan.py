"""Two-level packing plans + cost-aware gang scheduler
(``ramses_tpu/ensemble/meshplan.py``, ``queue.plan_gang``).

Pins the scheduling contracts of the ensemble x slab composition:

  * submit stamps each record with the ``members x cells x steps``
    cost plus shard clamps (best-effort: unparseable -> unstamped);
  * ``plan_gang`` bin-packs small jobs cost-ascending onto the mesh,
    drains to exclusive mode for mesh-wide jobs, honors min/max shard
    clamps, and bounds starvation (a big job waiting past
    ``starve_s`` preempts the packers);
  * ``plan_for`` picks packed / slab / single from the namelist and
    the granted submesh alone.
"""

import os

import pytest

pytest.importorskip("jax")

from ramses_tpu.config import params_from_dict
from ramses_tpu.ensemble import queue as jq
from ramses_tpu.ensemble.meshplan import (MeshPlan, largest_divisor,
                                          member_cells, plan_for,
                                          slab_eligible, stamp_cost)

pytestmark = pytest.mark.smoke


def _hydro_nml(nmember=1, lvl=4, nstepmax=6):
    return (
        "&RUN_PARAMS\nhydro=.true.\nnstepmax=%d\n/\n"
        "&AMR_PARAMS\nlevelmin=%d\nlevelmax=%d\n/\n"
        "&OUTPUT_PARAMS\ntend=1e9\n/\n"
        "&INIT_PARAMS\nd_region=1.0\np_region=1e-5\n/\n"
        "&ENSEMBLE_PARAMS\nnmember=%d\nperturb_amp=1e-3\n/\n"
        % (nstepmax, lvl, lvl, nmember))


def _params(lvl=4, lmax=None, nmember=1, ndim=3, **ens):
    return params_from_dict({
        "run_params": {"hydro": True, "nstepmax": 6},
        "amr_params": {"levelmin": lvl, "levelmax": lmax or lvl},
        "output_params": {"tend": 1e9},
        "init_params": {"d_region": [1.0], "p_region": [1e-5]},
        "ensemble_params": dict({"nmember": nmember}, **ens),
    }, ndim=ndim)


# ---------------------------------------------------------------------
# cost stamp
# ---------------------------------------------------------------------
def test_stamp_cost_fields():
    c = stamp_cost(_hydro_nml(nmember=4, lvl=4, nstepmax=6), ndim=3)
    assert c["members"] == 4
    assert c["cells"] == 16 ** 3
    assert c["steps"] == 6
    assert c["cost"] == 4 * 16 ** 3 * 6
    assert c["min_shards"] == 0 and c["max_shards"] == 0
    assert c["exclusive"] is False


def test_stamp_cost_exclusive_over_budget():
    nml = _hydro_nml(nmember=1, lvl=5) + \
        "&ENSEMBLE_PARAMS\npack_cell_budget=64\n/\n"
    c = stamp_cost(nml, ndim=3)
    assert c["cells"] == 32 ** 3 and c["exclusive"] is True
    # a calibrate job is exclusive by kind, not by size — but the
    # size bit in the stamp stays a pure cell-budget statement
    c2 = stamp_cost(_hydro_nml(), ndim=3, kind="calibrate")
    assert c2["exclusive"] is False
    rec = {"kind": "calibrate", "cost": c2}
    assert jq._is_exclusive(rec)


def test_stamp_cost_amr_worst_case_and_shard_cap():
    nml = ("&RUN_PARAMS\nhydro=.true.\nnstepmax=10\n/\n"
           "&AMR_PARAMS\nlevelmin=4\nlevelmax=6\n/\n"
           "&OUTPUT_PARAMS\ntend=1e9\n/\n")
    c = stamp_cost(nml, ndim=3)
    # worst-case refinement: base cells x 2^(ndim * depth)
    assert c["cells"] == 16 ** 3 * 2 ** (3 * 2)
    # AMR namelists inherit the dense-slab device ceiling
    from ramses_tpu.parallel.dense_slab import max_slab_devices
    assert c["max_shards"] == max_slab_devices(6, 3)


def test_stamp_cost_uncostable_is_none():
    # the namelist parser is lenient, so the guard is around the whole
    # estimate: a config that can't be costed submits unstamped
    assert stamp_cost("&AMR_PARAMS\nlevelmin=potato\n/\n",
                      ndim=3) is None


def test_submit_stamps_cost(tmp_path):
    qd = str(tmp_path / "q")
    jid = jq.submit(qd, _hydro_nml(nmember=3), job_id="stamped")
    recs = jq.peek_queued(qd)
    assert [r["id"] for r in recs] == [jid]
    assert recs[0]["cost"]["members"] == 3
    assert recs[0]["cost"]["cost"] > 0


def test_claim_by_job_id(tmp_path):
    qd = str(tmp_path / "q")
    jq.submit(qd, _hydro_nml(), job_id="a")
    jq.submit(qd, _hydro_nml(), job_id="b")
    job = jq.claim(qd, worker="w", job_id="b")
    assert job.id == "b"
    assert [r["id"] for r in jq.peek_queued(qd)] == ["a"]
    # a lost race (id already claimed) returns None, not an error
    assert jq.claim(qd, worker="w2", job_id="b") is None


# ---------------------------------------------------------------------
# gang planning (pure decisions — no fs, no jax)
# ---------------------------------------------------------------------
def _rec(jid, members=1, cells=64, steps=4, submitted=1000.0,
         exclusive=False, min_shards=0, max_shards=0, kind="run"):
    return {"id": jid, "kind": kind, "submitted_unix": submitted,
            "cost": {"members": members, "cells": cells,
                     "steps": steps,
                     "cost": members * cells * steps,
                     "min_shards": min_shards,
                     "max_shards": max_shards,
                     "exclusive": exclusive}}


def test_plan_gang_binpacks_cost_ascending():
    a = _rec("a", members=8, cells=64)      # cost 2048
    b = _rec("b", members=4, cells=64)      # cost 1024 (cheapest)
    gang = jq.plan_gang([a, b], ndev=8, now=1001.0)
    assert [(r["id"], n) for r, n in gang] == [("b", 4), ("a", 4)]
    assert sum(n for _, n in gang) <= 8


def test_plan_gang_shard_clamps():
    a = _rec("a", members=8, max_shards=2)
    b = _rec("b", members=8, min_shards=4)
    gang = dict((r["id"], n) for r, n in
                jq.plan_gang([a, b], ndev=8, now=1001.0))
    assert gang["a"] <= 2
    assert gang["b"] >= 4
    # a lone 1-member job never gets more than 1 device — extra
    # replicas would idle
    solo = jq.plan_gang([_rec("s", members=1)], ndev=8, now=1001.0)
    assert [(r["id"], n) for r, n in solo] == [("s", 1)]


def test_plan_gang_exclusive_drains():
    big = _rec("big", members=1, cells=10 ** 7, exclusive=True)
    small = _rec("small", members=4)
    # smalls present: they pack first, the big job waits
    gang = jq.plan_gang([big, small], ndev=8, now=1001.0)
    assert [r["id"] for r, _ in gang] == ["small"]
    # only the big job left: it takes the whole mesh
    gang = jq.plan_gang([big], ndev=8, now=1001.0)
    assert [(r["id"], n) for r, n in gang] == [("big", 8)]


def test_plan_gang_starvation_bound():
    big = _rec("big", exclusive=True, submitted=0.0)
    small = _rec("small", members=4, submitted=999.0)
    # waited past starve_s: the exclusive job preempts the packers
    gang = jq.plan_gang([big, small], ndev=8, now=1000.0,
                        starve_s=600.0)
    assert [(r["id"], n) for r, n in gang] == [("big", 8)]
    # not yet starving: smalls pack as usual
    gang = jq.plan_gang([big, small], ndev=8, now=500.0,
                        starve_s=600.0)
    assert [r["id"] for r, _ in gang] == ["small"]


def test_plan_gang_fifo_fallback():
    a = _rec("a", members=8, cells=10 ** 7, exclusive=True)
    b = _rec("b", members=1)
    gang = jq.plan_gang([a, b], ndev=8, order="fifo")
    assert [(r["id"], n) for r, n in gang] == [("a", 8)]
    with pytest.raises(ValueError, match="claim order"):
        jq.plan_gang([a], ndev=8, order="nope")


def test_plan_gang_unstamped_is_small_fifo_job():
    bare = {"id": "old", "submitted_unix": 1000.0}   # pre-stamp record
    gang = jq.plan_gang([bare], ndev=8, now=1001.0)
    assert [(r["id"], n) for r, n in gang] == [("old", 1)]


# ---------------------------------------------------------------------
# plan_for mode selection
# ---------------------------------------------------------------------
def test_plan_for_modes():
    p = _params(lvl=4, nmember=8)
    assert plan_for(p, 8, n_devices=1).mode == "single"
    plan = plan_for(p, 8, device_ids=(0, 1, 2, 3))
    assert plan.mode == "packed" and plan.device_ids == (0, 1, 2, 3)
    # over the pack budget + slab-eligible (periodic uniform hydro,
    # nx divisible): mesh-wide slab
    p2 = _params(lvl=5, nmember=1, pack_cell_budget=64)
    assert slab_eligible(p2, 8)
    assert plan_for(p2, 1, n_devices=8).mode == "slab"
    # over budget but NOT eligible (AMR): fall back to single
    p3 = _params(lvl=4, lmax=6, nmember=1, pack_cell_budget=64)
    assert not slab_eligible(p3, 8)
    assert plan_for(p3, 1, n_devices=8).mode == "single"


def test_member_cells_worst_case():
    assert member_cells(_params(lvl=4, ndim=3)) == 16 ** 3
    assert member_cells(_params(lvl=4, lmax=5, ndim=2)) == \
        16 ** 2 * 2 ** (2 * 1)


def test_largest_divisor():
    assert largest_divisor(8, 8) == 8
    assert largest_divisor(8, 3) == 2
    assert largest_divisor(6, 4) == 3
    assert largest_divisor(5, 4) == 1
    assert largest_divisor(1, 8) == 1


def test_meshplan_validation_and_describe():
    with pytest.raises(ValueError, match="mode"):
        MeshPlan(mode="weird")
    plan = MeshPlan.packed((0, 1), max_replicas=2)
    d = plan.describe()
    assert d == {"mode": "packed", "devices": 2,
                 "device_ids": [0, 1], "max_replicas": 2}
    assert MeshPlan.single().n_devices == 1
