"""Single-level (uniform Cartesian) hydro solver.

The degenerate one-level octree of SURVEY.md §7 stage 2: the whole grid is
one dense device array, a full step is one fused XLA program
(pad → ctoprim → slopes → trace → riemann → update), and N steps run as a
``lax.scan`` with zero host round-trips — the design replaces the
per-nvector-batch sweep of ``godunov_fine`` (``hydro/godunov_fine.f90:5-35``)
with whole-grid fusion.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ramses_tpu.grid import boundary as bmod
from ramses_tpu.hydro import muscl
from ramses_tpu.hydro.core import HydroStatic
from ramses_tpu.hydro.timestep import compute_dt


@dataclass(frozen=True)
class UniformGrid:
    """Static description of a uniform-grid problem (hashable, jit-static)."""
    cfg: HydroStatic
    shape: Tuple[int, ...]
    dx: float
    bc: bmod.BoundarySpec

    @property
    def ncell(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n


def _pallas_ok(grid: UniformGrid, dtype) -> bool:
    """True when the fused Pallas TPU kernel covers this grid."""
    if grid.cfg.ndim != 3:
        return False
    from ramses_tpu.hydro import pallas_muscl as pk
    return pk.kernel_available(grid.cfg, grid.shape, grid.bc.faces, dtype)


@partial(jax.jit, static_argnames=("grid",))
def step(grid: UniformGrid, u, dt):
    """One conservative MUSCL-Hancock step on the active grid.

    Dispatches to the fused Pallas kernel
    (:mod:`ramses_tpu.hydro.pallas_muscl`) when it covers the config;
    the XLA path below is the reference implementation (bit-identical)."""
    cfg = grid.cfg
    # the time axis runs in f64 while the state may be f32/bf16: keep
    # the sweep in the state dtype
    dt = jnp.asarray(dt, u.dtype)
    if _pallas_ok(grid, u.dtype):
        from ramses_tpu.hydro import pallas_muscl as pk
        up, _ = pk.pad_xy(u, grid.bc, cfg)
        return pk.fused_step_padded(up, dt, cfg, grid.dx, grid.shape)
    up = bmod.pad(u, grid.bc, cfg, muscl.NGHOST, dx=grid.dx)
    flux, tmp = muscl.unsplit(up, None, dt, (grid.dx,) * cfg.ndim, cfg)
    un = muscl.apply_fluxes(up, flux, cfg)
    if cfg.pressure_fix or cfg.nener:
        un = muscl.dual_energy_fix(up, un, tmp, dt,
                                   (grid.dx,) * cfg.ndim, cfg)
    return bmod.unpad(un, cfg.ndim, muscl.NGHOST)


@partial(jax.jit, static_argnames=("grid",))
def step_with_flux(grid: UniformGrid, u, dt):
    """Like :func:`step` but also returns the mass flux·dt/dx at the LOW
    face of every active cell, ``[ndim, *sp]`` — the quantity the
    Monte-Carlo tracers sample (``hydro/godunov_fine.f90:685-715``)."""
    cfg = grid.cfg
    dt = jnp.asarray(dt, u.dtype)
    up = bmod.pad(u, grid.bc, cfg, muscl.NGHOST, dx=grid.dx)
    flux, tmp = muscl.unsplit(up, None, dt, (grid.dx,) * cfg.ndim, cfg)
    un = muscl.apply_fluxes(up, flux, cfg)
    if cfg.pressure_fix or cfg.nener:
        un = muscl.dual_energy_fix(up, un, tmp, dt,
                                   (grid.dx,) * cfg.ndim, cfg)
    mass_flux = jnp.stack([bmod.unpad(flux[d][0], cfg.ndim, muscl.NGHOST)
                           for d in range(cfg.ndim)])
    return bmod.unpad(un, cfg.ndim, muscl.NGHOST), mass_flux


@partial(jax.jit, static_argnames=("grid",))
def cfl_dt(grid: UniformGrid, u):
    return compute_dt(u, None, grid.dx, grid.cfg)


@partial(jax.jit, static_argnames=("grid", "nsteps", "trace", "dt_scale"))
def run_steps(grid: UniformGrid, u, t, tend, nsteps: int,
              trace: bool = False, dt_scale: float = 1.0):
    """Advance up to ``nsteps`` steps entirely on device.

    dt is recomputed each step (``courant_fine``), clipped to land exactly
    on ``tend``; steps past ``tend`` are no-ops.  Returns (u, t, n_done);
    ``trace=True`` (telemetry-instrumented runs) additionally stacks
    per-step ``(t_after, dt)`` scan outputs so the driver can emit one
    record per coarse step from a single summary fetch.

    ``dt_scale < 1`` shrinks every Courant dt by that factor — the
    redo-step retry ladder (resilience/stepguard) re-runs a tripped
    window at halved dt, mirroring the reference's dtnew halving.

    On the Pallas path the Courant reduction of the updated state comes
    out of the step kernel itself (free — the primitives are already in
    VMEM), so each iteration is exactly one kernel launch.
    """
    if _pallas_ok(grid, u.dtype):
        return _run_steps_pallas(grid, u, t, tend, nsteps, trace=trace,
                                 dt_scale=dt_scale)

    def body(carry, _):
        u, t, ndone = carry
        dt = cfl_dt(grid, u) * dt_scale
        dt = jnp.minimum(dt, jnp.maximum(tend - t, 0.0))
        active = t < tend
        un = step(grid, u, jnp.where(active, dt, 0.0))
        u = jnp.where(active, un, u)
        t = jnp.where(active, t + dt, t)
        ndone = ndone + jnp.where(active, 1, 0)
        ys = (t, jnp.where(active, dt, 0.0)) if trace else None
        return (u, t, ndone), ys

    (u, t, ndone), hist = jax.lax.scan(body, (u, t, jnp.array(0)), None,
                                       length=nsteps)
    if trace:
        return u, t, ndone, hist
    return u, t, ndone


@partial(jax.jit, static_argnames=("grid", "nsteps", "trace", "dt_scale"))
def _run_steps_pallas(grid: UniformGrid, u, t, tend, nsteps: int,
                      trace: bool = False, dt_scale: float = 1.0):
    from ramses_tpu.hydro import pallas_muscl as pk

    cfg = grid.cfg
    dtmax = cfg.courant_factor * grid.dx / cfg.smallc
    dt0 = compute_dt(u, None, grid.dx, cfg) * dt_scale

    def body(carry, _):
        u, t, ndone, dtc = carry
        dt = jnp.minimum(dtc, jnp.maximum(tend - t, 0.0))
        active = t < tend
        up, _ = pk.pad_xy(u, grid.bc, cfg)
        un, crt = pk.fused_step_padded(up, jnp.where(active, dt, 0.0),
                                       cfg, grid.dx, grid.shape,
                                       courant=True)
        dtn = jnp.minimum(dtmax, crt[0, 0] * dt_scale)
        u = jnp.where(active, un, u)
        t = jnp.where(active, t + dt, t)
        dtc = jnp.where(active, dtn, dtc)
        ndone = ndone + jnp.where(active, 1, 0)
        ys = (t, jnp.where(active, dt, 0.0)) if trace else None
        return (u, t, ndone, dtc), ys

    (u, t, ndone, _), hist = jax.lax.scan(
        body, (u, t, jnp.array(0), dt0), None, length=nsteps)
    if trace:
        return u, t, ndone, hist
    return u, t, ndone


@partial(jax.jit, static_argnames=("grid", "cspec", "nsteps", "dt_scale"))
def run_steps_cool(grid: UniformGrid, u, t, tend, nsteps: int,
                   tables, cspec, dt_scale: float = 1.0):
    """:func:`run_steps` with the cooling source applied after each hydro
    step (the ``cooling_fine`` call that follows ``godunov_fine`` in
    ``amr/amr_step.f90:448-474``).  ``dt_scale < 1`` is the redo-step
    retry knob, as on :func:`run_steps`."""
    from ramses_tpu.hydro.cooling import cooling_step

    def body(carry, _):
        u, t, ndone = carry
        dt = cfl_dt(grid, u) * dt_scale
        dt = jnp.minimum(dt, jnp.maximum(tend - t, 0.0))
        active = t < tend
        dt_eff = jnp.where(active, dt, 0.0)
        un = step(grid, u, dt_eff)
        un = cooling_step(un, tables, cspec, dt_eff, grid.cfg)
        u = jnp.where(active, un, u)
        t = jnp.where(active, t + dt, t)
        ndone = ndone + jnp.where(active, 1, 0)
        return (u, t, ndone), None

    (u, t, ndone), _ = jax.lax.scan(body, (u, t, jnp.array(0)), None,
                                    length=nsteps)
    return u, t, ndone


def batch_summary(u, ndim: int, dx: float, ienergy: int, bf=None):
    """Per-member conserved/finiteness summary ``[B, 3]`` for the
    batched guard (resilience/stepguard.BatchGuard): columns are
    (all-finite flag, mass total, energy total).  A NaN that lands on
    the *last* step of a fused window leaves the member's ``t`` finite,
    so the guard needs a state-derived channel too; computed on device
    so arming the guard only widens the existing per-dispatch fetch
    instead of adding one."""
    axes = tuple(range(1, u.ndim))
    finite = jnp.all(jnp.isfinite(u), axis=axes)
    if bf is not None:
        finite &= jnp.all(jnp.isfinite(bf),
                          axis=tuple(range(1, bf.ndim)))
    vol = dx ** ndim
    sp = tuple(range(1, u.ndim - 1))     # spatial axes of u[:, ivar]
    mass = jnp.sum(u[:, 0], axis=sp)
    energy = jnp.sum(u[:, ienergy], axis=sp)
    return jnp.stack([finite.astype(u.dtype),
                      mass * vol, energy * vol], axis=-1)


@partial(jax.jit,
         static_argnames=("grid", "nsteps", "dt_scale", "summarize"))
def run_steps_batch(grid: UniformGrid, u, t, tend, nsteps: int,
                    dt_scale: float = 1.0, summarize: bool = False):
    """:func:`run_steps` vmapped over a leading ensemble axis.

    ``u`` is ``[B, nvar, *sp]``, ``t``/``tend`` are ``[B]`` — one
    compiled program advances every member; the per-step
    ``active = t < tend`` masking inside :func:`run_steps` becomes a
    per-member ``lax.select`` under vmap, so members that reach their
    own ``tend`` idle cheaply until the batch drains.  Returns
    ``(u, t, ndone)`` with ``ndone[B]`` counting each member's real
    steps.  ``summarize=True`` (batched step-guard armed) additionally
    returns the :func:`batch_summary` ``[B, 3]``.  The batch shares
    one jit cache entry per ``grid`` — the frozen static dataclass is
    the cache key (ensemble/batch groups members by it)."""
    def solo(u_, t_, tend_):
        return run_steps(grid, u_, t_, tend_, nsteps, dt_scale=dt_scale)
    u, t, ndone = jax.vmap(solo)(u, t, tend)
    if summarize:
        cfg = grid.cfg
        return u, t, ndone, batch_summary(u, cfg.ndim, grid.dx,
                                          cfg.ndim + 1)
    return u, t, ndone


@partial(jax.jit, static_argnames=("grid", "cspec", "nsteps",
                                   "dt_scale", "summarize"))
def run_steps_cool_batch(grid: UniformGrid, u, t, tend, nsteps: int,
                         tables, cspec, dt_scale: float = 1.0,
                         summarize: bool = False):
    """:func:`run_steps_cool` over a leading ensemble axis; ``tables``
    is stacked per-member too (cooling-constant sweeps are traced table
    data, not jit keys — only ``cspec`` splits the cache)."""
    def solo(u_, t_, tend_, tb_):
        return run_steps_cool(grid, u_, t_, tend_, nsteps, tb_, cspec,
                              dt_scale=dt_scale)
    u, t, ndone = jax.vmap(solo)(u, t, tend, tables)
    if summarize:
        cfg = grid.cfg
        return u, t, ndone, batch_summary(u, cfg.ndim, grid.dx,
                                          cfg.ndim + 1)
    return u, t, ndone


def totals(u, cfg: HydroStatic, dx: float):
    """Conservation audit (mass, momentum, energy) — ``check_cons``
    (``hydro/courant_fine.f90:161``)."""
    vol = dx ** cfg.ndim
    return {
        "mass": jnp.sum(u[0]) * vol,
        "momentum": [jnp.sum(u[1 + d]) * vol for d in range(cfg.ndim)],
        "energy": jnp.sum(u[cfg.ndim + 1]) * vol,
    }
