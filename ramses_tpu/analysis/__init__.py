"""Static analysis of the lowered step-chain programs.

A rule-based lint engine over the StableHLO this repo actually
compiles (plus an AST pass for source-level host-sync hazards): the
hazard classes every past perf/correctness incident belonged to —
duplicated stencil gathers, closed-over constants, nondeterministic
GSPMD scatters, dropped donations, f64 leaks, stray host syncs —
checked statically on the CPU backend, in CI, before a TPU tunnel is
ever involved.

Entry points:

* ``tools/lint.py`` — the CLI (``--check`` gates CI,
  ``--update-baseline`` accepts current findings);
* :func:`ramses_tpu.analysis.engine.audit_sim` — the telemetry
  run-header hook (``analysis_findings`` next to
  ``hlo_gather_elems``);
* :mod:`ramses_tpu.analysis.programs` — the canonical program
  enumerator (one small lowered program per driver family).

See ``docs/static_analysis.md`` for the rule catalog and the
baseline workflow.
"""

from ramses_tpu.analysis.engine import (audit_program, audit_sim,
                                        report, run)
from ramses_tpu.analysis.rules import (Finding, Rule, Severity,
                                       all_rules, get_rule,
                                       load_baseline, save_baseline,
                                       severity_counts)

__all__ = [
    "Finding", "Rule", "Severity", "all_rules", "get_rule",
    "load_baseline", "save_baseline", "severity_counts",
    "audit_program", "audit_sim", "report", "run",
]
