"""Halo chain: membership, unbinding, catalogue, merger trees
(``pm/unbinding.f90``, ``pm/clump_merger.f90``, ``pm/merger_tree.f90``)."""

import numpy as np
import pytest

pytest.importorskip("jax")

from ramses_tpu.pm.clumps import find_clumps
from ramses_tpu.pm.halo import (MergerTree, build_catalogue,
                                link_catalogues, particle_labels,
                                unbind_clump, write_halo_table)


def _blob(rng, center, n, sigma_x, sigma_v, m=1.0):
    x = rng.normal(center, sigma_x, (n, 3))
    v = rng.normal(0.0, sigma_v, (n, 3))
    return x, v, np.full(n, m)


def _make_two_halo_system(rng, n1=400, n2=200):
    """Two bound blobs + diffuse background in a unit box."""
    x1, v1, m1 = _blob(rng, [0.3, 0.5, 0.5], n1, 0.02, 0.5)
    x2, v2, m2 = _blob(rng, [0.7, 0.5, 0.5], n2, 0.02, 0.35)
    xb = rng.uniform(0, 1, (100, 3))
    vb = rng.normal(0, 0.1, (100, 3))
    mb = np.full(100, 1.0)
    x = np.mod(np.concatenate([x1, x2, xb]), 1.0)
    v = np.concatenate([v1, v2, vb])
    m = np.concatenate([m1, m2, mb])
    ids = np.arange(len(m), dtype=np.int64)
    return x, v, m, ids


def _label_particles(x, m, n=32):
    """NGP density on an n^3 grid → watershed labels → per-particle."""
    dx = 1.0 / n
    idx = tuple(np.clip((x[:, d] / dx).astype(int), 0, n - 1)
                for d in range(3))
    rho = np.zeros((n, n, n))
    np.add.at(rho, idx, m / dx ** 3)
    thr = float(rho.mean()) * 3.0
    labels, _clumps = find_clumps(rho, thr, relevance=1.5, dx=dx)
    return particle_labels(x, labels, dx, 1.0)


def test_unbind_strips_fast_interloper():
    rng = np.random.default_rng(2)
    n = 300
    x, v, m = _blob(rng, [0.5, 0.5, 0.5], n, 0.02, 0.0)
    # G*M ~ 300 over r~0.02: escape speed ~ sqrt(2GM/r) ~ 170
    v[0] = [1e4, 0.0, 0.0]            # far beyond escape speed
    bound = unbind_clump(x, v, m, np.array([0.5, 0.5, 0.5]), 1.0, G=1.0)
    assert not bound[0]
    assert bound.sum() > 0.8 * n


def test_catalogue_two_halos():
    rng = np.random.default_rng(3)
    x, v, m, ids = _make_two_halo_system(rng)
    pl = _label_particles(x, m)
    halos = build_catalogue(x, v, m, ids, pl, 1.0, G=1.0)
    assert len(halos) >= 2
    # heaviest first; the two blobs dominate
    assert halos[0].mass > halos[1].mass
    assert halos[0].npart > 200 and halos[1].npart > 100
    # centres near the seeded blobs (in some order)
    cx = sorted([halos[0].pos[0], halos[1].pos[0]])
    assert abs(cx[0] - 0.3) < 0.05 and abs(cx[1] - 0.7) < 0.05
    # bound sets: ids are disjoint
    assert len(np.intersect1d(halos[0].ids, halos[1].ids)) == 0


def test_merger_tree_links_and_merger():
    rng = np.random.default_rng(4)
    x, v, m, ids = _make_two_halo_system(rng)
    pl = _label_particles(x, m)
    cat1 = build_catalogue(x, v, m, ids, pl, 1.0, G=1.0)[:2]

    # snapshot 2: the two blobs have merged at the midpoint
    x2 = x.copy()
    sel1 = np.isin(ids, cat1[0].ids)
    sel2 = np.isin(ids, cat1[1].ids)
    mid = np.array([0.5, 0.5, 0.5])
    x2[sel1] = mid + rng.normal(0, 0.015, (sel1.sum(), 3))
    x2[sel2] = mid + rng.normal(0, 0.015, (sel2.sum(), 3))
    pl2 = _label_particles(x2, m)
    cat2 = build_catalogue(x2, v, m, ids, pl2, 1.0, G=1.0)[:1]

    links = link_catalogues(cat1, cat2)
    descs = {l.desc for l in links}
    assert len(descs) == 1                      # one descendant
    progs = {l.prog for l in links}
    assert cat1[0].index in progs and cat1[1].index in progs
    mains = [l for l in links if l.main]
    assert len(mains) == 1
    # main progenitor contributes the most particles (the heavier blob)
    assert mains[0].prog == cat1[0].index

    tree = MergerTree()
    tree.add_snapshot(0.0, cat1)
    tree.add_snapshot(1.0, cat2)
    got = tree.progenitors(1, cat2[0].index)
    assert {l.prog for l in got} == progs


@pytest.mark.slow
def test_halo_cli_on_snapshots(tmp_path):
    """End-to-end: PM sim → two dumps → halos CLI → tables + tree."""
    import jax.numpy as jnp
    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.config import params_from_dict
    from ramses_tpu.pm.particles import ParticleSet
    from ramses_tpu.utils.halos import main as halos_main

    rng = np.random.default_rng(7)
    x1 = np.mod(rng.normal([0.4, 0.5, 0.5], 0.03, (300, 3)), 1.0)
    xb = rng.uniform(0, 1, (100, 3))
    x = np.concatenate([x1, xb])
    v = np.zeros_like(x)
    m = np.full(400, 1.0 / 400)
    p = ParticleSet.make(jnp.asarray(x), jnp.asarray(v), jnp.asarray(m))
    groups = {
        "run_params": {"hydro": True, "poisson": True, "pic": True},
        "amr_params": {"levelmin": 4, "levelmax": 5, "boxlen": 1.0},
        "init_params": {"nregion": 1, "region_type": ["square"],
                        "x_center": [0.5], "y_center": [0.5],
                        "z_center": [0.5],
                        "length_x": [10.0], "length_y": [10.0],
                        "length_z": [10.0],
                        "exp_region": [10.0],
                        "d_region": [0.05], "p_region": [0.05]},
        "hydro_params": {"gamma": 5.0 / 3.0, "courant_factor": 0.5},
        "refine_params": {"err_grad_d": 0.3},
        "output_params": {"tend": 0.2},
    }
    sim = AmrSim(params_from_dict(groups, ndim=3), dtype=jnp.float64,
                 particles=p)
    sim.evolve(0.02, nstepmax=2)
    d1 = sim.dump(1, str(tmp_path))
    sim.evolve(0.05, nstepmax=5)
    d2 = sim.dump(2, str(tmp_path))
    tree = tmp_path / "tree.txt"
    rc = halos_main([d1, d2, "--nx", "32", "--threshold-over-mean", "3",
                     "--tree", str(tree)])
    assert rc == 0
    rows = np.atleast_2d(np.loadtxt(tmp_path / "output_00001"
                                    / "halos.txt"))
    assert rows.shape[0] >= 1 and rows[0, 1] >= 200   # blob captured
    tl = np.atleast_2d(np.loadtxt(tree))
    # columns: snap desc prog_snap prog shared frac main
    assert tl.shape[0] >= 1 and tl[0, 4] >= 200       # shared tracers
    assert tl[0, 6] == 1                              # main progenitor
    assert tl[0, 5] > 0.5                             # progenitor frac


def test_halo_table_roundtrip(tmp_path):
    rng = np.random.default_rng(5)
    x, v, m, ids = _make_two_halo_system(rng)
    pl = _label_particles(x, m)
    halos = build_catalogue(x, v, m, ids, pl, 1.0, G=1.0)
    path = tmp_path / "halos.txt"
    write_halo_table(halos, str(path))
    rows = np.loadtxt(path)
    rows = np.atleast_2d(rows)
    assert rows.shape[0] == len(halos)
    np.testing.assert_allclose(rows[0, 2], halos[0].mass, rtol=1e-5)


@pytest.mark.smoke
def test_unbinding_option_set():
    """Reference unbinding options: the binned mass-profile potential
    tracks the exact one, and saddle_pot strips borderline members."""
    from ramses_tpu.pm.halo import unbind_clump
    rng = np.random.default_rng(9)
    n = 400
    x = 0.5 + rng.normal(0, 0.01, (n, 3))
    m = np.ones(n)
    # virial-ish speeds, plus a shell of marginal members
    v = rng.normal(0, 0.5, (n, 3))
    c = np.full(3, 0.5)
    b_exact = unbind_clump(x, v, m, c, 1.0, G=1.0)
    b_binned = unbind_clump(x, v, m, c, 1.0, G=1.0, nmassbins=25)
    # the binned potential is an approximation: memberships agree on
    # the overwhelming majority
    assert (b_exact == b_binned).mean() > 0.95
    b_saddle = unbind_clump(x, v, m, c, 1.0, G=1.0, saddle_pot=True)
    # referencing energies to the boundary potential is strictly
    # harsher than referencing to infinity
    assert b_saddle.sum() < b_exact.sum()
    assert not np.any(b_saddle & ~b_exact)


@pytest.mark.smoke
def test_merger_history_three_snapshots():
    """PHEW + unbinding + tree reproduce a hand-checkable history:
    halos A and B merge (A the main progenitor), halo D drops out of
    one catalogue and re-links across the gap (merger_tree.f90
    jumpers)."""
    from ramses_tpu.pm.clumps import find_clumps
    from ramses_tpu.pm.halo import (MergerTree, build_catalogue,
                                    particle_labels)

    rng = np.random.default_rng(4)
    n = 64
    dx = 1.0 / n

    def blob(center, npart, id0, sigma=0.01):
        x = np.mod(rng.normal(center, sigma, (npart, 3)), 1.0)
        return x, id0 + np.arange(npart)

    def catalogue(blobs):
        xs = np.concatenate([b[0] for b in blobs])
        ids = np.concatenate([b[1] for b in blobs])
        rho, _ = np.histogramdd(xs, bins=(n,) * 3,
                                range=[(0.0, 1.0)] * 3)
        labels, _ = find_clumps(rho, threshold=3.0, dx=dx)
        pl = particle_labels(xs, np.asarray(labels), dx, 1.0)
        return build_catalogue(xs, np.zeros_like(xs), np.ones(len(xs)),
                               ids, pl, 1.0, npart_min=20)

    A1 = blob([0.3, 0.5, 0.5], 500, 0)
    B1 = blob([0.7, 0.5, 0.5], 250, 1000)
    D1 = blob([0.5, 0.15, 0.5], 80, 2000)
    h1 = catalogue([A1, B1, D1])
    assert len(h1) == 3
    A, B, D = h1[0], h1[1], h1[2]          # heaviest first
    # watershed labels only above-threshold cells: the blob cores
    assert 350 <= A.npart <= 500 and 150 <= B.npart <= 250
    assert 40 <= D.npart <= 80

    # snapshot 2: A and B merged at the centre; D dispersed (gone)
    AB2 = (np.mod(rng.normal([0.5, 0.5, 0.5], 0.012, (750, 3)), 1.0),
           np.concatenate([A1[1], B1[1]]))
    Dgone = (np.mod(rng.normal([0.85, 0.85, 0.85], 0.15, (80, 3)), 1.0),
             D1[1])
    h2 = catalogue([AB2, Dgone])
    assert len(h2) == 1                    # D fell below threshold
    M2 = h2[0]

    # snapshot 3: the merged halo persists; D reassembles
    AB3 = (np.mod(rng.normal([0.52, 0.5, 0.5], 0.012, (750, 3)), 1.0),
           AB2[1])
    D3 = blob([0.5, 0.15, 0.5], 80, 2000)
    h3 = catalogue([AB3, D3])
    assert len(h3) == 2
    M3, Dre = h3[0], h3[1]

    tree = MergerTree(max_gap=2)
    tree.add_snapshot(0.0, h1)
    tree.add_snapshot(1.0, h2)
    tree.add_snapshot(2.0, h3)

    # snapshot 2: the merged halo's main progenitor is A (heavier),
    # B is a non-main progenitor; both contributed ~all their tracers
    links2 = tree.progenitors(1, M2.index)
    byprog = {l.prog: l for l in links2}
    assert byprog[A.index].main and not byprog[B.index].main
    assert byprog[A.index].frac > 0.8 and byprog[B.index].frac > 0.8

    # snapshot 3: reborn D links ACROSS THE GAP to snapshot-0 D
    linksD = tree.progenitors(2, Dre.index)
    assert len(linksD) >= 1
    gap = [l for l in linksD if l.main][0]
    assert gap.snap_prog == 0 and gap.prog == D.index
    assert gap.frac > 0.5

    # the main branch of the final big halo walks back through the
    # merger to A
    assert tree.main_branch(2, M3.index) == [(2, M3.index),
                                             (1, M2.index),
                                             (0, A.index)]


def test_runtime_clumpfind_at_outputs(tmp_path):
    """&RUN_PARAMS clumpfind: every dump runs the PHEW chain on the
    live particles and grows the run's merger tree across outputs
    (pm/clump_finder.f90 + merger_tree.f90 in-run roles)."""
    import jax.numpy as jnp

    from ramses_tpu.amr.hierarchy import AmrSim
    from ramses_tpu.config import Params
    from ramses_tpu.pm.particles import ParticleSet

    rng = np.random.default_rng(6)
    x = np.concatenate([
        np.mod(rng.normal([0.3, 0.5, 0.5], 0.02, (300, 3)), 1.0),
        rng.uniform(0, 1, (60, 3))])
    ps = ParticleSet.make(jnp.asarray(x),
                          jnp.zeros((360, 3)),
                          jnp.asarray(np.full(360, 1.0 / 360)))
    p = Params(ndim=3)
    p.run.hydro = True
    p.run.pic = True
    p.run.clumpfind = True
    p.clumpfind.nx_clump = 32
    p.clumpfind.npart_min = 20
    p.amr.levelmin = p.amr.levelmax = 4
    p.init.nregion = 1
    p.init.region_type = ["square"]
    p.init.x_center, p.init.y_center, p.init.z_center = [0.5], [0.5], [0.5]
    p.init.length_x = p.init.length_y = p.init.length_z = [10.0]
    p.init.exp_region = [10.0]
    p.init.d_region, p.init.p_region = [1.0], [1.0]
    p.init.u_region, p.init.v_region = [0.0], [0.0]
    p.init.w_region = [0.0]
    sim = AmrSim(p, dtype=jnp.float64, particles=ps)
    out1 = sim.dump(1, str(tmp_path))
    rows = np.atleast_2d(np.loadtxt(
        str(tmp_path / "output_00001" / "clump_00001.txt")))
    assert rows.shape[0] >= 1 and rows[0, 1] >= 200   # the blob
    out2 = sim.dump(2, str(tmp_path))
    tree = np.atleast_2d(np.loadtxt(
        str(tmp_path / "output_00002" / "mergertree_00002.txt")))
    # the blob links to itself across the two outputs as main prog
    assert tree.shape[0] >= 1 and tree[0, 6] == 1
    # a "restart" (fresh sim, no in-memory tree) rebuilds the history
    # from the persisted catalogues and still links output 3 back
    sim2 = AmrSim(p, dtype=jnp.float64, particles=ps)
    sim2.dump(3, str(tmp_path))
    tree3 = np.atleast_2d(np.loadtxt(
        str(tmp_path / "output_00003" / "mergertree_00003.txt")))
    assert tree3.shape[0] >= 1 and tree3[0, 6] == 1
