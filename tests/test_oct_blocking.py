"""Gather-fused blocked oct sweep (amr/maps.py BlockMaps +
amr/kernels.py tile_sweep + the hierarchy wiring).

The oracle is the same invariance trick the rest of the AMR suite
uses: the blocked Morton-tile decomposition is a *layout* change, so
``oct_blocking=.true.`` must reproduce the per-oct stencil path
bitwise — same conserved state, same refinement flags, same trees —
on every configuration it is eligible for.  Map-level tests
cross-check the gathered tile values against the tree geometry
directly, and the incremental-rebuild contract (unchanged tiles are
never rebuilt) is pinned on real regrids.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from ramses_tpu.amr import maps as mapmod
from ramses_tpu.amr.hierarchy import AmrSim
from ramses_tpu.amr.tree import cell_offsets
from ramses_tpu.config import params_from_dict, params_from_string

SEDOV3D = """
&RUN_PARAMS
hydro=.true.
/
&AMR_PARAMS
levelmin={lmin}
levelmax={lmax}
boxlen=1.0
oct_blocking={blk}
/
&INIT_PARAMS
nregion=2
region_type(1)='square'
region_type(2)='point'
x_center=0.5,0.5
y_center=0.5,0.5
z_center=0.5,0.5
length_x=10.0,1.0
length_y=10.0,1.0
length_z=10.0,1.0
d_region=1.0,0.0
p_region=1e-5,0.1
/
&HYDRO_PARAMS
gamma=1.4
courant_factor=0.7
slope_type=1
riemann='{riemann}'
/
&REFINE_PARAMS
err_grad_p=0.1
/
"""


def _sedov(blk, lmin=4, lmax=5, ndim=3, dtype=None, riemann="llf"):
    p = params_from_string(
        SEDOV3D.format(lmin=lmin, lmax=lmax, blk=blk, riemann=riemann),
        ndim=ndim)
    return AmrSim(p, dtype=dtype or jnp.float64)


def _check_maps(sim):
    """Cross-check BlockMaps against the tree: every gathered slot must
    resolve to the cell its Morton key names, an interp row for its
    missing-father key, or the zero trash row."""
    from ramses_tpu.amr import keys as kmod
    nd = sim.tree.ndim
    for l, b in sim.blocks.items():
        lev = sim.tree.levels[l]
        # fabricate a cell field = its own BC-mapped Morton key; interp
        # rows get a distinct marker family, trash row a third
        u = np.full((b.ncell_pad, 1), -1.0)
        co = cell_offsets(nd)
        gc = (2 * lev.og[:, None, :] + co[None, :, :]).reshape(-1, nd)
        u[:len(gc), 0] = kmod.encode(gc, nd).astype(float)
        iv = np.full((b.ni_pad, 1), -2.0)
        iv[:b.ni, 0] = -1000.0 - np.arange(b.ni)
        src = np.concatenate([u, iv, [[-3.0]]], axis=0)
        got = src[np.asarray(b.tile_src), 0][:b.ntile]
        ck = b.slot_ckey
        exists = (sim.tree.lookup_keys(l, (ck >> nd).reshape(-1)) >= 0) \
            .reshape(ck.shape)
        assert np.array_equal(got[exists], ck[exists].astype(float)), \
            f"level {l}: existing-cell slots"
        missing = got[~exists]
        assert ((missing <= -1000.0) | (missing == -3.0)).all(), \
            f"level {l}: missing slots must be interp or trash"
        if b.ni:
            # an interp slot's row index must equal the rank of its key
            rows = (-(missing + 1000.0)).astype(int)
            onrow = missing <= -1000.0
            uniq = np.unique(ck[~exists][onrow])
            assert np.array_equal(
                rows[onrow], np.searchsorted(uniq, ck[~exists][onrow])), \
                f"level {l}: interp row ranks"
        # scatter maps invert the layout: flat cell order <-> tile slots
        nreal = lev.noct * (1 << nd)
        flat = np.arange(b.ntile_pad * (1 << (nd * (b.shift + 1)))) \
            .reshape(b.ntile_pad, -1)
        vals = flat[np.asarray(b.cell_tile)[:nreal],
                    np.asarray(b.cell_slot)[:nreal]]
        assert len(np.unique(vals)) == nreal, f"level {l}: cell scatter"


def test_block_maps_consistency():
    sim = _sedov(".true.")
    assert sim.blocks, "no blocked levels built"
    _check_maps(sim)


def test_unchanged_regrid_rebuilds_zero_blocks():
    """Steady-state regrid contract: tree untouched => every per-block
    map is reused, zero rebuilt."""
    sim = _sedov(".true.")
    assert sim.block_stats["blocks_total"] > 0
    sim.regrid()
    assert sim.block_stats["blocks_total"] > 0
    assert sim.block_stats["blocks_rebuilt"] == 0, sim.block_stats


def test_incremental_rebuild_matches_fresh():
    """After a real regrid, the prev-reusing build must equal a fresh
    build field-for-field."""
    sim = _sedov(".true.")
    for _ in range(2):
        sim.step_coarse(sim.coarse_dt())
    sim.regrid()
    shift = int(sim.params.amr.oct_block_shift)
    for l, b in sim.blocks.items():
        fresh = mapmod.build_block_maps(
            sim.tree, l, sim.bc_kinds, shift=shift,
            noct_pad=sim.maps[l].noct_pad)
        assert fresh.blocks_rebuilt == fresh.ntile
        for f in ("tile_src", "tile_ok", "interp_cell", "interp_nb",
                  "interp_sgn", "cell_tile", "cell_slot", "oct_tile",
                  "oct_slot", "tile_key", "slot_ckey"):
            a, c = getattr(b, f), getattr(fresh, f)
            assert np.array_equal(np.asarray(a), np.asarray(c)), (l, f)
        if b.tile_vsgn is not None:
            assert np.array_equal(b.tile_vsgn, fresh.tile_vsgn), l


def _parity(lmin, lmax, ndim, dtype=None, riemann="llf", nstep=2,
            with_regrid=True):
    sims = {}
    for blk in (".true.", ".false."):
        s = _sedov(blk, lmin=lmin, lmax=lmax, ndim=ndim, dtype=dtype,
                   riemann=riemann)
        if blk == ".true.":
            assert s.blocks, "no blocked levels built"
        else:
            assert not s.blocks
        for _ in range(nstep):
            s.step_coarse(s.coarse_dt())
        if with_regrid:
            s.regrid()
            s.step_coarse(s.coarse_dt())
        sims[blk] = s
    sa, sb = sims[".true."], sims[".false."]
    assert sorted(sa.levels()) == sorted(sb.levels())
    for l in sa.levels():
        # identical trees (flags parity, incl. tile_refine_flags)
        assert np.array_equal(np.asarray(sa.tree.levels[l].keys),
                              np.asarray(sb.tree.levels[l].keys)), l
        # FULL padded arrays: pad rows must stay bitwise too (the
        # sharded-vs-single suite compares them)
        ua, ub = np.asarray(sa.u[l]), np.asarray(sb.u[l])
        assert np.array_equal(ua, ub), \
            f"level {l}: maxdiff={np.abs(ua - ub).max()}"


def test_blocked_parity_3d_sedov():
    """Blocked vs per-oct stencil path: bitwise-identical state and
    trees through steps + a regrid (XLA tile fallback on CPU)."""
    _parity(4, 5, 3)


@pytest.mark.slow          # ~32s; nightly tier on the 1-core box
def test_blocked_parity_2d_sedov():
    _parity(4, 6, 2)


@pytest.mark.slow
def test_blocked_parity_3d_hllc_two_level_span():
    _parity(4, 6, 3, riemann="hllc")


@pytest.mark.slow
def test_blocked_parity_gravity():
    """Self-gravity run: want_flux path (phi mass-flux planes) must also
    be bitwise under blocking."""
    def blob(blk):
        groups = {
            "run_params": {"hydro": True, "poisson": True},
            "amr_params": {"levelmin": 4, "levelmax": 5, "boxlen": 1.0,
                           "oct_blocking": blk},
            "init_params": {"nregion": 2,
                            "region_type": ["square", "square"],
                            "x_center": [0.5, 0.5],
                            "y_center": [0.5, 0.5],
                            "z_center": [0.5, 0.5],
                            "length_x": [10.0, 0.25],
                            "length_y": [10.0, 0.25],
                            "length_z": [10.0, 0.25],
                            "exp_region": [10.0, 2.0],
                            "d_region": [1.0, 50.0],
                            "p_region": [10.0, 10.0]},
            "hydro_params": {"gamma": 1.4, "courant_factor": 0.5,
                             "riemann": "hllc"},
            "refine_params": {"err_grad_d": 0.2},
        }
        return AmrSim(params_from_dict(groups, ndim=3),
                      dtype=jnp.float64)

    sa, sb = blob(True), blob(False)
    assert sa.blocks and not sb.blocks
    for s in (sa, sb):
        for _ in range(2):
            s.step_coarse(s.coarse_dt())
    for l in sa.levels():
        nreal = sa.tree.levels[l].noct * 8
        assert np.array_equal(np.asarray(sa.u[l])[:nreal],
                              np.asarray(sb.u[l])[:nreal]), l


@pytest.mark.slow
def test_blocked_parity_pallas_interpret(monkeypatch):
    """The real Pallas tile kernel (interpret mode) vs the per-oct
    reference path: bitwise-identical f32 state.  Both sims run under
    FORCE_INTERPRET so the only difference is blocked vs stencil."""
    from ramses_tpu.hydro import pallas_oct
    monkeypatch.setattr(pallas_oct, "FORCE_INTERPRET", True)
    jax.clear_caches()                  # force a fresh branch choice
    try:
        sims = {}
        for blk in (".true.", ".false."):
            s = _sedov(blk, dtype=jnp.float32)
            if blk == ".true.":
                for l, b in s.blocks.items():
                    assert pallas_oct.tile_available(
                        s.cfg, b.ntile_pad, jnp.float32), (l, b.ntile_pad)
            for _ in range(2):
                s.step_coarse(s.coarse_dt())
            sims[blk] = s
        sa, sb = sims[".true."], sims[".false."]
        for l in sa.levels():
            nreal = sa.tree.levels[l].noct * 8
            assert np.array_equal(np.asarray(sa.u[l])[:nreal],
                                  np.asarray(sb.u[l])[:nreal]), l
    finally:
        jax.clear_caches()              # do not leak into other tests


# -------------------------------------------- universal eligibility

@pytest.mark.slow          # ~26s; nightly tier on the 1-core box
def test_blocked_parity_forced_layout():
    """Layout-composed tile tables: after a forced Hilbert relayout
    permutes the rows (balance.apply_layout_blocks), the blocked sweep
    must still reproduce the stencil path bitwise."""
    sims = {}
    for blk in (".true.", ".false."):
        p = params_from_string(
            SEDOV3D.format(lmin=4, lmax=6, blk=blk, riemann="llf"),
            ndim=2)
        p.amr.load_balance = True
        s = AmrSim(p, dtype=jnp.float64)
        for _ in range(2):
            s.step_coarse(s.coarse_dt())
        s.request_rebalance()
        s.regrid()
        assert s.layouts, "forced rebalance adopted no layout"
        for _ in range(2):
            s.step_coarse(s.coarse_dt())
        sims[blk] = s
    sa, sb = sims[".true."], sims[".false."]
    assert sa.blocks and not sb.blocks
    # the gate lift is doing work: a layout level IS blocked
    assert set(sa.blocks) & set(sa.layouts), (sa.blocks, sa.layouts)
    assert sorted(sa.layouts) == sorted(sb.layouts)
    for l, lay in sa.layouts.items():
        assert np.array_equal(lay.oct_row, sb.layouts[l].oct_row), l
    for l in sa.levels():
        assert np.array_equal(np.asarray(sa.tree.levels[l].keys),
                              np.asarray(sb.tree.levels[l].keys)), l
        ua, ub = np.asarray(sa.u[l]), np.asarray(sb.u[l])
        assert np.array_equal(ua, ub), \
            f"level {l}: maxdiff={np.abs(ua - ub).max()}"


@pytest.mark.slow          # ~33s; nightly tier on the 1-core box
def test_blocked_parity_sharded_mesh8():
    """mesh-of-8 == mesh-of-1 on the blocked path: row-sharded tile
    tables under GSPMD (FusedSpec.pallas_tiles=False pins the XLA tile
    formulation) reproduce the single-device run bitwise.  f32/3D is
    the regime the decomposition-invariance north star pins
    (test_determinism_f32.py); the partitioned tile program is NOT
    ulp-stable in other dtype/ndim corners."""
    from ramses_tpu.parallel.amr_sharded import ShardedAmrSim
    if len(jax.devices()) < 8:
        pytest.skip("needs an 8-device mesh")

    def mk(cls, **kw):
        p = params_from_string(
            SEDOV3D.format(lmin=4, lmax=5, blk=".true.", riemann="llf"),
            ndim=3)
        return cls(p, dtype=jnp.float32, **kw)

    s1 = mk(AmrSim)
    s8 = mk(ShardedAmrSim, devices=jax.devices()[:8])
    assert s1.blocks and s8.blocks, "blocked gate closed somewhere"
    assert s8._fused_spec().pallas_tiles is False
    for s in (s1, s8):
        for _ in range(2):
            s.step_coarse(s.coarse_dt())
        s.regrid()
        s.step_coarse(s.coarse_dt())
    for l in s1.levels():
        assert s8.tree.noct(l) == s1.tree.noct(l), l
        # noct_pad differs (mesh-multiple rounding): real rows only
        nreal = s1.tree.noct(l) * 8
        a = np.asarray(s1.u[l])[:nreal]
        b = np.asarray(s8.u[l])[:nreal]
        assert (a.view(np.uint32) == b.view(np.uint32)).all(), l


@pytest.mark.slow
def test_blocked_parity_sharded_blocked_vs_stencil():
    """3D f32 on the 8-device mesh: the row-sharded blocked tile sweep
    vs the row-sharded stencil sweep vs the mesh-of-1 stencil
    reference — one bitwise XLA family.  (The Pallas tile kernel's
    interpret-mode family is pinned single-device by
    test_blocked_parity_pallas_interpret: sharded meshes never take
    the Pallas kernel — FusedSpec.pallas_tiles=False by design.)"""
    from ramses_tpu.parallel.amr_sharded import ShardedAmrSim
    if len(jax.devices()) < 8:
        pytest.skip("needs an 8-device mesh")

    def mk(cls, blk, **kw):
        p = params_from_string(
            SEDOV3D.format(lmin=4, lmax=5, blk=blk, riemann="llf"),
            ndim=3)
        s = cls(p, dtype=jnp.float32, **kw)
        for _ in range(2):
            s.step_coarse(s.coarse_dt())
        return s

    s1 = mk(AmrSim, ".false.")
    s8b = mk(ShardedAmrSim, ".true.", devices=jax.devices()[:8])
    s8s = mk(ShardedAmrSim, ".false.", devices=jax.devices()[:8])
    assert s8b.blocks and not s8s.blocks
    for l in s1.levels():
        nreal = s1.tree.noct(l) * 8
        ref = np.asarray(s1.u[l])[:nreal]
        for tag, s in (("blocked8", s8b), ("stencil8", s8s)):
            got = np.asarray(s.u[l])[:nreal]
            assert (ref.view(np.uint32) == got.view(np.uint32)).all(), \
                (l, tag)


def _mhd_parity(lmin, lmax, ndim, nstep=2):
    """MHD CT blocked-vs-stencil parity: cells AND staggered faces."""
    from ramses_tpu.config import load_params
    from ramses_tpu.mhd.amr import MhdAmrSim
    sims = {}
    for blk in (True, False):
        p = load_params("namelists/tube_mhd.nml", ndim=ndim)
        p.amr.levelmin, p.amr.levelmax = lmin, lmax
        p.amr.oct_blocking = blk
        p.refine.err_grad_d = 0.02
        p.refine.err_grad_p = 0.05
        s = MhdAmrSim(p, dtype=jnp.float64)
        if blk:
            assert s.blocks, "no blocked MHD levels built"
        else:
            assert not s.blocks
        for _ in range(nstep):
            s.step_coarse(s.coarse_dt())
        s.regrid()
        s.step_coarse(s.coarse_dt())
        sims[blk] = s
    sa, sb = sims[True], sims[False]
    assert sorted(sa.levels()) == sorted(sb.levels())
    ttd = 1 << ndim
    for l in sa.levels():
        assert np.array_equal(np.asarray(sa.tree.levels[l].keys),
                              np.asarray(sb.tree.levels[l].keys)), l
        nreal = sa.tree.noct(l) * ttd
        # real rows only: the tile path zeroes the pad bf rows the
        # stencil path leaves as garbage (no consumer reads them)
        assert np.array_equal(np.asarray(sa.u[l])[:nreal],
                              np.asarray(sb.u[l])[:nreal]), l
        assert np.array_equal(np.asarray(sa.bfs[l])[:nreal],
                              np.asarray(sb.bfs[l])[:nreal]), l


@pytest.mark.slow          # ~145s; nightly tier on the 1-core box
def test_blocked_parity_mhd_ct_2d():
    """mhd_tile_sweep vs mhd_level_sweep through steps + a regrid:
    bitwise u and bf, including the z-EMF corner extraction."""
    _mhd_parity(4, 6, 2)


@pytest.mark.slow          # ~147s; nightly tier on the 1-core box
def test_blocked_parity_mhd_ct_3d():
    """3D exercises all three EMF pair planes and the non-pair-axis
    2-subcell mean."""
    _mhd_parity(3, 4, 3, nstep=1)


# --------------------------------------------- device-resident regrid

@pytest.mark.slow          # ~19s; nightly tier on the 1-core box
def test_device_regrid_matches_host(monkeypatch):
    """Changed-tree regrids on the device path must be bitwise-identical
    to the host build_prolong_maps reference — and must construct ZERO
    host prolongation tables while the reference builds many."""
    real = mapmod.build_prolong_maps
    counts, sims = {}, {}
    for dev_rg in (True, False):
        p = params_from_string(
            SEDOV3D.format(lmin=4, lmax=6, blk=".true.", riemann="llf"),
            ndim=2)
        p.amr.device_regrid = dev_rg
        s = AmrSim(p, dtype=jnp.float64)
        n = {"calls": 0}

        def spy(*a, _n=n, **k):
            _n["calls"] += 1
            return real(*a, **k)

        monkeypatch.setattr(mapmod, "build_prolong_maps", spy)
        try:
            for _ in range(3):
                for _ in range(2):
                    s.step_coarse(s.coarse_dt())
                s.regrid()
        finally:
            monkeypatch.setattr(mapmod, "build_prolong_maps", real)
        counts[dev_rg], sims[dev_rg] = n["calls"], s
    # the comparison is meaningful only if trees actually changed
    assert counts[False] > 0, "host run saw no changed-tree regrid"
    assert counts[True] == 0, "device path fell back to host tables"
    sa, sb = sims[True], sims[False]
    for l in sa.levels():
        assert np.array_equal(np.asarray(sa.tree.levels[l].keys),
                              np.asarray(sb.tree.levels[l].keys)), l
        ua, ub = np.asarray(sa.u[l]), np.asarray(sb.u[l])
        assert np.array_equal(ua, ub), \
            f"level {l}: maxdiff={np.abs(ua - ub).max()}"


def test_steady_regrid_builds_no_host_tables(monkeypatch):
    """Zero-host-allocation pin: a steady-state regrid (unchanged tree,
    unchanged layouts) must construct no host migration tables, upload
    no key arrays, and reuse every level array by identity."""
    from ramses_tpu.amr import device_regrid as dregrid
    sim = _sedov(".true.", lmin=4, lmax=5, ndim=2)
    for _ in range(2):
        sim.step_coarse(sim.coarse_dt())
    sim.regrid()                        # absorb any pending tree change
    before = {l: sim.u[l] for l in sim.levels()}

    def boom(*a, **k):
        raise AssertionError("host table built on a steady regrid")

    monkeypatch.setattr(mapmod, "build_prolong_maps", boom)
    monkeypatch.setattr(dregrid, "upload_keys", boom)
    sim.regrid()                        # guaranteed steady-state
    for l in sim.levels():
        assert sim.u[l] is before[l], l
