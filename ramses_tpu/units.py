"""Physical constants and user→cgs unit conversion.

The reference keeps constants in ``amr/constants.f90`` and derives the five
conversion scales in ``amr/units.f90`` (gravity runs assume G=1 in user
units; cosmology runs supercomoving units).  Values are copied verbatim
from the published CODATA/NIST constants the reference cites.
"""

from __future__ import annotations

from dataclasses import dataclass

# amr/constants.f90:5-34
twopi = 6.2831853
pi = twopi / 2.0
kB = 1.3806490e-16        # Boltzmann [erg/K]
mH = 1.6605390e-24        # atomic mass unit [g]
factG_in_cgs = 6.6740800e-08  # G [cm^3 g^-1 s^-2]
C_CGS = 2.99792458e10         # speed of light [cm/s]
rhoc = 1.8800000e-29      # critical density [g/cc]
Mpc2cm = 3.0856776e+24
X_frac = 0.76             # hydrogen mass fraction (cooling_module X)
yr2sec = 3.15576e7
kpc2cm = Mpc2cm / 1e3


@dataclass(frozen=True)
class Units:
    """scale_* convert user units into cgs (``amr/units.f90``)."""
    scale_l: float
    scale_t: float
    scale_d: float

    @property
    def scale_v(self) -> float:
        return self.scale_l / self.scale_t

    @property
    def scale_T2(self) -> float:
        """(P/rho) in user units → (T/mu) in Kelvin."""
        return mH / kB * self.scale_v ** 2

    @property
    def scale_nH(self) -> float:
        """rho in user units → nH in H/cc."""
        return X_frac / mH * self.scale_d

    @property
    def scale_m(self) -> float:
        return self.scale_d * self.scale_l ** 3


def units(params, cosmo=None, aexp: float = 1.0) -> Units:
    """Conversion factors for a run (``amr/units.f90:14-35``).

    Cosmology runs use supercomoving units tied to (omega_m, h0, aexp);
    otherwise the &UNITS_PARAMS values are used as-is.
    """
    if params.run.cosmo and cosmo is not None:
        h0 = cosmo.h0
        omega_m = cosmo.omega_m
        scale_d = omega_m * rhoc * (h0 / 100.0) ** 2 / aexp ** 3
        scale_t = aexp ** 2 / (h0 * 1e5 / Mpc2cm)
        scale_l = aexp * cosmo.boxlen_ini * Mpc2cm / (h0 / 100.0)
    else:
        p = params.units
        scale_d = p.units_density
        scale_t = p.units_time
        scale_l = p.units_length
    return Units(scale_l=scale_l, scale_t=scale_t, scale_d=scale_d)
