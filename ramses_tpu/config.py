"""Runtime configuration.

Mirrors the reference's two-stage config system (SURVEY.md §5.6):
compile-time constants become static fields of jitted programs here, and the
runtime Fortran namelist (``amr/read_params.f90:51-70``,
``hydro/read_hydro_params.f90:23-109``) is parsed by :mod:`ramses_tpu.nml`
into the dataclasses below.  Defaults replicate the reference parameter
modules (``amr/amr_parameters.f90``, ``hydro/hydro_parameters.f90``).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List

from ramses_tpu.nml import densify, load_nml, parse_nml

MAXREGION = 100
MAXBOUND = 100
MAXLEVEL = 100
MAXOUT = 1000
HUGE = 1e30


@dataclass
class RunParams:
    """&RUN_PARAMS (amr/amr_parameters.f90:58-103)."""
    hydro: bool = False
    poisson: bool = False
    pic: bool = False
    cosmo: bool = False
    mhd: bool = False          # ours: solver selection is runtime, not VPATH
    rt: bool = False
    verbose: bool = False
    static: bool = False
    nrestart: int = 0
    nstepmax: int = 1000000
    ncontrol: int = 1
    nremap: int = 0
    nsubcycle: List[int] = field(default_factory=lambda: [2] * MAXLEVEL)
    ordering: str = "hilbert"
    cost_weighting: bool = True
    # lightcone particle emission each coarse step (&RUN_PARAMS
    # lightcone, amr/light_cone.f90; geometry in &LIGHTCONE_PARAMS)
    lightcone: bool = False
    # in-run PHEW clump finding at every output (&RUN_PARAMS clumpfind,
    # pm/clump_finder.f90; options in &CLUMPFIND_PARAMS)
    clumpfind: bool = False
    # Monte-Carlo gas tracers (&RUN_PARAMS tracer/MC_tracer,
    # pm/tracer_utils.f90): seed tracer_per_cell tracers per leaf cell
    tracer: bool = False
    tracer_per_cell: float = 1.0
    # runtime plug-in overlay (ramses_tpu/patch.py) — the namelist
    # equivalent of the reference's compile-time PATCH= VPATH shadowing
    patch: str = ""
    # NaN-trap sanitizer (SURVEY.md §5.2 — the runtime analogue of the
    # reference's FPE-trapping debug builds): jax_debug_nans at jit
    # level plus per-step finite checks in the ops guard, which dumps a
    # crash snapshot and stops the run on the first non-finite state
    debug_nan: bool = False
    # fault-tolerant execution (ramses_tpu/resilience): auto_resume (or
    # nrestart=-1) restarts from the newest manifest-valid checkpoint;
    # max_step_retries>0 arms rollback-with-halved-dt on non-finite
    # steps (redo-step semantics, LLF escalation on the 2nd retry);
    # fault_inject is the deterministic test harness ('nan@K',
    # 'sigterm@K', 'truncate:NAME')
    auto_resume: bool = False
    max_step_retries: int = 0
    fault_inject: str = ""
    # hang watchdog (resilience/watchdog.py): wall-clock budgets for
    # the first (compiling) fused window, every later window, and
    # checkpoint writes.  0 disables (zero-overhead off); on expiry a
    # structured 'hang' event + emergency hang_NNNNN dump land and the
    # supervisor resumes immediately from the newest checkpoint.
    # RAMSES_{COMPILE,STEP,IO}_DEADLINE_S env vars override.
    compile_deadline_s: float = 0.0
    step_deadline_s: float = 0.0
    io_deadline_s: float = 0.0
    # mesh-shape-elastic restore (io/pario.py format 2): a sharded
    # checkpoint restores onto the CURRENT process/device mesh (write
    # on 8, restore on 4 or 1, and vice versa).  .false. refuses a
    # restore whose saved process count differs from the current run.
    elastic_restore: bool = True
    # JAX persistent compilation cache directory (env fallback
    # RAMSES_COMPILE_CACHE): set before the first trace so a known
    # namelist cold-starts in O(load) instead of O(compile); "" keeps
    # the package default (~/.cache/ramses_tpu_xla on TPU, off on
    # CPU-forced runs).  Cache hit/miss counts land in the telemetry
    # run header.
    compile_cache_dir: str = ""


@dataclass
class AmrParams:
    """&AMR_PARAMS (amr/amr_parameters.f90:81-95)."""
    levelmin: int = 1
    levelmax: int = 1
    ngridmax: int = 0
    ngridtot: int = 0
    npartmax: int = 0
    nparttot: int = 0
    nexpand: List[int] = field(default_factory=lambda: [1] * MAXLEVEL)
    boxlen: float = 1.0
    nx: int = 1
    ny: int = 1
    nz: int = 1
    # cost-weighted Hilbert load balancing (amr/load_balance.f90
    # cost_weighting): opt-in rebalance of partial-level row layouts at
    # regrid time when max/mean device cost exceeds the threshold
    load_balance: bool = False
    load_balance_threshold: float = 1.1
    # gather-fused blocked tile sweep on partial levels: octs grouped
    # into Morton-aligned tiles of 2^oct_block_shift octs per side so
    # the stencil gather is one compact tile batch instead of a
    # ~(3^ndim)x duplicated per-oct batch (universal: hydro/rhd/MHD,
    # load-balance layouts, and row-sharded meshes; explicit-comm
    # schedules keep the stencil path)
    oct_blocking: bool = True
    oct_block_shift: int = 2
    # device-resident regrid migration (amr/device_regrid.py): derive
    # the survivor-copy/prolongation maps on device from the level key
    # arrays instead of per-level host numpy tables; families that
    # replay migration into side-channel state (MHD/RT) and
    # layout-permuted levels keep the bitwise-identical host path
    device_regrid: bool = True
    # multi-chip halo exchange backend (parallel/dma_halo.py): "auto"
    # resolves to the Pallas async remote-copy (DMA) engine on a real
    # TPU backend and to lax.ppermute everywhere else; "ppermute" /
    # "dma" force a backend (an unavailable "dma" warns and falls back)
    halo_backend: str = "auto"
    cost_weight_hydro: float = 1.0
    cost_weight_mhd: float = 2.0
    cost_weight_rt: float = 1.5
    cost_weight_part: float = 0.3
    # out-of-core hierarchy (amr/offload.py): "off" keeps every level
    # HBM-resident (the bit-for-bit untouched fast path); "on" parks
    # inactive levels in host RAM with async double-buffered prefetch
    # around the subcycle schedule; "auto" engages only when the
    # estimated resident set exceeds offload_hbm_budget_mb
    offload: str = "off"
    # device-memory budget [MiB] the auto mode compares the estimated
    # resident set against; 0 reads the device's reported bytes_limit
    # (platforms that report none never auto-engage)
    offload_hbm_budget_mb: float = 0.0
    # levels smaller than this [MiB] are never parked — the transfer
    # cost outweighs the HBM reclaimed
    offload_min_park_mb: float = 0.0


@dataclass
class ClumpfindParams:
    """&CLUMPFIND_PARAMS (pm/clfind_commons.f90:12-17)."""
    density_threshold: float = -1.0   # code units; <0 → 5x mean density
    relevance_threshold: float = 2.0  # peak/saddle merge ratio
    saddle_threshold: float = -1.0    # >0: HOP-style clump→halo merge
    mass_threshold: float = 0.0       # min clump mass [particle masses]
    npart_min: int = 10
    unbind: bool = True               # &UNBINDING_PARAMS role
    saddle_pot: bool = False
    nmassbins: int = 0
    nx_clump: int = 64                # deposition grid per dim


@dataclass
class LightconeParams:
    """&LIGHTCONE_PARAMS (amr/read_params.f90:62): narrow-cone opening
    half-angles [degrees] and the maximum emission redshift.  Angles
    >= 90 degrees mean full sky."""
    thetay_cone: float = 12.5
    thetaz_cone: float = 12.5
    zmax_cone: float = 2.0


@dataclass
class OutputParams:
    """&OUTPUT_PARAMS (amr/amr_parameters.f90:109-121)."""
    noutput: int = 0
    foutput: int = 1000000
    tout: List[float] = field(default_factory=list)
    aout: List[float] = field(default_factory=list)
    delta_tout: float = HUGE
    tend: float = 0.0
    walltime_hrs: float = -1.0
    minutes_dump: float = 1.0
    output_dir: str = "."
    # structured run telemetry (ramses_tpu/telemetry): JSONL event-log
    # path ('' = off — the zero-overhead default) and the coarse-step
    # cadence of emitted records
    telemetry: str = ""
    telemetry_interval: int = 1
    # keep only the newest N manifest-valid checkpoints (0 = keep all);
    # rotation never touches pre-atomic output dirs without manifests
    checkpoint_keep: int = 0
    # also write each particle output as a Gadget SnapFormat=1 file
    # (io/gadget.py write_gadget — the reference's savegadget flag)
    savegadget: bool = False
    # elastic sharded checkpoints (io/pario.py format 2): .true. makes
    # dump() write pario_NNNNN/ shard dirs under the two-phase global
    # commit instead of reference-format output_NNNNN/ snapshots
    pario: bool = False
    # writer concurrency bound for pario dumps — the reference's
    # IOGROUPSIZE ring: per-process semaphore over the writer threads
    # AND cross-host wave stagger (0 = unbounded, all hosts at once)
    io_group_size: int = 0
    # split each process's pario payload into this many shard dirs
    # written concurrently (0/1 = one shard per process; >1 exercises
    # the per-shard decomposition on a single-host test mesh)
    pario_split_hosts: int = 0
    # observability HTTP server (ramses_tpu/obs): TCP port for the
    # streaming results/metrics endpoints (/healthz /jobs /metrics,
    # resumable telemetry tails, manifest-validated artifact files).
    # 0 = off.  Serve workers usually arm it with --obs-port instead;
    # set here, a solo run serves its own output dir as a single-run
    # view.  Scrapes read artifacts only — zero added device fetches.
    obs_port: int = 0
    # bind address for the observability server (default loopback;
    # 0.0.0.0 exposes it on all interfaces)
    obs_bind: str = "127.0.0.1"


@dataclass
class InitParams:
    """&INIT_PARAMS regions (amr/amr_parameters.f90:301-311)."""
    nregion: int = 0
    region_type: List[str] = field(default_factory=list)
    x_center: List[float] = field(default_factory=list)
    y_center: List[float] = field(default_factory=list)
    z_center: List[float] = field(default_factory=list)
    length_x: List[float] = field(default_factory=list)
    length_y: List[float] = field(default_factory=list)
    length_z: List[float] = field(default_factory=list)
    exp_region: List[float] = field(default_factory=list)
    d_region: List[float] = field(default_factory=list)
    u_region: List[float] = field(default_factory=list)
    v_region: List[float] = field(default_factory=list)
    w_region: List[float] = field(default_factory=list)
    p_region: List[float] = field(default_factory=list)
    # MHD region fields (mhd/hydro_parameters.f90:80-82): uniform B per region
    A_region: List[float] = field(default_factory=list)
    B_region: List[float] = field(default_factory=list)
    C_region: List[float] = field(default_factory=list)
    filetype: str = "ascii"
    initfile: List[str] = field(default_factory=list)
    aexp_ini: float = 10.0
    multiple: bool = False


@dataclass
class HydroParams:
    """&HYDRO_PARAMS (hydro/hydro_parameters.f90:75-90)."""
    gamma: float = 1.4
    gamma_rad: List[float] = field(default_factory=list)
    courant_factor: float = 0.5
    smallr: float = 1e-10
    smallc: float = 1e-10
    niter_riemann: int = 10
    slope_type: int = 1
    slope_theta: float = 1.5
    scheme: str = "muscl"
    riemann: str = "llf"
    riemann2d: str = "llf"     # MHD corner solver
    difmag: float = 0.0
    pressure_fix: bool = False
    beta_fix: float = 0.0
    eta_mag: float = 0.0


@dataclass
class RefineParams:
    """&REFINE_PARAMS (hydro/hydro_parameters.f90:47-58 + amr flags)."""
    err_grad_d: float = -1.0
    err_grad_u: float = -1.0
    err_grad_p: float = -1.0
    err_grad_b: float = -1.0    # MHD (mhd/hydro_parameters variant)
    floor_d: float = 1e-10
    floor_u: float = 1e-10
    floor_p: float = 1e-10
    floor_b: float = 1e-10
    interpol_var: int = 0
    interpol_type: int = 1
    jeans_refine: List[float] = field(default_factory=lambda: [-1.0] * MAXLEVEL)
    m_refine: List[float] = field(default_factory=lambda: [-1.0] * MAXLEVEL)
    mass_sph: float = 0.0
    x_refine: List[float] = field(default_factory=lambda: [0.0] * MAXLEVEL)
    y_refine: List[float] = field(default_factory=lambda: [0.0] * MAXLEVEL)
    z_refine: List[float] = field(default_factory=lambda: [0.0] * MAXLEVEL)
    r_refine: List[float] = field(default_factory=lambda: [-1.0] * MAXLEVEL)
    a_refine: List[float] = field(default_factory=lambda: [1.0] * MAXLEVEL)
    b_refine: List[float] = field(default_factory=lambda: [1.0] * MAXLEVEL)
    exp_refine: List[float] = field(default_factory=lambda: [2.0] * MAXLEVEL)


@dataclass
class BoundaryParams:
    """&BOUNDARY_PARAMS (amr/amr_parameters.f90:313-330).

    boundary_type semantics follow the reference: per-region integer code,
    1/2 = x-reflexive, 3/4 = y, 5/6 = z, 2x = outflow variants (20+ codes
    collapse to: 0 periodic, 1 reflecting, 2 outflow, 3 inflow/imposed).
    We keep the raw codes and region boxes.
    """
    nboundary: int = 0
    bound_type: List[int] = field(default_factory=list)
    ibound_min: List[int] = field(default_factory=list)
    ibound_max: List[int] = field(default_factory=list)
    jbound_min: List[int] = field(default_factory=list)
    jbound_max: List[int] = field(default_factory=list)
    kbound_min: List[int] = field(default_factory=list)
    kbound_max: List[int] = field(default_factory=list)
    d_bound: List[float] = field(default_factory=list)
    u_bound: List[float] = field(default_factory=list)
    v_bound: List[float] = field(default_factory=list)
    w_bound: List[float] = field(default_factory=list)
    p_bound: List[float] = field(default_factory=list)
    no_inflow: bool = False


@dataclass
class PoissonParams:
    """&POISSON_PARAMS (amr/amr_parameters.f90 + poisson commons)."""
    epsilon: float = 1e-4
    gravity_type: int = 0
    gravity_params: List[float] = field(default_factory=lambda: [0.0] * 10)
    cg_levelmin: int = 999
    cic_levelmax: int = 0


@dataclass
class RtParams:
    """&RT_PARAMS (rt/rt_init.f90:151-152) + the group/SED surface of
    ``rt/rt_parameters.f90`` (nGroups, group energy bounds, stellar
    blackbody SED) and a point-source shortcut (the reference injects
    via stellar particles or &RT_REGIONS; ``rt_src_*`` is the reduced
    single-source form the Stromgren tests use)."""
    rt_c_fraction: float = 0.01
    rt_courant_factor: float = 0.8
    rt_otsa: bool = True
    rt_nsubcycle: int = 1
    rt_is_outflow_bound: bool = False
    rt_ngroups: int = 1
    rt_t_star: float = 1e5            # blackbody SED temperature [K]
    rt_y_he: float = 0.0              # helium mass fraction in the chem
    # empty = unset → group defaults from rt/spectra.DEFAULT_BOUNDS
    rt_egy_bounds: List[float] = field(default_factory=list)
    rt_src_pos: List[float] = field(default_factory=lambda: [0.5, 0.5, 0.5])
    rt_ndot: float = 0.0              # source photons/s (0: no source)
    # multi-source surface (rt_parameters.f90 rt_nsource point list,
    # namelist/rad_beams.nml usage) — per-source centres in box units,
    # rates in photons/s, optional beam direction (rt_u/v/w_source)
    rt_nsource: int = 0
    rt_source_type: List[str] = field(default_factory=list)
    rt_src_x_center: List[float] = field(default_factory=list)
    rt_src_y_center: List[float] = field(default_factory=list)
    rt_src_z_center: List[float] = field(default_factory=list)
    rt_n_source: List[float] = field(default_factory=list)
    rt_u_source: List[float] = field(default_factory=list)
    rt_v_source: List[float] = field(default_factory=list)
    rt_w_source: List[float] = field(default_factory=list)
    # pure photon propagation: skip the thermochemistry entirely
    # (rt_pp / rt_freeflow of rt_parameters.f90)
    rt_pp: bool = False
    rt_freeflow: bool = False
    # stellar SED tables (rt/rt_spectra.f90): directory holding
    # metallicity_bins.dat / age_bins.dat / all_seds.dat; empty →
    # RAMSES_SED_DIR env, else the blackbody SED above
    sed_dir: str = ""
    sedprops_update: int = 5          # group-prop refresh cadence (steps)
    rt_esc_frac: float = 1.0          # stellar photon escape fraction
    # homogeneous UV background inside the RT chemistry
    # (rt_UV_hom; amplitude from &COOLING_PARAMS J21/a_spec/z_reion)
    rt_uv_hom: bool = False


@dataclass
class CoolingParams:
    """&COOLING_PARAMS (hydro/read_hydro_params.f90:92-95)."""
    cooling: bool = False
    metal: bool = False
    isothermal: bool = False
    haardt_madau: bool = False
    J21: float = 0.0
    a_spec: float = 1.0
    self_shielding: bool = False
    z_ave: float = 0.0
    z_reion: float = 8.5
    T2max: float = 1e50
    neq_chem: bool = False
    cooling_ism: bool = False
    barotropic_eos: bool = False
    barotropic_eos_form: str = "isothermal"
    polytrope_rho: float = 0.0
    polytrope_index: float = 1.0
    T_eos: float = 10.0
    mu_gas: float = 1.0


@dataclass
class UnitsParams:
    """&UNITS_PARAMS (amr/units.f90)."""
    units_density: float = 1.0
    units_time: float = 1.0
    units_length: float = 1.0


@dataclass
class EnsembleParams:
    """&ENSEMBLE_PARAMS (ours: the batched many-scenario engine,
    ramses_tpu/ensemble — no reference equivalent; the reference runs
    one namelist per MPI job).

    ``nmember > 1`` turns the namelist into an ensemble: the uniform
    fused step chain is vmapped over a leading member axis so one
    compiled program advances every member.  ``sweep_name`` rows give
    dotted parameter paths ("init.p_region[1]", "hydro.gamma") ramped
    linearly from ``sweep_start`` to ``sweep_stop`` across members;
    ``perturb_amp > 0`` additionally applies a deterministic per-member
    density perturbation seeded by ``perturb_seed + member``."""
    nmember: int = 0
    sweep_name: List[str] = field(default_factory=list)
    sweep_start: List[float] = field(default_factory=list)
    sweep_stop: List[float] = field(default_factory=list)
    perturb_amp: float = 0.0
    perturb_seed: int = 0
    chunk_steps: int = 16          # fused steps per engine dispatch
    # member isolation ladder (resilience/stepguard.BatchGuard): a
    # non-finite member is rolled back to its pre-window state and
    # re-advanced at halved dt (LLF escalation from the second retry);
    # after max_member_retries failures it is quarantined so the rest
    # of the batch keeps running.  member_quarantine arms the guard
    # even with zero retries (trip -> quarantine directly).  Both off
    # by default: the engine retains no state and adds no fetches.
    max_member_retries: int = 0
    member_quarantine: bool = False
    # run-service knobs (ensemble/queue): a running job whose heartbeat
    # mtime is older than queue_stale_s is presumed orphaned and may be
    # reclaimed by another worker
    queue_stale_s: float = 300.0
    # two-level parallelism (ensemble/meshplan.MeshPlan): a job whose
    # per-member cell count stays at or below pack_cell_budget packs
    # members across independent per-device replicas (the member vmap
    # sharded over a replica mesh axis); above the budget the job is
    # mesh-wide — members stream through the explicit slab pipeline on
    # the full local mesh
    pack_cell_budget: int = 2 ** 21
    # cap on the replica count a packed job may spread over (0 = every
    # device the scheduler assigned)
    pack_max_replicas: int = 0
    # scheduler demand clamps stamped into the queue record at submit
    # (0 = auto: min 1 shard, max = the worker's mesh size); a
    # mesh-wide job effectively pins min_shards to the whole mesh
    min_shards: int = 0
    max_shards: int = 0
    # starvation bound for the cost-aware gang scheduler: a queued
    # mesh-wide (exclusive) job older than this preempts small-job
    # bin-packing — the worker drains to exclusive mode and runs it
    # next regardless of cost order
    gang_starve_s: float = 600.0
    # serve-loop default: point the persistent compile cache at a
    # shared <queue_dir>/compile_cache so fleet workers warm-start each
    # other (an explicit &RUN_PARAMS compile_cache_dir or
    # RAMSES_COMPILE_CACHE still wins); .false. restores the PR 12
    # opt-in behavior
    shared_compile_cache: bool = True
    # hang watchdog for the batched engine (resilience/watchdog.py):
    # same semantics as the &RUN_PARAMS deadlines, but guarding the
    # engine's per-chunk dispatch fetch; a hang escaping run_job makes
    # the serve loop requeue the job with stage="hang"
    compile_deadline_s: float = 0.0
    step_deadline_s: float = 0.0
    io_deadline_s: float = 0.0
    # disk-pressure degradation (resilience/diskguard): free-space
    # watermarks [MiB] on the job's results filesystem.  Below
    # disk_soft_free_mb the per-chunk checkpoint beat is shed (the run
    # keeps stepping; an io_degraded event + Prometheus gauge say so);
    # the worker-level hard watermark additionally pauses new claims.
    # 0 disables; RAMSES_DISK_SOFT_MB / RAMSES_DISK_HARD_MB env vars
    # override per worker
    disk_soft_free_mb: float = 0.0
    disk_hard_free_mb: float = 0.0


@dataclass
class CalibrationParams:
    """&CALIBRATION_PARAMS (ours: the differentiable calibration service,
    ramses_tpu/diff — no reference equivalent; fits namelist parameters
    to a target rollout by Adam gradient descent through the checkpointed
    adjoint step chain)."""
    # master switch: run this namelist as a calibration (fit selected
    # parameters against a target rollout) instead of a forward
    # simulation; `--calibrate` on the CLI and calibrate-kind queue jobs
    # take the same path
    calibrate: bool = False
    # fit the EOS gamma (traced through the inlined step chain) — the
    # namelist's &HYDRO_PARAMS gamma is the *truth* used to synthesise
    # the target, and the optimizer starts from a perturbed guess
    fit_gamma: bool = True
    # additionally fit a log-amplitude scale on the initial condition
    # (one scalar multiplying the whole IC state)
    fit_ic: bool = False
    # Courant steps in the target/fit rollout window
    nsteps: int = 8
    # physical end time of the rollout; 0 → the last &OUTPUT_PARAMS tout
    tend: float = 0.0
    # remat window length of the checkpointed scan;
    # 0 → ceil(sqrt(nsteps)) (the O(sqrt N) adjoint-memory schedule)
    inner: int = 0
    # optimizer iterations
    niter: int = 60
    # Adam learning rate
    lr: float = 2e-2
    # clip the per-member global gradient norm (0 = off)
    grad_clip: float = 0.0
    # batched calibration: B independent members advance in one compiled
    # vmapped program (cf. &ENSEMBLE_PARAMS nmember)
    nmember: int = 1
    # initial gamma guess; 0 → truth * (1 + guess_spread).  With
    # nmember > 1 the member guesses are spread uniformly over
    # guess ± truth*guess_spread
    gamma_guess: float = 0.0
    guess_spread: float = 0.05
    # initial IC log-amplitude guess (fit_ic)
    ic_guess: float = 0.0
    # divergence screen: a member whose loss is non-finite or exceeds
    # diverge_loss (0 = non-finite only) is quarantined via the
    # BatchGuard ladder — its parameters freeze, the batch keeps running
    diverge_loss: float = 0.0
    # optimizer-state checkpoint cadence in iterations (0 = final only);
    # checkpoints are manifest-valid output_NNNNN dirs, so &RUN_PARAMS
    # auto_resume restarts a killed calibration from the last one
    checkpoint_every: int = 0


@dataclass
class Params:
    """Full runtime configuration (one object per simulation)."""
    ndim: int = 3               # compile-time in the reference (bin/Makefile:7)
    nvar: int = 0               # 0 → ndim+2+nener+npassive
    nener: int = 0
    npassive: int = 0
    run: RunParams = field(default_factory=RunParams)
    amr: AmrParams = field(default_factory=AmrParams)
    output: OutputParams = field(default_factory=OutputParams)
    init: InitParams = field(default_factory=InitParams)
    hydro: HydroParams = field(default_factory=HydroParams)
    refine: RefineParams = field(default_factory=RefineParams)
    boundary: BoundaryParams = field(default_factory=BoundaryParams)
    poisson: PoissonParams = field(default_factory=PoissonParams)
    cooling: CoolingParams = field(default_factory=CoolingParams)
    rt: RtParams = field(default_factory=RtParams)
    units: UnitsParams = field(default_factory=UnitsParams)
    ensemble: EnsembleParams = field(default_factory=EnsembleParams)
    calibration: CalibrationParams = field(
        default_factory=CalibrationParams)
    lightcone: LightconeParams = field(
        default_factory=LightconeParams)
    clumpfind: ClumpfindParams = field(
        default_factory=ClumpfindParams)
    raw: Dict[str, Dict[str, Any]] = field(default_factory=dict)

    def __post_init__(self):
        if self.nvar == 0:
            self.nvar = self.ndim + 2 + self.nener + self.npassive
        else:
            self.npassive = self.nvar - self.ndim - 2 - self.nener


_GROUP_MAP = {
    "run_params": "run",
    "amr_params": "amr",
    "output_params": "output",
    "init_params": "init",
    "hydro_params": "hydro",
    "refine_params": "refine",
    "boundary_params": "boundary",
    "poisson_params": "poisson",
    "cooling_params": "cooling",
    "rt_params": "rt",
    "units_params": "units",
    "ensemble_params": "ensemble",
    "calibration_params": "calibration",
    "lightcone_params": "lightcone",
    "clumpfind_params": "clumpfind",
}

# fields that are per-region/bound/level lists: (field, count_attr, default)
_LIST_FIELDS = {
    "init": dict(count="nregion",
                 fields=dict(region_type="square", x_center=0.0, y_center=0.0,
                             z_center=0.0, length_x=1e10, length_y=1e10,
                             length_z=1e10, exp_region=2.0, d_region=0.0,
                             u_region=0.0, v_region=0.0, w_region=0.0,
                             p_region=0.0, A_region=0.0, B_region=0.0,
                             C_region=0.0)),
    "boundary": dict(count="nboundary",
                     fields=dict(bound_type=0, ibound_min=0, ibound_max=0,
                                 jbound_min=0, jbound_max=0, kbound_min=0,
                                 kbound_max=0, d_bound=0.0, u_bound=0.0,
                                 v_bound=0.0, w_bound=0.0, p_bound=0.0)),
}


def params_from_dict(groups: Dict[str, Dict[str, Any]],
                     ndim: int = 3, **overrides: Any) -> Params:
    """Build :class:`Params` from parsed namelist groups."""
    p = Params(ndim=ndim, **overrides)
    p.raw = groups
    for gname, attr in _GROUP_MAP.items():
        gdict = groups.get(gname)
        if not gdict:
            continue
        sub = getattr(p, attr)
        valid = {f.name: f for f in dataclasses.fields(sub)}
        for key, value in gdict.items():
            if key == "boundary_type":
                key = "bound_type"  # nml name differs from our field name
            # the parser lowercases namelist keys; map back the reference's
            # capitalized MHD region fields (mhd/hydro_parameters.f90:80-82)
            key = {"a_region": "A_region", "b_region": "B_region",
                   "c_region": "C_region", "j21": "J21", "t2max": "T2max",
                   "t_eos": "T_eos"}.get(key, key)
            if key not in valid:
                continue  # unknown keys ignored (subsystem not yet built)
            ftype = valid[key].type
            cur = getattr(sub, key)
            if isinstance(cur, list) or str(ftype).startswith("List"):
                setattr(sub, key, value if isinstance(value, (list, dict))
                        else [value])
            else:
                if isinstance(value, list):
                    value = value[0]
                setattr(sub, key, value)
    # initfile(1)=... indexed assignment (the reference's multi-level
    # zoom IC syntax, amr/init_time.f90 initfile(1:nlevelmax)) parses
    # to a {1-based-index: value} dict: densify to an ordered list
    if isinstance(p.init.initfile, dict):
        idx = p.init.initfile
        nmax = max(idx)
        p.init.initfile = [
            (idx[i][0] if isinstance(idx.get(i), list) else idx.get(i, ""))
            for i in range(1, nmax + 1)]
    # densify per-region / per-boundary lists
    for attr, spec in _LIST_FIELDS.items():
        sub = getattr(p, attr)
        n = getattr(sub, spec["count"])
        for fname, default in spec["fields"].items():
            setattr(sub, fname, densify(getattr(sub, fname) or None, n, default))
    # densify per-level lists
    p.run.nsubcycle = [int(v) for v in
                       densify(p.run.nsubcycle, MAXLEVEL, 2)]
    p.amr.nexpand = [int(v) for v in densify(p.amr.nexpand, MAXLEVEL, 1)]
    for f in ("jeans_refine", "m_refine", "x_refine", "y_refine", "z_refine",
              "r_refine", "a_refine", "b_refine", "exp_refine"):
        cur = getattr(p.refine, f)
        dflt = {"a_refine": 1.0, "b_refine": 1.0, "exp_refine": 2.0,
                "x_refine": 0.0, "y_refine": 0.0, "z_refine": 0.0}.get(f, -1.0)
        setattr(p.refine, f, [float(v) for v in densify(cur, MAXLEVEL, dflt)])
    # output times (tout/aout accept scalars, lists and indexed assignment)
    for f in ("tout", "aout"):
        cur = getattr(p.output, f)
        if isinstance(cur, dict) or any(isinstance(v, dict) for v in cur
                                        if isinstance(cur, list)):
            if isinstance(cur, list):  # list wrapping a {idx: vals} dict
                cur = cur[0]
            n = max(p.output.noutput, max(cur) + max(len(v) for v in
                                                     cur.values()) - 1)
            setattr(p.output, f, [float(v) for v in densify(cur, n, HUGE)])
        elif not isinstance(cur, list):
            setattr(p.output, f, [cur])
    if p.output.noutput == 0 and p.output.tout:
        p.output.noutput = len(p.output.tout)
    # tend/delta_tout style (e.g. the reference's dice namelists): synthesise
    # the tout ladder the driver iterates over.
    if p.output.tend > 0.0 and not p.output.tout:
        dt = p.output.delta_tout
        if dt >= HUGE or dt <= 0.0:
            p.output.tout = [p.output.tend]
        else:
            ts, t = [], dt
            while t < p.output.tend * (1.0 - 1e-12):
                ts.append(t)
                t += dt
            ts.append(p.output.tend)
            p.output.tout = ts
        p.output.noutput = len(p.output.tout)
    if p.amr.ngridmax == 0 and p.amr.ngridtot:
        p.amr.ngridmax = p.amr.ngridtot
    return p


def load_params(path: str, ndim: int = 3, **overrides: Any) -> Params:
    """Load a RAMSES-style namelist file into a :class:`Params`."""
    return params_from_dict(load_nml(path), ndim=ndim, **overrides)


def params_from_string(text: str, ndim: int = 3, **overrides: Any) -> Params:
    return params_from_dict(parse_nml(text), ndim=ndim, **overrides)
