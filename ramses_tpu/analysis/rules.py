"""Rule registry + finding model of the static-analysis engine.

Every major perf/correctness incident in this repo's history was
visible in the *lowered program* before any TPU ran it: the
6^d-duplicated stencil gather (PR 8), the ``ct_core`` closed-over
constant that caused involuntary full rematerialization (PR 10), the
GSPMD scatter reassociation that broke MHD determinism to ~1 ulp
(ROADMAP item 2), donation regressions, and stray host syncs.  This
package turns each of those incident classes into a :class:`Rule`
that runs over the lowered StableHLO of the canonical step-chain
programs (:mod:`ramses_tpu.analysis.programs`) — or, for the
source-level hazards, over the ``ramses_tpu`` AST — on the CPU test
backend, so the regression fails in CI instead of on a TPU tunnel.

Suppression model: every :class:`Finding` carries a *fingerprint*
that is stable across line moves and tree rebuilds (rule id +
program/module + a salient structural key, never raw byte offsets).
``analysis/baseline.json`` holds the fingerprints of accepted
findings; ``tools/lint.py --check`` fails only on findings outside
the baseline, and ``--update-baseline`` rewrites it.
"""

from __future__ import annotations

import enum
import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


class Severity(enum.IntEnum):
    """Ordered so gates can threshold (``>= WARN`` fails --check)."""
    INFO = 0
    WARN = 1
    ERROR = 2

    def __str__(self) -> str:       # human sink prints "error", not "2"
        return self.name.lower()


@dataclass(frozen=True)
class Finding:
    """One rule violation in one program (or source module).

    ``key`` is the structural identity the fingerprint hashes —
    callers choose it so a finding survives unrelated churn (e.g.
    ``tensor<216x64xf32>`` for a constant, ``module:function:call``
    for a host sync) but changes when the hazard itself changes.
    """
    rule: str                       # rule id, e.g. "gather-blowup"
    severity: Severity
    program: str                    # program name or source module
    message: str                    # one-line human statement
    key: str                        # structural identity (fingerprinted)
    detail: Dict[str, Any] = field(default_factory=dict)

    @property
    def fingerprint(self) -> str:
        h = hashlib.sha256(
            f"{self.rule}|{self.program}|{self.key}".encode())
        return h.hexdigest()[:16]

    def to_json(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "severity": str(self.severity),
            "program": self.program,
            "message": self.message,
            "key": self.key,
            "fingerprint": self.fingerprint,
            "detail": self.detail,
        }


@dataclass(frozen=True)
class Rule:
    """One hazard class: a checker over a lowered program (kind
    ``"hlo"``) or over the package source tree (kind ``"source"``).

    HLO checkers are called once per program as ``check(program)``;
    source checkers once per run as ``check(root_dir)``.  Both return
    a list of :class:`Finding`.
    """
    id: str
    kind: str                       # "hlo" | "source"
    doc: str                        # incident the rule is grounded in
    check: Callable[..., List["Finding"]]


_REGISTRY: Dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    if rule.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {rule.id!r}")
    _REGISTRY[rule.id] = rule
    return rule


def all_rules() -> List[Rule]:
    """Registered rules, importing the built-in rule modules on first
    use (registration is an import side effect there)."""
    from ramses_tpu.analysis import hlo_rules, source_rules  # noqa: F401
    return list(_REGISTRY.values())


def get_rule(rule_id: str) -> Rule:
    from ramses_tpu.analysis import hlo_rules, source_rules  # noqa: F401
    return _REGISTRY[rule_id]


# ---------------------------------------------------------------------
# baseline: fingerprinted accepted findings
# ---------------------------------------------------------------------
BASELINE_VERSION = 1
DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                "baseline.json")


def load_baseline(path: Optional[str] = None) -> Dict[str, Dict[str, Any]]:
    """``{fingerprint: entry}`` of accepted findings (empty when the
    file does not exist yet)."""
    path = path or DEFAULT_BASELINE
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {data.get('version')!r}, "
            f"expected {BASELINE_VERSION}")
    return {e["fingerprint"]: e for e in data.get("findings", [])}


def save_baseline(findings: List[Finding],
                  path: Optional[str] = None) -> str:
    """Write the accepted-findings baseline for ``findings`` (sorted,
    deduplicated by fingerprint so reruns produce byte-identical
    files)."""
    path = path or DEFAULT_BASELINE
    seen: Dict[str, Dict[str, Any]] = {}
    for f in findings:
        seen.setdefault(f.fingerprint, {
            "fingerprint": f.fingerprint,
            "rule": f.rule,
            "program": f.program,
            "key": f.key,
            "message": f.message,
        })
    data = {
        "version": BASELINE_VERSION,
        "findings": [seen[k] for k in sorted(seen)],
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def split_baselined(findings: List[Finding],
                    baseline: Dict[str, Dict[str, Any]]):
    """``(new, accepted)`` partition of ``findings`` against a loaded
    baseline."""
    new, accepted = [], []
    for f in findings:
        (accepted if f.fingerprint in baseline else new).append(f)
    return new, accepted


def severity_counts(findings: List[Finding]) -> Dict[str, int]:
    """``{"error": n, "warn": n, "info": n}`` — the telemetry
    run-header shape (``analysis_findings``)."""
    out = {"error": 0, "warn": 0, "info": 0}
    for f in findings:
        out[str(f.severity)] += 1
    return out
