"""Halo finder + merger tree CLI over snapshot outputs.

The reference's halo chain (``pm/clump_finder.f90`` →
``pm/unbinding.f90`` → ``pm/merger_tree.f90``) runs inside the
simulation; the standalone analysis equivalents live in ``utils/f90``
(``part2map``-family).  This CLI reads the particle files of one or
more ``output_NNNNN`` directories, deposits an NGP density grid, runs
the watershed clump finder, unbinds, writes a halo table per output,
and links consecutive outputs into a merger tree.

CLI:  ``python -m ramses_tpu.utils.halos output_00001 output_00002
      --nx 64 --threshold-over-mean 5 --tree tree.txt``
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

from ramses_tpu.io import reader as rdr
from ramses_tpu.pm.clumps import find_clumps
from ramses_tpu.pm.halo import MergerTree, build_catalogue, write_halo_table


def load_particles(outdir: str):
    """(x [n, ndim], v, m, ids, boxlen, t) from one output directory."""
    snap = rdr.load_snapshot(outdir)
    if "part" not in snap:
        raise FileNotFoundError(f"no particle files in {outdir}")
    ndim = snap["info"]["ndim"]
    boxlen = snap["amr"][0].header["boxlen"]
    t = snap["info"].get("time", 0.0)
    xs, vs, ms, ids = [], [], [], []
    for part in snap["part"]:
        n = len(part["mass"])
        if n == 0:
            continue
        xs.append(np.stack([part[f"position_{'xyz'[d]}"]
                            for d in range(ndim)], axis=1))
        vs.append(np.stack([part[f"velocity_{'xyz'[d]}"]
                            for d in range(ndim)], axis=1))
        ms.append(np.asarray(part["mass"]))
        ids.append(np.asarray(part["identity"], dtype=np.int64))
    if not xs:
        raise ValueError(f"{outdir}: particle files are empty")
    return (np.concatenate(xs), np.concatenate(vs), np.concatenate(ms),
            np.concatenate(ids), float(boxlen), float(t))


def catalogue_from_arrays(x, v, m, ids, boxlen, nx: int = 64,
                          threshold: float = -1.0,
                          threshold_over_mean: float = 5.0,
                          relevance: float = 1.5, G: float = 1.0,
                          npart_min: int = 10, unbind: bool = True,
                          saddle_pot: bool = False, nmassbins: int = 0,
                          saddle_threshold: float = 0.0):
    """PHEW chain on in-memory particle arrays: deposit → watershed →
    unbind.  Shared by the offline CLI and the in-run
    ``clumpfind=.true.`` pass.  ``threshold``: absolute density
    threshold in code units (<0 → ``threshold_over_mean`` × mean)."""
    nd = x.shape[1]
    dx = boxlen / nx
    idx = tuple(np.clip((np.mod(x[:, d], boxlen) / dx).astype(int),
                        0, nx - 1) for d in range(nd))
    rho = np.zeros((nx,) * nd)
    np.add.at(rho, idx, m / dx ** nd)
    thr = (float(threshold) if threshold > 0
           else float(rho.mean()) * threshold_over_mean)
    labels, _ = find_clumps(rho, thr, relevance=relevance, dx=dx,
                            saddle_threshold=saddle_threshold)
    pl = np.asarray(labels)[idx]        # NGP labels, one gather
    return build_catalogue(x, v, m, ids, pl, boxlen, G=G,
                           unbind=unbind, npart_min=npart_min,
                           saddle_pot=saddle_pot, nmassbins=nmassbins)


def catalogue_output(outdir: str, nx: int = 64,
                     threshold_over_mean: float = 5.0,
                     relevance: float = 1.5, G: float = 1.0,
                     npart_min: int = 10, unbind: bool = True,
                     saddle_pot: bool = False, nmassbins: int = 0):
    """Full chain on one output directory; returns (halos, t)."""
    x, v, m, ids, boxlen, t = load_particles(outdir)
    return catalogue_from_arrays(
        x, v, m, ids, boxlen, nx=nx,
        threshold_over_mean=threshold_over_mean, relevance=relevance,
        G=G, npart_min=npart_min, unbind=unbind,
        saddle_pot=saddle_pot, nmassbins=nmassbins), t


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ramses_tpu.utils.halos")
    ap.add_argument("outdirs", nargs="+",
                    help="output_NNNNN directories, time-ordered")
    ap.add_argument("--nx", type=int, default=64)
    ap.add_argument("--threshold-over-mean", type=float, default=5.0)
    ap.add_argument("--relevance", type=float, default=1.5)
    ap.add_argument("--npart-min", type=int, default=10)
    ap.add_argument("--no-unbind", action="store_true")
    ap.add_argument("--saddle-pot", action="store_true",
                    help="reference binding energies to the clump "
                         "boundary potential (unbinding.f90 saddle_pot)")
    ap.add_argument("--nmassbins", type=int, default=0,
                    help="binned mass-profile potential with N radial "
                         "bins (0 = exact per-particle monopole)")
    ap.add_argument("--nmost-bound", type=int, default=0,
                    help="merger-tree tracers per halo (0 = all bound; "
                         "merger_tree.f90 nmost_bound)")
    ap.add_argument("--max-gap", type=int, default=2,
                    help="snapshots a vanished progenitor stays "
                         "linkable across (merger_tree.f90 jumpers)")
    ap.add_argument("--tree", default=None,
                    help="merger-tree table path (needs >=2 outputs)")
    args = ap.parse_args(argv)

    tree = MergerTree(max_gap=args.max_gap,
                      nmost_bound=args.nmost_bound)
    for outdir in args.outdirs:
        halos, t = catalogue_output(
            outdir, nx=args.nx,
            threshold_over_mean=args.threshold_over_mean,
            relevance=args.relevance, npart_min=args.npart_min,
            unbind=not args.no_unbind, saddle_pot=args.saddle_pot,
            nmassbins=args.nmassbins)
        table = os.path.join(outdir, "halos.txt")
        write_halo_table(halos, table)
        print(f"{outdir}: {len(halos)} halos -> {table}"
              + (f" (max mass {halos[0].mass:.4e})" if halos else ""))
        tree.add_snapshot(t, halos)
    if args.tree and len(args.outdirs) >= 2:
        tree.write(args.tree)
        nlink = sum(len(ls) for _s, ls in tree.links)
        print(f"merger tree: {nlink} links -> {args.tree}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
