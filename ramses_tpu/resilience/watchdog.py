"""Deadline watchdog: hangs become first-class, classified faults.

The failure mode the crash/NaN ladder (stepguard) cannot see is a run
that simply *stops making progress* — a wedged device tunnel, a
backend init that never returns, a compile that spins.  Every driver
does exactly one blocking host fetch per fused window, so "hung" has a
precise, observable definition: that fetch exceeded its wall-clock
budget.  A :class:`Watchdog` arms a monitor thread around the fetch;
on expiry it

  1. emits a structured ``hang`` telemetry event,
  2. writes an emergency manifest-valid ``hang_NNNNN/`` dump from the
     last *fetched host* state (never touching the device — the device
     is what hung),
  3. raises :class:`HangDetected` in the main thread (a SIGALRM-based
     soft interrupt, which breaks out of injected hangs and most
     interruptible waits), and
  4. if the guarded section still has not exited after a grace period
     (a true uninterruptible hang in C), hard-exits the process with
     :data:`HANG_EXIT_CODE` so a parent supervisor — the serve loop's
     stale reclaim, bench.py's subprocess parent, a cluster batch
     system — can classify hang vs crash by exit status.

``resilience/supervisor.py`` catches :class:`HangDetected` distinctly
from crashes and NaN ladders and applies the hang policy: immediate
resume from the newest checkpoint (no backoff, no dt-halving — the
state is not numerically suspect) under a bounded hang-retry budget.

Deadlines come from ``&RUN_PARAMS`` / ``&ENSEMBLE_PARAMS``
(``compile_deadline_s`` / ``step_deadline_s`` / ``io_deadline_s``) or
the matching ``RAMSES_*_DEADLINE_S`` environment overrides.  All three
unset means :meth:`Watchdog.from_params` returns ``None`` — the same
zero-overhead off switch as StepGuard/FaultInjector: drivers skip the
guard entirely and add no host<->device fetches (pinned by the
device_get-counting test in ``tests/test_watchdog.py``).
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Optional

#: process exit status for an unrecoverable (hard) hang — distinct
#: from crash (nonzero) and clean exit so parents classify by rc.
HANG_EXIT_CODE = 87

PHASES = ("compile", "step", "io")

_lock = threading.Lock()
_pending: Dict[str, Any] = {}      # monitor -> main-thread handoff
_installed = False
_prev_handler = None


class HangDetected(RuntimeError):
    """A guarded phase exceeded its wall-clock deadline.

    Carries the classification payload (phase, deadline, last-known
    host step/time) so supervisors can log hang-vs-crash distinctly.
    """

    def __init__(self, phase: str = "step", deadline_s: float = 0.0,
                 nstep=None, t=None):
        self.phase = phase
        self.deadline_s = float(deadline_s)
        self.nstep = nstep
        self.t = t
        at = f" at nstep={nstep}" if nstep is not None else ""
        super().__init__(f"phase {phase!r} exceeded "
                         f"{self.deadline_s:g}s deadline{at}")


def _on_alarm(signum, frame):
    """SIGALRM entry: raise the pending hang in the main thread.  With
    nothing pending (foreign alarm) defer to the previous handler."""
    with _lock:
        info = _pending.pop("hang", None)
    if info is None:
        prev = _prev_handler
        if callable(prev):
            prev(signum, frame)
        return
    raise HangDetected(**info)


def _install_handler() -> bool:
    """Install the shared SIGALRM soft-interrupt handler (idempotent;
    main thread only — elsewhere the hard-exit path still covers)."""
    global _installed, _prev_handler
    if _installed:
        return True
    if threading.current_thread() is not threading.main_thread():
        return False
    try:
        prev = signal.signal(signal.SIGALRM, _on_alarm)
    except (ValueError, OSError):      # no signals on this platform
        return False
    if prev not in (signal.SIG_DFL, signal.SIG_IGN, None):
        _prev_handler = prev
    _installed = True
    return True


def _uninstall_handler():
    """Restore the pre-watchdog SIGALRM disposition (test hygiene)."""
    global _installed, _prev_handler
    if not _installed:
        return
    try:
        signal.signal(signal.SIGALRM, _prev_handler or signal.SIG_DFL)
    except (ValueError, OSError):
        pass
    _installed = False
    _prev_handler = None
    with _lock:
        _pending.clear()


class Watchdog:
    """Per-phase wall-clock deadlines around blocking device fetches.

    Drivers hold ``self._wd = Watchdog.from_params(params)`` — ``None``
    when every deadline is unset (zero-overhead off) — and wrap each
    fused-window dispatch+fetch in ``with wd.guard("step"): ...``.
    The first step guard per process uses ``compile_deadline_s`` when
    set (compile happens inside the first dispatch), later ones
    ``step_deadline_s``; dump paths use ``guard("io")``.

    After every successful fetch the driver calls
    ``wd.note(nstep=..., t=...)`` so the expiry path can stamp the
    emergency dump and telemetry with the last *fetched* host state.
    """

    def __init__(self, compile_deadline_s: float = 0.0,
                 step_deadline_s: float = 0.0,
                 io_deadline_s: float = 0.0,
                 telemetry=None, base_dir: str = ".",
                 grace_s: float = 30.0, hard_exit: bool = True):
        self.deadlines = {"compile": float(compile_deadline_s or 0.0),
                          "step": float(step_deadline_s or 0.0),
                          "io": float(io_deadline_s or 0.0)}
        self.telemetry = telemetry
        self.base_dir = str(base_dir or ".")
        self.grace_s = float(os.environ.get("RAMSES_HANG_GRACE_S",
                                            grace_s))
        self.hard_exit = bool(hard_exit)
        self.hangs = 0                 # expiries observed
        self._warmed = False           # first step guard == compile
        self._host: Dict[str, Any] = {}
        self._ndump = 0
        self._installed = _install_handler()

    # ---- construction -------------------------------------------------

    @classmethod
    def from_params(cls, params, scope: str = "run", telemetry=None,
                    base_dir: Optional[str] = None
                    ) -> Optional["Watchdog"]:
        """A watchdog when any ``*_deadline_s`` is set under the
        ``scope`` group (``run`` or ``ensemble``) or the matching
        ``RAMSES_{COMPILE,STEP,IO}_DEADLINE_S`` env override, else
        ``None`` (the zero-overhead off switch)."""
        grp = getattr(params, scope, None)

        def pick(key: str) -> float:
            env = os.environ.get(f"RAMSES_{key.upper()}")
            if env is not None:
                try:
                    return float(env)
                except ValueError:
                    pass
            return float(getattr(grp, key, 0.0) or 0.0)

        c = pick("compile_deadline_s")
        s = pick("step_deadline_s")
        io = pick("io_deadline_s")
        if c <= 0.0 and s <= 0.0 and io <= 0.0:
            return None
        if base_dir is None:
            base_dir = str(getattr(getattr(params, "output", None),
                                   "output_dir", "."))
        return cls(c, s, io, telemetry=telemetry, base_dir=base_dir)

    # ---- host-state bookkeeping --------------------------------------

    def note(self, **fields):
        """Record the latest fetched host scalars (nstep, t, ...) —
        the only state the expiry path may touch."""
        self._host.update(fields)

    # ---- guarding -----------------------------------------------------

    def _effective(self, phase: str):
        """(effective phase, deadline): the first step window runs
        under the compile budget when one is set."""
        if phase == "step" and not self._warmed \
                and self.deadlines["compile"] > 0.0:
            return "compile", self.deadlines["compile"]
        return phase, self.deadlines.get(phase, 0.0)

    @contextmanager
    def guard(self, phase: str = "step"):
        """Deadline-guard the enclosed blocking section."""
        eff, deadline = self._effective(phase)
        if deadline <= 0.0:
            try:
                yield
            finally:
                if phase == "step":
                    self._warmed = True
            return
        done = threading.Event()
        th = threading.Thread(target=self._monitor,
                              args=(eff, deadline, done),
                              name=f"watchdog-{eff}", daemon=True)
        th.start()
        try:
            yield
        finally:
            done.set()
            if phase == "step":
                self._warmed = True

    def _monitor(self, phase: str, deadline: float,
                 done: threading.Event):
        if done.wait(deadline):
            return                      # guarded section finished
        self.hangs += 1
        info = {"phase": phase, "deadline_s": deadline,
                "nstep": self._host.get("nstep"),
                "t": self._host.get("t")}
        dump = None
        try:
            dump = self._emergency_dump(phase, deadline)
        except Exception:
            pass
        tel = self.telemetry
        if tel is not None:
            try:
                tel.record_event("hang", phase=phase,
                                 deadline_s=deadline, dump=dump,
                                 **dict(self._host))
            except Exception:
                pass
        print(f" watchdog: phase {phase!r} exceeded {deadline:g}s "
              f"deadline at nstep={info['nstep']}; classifying as "
              "hang", flush=True)
        with _lock:
            _pending["hang"] = info
        main = threading.main_thread()
        if self._installed and main.is_alive():
            try:
                signal.pthread_kill(main.ident, signal.SIGALRM)
            except (OSError, ValueError):
                pass
        if done.wait(self.grace_s):
            return                      # soft interrupt worked
        if self.hard_exit:
            print(f" watchdog: hang uninterruptible after "
                  f"{self.grace_s:g}s grace; exiting "
                  f"{HANG_EXIT_CODE}", flush=True)
            os._exit(HANG_EXIT_CODE)

    def _emergency_dump(self, phase: str, deadline: float
                        ) -> Optional[str]:
        """Manifest-valid ``hang_NNNNN/`` diagnostics dump from the
        last fetched host state.  The ``hang_`` prefix keeps it out of
        ``scan_checkpoints`` (prefix ``output_``) — it documents the
        hang, it is never resumed from."""
        from ramses_tpu.resilience.checkpoint import finalize_checkpoint
        self._ndump += 1
        final = os.path.join(self.base_dir, f"hang_{self._ndump:05d}")
        stage = final + ".tmp"
        os.makedirs(stage, exist_ok=True)
        payload = {"phase": phase, "deadline_s": deadline,
                   "time_unix": time.time()}
        payload.update(self._host)
        with open(os.path.join(stage, "hang.json"), "w") as f:
            json.dump(payload, f, indent=1, default=str)
        meta = {"kind": "hang", "phase": phase}
        for k in ("nstep", "t"):
            if self._host.get(k) is not None:
                meta[k] = self._host[k]
        return finalize_checkpoint(stage, final, meta=meta)
