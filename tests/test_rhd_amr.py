"""SRHD on the AMR hierarchy (reference ``rhd/`` solver family +
``amr/`` driver shadowing, SURVEY.md §2.4)."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.config import params_from_dict
from ramses_tpu.rhd.amr import RhdAmrSim
from ramses_tpu.rhd.driver import RhdSimulation


def _tube_groups(lmin, lmax, tend=0.35):
    return {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": lmin, "levelmax": lmax, "boxlen": 1.0},
        "boundary_params": {"nboundary": 2,
                            "ibound_min": [-1, 1], "ibound_max": [-1, 1],
                            "bound_type": [2, 2]},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.25, 0.75], "length_x": [0.5, 0.5],
                        "exp_region": [10.0, 10.0],
                        "d_region": [10.0, 1.0],
                        "p_region": [13.33, 1e-2]},
        "hydro_params": {"gamma": 5.0 / 3.0, "courant_factor": 0.5,
                         "slope_type": 1},
        "refine_params": {"err_grad_d": 0.05, "err_grad_p": 0.05,
                          "err_grad_u": 0.05},
        "output_params": {"tend": tend},
    }


def _leaf_rho_on(sim: RhdAmrSim, n: int):
    """Leaf density block-filled onto a uniform n-cell 1D grid (each
    leaf covers n/2^l fine cells)."""
    rho = np.zeros(n)
    for l in sim.levels():
        xc, q = sim.leaf_prims(l)
        if not len(q):
            continue
        w = n // (1 << l)
        i0 = np.clip(((xc[:, 0] - 0.5 / (1 << l)) * n).round().astype(int),
                     0, n - w)
        for k in range(len(q)):
            rho[i0[k]:i0[k] + w] = q[k, 0]
    return rho


@pytest.mark.slow
def test_amr_blast_tube_beats_coarse_uniform():
    """Marti-Mueller-style blast: the 5→7 AMR run's L1(ρ) error vs a
    fine (levelmin=9) uniform oracle beats the uniform levelmin=5 run."""
    tend = 0.35
    p_amr = params_from_dict(_tube_groups(5, 7, tend), ndim=1)
    amr = RhdAmrSim(p_amr, dtype=jnp.float64)
    amr.evolve(tend)
    assert amr.nstep > 5

    p_fine = params_from_dict(_tube_groups(9, 9, tend), ndim=1)
    fine = RhdSimulation(p_fine, dtype=jnp.float64)
    fine.evolve(tend)
    rho_ref = fine.prims()[0]

    p_coarse = params_from_dict(_tube_groups(5, 5, tend), ndim=1)
    coarse = RhdSimulation(p_coarse, dtype=jnp.float64)
    coarse.evolve(tend)

    n = 512
    ref_on = rho_ref  # 512 cells at levelmin=9
    rho_amr = _leaf_rho_on(amr, n)
    rho_coarse = np.repeat(coarse.prims()[0], n // 32)
    l1_amr = np.abs(rho_amr - ref_on).mean()
    l1_coarse = np.abs(rho_coarse - ref_on).mean()
    assert l1_amr < 0.6 * l1_coarse, (l1_amr, l1_coarse)
    # the blast refined: fine levels exist and hold real octs
    assert amr.tree.noct(7) > 8


def test_lorentz_refinement_triggers():
    """A velocity-jump (Lorentz-gradient) region refines even with the
    density/pressure criteria off."""
    g = _tube_groups(5, 6, 0.1)
    g["init_params"]["d_region"] = [1.0, 1.0]
    g["init_params"]["p_region"] = [1.0, 1.0]
    g["init_params"]["u_region"] = [0.8, 0.0]
    g["refine_params"] = {"err_grad_d": -1.0, "err_grad_p": -1.0,
                          "err_grad_u": 0.1}
    p = params_from_dict(g, ndim=1)
    sim = RhdAmrSim(p, dtype=jnp.float64)
    assert sim.tree.has(6) and sim.tree.noct(6) > 0
    sim.evolve(0.05)
    assert sim.max_lorentz() > 1.2


@pytest.mark.slow
def test_conservation_periodic_2d_amr():
    """D, S, τ conserved across refined interfaces + regrids."""
    groups = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 4, "levelmax": 6, "boxlen": 1.0},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.5, 0.5], "y_center": [0.5, 0.5],
                        "length_x": [10.0, 0.25], "length_y": [10.0, 0.25],
                        "exp_region": [10.0, 2.0],
                        "d_region": [1.0, 1.0],
                        "p_region": [0.1, 10.0]},
        "hydro_params": {"gamma": 5.0 / 3.0, "courant_factor": 0.5},
        "refine_params": {"err_grad_d": 0.1, "err_grad_p": 0.1,
                          "err_grad_u": 0.1},
        "output_params": {"tend": 0.05},
    }
    p = params_from_dict(groups, ndim=2)
    sim = RhdAmrSim(p, dtype=jnp.float64)
    tot0 = sim.totals()
    sim.evolve(0.05)
    tot1 = sim.totals()
    assert sim.nstep >= 3
    # D and τ: relative; S: absolute (starts at 0)
    assert np.isclose(tot1[0], tot0[0], rtol=1e-10)
    assert np.isclose(tot1[4], tot0[4], rtol=1e-10)
    np.testing.assert_allclose(tot1[1:4], tot0[1:4], atol=1e-11)
    assert sim.tree.noct(5) > 0


def test_cli_dispatch_rhd_amr(tmp_path):
    """--solver rhd with levelmax>levelmin goes through RhdAmrSim."""
    import ramses_tpu.__main__ as main_mod
    nml = tmp_path / "rhd_amr.nml"
    nml.write_text(f"""
&RUN_PARAMS
hydro=.true.
nstepmax=3
/
&AMR_PARAMS
levelmin=4
levelmax=5
boxlen=1.0
/
&BOUNDARY_PARAMS
nboundary=2
ibound_min=-1,1
ibound_max=-1,1
bound_type=2,2
/
&INIT_PARAMS
nregion=2
region_type='square','square'
x_center=0.25,0.75
length_x=0.5,0.5
exp_region=10.0,10.0
d_region=10.0,1.0
p_region=13.33,0.01
/
&HYDRO_PARAMS
gamma=1.666667
courant_factor=0.5
/
&REFINE_PARAMS
err_grad_d=0.1
err_grad_p=0.1
/
&OUTPUT_PARAMS
tend=0.05
output_dir='{tmp_path}'
/
""")
    assert main_mod.main([str(nml), "--ndim", "1", "--solver", "rhd",
                          "--dtype", "float64"]) == 0
    assert (tmp_path / "output_00001" / "info_00001.txt").exists()


def test_rhd_amr_snapshot_roundtrip(tmp_path):
    """Dump → restore with the RELATIVISTIC prim/cons conversions:
    (D, S, τ) round-trips through the rho/v/P file columns, and
    continued stepping matches the uncheckpointed run."""
    tend = 0.2
    p = params_from_dict(_tube_groups(5, 6, tend), ndim=1)
    sim = RhdAmrSim(p, dtype=jnp.float64)
    sim.evolve(0.1, nstepmax=6)
    outdir = sim.dump(1, str(tmp_path))

    p2 = params_from_dict(_tube_groups(5, 6, tend), ndim=1)
    sim2 = RhdAmrSim.from_snapshot(p2, outdir, dtype=jnp.float64)
    assert sim2.t == pytest.approx(sim.t, rel=1e-14)
    for l in sim.levels():
        nc = sim.maps[l].noct * 2
        np.testing.assert_allclose(
            np.asarray(sim2.u[l])[:nc], np.asarray(sim.u[l])[:nc],
            rtol=1e-10, atol=1e-13)
    for s in (sim, sim2):
        s.step_coarse(s.coarse_dt())
    for l in sim.levels():
        nc = sim.maps[l].noct * 2
        np.testing.assert_allclose(
            np.asarray(sim2.u[l])[:nc], np.asarray(sim.u[l])[:nc],
            rtol=1e-9, atol=1e-12)
