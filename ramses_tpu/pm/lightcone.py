"""Cosmological lightcone particle selection.

The geometry core of ``amr/light_cone.f90`` (``perform_my_selection:424``):
between two coarse steps the lightcone shell [r1, r2] (comoving distance
travelled by light) sweeps through periodic replicas of the box; particles
inside the shell are emitted once with their replica-shifted coordinates.
Comoving distances come from the Friedmann conformal-time table the
cosmology module already integrates (r = c·Δτ in supercomoving units).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def shell_radii(cosmo, aexp1: float, aexp2: float) -> Tuple[float, float]:
    """Comoving radii [box-length units] of the lightcone shell between
    two expansion factors (observer at aexp=1): the PROPER comoving
    distance chi(a) = ∫ c·da'/(a'²H) from the Friedmann tables —
    the ``coord_distance`` integral of ``amr/light_cone.f90:795-804``
    (NOT the super-conformal Δτ, whose dτ = dt/a² lacks the c/a
    weighting)."""
    return (float(cosmo.chi_of_aexp(aexp2)),
            float(cosmo.chi_of_aexp(aexp1)))


def rotation_matrix(thetay: float = 0.0, thetaz: float = 0.0) -> np.ndarray:
    """Observer orientation (``light_cone.f90`` compute_rotation_matrix
    ``:580-640``: a y-rotation by ``thetay`` then a z-rotation by
    ``thetaz`` pointing the cone axis)."""
    cy, sy = np.cos(thetay), np.sin(thetay)
    cz, sz = np.cos(thetaz), np.sin(thetaz)
    ry = np.array([[cy, 0.0, sy], [0.0, 1.0, 0.0], [-sy, 0.0, cy]])
    rz = np.array([[cz, -sz, 0.0], [sz, cz, 0.0], [0.0, 0.0, 1.0]])
    return rz @ ry


def _replica_shifts(obs: np.ndarray, r1: float, r2: float,
                    boxlen: float, ndim: int) -> np.ndarray:
    """Periodic replica shifts whose box can intersect the shell
    [r1, r2), built axis by axis with incremental pruning.

    A proper comoving r2 can span hundreds of box lengths (deep
    cones), so materializing the full (2·nrep+1)^ndim shift cube —
    O(r2^ndim) memory — is not an option; pruning each axis on the
    partial minimum distance keeps intermediates at the shell's
    surface size O(r2^(ndim-1)) (``compute_replica``'s bounds,
    ``amr/light_cone.f90``)."""
    nrep = int(np.ceil(r2 / boxlen)) + 1
    k = np.arange(-nrep, nrep + 1) * boxlen
    los = [np.maximum(np.abs(k - obs[d]) - boxlen, 0.0) ** 2
           for d in range(ndim)]
    his = [(np.abs(k - obs[d]) + boxlen) ** 2 for d in range(ndim)]
    # largest possible contribution of the axes NOT yet expanded: rows
    # whose partial dmax2 + rem_max still misses r1 are ball interior
    # and can be dropped mid-build — without this, the dmin2 prune
    # alone keeps the whole O(r2^ndim) interior
    rem_max = [sum(h.max() for h in his[d + 1:]) for d in range(ndim)]
    shifts = np.zeros((1, 0))
    dmin2 = np.zeros(1)
    dmax2 = np.zeros(1)
    for d in range(ndim):
        # expand in k-chunks: pruning per chunk caps the transient at
        # O(|survivors| · chunk) — the unchunked last-axis expansion
        # would materialize the O(r2^ndim) interior before its prune
        parts = []
        for c0 in range(0, len(k), 16):
            kc, loc, hic = (a[c0:c0 + 16] for a in (k, los[d], his[d]))
            s = np.concatenate(
                [np.repeat(shifts, len(kc), axis=0),
                 np.tile(kc, len(shifts))[:, None]], axis=1)
            mn = (dmin2[:, None] + loc[None, :]).ravel()
            mx = (dmax2[:, None] + hic[None, :]).ravel()
            # later axes only grow both bounds, so both prunes are safe
            keep = (mn < r2 * r2) & (mx + rem_max[d] >= r1 * r1)
            parts.append((s[keep], mn[keep], mx[keep]))
        shifts = np.concatenate([p[0] for p in parts])
        dmin2 = np.concatenate([p[1] for p in parts])
        dmax2 = np.concatenate([p[2] for p in parts])
    return shifts


def cone_selection(x: np.ndarray, obs: Sequence[float], r1: float,
                   r2: float, boxlen: float = 1.0,
                   opening: Optional[float] = None,
                   axis: Sequence[float] = (0, 0, 1.0),
                   rotation: Optional[np.ndarray] = None,
                   half_angles: Optional[Tuple[float, float]] = None,
                   v: Optional[np.ndarray] = None):
    """Select particles in the shell r1 <= |x_rep − obs| < r2 over all
    periodic replicas intersecting the shell.

    Returns (positions [m, ndim] in observer coordinates, radii [m],
    source indices [m]) — a particle can appear in several replicas
    (``light_cone.f90`` replica loops).  ``rotation``: optional
    [ndim, ndim] observer orientation (see :func:`rotation_matrix`)
    applied to the emitted coordinates — the narrow-cone frame of
    ``perform_my_selection_narrow``; the opening-angle cut then acts
    along ``axis`` IN THE ROTATED FRAME.  ``half_angles`` =
    (thetay, thetaz) [radians]: the reference's RECTANGULAR cut
    (|x| ≤ z·tan(thetay), |y| ≤ z·tan(thetaz), z > 0 in the rotated
    frame).  ``v``: optional velocities, emitted alongside positions
    (the reference writes xp AND vp per cone particle).
    """
    x = np.asarray(x)
    ndim = x.shape[1]
    obs = np.asarray(obs, dtype=np.float64)
    shifts = _replica_shifts(obs, r1, r2, boxlen, ndim)

    out_x, out_r, out_i = [], [], []
    ax = np.asarray(axis, dtype=np.float64)[:ndim]
    ax = ax / np.linalg.norm(ax)
    cos_open = np.cos(opening) if opening is not None else None
    tan_yz = (tuple(np.tan(a) for a in half_angles)
              if half_angles is not None else None)
    for s in shifts:
        pos = x + s[None, :] - obs[None, :]
        if rotation is not None:
            pos = pos @ np.asarray(rotation).T[:ndim, :ndim]
        r = np.sqrt((pos ** 2).sum(1))
        m = (r >= r1) & (r < r2)
        if cos_open is not None:
            mu = (pos @ ax) / np.maximum(r, 1e-300)
            m &= mu >= cos_open
        if tan_yz is not None and ndim == 3:
            z = pos[:, 2]
            m &= ((z > 0.0)
                  & (np.abs(pos[:, 0]) <= z * tan_yz[0])
                  & (np.abs(pos[:, 1]) <= z * tan_yz[1]))
        if m.any():
            out_x.append(pos[m])
            out_r.append(r[m])
            out_i.append(np.where(m)[0])
    if not out_x:
        return (np.zeros((0, ndim)), np.zeros(0),
                np.zeros(0, dtype=np.int64))
    return (np.concatenate(out_x), np.concatenate(out_r),
            np.concatenate(out_i))


def write_cone(path: str, pos: np.ndarray, r: np.ndarray,
               idx: np.ndarray, aexp: float, vel=None,
               a_emit=None) -> None:
    """Cone dump (``output_cone`` reduced to an npz payload: positions,
    radii, source indices, velocities, per-particle emission aexp)."""
    payload = dict(pos=pos, r=r, idx=idx, aexp=aexp)
    if vel is not None:
        payload["vel"] = vel
    if a_emit is not None:
        payload["a_emit"] = a_emit
    np.savez_compressed(path, **payload)


def emit_coarse_step(sim, outdir: str = ".") -> Optional[str]:
    """Per-coarse-step lightcone emission (``amr_step.f90:177-178``
    ``output_cone``): the shell swept since the previous coarse step,
    observer at the box centre, narrow cone per &LIGHTCONE_PARAMS
    (full sky when the half-angles reach 90°).  Each particle carries
    its emission expansion factor interpolated at its comoving radius.
    Returns the written path (None when nothing was emitted)."""
    import os

    cosmo = sim.cosmo
    lc = sim.params.lightcone
    a_now = sim.aexp_now()
    a_prev = getattr(sim, "_cone_aexp_prev", None)
    sim._cone_aexp_prev = a_now
    if a_prev is None or sim.p is None or a_now <= a_prev:
        return None
    if a_now < 1.0 / (1.0 + float(lc.zmax_cone)):
        return None                    # beyond the cone's zmax
    r2, r1 = shell_radii(cosmo, a_prev, a_now)
    r1, r2 = r1 * sim.boxlen, r2 * sim.boxlen   # box → code units
    if r1 > r2:
        r1, r2 = r2, r1
    if r2 <= r1:
        return None
    act = np.asarray(sim.p.active)
    x = np.asarray(sim.p.x)[act]
    vpart = np.asarray(sim.p.v)[act]
    obs = np.full(sim.cfg.ndim, 0.5 * sim.boxlen)
    ty = np.radians(float(lc.thetay_cone))
    tz = np.radians(float(lc.thetaz_cone))
    half = ((ty, tz) if (ty < np.pi / 2 and tz < np.pi / 2
                         and sim.cfg.ndim == 3) else None)
    pos, r, idx = cone_selection(x, obs, r1, r2, boxlen=sim.boxlen,
                                 half_angles=half)
    if len(r) == 0:
        return None
    # emission epoch per particle: a at comoving distance r
    a_emit = np.asarray(cosmo.aexp_of_chi(r / sim.boxlen))
    os.makedirs(outdir, exist_ok=True)
    path = os.path.join(outdir, f"cone_{sim.nstep:05d}.npz")
    write_cone(path, pos, r, idx, a_now, vel=vpart[idx],
               a_emit=a_emit)
    return path
