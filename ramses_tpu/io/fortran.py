"""Fortran sequential-unformatted record I/O.

Every ``write(ilun) data`` of the reference produces
``<int32 nbytes> <payload> <int32 nbytes>``; the whole snapshot format
(``amr/output_amr.f90:268-316``, ``hydro/output_hydro.f90:54-65``) is a
concatenation of such records.  This module is the byte-level substrate for
:mod:`ramses_tpu.io.snapshot` and the restart reader.
"""

from __future__ import annotations

import struct
from typing import BinaryIO

import numpy as np

_MARK = struct.Struct("<i")


def write_record(f: BinaryIO, *arrays) -> None:
    """Write one record whose payload is the given arrays concatenated.

    Mixed payloads (e.g. ``write(ilun) noutput, iout, ifout``) pass several
    scalars/arrays; each is converted with its own dtype preserved.
    """
    parts = []
    for a in arrays:
        if isinstance(a, bytes):
            parts.append(a)
        else:
            parts.append(np.ascontiguousarray(a).tobytes())
    payload = b"".join(parts)
    f.write(_MARK.pack(len(payload)))
    f.write(payload)
    f.write(_MARK.pack(len(payload)))


def write_ints(f: BinaryIO, *vals, dtype=np.int32) -> None:
    write_record(f, np.asarray(vals, dtype=dtype))


def write_reals(f: BinaryIO, *vals) -> None:
    write_record(f, np.asarray(vals, dtype=np.float64))


def write_str(f: BinaryIO, s: str, width: int) -> None:
    """character(len=width) record, blank-padded (Fortran semantics)."""
    write_record(f, s.encode("ascii")[:width].ljust(width))


def read_record(f: BinaryIO) -> bytes:
    head = f.read(4)
    if len(head) < 4:
        raise EOFError("end of Fortran record stream")
    (n,) = _MARK.unpack(head)
    payload = f.read(n)
    (tail,) = _MARK.unpack(f.read(4))
    if tail != n:
        raise IOError(f"record marker mismatch: {n} != {tail}")
    return payload


def read_array(f: BinaryIO, dtype) -> np.ndarray:
    return np.frombuffer(read_record(f), dtype=dtype)


def read_ints(f: BinaryIO, dtype=np.int32) -> np.ndarray:
    return read_array(f, dtype)


def read_int(f: BinaryIO) -> int:
    return int(read_array(f, np.int32)[0])


def read_reals(f: BinaryIO) -> np.ndarray:
    return read_array(f, np.float64)


def read_str(f: BinaryIO) -> str:
    return read_record(f).decode("ascii").rstrip()


def skip_record(f: BinaryIO) -> int:
    """Skip one record without decoding; returns payload byte count."""
    (n,) = _MARK.unpack(f.read(4))
    f.seek(n + 4, 1)
    return n
