"""Particle-mesh tests: deposition, interpolation, orbits, cosmology."""

import jax.numpy as jnp
import numpy as np
import pytest

from ramses_tpu.pm import particles as pm
from ramses_tpu.pm.cosmology import Cosmology, friedman
from ramses_tpu.pm.coupling import PMSpec, pm_hydro_step, run_steps_pm
from ramses_tpu.poisson.coupling import GravitySpec



pytestmark = pytest.mark.smoke

def _pset(x, v=None, m=None, **kw):
    x = np.atleast_2d(np.asarray(x, np.float64))
    n = x.shape[0]
    v = np.zeros_like(x) if v is None else np.atleast_2d(v)
    m = np.ones(n) if m is None else np.asarray(m)
    return pm.ParticleSet.make(x, v, m, **kw)


@pytest.mark.parametrize("dep", [pm.deposit_cic, pm.deposit_ngp,
                                 pm.deposit_tsc])
def test_deposit_conserves_mass(dep):
    rng = np.random.default_rng(0)
    n, shape, dx = 100, (16, 16, 16), 1.0 / 16
    p = _pset(rng.uniform(0, 1, (n, 3)), m=rng.uniform(0.5, 2.0, n))
    rho = dep(p, shape, dx)
    vol = dx ** 3
    assert np.isclose(float(jnp.sum(rho)) * vol, float(jnp.sum(p.m)),
                      rtol=1e-12)


def test_cic_particle_at_cell_center():
    shape, dx = (8, 8), 1.0 / 8
    # cell center of cell (3, 5)
    p = _pset([[(3 + 0.5) * dx, (5 + 0.5) * dx]], m=[2.0])
    rho = pm.deposit_cic(p, shape, dx)
    assert np.isclose(float(rho[3, 5]), 2.0 / dx ** 2, rtol=1e-12)
    assert np.isclose(float(jnp.sum(jnp.abs(rho))), 2.0 / dx ** 2, rtol=1e-12)


def test_cic_deposit_gather_adjoint_constant_field():
    """Gathering a constant field returns the constant exactly."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.uniform(0, 1, (50, 3)))
    field = jnp.full((2, 8, 8, 8), 3.25)
    out = pm.gather_cic(field, x, 1.0 / 8)
    assert np.allclose(np.asarray(out), 3.25, rtol=1e-12)


def test_gather_linear_field_exact():
    """CIC interpolation is exact for a linear field (away from wrap)."""
    n = 16
    dx = 1.0 / n
    xs = (jnp.arange(n) + 0.5) * dx
    field = jnp.broadcast_to(xs[:, None, None], (n, n, n))[None]
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.uniform(0.2, 0.8, (40, 3)))
    out = pm.gather_cic(field, x, dx)
    assert np.allclose(np.asarray(out[:, 0]), np.asarray(x[:, 0]),
                       atol=1e-12)


def test_circular_orbit_in_point_mass_field():
    """KDK leapfrog in an analytic point-mass field holds a circular orbit."""
    n = 64
    dx = 1.0 / n
    c = (n // 2 + 0.5) * dx            # mass at a cell center
    r0, gm = 0.25, 1.0
    vcirc = np.sqrt(gm / r0)
    p = _pset([[c + r0, c, c]], v=[[0.0, vcirc, 0.0]], m=[1e-10])
    gspec = GravitySpec(enabled=True, gravity_type=2,
                        gravity_params=(gm, 0.0, c, c, c), boxlen=1.0)
    pspec = PMSpec(enabled=True, hydro=False, boxlen=1.0,
                   courant_factor=0.2)
    from ramses_tpu.grid.uniform import UniformGrid
    from ramses_tpu.grid.boundary import BoundarySpec
    from ramses_tpu.hydro.core import HydroStatic
    grid = UniformGrid(cfg=HydroStatic(ndim=3), shape=(n, n, n), dx=dx,
                       bc=BoundarySpec.periodic(3))
    f = jnp.zeros((3, n, n, n), jnp.float64)
    t = jnp.asarray(0.0, jnp.float64)
    period = 2 * np.pi * r0 / vcirc
    u, p2, f, t, dt_old, ndone = run_steps_pm(
        grid, gspec, pspec, None, p, f, t,
        jnp.asarray(period, jnp.float64), jnp.asarray(0.0, jnp.float64), 600)
    assert float(t) >= period * 0.999
    r = np.sqrt((float(p2.x[0, 0]) - c) ** 2 + (float(p2.x[0, 1]) - c) ** 2
                + (float(p2.x[0, 2]) - c) ** 2)
    # CIC-interpolated grid force: ~1% radius error after a full orbit
    assert abs(r - r0) / r0 < 0.02


def test_selfgravity_two_particle_attraction():
    """Two nearby massive particles must accelerate toward each other."""
    n = 32
    dx = 1.0 / n
    p = _pset([[0.4, 0.5, 0.5], [0.6, 0.5, 0.5]], m=[10.0, 10.0])
    gspec = GravitySpec(enabled=True)
    pspec = PMSpec(enabled=True, hydro=False, boxlen=1.0)
    from ramses_tpu.grid.uniform import UniformGrid
    from ramses_tpu.grid.boundary import BoundarySpec
    from ramses_tpu.hydro.core import HydroStatic
    grid = UniformGrid(cfg=HydroStatic(ndim=3), shape=(n, n, n), dx=dx,
                       bc=BoundarySpec.periodic(3))
    f = jnp.zeros((3, n, n, n), jnp.float64)
    u, p2, f2 = pm_hydro_step(grid, gspec, pspec, None, p, f,
                              jnp.asarray(0.01), jnp.asarray(0.0))
    assert float(p2.v[0, 0]) > 0.0   # left particle pushed right
    assert float(p2.v[1, 0]) < 0.0   # right particle pushed left
    assert np.isclose(float(p2.v[0, 0]), -float(p2.v[1, 0]), rtol=1e-10)


def test_friedman_eds_age():
    """Einstein-de Sitter: age = 2/3 H0^-1, a(tau): tau = 2 - 2/sqrt(a)."""
    a, h, tau, t, chi = friedman(1.0, 0.0, 0.0, 1e-3)
    assert np.isclose(-t[0], 2.0 / 3.0, rtol=1e-3)
    i = np.searchsorted(a, 0.25)
    assert np.isclose(tau[i], 2.0 - 2.0 / np.sqrt(a[i]), rtol=1e-3)


def test_cosmology_roundtrip_and_hexp():
    cosmo = Cosmology(omega_m=0.3, omega_l=0.7, omega_k=0.0, aexp_ini=1e-2)
    a = 0.5
    tau = cosmo.tau_of_aexp(a)
    assert np.isclose(float(cosmo.aexp_of_tau(tau)), a, rtol=1e-6)
    # hexp = dadtau/a = sqrt(a^3(Om + Ol a^3))/a at a
    expect = np.sqrt(a ** 3 * (0.3 + 0.7 * a ** 3)) / a
    assert np.isclose(float(cosmo.hexp_of_tau(tau)), expect, rtol=1e-4)


def test_particle_dt():
    p = _pset([[0.5, 0.5]], v=[[0.25, 0.1]])
    dt = pm.particle_dt(p, 1.0 / 32, 0.5)
    assert np.isclose(float(dt), 0.5 * (1.0 / 32) / 0.25, rtol=1e-12)


def test_driver_pm_integration():
    """Full driver run: hydro + self-gravity + particles via namelist."""
    from ramses_tpu.config import params_from_string
    from ramses_tpu.driver import Simulation

    nml = "\n".join([
        "&RUN_PARAMS", "hydro=.true.", "poisson=.true.", "pic=.true.", "/",
        "&AMR_PARAMS", "levelmin=3", "levelmax=3", "boxlen=1.0", "/",
        "&OUTPUT_PARAMS", "noutput=1", "tout=0.01", "/",
        "&INIT_PARAMS", "nregion=1", "region_type(1)='square'",
        "d_region=1.0", "p_region=1.0", "/",
    ])
    p = params_from_string(nml)
    rng = np.random.default_rng(0)
    parts = pm.ParticleSet.make(rng.uniform(0, 1, (32, 3)),
                                np.zeros((32, 3)), np.full(32, 0.01))
    sim = Simulation(p, dtype=jnp.float64, particles=parts)
    sim.evolve()
    assert sim.state.t >= 0.01 * (1 - 1e-9)
    assert float(jnp.max(jnp.abs(sim.state.p.v))) > 0.0  # particles kicked
    assert bool(jnp.all(jnp.isfinite(sim.state.u)))


def test_driver_cosmo_outputs_fire():
    """Cosmo run in negative conformal time must still fire aout dumps."""
    from ramses_tpu.config import params_from_string
    from ramses_tpu.driver import Simulation

    nml = "\n".join([
        "&RUN_PARAMS", "hydro=.true.", "poisson=.true.", "pic=.true.",
        "cosmo=.true.", "/",
        "&AMR_PARAMS", "levelmin=3", "levelmax=3", "boxlen=1.0", "/",
        "&OUTPUT_PARAMS", "aout=0.52,0.55", "/",
        "&INIT_PARAMS", "nregion=1", "region_type(1)='square'",
        "d_region=1.0", "p_region=1.0", "aexp_ini=0.5", "/",
        "&COSMO_PARAMS", "omega_m=1.0", "omega_l=0.0", "/",
    ])
    p = params_from_string(nml)
    rng = np.random.default_rng(1)
    parts = pm.ParticleSet.make(rng.uniform(0, 1, (16, 3)),
                                np.zeros((16, 3)), np.full(16, 0.05))
    sim = Simulation(p, dtype=jnp.float64, particles=parts)
    fired = []
    sim.on_output = lambda s, i: fired.append(i)
    sim.evolve()
    assert fired == [1, 2]
    aexp_end = float(sim.cosmo.aexp_of_tau(sim.state.t))
    assert np.isclose(aexp_end, 0.55, rtol=1e-3)
