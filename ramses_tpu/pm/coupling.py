"""Coupled particle + hydro + gravity stepper (uniform grid).

This is THE single-level stepper: gravity-only runs use it with particles
disabled, N-body-only runs with hydro disabled — one copy of the coupled
sequence (the reference likewise has one ``amr_step`` for every physics
combination).

Replicates the per-step operation order of ``amr/amr_step.f90`` for the
single-level case (SURVEY.md §3.2), with the reference's split-kick
leapfrog:

  1. ``rho_fine``: total density = gas + CIC(particles)   (:219-225)
  2. hydro gravity un-kick (-0.5 dt, old force)           (:246)
  3. Poisson solve -> phi -> f = -grad(phi)               (:250-266)
  4. ``synchro_fine``: particle kick v += f(x) 0.5*dt_old (:268-273)
     — completes the *previous* step's kick with the new force
  5. hydro kick +0.5 dt new force; Godunov sweep with the gravity
     predictor; final hydro kick +0.5 dt                  (:279,388,427)
  6. ``move_fine``: v += f(x) 0.5*dt_new then x += v dt   (:479-486)
  7. dt for the next step: min(hydro CFL, particle Courant,
     free-fall, cosmological 0.1/hexp)                    (pm/newdt_fine.f90)

Cosmology: integration runs in supercomoving conformal time; the Poisson
rhs factor becomes ``1.5*omega_m*aexp`` and aexp/hexp are interpolated
from the Friedmann tables each step (``amr/update_time.f90``).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ramses_tpu.grid import boundary as bmod
from ramses_tpu.grid.uniform import UniformGrid
from ramses_tpu.hydro import muscl
from ramses_tpu.hydro.timestep import compute_dt
from ramses_tpu.pm import particles as pmod
from ramses_tpu.pm.cosmology import Cosmology
from ramses_tpu.poisson.coupling import (GravitySpec, _all_periodic,
                                         _pad_force, gravity_field, kick)


def deposit_scheme_from_params(p) -> str:
    """Validated &PM_PARAMS deposit scheme (shared by the uniform and
    AMR particle paths so both read the namelist identically)."""
    dep = str((p.raw or {}).get("pm_params", {})
              .get("deposit", "cic")).strip("'\" ").lower()
    if dep not in ("ngp", "cic", "tsc"):
        raise ValueError(
            f"&PM_PARAMS deposit={dep!r}: expected ngp|cic|tsc")
    return dep


@dataclass(frozen=True)
class PMSpec:
    """Static particle-mesh configuration."""
    enabled: bool = False
    hydro: bool = True
    deposit: str = "cic"          # cic | ngp | tsc
    courant_factor: float = 0.5
    boxlen: float = 1.0
    cosmo: bool = False

    @classmethod
    def from_params(cls, p) -> "PMSpec":
        return cls(enabled=bool(p.run.pic), hydro=bool(p.run.hydro),
                   deposit=deposit_scheme_from_params(p),
                   courant_factor=float(p.hydro.courant_factor),
                   boxlen=float(p.amr.boxlen), cosmo=bool(p.run.cosmo))


def deposit(spec: PMSpec, p: pmod.ParticleSet, shape, dx: float):
    fn = {"cic": pmod.deposit_cic, "ngp": pmod.deposit_ngp,
          "tsc": pmod.deposit_tsc}[spec.deposit]
    return fn(p, shape, dx)


def gather(spec: PMSpec, field, x, dx: float):
    """Force interpolation with the SAME kernel as deposition — mismatched
    pairs produce particle self-forces (the reference ties both to
    ``interp_mode``, ``pm/move_fine.f90:255``)."""
    fn = {"cic": pmod.gather_cic, "ngp": pmod.gather_ngp,
          "tsc": pmod.gather_tsc}[spec.deposit]
    return fn(field, x, dx)


def total_density(spec: PMSpec, u, p: Optional[pmod.ParticleSet],
                  shape, dx: float):
    """``rho_fine``: gas density + particle deposition."""
    rho = u[0] if (spec.hydro and u is not None) else \
        jnp.zeros(shape, jnp.float64 if jax.config.jax_enable_x64
                  else jnp.float32)
    if spec.enabled and p is not None:
        rho = rho + deposit(spec, p, shape, dx)
    return rho


@partial(jax.jit, static_argnames=("grid", "gspec", "pspec"))
def pm_hydro_step(grid: UniformGrid, gspec: GravitySpec, pspec: PMSpec,
                  u, p: Optional[pmod.ParticleSet], f_old, dt, dt_old,
                  fourpi=None, rho=None):
    """One coupled step; returns (u, p, f_new).

    ``rho`` may pass in the already-deposited total density at x^n (the
    scan body computes it once for both dt and the step).
    """
    cfg = grid.cfg
    particles = pspec.enabled and p is not None
    # 1. total density at x^n
    if rho is None:
        rho = total_density(pspec, u, p, grid.shape, grid.dx)
    # 2-3. gravity update
    if pspec.hydro and gspec.enabled:
        u = kick(u, f_old, -0.5 * dt, cfg)
    f = (gravity_field(gspec, rho, grid.dx, fourpi) if gspec.enabled
         else jnp.zeros_like(f_old))
    # 4. complete previous particle kick with new force at x^n
    if particles:
        f_at_p = gather(pspec, f, p.x, grid.dx)
        p = pmod.kick(p, f_at_p, 0.5 * dt_old)
    # 5. hydro with gravity predictor
    if pspec.hydro:
        if gspec.enabled:
            u = kick(u, f, +0.5 * dt, cfg)
        up = bmod.pad(u, grid.bc, cfg, muscl.NGHOST, dx=grid.dx)
        mode = "wrap" if _all_periodic(grid.bc) else "edge"
        fp = _pad_force(f, cfg.ndim, mode)
        grav = [fp[d] for d in range(cfg.ndim)] if gspec.enabled else None
        flux, tmp = muscl.unsplit(up, grav, dt, (grid.dx,) * cfg.ndim,
                                  cfg)
        un = muscl.apply_fluxes(up, flux, cfg)
        if cfg.pressure_fix or cfg.nener:
            un = muscl.dual_energy_fix(up, un, tmp, dt,
                                       (grid.dx,) * cfg.ndim, cfg,
                                       hexp=0.0)
        u = bmod.unpad(un, cfg.ndim, muscl.NGHOST)
        if gspec.enabled:
            u = kick(u, f, +0.5 * dt, cfg)
    # 6. particle half-kick + drift
    if particles:
        p = pmod.kick(p, f_at_p, 0.5 * dt)
        p = pmod.drift(p, dt, pspec.boxlen)
    return u, p, f


def pm_compute_dt(grid: UniformGrid, gspec: GravitySpec, pspec: PMSpec,
                  u, p, f, hexp=None, fourpi=None, rho=None):
    """min(hydro CFL, particle Courant, free-fall, cosmo 0.1/hexp)."""
    cfg = grid.cfg
    dts = []
    if pspec.hydro:
        grav = [f[d] for d in range(cfg.ndim)] if gspec.enabled else None
        dts.append(compute_dt(u, grav, grid.dx, cfg))
    if pspec.enabled and p is not None:
        dts.append(pmod.particle_dt(p, grid.dx, pspec.courant_factor))
    if gspec.enabled:
        if rho is None:
            rho = total_density(pspec, u, p, grid.shape, grid.dx)
        fp = gspec.fourpi if fourpi is None else fourpi
        dts.append(pmod.freefall_dt(jnp.max(rho), pspec.courant_factor, fp))
    if not dts:
        # nothing constrains dt (e.g. cosmo-only run): expansion cap below,
        # else a fixed fallback
        dts.append(jnp.asarray(1e30))
    dt = dts[0]
    for d in dts[1:]:
        dt = jnp.minimum(dt, d)
    if hexp is not None:
        dt = jnp.minimum(dt, 0.1 / jnp.abs(hexp))
    return dt


@partial(jax.jit, static_argnames=("grid", "gspec", "pspec", "nsteps",
                                   "cosmo"))
def run_steps_pm(grid: UniformGrid, gspec: GravitySpec, pspec: PMSpec,
                 u, p, f, t, tend, dt_old, nsteps: int,
                 cosmo: Optional[Cosmology] = None):
    """Advance up to nsteps coupled steps on device.

    With ``cosmo``, ``t`` is supercomoving conformal time tau and aexp /
    hexp / the Poisson factor are table look-ups per step.
    """
    def body(carry, _):
        u, p, f, t, dt_old, ndone = carry
        if cosmo is not None:
            aexp = cosmo.aexp_of_tau(t)
            hexp = cosmo.hexp_of_tau(t)
            fourpi = 1.5 * cosmo.omega_m * aexp
        else:
            hexp, fourpi = None, None
        rho = total_density(pspec, u, p, grid.shape, grid.dx)
        dt = pm_compute_dt(grid, gspec, pspec, u, p, f, hexp, fourpi,
                           rho=rho)
        dt = jnp.minimum(dt, jnp.maximum(tend - t, 0.0))
        active = t < tend
        dt = jnp.where(active, dt, 0.0)
        un, pn, fn = pm_hydro_step(grid, gspec, pspec, u, p, f, dt, dt_old,
                                   fourpi, rho=rho)
        if u is not None:
            u = jnp.where(active, un, u)
        if p is not None:
            p = jax.tree_util.tree_map(
                lambda a, b: jnp.where(active, b, a), p, pn)
        f = jnp.where(active, fn, f)
        t = t + dt
        dt_old = jnp.where(active, dt, dt_old)
        ndone = ndone + jnp.where(active, 1, 0)
        return (u, p, f, t, dt_old, ndone), None

    (u, p, f, t, dt_old, ndone), _ = jax.lax.scan(
        body, (u, p, f, t, dt_old, jnp.array(0)), None, length=nsteps)
    return u, p, f, t, dt_old, ndone
