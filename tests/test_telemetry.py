"""Run-telemetry subsystem (ramses_tpu/telemetry/).

Pins the subsystem's two contracts:

  * instrumented runs get ONE JSONL record per coarse step carrying the
    full schema (REQUIRED_STEP_KEYS) — including through the fused
    ``step_chunk`` fast path, which must stay engaged (``verbose=True``
    used to silently drop to the per-step slow path);
  * un-instrumented runs pay ZERO overhead — no ``jax.device_get``,
    NullTimers (no label switches), the shared no-op NULL recorder.
"""

import json
import os
import subprocess
import sys
import time
import types

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench

from ramses_tpu.config import params_from_string
from ramses_tpu.telemetry import (NULL, REQUIRED_STEP_KEYS, NullTelemetry,
                                  Telemetry, TelemetrySpec)
from ramses_tpu.telemetry import heartbeat as hb_mod
from ramses_tpu.telemetry import screen as screen_mod
from ramses_tpu.utils.timers import NullTimers, Timers

pytestmark = pytest.mark.smoke

HERE = os.path.dirname(os.path.abspath(__file__))

SEDOV2D = """
&RUN_PARAMS
hydro=.true.
nstepmax={nstep}
ncontrol=1
/
&AMR_PARAMS
levelmin=4
levelmax=5
boxlen=1.0
/
&INIT_PARAMS
nregion=2
region_type(1)='square'
region_type(2)='point'
x_center=0.5,0.5
y_center=0.5,0.5
length_x=10.0,1.0
length_y=10.0,1.0
exp_region=10.0,10.0
d_region=1.0,0.0
p_region=1e-5,0.1
/
&OUTPUT_PARAMS
{output}
/
&HYDRO_PARAMS
gamma=1.4
courant_factor=0.8
/
&REFINE_PARAMS
err_grad_p=0.1
/
"""


def _amr_sim(tmp_path, nstep=6, telemetry=True):
    from ramses_tpu.amr.hierarchy import AmrSim
    out = (f"telemetry='{tmp_path}/run.jsonl'\ntelemetry_interval=1"
           if telemetry else "tend=1.0")
    p = params_from_string(SEDOV2D.format(nstep=nstep, output=out),
                           ndim=2)
    return AmrSim(p)


def _records(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


# ---------------------------------------------------------------------
# JSONL schema roundtrip
# ---------------------------------------------------------------------
@pytest.mark.slow          # ~14s; nightly tier on the 1-core box
def test_jsonl_schema_one_record_per_coarse_step(tmp_path):
    sim = _amr_sim(tmp_path, nstep=5)
    assert sim.telemetry.enabled
    assert isinstance(sim.timers, Timers) \
        and not isinstance(sim.timers, NullTimers)
    sim.evolve(1e9, nstepmax=5)
    sim.telemetry.close(sim, print_timers=False)
    recs = _records(tmp_path / "run.jsonl")
    assert recs[0]["kind"] == "run_header"
    assert recs[0]["schema_version"] == 1
    assert recs[0]["run_info"]["driver"] == "AmrSim"
    assert recs[-1]["kind"] == "run_footer"
    steps = [r for r in recs if r["kind"] == "step"]
    assert len(steps) == sim.nstep == 5
    assert [r["nstep"] for r in steps] == [1, 2, 3, 4, 5]
    for r in steps:
        missing = [k for k in REQUIRED_STEP_KEYS if k not in r]
        assert not missing, missing
        assert r["octs"], "per-level oct census must be present"
        assert r["steps"] == 1
    # phase wallclock must reach the records (timers are live)
    assert any(r["phases_s"] for r in steps)
    assert recs[-1]["records"] == 5
    # a second close is a no-op, not a duplicate footer
    sim.telemetry.close(sim, print_timers=False)
    assert len(_records(tmp_path / "run.jsonl")) == len(recs)


def test_telemetry_interval_coalesces(tmp_path):
    tel = Telemetry(TelemetrySpec(path=str(tmp_path / "i.jsonl"),
                                  interval=3))
    sim = types.SimpleNamespace(nstep=0, t=0.0, dt_old=1e-3)
    for i in range(7):
        tel.record_step(sim, dt=1e-3, wall_s=0.5, nstep=i + 1,
                        t=(i + 1) * 1e-3)
    tel.close(print_timers=False)
    steps = [r for r in _records(tmp_path / "i.jsonl")
             if r["kind"] == "step"]
    assert len(steps) == 2                 # 7 steps // interval 3
    assert [r["steps"] for r in steps] == [3, 3]
    # wallclock between emissions accumulates onto the emitted record
    assert steps[0]["wall_s"] == pytest.approx(1.5)


# ---------------------------------------------------------------------
# the chunked fast path must stay engaged under verbose/telemetry
# ---------------------------------------------------------------------
def test_chunked_fast_path_survives_instrumentation(tmp_path, capsys):
    sim = _amr_sim(tmp_path, nstep=8)
    sim.regrid_interval = 0                # frozen tree: chunk-eligible

    def boom(dt):
        raise AssertionError(
            "instrumentation forced the per-step slow path")

    sim.step_coarse = boom
    sim.evolve(1e9, nstepmax=8, verbose=True)
    sim.telemetry.close(sim, print_timers=False)
    steps = [r for r in _records(tmp_path / "run.jsonl")
             if r["kind"] == "step"]
    # per-step records reconstructed from the chunk's scan summary
    assert len(steps) == sim.nstep == 8
    assert all(r.get("chunked", 0) > 1 for r in steps)
    assert [r["nstep"] for r in steps] == list(range(1, 9))
    # strictly advancing time, positive dt — real per-step values, not
    # a smeared aggregate
    ts = [r["t"] for r in steps]
    assert all(b > a for a, b in zip(ts, ts[1:]))
    assert all(r["dt"] > 0 for r in steps)
    out = capsys.readouterr().out
    assert "chunk=" in out                 # verbose line from the sink


# ---------------------------------------------------------------------
# zero overhead when off
# ---------------------------------------------------------------------
def test_zero_overhead_when_off(tmp_path, monkeypatch):
    import jax

    sim = _amr_sim(tmp_path, nstep=16, telemetry=False)
    assert sim.telemetry is NULL
    assert isinstance(sim.timers, NullTimers)
    sim.regrid_interval = 0
    sim.evolve(1e9, nstepmax=4)            # warm the fused chunk
    calls = {"n": 0}
    real = jax.device_get

    def counted(x):
        calls["n"] += 1
        return real(x)

    monkeypatch.setattr(jax, "device_get", counted)
    sim.evolve(1e9, nstepmax=sim.nstep + 8)
    assert calls["n"] == 0, \
        "un-instrumented evolve must not add device fetches"


def test_null_telemetry_is_shared_noop():
    assert isinstance(NULL, NullTelemetry)
    assert NULL.enabled is False
    NULL.record_step(None, dt=1.0)
    NULL.record_chunk(None, [], [], 0, 0.0, nstep_end=3)
    NULL.record_event("x", a=1)
    NULL.warn("w")
    NULL.close(None, print_timers=False)   # all no-ops, no raises


# ---------------------------------------------------------------------
# timers: sync-mode attribution
# ---------------------------------------------------------------------
def test_timers_sync_attributes_drain_to_enqueuing_label(monkeypatch):
    from ramses_tpu.utils import timers as tmod

    clock = {"t": 0.0}
    monkeypatch.setattr(
        tmod, "time", types.SimpleNamespace(
            perf_counter=lambda: clock["t"]))

    def drain():                            # a 5s device drain
        clock["t"] += 5.0

    tm = tmod.Timers(sync=drain)
    tm.timer("hydro")
    clock["t"] += 1.0                       # 1s of host work under hydro
    tm.timer("regrid")                      # drain runs BEFORE the switch
    clock["t"] += 2.0
    tm.stop()
    # the 5s drain is work hydro ENQUEUED: it must land on hydro, not
    # on whichever section happens to block next
    assert tm.acc["hydro"] == pytest.approx(6.0)
    assert tm.acc["regrid"] == pytest.approx(7.0)


def test_timers_snapshot_includes_active_label(monkeypatch):
    from ramses_tpu.utils import timers as tmod

    clock = {"t": 0.0}
    monkeypatch.setattr(
        tmod, "time", types.SimpleNamespace(
            perf_counter=lambda: clock["t"]))
    tm = tmod.Timers()
    tm.timer("a")
    clock["t"] += 2.0
    snap = tm.snapshot()                    # no label switch
    assert snap["a"] == pytest.approx(2.0)
    assert tm._label == "a" and tm.acc == {}


# ---------------------------------------------------------------------
# screen sink
# ---------------------------------------------------------------------
class _FakeTree:
    def noct(self, l):
        return {4: 64, 5: 120}[l]


def test_control_block_golden():
    sim = types.SimpleNamespace(
        nstep=12, t=0.5, dt_old=1e-3, tree=_FakeTree(),
        levels=lambda: [4, 5], balance_stats=None)
    line = screen_mod.control_block(sim, max_rss=100.0, dev_mb=50.0,
                                    audit=False)
    assert line == (" Main step=     12 t= 5.000000e-01 dt= 1.0000e-03 "
                    "mem=   100.0M/    50.0M octs={4: 64, 5: 120}")


def test_step_line_chunk_and_extra():
    sim = types.SimpleNamespace(nstep=7, t=0.25)
    line = screen_mod.step_line(sim, dt=2e-3, chunk=8, extra="x=1")
    assert line == "step      7  t=2.500000e-01 dt=2.000e-03 chunk=8 x=1"


# ---------------------------------------------------------------------
# warning capture
# ---------------------------------------------------------------------
def test_warning_capture_folds_into_records(tmp_path):
    import warnings

    prev = warnings.showwarning
    tel = Telemetry(TelemetrySpec(path=str(tmp_path / "w.jsonl")))
    tel.install_warning_capture()
    try:
        warnings.warn("arrays REPLICATE on every device")
        sim = types.SimpleNamespace(nstep=1, t=0.0)
        tel.record_step(sim, dt=1e-3)
    finally:
        tel.close(print_timers=False)
    assert warnings.showwarning is prev    # close() restores the hook
    steps = [r for r in _records(tmp_path / "w.jsonl")
             if r["kind"] == "step"]
    assert any("REPLICATE" in w["msg"]
               for r in steps for w in r.get("warnings", []))


# ---------------------------------------------------------------------
# report tool
# ---------------------------------------------------------------------
def test_report_renders_markdown(tmp_path):
    src = tmp_path / "r.jsonl"
    with open(src, "w") as f:
        f.write(json.dumps({"kind": "run_header", "schema_version": 1,
                            "telemetry_interval": 1,
                            "run_info": {"driver": "AmrSim",
                                         "ndim": 2}}) + "\n")
        f.write(json.dumps({"kind": "step", "nstep": 1, "t": 1e-3,
                            "dt": 1e-3, "steps": 1, "wall_s": 0.5,
                            "phases_s": {"hydro": 0.4},
                            "cell_updates": 1000,
                            "mus_per_cell_update": 500.0,
                            "octs": {"4": 64}, "rss_mb": 10.0,
                            "device_mb": 1.0, "rss_hwm_mb": 10.0,
                            "device_hwm_mb": 1.0, "recompiles": 2,
                            "recompiles_total": 2}) + "\n")
        f.write(json.dumps({"kind": "run_footer", "wall_s": 1.0,
                            "records": 1, "recompiles_total": 2,
                            "warnings_total": 0}) + "\n")
    out = tmp_path / "r.md"
    r = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(HERE), "tools",
                      "telemetry_report.py"),
         str(src), "-o", str(out)],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    md = out.read_text()
    assert "# Telemetry report" in md
    assert "| 1 | 1.000000e-03 |" in md    # the step row
    assert "hydro" in md                   # phase table


# ---------------------------------------------------------------------
# heartbeats (bench sidecar)
# ---------------------------------------------------------------------
def test_heartbeat_roundtrip(tmp_path):
    path = str(tmp_path / "hb.jsonl")
    hb = hb_mod.Heartbeat(path)
    hb.mark("start", sub="amr")
    hb.mark("warm")
    phases = hb_mod.read_phases(path)
    assert [p["phase"] for p in phases] == ["start", "warm"]
    assert phases[0]["sub"] == "amr"
    assert hb_mod.last_phase(path)["phase"] == "warm"
    # no-op heartbeat (unset env) never touches the filesystem
    hb_mod.Heartbeat("").mark("x")


def test_bench_timeout_reports_phase(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "_hb_path",
                        lambda name: str(tmp_path / f"hb_{name}.jsonl"))
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)

    def fake_run(cmd, **kw):
        # the child got as far as warmup, then hung
        with open(kw["env"]["BENCH_HEARTBEAT_PATH"], "w") as f:
            f.write(json.dumps({"phase": "start", "t_s": 0.0}) + "\n")
            f.write(json.dumps({"phase": "import jax",
                                "t_s": 1.1}) + "\n")
            f.write(json.dumps({"phase": "warm", "t_s": 3.2}) + "\n")
        raise subprocess.TimeoutExpired(cmd, kw.get("timeout", 0))

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    r = bench.run_sub("uniform", deadline=time.monotonic() + 1000.0)
    assert "timed out" in r["error"]
    assert r["phase_at_timeout"] == "warm"
    assert r["phase_t_s"] == pytest.approx(3.2)
    assert [p["phase"] for p in r["heartbeat"]][-1] == "warm"


def test_bench_timeout_without_heartbeat(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "_hb_path",
                        lambda name: str(tmp_path / "never_written.jsonl"))
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)

    def fake_run(cmd, **kw):
        raise subprocess.TimeoutExpired(cmd, kw.get("timeout", 0))

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    r = bench.run_sub("mg", deadline=time.monotonic() + 1000.0)
    assert "no heartbeat" in r["phase_at_timeout"]


def test_run_header_carries_halo_fields(tmp_path):
    """Every run_header names the resolved halo backend and the traced
    per-step halo traffic (zero on the GSPMD path, populated once the
    explicit slab pipeline traces)."""
    sim = _amr_sim(tmp_path, nstep=2)
    sim.evolve(1e9, nstepmax=2)
    sim.telemetry.close(sim, print_timers=False)
    recs = _records(tmp_path / "run.jsonl")
    info = recs[0]["run_info"]
    assert info["halo_backend"] == "ppermute"      # CPU: auto -> ppermute
    for k in ("halo_bytes", "halo_exchanges", "halo_overlap_frac"):
        assert k in info
    # timers are live in this driver -> per-step overlap fraction lands
    steps = [r for r in recs if r["kind"] == "step"]
    assert all("halo_overlap_frac" in r for r in steps if r["phases_s"])
