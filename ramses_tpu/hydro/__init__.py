from ramses_tpu.hydro.core import HydroStatic  # noqa: F401
