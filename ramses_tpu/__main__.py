"""Command-line entry point: ``python -m ramses_tpu run.nml``.

The ``program ramses`` equivalent (``amr/ramses.f90:1-15``): parse the
namelist given as first argument, run the adaptive loop, write snapshots
at the configured output times.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ramses_tpu",
        description="TPU-native AMR astrophysics framework")
    ap.add_argument("namelist", help="Fortran-namelist runtime config")
    ap.add_argument("--ndim", type=int, default=3,
                    help="spatial dimensions (compile-time in the reference)")
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "float64", "bfloat16"])
    ap.add_argument("--amr", action="store_true",
                    help="force the multi-level AMR driver even when "
                         "levelmin==levelmax")
    ap.add_argument("--solver", default=None,
                    choices=["hydro", "mhd", "rhd"],
                    help="solver family (the reference's SOLVER= make "
                         "variable); default: mhd when &INIT_PARAMS sets "
                         "A/B/C_region, hydro otherwise")
    ap.add_argument("--patch", default=None,
                    help="user plug-in file overriding condinit/gravana/"
                         "boundana/source hooks (the runtime equivalent "
                         "of the reference's compile-time PATCH= VPATH "
                         "shadowing, bin/Makefile:153-160)")
    ap.add_argument("--verbose", "-v", action="store_true")
    ap.add_argument("--walltime", type=float, default=None,
                    help="wall-clock budget in hours; the watchdog dumps "
                         "a restartable snapshot and stops before it "
                         "expires (amr/adaptive_loop.f90:216-226)")
    ap.add_argument("--auto-resume", action="store_true",
                    help="resume from the newest manifest-valid "
                         "checkpoint in the output dir (same as "
                         "&RUN_PARAMS auto_resume=.true.)")
    ap.add_argument("--max-attempts", type=int, default=1,
                    help="supervised retry-with-resume: on an "
                         "interrupted or failed run, rebuild from the "
                         "latest valid checkpoint and continue, up to "
                         "this many attempts (exponential backoff)")
    args = ap.parse_args(argv)

    import jax.numpy as jnp

    from ramses_tpu.config import load_params

    dtype = getattr(jnp, args.dtype)
    params = load_params(args.namelist, ndim=args.ndim)

    if params.run.debug_nan:
        # jit-level NaN trap (SURVEY.md §5.2): every compiled program
        # re-checks outputs and raises AT the producing op — the
        # runtime analogue of the reference's FPE-trapping debug build
        import jax
        jax.config.update("jax_debug_nans", True)

    if args.patch:
        from ramses_tpu import patch
        patch.install(args.patch, verbose=True)

    solver = args.solver
    if solver is None:
        solver = ("mhd" if any(params.init.A_region) or
                  any(params.init.B_region) or any(params.init.C_region)
                  else "hydro")

    def make_guard(sim):
        from ramses_tpu.utils.ops import OpsGuard
        return OpsGuard(sim, params.output.output_dir,
                        walltime_s=(args.walltime * 3600.0
                                    if args.walltime else None))

    # Supervised retry-with-resume (ramses_tpu/resilience): every branch
    # is phrased as build(restart_dir)/drive(sim) and routed through the
    # supervisor, which resolves nrestart/auto_resume on attempt 1 and
    # rebuilds from the newest manifest-valid checkpoint on later ones.
    if args.auto_resume:
        params.run.auto_resume = True
    supervised = (args.max_attempts > 1 or params.run.auto_resume
                  or params.run.nrestart == -1)
    attempts = max(2, args.max_attempts) if supervised else 1

    def launch(build, drive, tend=None):
        from ramses_tpu.resilience import supervisor as rsup
        return rsup.supervise(build, drive, params,
                              base_dir=params.output.output_dir,
                              max_attempts=attempts, tend=tend)

    def drive_amr(tend):
        def drive(sim):
            guard = make_guard(sim)
            guard.run_guarded(lambda: sim.evolve(
                tend, nstepmax=params.run.nstepmax,
                verbose=args.verbose, guard=guard))
        return drive

    if solver == "rhd":
        if args.amr or params.amr.levelmax > params.amr.levelmin:
            from ramses_tpu.rhd.amr import RhdAmrSim
            tend = (params.output.tout[-1] if params.output.tout
                    else params.output.tend)
            sim = launch(
                lambda restart: (
                    RhdAmrSim.from_snapshot(params, restart, dtype=dtype)
                    if restart else RhdAmrSim(params, dtype=dtype)),
                drive_amr(tend), tend=tend)
            print(f"rhd-amr t={sim.t:.5e} nstep={sim.nstep} "
                  f"lor_max={sim.max_lorentz():.3f} "
                  f"octs={[sim.tree.noct(l) for l in sim.levels()]}")
            sim.dump(1, params.output.output_dir,
                     namelist_path=args.namelist)
        else:
            from ramses_tpu.rhd.driver import RhdSimulation

            def drive(sim):
                guard = make_guard(sim)
                guard.run_guarded(lambda: sim.evolve(
                    nstepmax=params.run.nstepmax, verbose=args.verbose,
                    guard=guard))

            sim = launch(
                lambda restart: (
                    RhdSimulation.from_snapshot(params, restart,
                                                dtype=dtype)
                    if restart else RhdSimulation(params, dtype=dtype)),
                drive)
            sim.dump(1, params.output.output_dir,
                     namelist_path=args.namelist)
    elif solver == "mhd":
        if args.amr or params.amr.levelmax > params.amr.levelmin:
            from ramses_tpu.mhd.amr import MhdAmrSim
            tend = (params.output.tout[-1] if params.output.tout
                    else params.output.tend)
            sim = launch(
                lambda restart: (
                    MhdAmrSim.from_snapshot(params, restart, dtype=dtype)
                    if restart else MhdAmrSim(params, dtype=dtype)),
                drive_amr(tend), tend=tend)
            print(f"mhd-amr t={sim.t:.5e} nstep={sim.nstep} "
                  f"max|divB|/max|B|*dx={sim.max_divb():.3e}")
            sim.dump(1, params.output.output_dir,
                     namelist_path=args.namelist)
        else:
            from ramses_tpu.mhd.driver import MhdSimulation

            def drive(sim):
                guard = make_guard(sim)
                guard.run_guarded(lambda: sim.evolve(
                    nstepmax=params.run.nstepmax, verbose=args.verbose,
                    guard=guard))

            sim = launch(
                lambda restart: (
                    MhdSimulation.from_snapshot(params, restart,
                                                dtype=dtype)
                    if restart else MhdSimulation(params, dtype=dtype)),
                drive)
            sim.dump(1, params.output.output_dir,
                     namelist_path=args.namelist)
    elif args.amr or params.amr.levelmax > params.amr.levelmin:
        from ramses_tpu.amr.hierarchy import AmrSim

        def build(restart):
            if restart:
                return AmrSim.from_snapshot(params, restart, dtype=dtype)
            particles = None
            dense = None
            if (params.run.cosmo and params.init.initfile
                    and params.init.filetype in ("grafic", "gadget")):
                from ramses_tpu.driver import load_cosmo_ics
                from ramses_tpu.hydro.core import HydroStatic
                from ramses_tpu.pm.cosmology import Cosmology
                cosmo = Cosmology.from_params(params)
                n = 2 ** params.amr.levelmin
                particles, dense = load_cosmo_ics(
                    params, cosmo, HydroStatic.from_params(params),
                    (n,) * params.ndim)
            return AmrSim(params, dtype=dtype, particles=particles,
                          init_dense_u=dense)

        def amr_tend(sim):
            if sim.cosmo is not None and params.output.aout:
                return float(sim.cosmo.tau_of_aexp(
                    min(params.output.aout[-1], 1.0)))
            return (params.output.tout[-1] if params.output.tout
                    else params.output.tend)

        def drive(sim):
            guard = make_guard(sim)
            guard.run_guarded(lambda: sim.evolve(
                amr_tend(sim), nstepmax=params.run.nstepmax,
                verbose=args.verbose, guard=guard))

        sim = launch(build, drive)
        if sim.cosmo is not None:
            print(f"cosmo-amr aexp={sim.aexp_now():.4f} nstep={sim.nstep} "
                  f"octs={[sim.tree.noct(l) for l in sim.levels()]}")
        sim.dump(1, params.output.output_dir, namelist_path=args.namelist)
    else:
        from ramses_tpu.driver import Simulation

        def build(restart):
            sim = (Simulation.from_snapshot(params, restart, dtype=dtype)
                   if restart else Simulation(params, dtype=dtype))
            sim.on_output = lambda s, i: s.dump(
                i, namelist_path=args.namelist)
            return sim

        def drive(sim):
            guard = make_guard(sim)
            guard.run_guarded(lambda: sim.evolve(verbose=args.verbose,
                                                 guard=guard))

        sim = launch(build, drive)
    # run-footer + output_timer breakdown (telemetry also closes via
    # atexit, but a clean exit should flush before the interpreter
    # teardown races the JSONL file handle)
    tel = getattr(sim, "telemetry", None)
    if tel is not None:
        tel.close(sim)
    return 0


if __name__ == "__main__":
    sys.exit(main())
