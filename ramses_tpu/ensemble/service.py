"""Run service: drain the job queue under supervised execution.

``serve(queue_dir)`` is the worker loop: reclaim stale records, plan
which queued jobs to claim next (cost-aware gang scheduling by
default — :func:`ramses_tpu.ensemble.queue.plan_gang` — with blind
FIFO as the fallback knob), and run them through the batched
:class:`~ramses_tpu.ensemble.batch.EnsembleEngine`.

A gang of small jobs is bin-packed onto disjoint submesh slices of the
local device mesh (each job's :class:`~ramses_tpu.ensemble.meshplan.
MeshPlan` shards its member axis over its slice) and driven
concurrently by the interleaved chunk loop in :func:`run_gang` —
every job's fused windows are dispatched before any host thread blocks
on results, so all submeshes compute at once.  A mesh-wide job (or a
calibrate) drains the gang and runs alone through the fully
supervised :func:`run_job` path (auto-resume from the newest
manifest-valid checkpoint, hang kill-and-requeue).

Every job defaults its persistent compile cache to the queue's shared
``<queue_dir>/compile_cache`` dir (``&ENSEMBLE_PARAMS
shared_compile_cache``), so fleet workers warm-start each other: the
second worker to claim a known config compiles nothing.
"""

from __future__ import annotations

import json
import os
import re
import signal
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ramses_tpu.ensemble import breaker as bkr
from ramses_tpu.ensemble import queue as jq
from ramses_tpu.resilience.diskguard import DiskGuard, guarded_save
from ramses_tpu.resilience.watchdog import HangDetected

#: jax.config keys the serve loop snapshots on entry and restores on
#: exit: defaulting the shared compile cache must not leak persistent-
#: cache config into whatever the process does after serving
_JAX_CACHE_KEYS = ("jax_compilation_cache_dir",
                   "jax_persistent_cache_min_compile_time_secs",
                   "jax_persistent_cache_min_entry_size_bytes",
                   "jax_persistent_cache_enable_xla_caches")


class DrainRequested(Exception):
    """Raised out of a job's chunk beat after a drain request
    (SIGTERM): the in-flight chunk finished and a checkpoint was
    attempted, so the serve loop requeues the job with
    ``stage="drain"`` (attempt refunded) and exits cleanly — the next
    worker resumes from the drain checkpoint."""


#: process-wide drain latch — SIGTERM's handler only sets an event, so
#: the signal is safe to take mid-chunk; the beat acts on it at the
#: next chunk boundary
_DRAIN = threading.Event()


def request_drain() -> None:
    """Ask every serve loop in this process to graceful-drain: finish
    the current chunk, checkpoint, requeue held jobs with
    ``stage="drain"``, exit.  The public API for embedders/tests;
    SIGTERM routes here when :func:`serve` runs on the main thread."""
    _DRAIN.set()


def drain_requested() -> bool:
    return _DRAIN.is_set()


def _backoff_knobs() -> Tuple[float, float]:
    """Requeue-backoff (base, cap) seconds — env-configured per worker
    (``RAMSES_QUEUE_BACKOFF_S`` / ``RAMSES_QUEUE_BACKOFF_CAP_S``);
    base 0 disables the eligibility gate."""
    def _f(name, dflt):
        try:
            raw = os.environ.get(name)
            return float(raw) if raw not in (None, "") else dflt
        except (TypeError, ValueError):
            return dflt
    return _f("RAMSES_QUEUE_BACKOFF_S", 1.0), \
        _f("RAMSES_QUEUE_BACKOFF_CAP_S", 60.0)


def _job_setup(queue_dir: str, job: "jq.Job", log=print):
    """Shared per-job setup for both the supervised solo path and the
    gang driver: materialize the namelist, default the shared compile
    cache, arm auto-resume, scrub rotten checkpoints.  Returns
    ``(params, rdir, dtype)``."""
    import jax.numpy as jnp

    from ramses_tpu.config import params_from_string
    from ramses_tpu.platform import setup_compile_cache
    from ramses_tpu.resilience import scrub_checkpoints

    rec = job.record
    rdir = jq.results_dir(queue_dir, job.id)
    os.makedirs(rdir, exist_ok=True)
    nml_path = os.path.join(rdir, "run.nml")
    with open(nml_path, "w") as f:
        f.write(rec["namelist"])
    params = params_from_string(rec["namelist"],
                                ndim=int(rec.get("ndim", 3)))
    # persistent compile cache before the first trace: a fleet worker
    # re-claiming a known namelist cold-starts in O(load), not
    # O(compile).  Default: the queue's shared dir, so workers warm-
    # start EACH OTHER; an explicit &RUN_PARAMS compile_cache_dir or
    # RAMSES_COMPILE_CACHE env still wins, and
    # &ENSEMBLE_PARAMS shared_compile_cache=.false. opts out.
    if (not (params.run.compile_cache_dir or "").strip()
            and not os.environ.get("RAMSES_COMPILE_CACHE", "").strip()
            and params.ensemble.shared_compile_cache):
        params.run.compile_cache_dir = os.path.join(queue_dir,
                                                    "compile_cache")
    setup_compile_cache(params)
    params.output.output_dir = rdir
    if not params.output.telemetry:
        params.output.telemetry = os.path.join(rdir, "telemetry.jsonl")
    # a re-claimed job (stale worker) must continue from the dead
    # worker's last checkpoint, so the restart resolution picks the
    # newest manifest-valid dir instead of starting fresh
    params.run.auto_resume = True
    # checkpoints can rot between beats (torn shard, truncated file on
    # a dying node): quarantine them NOW so the auto-resume scan below
    # never loops over a dir that validates at scan time but fails at
    # restore time
    scrub_checkpoints(rdir, log=log)
    dtype = getattr(jnp, rec.get("dtype") or "float32")
    return params, rdir, dtype


def _bind_trace(eng, rec: Dict[str, Any]) -> None:
    """Correlate the engine's artifacts with the job's trace: the
    submit-time ``trace_id`` (plus job/worker ids) lands in every
    telemetry record (:meth:`Telemetry.bind`) and every checkpoint
    manifest meta (``EnsembleEngine.trace_meta``) — one id joins
    submit -> claim -> telemetry -> failure_log -> manifest however
    many workers the job bounces through."""
    fields = {"trace_id": str(rec.get("trace_id") or ""),
              "job": str(rec.get("id") or ""),
              "worker": str(rec.get("worker") or "")}
    eng.trace_meta = {k: v for k, v in fields.items() if v}
    # the claim's fencing token rides into telemetry records and
    # checkpoint manifest meta: any artifact a fenced-out zombie still
    # managed to write is attributable (and dismissible) by generation
    fence = int(rec.get("fence", 0) or 0)
    if fence:
        eng.trace_meta["fence"] = fence
    eng.telemetry.bind(**eng.trace_meta)


def _job_result(eng, rdir: str, params, rec: Dict[str, Any],
                snap: str, cache0: Dict[str, int],
                log=print, gang_info: Optional[Dict[str, Any]] = None
                ) -> Dict[str, Any]:
    """The result dict recorded on ``done`` — shared by the solo and
    gang paths.  ``cache0`` is the ``compile_cache_stats()`` snapshot
    taken before the job started; the recorded hit/miss counts are the
    deltas this job (or its gang) produced.  Must run before the job's
    telemetry closes: the summary is also emitted as a ``job_summary``
    event so the packing economics (queue wait, gang busy_frac,
    scenarios/device/s) are tailable without opening the queue record."""
    from ramses_tpu.platform import compile_cache_stats

    stats = compile_cache_stats()
    result = {"results_dir": rdir, "snapshot": snap,
              "telemetry": params.output.telemetry,
              "nmember": eng.nmember, "ngroup": len(eng.groups),
              "t_min": eng.t, "nstep_max": eng.nstep,
              "cell_updates": eng.cell_updates,
              "compile_cache_hits":
                  int(stats["hits"]) - int(cache0.get("hits", 0)),
              "compile_cache_misses":
                  int(stats["misses"]) - int(cache0.get("misses", 0)),
              "packing": eng.run_info().get("packing")}
    sub = float(rec.get("submitted_unix") or 0.0)
    claimed = float(rec.get("claimed_unix") or 0.0)
    if sub and claimed:
        result["queue_wait_s"] = round(max(0.0, claimed - sub), 3)
    if eng.wall_s > 0.0:
        result["scenarios_per_device_s"] = round(
            eng.nmember / eng.wall_s / eng.plan.n_devices, 4)
    if eng.quarantined:
        # partial completion: quarantined members are a property of the
        # job's *result*, not a worker failure — the job lands in
        # done/ with the census attached and never burns another queue
        # attempt on behalf of its healthy members
        result["partial"] = True
        result["failed_members"] = [
            {"member": int(k), **info}
            for k, info in sorted(eng.quarantined.items())]
        log(f"serve: {rec.get('id', '?')} partial completion — "
            f"{eng.quarantined_count}/{eng.nmember} members "
            f"quarantined")
    summary = {k: result[k] for k in
               ("queue_wait_s", "scenarios_per_device_s",
                "compile_cache_hits", "compile_cache_misses",
                "nmember", "cell_updates") if k in result}
    if gang_info:
        result["gang"] = gang_info
        summary["busy_frac"] = gang_info.get("busy_frac")
        summary["gang_jobs"] = gang_info.get("jobs")
    if eng.quarantined:
        summary["quarantined"] = eng.quarantined_count
    try:
        eng.telemetry.record_event("job_summary", **summary)
    except Exception:           # noqa: BLE001 — reporting only
        pass
    return result


def run_job(queue_dir: str, job: "jq.Job", max_attempts: int = 2,
            verbose: bool = False, log=print,
            device_ids: Optional[Sequence[int]] = None,
            plan=None) -> Dict[str, Any]:
    """Execute one claimed job; returns the result dict recorded on
    ``done``.  Raises on failure (caller moves the record).

    ``device_ids`` is the submesh slice the scheduler assigned (None =
    every local device); ``plan`` overrides the automatic
    :func:`~ramses_tpu.ensemble.meshplan.plan_for` packing choice."""
    from ramses_tpu.ensemble.batch import EnsembleEngine, EnsembleSpec
    from ramses_tpu.ensemble.meshplan import plan_for
    from ramses_tpu.platform import compile_cache_stats
    from ramses_tpu.resilience import supervisor as rsup

    rec = job.record
    cache0 = compile_cache_stats()
    params, rdir, dtype = _job_setup(queue_dir, job, log=log)
    if jq.job_kind(rec) == "calibrate" or params.calibration.calibrate:
        # calibrate-kind job: gradient-descent calibration through the
        # differentiable rollout (ramses_tpu/diff) — same artifact shape
        # (results dir + telemetry JSONL + resumable output_NNNNN
        # checkpoints), heartbeating the claim once per optimizer
        # iteration instead of per fused window
        from ramses_tpu.diff.calibrate import run_calibration_job

        result = run_calibration_job(
            params, dtype=dtype, base_dir=rdir, log=log,
            on_iter=lambda it, loss: jq.heartbeat(job))
        result["results_dir"] = rdir
        result["telemetry"] = params.output.telemetry
        stats = compile_cache_stats()
        result["compile_cache_hits"] = (int(stats["hits"])
                                        - int(cache0["hits"]))
        result["compile_cache_misses"] = (int(stats["misses"])
                                          - int(cache0["misses"]))
        return result
    spec = EnsembleSpec.from_params(params, sweeps=rec.get("sweeps"),
                                    solver=rec.get("solver", ""))
    if plan is None:
        plan = plan_for(params, spec.nmember, device_ids=device_ids,
                        solver=spec.solver)

    def build(restart):
        if restart:
            eng = EnsembleEngine.from_checkpoint(spec, restart,
                                                 dtype=dtype,
                                                 plan=plan)
        else:
            eng = EnsembleEngine(spec, dtype=dtype, plan=plan)
        _bind_trace(eng, rec)
        return eng

    from ramses_tpu.obs.profile import ProfileRequestWatcher
    watcher = ProfileRequestWatcher(rdir, log=log)

    dguard = DiskGuard.from_params(params, rdir, log=log)

    def drive(eng):
        from ramses_tpu.resilience.checkpoint import rotate_checkpoints

        def beat(e):
            # worker liveness + resumability advance together: every
            # fused window refreshes the fenced claim heartbeat and
            # lands a manifest-valid checkpoint (keep the newest two).
            # A reclaimed zombie dies HERE — heartbeat() raises
            # FenceLost, which escalates straight out of supervise.
            jq.heartbeat(job)

            def _save():
                e.save(rdir)
                rotate_checkpoints(rdir, keep=2)
            # disk-pressure degradation: below the soft watermark (or
            # after an injected/real ENOSPC) the checkpoint is shed and
            # the run keeps stepping — resumability gets coarser, the
            # worker survives
            guarded_save(_save, dguard, telemetry=e.telemetry, log=log,
                         where="chunk-beat")
            if drain_requested() and not e.run_complete():
                raise DrainRequested(
                    f"job {job.id}: worker draining (SIGTERM)")
            # on-demand profiling (ramses_tpu/obs/profile): the chunk
            # boundary is the one point with no fused window in flight
            watcher.poll(telemetry=e.telemetry)
        eng.run(verbose=verbose, on_chunk=beat)

    # hang_retries=0: a deadline-expired chunk escapes immediately so
    # the serve loop can kill-and-requeue with stage="hang" instead of
    # retrying inside a worker the queue already believes is live;
    # escalate: fence loss and drain are serve-loop control flow, not
    # run failures — they must never burn a supervised retry
    try:
        eng = rsup.supervise(build, drive, params, base_dir=rdir,
                             max_attempts=max_attempts, log=log,
                             hang_retries=0,
                             escalate=(jq.FenceLost, DrainRequested))
    finally:
        # never leave a device trace open across attempts/errors —
        # jax.profiler allows one active trace per process
        watcher.stop()
    snap = eng.save(rdir)
    eng.telemetry.record_event("ensemble_done", nmember=eng.nmember,
                               ngroup=len(eng.groups), t_min=eng.t,
                               nstep_max=eng.nstep, snapshot=snap,
                               quarantined=eng.quarantined_count)
    if not eng.run_complete():
        eng.telemetry.close(eng, print_timers=False)
        raise RuntimeError(
            f"job {job.id}: incomplete after {max_attempts} attempts "
            f"(t_min={eng.t:.6g} nstep_max={eng.nstep})")
    result = _job_result(eng, rdir, params, rec, snap, cache0, log=log)
    eng.telemetry.close(eng, print_timers=False)
    return result


def _dispose(job: "jq.Job", err: BaseException, counts: Dict[str, int],
             max_attempts: int, telemetry, log, stage: str = "requeue"
             ) -> None:
    """Requeue-or-fail one errored job, mirroring the serve loop's
    attempt accounting.  Requeues carry the jittered-exponential
    backoff gate (:func:`_backoff_knobs`) so a crash-looping job can't
    thundering-herd the fleet's claim scans.  A :class:`FenceLost`
    raised by the disposal itself means the record was reclaimed out
    from under this worker mid-error — the job is simply abandoned
    (its new owner carries it) and no count is taken."""
    text = "".join(traceback.format_exception_only(type(err),
                                                   err)).strip()
    log(f"serve: {job.id} "
        f"{'hang' if stage == 'hang' else 'failed'}: {err!r}")
    base_s, cap_s = _backoff_knobs()
    try:
        if int(job.record.get("attempts", 0)) < max_attempts:
            jq.requeue(job, error=text, telemetry=telemetry,
                       stage=stage, backoff_base_s=base_s,
                       backoff_cap_s=cap_s)
            counts["requeued"] += 1
        else:
            jq.fail(job, error=text, telemetry=telemetry, stage=stage)
            counts["failed"] += 1
    except jq.FenceLost as fe:
        log(f"serve: {job.id} disposal refused (claim reclaimed): "
            f"{fe}")


def run_gang(queue_dir: str,
             gang: List[Tuple["jq.Job", Tuple[int, ...]]],
             max_attempts: int = 2, verbose: bool = False, log=print,
             telemetry=None) -> Dict[str, int]:
    """Drive a gang of co-scheduled small jobs concurrently, each on
    its assigned submesh slice.

    The interleaved chunk loop is the whole trick: every live job's
    fused window is *dispatched* (``EnsembleEngine.begin_chunk`` —
    async, no host block) before any window's results are *fetched*
    (``finish_chunk``), so the disjoint submeshes compute at the same
    time even though one host thread drives them all.  Each job keeps
    its own heartbeat/checkpoint beat and its own failure handling —
    one member blowing up requeues that job alone, the rest of the
    gang keeps running.  Returns done/failed/requeued counts."""
    import jax

    from ramses_tpu.ensemble.batch import EnsembleEngine, EnsembleSpec
    from ramses_tpu.ensemble.meshplan import plan_for
    from ramses_tpu.platform import compile_cache_stats
    from ramses_tpu.resilience import (resolve_restart_dir,
                                       rotate_checkpoints)

    from ramses_tpu.obs.profile import ProfileRequestWatcher

    counts = {"done": 0, "failed": 0, "requeued": 0}
    ndev = len(jax.devices())
    cache0 = compile_cache_stats()
    busy = sum(len(d) for _, d in gang)
    gang_info = {"jobs": len(gang), "busy_devices": int(busy),
                 "ndev": int(ndev),
                 "busy_frac": round(busy / max(1, ndev), 3)}
    active: List[Dict[str, Any]] = []
    for job, dev_ids in gang:
        try:
            params, rdir, dtype = _job_setup(queue_dir, job, log=log)
            spec = EnsembleSpec.from_params(
                params, sweeps=job.record.get("sweeps"),
                solver=job.record.get("solver", ""))
            plan = plan_for(params, spec.nmember, device_ids=dev_ids,
                            solver=spec.solver)
            restart = resolve_restart_dir(params, base_dir=rdir,
                                          log=log)
            eng = (EnsembleEngine.from_checkpoint(
                spec, restart, dtype=dtype, plan=plan) if restart
                else EnsembleEngine(spec, dtype=dtype, plan=plan))
        except Exception as e:  # noqa: BLE001 — worker boundary
            _dispose(job, e, counts, max_attempts, telemetry, log)
            continue
        _bind_trace(eng, job.record)
        log(f"serve: gang member {job.id} on devices "
            f"{list(dev_ids)} ({plan.mode})")
        active.append({"job": job, "rdir": rdir, "params": params,
                       "eng": eng,
                       "dguard": DiskGuard.from_params(params, rdir,
                                                       log=log),
                       "watch": ProfileRequestWatcher(rdir, log=log)})
    if telemetry is not None:
        try:
            telemetry.record_event(
                "gang_schedule",
                job_ids=[st["job"].id for st in active], **gang_info)
        except Exception:
            pass
    while active:
        if drain_requested():
            # SIGTERM graceful drain: the in-flight chunks are done
            # (we only reach a loop top between chunks) — checkpoint
            # every held job and hand it back with stage="drain"; the
            # attempt is refunded because the drain is this worker's
            # doing, not the job's
            for st in list(active):
                st["watch"].stop()
                dg = st.get("dguard")
                guarded_save(lambda _st=st: _st["eng"].save(
                    _st["rdir"]), dg, telemetry=st["eng"].telemetry,
                    log=log, where="drain")
                st["eng"].telemetry.close(st["eng"],
                                          print_timers=False)
                try:
                    jq.requeue(st["job"],
                               error="worker draining (SIGTERM)",
                               telemetry=telemetry, stage="drain",
                               count_attempt=False)
                    counts["requeued"] += 1
                    log(f"serve: {st['job'].id} drained -> queued")
                except jq.FenceLost as fe:
                    log(f"serve: {st['job'].id} drain requeue "
                        f"refused (claim reclaimed): {fe}")
            return counts
        begun: List[Tuple[Dict[str, Any], Any]] = []
        for st in list(active):
            try:
                begun.append((st, st["eng"].begin_chunk()))
            except jq.FenceLost as e:
                st["watch"].stop()
                log(f"serve: {st['job'].id} fence lost — abandoning "
                    f"(new owner carries it): {e}")
                active.remove(st)
            except BaseException as e:  # noqa: BLE001
                stage = "hang" if isinstance(e, HangDetected) \
                    else "requeue"
                st["watch"].stop()
                _dispose(st["job"], e, counts, max_attempts,
                         telemetry, log, stage=stage)
                active.remove(st)
        for st, ctx in begun:
            if st not in active:
                continue
            try:
                eng = st["eng"]
                stepped = eng.finish_chunk(ctx)
                eng.telemetry.record_event(
                    "ensemble_chunk", nmember=eng.nmember,
                    ngroup=len(eng.groups), steps=stepped,
                    t_min=eng.t, nstep_max=eng.nstep,
                    quarantined=eng.quarantined_count,
                    wall_s=round(eng.wall_s, 6))
                jq.heartbeat(st["job"])
                guarded_save(lambda _st=st: (
                    _st["eng"].save(_st["rdir"]),
                    rotate_checkpoints(_st["rdir"], keep=2)),
                    st.get("dguard"), telemetry=eng.telemetry,
                    log=log, where="gang-beat")
                st["watch"].poll(telemetry=eng.telemetry)
                if stepped == 0 and not st["eng"].run_complete():
                    raise RuntimeError(
                        f"job {st['job'].id}: no progress in a chunk "
                        "(inconsistent tend/nstepmax)")
            except jq.FenceLost as e:
                st["watch"].stop()
                log(f"serve: {st['job'].id} fence lost — abandoning "
                    f"(new owner carries it): {e}")
                active.remove(st)
            except BaseException as e:  # noqa: BLE001
                stage = "hang" if isinstance(e, HangDetected) \
                    else "requeue"
                st["watch"].stop()
                _dispose(st["job"], e, counts, max_attempts,
                         telemetry, log, stage=stage)
                active.remove(st)
        for st in list(active):
            eng = st["eng"]
            if not eng.run_complete():
                continue
            st["watch"].stop(telemetry=eng.telemetry)
            snap = eng.save(st["rdir"])
            eng.telemetry.record_event(
                "ensemble_done", nmember=eng.nmember,
                ngroup=len(eng.groups), t_min=eng.t,
                nstep_max=eng.nstep, snapshot=snap,
                quarantined=eng.quarantined_count)
            result = _job_result(eng, st["rdir"], st["params"],
                                 st["job"].record, snap, cache0,
                                 log=log, gang_info=gang_info)
            eng.telemetry.close(eng, print_timers=False)
            try:
                jq.complete(st["job"], result=result)
                counts["done"] += 1
                log(f"serve: {st['job'].id} done -> {snap}")
            except jq.FenceLost as fe:
                log(f"serve: {st['job'].id} completion refused "
                    f"(claim reclaimed): {fe}")
            active.remove(st)
    return counts


def _counts_line(queue_dir: str) -> str:
    c = jq.queue_counts(queue_dir)
    return (f"queued={c['queued']} running={c['running']} "
            f"done={c['done']} failed={c['failed']} "
            f"parked={c.get('parked', 0)}")


def _worker_telemetry(queue_dir: str, worker: str):
    """Per-worker telemetry sink at ``<queue_dir>/workers/<worker>
    .jsonl``: queue lifecycle events (serve_start/serve_idle/requeue/
    fail/reclaim/gang_schedule) in the same JSONL schema as run
    telemetry, so ``tools/telemetry_report.py`` renders it and the obs
    ``/metrics`` scrape reads the file's mtime as worker liveness."""
    from ramses_tpu.obs.metrics import WORKERS_DIR
    from ramses_tpu.telemetry.recorder import Telemetry, TelemetrySpec

    safe = re.sub(r"[^A-Za-z0-9_.-]", "_", worker) or "worker"
    path = os.path.join(queue_dir, WORKERS_DIR, safe + ".jsonl")
    tel = Telemetry(TelemetrySpec(path=path),
                    run_info={"driver": "serve-worker",
                              "worker": worker,
                              "queue_dir": os.path.abspath(queue_dir)})
    # a restarted worker of the same name extends its history instead
    # of truncating it — the sink is a fleet log, not a run log
    tel._append = True
    tel.bind(worker=worker)
    return tel


def serve(queue_dir: str, worker: str = "", max_jobs: int = 0,
          idle_exit: bool = False, poll_s: float = 1.0,
          stale_s: Optional[float] = None, max_attempts: int = 2,
          verbose: bool = False, log=print, beat_s: float = 30.0,
          telemetry=None, order: str = "cost",
          gang_starve_s: float = 600.0,
          obs_port: Optional[int] = None,
          obs_bind: str = "127.0.0.1",
          startup_fsck: bool = True) -> Dict[str, int]:
    """Worker loop: claim and run jobs until the queue is drained
    (``idle_exit``) or ``max_jobs`` jobs have been processed
    (0 = unbounded).  Returns done/failed counts for this worker.

    ``order`` is the claim order: ``"cost"`` (default) plans each
    claim with the cost-aware gang scheduler — bin-packing small jobs
    concurrently onto submesh slices, draining to exclusive mode for
    mesh-wide jobs, with ``gang_starve_s`` bounding how long a big job
    can be overtaken — while ``"fifo"`` restores the blind
    oldest-first single-job behavior.

    Fleet hardening: on the main thread SIGTERM triggers a **graceful
    drain** (finish the in-flight chunk, checkpoint, requeue held
    jobs with ``stage="drain"`` and the attempt refunded, exit 0);
    embedders/tests call :func:`request_drain` directly.  Startup runs
    the always-safe queue-fsck repairs (``startup_fsck=False`` opts
    out).  Claims honor the requeue-backoff eligibility gate and the
    poison-config circuit breaker (matching queued jobs are parked
    while a breaker is open; TTL expiry half-opens it from this poll
    loop).  Under hard disk pressure (``RAMSES_DISK_HARD_MB``) the
    worker pauses claiming — alive and heartbeating — until space
    returns.

    Observability: ``telemetry`` defaults to a per-worker sink under
    ``<queue_dir>/workers/`` receiving the queue lifecycle events
    (requeue/fail/reclaim/gang_schedule) plus a structured
    ``serve_idle`` heartbeat with queue counts every ``beat_s``
    seconds while idle — fleet idleness is scrapeable, not just
    greppable.  ``obs_port`` (0 = ephemeral) arms the streaming
    results/metrics HTTP server (ramses_tpu/obs) over the queue dir
    for the lifetime of the loop."""
    jq.init_queue(queue_dir)
    worker = worker or f"{os.uname().nodename}:{os.getpid()}"
    counts = {"done": 0, "failed": 0, "requeued": 0}
    own_tel = None
    if telemetry is None:
        telemetry = own_tel = _worker_telemetry(queue_dir, worker)
    obs = None
    if obs_port is not None:
        from ramses_tpu.obs.server import ObsServer
        obs = ObsServer(queue_dir, port=int(obs_port), bind=obs_bind,
                        log=log if verbose else None).start()
        if log is not None:
            log(f"serve: obs server on {obs.url}")
    last_beat = 0.0
    # the shared-compile-cache default mutates process-global jax
    # config; snapshot it so an in-process caller (tests, a notebook)
    # gets its compilation-cache settings back when serve returns
    cache_snap = None
    # SIGTERM -> graceful drain.  Only the main thread may install
    # signal handlers; elsewhere (in-process embedding, test threads)
    # request_drain() is the API.  The previous handler is restored on
    # exit so serve-in-a-library never leaks its policy.
    _DRAIN.clear()
    prev_term = None
    try:
        prev_term = signal.signal(signal.SIGTERM,
                                  lambda _s, _f: request_drain())
    except ValueError:
        pass
    if startup_fsck:
        # crash-consistency sweep of the always-safe classes (torn
        # record tmps, orphaned heartbeats, orphaned parks) before
        # touching the queue; anything needing judgement is only
        # logged for the operator CLI
        try:
            from ramses_tpu.ensemble import fsck as qfsck
            qfsck.startup_repair(queue_dir, log=log)
        except Exception as e:  # noqa: BLE001 — advisory pass
            if log is not None:
                log(f"serve: startup fsck skipped: {e!r}")
    # worker-level disk watermark (env): at hard pressure stop
    # claiming, stay alive
    wguard = DiskGuard.from_env(queue_dir, log=log)
    backoff_base_s, backoff_cap_s = _backoff_knobs()
    try:
        telemetry.record_event("serve_start", worker=worker,
                               obs_url=obs.url if obs else "",
                               **jq.queue_counts(queue_dir))
        while True:
            if drain_requested():
                telemetry.record_event("serve_drain", worker=worker,
                                       **jq.queue_counts(queue_dir))
                if log is not None:
                    log(f"serve: drain requested — exiting clean; "
                        f"{_counts_line(queue_dir)}")
                return counts
            if not wguard.allow_claim():
                # hard disk pressure: claiming pauses, the worker
                # stays alive (io_degraded emitted on the transition
                # edge by emit()) and re-checks every poll
                wguard.emit(telemetry, where="claim")
                time.sleep(poll_s)
                continue
            wguard.emit(telemetry, where="claim")   # recovery edge
            # default staleness from the first job's namelist is
            # unknowable before claiming — use the CLI/default value
            jq.reclaim_stale(queue_dir, stale_s=stale_s or 300.0,
                             max_attempts=max_attempts, log=log,
                             telemetry=telemetry,
                             backoff_base_s=backoff_base_s,
                             backoff_cap_s=backoff_cap_s)
            # poison-config breaker maintenance: TTL-expired breakers
            # half-open (one probe released); open breakers park any
            # matching queued jobs before we plan a claim
            bkr.sweep(queue_dir, telemetry=telemetry,
                      log=log if verbose else None)
            records = jq.peek_queued(queue_dir)
            open_fps = bkr.open_fingerprints(queue_dir)
            if open_fps:
                keep = []
                for r in records:
                    fp = bkr.fingerprint_of(r)
                    if fp in open_fps:
                        bkr.park_record(queue_dir, r, open_fps[fp],
                                        telemetry=telemetry, log=log)
                    else:
                        keep.append(r)
                records = keep
            if not records:
                if idle_exit:
                    telemetry.record_event("serve_idle", exiting=True,
                                           **jq.queue_counts(queue_dir))
                    if log is not None:
                        log(f"serve: idle, exiting — "
                            f"{_counts_line(queue_dir)}")
                    return counts
                now = time.monotonic()
                if now - last_beat >= beat_s:
                    # structured idle heartbeat through the telemetry
                    # sink (not a bare print): the obs /metrics scrape
                    # reads the sink's mtime as worker liveness and
                    # the event carries the queue census
                    telemetry.record_event(
                        "serve_idle", **jq.queue_counts(queue_dir))
                    last_beat = now
                time.sleep(poll_s)
                continue
            now_w = time.time()
            eligible = [r for r in records
                        if float(r.get("not_before_unix") or 0.0)
                        <= now_w]
            if not eligible:
                # every queued record is inside its requeue-backoff
                # window: the queue is NOT idle (no idle_exit), the
                # jobs are just not claimable yet
                time.sleep(poll_s)
                continue
            records = eligible
            import jax
            if cache_snap is None:
                from ramses_tpu import platform as _plat
                cache_snap = ({k: getattr(jax.config, k)
                               for k in _JAX_CACHE_KEYS},
                              _plat._CACHE_STATS["dir"])
            ndev = len(jax.devices())
            planned = jq.plan_gang(records, ndev, order=order,
                                   starve_s=gang_starve_s)
            if max_jobs:
                # cap the gang by the remaining job budget so
                # max_jobs=N never over-claims inside one gang round
                left = max_jobs - counts["done"] - counts["failed"]
                planned = planned[:max(0, left)]
            gang: List[Tuple[jq.Job, Tuple[int, ...]]] = []
            offset = 0
            for rec, n in planned:
                job = jq.claim(queue_dir, worker=worker,
                               job_id=rec["id"])
                if job is None:
                    continue           # lost the race to a peer worker
                gang.append((job, tuple(range(offset, offset + n))))
                offset += n
            if not gang:
                time.sleep(poll_s * 0.1)
                continue
            if len(gang) == 1:
                # solo claim (mesh-wide, calibrate, fifo mode, or just
                # a one-job queue): the fully supervised path
                job, dev_ids = gang[0]
                log(f"serve: claimed {job.id} "
                    f"(attempt {job.record['attempts']}/{max_attempts},"
                    f" devices {list(dev_ids)})")
                try:
                    result = run_job(queue_dir, job,
                                     max_attempts=max_attempts,
                                     verbose=verbose, log=log,
                                     device_ids=dev_ids)
                except DrainRequested as e:
                    # graceful drain: the chunk finished and a drain
                    # checkpoint was attempted inside the beat — hand
                    # the job back (attempt refunded) and let the
                    # loop-top drain check exit this worker
                    try:
                        jq.requeue(job, error=str(e),
                                   telemetry=telemetry, stage="drain",
                                   count_attempt=False)
                        counts["requeued"] += 1
                        log(f"serve: {job.id} drained -> queued")
                    except jq.FenceLost as fe:
                        log(f"serve: {job.id} drain requeue refused "
                            f"(claim reclaimed): {fe}")
                except jq.FenceLost as e:
                    # this worker zombied past the stale timeout and
                    # the job was reclaimed: abandon it — the refusal
                    # is already durable in the record's failure_log
                    log(f"serve: {job.id} fence lost — abandoning "
                        f"(new owner carries it): {e}")
                except HangDetected as e:
                    # serve-loop liveness: a deadline-expired chunk
                    # comes back HERE (run_job runs hang_retries=0) —
                    # the wedged job is killed-and-requeued with
                    # stage="hang" immediately instead of zombifying
                    # this worker until stale-reclaim
                    _dispose(job, e, counts, max_attempts, telemetry,
                             log, stage="hang")
                except Exception as e:  # noqa: BLE001 — worker boundary
                    _dispose(job, e, counts, max_attempts, telemetry,
                             log)
                else:
                    try:
                        jq.complete(job, result=result)
                        counts["done"] += 1
                        log(f"serve: {job.id} done -> "
                            f"{result.get('snapshot') or result.get('checkpoint')}")
                    except jq.FenceLost as fe:
                        log(f"serve: {job.id} completion refused "
                            f"(claim reclaimed): {fe}")
            else:
                log(f"serve: gang of {len(gang)} jobs over "
                    f"{sum(len(d) for _, d in gang)}/{ndev} devices")
                gc = run_gang(queue_dir, gang,
                              max_attempts=max_attempts,
                              verbose=verbose, log=log,
                              telemetry=telemetry)
                for k in counts:
                    counts[k] += gc[k]
            if max_jobs and counts["done"] + counts["failed"] >= max_jobs:
                return counts
    finally:
        if prev_term is not None:
            try:
                signal.signal(signal.SIGTERM, prev_term)
            except ValueError:
                pass
        if own_tel is not None:
            try:
                own_tel.record_event("serve_exit", worker=worker,
                                     **counts)
            except Exception:   # noqa: BLE001
                pass
            own_tel.close(print_timers=False)
        if obs is not None:
            obs.close()
        if cache_snap is not None:
            import jax

            from ramses_tpu import platform as _plat
            for k, v in cache_snap[0].items():
                jax.config.update(k, v)
            _plat._CACHE_STATS["dir"] = cache_snap[1]


def submit_namelist(queue_dir: str, namelist_path: str,
                    sweeps: Optional[Dict[str, Any]] = None,
                    solver: str = "", ndim: int = 3,
                    dtype: str = "float32", kind: str = "run") -> str:
    """CLI submit helper: inline the namelist file into the job record
    so workers need no shared checkout."""
    with open(namelist_path) as f:
        text = f.read()
    return jq.submit(queue_dir, text, sweeps=sweeps, solver=solver,
                     ndim=ndim, dtype=dtype, kind=kind,
                     meta={"namelist_path": os.path.abspath(
                         namelist_path)})


def parse_sweep_args(items) -> Dict[str, list]:
    """``--sweep key=v1,v2,...`` CLI rows into a sweeps dict (values
    parsed as JSON scalars when possible, else kept as strings)."""
    sweeps: Dict[str, list] = {}
    for item in items or ():
        key, _, vals = item.partition("=")
        if not vals:
            raise ValueError(f"--sweep '{item}': expected key=v1,v2,...")
        parsed = []
        for v in vals.split(","):
            try:
                parsed.append(json.loads(v))
            except json.JSONDecodeError:
                parsed.append(v)
        sweeps[key.strip()] = parsed
    return sweeps
