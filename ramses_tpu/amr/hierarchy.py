"""AMR simulation driver: recursive subcycled level stepping.

The host-side recursion of ``amr_step`` (``amr/amr_step.f90:1-586``) with
the hydro-only operation order preserved:

    set_unew(l) → recurse(l+1) ×2 → godunov(l) [+ coarse corrections]
    → set_uold(l) → upload_fine(l)

Timestep policy: one CFL evaluation per coarse step,
``dt = min_l courant(l) · 2^(l-levelmin)``, then exact factor-2 subcycling
(the reference's per-level adaptive ``dtnew``/``dtold`` bookkeeping,
``amr/update_time.f90``, is replaced by this stricter-but-simpler global
choice — fine dts are exact halves, so the flux-correction weights of
``godfine1`` are exact).  Refinement runs at coarse-step boundaries
(the reference refines every level substep; coarse-step granularity is the
standard regrid-interval relaxation).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ramses_tpu.amr import flag as flagmod
from ramses_tpu.amr import kernels as K
from ramses_tpu.amr import maps as mapmod
from ramses_tpu.amr.tree import Octree, cell_offsets
from ramses_tpu.config import Params
from ramses_tpu.grid import boundary as bmod
from ramses_tpu.hydro.core import HydroStatic
from ramses_tpu.init import regions


class _Cfg1:
    """Minimal cfg shim for interp_cells on a single-column array."""

    def __init__(self, ndim: int):
        self.ndim = ndim


class AmrSim:
    """Adaptive simulation: host octree + per-level device states."""

    def __init__(self, params: Params, dtype=jnp.float32,
                 init_tree: Optional[Octree] = None):
        self.params = params
        self.cfg = HydroStatic.from_params(params)
        self.dtype = dtype
        self.boxlen = float(params.amr.boxlen)
        spec = bmod.BoundarySpec.from_params(params)
        self.bspec = spec
        self.bc_kinds = [(f[0].kind, f[1].kind) for f in spec.faces]
        self.lmin = params.amr.levelmin
        self.lmax = params.amr.levelmax
        self.t = 0.0
        self.nstep = 0
        self.regrid_interval = 1
        # self-gravity (per-level Poisson, SURVEY.md §3.3)
        self.gravity = bool(params.run.poisson)
        if self.gravity:
            if any(k != 0 for pair in self.bc_kinds for k in pair):
                raise NotImplementedError(
                    "AMR self-gravity requires periodic boundaries")
            self.fourpi = 4.0 * np.pi
        self.phi: Dict[int, jnp.ndarray] = {}
        self.fg: Dict[int, jnp.ndarray] = {}

        if init_tree is not None:
            self.tree = init_tree
            self._rebuild_maps()
            self._alloc_from_ics()
        else:
            self._init_refine()

    # ------------------------------------------------------------------
    # setup
    # ------------------------------------------------------------------
    def dx(self, lvl: int) -> float:
        return self.boxlen / (1 << lvl)

    def _noct_pad(self, noct: int) -> Optional[int]:
        """Padded oct count; subclasses align it to the device mesh."""
        return None

    def _place(self, arr, kind: str):
        """Placement hook: ``kind`` ∈ {octs, cells, rep} row semantics.
        Single-device base class keeps arrays as-is; the sharded subclass
        device_puts octs/cells-row arrays across the mesh."""
        return arr

    def _rebuild_maps(self):
        self.maps: Dict[int, mapmod.LevelMaps] = {}
        self.dev: Dict[int, dict] = {}
        for l in range(self.lmin, self.lmax + 1):
            if not self.tree.has(l):
                break
            m = mapmod.build_level_maps(
                self.tree, l, self.bc_kinds,
                noct_pad=self._noct_pad(self.tree.noct(l)))
            self.maps[l] = m
            valid_cell = np.repeat(m.valid_oct, 2 ** self.tree.ndim)
            if m.complete:
                # dense path: permutation + restriction only
                self.dev[l] = dict(
                    perm=self._place(jnp.asarray(m.perm), "cells"),
                    inv_perm=self._place(jnp.asarray(m.inv_perm), "cells"),
                    ok_dense=(self._place(jnp.asarray(m.ok_dense), "cells")
                              if m.ok_dense is not None else None),
                    ref_cell=self._place(jnp.asarray(m.ref_cell), "rep"),
                    son_oct=self._place(jnp.asarray(m.son_oct), "rep"),
                    valid_cell=self._place(jnp.asarray(valid_cell),
                                           "cells"),
                )
                continue
            self.dev[l] = dict(
                stencil_src=self._place(jnp.asarray(m.stencil_src), "octs"),
                vsgn=(self._place(jnp.asarray(m.vsgn), "octs")
                      if m.vsgn is not None else None),
                ok_ref=self._place(jnp.asarray(m.ok_ref), "octs"),
                interp_cell=self._place(jnp.asarray(m.interp_cell), "rep"),
                interp_nb=self._place(jnp.asarray(m.interp_nb), "rep"),
                interp_sgn=self._place(
                    jnp.asarray(m.interp_sgn, dtype=self.dtype), "rep"),
                corr_idx=self._place(jnp.asarray(m.corr_idx), "rep"),
                ref_cell=self._place(jnp.asarray(m.ref_cell), "rep"),
                son_oct=self._place(jnp.asarray(m.son_oct), "rep"),
                valid_cell=self._place(jnp.asarray(valid_cell), "cells"),
            )
            if self.gravity:
                g = mapmod.build_gravity_maps(self.tree, l, self.bc_kinds,
                                              noct_pad=m.noct_pad)
                self.dev[l].update(
                    g_nb=self._place(jnp.asarray(g.nb), "cells"),
                    g_cell=self._place(jnp.asarray(g.g_cell), "rep"),
                    g_gnb=self._place(jnp.asarray(g.g_nb), "rep"),
                    g_sgn=self._place(jnp.asarray(g.g_sgn), "rep"),
                    g_valid=self._place(jnp.asarray(g.valid_cell),
                                        "cells"))

    def _ic_state(self, lvl: int) -> jnp.ndarray:
        """Analytic conservative ICs on this level's (padded) cells."""
        m = self.maps[lvl]
        centers = self.tree.cell_centers(lvl, self.boxlen)
        x = [centers[:, d] for d in range(self.cfg.ndim)]
        q = regions.region_condinit(x, self.dx(lvl), self.params, self.cfg)
        u = regions.prim_to_cons(q, self.cfg)          # [nvar, ncell]
        out = np.zeros((m.ncell_pad, self.cfg.nvar))
        out[:u.shape[1]] = u.T
        out[u.shape[1]:, 0] = self.cfg.smallr
        out[u.shape[1]:, self.cfg.ndim + 1] = self.cfg.smalle * self.cfg.smallr
        return self._place(jnp.asarray(out, dtype=self.dtype), "cells")

    def _alloc_from_ics(self):
        self.u: Dict[int, jnp.ndarray] = {}
        for l in self.levels():
            self.u[l] = self._ic_state(l)
        self._restrict_all()

    def _init_refine(self):
        """Iterative initial mesh build (``amr/init_refine.f90:5-154``):
        apply analytic ICs, flag, rebuild, repeat until stable."""
        self.tree = Octree.base(self.tree_ndim, self.lmin, self.lmax)
        self._rebuild_maps()
        self._alloc_from_ics()
        for _ in range(self.lmax - self.lmin + 2):
            newtree = self._flag_and_tree()
            same = True
            for l in range(self.lmin, self.lmax + 1):
                if newtree.has(l) != self.tree.has(l):
                    same = False
                elif newtree.has(l) and not np.array_equal(
                        newtree.levels[l].keys, self.tree.levels[l].keys):
                    same = False
            if same:
                break
            self.tree = newtree
            self._rebuild_maps()
            self._alloc_from_ics()

    @property
    def tree_ndim(self) -> int:
        return self.params.ndim

    def levels(self):
        return [l for l in range(self.lmin, self.lmax + 1)
                if self.tree.has(l)]

    # ------------------------------------------------------------------
    # refinement
    # ------------------------------------------------------------------
    def _flag_and_tree(self) -> Octree:
        r = self.params.refine
        crit: Dict[int, np.ndarray] = {}
        for l in self.levels():
            d = self.dev[l]
            m = self.maps[l]
            eg = (float(r.err_grad_d), float(r.err_grad_u),
                  float(r.err_grad_p))
            fls = (float(r.floor_d), float(r.floor_u), float(r.floor_p))
            if m.complete:
                fl = K.dense_refine_flags(
                    self.u[l], d["inv_perm"], d["perm"], eg, fls,
                    (1 << l,) * self.cfg.ndim, self.bspec, self.cfg)
            else:
                interp = self._interp_for(l)
                fl = K.refine_flags(
                    self.u[l], interp, d["stencil_src"], d["vsgn"], eg, fls,
                    self.cfg)
            fl = np.asarray(fl)[:m.noct].reshape(-1)   # flat-cell order
            geo = flagmod.geometry_flags(
                self.tree.cell_centers(l, self.boxlen), l, self.params)
            crit[l] = fl | geo
        return flagmod.compute_new_tree(self.tree, crit, self.bc_kinds,
                                        self.params)

    def regrid(self):
        """Flag, rebuild the tree, and migrate device state
        (``flag_fine`` + ``refine_fine``/``kill_grid``,
        ``amr/refine_utils.f90:332,953``)."""
        if self.lmax == self.lmin:
            return
        newtree = self._flag_and_tree()
        old_u = self.u
        oldtree = self.tree
        self.tree = newtree
        self._rebuild_maps()
        twotondim = 2 ** self.cfg.ndim
        offs = cell_offsets(self.cfg.ndim)
        new_u: Dict[int, jnp.ndarray] = {}
        for l in self.levels():
            m = self.maps[l]
            if l == self.lmin:
                # base level is identical (complete, same sorted order)
                new_u[l] = old_u[l]
                continue
            cd, cs, new_octs, f_cell, nb = mapmod.build_prolong_maps(
                self.tree, oldtree, l, self.bc_kinds)
            buf = np.zeros((m.ncell_pad, self.cfg.nvar), dtype=np.float32)
            u_new = self._place(jnp.asarray(buf, dtype=self.dtype), "cells")
            if len(cd):
                rows_d = (cd[:, None] * twotondim
                          + np.arange(twotondim)[None, :]).reshape(-1)
                rows_s = (cs[:, None] * twotondim
                          + np.arange(twotondim)[None, :]).reshape(-1)
                u_new = u_new.at[jnp.asarray(rows_d)].set(
                    old_u[l][jnp.asarray(rows_s)])
            if len(new_octs):
                # one interpolation request per (new oct, child cell)
                nn = len(new_octs)
                sgn = (offs * 2 - 1).astype(np.float64)  # [2^d, ndim]
                cell_rep = np.repeat(f_cell, twotondim)
                nb_rep = np.repeat(nb, twotondim, axis=0)
                sgn_rep = np.tile(sgn, (nn, 1))
                vals = K.interp_cells(
                    new_u[l - 1], jnp.asarray(cell_rep),
                    jnp.asarray(nb_rep),
                    jnp.asarray(sgn_rep, dtype=self.dtype), self.cfg,
                    itype=int(self.params.refine.interpol_type))
                rows = (new_octs[:, None] * twotondim
                        + np.arange(twotondim)[None, :]).reshape(-1)
                u_new = u_new.at[jnp.asarray(rows)].set(
                    vals.astype(self.dtype))
            new_u[l] = u_new
        self.u = new_u
        self._restrict_all()

    def _restrict_all(self):
        """Restriction sweep fine→coarse so non-leaf cells hold son means."""
        for l in sorted(self.levels(), reverse=True):
            if self.tree.has(l + 1):
                d = self.dev[l]
                self.u[l] = K.restrict_upload(self.u[l], self.u[l + 1],
                                              d["ref_cell"], d["son_oct"],
                                              self.cfg)

    # ------------------------------------------------------------------
    # time stepping
    # ------------------------------------------------------------------
    def _interp_for(self, l: int) -> jnp.ndarray:
        d = self.dev[l]
        if l == self.lmin:
            return jnp.zeros((self.maps[l].ni_pad, self.cfg.nvar),
                             self.dtype)
        return K.interp_cells(self.u[l - 1], d["interp_cell"],
                              d["interp_nb"], d["interp_sgn"], self.cfg,
                              itype=int(self.params.refine.interpol_type))

    def coarse_dt(self) -> float:
        dts = []
        for l in self.levels():
            d = self.dev[l]
            dt_l = K.level_courant(self.u[l], d["valid_cell"], self.dx(l),
                                   self.cfg)
            dts.append(float(dt_l) * (2 ** (l - self.lmin)))
        return min(dts)

    def solve_gravity(self):
        """Per-level Poisson solve, coarse→fine one-way interface
        (``multigrid_fine``): exact periodic FFT on any COMPLETE level
        (the base always; fully-refined levels above too),
        Dirichlet-ghost CG on partial levels; then the gradient force."""
        from ramses_tpu.poisson import amr_solve as gs
        from ramses_tpu.poisson.solver import fft_solve

        nd = self.cfg.ndim
        # mean density over leaves (periodic solvability)
        rho_mean = float(self.totals()[0]) / self.boxlen ** nd
        for l in self.levels():
            m = self.maps[l]
            d = self.dev[l]
            dx = self.dx(l)
            rho = self.u[l][:, 0]
            rhs = self.fourpi * (rho - rho_mean)
            if m.complete:
                # whole-box level: exact periodic FFT solve on the dense
                # grid, force by central-difference rolls
                nb_ = 1 << l
                ncell = m.noct * (1 << nd)
                dense = rhs[d["inv_perm"]].reshape((nb_,) * nd)
                phi_dense = fft_solve(dense, dx)
                phi = jnp.zeros((m.ncell_pad,), rhs.dtype)
                phi = phi.at[:ncell].set(
                    phi_dense.reshape(-1)[d["perm"]])
                fg_rows = gs.grad_dense(phi_dense,
                                        jnp.asarray(dx, rhs.dtype),
                                        nd)[d["perm"]]
                if m.ncell_pad > ncell:
                    fg_rows = jnp.zeros(
                        (m.ncell_pad, nd), fg_rows.dtype
                    ).at[:ncell].set(fg_rows)
                self.phi[l] = phi
                self.fg[l] = fg_rows.astype(self.dtype)
                continue
            else:
                ghosts = K.interp_cells(
                    self.phi[l - 1][:, None], d["g_cell"], d["g_gnb"],
                    d["g_sgn"].astype(self.phi[l - 1].dtype),
                    _Cfg1(nd), itype=1)[:, 0]
                phi = gs.cg_level(rhs, ghosts, d["g_nb"],
                                  jnp.asarray(dx, rhs.dtype),
                                  d["g_valid"], nd, iters=150)
            self.phi[l] = phi
            self.fg[l] = gs.grad_phi(phi, ghosts, d["g_nb"],
                                     jnp.asarray(dx, phi.dtype),
                                     d["g_valid"], nd).astype(self.dtype)

    def step_coarse(self, dt: float):
        self.unew: Dict[int, jnp.ndarray] = {}
        if self.gravity:
            self.solve_gravity()
        self._advance(self.lmin, float(dt))
        self.t += float(dt)
        self.nstep += 1

    def _advance(self, l: int, dt: float):
        if self.gravity:                               # synchro −½dt
            from ramses_tpu.poisson.amr_solve import kick_flat
            self.u[l] = kick_flat(self.u[l], self.fg[l],
                                  jnp.asarray(0.5 * dt, self.dtype),
                                  self.cfg.ndim, self.cfg.smallr)
        self.unew[l] = self.u[l]                       # set_unew
        if self.tree.has(l + 1):
            self._advance(l + 1, 0.5 * dt)             # subcycle ×2
            self._advance(l + 1, 0.5 * dt)
        d = self.dev[l]
        m = self.maps[l]
        if m.complete:
            du = K.dense_sweep(
                self.u[l], d["inv_perm"], d["perm"], d["ok_dense"],
                jnp.asarray(dt, self.dtype), self.dx(l),
                (1 << l,) * self.cfg.ndim, self.bspec, self.cfg)
            corr = None
        else:
            interp = self._interp_for(l)
            du, corr = K.level_sweep(
                self.u[l], interp, d["stencil_src"], d["vsgn"], d["ok_ref"],
                None, jnp.asarray(dt, self.dtype), self.dx(l), self.cfg)
        self.unew[l] = self.unew[l] + du
        if l > self.lmin and corr is not None:
            self.unew[l - 1] = K.scatter_corrections(
                self.unew[l - 1], corr, d["corr_idx"], self.cfg)
        self.u[l] = self.unew[l]                       # set_uold
        if self.gravity:                               # synchro +½dt
            from ramses_tpu.poisson.amr_solve import kick_flat
            self.u[l] = kick_flat(self.u[l], self.fg[l],
                                  jnp.asarray(0.5 * dt, self.dtype),
                                  self.cfg.ndim, self.cfg.smallr)
        if self.tree.has(l + 1):
            self.u[l] = K.restrict_upload(self.u[l], self.u[l + 1],
                                          d["ref_cell"], d["son_oct"],
                                          self.cfg)

    def evolve(self, tend: float, nstepmax: int = 10 ** 9,
               verbose: bool = False):
        while self.t < tend * (1 - 1e-12) and self.nstep < nstepmax:
            if self.regrid_interval and \
                    self.nstep % self.regrid_interval == 0:
                self.regrid()
            dt = min(self.coarse_dt(), tend - self.t)
            self.step_coarse(dt)
            if verbose:
                print(f"step {self.nstep} t={self.t:.5e} dt={dt:.3e} "
                      f"octs={[self.tree.noct(l) for l in self.levels()]}")

    # ------------------------------------------------------------------
    # diagnostics
    # ------------------------------------------------------------------
    def totals(self):
        """Conservation audit over leaf cells (``check_cons``)."""
        cfg = self.cfg
        tot = np.zeros(cfg.nvar)
        for l in self.levels():
            m = self.maps[l]
            vol = self.dx(l) ** cfg.ndim
            u = np.asarray(self.u[l])[:m.noct * 2 ** cfg.ndim]
            leaf = ~self.tree.refined_mask(l)
            tot += u[leaf].sum(axis=0) * vol
        return tot

    def leaf_sample(self, lvl: int):
        """(centers [n, ndim], u [n, nvar]) of leaf cells at one level."""
        m = self.maps[lvl]
        u = np.asarray(self.u[lvl])[:m.noct * 2 ** self.cfg.ndim]
        leaf = ~self.tree.refined_mask(lvl)
        return self.tree.cell_centers(lvl, self.boxlen)[leaf], u[leaf]

    def ncell_leaf(self) -> int:
        return sum(int((~self.tree.refined_mask(l)).sum())
                   for l in self.levels())

    # ------------------------------------------------------------------
    # snapshot / restart (SURVEY.md §3.4, §5.4)
    # ------------------------------------------------------------------
    def dump(self, iout: int = 1, base_dir: str = ".",
             namelist_path: Optional[str] = None) -> str:
        """Write a reference-format ``output_NNNNN/`` snapshot."""
        from ramses_tpu.io import snapshot as snapmod
        snap = snapmod.snapshot_from_amr(self, iout)
        return snapmod.dump_all(snap, iout, base_dir,
                                namelist_path=namelist_path)

    @classmethod
    def from_snapshot(cls, params: Params, outdir: str,
                      dtype=jnp.float32) -> "AmrSim":
        """Resume from a snapshot directory (``nrestart`` path)."""
        from ramses_tpu.io.restart import restore_tree_state
        cfg = HydroStatic.from_params(params)
        tree_og, u_lv, meta, _parts = restore_tree_state(
            outdir, cfg, params.amr.levelmin)
        tree = Octree(params.ndim, params.amr.levelmin, params.amr.levelmax)
        for l, og in tree_og.items():
            tree.set_level(l, og)
        sim = cls(params, dtype=dtype, init_tree=tree)
        for l, u in u_lv.items():
            # restored rows follow file order == our sorted-key order, but
            # re-map defensively through the rebuilt tree's key order
            og = tree_og[l]
            pos = tree.lookup(l, og)
            m = sim.maps[l]
            ttd = 2 ** cfg.ndim
            out = np.array(sim.u[l])
            cells = u.reshape(len(og), ttd, cfg.nvar)
            out[:m.noct * ttd] = cells[np.argsort(pos)].reshape(-1, cfg.nvar)
            sim.u[l] = jnp.asarray(out, dtype=dtype)
        sim._restrict_all()
        sim.t = float(meta["t"])
        sim.nstep = int(meta["nstep"])
        return sim
