from ramses_tpu.grid.uniform import UniformGrid  # noqa: F401
