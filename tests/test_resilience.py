"""Fault-tolerant execution layer (ramses_tpu/resilience/).

Pins the three pillars:

  * atomic validated checkpoints — a kill mid-dump never leaves a
    directory that scans as a checkpoint, stale dirs are replaced (not
    merged), corrupt manifests/payloads are skipped for the next-oldest
    valid one, rotation keeps the last N;
  * supervised auto-resume — bounded retry-with-resume reproduces an
    uninterrupted run within round-off after a SIGTERM mid-run;
  * in-run NaN rollback — an injected NaN is recovered by the redo-step
    ladder with the telemetry step-record stream indistinguishable in
    length from a clean run, at zero device-fetch overhead when armed.
"""

import json
import os
import signal
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ramses_tpu.config import params_from_string
from ramses_tpu.resilience import checkpoint as ckpt
from ramses_tpu.resilience import faultinject as finj
from ramses_tpu.resilience import supervisor as rsup
from ramses_tpu.resilience.stepguard import StepGuard

pytestmark = pytest.mark.smoke

AMR2D = """
&RUN_PARAMS
hydro=.true.
nstepmax={nstep}
ncontrol=1
{run_extra}
/
&AMR_PARAMS
levelmin=4
levelmax=5
boxlen=1.0
/
&INIT_PARAMS
nregion=2
region_type(1)='square'
region_type(2)='point'
x_center=0.5,0.5
y_center=0.5,0.5
length_x=10.0,1.0
length_y=10.0,1.0
exp_region=10.0,10.0
d_region=1.0,0.0
p_region=1e-5,0.1
/
&OUTPUT_PARAMS
{out_extra}
/
&HYDRO_PARAMS
gamma=1.4
courant_factor=0.8
/
&REFINE_PARAMS
err_grad_p=0.1
/
"""

UNI2D = """
&RUN_PARAMS
hydro=.true.
nstepmax={nstep}
ncontrol=1
{run_extra}
/
&AMR_PARAMS
levelmin=4
levelmax=4
boxlen=1.0
/
&INIT_PARAMS
nregion=2
region_type(1)='square'
region_type(2)='point'
x_center=0.5,0.5
y_center=0.5,0.5
length_x=10.0,1.0
length_y=10.0,1.0
exp_region=10.0,10.0
d_region=1.0,0.0
p_region=1e-5,0.1
/
&OUTPUT_PARAMS
noutput=1
tout=1.0
{out_extra}
/
&HYDRO_PARAMS
gamma=1.4
courant_factor=0.8
/
"""


def _uni_params(nstep=6, run_extra="", out_extra=""):
    return params_from_string(
        UNI2D.format(nstep=nstep, run_extra=run_extra,
                     out_extra=out_extra), ndim=2)


def _uni_sim(nstep=6, run_extra="", out_extra="", dtype=jnp.float64):
    from ramses_tpu.driver import Simulation
    return Simulation(_uni_params(nstep, run_extra, out_extra),
                      dtype=dtype)


def _amr_sim(tmp_path, nstep=6, run_extra="", telemetry=True):
    from ramses_tpu.amr.hierarchy import AmrSim
    out = (f"telemetry='{tmp_path}/run.jsonl'\ntelemetry_interval=1"
           if telemetry else "tend=1.0")
    p = params_from_string(AMR2D.format(nstep=nstep, run_extra=run_extra,
                                        out_extra=out), ndim=2)
    return AmrSim(p)


def _records(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


# ---------------------------------------------------------------------
# config plumbing + fault spec
# ---------------------------------------------------------------------
def test_config_keys_parse():
    p = _uni_params(
        run_extra=("auto_resume=.true.\nmax_step_retries=3\n"
                   "fault_inject='nan@3'"),
        out_extra="checkpoint_keep=2")
    assert p.run.auto_resume is True
    assert p.run.max_step_retries == 3
    assert p.run.fault_inject == "nan@3"
    assert p.output.checkpoint_keep == 2


def test_fault_spec_parse_arming_and_window_clamp():
    inj = finj.FaultInjector("nan@3, sigterm@5")
    assert inj.faults == [("nan", 3), ("sigterm", 5)]
    with pytest.raises(ValueError):
        finj.FaultInjector("explode@1")
    # strict arming: a run first observed AT/AFTER the trigger step
    # (i.e. a resumed run) never re-fires the fault
    resumed = types.SimpleNamespace(nstep=7, u=jnp.zeros((4, 4)))
    assert inj.maybe_nan(resumed) is False
    assert inj.clamp_window(7, 16) == 16    # disarmed: no clamping
    # pending faults clamp fused windows to land exactly on step K
    inj2 = finj.FaultInjector("nan@5")
    assert inj2.clamp_window(0, 16) == 5
    assert inj2.clamp_window(3, 16) == 2
    assert inj2.clamp_window(5, 16) == 16   # past the target


def test_member_targeted_fault_parse_clamp_and_poison():
    """``nan@K:member=J``: the faults list keeps its historic 2-tuple
    shape (member targeting rides the parallel ``member_of`` dict), the
    solo drivers skip targeted faults, and the batched clamp/poison key
    on member J's OWN step count."""
    inj = finj.FaultInjector("nan@5:member=2,sigterm@9")
    assert inj.faults == [("nan", 5), ("sigterm", 9)]
    assert inj.member_of == {0: 2}
    with pytest.raises(ValueError, match="member"):
        finj.FaultInjector("nan@3:lane=1")
    # a solo sim never fires a member-targeted fault
    sim = types.SimpleNamespace(nstep=0, u=jnp.zeros((4, 4)))
    assert inj.maybe_nan(sim) is False
    assert np.isfinite(np.asarray(sim.u)).all()
    # member faults clamp against THAT member's step count, untargeted
    # faults against the engine-global one
    assert inj.clamp_window_batch(16, 0, lambda j: {2: 3}[j]) == 2
    assert inj.clamp_window_batch(16, 7, lambda j: {2: 5}[j]) == 2
    assert inj.clamp_window_batch(16, 9, lambda j: {2: 7}[j]) == 16

    # batched poison lands in member J's LANE, exactly at its step K
    inj2 = finj.FaultInjector("nan@5:member=2")
    grp = types.SimpleNamespace(members=[4, 2],
                                state=(jnp.ones((2, 3, 4, 4)),),
                                nstep=np.array([7, 3]))
    assert inj2.maybe_nan_batch(grp) == []    # arms at nstep 3 < 5
    grp.nstep = np.array([9, 5])
    assert inj2.maybe_nan_batch(grp) == [2]
    u = np.asarray(grp.state[0])
    assert np.isnan(u[1, 0, 0, 0]) and np.isfinite(u[0]).all()
    assert inj2.maybe_nan_batch(grp) == []    # exactly-once
    # strict arming: a resume first observed at nstep >= K never fires
    inj3 = finj.FaultInjector("nan@5:member=2")
    grp.nstep = np.array([9, 6])
    assert inj3.maybe_nan_batch(grp) == []
    assert inj3.clamp_window_batch(16, 0, lambda j: 1) == 16


# ---------------------------------------------------------------------
# atomic checkpoints
# ---------------------------------------------------------------------
def test_kill_mid_dump_never_leaves_valid_checkpoint(tmp_path,
                                                     monkeypatch):
    sim = _uni_sim(nstep=2)
    base = str(tmp_path)

    def killed(src, dst):
        raise RuntimeError("simulated kill -9 before the atomic rename")

    with monkeypatch.context() as m:
        m.setattr(os, "replace", killed)
        with pytest.raises(RuntimeError, match="kill -9"):
            sim.dump(1, base)
    # the staged dir never became output_00001 and nothing in the base
    # dir parses as a checkpoint
    assert not os.path.exists(os.path.join(base, "output_00001"))
    assert ckpt.latest_valid_checkpoint(base, log=lambda *_: None) is None
    # the retry cleans the stale stage and finalizes atomically
    out = sim.dump(1, base)
    ok, reason = ckpt.validate_checkpoint(out)
    assert ok, reason
    assert ckpt.latest_valid_checkpoint(base, log=lambda *_: None) == out


def test_stale_output_dir_replaced_not_merged(tmp_path):
    sim = _uni_sim(nstep=2)
    base = str(tmp_path)
    stale = os.path.join(base, "output_00001")
    os.makedirs(stale)
    with open(os.path.join(stale, "junk_from_older_run.out"), "w") as f:
        f.write("stale")
    out = sim.dump(1, base)
    assert out == stale
    assert not os.path.exists(
        os.path.join(stale, "junk_from_older_run.out")), \
        "dump must REPLACE a pre-existing output dir, not merge into it"
    ok, reason = ckpt.validate_checkpoint(out)
    assert ok, reason


def test_scan_skips_corrupt_and_picks_next_oldest(tmp_path):
    sim = _uni_sim(nstep=2)
    base = str(tmp_path)
    d1 = sim.dump(1, base)
    sim.state.nstep, sim.state.t = 3, 0.25
    d2 = sim.dump(2, base)
    assert ckpt.latest_valid_checkpoint(base, log=lambda *_: None) == d2
    # truncate one payload file in the newest: hash/size mismatch
    files = [f for f in sorted(os.listdir(d2)) if f != ckpt.MANIFEST_NAME]
    victim = os.path.join(d2, files[0])
    with open(victim, "r+b") as f:
        f.truncate(max(0, os.path.getsize(victim) // 2))
    ok, reason = ckpt.validate_checkpoint(d2)
    assert not ok and files[0] in reason
    skips = []
    assert ckpt.latest_valid_checkpoint(
        base, log=lambda m: skips.append(str(m))) == d1
    assert any("output_00002" in s for s in skips), \
        "a skipped corrupt checkpoint must be logged with a reason"
    # corrupt the survivor's manifest JSON too: nothing valid remains
    with open(os.path.join(d1, ckpt.MANIFEST_NAME), "w") as f:
        f.write("{not json")
    assert ckpt.latest_valid_checkpoint(base, log=lambda *_: None) is None


def test_rotation_keeps_last_n_manifest_dirs_only(tmp_path):
    sim = _uni_sim(nstep=2, out_extra="checkpoint_keep=2")
    base = str(tmp_path)
    # a pre-manifest (legacy) science output must never be rotated away
    legacy = os.path.join(base, "output_00077")
    os.makedirs(legacy)
    for i in (1, 2, 3):
        sim.state.nstep = i
        sim.dump(i, base)
    assert not os.path.exists(os.path.join(base, "output_00001")), \
        "keep_last=2 must delete the oldest manifest-valid checkpoint"
    assert os.path.exists(os.path.join(base, "output_00002"))
    assert os.path.exists(os.path.join(base, "output_00003"))
    assert os.path.exists(legacy)


def test_resolve_restart_dir_modes(tmp_path):
    base = str(tmp_path)
    p = _uni_params()
    p.run.nrestart = 2
    with pytest.raises(FileNotFoundError):
        ckpt.resolve_restart_dir(p, base_dir=base, log=lambda *_: None)
    sim = _uni_sim(nstep=2)
    d2 = sim.dump(2, base)
    assert ckpt.resolve_restart_dir(p, base_dir=base,
                                    log=lambda *_: None) == d2
    # explicit restart from a checkpoint that fails validation is loud
    with open(os.path.join(d2, ckpt.MANIFEST_NAME), "a") as f:
        f.write("garbage")
    with pytest.raises(RuntimeError, match="nrestart=-1"):
        ckpt.resolve_restart_dir(p, base_dir=base, log=lambda *_: None)
    # auto mode skips it and finds the next valid one
    d1 = sim.dump(1, base)
    p.run.nrestart = -1
    assert ckpt.resolve_restart_dir(p, base_dir=base,
                                    log=lambda *_: None) == d1


def test_truncate_fault_injection_breaks_validation(tmp_path,
                                                    monkeypatch):
    monkeypatch.setenv(finj.ENV_VAR, "truncate:hydro")
    finj._truncate_fired.clear()
    try:
        sim = _uni_sim(nstep=2)
        out = sim.dump(1, str(tmp_path))
        ok, reason = ckpt.validate_checkpoint(out)
        assert not ok and "hydro" in reason
        assert ckpt.latest_valid_checkpoint(
            str(tmp_path), log=lambda *_: None) is None
    finally:
        finj._truncate_fired.clear()


# ---------------------------------------------------------------------
# NaN rollback-with-retry
# ---------------------------------------------------------------------
def test_amr_nan_rollback_recovers_with_identical_record_stream(
        tmp_path):
    clean = _amr_sim(tmp_path / "clean", nstep=6)
    clean.evolve(1e9, nstepmax=6)
    clean.telemetry.close(clean, print_timers=False)
    clean_steps = [r for r in _records(tmp_path / "clean" / "run.jsonl")
                   if r["kind"] == "step"]

    faulty = _amr_sim(tmp_path / "faulty", nstep=6,
                      run_extra="max_step_retries=2\nfault_inject='nan@3'")
    faulty.evolve(1e9, nstepmax=6)
    faulty.telemetry.close(faulty, print_timers=False)
    recs = _records(tmp_path / "faulty" / "run.jsonl")
    steps = [r for r in recs if r["kind"] == "step"]

    assert faulty.nstep == 6 and np.isfinite(faulty.t)
    assert len(steps) == len(clean_steps) == 6, \
        "a recovered step must emit exactly one step record"
    assert [r["nstep"] for r in steps] == [1, 2, 3, 4, 5, 6]
    kinds = [r["kind"] for r in recs]
    assert "fault" in kinds and "rollback" in kinds \
        and "rollback_recovered" in kinds
    rb = next(r for r in recs if r["kind"] == "rollback")
    assert rb["attempt"] == 1 and 0 < rb["dt"] <= 0.5
    assert recs[-1]["kind"] == "run_footer"
    assert recs[-1]["events"]["rollback_recovered"] == 1


def test_uniform_nan_rollback_recovers():
    sim = _uni_sim(nstep=5,
                   run_extra="max_step_retries=2\nfault_inject='nan@2'")
    sim.evolve()
    assert sim.nstep == 5
    assert np.isfinite(sim.t) and sim.t > 0
    assert np.isfinite(np.asarray(sim.state.u)).all()
    assert sim._sguard.rollbacks >= 1
    assert sim._sguard.recovered >= 1
    assert sim._sguard.aborts == 0


def test_retry_ladder_exhaustion_emergency_dumps_and_raises(tmp_path,
                                                            monkeypatch):
    from ramses_tpu.resilience.stepguard import StepRetryExhausted
    sim = _uni_sim(nstep=4,
                   run_extra="max_step_retries=2\nfault_inject='nan@1'",
                   out_extra=f"output_dir='{tmp_path}'")
    # make every retry fail too: the ladder must exhaust, dump the last
    # clean state, and abort loudly
    monkeypatch.setattr(StepGuard, "ok",
                        staticmethod(lambda *vals: False))
    with pytest.raises(StepRetryExhausted):
        sim.evolve()
    assert sim._sguard.aborts == 1
    out = os.path.join(str(tmp_path), "output_00999")
    assert os.path.exists(out)
    ok, reason = ckpt.validate_checkpoint(out)
    assert ok, reason
    # the emergency dump is the retained CLEAN pre-step state: the
    # all-False guard trips on the very first window, so nstep is 0
    meta = ckpt.read_manifest_meta(out)
    assert int(meta["nstep"]) == 0


def test_zero_overhead_when_guard_armed(tmp_path, monkeypatch):
    sim = _amr_sim(tmp_path, nstep=16, telemetry=False,
                   run_extra="max_step_retries=2")
    assert sim._sguard is not None
    sim.regrid_interval = 0
    sim.evolve(1e9, nstepmax=4)            # warm the fused chunk
    calls = {"n": 0}
    real = jax.device_get

    def counted(x):
        calls["n"] += 1
        return real(x)

    monkeypatch.setattr(jax, "device_get", counted)
    sim.evolve(1e9, nstepmax=sim.nstep + 8)
    assert calls["n"] == 0, \
        "arming the step guard must not add host<->device fetches"


# ---------------------------------------------------------------------
# OpsGuard trap + dump-thread draining
# ---------------------------------------------------------------------
def _fake_sim(tmp_path, **kw):
    events = []
    tel = types.SimpleNamespace(
        record_event=lambda k, **f: events.append((k, f)))
    sim = types.SimpleNamespace(
        dt_old=1e-3, nstep=3, t=0.1, telemetry=tel,
        dump=lambda iout, base: str(tmp_path), **kw)
    return sim, events


def test_opsguard_traps_nonfinite_and_nonpositive_dt(tmp_path):
    from ramses_tpu.utils.ops import OpsGuard
    sim, events = _fake_sim(tmp_path)
    sim.dt_old = float("nan")
    g = OpsGuard(sim, str(tmp_path), install_signals=False,
                 nan_check=True)
    assert g.check() is False
    assert events[0][0] == "fault"
    assert events[0][1]["reason"] == "nonfinite_dt"

    sim2, events2 = _fake_sim(tmp_path)
    sim2.dt_old = 0.0
    g2 = OpsGuard(sim2, str(tmp_path), install_signals=False,
                  nan_check=True)
    assert g2.check() is False
    assert events2[0][1]["reason"] == "nonpositive_dt"

    # dt == 0 before the first step is normal startup, not a fault
    sim3, events3 = _fake_sim(tmp_path)
    sim3.dt_old, sim3.nstep = 0.0, 0
    g3 = OpsGuard(sim3, str(tmp_path), install_signals=False,
                  nan_check=True)
    assert g3.check() is True
    assert not events3


def test_async_dumper_drain_and_stop_path_reporting(tmp_path,
                                                    monkeypatch):
    from ramses_tpu.io import snapshot as snapmod
    from ramses_tpu.io.async_writer import AsyncDumper
    from ramses_tpu.utils.ops import OpsGuard

    def boom(*a, **kw):
        raise RuntimeError("disk full")

    monkeypatch.setattr(snapmod, "dump_all", boom)
    d = AsyncDumper()
    d.submit(None, 1, str(tmp_path))
    errs = d.drain()
    assert len(errs) == 1 and "disk full" in str(errs[0])
    assert d.drain() == []                 # drained errors are cleared

    # the OpsGuard stop path must surface writer failures as io_error
    # telemetry events instead of raising past the clean shutdown
    d.submit(None, 2, str(tmp_path))
    sim, events = _fake_sim(tmp_path, dumper=d)
    g = OpsGuard(sim, str(tmp_path), install_signals=False,
                 nan_check=False)
    g._stop_requested = True
    assert g.check() is False
    assert any(k == "io_error" and "disk full" in f["error"]
               for k, f in events)
    d.close()


# ---------------------------------------------------------------------
# supervised auto-resume
# ---------------------------------------------------------------------
def test_supervisor_bounded_attempts_with_backoff(tmp_path, monkeypatch):
    sleeps = []
    monkeypatch.setattr(rsup.time, "sleep", lambda s: sleeps.append(s))
    p = _uni_params(nstep=5)
    calls = {"n": 0}

    def build(restart):
        assert restart is None             # no checkpoints on disk
        return types.SimpleNamespace(nstep=0, t=0.0, telemetry=None)

    def drive(sim):
        calls["n"] += 1
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        rsup.supervise(build, drive, p, base_dir=str(tmp_path),
                       max_attempts=3, log=lambda *_: None)
    assert calls["n"] == 3
    assert sleeps == [1.0, 2.0]            # exponential, from base 1 s
    assert rsup.backoff_delay(10) == 30.0  # capped


def test_run_complete_semantics():
    p = _uni_params(nstep=5)
    assert rsup.run_complete(
        types.SimpleNamespace(nstep=5, t=0.0), p)      # nstepmax hit
    assert rsup.run_complete(
        types.SimpleNamespace(nstep=1, t=1.0), p)      # tend reached
    assert not rsup.run_complete(
        types.SimpleNamespace(nstep=1, t=0.1), p)


def test_sigterm_supervised_resume_matches_uninterrupted_run(
        tmp_path, monkeypatch):
    from ramses_tpu.driver import Simulation
    from ramses_tpu.utils.ops import OpsGuard
    monkeypatch.setattr(rsup.time, "sleep", lambda s: None)
    prev_term = signal.getsignal(signal.SIGTERM)
    prev_int = signal.getsignal(signal.SIGINT)
    try:
        ref = _uni_sim(nstep=8, dtype=jnp.float64)
        ref.evolve()
        assert ref.nstep == 8

        outdir = str(tmp_path / "run")
        os.makedirs(outdir)
        p = _uni_params(nstep=8, run_extra="fault_inject='sigterm@4'")

        def build(restart):
            return (Simulation.from_snapshot(p, restart,
                                             dtype=jnp.float64)
                    if restart else Simulation(p, dtype=jnp.float64))

        def drive(sim):
            guard = OpsGuard(sim, outdir)
            guard.run_guarded(lambda: sim.evolve(guard=guard))

        logs = []
        sim = rsup.supervise(build, drive, p, base_dir=outdir,
                             max_attempts=3,
                             log=lambda m: logs.append(str(m)))
        assert any("resuming from" in m for m in logs), \
            "the SIGTERM must interrupt the run mid-way (attempt 2 " \
            "resumes from the stop checkpoint)"
        assert sim.nstep == 8
        np.testing.assert_allclose(
            np.asarray(sim.state.u), np.asarray(ref.state.u),
            rtol=1e-9, atol=1e-12)
        assert abs(sim.t - ref.t) <= 1e-12 * max(abs(ref.t), 1.0)
    finally:
        signal.signal(signal.SIGTERM, prev_term)
        signal.signal(signal.SIGINT, prev_int)


def test_telemetry_resume_appends_and_counts_events(tmp_path):
    from ramses_tpu.telemetry import Telemetry, TelemetrySpec
    path = tmp_path / "t.jsonl"
    tel = Telemetry(TelemetrySpec(path=str(path), interval=1))
    sim = types.SimpleNamespace(nstep=1, t=0.1, dt_old=1e-3)
    tel.record_step(sim, dt=1e-3)
    tel.close(print_timers=False)
    n0 = len(_records(path))

    tel2 = Telemetry(TelemetrySpec(path=str(path), interval=1))
    tel2.mark_resumed("output_00042", attempt=2)
    sim.nstep = 2
    tel2.record_step(sim, dt=1e-3)
    tel2.close(print_timers=False)
    recs = _records(path)
    assert len(recs) > n0, "a resumed sink must APPEND, not truncate"
    resume = [r for r in recs if r["kind"] == "resume"]
    assert resume and resume[0]["attempt"] == 2
    assert resume[0]["outdir"] == "output_00042"
    assert recs[-1]["events"]["resume"] == 1
