"""Age/metallicity-binned stellar SED tables for the RT module.

The ``rt/rt_spectra.f90`` machinery (1,795 LoC there): read a SED
library from a directory in the reference's on-disk format —
``metallicity_bins.dat`` / ``age_bins.dat`` (formatted counts + one
value per line) and ``all_seds.dat`` (Fortran unformatted: one record
``(nLambda, dum)``, one wavelength record [Å], then one luminosity
record per (metallicity, age) pair in L⊙/Å/M⊙) — and integrate each
photon group's properties per (age, Z) bin:

  * ``lphot``  photons/s/M⊙ emitted into the group,
  * ``egy``    mean photon energy [erg],
  * ``csn``    photon-number-weighted HI/HeI/HeII cross sections [cm²],
  * ``cse``    energy-weighted cross sections [cm²].

Star particles then drive injection (rate = m★ · lphot(age, Z)) and
the photon-rate-weighted population average refreshes the chemistry's
group properties every ``sedprops_update`` coarse steps
(``rt_spectra.f90`` update_SED_group_props role).  A directory written
by :func:`write_sed_dir` round-trips bit-exactly, and real
bc03-format libraries read unchanged.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Sequence, Tuple

import numpy as np

from ramses_tpu.io import fortran as frt
from ramses_tpu.rt.chem import EV
from ramses_tpu.rt.spectra import Group3, cross_section

H_PLANCK = 6.62607e-27              # erg s
C_CGS = 2.99792458e10               # cm/s
ANG = 1e-8                          # cm
L_SUN = 3.826e33                    # erg/s (the reference's L_sun)
HC_EV_ANG = H_PLANCK * C_CGS / (EV * ANG)   # E[eV] = HC_EV_ANG / λ[Å]


@dataclass(frozen=True)
class SedLibrary:
    """Raw SED library: λ grid [Å], age bins [Gyr], metallicity bins
    (mass fraction), seds[nLambda, nAge, nZ] in L⊙/Å/M⊙."""
    lam_A: np.ndarray
    ages_gyr: np.ndarray
    zs: np.ndarray
    seds: np.ndarray


def _read_bins(path: str) -> np.ndarray:
    with open(path) as f:
        n = int(f.readline())
        return np.array([float(f.readline()) for _ in range(n)])


def read_sed_dir(sed_dir: str) -> SedLibrary:
    """Read a reference-format SED directory (``rt_spectra.f90:286-356``,
    falling back to the ``RAMSES_SED_DIR`` environment variable like the
    reference when ``sed_dir`` is empty)."""
    sed_dir = sed_dir or os.environ.get("RAMSES_SED_DIR", "")
    for fn in ("metallicity_bins.dat", "age_bins.dat", "all_seds.dat"):
        if not os.path.exists(os.path.join(sed_dir, fn)):
            raise FileNotFoundError(
                f"SED directory {sed_dir!r} must contain "
                "metallicity_bins.dat, age_bins.dat, all_seds.dat "
                "(rt/rt_spectra.f90 format)")
    zs = _read_bins(os.path.join(sed_dir, "metallicity_bins.dat"))
    ages = _read_bins(os.path.join(sed_dir, "age_bins.dat")) * 1e-9  # Gyr
    if ages[0] != 0.0:
        ages[0] = 0.0               # reference zeroes the first bin
    with open(os.path.join(sed_dir, "all_seds.dat"), "rb") as f:
        nls = int(frt.read_ints(f)[0])
        lam = frt.read_reals(f)
        seds = np.empty((nls, len(ages), len(zs)))
        for iz in range(len(zs)):
            for ia in range(len(ages)):
                seds[:, ia, iz] = frt.read_reals(f)
    return SedLibrary(lam_A=lam, ages_gyr=ages, zs=zs, seds=seds)


def write_sed_dir(path: str, lib: SedLibrary) -> None:
    """Write a library in the reference's on-disk format."""
    os.makedirs(path, exist_ok=True)
    for fn, vals in (("metallicity_bins.dat", lib.zs),
                     ("age_bins.dat", lib.ages_gyr * 1e9)):
        with open(os.path.join(path, fn), "w") as f:
            f.write(f"{len(vals):8d}\n")
            for v in vals:
                f.write(f"{v:14.6e}\n")
    with open(os.path.join(path, "all_seds.dat"), "wb") as f:
        frt.write_ints(f, len(lib.lam_A), 0)
        frt.write_record(f, np.asarray(lib.lam_A, dtype=np.float64))
        for iz in range(len(lib.zs)):
            for ia in range(len(lib.ages_gyr)):
                frt.write_record(
                    f, np.asarray(lib.seds[:, ia, iz], dtype=np.float64))


class SedTables:
    """Per-(age, Z) group properties integrated from a SED library."""

    def __init__(self, lib: SedLibrary, bounds_eV: Sequence[float]):
        self.lib = lib
        self.bounds = tuple(float(b) for b in bounds_eV)
        ng = len(self.bounds) - 1
        na, nz = len(lib.ages_gyr), len(lib.zs)
        self.lphot = np.zeros((ng, na, nz))     # photons/s/Msun
        self.egy = np.zeros((ng, na, nz))       # erg
        self.csn = np.zeros((ng, 3, na, nz))    # cm^2
        self.cse = np.zeros((ng, 3, na, nz))
        lam = lib.lam_A
        E_eV = HC_EV_ANG / np.maximum(lam, 1e-30)
        sig = np.stack([cross_section(E_eV, sp) for sp in range(3)])
        for g in range(ng):
            lo, hi = self.bounds[g], self.bounds[g + 1]
            sel = (E_eV >= lo) & (E_eV < hi)
            if sel.sum() < 2:
                continue
            lmg = lam[sel]
            o = np.argsort(lmg)
            lmg = lmg[o]
            sg = sig[:, sel][:, o]
            for ia in range(na):
                for iz in range(nz):
                    J = lib.seds[sel, ia, iz][o] * L_SUN    # erg/s/Å/Msun
                    nph = J * (lmg * ANG) / (H_PLANCK * C_CGS)  # /s/Å
                    lp = np.trapezoid(nph, lmg)
                    le = np.trapezoid(J, lmg)
                    self.lphot[g, ia, iz] = lp
                    self.egy[g, ia, iz] = le / max(lp, 1e-300)
                    for sp in range(3):
                        self.csn[g, sp, ia, iz] = \
                            np.trapezoid(sg[sp] * nph, lmg) / max(lp, 1e-300)
                        self.cse[g, sp, ia, iz] = \
                            np.trapezoid(sg[sp] * J, lmg) / max(le, 1e-300)

    # ------------------------------------------------------------------
    def _weights(self, age_gyr, Z):
        """Bilinear interpolation weights in (log age, log Z), clamped
        to the table edges (``rt_spectra.f90`` inp_SED_table role)."""
        ages = np.maximum(self.lib.ages_gyr, 1e-6)
        zs = np.maximum(self.lib.zs, 1e-10)
        la = np.log10(np.clip(age_gyr, ages[0], ages[-1]))
        lz = np.log10(np.clip(Z, zs[0], zs[-1]))
        ia = np.clip(np.searchsorted(np.log10(ages), la) - 1,
                     0, len(ages) - 2)
        iz = np.clip(np.searchsorted(np.log10(zs), lz) - 1,
                     0, max(len(zs) - 2, 0))
        da = np.log10(ages)
        wa = np.clip((la - da[ia]) / np.maximum(da[ia + 1] - da[ia],
                                                1e-30), 0.0, 1.0)
        if len(zs) > 1:
            dz = np.log10(zs)
            wz = np.clip((lz - dz[iz]) / np.maximum(dz[iz + 1] - dz[iz],
                                                    1e-30), 0.0, 1.0)
        else:
            wz = np.zeros_like(lz)
            iz = np.zeros_like(ia)
        return ia, iz, wa, wz

    def _interp(self, tbl, ia, iz, wa, wz):
        """tbl[..., nA, nZ] bilinear at per-star (ia, iz, wa, wz)."""
        iz1 = np.minimum(iz + 1, tbl.shape[-1] - 1)
        t00 = tbl[..., ia, iz]
        t10 = tbl[..., ia + 1, iz]
        t01 = tbl[..., ia, iz1]
        t11 = tbl[..., ia + 1, iz1]
        return ((1 - wa) * (1 - wz) * t00 + wa * (1 - wz) * t10
                + (1 - wa) * wz * t01 + wa * wz * t11)

    def star_rates(self, age_gyr, Z, m_sun) -> np.ndarray:
        """Per-star per-group photon emission rates [nstar, ng]
        (photons/s): m★ · lphot(age, Z)."""
        ia, iz, wa, wz = self._weights(np.asarray(age_gyr),
                                       np.asarray(Z))
        lp = self._interp(self.lphot, ia, iz, wa, wz)    # [ng, nstar]
        return (lp * np.asarray(m_sun)[None, :]).T

    def population_groups(self, age_gyr, Z, m_sun) -> Tuple[Group3, ...]:
        """Photon-rate-weighted group properties of a star population —
        the quantities the chemistry consumes, refreshed at the
        ``sedprops_update`` cadence (``update_SED_group_props``)."""
        ia, iz, wa, wz = self._weights(np.asarray(age_gyr),
                                       np.asarray(Z))
        m = np.asarray(m_sun)
        lp = self._interp(self.lphot, ia, iz, wa, wz) * m    # [ng, ns]
        w = lp / np.maximum(lp.sum(axis=1, keepdims=True), 1e-300)
        egy = (self._interp(self.egy, ia, iz, wa, wz) * w).sum(axis=1)
        csn = (self._interp(self.csn, ia, iz, wa, wz)
               * w[:, None, :]).sum(axis=2)                  # [ng, 3]
        cse = (self._interp(self.cse, ia, iz, wa, wz)
               * w[:, None, :]).sum(axis=2)
        tot = lp.sum(axis=1)
        frac = tot / max(tot.sum(), 1e-300)
        return tuple(
            Group3(e_lo=self.bounds[g], e_hi=self.bounds[g + 1],
                   e_photon=float(egy[g]),
                   sigmaN=tuple(float(v) for v in csn[g]),
                   sigmaE=tuple(float(v) for v in cse[g]),
                   frac=float(frac[g]))
            for g in range(len(self.bounds) - 1))


def blackbody_library(t_of_age, ages_gyr, zs,
                      lam_A=None) -> SedLibrary:
    """Synthetic library helper: a blackbody whose temperature follows
    ``t_of_age(age_gyr)`` (tests; also a usable stand-in when no
    tabulated library ships with a run)."""
    if lam_A is None:
        lam_A = np.geomspace(100.0, 3000.0, 400)
    seds = np.zeros((len(lam_A), len(ages_gyr), len(zs)))
    lam_cm = lam_A * ANG
    for ia, age in enumerate(ages_gyr):
        T = float(t_of_age(age))
        from ramses_tpu.units import kB as KB
        x = np.clip(H_PLANCK * C_CGS / (lam_cm * KB * T), 1e-8, 600.0)
        blam = 1.0 / (lam_cm ** 5 * np.expm1(x))
        blam = blam / max(blam.max(), 1e-300)
        for iz in range(len(zs)):
            seds[:, ia, iz] = blam * (1.0 + 0.1 * iz)
    return SedLibrary(lam_A=np.asarray(lam_A),
                      ages_gyr=np.asarray(ages_gyr),
                      zs=np.asarray(zs), seds=seds)
