"""Label-based wallclock timers (``timer_m``, ``amr/update_time.f90:38-56``).

Same zero-overhead design as the reference: exactly one label is active;
switching to a new label accumulates the elapsed time on the previous
one.  ``output_timer`` prints the per-label breakdown and the fraction of
total — the reference's per-dump report (``:77-180``).  For deep kernel
profiles use ``jax.profiler`` (wired in ``profile_trace``); these timers
give the host-side phase accounting.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Optional


class Timers:
    def __init__(self, sync=None):
        self.acc: Dict[str, float] = {}
        self.count: Dict[str, int] = {}
        self._label: Optional[str] = None
        self._t0 = 0.0
        # Optional device-drain callable invoked at every label switch.
        # Async dispatch misattributes device time to whichever section
        # happens to block next; with ``sync`` set, each section pays for
        # exactly the work it enqueued (use for instrumented runs only —
        # draining costs a device round-trip per switch).
        self.sync = sync

    def timer(self, label: str):
        """Switch the active label (accumulates the previous one)."""
        if self.sync is not None and self._label is not None:
            self.sync()
        now = time.perf_counter()
        if self._label is not None:
            self.acc[self._label] = self.acc.get(self._label, 0.0) \
                + (now - self._t0)
            self.count[self._label] = self.count.get(self._label, 0) + 1
        self._label = label if label != "stop" else None
        self._t0 = now

    def stop(self):
        self.timer("stop")

    def snapshot(self) -> Dict[str, float]:
        """Accumulated seconds per label, including the still-running
        portion of the active label, without switching labels.  The
        telemetry recorder diffs consecutive snapshots to attribute
        wallclock to phases per record."""
        out = dict(self.acc)
        if self._label is not None:
            out[self._label] = out.get(self._label, 0.0) \
                + (time.perf_counter() - self._t0)
        return out

    @contextlib.contextmanager
    def section(self, label: str):
        prev = self._label
        self.timer(label)
        try:
            yield
        finally:
            self.timer(prev if prev is not None else "stop")

    def output_timer(self, file=None) -> str:
        """Per-label breakdown (``output_timer``, min/avg/max collapse to
        one host here; the sharded runs are single-controller)."""
        self.stop()
        total = sum(self.acc.values()) or 1.0
        lines = ["   --------------------------------------------------",
                 "   TIMER      %        time     calls   label",
                 "   --------------------------------------------------"]
        for lbl, t in sorted(self.acc.items(), key=lambda kv: -kv[1]):
            lines.append(f"   {100 * t / total:6.1f}   {t:10.3f}  "
                         f"{self.count.get(lbl, 0):8d}   {lbl}")
        lines.append(f"   total: {total:.3f} s")
        out = "\n".join(lines)
        if file is not None:
            print(out, file=file)
        return out


class NullTimers(Timers):
    """Zero-cost stand-in for un-instrumented runs.

    The reference's timers are compiled in unconditionally; here a run
    without telemetry must pay NOTHING — no ``perf_counter`` calls, no
    label switches (the telemetry subsystem's zero-overhead-off
    contract).  Drivers swap in a real :class:`Timers` only when
    telemetry (or an explicit instrumentation pass, e.g. bench.py's
    ``Timers(sync=sim.drain)``) asks for it.
    """

    def timer(self, label: str):
        pass

    @contextlib.contextmanager
    def section(self, label: str):
        yield

    def snapshot(self) -> Dict[str, float]:
        return {}


GLOBAL = Timers()
timer = GLOBAL.timer
section = GLOBAL.section
output_timer = GLOBAL.output_timer


@contextlib.contextmanager
def profile_trace(logdir: str):
    """jax.profiler wrapper: structured device traces (the observability
    the reference lacks, SURVEY.md §5.1)."""
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
