"""On-the-fly movie frames: projections and slices.

The movie engine (``amr/movie.f90:5-1169``): per-output 2D maps of
density/pressure/velocity etc. along a camera axis, written as simple
binary frame files.  Maps are device reductions (sum/mean/max along the
projection axis — a ``segment_mean`` in the AMR case); frame files carry
the reference's layout: time + bounds header, [nw, nh], float32 data.
"""

from __future__ import annotations

import os
from typing import Sequence

import jax.numpy as jnp
import numpy as np

from ramses_tpu.io import fortran as frt


def project(field, axis: int, kind: str = "mean", weights=None,
            vmin=None, vmax=None):
    """2D map from a dense 3D (or 2D) field: mean|sum|max|min|slice
    along ``axis`` (the reference movie shaders); mass-weighted mean
    when ``weights`` given.  ``vmin``/``vmax``: cells whose value
    falls outside the range are excluded from the projection
    (``varmin_frame``/``varmax_frame``, ``amr/movie.f90:456``)."""
    field = jnp.asarray(field)
    if field.ndim == 2:
        return field
    mask = None
    if vmin is not None or vmax is not None:
        mask = jnp.ones_like(field, dtype=bool)
        if vmin is not None:
            mask = mask & (field >= vmin)
        if vmax is not None:
            mask = mask & (field <= vmax)
    if kind == "slice":
        idx = [slice(None)] * field.ndim
        idx[axis] = field.shape[axis] // 2
        f = field if mask is None else field * mask  # excluded -> 0
        return f[tuple(idx)]
    if kind == "sum":
        f = field if mask is None else field * mask
        return jnp.sum(f, axis=axis)
    if kind == "max":
        f = field if mask is None else jnp.where(mask, field, -jnp.inf)
        return jnp.max(f, axis=axis)
    if kind == "min":
        f = field if mask is None else jnp.where(mask, field, jnp.inf)
        return jnp.min(f, axis=axis)
    w = (jnp.asarray(weights) if weights is not None
         else jnp.ones_like(field))
    if mask is not None:
        w = w * mask
    return (jnp.sum(field * w, axis=axis)
            / jnp.maximum(jnp.sum(w, axis=axis), 1e-300))


def smooth2d(m: np.ndarray, sigma_px: float) -> np.ndarray:
    """Separable Gaussian blur of a 2D map (``smooth_frame``: the
    reference widens each leaf's deposition footprint; blurring the
    finished map by the same scale is the dense-grid equivalent)."""
    if sigma_px <= 0.0:
        return m
    r = max(int(3.0 * sigma_px), 1)
    x = np.arange(-r, r + 1)
    k = np.exp(-0.5 * (x / sigma_px) ** 2)
    k /= k.sum()
    out = np.apply_along_axis(
        lambda a: np.convolve(np.pad(a, r, mode="edge"), k,
                              mode="valid"), 0, np.asarray(m))
    return np.apply_along_axis(
        lambda a: np.convolve(np.pad(a, r, mode="edge"), k,
                              mode="valid"), 1, out)


#: .map layout revision appended to the header record.  Version 1
#: frames pin the shape-record convention (first int = fastest-varying
#: extent, data written ``arr.T.ravel()``); frames without the tag
#: (version 0, 5-double header) predate the pin — a non-square
#: version-0 frame is orientation-ambiguous (see docs/io.md).
MAP_FORMAT_VERSION = 1


def write_frame(path: str, data, t: float = 0.0,
                bounds: Sequence[float] = (0, 1, 0, 1)) -> None:
    """Binary frame file (``output_frame`` map layout): record [t, xmin,
    xmax, ymin, ymax, version], record [nw, nh], record float32 data.
    The trailing version double is ours; the reference's 5-double
    header readers (``utils/py/map2img.py`` reads by index) skip it."""
    arr = np.asarray(data, dtype=np.float32)
    with open(path, "wb") as f:
        frt.write_record(f, np.asarray(
            [t, *bounds, float(MAP_FORMAT_VERSION)], dtype=np.float64))
        # the reference layout is Fortran column-major: the first int
        # is the FASTEST-varying extent (utils/py/map2img.py reads
        # reshape(ny, nx)); arr.T.ravel() puts axis 0 fastest, so the
        # shape record is arr.shape, NOT reversed (square movie frames
        # used to hide the distinction)
        frt.write_record(f, np.asarray(arr.shape, dtype=np.int32))
        frt.write_record(f, arr.T.ravel())


def read_frame(path: str):
    """Parse a ``.map`` frame.  ``version`` is 0 for pre-tag frames
    (whose non-square maps are orientation-ambiguous — the writer's
    shape convention was pinned with the tag); the data record length
    is checked against nw*nh so a truncated or shape-corrupt frame
    fails loudly instead of reshaping garbage."""
    with open(path, "rb") as f:
        head = frt.read_reals(f)
        version = int(head[5]) if len(head) > 5 else 0
        nw, nh = frt.read_ints(f)
        data = frt.read_array(f, np.float32)
        if data.size != int(nw) * int(nh):
            raise ValueError(
                f"{path}: data record holds {data.size} floats but the "
                f"shape record says nw*nh = {int(nw) * int(nh)} "
                f"({int(nw)}x{int(nh)}) — truncated or corrupt frame")
        data = data.reshape(nh, nw).T
    return dict(t=head[0], bounds=tuple(head[1:5]), data=data,
                version=version)


class Camera:
    """One movie camera (&MOVIE_PARAMS per-NMOV entry): projection
    axis, shader kind, and an optional zoom window given as BOX
    FRACTIONS in [0, 1] (``xcentre_frame``/``deltax_frame`` of
    ``amr/movie.f90`` divided by boxlen) — boxlen-independent, so the
    default covers the whole grid for any box size."""

    def __init__(self, axis: int = 2, kind: str = "mean",
                 center=(0.5, 0.5, 0.5), delta=(1.0, 1.0, 1.0),
                 varmin=None, varmax=None, smooth: float = 0.0):
        self.axis = axis
        self.kind = kind
        self.center = tuple(center)
        self.delta = tuple(delta)
        self.varmin = varmin          # per-camera value range
        self.varmax = varmax          # (varmin/varmax_frame)
        self.smooth = float(smooth)   # smooth_frame, in pixels

    def window(self, n: int, d: int):
        """[i0, i1) cell range of this camera's zoom along dim d."""
        lo = self.center[d] - 0.5 * self.delta[d]
        hi = self.center[d] + 0.5 * self.delta[d]
        i0 = max(int(round(lo * n)), 0)
        i1 = min(max(int(round(hi * n)), i0 + 1), n)
        return i0, i1


def _extract_field(u, name: str, cfg, ndim: int):
    if name == "density":
        return u[0]
    if name.startswith("velocity_"):
        d = "xyz".index(name[-1])
        return u[1 + d] / np.maximum(u[0], 1e-300)
    if name == "pressure":
        ek = sum(u[1 + d] ** 2 for d in range(ndim)) \
            / (2 * np.maximum(u[0], 1e-300))
        return (cfg.gamma - 1.0) * (u[1 + ndim] - ek)
    if name == "temperature":
        ek = sum(u[1 + d] ** 2 for d in range(ndim)) \
            / (2 * np.maximum(u[0], 1e-300))
        return ((cfg.gamma - 1.0) * (u[1 + ndim] - ek)
                / np.maximum(u[0], 1e-300))
    if name == "speed":
        return np.sqrt(sum(u[1 + d] ** 2 for d in range(ndim))) \
            / np.maximum(u[0], 1e-300)
    if name in ("metallicity", "var"):
        # first passive scalar as a mass fraction (i_mv_metallicity /
        # i_mv_var, movie.f90:736-745); loud when the run carries none
        ip = 2 + ndim + getattr(cfg, "nener", 0)
        if u.shape[0] <= ip:
            raise ValueError(
                f"movie field {name!r} needs a passive scalar "
                "(npassive/metals); this run has none")
        return u[ip] / np.maximum(u[0], 1e-300)
    raise ValueError(f"unknown movie field {name!r}")


PART_FIELDS = ("dm", "stars", "lum")   # particle-deposition shaders
AUX_FIELDS = ("xhi", "xhii", "xheii", "xheiii")  # RT ion fractions


class MovieWriter:
    """Multi-camera frame emission (the &MOVIE_PARAMS NMOV cameras:
    one ``movieN/`` directory per camera like ``amr/movie.f90``'s
    proj_axis string, each with its own axis/shader/zoom)."""

    def __init__(self, outdir: str, axis: int = 2, kind: str = "mean",
                 fields: Sequence[str] = ("density",), cameras=None,
                 extent=(1.0, 1.0, 1.0)):
        self.outdir = outdir
        self.fields = list(fields)
        self.cameras = (list(cameras) if cameras
                        else [Camera(axis=axis, kind=kind)])
        self._extent = tuple(extent)   # per-dim box extents (user units)
        self.iframe = 0
        for i in range(len(self.cameras)):
            os.makedirs(self._camdir(i), exist_ok=True)

    def _camdir(self, i: int) -> str:
        if len(self.cameras) == 1:
            return self.outdir
        return os.path.join(self.outdir, f"movie{i + 1}")

    def _part_map(self, name, parts, cam, ndim, shape, axis):
        """Particle-deposition shader: surface density of DM / stars /
        stellar "luminosity" on the camera plane (``movie.f90:884-894``
        i_mv_dm/stars/lum).  ``lum`` weights stars by the SED tables'
        photon rates when the run carries them, else by mass."""
        from ramses_tpu.pm.particles import FAM_DM, FAM_STAR
        x, m, fam, lumw = parts
        if name == "dm":
            sel = fam == FAM_DM
            w = m[sel]
        elif name == "stars":
            sel = fam == FAM_STAR
            w = m[sel]
        else:                          # lum
            sel = fam == FAM_STAR
            w = (lumw[sel] if lumw is not None else m[sel])
        ax2 = [d for d in range(ndim) if d != axis][:2]
        edges, sels = [], np.ones(int(sel.sum()), dtype=bool)
        xs = x[sel]
        if ndim == 3:
            # the camera slab also bounds the projection DEPTH (the
            # gas path crops along cam.axis; particles must match)
            na = shape[axis]
            i0, i1 = cam.window(na, axis)
            xa = xs[:, axis] / self._extent[axis]
            sels &= (xa >= i0 / na) & (xa < i1 / na)
        for d in ax2:
            nd_ = shape[d]
            i0, i1 = cam.window(nd_, d)
            lo, hi = i0 / nd_, i1 / nd_
            xd = xs[:, d] / self._extent[d]
            sels &= (xd >= lo) & (xd < hi)
            edges.append(np.linspace(lo, hi, (i1 - i0) + 1))
        pts = [xs[sels][:, d] / self._extent[d] for d in ax2]
        h, _ = np.histogramdd(np.stack(pts, axis=1) if pts else
                              np.zeros((0, 2)),
                              bins=edges, weights=w[sels])
        px = np.diff(edges[0])[0] * np.diff(edges[1])[0] \
            if len(edges) == 2 else 1.0
        return h / max(px, 1e-300)

    def _emit_dense(self, u, cfg, t: float, parts=None,
                    aux=None) -> list:
        ndim = u.ndim - 1
        n = u.shape[1]
        paths = []
        for ic, cam in enumerate(self.cameras):
            # zoom: crop the camera window before projecting
            idx = [slice(None)]
            for d in range(ndim):
                i0, i1 = cam.window(u.shape[1 + d], d)
                idx.append(slice(i0, i1))
            uc = u[tuple(idx)]
            axis = cam.axis if ndim == 3 else 0
            for name in self.fields:
                if name in PART_FIELDS:
                    if parts is None:
                        continue       # no particles in this run
                    m = self._part_map(name, parts, cam, ndim,
                                       u.shape[1:], axis)
                elif aux is not None and name in aux:
                    field = aux[name][tuple(idx[1:])]
                    m = project(field, axis, cam.kind,
                                weights=(uc[0] if cam.kind == "mean"
                                         else None),
                                vmin=cam.varmin, vmax=cam.varmax)
                elif name in AUX_FIELDS:
                    continue           # RT not active in this run
                else:
                    field = _extract_field(uc, name, cfg, ndim)
                    m = project(field, axis, cam.kind,
                                weights=(uc[0] if cam.kind == "mean"
                                         else None),
                                vmin=cam.varmin, vmax=cam.varmax)
                m = smooth2d(np.asarray(m), cam.smooth)
                path = os.path.join(
                    self._camdir(ic), f"{name}_{self.iframe:05d}.map")
                ax2 = [d for d in range(ndim) if d != axis][:2]
                bnd = []
                for d in ax2:
                    i0, i1 = cam.window(n, d)
                    bnd += [i0 / n, i1 / n]
                bnd += [0.0] * (4 - len(bnd))
                write_frame(path, np.asarray(m), t=t, bounds=bnd)
                paths.append(path)
        self.iframe += 1
        return paths

    @classmethod
    def from_params(cls, params, outdir=None):
        """(writer, imov interval) from &MOVIE_PARAMS, or (None, 0)
        when ``movie=.false.``.  ``proj_axis`` is the reference's
        one-char-per-camera string; ``{x,y,z}centre_frame`` /
        ``delta{x,y,z}_frame`` give per-camera zoom windows in code
        units (converted to box fractions here); ``movie_vars_txt``
        the emitted fields; ``imov`` the coarse-step cadence."""
        raw = params.raw.get("movie_params", {}) if params.raw else {}

        def g(k, dflt):
            v = raw.get(k, dflt)
            return v[0] if isinstance(v, list) and not isinstance(
                dflt, list) else v

        if not raw or not bool(g("movie", False)):
            return None, 0
        boxlen = float(params.amr.boxlen)
        # per-axis extents: non-cubic base grids (nx/ny/nz coarse
        # cells at boxlen/2^lmin per cell each) span base_d * boxlen
        base = [params.amr.nx, params.amr.ny, params.amr.nz]
        extent = [boxlen * max(int(b), 1) for b in base]
        axes = str(g("proj_axis", "z")).strip("'\" ")
        kind = str(g("shader", "mean")).strip("'\" ")
        fields = g("movie_vars_txt", ["density"])
        if isinstance(fields, str):
            fields = [fields]
        fields = [str(f).strip("'\" ") for f in fields]

        def per_cam(key, dflt, i):
            v = raw.get(key, dflt)
            if isinstance(v, list):
                return float(v[i]) if i < len(v) else float(dflt)
            return float(v)

        cams = []
        for i, ch in enumerate(axes):
            center = tuple(
                per_cam(f"{c}centre_frame", extent[d] / 2, i) / extent[d]
                for d, c in enumerate("xyz"))
            delta = tuple(
                per_cam(f"delta{c}_frame", extent[d], i) / extent[d]
                for d, c in enumerate("xyz"))
            def pick(key):
                v = raw.get(key)
                if v is None:
                    return None
                if isinstance(v, list):
                    return float(v[i]) if i < len(v) else None
                return float(v)

            cams.append(Camera(axis="xyz".index(ch), kind=kind,
                               center=center, delta=delta,
                               varmin=pick("varmin_frame"),
                               varmax=pick("varmax_frame"),
                               smooth=pick("smooth_frame") or 0.0))
        out = outdir or os.path.join(
            str(params.output.output_dir), "movie")
        return (cls(out, fields=fields, cameras=cams, extent=extent),
                max(1, int(g("imov", 1))))

    def emit(self, sim) -> list:
        """Write one frame set from a uniform Simulation-like object
        (needs only ``.state.u``/``.state.t`` — or ``.u``/``.t`` —
        and ``.cfg``)."""
        u = np.asarray(sim.state.u if hasattr(sim, "state") else sim.u)
        t = float(sim.state.t if hasattr(sim, "state") else sim.t)
        ps = getattr(getattr(sim, "state", sim), "p", None)
        parts = None
        if ps is not None:
            act = np.asarray(ps.active)
            parts = (np.asarray(ps.x)[act], np.asarray(ps.m)[act],
                     np.asarray(ps.family)[act], None)
        return self._emit_dense(u, sim.cfg, t, parts=parts)

    def emit_amr(self, sim) -> list:
        """Write one frame set from a live :class:`AmrSim`: leaves are
        block-filled onto the finest-level dense grid (vectorized for
        the dominant finest-level leaves), then each camera projects
        its window (``amr/movie.f90`` leaf walk)."""
        from ramses_tpu.utils.gridfill import leaves_to_dense

        lmax_used = max(sim.levels())
        rt = getattr(sim, "rt_amr", None)
        want_aux = rt is not None and any(f in AUX_FIELDS
                                          for f in self.fields)
        pos, lvls, vals = [], [], []
        for l in sim.levels():
            xc, uvals = sim.leaf_sample(l)
            if len(xc):
                pos.append(xc)
                lvls.append(np.full(len(xc), l))
                uv = np.asarray(uvals, dtype=np.float64)
                if want_aux:
                    m = sim.maps[l]
                    leaf = ~sim.tree.refined_mask(l)
                    nc = m.noct * 2 ** sim.cfg.ndim
                    xi = np.asarray(rt.xion[l])[:nc][leaf][:, None]
                    if rt.full3:
                        xhe = np.asarray(rt.xhe[l])[:nc][leaf]
                    else:
                        xhe = np.zeros((len(xi), 2))
                    uv = np.concatenate([uv, xi, xhe], axis=1)
                vals.append(uv)
        dense = leaves_to_dense(np.concatenate(pos),
                                np.concatenate(lvls),
                                np.concatenate(vals), lmax_used,
                                float(sim.boxlen))
        aux = None
        if want_aux:
            nvar = sim.cfg.nvar
            xhii, xheii, xheiii = dense[nvar], dense[nvar + 1], \
                dense[nvar + 2]
            aux = {"xhii": xhii, "xhi": 1.0 - xhii,
                   "xheii": xheii, "xheiii": xheiii}
            dense = dense[:nvar]
        parts = None
        if sim.p is not None and any(f in PART_FIELDS
                                     for f in self.fields):
            act = np.asarray(sim.p.active)
            lumw = None
            if "lum" in self.fields and rt is not None \
                    and getattr(rt, "sed", None) is not None:
                from ramses_tpu.pm.particles import FAM_STAR
                from ramses_tpu.pm.star_formation import M_SUN
                un = rt.un
                GYR = 3.15576e16
                # SED rates only for the stars; other lanes carry 0
                fam = np.asarray(sim.p.family)
                stars = act & (fam == FAM_STAR)
                age = np.maximum((sim.t - np.asarray(sim.p.tp)[stars])
                                 * un.scale_t / GYR, 0.0)
                msun = np.asarray(sim.p.m)[stars] * un.scale_d \
                    * un.scale_l ** sim.cfg.ndim / M_SUN
                lumw_all = np.zeros(len(fam))
                lumw_all[stars] = rt.sed.star_rates(
                    age, np.asarray(sim.p.zp)[stars], msun).sum(axis=1)
                lumw = lumw_all[act]
            parts = (np.asarray(sim.p.x)[act], np.asarray(sim.p.m)[act],
                     np.asarray(sim.p.family)[act], lumw)
        return self._emit_dense(dense, sim.cfg, float(sim.t),
                                parts=parts, aux=aux)
