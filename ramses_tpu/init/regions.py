"""Region-based analytic initial conditions.

Re-implements ``region_condinit`` (``hydro/init_flow_fine.f90:475-596``) and
the primitive→conservative conversion of ``condinit``
(``hydro/condinit.f90:30-75``) as vectorized numpy/JAX ops over the whole
grid instead of nvector cell batches.

Region semantics (&INIT_PARAMS):
  * ``square``: p-norm box test with exponent ``exp_region`` (>=10 → max
    norm); REPLACES primitives inside.
  * ``point``: CIC cloud of one cell around the centre; ADDS d/P scaled by
    1/cell-volume and velocities weighted by the CIC kernel.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ramses_tpu.config import Params
from ramses_tpu.hydro.core import HydroStatic


def cell_centers(shape: Sequence[int], dx: float, ndim: int):
    """Cell-centre coordinate arrays in user units [0, boxlen]."""
    axes = [(np.arange(n) + 0.5) * dx for n in shape]
    return np.meshgrid(*axes, indexing="ij")[:ndim]


def region_condinit(x: Sequence[np.ndarray], dx: float, p: Params,
                    cfg: HydroStatic) -> np.ndarray:
    """Primitive state [nvar, *shape] from &INIT_PARAMS regions (or the
    installed patch's ``condinit`` hook, which replaces it wholesale —
    the ``hydro/condinit.f90`` shadowing point)."""
    from ramses_tpu import patch
    hk = patch.hook("condinit")
    if hk is not None:
        return np.asarray(hk(x, dx, p, cfg))
    init = p.init
    shape = x[0].shape
    q = np.zeros((cfg.nvar,) + shape, dtype=np.float64)
    q[0] = cfg.smallr
    q[cfg.ndim + 1] = cfg.smallr * cfg.smallc ** 2 / cfg.gamma

    centers = [init.x_center, init.y_center, init.z_center]
    lengths = [init.length_x, init.length_y, init.length_z]
    vels = [init.u_region, init.v_region, init.w_region]

    for k in range(init.nregion):
        rtype = str(init.region_type[k]).strip()
        if rtype == "square":
            en = float(init.exp_region[k])
            if en < 10.0:
                r = sum((2.0 * np.abs(x[d] - centers[d][k]) /
                         lengths[d][k]) ** en for d in range(cfg.ndim))
                r = r ** (1.0 / en)
            else:
                r = np.maximum.reduce(
                    [2.0 * np.abs(x[d] - centers[d][k]) / lengths[d][k]
                     for d in range(cfg.ndim)])
            inside = r < 1.0
            q[0][inside] = init.d_region[k]
            for d in range(cfg.ndim):
                q[1 + d][inside] = vels[d][k]
            q[cfg.ndim + 1][inside] = init.p_region[k]
        elif rtype == "point":
            vol = dx ** cfg.ndim
            w = np.ones(shape)
            for d in range(cfg.ndim):
                w = w * np.maximum(1.0 - np.abs(x[d] - centers[d][k]) / dx,
                                   0.0)
            q[0] += init.d_region[k] * w / vol
            for d in range(cfg.ndim):
                q[1 + d] += vels[d][k] * w
            q[cfg.ndim + 1] += init.p_region[k] * w / vol
        else:
            raise ValueError(f"unknown region_type {rtype!r}")
    return q


def prim_to_cons(q: np.ndarray, cfg: HydroStatic) -> np.ndarray:
    """``condinit``'s primitive→conservative conversion."""
    u = np.empty_like(q)
    u[0] = q[0]
    eken = np.zeros_like(q[0])
    for d in range(cfg.ndim):
        u[1 + d] = q[0] * q[1 + d]
        eken += 0.5 * q[0] * q[1 + d] ** 2
    u[cfg.ndim + 1] = eken + q[cfg.ndim + 1] / (cfg.gamma - 1.0)
    for n in range(cfg.nener):
        i = cfg.ndim + 2 + n
        u[i] = q[i] / (cfg.gamma_rad[n] - 1.0)
        u[cfg.ndim + 1] += u[i]
    for s in range(cfg.npassive):
        i = cfg.ndim + 2 + cfg.nener + s
        u[i] = q[0] * q[i]
    return u


def condinit(shape: Sequence[int], dx: float, p: Params,
             cfg: HydroStatic) -> np.ndarray:
    """Conservative initial state on a uniform grid of ``shape`` cells."""
    x = cell_centers(shape, dx, cfg.ndim)
    return prim_to_cons(region_condinit(x, dx, p, cfg), cfg)
