"""Approximate Riemann solvers (vectorized, jit-safe).

Re-implements the reference's five solvers (``hydro/godunov_utils.f90``:
``riemann_approx:268``, ``riemann_acoustic:500``, ``riemann_llf:660``,
``riemann_hll:825``, ``riemann_hllc:988``) as pure elementwise JAX ops.
Where the Fortran compresses lanes and branches per cell, we compute all
branches and select with ``jnp.where`` — the XLA-native formulation.

Interface component layout (axis 0), for both inputs and the flux:
    0: rho | 1: normal velocity | 2: pressure | 3..1+ndim: tangential
    velocities | then nener non-thermal pressures | then passive scalars.
Flux output has one extra trailing component: the internal-energy flux
(used by the dual-energy ``pressure_fix``, ``hydro/godunov_fine.f90`` tmp).
Flux layout: 0 mass, 1 normal momentum, 2 total energy, 3.. tangential
momenta / non-thermal energy fluxes / passive fluxes, [-1] internal energy.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ramses_tpu.hydro.core import HydroStatic


def _prims(q, cfg: HydroStatic):
    """Floor density/pressure exactly as the reference does."""
    r = jnp.maximum(q[0], cfg.smallr)
    u = q[1]
    p = jnp.maximum(q[2], r * cfg.smallp)
    return r, u, p


def _etot(q, r, u, p, cfg: HydroStatic):
    """Total energy density from interface-layout primitives."""
    entho = 1.0 / (cfg.gamma - 1.0)
    e = p * entho + 0.5 * r * u * u
    for t in range(cfg.ndim - 1):
        e = e + 0.5 * r * q[3 + t] ** 2
    for n in range(cfg.nener):
        e = e + q[2 + cfg.ndim + n] / (cfg.gamma_rad[n] - 1.0)
    return e

def _ptot(q, p, cfg: HydroStatic):
    for n in range(cfg.nener):
        p = p + q[2 + cfg.ndim + n]
    return p


def _cspeed2(q, r, p, cfg: HydroStatic):
    """gamma*P (+ sum gamma_rad*Prad) / rho — squared signal speed."""
    c2 = cfg.gamma * p
    for n in range(cfg.nener):
        c2 = c2 + cfg.gamma_rad[n] * q[2 + cfg.ndim + n]
    return jnp.maximum(c2 / r, cfg.smallc ** 2)


def _cons_and_flux(q, cfg: HydroStatic):
    """Conservative state + physical flux in interface layout (+eint slot).

    Mirrors riemann_llf's uleft/fleft construction
    (``hydro/godunov_utils.f90:718-810``).
    """
    entho = 1.0 / (cfg.gamma - 1.0)
    r, u, p = _prims(q, cfg)
    et = _etot(q, r, u, p, cfg)
    ucons = [r, r * u, et]
    for t in range(cfg.ndim - 1):
        ucons.append(r * q[3 + t])
    for n in range(cfg.nener):
        ucons.append(q[2 + cfg.ndim + n] / (cfg.gamma_rad[n] - 1.0))
    for s in range(cfg.npassive):
        ucons.append(r * q[2 + cfg.ndim + cfg.nener + s])
    ucons.append(p * entho)  # internal energy slot
    ucons = jnp.stack(ucons)

    ptot = _ptot(q, p, cfg)
    fl = [r * u, r * u * u + ptot, u * (et + ptot)]
    for t in range(cfg.ndim - 1):
        fl.append(u * r * q[3 + t])
    for n in range(cfg.nener):
        fl.append(u * q[2 + cfg.ndim + n] / (cfg.gamma_rad[n] - 1.0))
    for s in range(cfg.npassive):
        fl.append(u * r * q[2 + cfg.ndim + cfg.nener + s])
    fl.append(u * p * entho)
    return ucons, jnp.stack(fl)


def riemann_llf(ql, qr, cfg: HydroStatic):
    """Local Lax-Friedrichs (``riemann_llf``, godunov_utils.f90:660)."""
    rl, ul, pl = _prims(ql, cfg)
    rr, ur, pr = _prims(qr, cfg)
    cl = jnp.sqrt(_cspeed2(ql, rl, pl, cfg))
    cr = jnp.sqrt(_cspeed2(qr, rr, pr, cfg))
    cmax = jnp.maximum(jnp.abs(ul) + cl, jnp.abs(ur) + cr)
    uleft, fleft = _cons_and_flux(ql, cfg)
    uright, fright = _cons_and_flux(qr, cfg)
    return 0.5 * (fleft + fright - cmax[None] * (uright - uleft))


def riemann_hll(ql, qr, cfg: HydroStatic):
    """HLL (``riemann_hll``, godunov_utils.f90:825)."""
    rl, ul, pl = _prims(ql, cfg)
    rr, ur, pr = _prims(qr, cfg)
    cl = jnp.sqrt(_cspeed2(ql, rl, pl, cfg))
    cr = jnp.sqrt(_cspeed2(qr, rr, pr, cfg))
    sl = jnp.minimum(jnp.minimum(ul, ur) - jnp.maximum(cl, cr), 0.0)
    sr = jnp.maximum(jnp.maximum(ul, ur) + jnp.maximum(cl, cr), 0.0)
    uleft, fleft = _cons_and_flux(ql, cfg)
    uright, fright = _cons_and_flux(qr, cfg)
    return (sr * fleft - sl * fright + sr * sl * (uright - uleft)) / (sr - sl)


def riemann_hllc(ql, qr, cfg: HydroStatic):
    """HLLC with Toro sampling (``riemann_hllc``, godunov_utils.f90:988)."""
    entho = 1.0 / (cfg.gamma - 1.0)
    rl, ul, pl = _prims(ql, cfg)
    rr, ur, pr = _prims(qr, cfg)
    el = pl * entho
    er = pr * entho
    etotl = _etot(ql, rl, ul, pl, cfg)
    etotr = _etot(qr, rr, ur, pr, cfg)
    ptotl = _ptot(ql, pl, cfg)
    ptotr = _ptot(qr, pr, cfg)
    cfastl = jnp.sqrt(_cspeed2(ql, rl, pl, cfg))
    cfastr = jnp.sqrt(_cspeed2(qr, rr, pr, cfg))

    SL = jnp.minimum(ul, ur) - jnp.maximum(cfastl, cfastr)
    SR = jnp.maximum(ul, ur) + jnp.maximum(cfastl, cfastr)
    rcl = rl * (ul - SL)
    rcr = rr * (SR - ur)
    ustar = (rcr * ur + rcl * ul + (ptotl - ptotr)) / (rcr + rcl)
    ptotstar = (rcr * ptotl + rcl * ptotr + rcl * rcr * (ul - ur)) / (rcr + rcl)

    # Gradient-safe star-state denominators.  sel() consumes the *L state
    # only when SL <= 0 < ustar (so SL - ustar < 0 strictly) and the *R
    # state only when ustar <= 0 < SR (so SR - ustar > 0 strictly), but an
    # exactly degenerate wave (ustar == SL or ustar == SR) puts an inf in
    # the *untaken* branch and reverse-mode where() turns the inf * 0
    # cotangent product into NaN.  Substitute a finite dummy denominator
    # wherever the branch is provably unconsumed; consumed values keep the
    # original denominator bit-for-bit, so the forward pass is unchanged.
    dSL = SL - ustar
    dSL = jnp.where(dSL < 0.0, dSL, -1.0)
    dSR = SR - ustar
    dSR = jnp.where(dSR > 0.0, dSR, 1.0)
    rstarl = rl * (SL - ul) / dSL
    etotstarl = ((SL - ul) * etotl - ptotl * ul + ptotstar * ustar) / dSL
    estarl = el * (SL - ul) / dSL
    rstarr = rr * (SR - ur) / dSR
    etotstarr = ((SR - ur) * etotr - ptotr * ur + ptotstar * ustar) / dSR
    estarr = er * (SR - ur) / dSR

    # sample at x/t = 0: SL>0 → L | ustar>0 → *L | SR>0 → *R | else R
    def sel(a_l, a_sl, a_sr, a_r):
        return jnp.where(SL > 0.0, a_l,
               jnp.where(ustar > 0.0, a_sl,
               jnp.where(SR > 0.0, a_sr, a_r)))

    ro = sel(rl, rstarl, rstarr, rr)
    uo = sel(ul, ustar, ustar, ur)
    ptoto = sel(ptotl, ptotstar, ptotstar, ptotr)
    etoto = sel(etotl, etotstarl, etotstarr, etotr)
    eo = sel(el, estarl, estarr, er)

    upwind_left = ustar > 0.0
    flux = [ro * uo, ro * uo * uo + ptoto, (etoto + ptoto) * uo]
    for t in range(cfg.ndim - 1):
        flux.append(ro * uo * jnp.where(upwind_left, ql[3 + t], qr[3 + t]))
    for n in range(cfg.nener):
        eradl = ql[2 + cfg.ndim + n] / (cfg.gamma_rad[n] - 1.0)
        eradr = qr[2 + cfg.ndim + n] / (cfg.gamma_rad[n] - 1.0)
        erado = sel(eradl, eradl * (SL - ul) / dSL,
                    eradr * (SR - ur) / dSR, eradr)
        flux.append(uo * erado)
    for s in range(cfg.npassive):
        i = 2 + cfg.ndim + cfg.nener + s
        flux.append(ro * uo * jnp.where(upwind_left, ql[i], qr[i]))
    flux.append(uo * eo)
    return jnp.stack(flux)


def riemann_approx(ql, qr, cfg: HydroStatic):
    """Two-shock iterative solver (``riemann_approx``, godunov_utils.f90:268).

    Newton-Raphson on p* for ``niter_riemann`` fixed iterations (the
    reference compresses converged lanes out; iterating them further is a
    no-op to machine precision and is branch-free here).
    """
    entho = 1.0 / (cfg.gamma - 1.0)
    gamma6 = (cfg.gamma + 1.0) / (2.0 * cfg.gamma)
    rl, ul, pl = _prims(ql, cfg)
    rr, ur, pr = _prims(qr, cfg)
    cl = cfg.gamma * pl * rl  # Lagrangian sound speed^2
    cr = cfg.gamma * pr * rr
    wl = jnp.sqrt(cl)
    wr = jnp.sqrt(cr)
    pstar0 = jnp.maximum(
        ((wr * pl + wl * pr) + wl * wr * (ul - ur)) / (wl + wr), 0.0)

    def body(_, pold):
        wwl = jnp.sqrt(cl * (1.0 + gamma6 * (pold - pl) / pl))
        wwr = jnp.sqrt(cr * (1.0 + gamma6 * (pold - pr) / pr))
        qL = 2.0 * wwl ** 3 / (wwl ** 2 + cl)
        qR = 2.0 * wwr ** 3 / (wwr ** 2 + cr)
        usl = ul - (pold - pl) / wwl
        usr = ur + (pold - pr) / wwr
        delp = jnp.maximum(qR * qL / (qR + qL) * (usl - usr), -pold)
        return pold + delp

    pstar = jax.lax.fori_loop(0, cfg.niter_riemann, body, pstar0)

    wl = jnp.sqrt(cl * (1.0 + gamma6 * (pstar - pl) / pl))
    wr = jnp.sqrt(cr * (1.0 + gamma6 * (pstar - pr) / pr))
    ustar = 0.5 * (ul + (pl - pstar) / wl + ur - (pr - pstar) / wr)

    left = ustar >= 0.0   # sgnm == +1
    ro = jnp.where(left, rl, rr)
    uo = jnp.where(left, ul, ur)
    po = jnp.where(left, pl, pr)
    wo = jnp.where(left, wl, wr)
    sgnm = jnp.where(left, 1.0, -1.0)
    co = jnp.maximum(cfg.smallc, jnp.sqrt(jnp.abs(cfg.gamma * po / ro)))

    shock = pstar >= po
    # Gradient-safe rarefaction density: |pstar/po|**(1/gamma) has an
    # unbounded derivative as pstar -> 0, so a vacuum-adjacent lane poisons
    # reverse-mode cotangents even though the forward value (0) is clamped
    # by smallr below.  Double-where: evaluate the power only where its
    # input is strictly positive (forward value at 0 is 0 either way).
    ps_rare = jnp.where(shock, po, pstar)
    ps_pos = ps_rare > 0.0
    ps_safe = jnp.where(ps_pos, ps_rare, po)
    rstar_shock = ro / (1.0 + ro * (po - pstar) / wo ** 2)
    rstar_rare = ro * jnp.where(
        ps_pos, jnp.abs(ps_safe / po) ** (1.0 / cfg.gamma), 0.0)
    rstar = jnp.where(shock, rstar_shock, rstar_rare)
    rstar = jnp.maximum(rstar, cfg.smallr)
    # sqrt has an infinite derivative at 0; gamma*pstar/rstar >= 0 always,
    # so guard the exact-zero lane (forward sqrt(0) == 0 is preserved).
    cs2 = cfg.gamma * pstar / rstar
    cs2_pos = cs2 > 0.0
    cstar = jnp.maximum(
        jnp.where(cs2_pos, jnp.sqrt(jnp.where(cs2_pos, cs2, 1.0)), 0.0),
        cfg.smallc)
    wo_ro = wo / ro
    spout = jnp.where(shock, wo_ro - sgnm * uo, co - sgnm * uo)
    spin = jnp.where(shock, wo_ro - sgnm * uo, cstar - sgnm * ustar)
    # rarefaction fan interpolation; the fan values are only consumed when
    # spout > 0 > spin, and outside the fan spout == spin makes the raw
    # fraction derivative unbounded — restrict the division to the fan.
    fan = (spout > 0.0) & (spin < 0.0)
    fan_den = jnp.where(fan, spout - spin + 1e-300, 1.0)
    frac = jnp.where(fan, spout / fan_den, 0.0)
    ufan = frac * ustar + (1.0 - frac) * uo
    pfan = frac * pstar + (1.0 - frac) * po

    qg_u = jnp.where(spout <= 0.0, uo, jnp.where(spin >= 0.0, ustar, ufan))
    qg_p = jnp.where(spout <= 0.0, po, jnp.where(spin >= 0.0, pstar, pfan))
    # the fan-branch power is consumed exactly on `fan`, where pfan > 0 is
    # guaranteed (frac in (0,1), po > 0); feed it po elsewhere.
    qg_pfan = jnp.where(fan, qg_p, po)
    fan_r = ro * jnp.abs(qg_pfan / po) ** (1.0 / cfg.gamma)
    qg_r = jnp.where(spout <= 0.0, ro,
           jnp.where(spin >= 0.0, rstar, fan_r))

    fmass = qg_r * qg_u
    fmom = qg_p + qg_r * qg_u ** 2
    etot = qg_p * entho + 0.5 * qg_r * qg_u ** 2
    passive_vals = []
    for t in range(cfg.ndim - 1):
        v = jnp.where(left, ql[3 + t], qr[3 + t])
        etot = etot + 0.5 * qg_r * v ** 2
        passive_vals.append(v)
    fener = qg_u * (etot + qg_p)
    flux = [fmass, fmom, fener]
    for v in passive_vals:
        flux.append(fmass * v)
    for n in range(cfg.nener):
        i = 2 + cfg.ndim + n
        flux.append(fmass * jnp.where(left, ql[i], qr[i]))
    for s in range(cfg.npassive):
        i = 2 + cfg.ndim + cfg.nener + s
        flux.append(fmass * jnp.where(left, ql[i], qr[i]))
    flux.append(fmass * (qg_p / qg_r * entho))
    return jnp.stack(flux)


def riemann_acoustic(ql, qr, cfg: HydroStatic):
    """Linearized (acoustic) solver (``riemann_acoustic``,
    godunov_utils.f90:500): one-shot Lagrangian p*/u* then sampling."""
    entho = 1.0 / (cfg.gamma - 1.0)
    rl, ul, pl = _prims(ql, cfg)
    rr, ur, pr = _prims(qr, cfg)
    cl = jnp.sqrt(_cspeed2(ql, rl, pl, cfg))
    cr = jnp.sqrt(_cspeed2(qr, rr, pr, cfg))
    wl = cl * rl
    wr = cr * rr
    pstar = ((wr * pl + wl * pr) + wl * wr * (ul - ur)) / (wl + wr)
    ustar = ((wr * ur + wl * ul) + (pl - pr)) / (wl + wr)

    left = ustar > 0.0
    ro = jnp.where(left, rl, rr)
    uo = jnp.where(left, ul, ur)
    po = jnp.where(left, pl, pr)
    co = jnp.maximum(cfg.smallc, jnp.sqrt(jnp.abs(cfg.gamma * po / ro)))
    sgnm = jnp.where(left, 1.0, -1.0)
    rstar = jnp.maximum(ro + (pstar - po) / co ** 2, cfg.smallr)
    # sqrt has an infinite derivative at 0 (acoustic pstar is unclamped and
    # can cross zero); double-where the exact-zero lane, forward-preserving.
    acs2 = jnp.abs(cfg.gamma * pstar / rstar)
    acs2_pos = acs2 > 0.0
    cstar = jnp.maximum(cfg.smallc, jnp.where(
        acs2_pos, jnp.sqrt(jnp.where(acs2_pos, acs2, 1.0)), 0.0))
    spout = co - sgnm * uo
    spin = cstar - sgnm * ustar
    ushock = 0.5 * (spin + spout)
    spout_ = jnp.where(pstar >= po, ushock, spout)
    spin_ = jnp.where(pstar >= po, ushock, spin)
    frac = jnp.clip(0.5 * (1.0 + (spout_ + spin_) /
                           jnp.maximum(spout_ - spin_, cfg.smallc)), 0.0, 1.0)
    qg_r = jnp.where(spout_ < 0.0, ro,
           jnp.where(spin_ > 0.0, rstar, frac * rstar + (1.0 - frac) * ro))
    qg_u = jnp.where(spout_ < 0.0, uo,
           jnp.where(spin_ > 0.0, ustar, frac * ustar + (1.0 - frac) * uo))
    qg_p = jnp.where(spout_ < 0.0, po,
           jnp.where(spin_ > 0.0, pstar, frac * pstar + (1.0 - frac) * po))

    fmass = qg_r * qg_u
    etot = qg_p * entho + 0.5 * qg_r * qg_u ** 2
    tang = []
    for t in range(cfg.ndim - 1):
        v = jnp.where(left, ql[3 + t], qr[3 + t])
        etot = etot + 0.5 * qg_r * v ** 2
        tang.append(v)
    flux = [fmass, qg_p + qg_r * qg_u ** 2, qg_u * (etot + qg_p)]
    for v in tang:
        flux.append(fmass * v)
    for n in range(cfg.nener):
        i = 2 + cfg.ndim + n
        flux.append(fmass * jnp.where(left, ql[i], qr[i]))
    for s in range(cfg.npassive):
        i = 2 + cfg.ndim + cfg.nener + s
        flux.append(fmass * jnp.where(left, ql[i], qr[i]))
    flux.append(fmass * (qg_p / qg_r * entho))
    return jnp.stack(flux)


SOLVERS = {
    "llf": riemann_llf,
    "hll": riemann_hll,
    "hllc": riemann_hllc,
    "exact": riemann_approx,
    "acoustic": riemann_acoustic,
}


def solve(ql, qr, cfg: HydroStatic):
    """Dispatch by name (``hydro/umuscl.f90:791-804``)."""
    try:
        return SOLVERS[cfg.riemann](ql, qr, cfg)
    except KeyError:
        raise ValueError(f"unknown Riemann solver {cfg.riemann!r}") from None
