"""MHD solver tests.

Correctness anchors (no frozen reference aggregates yet, SURVEY.md §4):
constant-state preservation, exact div(B)=0 under CT, B→0 reduction to
the hydro solver, cross-solver agreement (LLF vs HLLD converge to the
same weak solution), rotation invariance, conservation on periodic
domains, Brio-Wu and Orszag-Tang smoke physics.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from ramses_tpu.config import params_from_dict
from ramses_tpu.mhd import core, uniform as mu
from ramses_tpu.mhd.core import IBX, IP
from ramses_tpu.mhd.driver import MhdSimulation


def _briowu_params(lmin=6, riemann="hlld", slope=1):
    groups = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": lmin, "levelmax": lmin, "boxlen": 1.0},
        "boundary_params": {"nboundary": 2,
                            "ibound_min": [-1, 1], "ibound_max": [-1, 1],
                            "bound_type": [2, 2]},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.25, 0.75],
                        "length_x": [0.5, 0.5],
                        "exp_region": [10.0, 10.0],
                        "d_region": [1.0, 0.125],
                        "p_region": [1.0, 0.1],
                        "A_region": [0.75, 0.75],
                        "B_region": [1.0, -1.0],
                        "C_region": [0.0, 0.0]},
        "hydro_params": {"gamma": 2.0, "courant_factor": 0.7,
                         "riemann": riemann, "slope_type": slope},
        "output_params": {"tend": 0.1},
    }
    return params_from_dict(groups, ndim=1)


def _uniform_sim(ndim=2, lmin=4, riemann="hlld", bvals=(0.3, 0.4, 0.5),
                 v=(0.5, -0.3, 0.2)):
    groups = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": lmin, "levelmax": lmin, "boxlen": 1.0},
        "init_params": {"nregion": 1, "region_type": ["square"],
                        "x_center": [0.5], "y_center": [0.5],
                        "z_center": [0.5],
                        "length_x": [10.0], "length_y": [10.0],
                        "length_z": [10.0], "exp_region": [10.0],
                        "d_region": [1.0], "p_region": [1.0],
                        "u_region": [v[0]], "v_region": [v[1]],
                        "w_region": [v[2]],
                        "A_region": [bvals[0]], "B_region": [bvals[1]],
                        "C_region": [bvals[2]]},
        "hydro_params": {"gamma": 5.0 / 3.0, "riemann": riemann,
                         "courant_factor": 0.8},
        "output_params": {"tend": 0.1},
    }
    return MhdSimulation(params_from_dict(groups, ndim=ndim),
                         dtype=jnp.float64)


@pytest.mark.parametrize("riemann", ["llf", "hll", "hlld"])
def test_constant_state_preserved(riemann):
    sim = _uniform_sim(ndim=2, lmin=4, riemann=riemann)
    u0 = np.asarray(sim.u).copy()
    sim.evolve(0.05)
    assert sim.nstep > 0
    assert np.allclose(np.asarray(sim.u), u0, atol=1e-12)
    assert float(sim.max_divb()) < 1e-12


@pytest.mark.smoke
def test_divb_zero_3d_random_field():
    sim = _uniform_sim(ndim=3, lmin=3)
    rng = np.random.default_rng(0)
    n = 8
    # faces from a staggered vector potential curl ⇒ div B = 0 exactly
    ax, ay, az = rng.standard_normal((3, n, n, n))
    dx = sim.dx
    bfx = (np.roll(az, -1, 1) - az) / dx - (np.roll(ay, -1, 2) - ay) / dx
    bfy = (np.roll(ax, -1, 2) - ax) / dx - (np.roll(az, -1, 0) - az) / dx
    bfz = (np.roll(ay, -1, 0) - ay) / dx - (np.roll(ax, -1, 1) - ax) / dx
    bf = np.stack([bfx, bfy, bfz]) * 0.05
    u = np.asarray(sim.u).copy()
    bc = core.cell_center_b(list(bf), 3)
    for c in range(3):
        u[IBX + c] = bc[c]
    # refresh total energy with the new magnetic energy
    u[IP] = 1.0 / (5.0 / 3.0 - 1.0) + 0.5 * (
        u[1] ** 2 + u[2] ** 2 + u[3] ** 2) / u[0] + 0.5 * sum(
        b ** 2 for b in bc)
    sim.u = jnp.asarray(u)
    sim.bf = jnp.asarray(bf)
    assert float(sim.max_divb()) < 1e-10
    sim.evolve(0.02)
    assert sim.nstep > 0
    assert float(sim.max_divb()) < 1e-10
    assert np.all(np.isfinite(np.asarray(sim.u)))


def test_briowu_tube_physics():
    sim = MhdSimulation(_briowu_params(lmin=7), dtype=jnp.float64)
    sim.evolve(0.1)
    u = np.asarray(sim.u)
    q = np.asarray(core.ctoprim(sim.u, sim.cfg))
    rho = q[0]
    # end states untouched (waves have not reached the boundaries)
    assert np.isclose(rho[0], 1.0, atol=1e-8)
    assert np.isclose(rho[-1], 0.125, atol=1e-8)
    # compound/intermediate structure exists
    assert rho.min() > 0.1 and rho.max() <= 1.0 + 1e-10
    assert q[IBX + 1].min() < -0.9 and q[IBX + 1].max() > 0.9
    # Bx exactly constant in 1D CT
    assert np.allclose(np.asarray(sim.bf[0]), 0.75, atol=1e-13)
    assert np.all(np.isfinite(u))


def test_briowu_solver_cross_agreement():
    """LLF and HLLD converge to the same weak solution."""
    sol = {}
    for riemann in ("llf", "hlld"):
        sim = MhdSimulation(_briowu_params(lmin=8, riemann=riemann),
                            dtype=jnp.float64)
        sim.evolve(0.1)
        sol[riemann] = np.asarray(core.ctoprim(sim.u, sim.cfg))
    l1 = np.mean(np.abs(sol["llf"][0] - sol["hlld"][0]))
    assert l1 < 0.015, f"LLF vs HLLD density L1 {l1}"


def test_rotation_invariance_2d():
    """The same tube along x and along y gives identical profiles when
    stepped with an identical dt sequence (the drivers' CFL differs: the
    2D run pays the transverse fast-speed in its rate sum)."""
    simx = MhdSimulation(_briowu_params(lmin=6), dtype=jnp.float64)

    groups = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 6, "levelmax": 6, "boxlen": 1.0},
        "boundary_params": {"nboundary": 2,
                            "jbound_min": [-1, 1], "jbound_max": [-1, 1],
                            "ibound_min": [0, 0], "ibound_max": [0, 0],
                            "bound_type": [4, 4]},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.5, 0.5], "y_center": [0.25, 0.75],
                        "length_x": [10.0, 10.0], "length_y": [0.5, 0.5],
                        "exp_region": [10.0, 10.0],
                        "d_region": [1.0, 0.125],
                        "p_region": [1.0, 0.1],
                        # normal = y: A (x-comp) = tangential 1, B = 0.75
                        "u_region": [0.0, 0.0], "v_region": [0.0, 0.0],
                        "A_region": [1.0, -1.0],
                        "B_region": [0.75, 0.75],
                        "C_region": [0.0, 0.0]},
        # riemann2d='average' pins the Gardiner-Stone corner scheme this
        # test's sharp tolerance was calibrated for (the namelist
        # default is the reference's llf corner solver, whose transverse
        # dissipation shifts the profile at truncation order)
        "hydro_params": {"gamma": 2.0, "courant_factor": 0.7,
                         "riemann": "hlld", "riemann2d": "average",
                         "slope_type": 1},
        "output_params": {"tend": 0.1},
    }
    simy = MhdSimulation(params_from_dict(groups, ndim=2),
                         dtype=jnp.float64)
    dt = 0.25 / 64 / 3.0
    for _ in range(40):
        simx.u, simx.bf = mu.step(simx.grid, simx.u, simx.bf, dt)
        simy.u, simy.bf = mu.step(simy.grid, simy.u, simy.bf, dt)
    qx = np.asarray(core.ctoprim(simx.u, simx.cfg))        # [nvar, nx]
    qy = np.asarray(core.ctoprim(simy.u, simy.cfg))        # [nvar, nx, ny]
    # no symmetry breaking across the transverse dimension — exact
    assert np.abs(qy[0] - qy[0][0:1, :]).max() < 1e-12
    rho_y = qy[0][0, :]                                     # profile along y
    # cross-orientation agreement is at truncation order only: the 2D path
    # carries the corner-EMF (GS05) machinery that a 1D evolution has no
    # analogue of, so the tangential-field updates differ at O(dt·Δ)
    assert np.allclose(qx[0], rho_y, atol=1e-3)
    # tangential field maps: x-tube B_y ↔ y-tube B_x
    assert np.allclose(qx[IBX + 1], qy[IBX][0, :], atol=5e-3)


def test_b_zero_matches_hydro():
    """With B=0 the MHD solver must reproduce the hydro solver."""
    from ramses_tpu.driver import Simulation

    groups = {
        "run_params": {"hydro": True},
        "amr_params": {"levelmin": 7, "levelmax": 7, "boxlen": 1.0},
        "boundary_params": {"nboundary": 2,
                            "ibound_min": [-1, 1], "ibound_max": [-1, 1],
                            "bound_type": [2, 2]},
        "init_params": {"nregion": 2,
                        "region_type": ["square", "square"],
                        "x_center": [0.25, 0.75], "length_x": [0.5, 0.5],
                        "exp_region": [10.0, 10.0],
                        "d_region": [1.0, 0.125],
                        "p_region": [1.0, 0.1]},
        "hydro_params": {"gamma": 1.4, "courant_factor": 0.5,
                         "riemann": "hllc", "slope_type": 1},
        "output_params": {"noutput": 1, "tout": [0.1], "tend": 0.1},
    }
    ph = params_from_dict(groups, ndim=1)
    hsim = Simulation(ph, dtype=jnp.float64)
    hsim.evolve()

    groups["hydro_params"]["riemann"] = "hlld"
    pm = params_from_dict(dict(groups), ndim=1)
    msim = MhdSimulation(pm, dtype=jnp.float64)
    msim.evolve(0.1)

    rho_h = np.asarray(hsim.state.u)[0]
    rho_m = np.asarray(msim.u)[0]
    l1 = np.mean(np.abs(rho_h - rho_m))
    assert l1 < 5e-3, f"hydro vs B=0 MHD L1 {l1}"


def _orszag_tang(lmin=5):
    sim = _uniform_sim(ndim=2, lmin=lmin, bvals=(0.0, 0.0, 0.0),
                       v=(0.0, 0.0, 0.0))
    n = 2 ** lmin
    dx = sim.dx
    gamma = 5.0 / 3.0
    # standard OT: rho=gamma*p0... use the Fromang+2006 normalization
    d0 = 25.0 / (36.0 * np.pi)
    p0 = 5.0 / (12.0 * np.pi)
    b0 = 1.0 / np.sqrt(4.0 * np.pi)
    xc = (np.arange(n) + 0.5) * dx
    X, Y = np.meshgrid(xc, xc, indexing="ij")
    vx = -np.sin(2 * np.pi * Y)
    vy = np.sin(2 * np.pi * X)
    # vector potential Az on corners → exactly solenoidal staggered field
    xf = np.arange(n) * dx
    Xf, Yf = np.meshgrid(xf, xf, indexing="ij")
    Az = (b0 / (4 * np.pi) * np.cos(4 * np.pi * Xf)
          + b0 / (2 * np.pi) * np.cos(2 * np.pi * Yf))
    bfx = (np.roll(Az, -1, 1) - Az) / dx          # Bx = dAz/dy at x-faces
    bfy = -(np.roll(Az, -1, 0) - Az) / dx         # By = -dAz/dx at y-faces
    bf = np.stack([bfx, bfy, np.zeros((n, n))])
    bc = core.cell_center_b(list(bf), 2)
    u = np.zeros((8,) + (n, n))
    u[0] = d0
    u[1] = d0 * vx
    u[2] = d0 * vy
    u[IBX] = bc[0]
    u[IBX + 1] = bc[1]
    u[IP] = (p0 / (gamma - 1.0) + 0.5 * d0 * (vx ** 2 + vy ** 2)
             + 0.5 * (bc[0] ** 2 + bc[1] ** 2))
    sim.u = jnp.asarray(u)
    sim.bf = jnp.asarray(bf)
    return sim


def test_orszag_tang_conservation_and_divb():
    sim = _orszag_tang(lmin=5)
    m0 = float(sim.totals()["mass"])
    e0 = float(sim.totals()["energy"])
    sim.evolve(0.1)
    assert sim.nstep > 5
    assert float(sim.max_divb()) < 1e-11
    assert np.isclose(float(sim.totals()["mass"]), m0, rtol=1e-12)
    assert np.isclose(float(sim.totals()["energy"]), e0, rtol=1e-11)
    q = np.asarray(core.ctoprim(sim.u, sim.cfg))
    assert q[0].min() > 0.0 and np.all(np.isfinite(q))


def test_mhd_snapshot(tmp_path):
    from ramses_tpu.io import reader as rdr
    sim = _uniform_sim(ndim=2, lmin=3)
    sim.evolve(0.02)
    out = sim.dump(iout=1, base_dir=str(tmp_path))
    s = rdr.load_snapshot(out)
    names = s["var_names"]
    assert "B_x_left" in names and "B_z_right" in names
    cells = rdr.leaf_cells(s)
    assert len(cells["density"]) == 64
    assert np.allclose(cells["B_x_left"], 0.3, atol=1e-12)
    assert np.allclose(cells["B_y_right"], 0.4, atol=1e-12)
    assert np.allclose(cells["pressure"], 1.0, atol=1e-10)


def test_roe_eigensystem_exact():
    """At coincident L=R states the CG97 corrections vanish and the
    Roe eigenvectors must satisfy the EXACT primitive MHD eigen
    relations A_p r = lambda r (tests the published Roe-Balsara
    construction, mhd/roe.py)."""
    from ramses_tpu.mhd import roe as R

    cfg = core.MhdStatic(ndim=3)
    g = cfg.gamma
    for (r, p, vn, vt1, vt2, bn, bt1, bt2) in [
            (1.3, 0.7, 0.4, -0.2, 0.1, 0.6, -0.3, 0.5),
            (1.0, 1.0, 0.0, 0.0, 0.0, 1e-14, 0.0, 0.0),   # pure hydro
            (2.0, 0.5, -1.0, 0.3, 0.2, 1.2, 1e-15, 1e-15),  # Bt ~ 0
    ]:
        q = jnp.array([[r], [vn], [vt1], [vt2], [p], [bn], [bt1], [bt2]],
                      dtype=jnp.float64)
        m = R.roe_mean(q, q, jnp.asarray([bn], jnp.float64), g)
        lams, Rv = R._right_eigenvectors(m)
        lams = np.array(lams)[:, 0]
        Rv = np.array(Rv)[:, :, 0]
        A = np.zeros((7, 7))
        A[0, 0] = vn; A[0, 1] = r
        A[1, 1] = vn; A[1, 4] = 1 / r
        A[1, 5] = bt1 / r; A[1, 6] = bt2 / r
        A[2, 2] = vn; A[2, 5] = -bn / r
        A[3, 3] = vn; A[3, 6] = -bn / r
        A[4, 1] = g * p; A[4, 4] = vn
        A[5, 1] = bt1; A[5, 2] = -bn; A[5, 5] = vn
        A[6, 1] = bt2; A[6, 3] = -bn; A[6, 6] = vn
        for k in range(7):
            rk = Rv[:, k]
            err = np.linalg.norm(A @ rk - lams[k] * rk) \
                / max(np.linalg.norm(rk), 1e-30)
            # 1e-8 admits the near-degenerate Bt~1e-15 states where the
            # beta = 1/sqrt(2) convention takes over; exact states sit
            # at machine epsilon
            assert err < 1e-8, (r, p, bn, k, err)
        # well-conditioned basis (the solve-based wave strengths rely
        # on it)
        assert np.linalg.cond(Rv) < 1e4


def test_roe_upwind_consistency_and_conservation():
    """F(q, q) equals the exact flux; a Brio-Wu tube under roe/upwind
    conserves mass/energy and agrees with HLLD's weak solution."""
    from ramses_tpu.mhd import roe as R
    from ramses_tpu.mhd.riemann import _flux

    cfg = core.MhdStatic(ndim=3)
    q = jnp.array([[1.3], [0.4], [-0.2], [0.1], [0.7], [0.6], [-0.3],
                   [0.5]], dtype=jnp.float64)
    bn = jnp.asarray([0.6], jnp.float64)
    fe = _flux(1.3, 0.4, -0.2, 0.1, 0.7, 0.6, -0.3, 0.5, cfg.gamma)
    for fn in (R.roe, R.upwind):
        f = np.array(fn(q, q, bn, cfg))
        for i in range(8):
            assert abs(float(f[i, 0]) - float(np.asarray(fe[i]))) < 1e-12

    base = None
    for riemann in ("hlld", "roe", "upwind"):
        sim = MhdSimulation(_briowu_params(lmin=7, riemann=riemann),
                            dtype=jnp.float64)
        m0 = float(jnp.sum(sim.u[0]))
        sim.evolve(0.08)
        assert np.all(np.isfinite(np.asarray(sim.u))), riemann
        # outflow tube: interior waves haven't reached the ends, so
        # mass is conserved to roundoff
        assert np.isclose(float(jnp.sum(sim.u[0])), m0, rtol=1e-12)
        rho = np.asarray(core.ctoprim(sim.u, sim.cfg))[0]
        if base is None:
            base = rho
        else:
            l1 = np.mean(np.abs(rho - base))
            assert l1 < 0.02, (riemann, l1)


@pytest.mark.slow
def test_riemann2d_bank_orszag_tang():
    """Every 2D corner solver of the reference bank
    (riemann2d=llf|roe|upwind|hll|hlla|hlld, mhd/umuscl.f90:1946-2000)
    runs Orszag-Tang stably with machine-zero divB, and the upwinded
    EMFs agree with the Gardiner-Stone average at truncation order."""
    from ramses_tpu.mhd.uniform import MhdGrid, cfl_dt, step, totals

    def orszag(n, cfg):
        dx = 1.0 / n
        x = (np.arange(n) + 0.5) * dx
        X, Y = np.meshgrid(x, x, indexing="ij")
        rho = cfg.gamma ** 2 / (4 * np.pi) * np.ones((n, n))
        p = cfg.gamma / (4 * np.pi) * np.ones((n, n))
        vx, vy = -np.sin(2 * np.pi * Y), np.sin(2 * np.pi * X)
        B0 = 1 / np.sqrt(4 * np.pi)
        bf = np.zeros((3, n, n))
        bf[0] = -B0 * np.sin(2 * np.pi * Y)
        bf[1] = B0 * np.sin(4 * np.pi * np.meshgrid(x, x,
                                                    indexing="ij")[0])
        bcx = 0.5 * (bf[0] + np.roll(bf[0], -1, 0))
        bcy = 0.5 * (bf[1] + np.roll(bf[1], -1, 1))
        e = (p / (cfg.gamma - 1) + 0.5 * rho * (vx ** 2 + vy ** 2)
             + 0.5 * (bcx ** 2 + bcy ** 2))
        u = np.zeros((8, n, n))
        u[0] = rho; u[1] = rho * vx; u[2] = rho * vy
        u[4] = e; u[5] = bcx; u[6] = bcy
        return jnp.asarray(u), jnp.asarray(bf), dx

    sols = {}
    for r2d in ("average", "llf", "roe", "upwind", "hll", "hlla",
                "hlld"):
        cfg = core.MhdStatic(ndim=2, riemann="hlld", riemann2d=r2d)
        n = 32
        u, bf, dx = orszag(n, cfg)
        grid = MhdGrid(cfg=cfg, shape=(n, n), dx=dx,
                       bc_kinds=((0, 0), (0, 0)))
        m0 = float(totals(u, cfg, dx)["mass"])
        for _ in range(25):
            u, bf = step(grid, u, bf, float(cfl_dt(grid, u, bf)))
        bfx, bfy = np.asarray(bf[0]), np.asarray(bf[1])
        divb = ((np.roll(bfx, -1, 0) - bfx) / dx
                + (np.roll(bfy, -1, 1) - bfy) / dx)
        assert np.abs(divb).max() < 1e-11, r2d
        assert np.all(np.isfinite(np.asarray(u))), r2d
        assert np.isclose(float(totals(u, cfg, dx)["mass"]), m0,
                          rtol=1e-12), r2d
        sols[r2d] = np.asarray(u[0])
    for r2d, rho in sols.items():
        l1 = np.mean(np.abs(rho - sols["hlld"])) / np.mean(sols["hlld"])
        assert l1 < 0.03, (r2d, l1)
