"""Pallas async remote-copy (DMA) halo engine.

Every multi-chip sweep in this codebase moves ``NGHOST``-deep boundary
slabs between ring neighbours.  The portable spelling is
``lax.ppermute`` — correct, but BLOCKING: XLA sequences the collective
against the MUSCL interior update, so every step pays the full ICI
transfer latency on the critical path (the comm/compute serialization
the AMT papers, arXiv:2210.06439 / 2412.15518, identify as the exascale
scaling bottleneck; the reference RAMSES hides the same traffic behind
compute with two-sided MPI).

This module is the EXPLICIT asynchronous formulation: a Pallas kernel
per exchange issues ``pltpu.make_async_remote_copy`` of every boundary
slab to its ring neighbour — the copies stream over ICI while the
issuing core is free — then blocks only on the receive semaphores.
Because the ghost outputs are separate arrays (not data-dependencies of
the interior), the callers split their stencil update into an interior
region (consumes NO ghost data → schedulable while the DMA is in
flight) and thin boundary strips that wait for the ghosts
(:func:`ramses_tpu.parallel.dense_slab.dense_sweep_slab`,
:func:`ramses_tpu.parallel.halo.run_steps_halo`).

Backend contract: :func:`permute` / :func:`exchange_slabs` are drop-in
replacements for ``lax.ppermute`` with identical ring semantics —
device ``dst`` receives ``src``'s operand for every ``(src, dst)`` pair
— and the two backends agree BITWISE (pure data movement; asserted in
``tests/test_dma_halo.py`` under interpret mode).  Selection rides the
``&AMR_PARAMS halo_backend`` knob: ``auto`` resolves to ``dma`` on a
real TPU backend and ``ppermute`` everywhere else, so CPU runs (and the
tier-1 suite) never change behaviour unless a test forces interpret
mode via :data:`FORCE_INTERPRET`.

On compiled TPU the kernel first runs a neighbour barrier on the
global barrier semaphore (both ring neighbours must have entered the
kernel before anyone writes into a peer's output buffer — the standard
RDMA safety handshake); interpret mode skips the barrier (unsupported
there, and the interpreter serializes devices anyway).
"""

from __future__ import annotations

import itertools
import os
import warnings
from typing import Sequence

import jax
import jax.numpy as jnp

try:  # pallas is part of jax, but keep import-failure soft like pallas_muscl
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    # jax renamed TPUCompilerParams → CompilerParams between releases
    _CompilerParams = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
except Exception:                                  # pragma: no cover
    pl = pltpu = _CompilerParams = None

DISABLED = bool(os.environ.get("RAMSES_NO_PALLAS"))

# Test hook: run the DMA kernels in Pallas interpreter mode on any
# backend — lets CI drive the REAL async-remote-copy path (not a
# replica) on the CPU test backend.  Module attribute so tests can
# monkeypatch; also settable via env for whole-suite sweeps.
FORCE_INTERPRET = bool(os.environ.get("RAMSES_DMA_HALO_INTERPRET"))

# Trace-time traffic accounting.  jit caching means each compiled
# program traces once, so these counts approximate the per-step traffic
# of the LAST compiled sweep (bytes are per device, one direction).
# telemetry.sim_run_info snapshots them into every run_header.
TRAFFIC = {"bytes": 0, "exchanges": 0, "overlap_frac": 0.0}

# distinct barrier-semaphore ids for kernels that may run concurrently
# inside one program (e.g. the state and mask exchanges of a split
# sweep); trace order is deterministic SPMD so every device agrees
_collective_ids = itertools.count()


def traffic_snapshot() -> dict:
    return {"halo_bytes": int(TRAFFIC["bytes"]),
            "halo_exchanges": int(TRAFFIC["exchanges"]),
            "halo_overlap_frac": float(TRAFFIC["overlap_frac"])}


def reset_traffic():
    TRAFFIC.update(bytes=0, exchanges=0, overlap_frac=0.0)


def _count(*slabs):
    for s in slabs:
        TRAFFIC["bytes"] += int(s.size) * jnp.dtype(s.dtype).itemsize
        TRAFFIC["exchanges"] += 1


def available() -> bool:
    """True when the DMA kernel can run compiled (real TPU backend)."""
    if DISABLED or pl is None:
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:                              # pragma: no cover
        return False


_warned: set = set()


def resolve_backend(requested) -> str:
    """Map the ``&AMR_PARAMS halo_backend`` knob to a concrete backend.

    ``auto`` → ``dma`` on a real TPU, ``ppermute`` elsewhere (CPU
    behaviour untouched).  An explicit ``dma`` request is honoured on
    TPU or under :data:`FORCE_INTERPRET` (tests); otherwise it warns
    once and falls back so a namelist written for TPU still runs on a
    laptop."""
    req = str(requested or "auto").lower()
    if req == "auto":
        return "dma" if available() else "ppermute"
    if req == "dma":
        if available() or (FORCE_INTERPRET and pl is not None):
            return "dma"
        if "dma" not in _warned:
            _warned.add("dma")
            warnings.warn(
                "halo_backend='dma' requested but no TPU backend is "
                "available: falling back to ppermute")
        return "ppermute"
    if req != "ppermute" and req not in _warned:
        _warned.add(req)
        warnings.warn(f"unknown halo_backend {requested!r}: using "
                      "ppermute")
    return "ppermute"


def _interpret() -> bool:
    return FORCE_INTERPRET or jax.default_backend() != "tpu"


def shard_map_compat(fn, mesh, in_specs, out_specs, check_rep=True):
    """``shard_map`` across jax releases.  ``check_rep=False`` is
    required whenever the body contains a ``pallas_call`` (no
    replication rule exists for it); newer jax renamed the kwarg to
    ``check_vma``."""
    try:
        sm = jax.shard_map                         # jax >= 0.8
    except AttributeError:
        from jax.experimental.shard_map import shard_map as sm
    if check_rep:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    try:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)
    except TypeError:                              # pragma: no cover
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)


# ----------------------------------------------------------------------
# the kernel
# ----------------------------------------------------------------------
def _exchange_kernel(nslab: int, barrier: bool):
    """Kernel: start one async remote copy per slab (dst device ids in
    SMEM), then wait on every receive semaphore.  All copies are in
    flight together — the issuing core returns to the scheduler until
    the waits, which is what lets XLA overlap downstream independent
    compute with the transfer."""

    def kern(dst_ref, *refs):
        srcs = refs[:nslab]
        outs = refs[nslab:2 * nslab]
        sems = refs[2 * nslab:]
        if barrier:
            # RDMA safety: both peers must be inside the kernel before
            # anyone writes a peer's output buffer.  Each device
            # signals every destination it will write; the devices
            # writing to ME are exactly my destinations' mirror, so
            # waiting for nslab signals completes the handshake.
            bsem = pltpu.get_barrier_semaphore()
            for i in range(nslab):
                pltpu.semaphore_signal(
                    bsem, device_id=dst_ref[i],
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
            pltpu.semaphore_wait(bsem, nslab)
        copies = [
            pltpu.make_async_remote_copy(
                srcs[i], outs[i], sems[2 * i], sems[2 * i + 1],
                device_id=dst_ref[i],
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            for i in range(nslab)]
        for c in copies:
            c.start()
        for c in copies:
            c.wait()

    return kern


def _dma_exchange(slabs, dsts, interpret: bool):
    """One fused pallas_call moving every ``slabs[i]`` to device
    ``dsts[i]`` (traced int32 scalars).  Returns the received arrays —
    ring-symmetric exchanges guarantee the receive shapes match the
    send shapes."""
    n = len(slabs)
    dst_arr = jnp.stack([jnp.asarray(d, jnp.int32) for d in dsts])
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = _CompilerParams(
            collective_id=next(_collective_ids) % 32)
    outs = pl.pallas_call(
        _exchange_kernel(n, barrier=not interpret),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [pl.BlockSpec(memory_space=pltpu.ANY)] * n,
        out_specs=tuple(pl.BlockSpec(memory_space=pltpu.ANY)
                        for _ in range(n)),
        out_shape=tuple(jax.ShapeDtypeStruct(s.shape, s.dtype)
                        for s in slabs),
        scratch_shapes=[pltpu.SemaphoreType.DMA] * (2 * n),
        interpret=interpret,
        **kwargs)(dst_arr, *slabs)
    return list(outs)


def _dst_from_perm(perm, axis_name):
    """My destination device under a ppermute-style (src, dst) list."""
    tab = [0] * len(perm)
    for s, d in perm:
        tab[s] = d
    return jnp.asarray(tab, jnp.int32)[jax.lax.axis_index(axis_name)]


# ----------------------------------------------------------------------
# public exchange API (ppermute-compatible semantics)
# ----------------------------------------------------------------------
def exchange_slabs(sends: Sequence, perms: Sequence, axis_name: str,
                   backend: str = "ppermute", interpret=None):
    """``[ppermute(sends[i], axis, perms[i]) for i]`` — on the ``dma``
    backend all slabs ride ONE fused async-remote-copy kernel (one
    barrier, all transfers in flight together)."""
    _count(*sends)
    if backend != "dma":
        return [jax.lax.ppermute(s, axis_name, p)
                for s, p in zip(sends, perms)]
    if interpret is None:
        interpret = _interpret()
    dsts = [_dst_from_perm(p, axis_name) for p in perms]
    return _dma_exchange(list(sends), dsts, interpret)


def permute(x, axis_name: str, perm, backend: str = "ppermute",
            interpret=None):
    """Drop-in ``lax.ppermute`` with backend dispatch + traffic
    accounting (the single-direction form the explicit AMR comm
    schedules use, :mod:`ramses_tpu.parallel.amr_comm`)."""
    return exchange_slabs([x], [perm], axis_name, backend,
                          interpret=interpret)[0]


def exchange_pair(lo_send, hi_send, axis_name: str, fwd, bwd,
                  backend: str = "ppermute", interpret=None):
    """The halo pair: ``(ppermute(lo_send, fwd), ppermute(hi_send,
    bwd))`` — my high interior slab becomes the +1 neighbour's low
    ghost and vice versa.  Both directions share one DMA kernel."""
    lo, hi = exchange_slabs([lo_send, hi_send], [fwd, bwd], axis_name,
                            backend, interpret=interpret)
    return lo, hi
