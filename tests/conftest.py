"""Test configuration: CPU backend with 8 virtual devices.

Tests run on a virtual 8-device CPU mesh (the 'mpirun -np N on one host'
trick of the reference suite, ``tests/run_test_suite.sh:78-82``) with
float64 enabled so correctness oracles are precision-limited by the
algorithm, not the backend.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The image's sitecustomize registers a TPU-tunnel ("axon") PJRT plugin in
# every interpreter and forces jax_platforms="axon,cpu" via jax.config —
# overriding JAX_PLATFORMS from the environment.  Tests must run on the
# virtual 8-device CPU mesh, so force the config back before any backend
# is initialized (register() runs at interpreter start, long before us,
# but backends are only instantiated on first use).
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
