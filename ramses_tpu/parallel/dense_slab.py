"""Explicit slab-sharded dense sweep for COMPLETE levels.

The global-view :func:`ramses_tpu.amr.kernels.dense_sweep` hands the
flat↔dense bit-permutation transpose to XLA's SPMD partitioner; on a
multi-chip mesh the partitioner cannot follow the bit-interleaved
reshape and falls back to "involuntary full rematerialization" — the
whole base grid is gathered to every chip and re-split each coarse
step (MULTICHIP_r05 tail).  This module is the EXPLICIT formulation:
the complete level's row batch stays sharded ``P("oct")`` exactly as
it already is, and a ``shard_map`` body does per device

1. a SHARD-LOCAL bit-permutation (:func:`ramses_tpu.amr.bitperm.
   flat_to_dense_slab`): a contiguous flat row chunk IS an axis-aligned
   dense sub-box (the top ``log2(ndev)`` flat bits are the most
   significant coordinate bits, z-major), so each chip converts only
   the rows it owns — no cross-chip gather exists;
2. a ring halo exchange per cut axis through the backend-dispatched
   engine (:mod:`ramses_tpu.parallel.dma_halo`): Pallas async
   remote-copy DMA on TPU, ``lax.ppermute`` elsewhere — sequenced
   axis-by-axis over the progressively extended block so corner ghosts
   fill with their true global values; uncut axes wrap locally;
3. the unchanged padded-interior kernel
   (:func:`ramses_tpu.amr.kernels.dense_interior_update`) on the local
   box — per-cell arithmetic identical to the global path, so mesh-of-1
   and mesh-of-N agree BITWISE (asserted in tests/test_dense_slab.py).
   On the DMA backend the update is split into an interior region that
   consumes NO ghost data (computed while the DMA is in flight) and
   ``NGHOST``-thin boundary strips finished after the receive
   semaphores — per-cell purity makes the split bitwise-invisible;
4. the inverse shard-local bit-permutation back to flat rows.

The MHD constrained-transport advance gets the same treatment
(:func:`mhd_ct_slab`): shard-local bitperm of cells AND staggered
faces, depth-2/3 halos, the shared padded CT pipeline
(:func:`ramses_tpu.mhd.uniform.step_padded` or its Pallas kernel,
:mod:`ramses_tpu.mhd.pallas_ct`) on the local box, and a depth-1
exchange of the new low faces to rebuild the high-face slots — the
coarse-fine EMF override arrives as flat-row scatters built OUTSIDE
the shard_map (``mhd/amr.py`` ``emf_flat_idx``), so no global index
scatter survives on the multi-chip path.

Geometry: the cut degenerates to z-slabs for 2 devices, (z, y) pencils
for 4, and octants for 8 — always aligned with oct boundaries.  Scope:
fully periodic cubic power-of-two levels with unpadded row batches and
a power-of-two device count; everything else falls back to the
global-view sweep (kept bitwise-pinned as the single-device reference).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ramses_tpu.amr import bitperm
from ramses_tpu.hydro import muscl
from ramses_tpu.parallel import dma_halo
from ramses_tpu.parallel.mesh import OCT_AXIS


class SlabSpec(NamedTuple):
    """Static (hashable) description of one complete level's slab
    decomposition — rides inside ``FusedSpec`` as part of the jit key."""
    lvl: int
    ndim: int
    mbits: int             # log2(ndev): top flat bits = device index
    mesh: Mesh             # the 1-D "oct" mesh the rows shard over
    grid: Tuple[int, ...]  # device grid extent per axis (prod = ndev)
    loc: Tuple[int, ...]   # local dense sub-box shape per device
    # per-axis ring schedules ((fwd, bwd) pairs of (src, dst) tuples)
    # for cut axes; None = uncut (local periodic wrap)
    perms: tuple
    # resolved halo backend ("dma" | "ppermute") — dma_halo dispatch
    backend: str = "ppermute"


def max_slab_devices(lvl: int, ndim: int) -> int:
    """Largest power-of-two device count a complete level at ``lvl``
    can shard over under the eligibility rules of
    :func:`build_slab_spec` (``mbits <= ndim*(lvl-1)``, which also
    keeps every local extent >= the MUSCL stencil halo).  The job-level
    scheduler (ensemble/meshplan) uses this as the ``max_shards`` stamp
    for mesh-wide AMR namelists."""
    return 1 << max(0, ndim * (lvl - 1))


def build_slab_spec(mesh: Mesh, lvl: int, ndim: int,
                    shape: Tuple[int, ...], ncell_pad: int,
                    bc_kinds, halo_backend: str = "auto"
                    ) -> Optional[SlabSpec]:
    """SlabSpec for a complete level, or None when the level must keep
    the global-view path (non-periodic, non-cubic, padded rows, or a
    non-power-of-two / single-device mesh).  ``halo_backend``: the
    ``&AMR_PARAMS`` knob, resolved here via
    :func:`ramses_tpu.parallel.dma_halo.resolve_backend`."""
    if tuple(mesh.axis_names) != (OCT_AXIS,):
        return None
    ndev = int(mesh.devices.size)
    if ndev <= 1 or ndev & (ndev - 1):
        return None
    if tuple(shape) != (1 << lvl,) * ndim:
        return None
    ncell = (1 << lvl) ** ndim
    if ncell_pad != ncell:
        return None
    mbits = ndev.bit_length() - 1
    if mbits > ndim * (lvl - 1):
        return None
    if any(k != 0 for lohi in bc_kinds for k in lohi):
        return None                                   # periodic only
    gb = bitperm.grid_bits(lvl, ndim, mbits)
    grid = tuple(1 << b for b in gb)
    loc = bitperm.slab_shape(lvl, ndim, mbits)
    if any(loc[d] < muscl.NGHOST for d in range(ndim)):
        return None                                   # shard < stencil
    coords = bitperm.chunk_coords(lvl, ndim, mbits)
    dev_of = {g: D for D, g in enumerate(coords)}
    perms = []
    for d in range(ndim):
        if grid[d] == 1:
            perms.append(None)
            continue
        fwd = []
        bwd = []
        for D, g in enumerate(coords):
            up = list(g)
            dn = list(g)
            up[d] = (g[d] + 1) % grid[d]
            dn[d] = (g[d] - 1) % grid[d]
            fwd.append((D, dev_of[tuple(up)]))
            bwd.append((D, dev_of[tuple(dn)]))
        perms.append((tuple(fwd), tuple(bwd)))
    return SlabSpec(lvl=lvl, ndim=ndim, mbits=mbits, mesh=mesh,
                    grid=grid, loc=loc, perms=tuple(perms),
                    backend=dma_halo.resolve_backend(halo_backend))


def _take(a, ax: int, sl: slice):
    idx = [slice(None)] * a.ndim
    idx[ax] = sl
    return a[tuple(idx)]


def _sm(spec: SlabSpec, body, in_specs, out_specs, use_pallas=False):
    """shard_map with replication checking off whenever the body holds
    a pallas_call (DMA halos or the CT kernel)."""
    return dma_halo.shard_map_compat(
        body, spec.mesh, in_specs, out_specs,
        check_rep=(spec.backend != "dma" and not use_pallas))


def halo_extend(a, spec: SlabSpec, ng: int, spatial0: int,
                axes=None):
    """Extend the local dense block by ``ng`` ghost cells on every
    spatial axis (axes ``spatial0 .. spatial0+ndim-1``): ring exchange
    (DMA or ppermute per ``spec.backend``) on cut axes, local periodic
    wrap on uncut ones.  Later axes exchange the already-extended
    block, so corner ghosts carry their exact global-periodic values.
    ``axes``: optional subset of the original spatial axes to extend
    (the pallas shard path leaves its lane axis bare for the in-kernel
    periodic roll; the DMA overlap split defers its cut axis)."""
    for d in range(spec.ndim):
        if axes is not None and d not in axes:
            continue
        ax = spatial0 + d
        if spec.perms[d] is None:
            pads = [(0, 0)] * a.ndim
            pads[ax] = (ng, ng)
            a = jnp.pad(a, pads, mode="wrap")
        else:
            fwd, bwd = spec.perms[d]
            lo, hi = dma_halo.exchange_pair(
                _take(a, ax, slice(-ng, None)), _take(a, ax, slice(0, ng)),
                OCT_AXIS, list(fwd), list(bwd), backend=spec.backend)
            a = jnp.concatenate([lo, a, hi], axis=ax)
    return a


def dense_apply_slab(rows, spec: SlabSpec, local_fn, ng: int,
                     out_ndim: Optional[int] = None):
    """Generic slab engine: flat rows → per-shard dense sub-box →
    ``ng``-deep halo extension → ``local_fn(extended) -> [*loc,
    *trailing_out]`` → flat rows.  ``local_fn`` sees the block with the
    spatial axes LEADING (trailing feature axes untouched) and must
    return the un-extended local box.  ``out_ndim``: rank of the
    returned rows array (defaults to the input rank)."""
    nd = spec.ndim

    def body(r_loc):
        dense = bitperm.flat_to_dense_slab(r_loc, spec.lvl, nd,
                                           spec.mbits)
        out = local_fn(halo_extend(dense, spec, ng, 0))
        return bitperm.dense_to_flat_slab(out, spec.lvl, nd, spec.mbits)

    in_spec = P(OCT_AXIS, *([None] * (rows.ndim - 1)))
    out_rank = out_ndim if out_ndim is not None else rows.ndim
    out_spec = P(OCT_AXIS, *([None] * (out_rank - 1)))
    return _sm(spec, body, (in_spec,), out_spec)(rows)


def _split_axis(spec: SlabSpec, ng: int) -> Optional[int]:
    """Cut axis for the DMA comm/compute overlap split, or None when
    the split does not apply.  The LAST cut axis is chosen because its
    exchange comes last in :func:`halo_extend`'s sequencing — deferring
    it (while the other axes extend first) reproduces the exact corner
    values of the unsplit pipeline."""
    if spec.backend != "dma":
        return None
    cut = [d for d in range(spec.ndim) if spec.perms[d] is not None]
    if not cut:
        return None
    d = cut[-1]
    return d if spec.loc[d] > 2 * ng else None


def dense_sweep_slab(u_flat, ok_flat, dt, dx: float, spec: SlabSpec,
                     cfg, ret_flux: bool = False):
    """Slab-sharded complete-level hydro sweep — the explicit-comm
    formulation of :func:`ramses_tpu.amr.kernels.dense_sweep` (same
    physics, bitwise-identical du/phi).  ``ok_flat``: flat-row refined
    mask or None; ``dt`` traced scalar.  Returns du rows (+ phi rows
    when ``ret_flux``), sharded like the input.

    On the DMA backend the update is region-split for comm/compute
    overlap: the boundary slabs of the deferred cut axis start their
    async remote copy, the interior band (which reads no ghost data of
    that axis) is computed while the transfer is in flight, and two
    ``NGHOST``-thin strips are finished from the received ghosts.
    :func:`ramses_tpu.amr.kernels.dense_interior_update` is pure
    per-cell arithmetic, so the split output is bitwise identical to
    the unsplit (and to the ppermute) formulation."""
    from ramses_tpu.amr import kernels as K
    from ramses_tpu.hydro import pallas_muscl as pk

    nd = spec.ndim
    ng = muscl.NGHOST
    masked = ok_flat is not None
    # per-shard fused TPU kernel: relabel an uncut %128 axis to the
    # kernel lane role; None (e.g. every CPU run, or all axes cut)
    # takes the shared XLA interior update
    cut = tuple(p is not None for p in spec.perms)
    kaxes = (pk.shard_axes(cfg, spec.loc, cut, u_flat.dtype)
             if nd == 3 else None)
    dsp = _split_axis(spec, ng) if kaxes is None else None
    if dsp is not None:
        dma_halo.TRAFFIC["overlap_frac"] = (
            (spec.loc[dsp] - 2 * ng) / spec.loc[dsp])

    def _update(up, okp, dt_, shape):
        return K.dense_interior_update(up, okp, dt_, dx, shape, cfg,
                                       ret_flux=ret_flux)

    def body(u_loc, ok_loc, dt_):
        ud = bitperm.flat_to_dense_slab(u_loc, spec.lvl, nd, spec.mbits)
        ext = None if kaxes is None else kaxes[:2]
        if dsp is not None:
            ext = tuple(d for d in range(nd) if d != dsp)
        up = halo_extend(jnp.moveaxis(ud, -1, 0), spec, ng, 1, axes=ext)
        okp = None
        if masked:
            # convert on the flat rows (clean shard-local op), halo the
            # arithmetic mask exactly like the state
            okd = bitperm.flat_to_dense_slab(
                ok_loc.astype(u_loc.dtype), spec.lvl, nd, spec.mbits)
            okp = halo_extend(okd, spec, ng, 0, axes=ext)
        if kaxes is not None:
            out = pk.fused_step_shard(up, okp, dt_, cfg, dx, spec.loc,
                                      kaxes, want_flux=ret_flux)
        elif dsp is not None:
            # overlap split: start the DMA of the deferred axis' slabs,
            # compute the ghost-free interior band meanwhile, finish
            # the two boundary strips from the received ghosts
            fwd, bwd = spec.perms[dsp]
            ax = 1 + dsp
            sends = [_take(up, ax, slice(-ng, None)),
                     _take(up, ax, slice(0, ng))]
            perms = [list(fwd), list(bwd)]
            if masked:
                sends += [_take(okp, dsp, slice(-ng, None)),
                          _take(okp, dsp, slice(0, ng))]
                perms += [list(fwd), list(bwd)]
            ghosts = dma_halo.exchange_slabs(sends, perms, OCT_AXIS,
                                             backend=spec.backend)
            shape_int = tuple(spec.loc[d] - (2 * ng if d == dsp else 0)
                              for d in range(nd))
            shape_strip = tuple(ng if d == dsp else spec.loc[d]
                                for d in range(nd))
            out_int = _update(up, okp, dt_, shape_int)
            lo_u = jnp.concatenate(
                [ghosts[0], _take(up, ax, slice(0, 2 * ng))], axis=ax)
            hi_u = jnp.concatenate(
                [_take(up, ax, slice(-2 * ng, None)), ghosts[1]], axis=ax)
            lo_ok = hi_ok = None
            if masked:
                lo_ok = jnp.concatenate(
                    [ghosts[2], _take(okp, dsp, slice(0, 2 * ng))],
                    axis=dsp)
                hi_ok = jnp.concatenate(
                    [_take(okp, dsp, slice(-2 * ng, None)), ghosts[3]],
                    axis=dsp)
            out_lo = _update(lo_u, lo_ok, dt_, shape_strip)
            out_hi = _update(hi_u, hi_ok, dt_, shape_strip)
            if ret_flux:
                out = (jnp.concatenate(
                           [out_lo[0], out_int[0], out_hi[0]], axis=ax),
                       jnp.concatenate(
                           [out_lo[1], out_int[1], out_hi[1]], axis=dsp))
            else:
                out = jnp.concatenate([out_lo, out_int, out_hi], axis=ax)
        else:
            out = _update(up, okp, dt_, spec.loc)
        du = out[0] if ret_flux else out
        du_rows = bitperm.dense_to_flat_slab(
            jnp.moveaxis(du, 0, -1), spec.lvl, nd, spec.mbits)
        if not ret_flux:
            return du_rows
        phi_rows = bitperm.dense_to_flat_slab(out[1], spec.lvl, nd,
                                              spec.mbits)
        return du_rows, phi_rows

    ok_in = P(OCT_AXIS) if masked else P()
    out_specs = ((P(OCT_AXIS, None), P(OCT_AXIS, None, None))
                 if ret_flux else P(OCT_AXIS, None))
    if not masked:
        # shard_map needs a concrete operand for every spec slot
        ok_flat = jnp.zeros((), u_flat.dtype)
    return _sm(spec, body, (P(OCT_AXIS, None), ok_in, P()),
               out_specs)(u_flat, ok_flat, dt)


def dense_flags_slab(u_flat, spec: SlabSpec, flags_fn, twotondim: int):
    """Slab-sharded complete-level refinement flags: ``flags_fn`` maps
    the 1-ghost-extended local block ``[nvar, *loc+2]`` to a bool grid
    of the same spatial shape (the shared ``_grad_flags`` family); the
    interior is sliced here.  Returns ``[noct, 2^ndim]`` flags rows."""
    nd = spec.ndim

    def local_fn(dense_ext):
        ok = flags_fn(jnp.moveaxis(dense_ext, -1, 0))
        return ok[tuple(slice(1, -1) for _ in range(nd))]

    flags = dense_apply_slab(u_flat, spec, local_fn, ng=1, out_ndim=1)
    return flags.reshape(flags.shape[0] // twotondim, twotondim)


# ----------------------------------------------------------------------
# slab-sharded MHD constrained transport
# ----------------------------------------------------------------------
def mhd_slab_ok(spec: Optional[SlabSpec]) -> bool:
    """The CT advance needs face halos one deeper than the hydro
    stencil (``ng+1 = 3``), so every local extent must cover them."""
    from ramses_tpu.mhd import uniform as mu
    return (spec is not None
            and min(spec.loc) >= mu.NGHOST + 1)


def mhd_ct_slab(u_flat, bf_flat, dt, dx: float, spec: SlabSpec, cfg,
                ok_flat=None, ovr_flat=None):
    """Slab-sharded complete-level CT advance — the explicit
    formulation of the ``mu.step`` global-view branch of
    ``mhd/amr.py`` ``_mhd_advance_traced`` (same per-cell pipeline,
    bitwise-identical du / faces).

    ``u_flat`` [ncell, nvar] cell conservative rows; ``bf_flat``
    [ncell, NCOMP, 2] staggered (lo, hi) face rows; ``ok_flat``
    optional flat-row refined mask; ``ovr_flat`` optional coarse-fine
    EMF override as ``(msk_rows, val_rows)`` — BOTH ``[ncell, npairs]``
    flat-row arrays (mask in the state dtype), scattered OUTSIDE this
    call from the Morton-interleaved ``emf_flat_idx`` map so the
    shard_map body sees only row-sharded operands.  Returns
    ``(du_rows [ncell, nvar], b_rows [ncell, NCOMP, 2])``.

    High faces are rebuilt from the new low faces with a depth-1 ring
    exchange (the slab analogue of the global path's periodic
    ``jnp.roll`` in ``_dense_hi``)."""
    from ramses_tpu.mhd import pallas_ct
    from ramses_tpu.mhd import uniform as mu
    from ramses_tpu.mhd.core import NCOMP

    nd = spec.ndim
    ng = mu.NGHOST
    pairs = [(d1, d2) for d1 in range(nd) for d2 in range(d1 + 1, nd)]
    masked = ok_flat is not None
    has_ovr = ovr_flat is not None
    use_kernel = pallas_ct.slab_available(cfg, spec.loc, u_flat.dtype)

    def ftds(rows):
        return bitperm.flat_to_dense_slab(rows, spec.lvl, nd, spec.mbits)

    def dtfs(dense):
        return bitperm.dense_to_flat_slab(dense, spec.lvl, nd, spec.mbits)

    def body(u_loc, bf_loc, ok_loc, om_loc, ov_loc, dt_):
        up0 = jnp.moveaxis(ftds(u_loc), -1, 0)           # [nvar, *loc]
        bld = ftds(bf_loc)                               # [*loc, NCOMP, 2]
        bfd = jnp.stack([bld[..., c, 0] for c in range(NCOMP)])
        up = halo_extend(up0, spec, ng, 1)
        # faces get one extra ghost layer (the cell-centred average
        # must be valid in every padded cell — mu.step's contract)
        bf_ext = halo_extend(bfd, spec, ng + 1, 1)
        okp = None
        if masked:
            okd = ftds(ok_loc.astype(u_loc.dtype))
            okp = halo_extend(okd, spec, ng, 0)
        ovr = None
        if has_ovr:
            omp = halo_extend(jnp.moveaxis(ftds(om_loc), -1, 0),
                              spec, ng, 1)               # [npairs, *loc+2ng]
            ovp = halo_extend(jnp.moveaxis(ftds(ov_loc), -1, 0),
                              spec, ng, 1)
            ovr = {pair: (omp[pi] > 0.5, ovp[pi])
                   for pi, pair in enumerate(pairs)}
        if use_kernel:
            un_p, bfn_p = pallas_ct.ct_step_slab(
                up, bf_ext, dt_, (dx,) * nd, cfg,
                okp=okp, ovr=ovr,
                interpret=pallas_ct.interpret_mode())
        else:
            un_p, bfn_p = mu.step_padded(
                cfg, (dx,) * nd, up, bf_ext, dt_,
                okp=None if okp is None else okp > 0.5, ovr=ovr)
        du = mu._unpad(un_p, nd) - up0
        bfn_lo = [mu._unpad(b, nd) for b in bfn_p]       # each [*loc]
        # high faces: the next cell's low face.  Within the block a
        # shift; the top plane comes from the +1 neighbour via a
        # depth-1 exchange (global path: periodic jnp.roll in
        # _dense_hi) — uncut axes wrap locally, identical by
        # periodicity.
        hi = [None] * NCOMP
        if nd:
            ext1 = halo_extend(jnp.stack(bfn_lo[:nd]), spec, 1, 1)
            for c in range(nd):
                idx = [slice(None)] * nd
                for d in range(nd):
                    idx[d] = slice(2, None) if d == c else slice(1, -1)
                hi[c] = ext1[c][tuple(idx)]
        for c in range(nd, NCOMP):
            hi[c] = bfn_lo[c]                # degenerate: hi == lo
        comps = jnp.stack([jnp.stack([bfn_lo[c], hi[c]], axis=-1)
                           for c in range(NCOMP)], axis=-2)
        return (dtfs(jnp.moveaxis(du, 0, -1)), dtfs(comps))

    ok_in = P(OCT_AXIS) if masked else P()
    ov_in = P(OCT_AXIS, None) if has_ovr else P()
    if not masked:
        ok_flat = jnp.zeros((), u_flat.dtype)
    if has_ovr:
        om_rows, ov_rows = ovr_flat
    else:
        om_rows = ov_rows = jnp.zeros((), u_flat.dtype)
    return _sm(spec, body,
               (P(OCT_AXIS, None), P(OCT_AXIS, None, None), ok_in,
                ov_in, ov_in, P()),
               (P(OCT_AXIS, None), P(OCT_AXIS, None, None)),
               use_pallas=use_kernel)(
        u_flat, bf_flat, ok_flat, om_rows, ov_rows, dt)
