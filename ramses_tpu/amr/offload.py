"""Out-of-core AMR: host-parked inactive levels around the subcycle.

The hierarchy is HBM-resident, so ``levelmax`` is capped by device
memory long before the blocked sweep or the halo engine become the
bottleneck.  GAMER (arXiv:1007.3818) ran AMR out-of-core by staging
inactive levels off the accelerator; this module mirrors that for the
fused step chain:

* a **residency planner** linearizes the ``advance(i, dtl)`` recursion
  of ``hierarchy._advance_traced`` into an op schedule
  (enter/sweep/restrict/courant) and computes each op's working set —
  the active level plus the coarse/fine neighbors its interpolation,
  restriction, and flux-correction touch; everything else may park;
* a **transfer engine** keeps each level's state either on device or
  in a :class:`HostBuffer`.  Eviction is ``copy_to_host_async`` into
  host staging followed by deletion of the device copy; prefetch is an
  async ``jax.device_put`` issued one op ahead (double buffer) so the
  upload of op k+1's working set overlaps op k's compute.  A fetch the
  prefetcher did not land in time is a **stall** and is counted.

The fused step is re-run as per-level jitted segments with swap points
between them.  Each segment replays the exact kernel calls of the
monolithic trace on the same operands in the same order, and the
subcycle dt is formed as ``dt * 2**-i`` (a static power-of-two scale,
bitwise equal to the recursion's successive ``0.5 * dtl`` halvings),
so the segmented step is bitwise identical to the single-window
program — pinned by ``tests/test_offload.py``.

Gated behind ``&AMR_PARAMS offload`` (off/auto/on); ``off`` leaves the
monolithic fast path untouched (zero new HLO, zero device fetches —
pinned by the zero-overhead test).  ``auto`` engages only when the
estimated resident set exceeds ``offload_hbm_budget_mb`` (default read
from the device's reported ``bytes_limit``; platforms that report none
never auto-engage, which keeps CPU test runs deterministic).
"""

from __future__ import annotations

import warnings
from functools import lru_cache, partial
from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ramses_tpu.amr import kernels as K


class HostBuffer:
    """A level's state parked in host RAM.

    Stands in for the device array inside ``sim.u`` while parked:
    exposes ``shape``/``dtype``/``nbytes`` (regrid's reuse check and
    the residency planner read them) and zero-copy ``__array__`` so
    pario format 2 dumps parked levels straight from host staging
    without a device round-trip.  ``__getitem__`` serves the tiny
    probe slices ``drain()`` takes.
    """

    __slots__ = ("host",)

    def __init__(self, host: np.ndarray):
        self.host = host

    @property
    def shape(self):
        return self.host.shape

    @property
    def dtype(self):
        return self.host.dtype

    @property
    def nbytes(self) -> int:
        return self.host.nbytes

    def __array__(self, dtype=None, copy=None):
        if dtype is None or dtype == self.host.dtype:
            return self.host
        return self.host.astype(dtype)

    def __getitem__(self, key):
        return self.host[key]

    def __len__(self):
        return len(self.host)

    def __repr__(self):
        return (f"HostBuffer(shape={self.host.shape}, "
                f"dtype={self.host.dtype})")


def is_parked(arr) -> bool:
    return isinstance(arr, HostBuffer)


def as_device(arr):
    """Fetch a possibly-parked array onto the device (blocking)."""
    if isinstance(arr, HostBuffer):
        return jax.device_put(arr.host)
    return arr


# ----------------------------------------------------------------------
# residency planner: linearize the subcycle recursion into an op
# schedule with per-op working sets
# ----------------------------------------------------------------------
class _Op(NamedTuple):
    kind: str          # "enter" | "sweep" | "restrict" | "courant"
    i: int             # index into spec.levels
    scale: float       # static power-of-two dt scale (sweep ops)
    ws: frozenset      # levels that must be device-resident for the op


def _working_set(spec, kind: str, i: int) -> frozenset:
    levels = spec.levels
    l = levels[i]
    if kind == "enter":
        return frozenset()              # host-side alias only
    if kind == "sweep":
        if spec.complete[i]:
            return frozenset((l,))
        return frozenset((l - 1, l))    # interp source + corr fold
    if kind == "restrict":
        return frozenset((l, levels[i + 1]))
    if kind == "courant":
        return frozenset((l,))
    raise AssertionError(kind)


@lru_cache(maxsize=None)
def plan_schedule(spec) -> tuple:
    """The linearized subcycle schedule for one coarse step.

    Emits ops in the exact order the ``advance`` recursion executes
    them, then inserts each level's Courant op directly after the LAST
    op that writes that level's state (``u[l]`` never changes again, so
    this equals the monolithic end-of-step Courant evaluation while
    letting the level park immediately afterwards).
    """
    levels = spec.levels
    ops = []

    def rec(i, scale):
        ops.append(("enter", i, scale))
        if i + 1 < len(levels):
            rec(i + 1, scale * 0.5)
            rec(i + 1, scale * 0.5)
        ops.append(("sweep", i, scale))
        if i + 1 < len(levels):
            ops.append(("restrict", i, 0.0))

    rec(0, 1.0)
    last_write = {}
    for k, (kind, i, _) in enumerate(ops):
        if kind in ("sweep", "restrict"):
            last_write[i] = k
    out = []
    for k, (kind, i, scale) in enumerate(ops):
        out.append(_Op(kind, i, scale, _working_set(spec, kind, i)))
        for j, kk in last_write.items():
            if kk == k:
                out.append(_Op("courant", j, 0.0,
                               _working_set(spec, "courant", j)))
    return tuple(out)


# ----------------------------------------------------------------------
# per-level jitted segments — each replays the exact monolithic kernel
# calls for one op, so the segmented step is bitwise identical
# ----------------------------------------------------------------------
@partial(jax.jit, static_argnames=("spec", "i", "scale"))
def _seg_sweep(u_l, u_lm1, unew_l, unew_lm1, d, dt, spec, i: int,
               scale: float):
    cfg = spec.cfg
    l = spec.levels[i]
    dtl = dt * scale       # static power-of-two: bitwise == 0.5*dtl chain
    dxl = spec.boxlen / (1 << l)
    if spec.complete[i]:
        root = spec.root or (1,) * cfg.ndim
        shp = tuple(r << l for r in root[:cfg.ndim])
        du = K.dense_sweep(u_l, d.get("inv_perm"), d.get("perm"),
                           d["ok_dense"], dtl, dxl, shp, spec.bspec, cfg)
        corr = None
    elif spec.blocked and spec.blocked[i]:
        interp = K.interp_cells(u_lm1, d["b_interp_cell"],
                                d["b_interp_nb"], d["b_interp_sgn"], cfg,
                                itype=spec.itype)
        out = K.tile_sweep(
            u_l, interp, d["tile_src"], d["tile_vsgn"], d["tile_ok"],
            d["cell_tile"], d["cell_slot"], d["oct_tile"], d["oct_slot"],
            dtl, dxl, cfg, spec.block_shift,
            pallas_ok=spec.pallas_tiles)
        du, corr = out[0], out[1]
    else:
        interp = K.interp_cells(u_lm1, d["interp_cell"], d["interp_nb"],
                                d["interp_sgn"], cfg, itype=spec.itype)
        out = K.level_sweep(u_l, interp, d["stencil_src"], d["vsgn"],
                            d["ok_ref"], None, dtl, dxl, cfg)
        du, corr = out[0], out[1]
    unew_l = unew_l + du
    if corr is not None and l > spec.lmin:
        unew_lm1 = K.scatter_corrections(unew_lm1, corr, d["corr_idx"],
                                         cfg)
    return unew_l, unew_lm1


@partial(jax.jit, static_argnames=("spec", "i"))
def _seg_restrict(u_l, u_fine, d, spec, i: int):
    return K.restrict_upload(u_l, u_fine, d["ref_cell"], d["son_oct"],
                             spec.cfg)


@partial(jax.jit, static_argnames=("spec", "i"))
def _seg_courant(u_l, d, spec, i: int):
    l = spec.levels[i]
    dt_l = K.level_courant(u_l, d["valid_cell"],
                           spec.boxlen / (1 << l), spec.cfg, None)
    return dt_l * (2.0 ** (l - spec.lmin))


@partial(jax.jit, static_argnames=("spec", "i", "eg", "fls", "itype",
                                   "ttd"))
def _seg_flags(u_l, u_lm1, d, spec, i: int, eg, fls, itype: int,
               ttd: int):
    """One level of ``hierarchy._fused_flags`` + the uint8 bitpack."""
    cfg = spec.cfg
    l = spec.levels[i]
    if spec.complete[i]:
        root = spec.root or (1,) * cfg.ndim
        shp = tuple(r << l for r in root[:cfg.ndim])
        fl = K.dense_refine_flags(u_l, d.get("inv_perm"), d.get("perm"),
                                  eg, fls, shp, spec.bspec, cfg,
                                  dx=spec.boxlen / (1 << l))
    elif spec.blocked and spec.blocked[i]:
        if l == spec.lmin:
            interp = jnp.zeros((d["b_interp_cell"].shape[0], cfg.nvar),
                               u_l.dtype)
        else:
            interp = K.interp_cells(u_lm1, d["b_interp_cell"],
                                    d["b_interp_nb"], d["b_interp_sgn"],
                                    cfg, itype=itype)
        fl = K.tile_refine_flags(u_l, interp, d["tile_src"],
                                 d["tile_vsgn"], d["cell_tile"],
                                 d["cell_slot"], eg, fls, cfg,
                                 spec.block_shift)
    else:
        if l == spec.lmin:
            interp = jnp.zeros((d["interp_cell"].shape[0], cfg.nvar),
                               u_l.dtype)
        else:
            interp = K.interp_cells(u_lm1, d["interp_cell"],
                                    d["interp_nb"], d["interp_sgn"], cfg,
                                    itype=itype)
        fl = K.refine_flags(u_l, interp, d["stencil_src"], d["vsgn"], eg,
                            fls, cfg)
    shifts = jnp.arange(ttd, dtype=jnp.uint32)
    return (fl.astype(jnp.uint32) << shifts[None, :]).sum(
        axis=1).astype(jnp.uint8)


# ----------------------------------------------------------------------
# transfer engine
# ----------------------------------------------------------------------
class OffloadEngine:
    """Residency manager for the level-state dict ``sim.u``.

    v1 scope: parks the conservative-state arrays only; the per-level
    device index maps (``sim.dev``) stay resident — they are integer
    tables a small fraction of the state size, and parking them would
    break the regrid map-reuse fast path.  The reported high-water is
    therefore the *managed-state* device footprint.
    """

    #: ops of lookahead the prefetcher runs ahead of compute (the
    #: double buffer); 0 disables prefetch (every fetch stalls) — the
    #: stall-accounting test uses that
    prefetch_depth = 1
    #: ops of lookahead whose working sets are protected from eviction
    keep_ahead = 2

    def __init__(self, mode: str, budget_mb: float = 0.0,
                 min_park_mb: float = 0.0):
        self.mode = mode
        self.budget_mb = float(budget_mb)
        self.min_park_bytes = int(float(min_park_mb) * (1 << 20))
        self._cache_maps = None     # identity of sim.maps at last decide
        self._cache_val = False
        self._warned = False
        self._inflight: Dict[int, object] = {}   # level -> device array
        self._pending = []                       # [(level, device array)]
        # cumulative transfer counters; per-step stats are deltas
        # between run_step boundaries (so regrid/dt/flags traffic lands
        # in the step record that follows it)
        self._tot = dict(stalls=0, prefetches=0, overlapped=0,
                         fetches=0, parks=0, bytes_parked=0,
                         bytes_fetched=0)
        self._mark = dict(self._tot)
        self._hwm = 0
        self.last_step_stats: Optional[dict] = None

    # -- construction ---------------------------------------------------
    @classmethod
    def from_params(cls, params) -> Optional["OffloadEngine"]:
        mode = str(getattr(params.amr, "offload", "off")
                   or "off").strip().lower()
        if mode in ("off", "", "false", ".false."):
            return None
        if mode not in ("auto", "on"):
            raise ValueError(f"&AMR_PARAMS offload={mode!r}: expected "
                             f"off, auto, or on")
        return cls(mode,
                   float(getattr(params.amr, "offload_hbm_budget_mb",
                                 0.0)),
                   float(getattr(params.amr, "offload_min_park_mb",
                                 0.0)))

    # -- engagement -----------------------------------------------------
    def ineligible_reason(self, sim) -> Optional[str]:
        """Why the segmented path cannot serve this sim (None = it can).

        Offload composes with the plain fused hydro step (incl. RHD).
        Anything that runs extra physics inside or around the step —
        gravity kicks, in-step cooling, PIC/cosmology drifts, tracer
        flux capture — or that holds extra references into ``sim.u``
        (step-guard snapshots, fault injection) keeps the monolithic
        window.
        """
        if not getattr(sim, "_offload_capable", False):
            return "solver family has its own step driver"
        if getattr(sim, "ndev", 1) != 1 or getattr(sim, "_comm_specs",
                                                   None):
            return "multi-device mesh"
        checks = [(sim.gravity, "self-gravity"), (sim.pic, "particles"),
                  (sim.cosmo is not None, "cosmology"),
                  (sim.cool_spec is not None, "in-step cooling"),
                  (sim.tracer_x is not None, "MC tracers"),
                  (sim.sinks is not None, "sinks"),
                  (getattr(sim, "rt_amr", None) is not None,
                   "radiative transfer"),
                  (sim.movie is not None, "movie frames"),
                  (sim.sf_spec.enabled, "star formation"),
                  (sim._sguard is not None, "step retries"),
                  (sim._fault is not None, "fault injection")]
        for bad, why in checks:
            if bad:
                return why
        from ramses_tpu import patch as _patch
        if _patch.hook("source") is not None:
            return "patch source hook"
        return None

    def _budget_bytes(self) -> Optional[int]:
        if self.budget_mb > 0:
            return int(self.budget_mb * (1 << 20))
        try:
            stats = jax.local_devices()[0].memory_stats()
            if stats and stats.get("bytes_limit"):
                return int(stats["bytes_limit"])
        except Exception:
            pass
        return None

    def estimated_bytes(self, sim) -> int:
        return sum(int(a.nbytes) for a in sim.u.values())

    def engaged(self, sim) -> bool:
        """Decide (and cache per tree rebuild) whether offload runs.

        ``_rebuild_maps`` replaces ``sim.maps`` with a fresh dict, so
        the decision is re-taken exactly when the level structure (and
        hence the resident-set estimate) changes.
        """
        if self._cache_maps is sim.maps:
            return self._cache_val
        reason = self.ineligible_reason(sim)
        if reason is not None:
            if self.mode == "on" and not self._warned:
                warnings.warn(f"&AMR_PARAMS offload=on ignored: "
                              f"{reason}")
                self._warned = True
            val = False
        elif self.mode == "on":
            val = True
        else:                                   # auto
            budget = self._budget_bytes()
            val = (budget is not None
                   and self.estimated_bytes(sim) > budget)
        if not val:
            self.unpark_all(sim)
        self._cache_maps = sim.maps
        self._cache_val = val
        return val

    # -- residency mechanics --------------------------------------------
    def _fetch(self, u: dict, unew: dict, l: int):
        """Make level ``l`` device-resident; account overlap vs stall."""
        buf = u.get(l)
        if not isinstance(buf, HostBuffer):
            return
        arr = self._inflight.pop(l, None)
        if arr is not None:
            try:
                ready = bool(arr.is_ready())
            except Exception:
                ready = True
            if ready:
                self._tot["overlapped"] += 1
            else:
                self._tot["stalls"] += 1
        else:
            self._tot["stalls"] += 1
            arr = jax.device_put(buf.host)
        self._tot["fetches"] += 1
        self._tot["bytes_fetched"] += buf.nbytes
        if unew.get(l) is buf:
            unew[l] = arr
        u[l] = arr

    def _prefetch(self, u: dict, wanted):
        if self.prefetch_depth <= 0:
            return                # stall-accounting / debugging mode
        for l in wanted:
            if isinstance(u.get(l), HostBuffer) and l not in self._inflight:
                self._inflight[l] = jax.device_put(u[l].host)
                self._tot["prefetches"] += 1

    def _evict(self, u: dict, unew: dict, l: int):
        arr = u.get(l)
        if isinstance(arr, HostBuffer) or arr is None:
            return
        if unew.get(l) is not None and unew[l] is not arr:
            return        # children folded corrections in — pinned
        if arr.nbytes < self.min_park_bytes:
            return
        if any(a is arr for _, a in self._pending):
            return
        try:
            arr.copy_to_host_async()
        except Exception:
            pass          # backends without async D2H fall back to the
        self._pending.append((l, arr))          # blocking asarray below

    def _drain(self, u: dict, unew: dict):
        """Finish pending evictions: park the host copy, free HBM."""
        keep = []
        for l, arr in self._pending:
            if u.get(l) is not arr:
                continue                        # re-fetched meanwhile
            host = np.asarray(arr)
            buf = HostBuffer(host)
            u[l] = buf
            if unew.get(l) is arr:
                unew[l] = buf
            self._tot["parks"] += 1
            self._tot["bytes_parked"] += buf.nbytes
            try:
                arr.delete()
            except Exception:
                pass
        self._pending = keep

    def _cancel_inflight(self, l: int):
        self._inflight.pop(l, None)

    def _note_hwm(self, u: dict, unew: dict):
        seen, tot = set(), 0
        for d_ in (u, unew):
            for a in d_.values():
                if isinstance(a, HostBuffer) or a is None:
                    continue
                if id(a) in seen:
                    continue
                seen.add(id(a))
                tot += int(a.nbytes)
        for a in self._inflight.values():
            tot += int(a.nbytes)
        if tot > self._hwm:
            self._hwm = tot

    def unpark_all(self, sim):
        """Fetch every parked level back to device (blocking)."""
        self._inflight.clear()
        self._pending = []
        for l, a in list(sim.u.items()):
            if isinstance(a, HostBuffer):
                sim.u[l] = jax.device_put(a.host)

    # -- the segmented coarse step --------------------------------------
    def run_step(self, sim, dt: float, spec):
        """One coarse step via per-level segments with swap points.

        Returns ``(u, dtn)`` exactly like ``_fused_coarse_step`` (flux
        capture and gravity never reach here — see
        :meth:`ineligible_reason`).
        """
        plan = plan_schedule(spec)
        u = dict(sim.u)
        unew: Dict[int, object] = {}
        dts: Dict[int, object] = {}
        levels = spec.levels
        dt_dev = jnp.asarray(float(dt), sim.dtype)
        n = len(plan)
        for k, op in enumerate(plan):
            for l in op.ws:
                self._fetch(u, unew, l)
            # double buffer: issue the next ops' uploads so they ride
            # under this op's compute
            ahead = set()
            for kk in range(k + 1, min(n, k + 1 + self.prefetch_depth)):
                ahead |= plan[kk].ws
            self._prefetch(u, ahead)
            l = levels[op.i]
            if op.kind == "enter":
                unew[l] = u[l]
            elif op.kind == "sweep":
                if spec.complete[op.i]:
                    unew[l], _ = _seg_sweep(u[l], None, unew[l], None,
                                            sim.dev[l], dt_dev, spec,
                                            op.i, op.scale)
                else:
                    unew[l], unew[l - 1] = _seg_sweep(
                        u[l], u[l - 1], unew[l], unew.get(l - 1),
                        sim.dev[l], dt_dev, spec, op.i, op.scale)
                u[l] = unew[l]
            elif op.kind == "restrict":
                u[l] = _seg_restrict(u[l], u[levels[op.i + 1]],
                                     sim.dev[l], spec, op.i)
                # the pre-restrict unew is dead until the next coarse
                # step's ENTER re-aliases it; re-alias now so the
                # corrections pin does not keep this level resident
                unew[l] = u[l]
            elif op.kind == "courant":
                dts[op.i] = _seg_courant(u[l], sim.dev[l], spec, op.i)
            self._note_hwm(u, unew)
            # park whatever the next few ops do not touch
            keep = set()
            for kk in range(k + 1, min(n, k + 1 + self.keep_ahead)):
                keep |= plan[kk].ws
            for lv in list(u):
                if lv not in keep and not isinstance(u[lv], HostBuffer):
                    self._evict(u, unew, lv)
            self._drain(u, unew)
        dtn = jnp.min(jnp.stack([dts[i] for i in range(len(levels))]))
        # between steps keep only what the next step touches first
        first = plan[0].ws | (plan[1].ws if n > 1 else frozenset())
        for kk in range(n):
            if plan[kk].kind == "sweep":
                first = first | plan[kk].ws
                break
        for lv in list(u):
            if lv not in first and not isinstance(u[lv], HostBuffer):
                self._evict(u, unew, lv)
        self._drain(u, unew)
        self._emit_stats()
        return u, dtn

    def _emit_stats(self):
        d = {k: self._tot[k] - self._mark[k] for k in self._tot}
        d["overlap_frac"] = (d["overlapped"] / d["fetches"]
                             if d["fetches"] else 1.0)
        d["device_hwm_bytes"] = self._hwm
        self.last_step_stats = d
        self._mark = dict(self._tot)
        self._hwm = 0

    # -- segmented auxiliaries (dt, flags, restrict-all) ----------------
    def coarse_dt_min(self, sim, spec) -> float:
        """Per-level Courant min with the same residency discipline."""
        u, unew = sim.u, {}
        parked0 = {l for l, a in u.items() if isinstance(a, HostBuffer)}
        dts = []
        levels = spec.levels
        for i, l in enumerate(levels):
            self._fetch(u, unew, l)
            if i + 1 < len(levels):
                self._prefetch(u, (levels[i + 1],))
            dts.append(_seg_courant(u[l], sim.dev[l], spec, i))
            if l in parked0:
                self._evict(u, unew, l)
                self._drain(u, unew)
        return float(jnp.min(jnp.stack(dts)))

    def criteria_flags_packed(self, sim, spec, eg, fls, itype: int,
                              ttd: int) -> tuple:
        """All levels' packed refinement flags, one level resident at a
        time (plus its interp source)."""
        u, unew = sim.u, {}
        parked0 = {l for l, a in u.items() if isinstance(a, HostBuffer)}
        out = []
        levels = spec.levels
        for i, l in enumerate(levels):
            need = (l,) if (spec.complete[i] or l == spec.lmin) \
                else (l - 1, l)
            for lv in need:
                self._fetch(u, unew, lv)
            if i + 1 < len(levels):
                self._prefetch(u, (levels[i + 1],))
            ulm1 = u.get(l - 1) if l > spec.lmin else None
            out.append(_seg_flags(u[l], ulm1, sim.dev[l], spec, i, eg,
                                  fls, itype, ttd))
            for lv in list(u):
                if lv < l and lv in parked0 \
                        and not isinstance(u[lv], HostBuffer):
                    self._evict(u, unew, lv)
            self._drain(u, unew)
        return tuple(out)

    def restrict_all_segmented(self, sim, spec):
        """``_restrict_all`` with at most two levels resident."""
        u, unew = sim.u, {}
        parked0 = {l for l, a in u.items() if isinstance(a, HostBuffer)}
        levels = spec.levels
        for i in range(len(levels) - 2, -1, -1):
            l, lf = levels[i], levels[i + 1]
            for lv in (l, lf):
                self._fetch(u, unew, lv)
            if i > 0:
                self._prefetch(u, (levels[i - 1],))
            u[l] = _seg_restrict(u[l], u[lf], sim.dev[l], spec, i)
            if lf in parked0:
                self._evict(u, unew, lf)
                self._drain(u, unew)
