"""HLO-level rules: hazard classes read off the lowered StableHLO.

Each rule is grounded in a documented incident from this repo's
history (see the rule docstrings).  All of them run on the CPU test
backend from a *lowering* (trace only, no compile), so the whole
audit costs seconds and runs in CI on every push.

The checkers work on :class:`ramses_tpu.analysis.programs.Program`
objects but only duck-type them: anything with ``.name``, ``.text``
and ``.meta`` works, which is what the telemetry run-header hook and
the fixture tests exploit.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List

from ramses_tpu.analysis.rules import Finding, Rule, Severity, register
from ramses_tpu.telemetry import hlo as _hlo

# ---------------------------------------------------------------------
# shared StableHLO text probes
# ---------------------------------------------------------------------
_TENSOR_RE = re.compile(r"tensor<([0-9x]*?)x?([a-z][a-z0-9]*)>")
_CONST_RE = re.compile(
    r"stablehlo\.constant\b[^\n]*?:\s*tensor<([0-9x]*?)x?"
    r"([a-z][a-z0-9]*)>")
_ARG_RE = re.compile(r"%arg\d+: tensor<([0-9x]*?)x?([a-z][a-z0-9]*)>")
# donation shows up as tf.aliasing_output (fixed output aliasing) or
# jax.buffer_donor (compiler-chosen aliasing — what jit emits for
# committed/sharded inputs)
_DONATION_MARKERS = ("tf.aliasing_output", "jax.buffer_donor")
_SCATTER_RE = re.compile(r'"stablehlo\.scatter"')
_NUM_PARTITIONS_RE = re.compile(r"mhlo\.num_partitions = (\d+)")

_BITS = {"f64": 64, "f32": 32, "f16": 16, "bf16": 16, "f8": 8,
         "i64": 64, "ui64": 64, "i32": 32, "ui32": 32, "i16": 16,
         "ui16": 16, "i8": 8, "ui8": 8, "i1": 1, "pred": 1}


def _elems(dims_txt: str) -> int:
    n = 1
    for d in dims_txt.split("x"):
        if d:
            n *= int(d)
    return n


def _nbytes(dims_txt: str, dty: str) -> int:
    return (_elems(dims_txt) * _BITS.get(dty, 32) + 7) // 8


def is_partitioned(text: str) -> bool:
    """True when the lowered module targets >1 GSPMD partition (the
    regime where scatter-add reassociation is nondeterministic)."""
    m = _NUM_PARTITIONS_RE.search(text)
    return bool(m) and int(m.group(1)) > 1


def main_args(text: str):
    """``(dims, dtype, attrs)`` per ``@main`` argument of the lowered
    module.  ``attrs`` is the raw text between this argument's type
    and the next argument (sharding strings nest braces, so a plain
    ``\\{[^}]*\\}`` capture truncates — slicing arg-to-arg does not)."""
    m = re.search(r"func\.func public @main\((.*?)\)\s*(->|\{)", text,
                  re.DOTALL)
    if not m:
        return []
    sig = m.group(1)
    hits = list(_ARG_RE.finditer(sig))
    out = []
    for i, h in enumerate(hits):
        end = hits[i + 1].start() if i + 1 < len(hits) else len(sig)
        out.append((h.group(1), h.group(2), sig[h.end():end]))
    return out


def _is_donated(attrs: str) -> bool:
    return any(mk in attrs for mk in _DONATION_MARKERS)


# ---------------------------------------------------------------------
# gather-blowup  (PR 8: the 6^d-duplicated stencil gather)
# ---------------------------------------------------------------------
def check_gather_ratio(text_ref: str, text: str,
                       min_ratio: float = 2.0):
    """``(ok, ref_elems, elems)`` — the blocked/optimized program must
    gather at least ``min_ratio``x fewer RESULT elements than the
    reference formulation.  This IS the legacy
    ``test_hlo_inventory.py`` >=2x gate; the test and the lint rule
    both call it so they cannot drift."""
    ref = _hlo.count_gather_elems(text_ref)
    cur = _hlo.count_gather_elems(text)
    return ref >= min_ratio * cur, ref, cur


def _check_gather_blowup(program) -> List[Finding]:
    meta = program.meta
    out: List[Finding] = []
    elems = _hlo.count_gather_elems(program.text)
    ops = _hlo.raw_gather_count(program.text)
    budget = meta.get("gather_budget_elems")
    if budget is not None and elems > budget:
        out.append(Finding(
            rule="gather-blowup", severity=Severity.ERROR,
            program=program.name,
            message=(f"lowered program gathers {elems:,} result "
                     f"elements, over its budget of {budget:,} "
                     f"({ops} gather ops) — the PR 8 duplicated-"
                     "stencil regression class"),
            key="budget",
            detail={"elems": elems, "budget": budget, "ops": ops}))
    ref_text = meta.get("gather_ref_text")
    if ref_text is not None:
        min_ratio = float(meta.get("min_gather_ratio", 2.0))
        ok, ref, cur = check_gather_ratio(ref_text, program.text,
                                          min_ratio)
        if not ok:
            out.append(Finding(
                rule="gather-blowup", severity=Severity.ERROR,
                program=program.name,
                message=(f"blocked formulation gathers {cur:,} "
                         f"elements vs {ref:,} on the stencil path "
                         f"— under the required {min_ratio:g}x win"),
                key="ratio",
                detail={"elems": cur, "ref_elems": ref,
                        "min_ratio": min_ratio}))
    return out


register(Rule(
    id="gather-blowup", kind="hlo", check=_check_gather_blowup,
    doc=("PR 8: partial-level sweeps once gathered a 6^d-duplicated "
         "per-oct stencil batch (160M elements on the evolved Sedov "
         "tree).  Gates the gathered RESULT element count of the "
         "lowered fused step against a per-program budget and/or a "
         "minimum win ratio over the stencil formulation.")))


# ---------------------------------------------------------------------
# large-constant-capture  (PR 10: the ct_core closed-over table)
# ---------------------------------------------------------------------
CONST_LIMIT_BYTES = 65536


def _check_large_constant(program) -> List[Finding]:
    limit = int(program.meta.get("const_limit_bytes",
                                 CONST_LIMIT_BYTES))
    hits: Dict[str, Dict[str, Any]] = {}
    for dims, dty in _CONST_RE.findall(program.text):
        nb = _nbytes(dims, dty)
        if nb < limit:
            continue
        ty = f"tensor<{dims + 'x' if dims else ''}{dty}>"
        h = hits.setdefault(ty, {"bytes": nb, "count": 0})
        h["count"] += 1
    return [Finding(
        rule="large-constant-capture", severity=Severity.ERROR,
        program=program.name,
        message=(f"{h['count']} stablehlo.constant op(s) of {ty} "
                 f"({h['bytes']:,} B >= {limit:,} B) baked into the "
                 "jitted step body — closed-over arrays replicate "
                 "per partition and defeat donation (the PR 10 "
                 "ct_core remat source); pass them as arguments"),
        key=ty, detail=h) for ty, h in sorted(hits.items())]


register(Rule(
    id="large-constant-capture", kind="hlo",
    check=_check_large_constant,
    doc=("PR 10: mhd/uniform.py ct_core closed over a gather-index "
         "table; XLA baked it into the program as a constant, the "
         "SPMD partitioner could only replicate it, and every coarse "
         "step paid an involuntary full rematerialization.  Flags "
         "any stablehlo.constant over a size threshold inside a "
         "jitted step body.")))


# ---------------------------------------------------------------------
# nondeterministic-scatter  (ROADMAP 2: MHD 1-ulp GSPMD scatter)
# ---------------------------------------------------------------------
def _check_nondet_scatter(program) -> List[Finding]:
    text = program.text
    partitioned = program.meta.get("partitioned")
    if partitioned is None:
        partitioned = is_partitioned(text)
    if not partitioned:
        return []
    hits: Dict[str, int] = {}
    for m in _SCATTER_RE.finditer(text):
        window = text[m.start():m.start() + 4000]
        if "unique_indices = false" not in window:
            continue
        body_end = window.find("}) :")
        body = window[:body_end if body_end > 0 else None]
        if "stablehlo.add" not in body:
            continue                # overwrite scatters reorder safely
        tym = re.search(r"\)\s*->\s*\(?\s*(tensor<[^>]+>)",
                        window[body_end if body_end > 0 else 0:])
        ty = tym.group(1) if tym else "tensor<?>"
        hits[ty] = hits.get(ty, 0) + 1
    return [Finding(
        rule="nondeterministic-scatter", severity=Severity.WARN,
        program=program.name,
        message=(f"{n} scatter-add op(s) onto {ty} with "
                 "unique_indices=false in a GSPMD-partitioned "
                 "program — the partitioner may reassociate the "
                 "float adds across shards (the MHD mesh-of-8 ~1-ulp "
                 "drift); route through the deterministic owner-fold "
                 "(amr_comm.sweep_correct_explicit) or mark indices "
                 "unique"),
        key=ty, detail={"count": n, "result": ty})
        for ty, n in sorted(hits.items())]


register(Rule(
    id="nondeterministic-scatter", kind="hlo",
    check=_check_nondet_scatter,
    doc=("ROADMAP item 2: MHD partial-level corrections folded "
         "through a GSPMD scatter-add agreed with the mesh-of-1 run "
         "only to ~1 ulp — scatter-adds whose indices are not "
         "declared unique let the partitioner reassociate float "
         "sums.  Flags non-unique scatter-adds in partitioned "
         "programs.")))


# ---------------------------------------------------------------------
# donation-miss  (PR 2 donation plumbing; BASELINE copy regressions)
# ---------------------------------------------------------------------
DONATION_LIMIT_BYTES = 8 << 20


def _check_donation(program) -> List[Finding]:
    args = main_args(program.text)
    out: List[Finding] = []
    donated = sum(1 for _, _, attrs in args if _is_donated(attrs))
    if program.meta.get("expect_donation") and donated == 0:
        out.append(Finding(
            rule="donation-miss", severity=Severity.ERROR,
            program=program.name,
            message=("step chain declared donating but NO lowered "
                     "argument carries a donation marker "
                     "(tf.aliasing_output / jax.buffer_donor) — the "
                     "donation was dropped and every step pays a "
                     "full state copy"),
            key="no-aliasing", detail={"args": len(args)}))
    limit = int(program.meta.get("donation_limit_bytes",
                                 DONATION_LIMIT_BYTES))
    undonated: Dict[str, Dict[str, Any]] = {}
    for dims, dty, attrs in args:
        nb = _nbytes(dims, dty)
        if nb < limit or _is_donated(attrs):
            continue
        ty = f"tensor<{dims + 'x' if dims else ''}{dty}>"
        h = undonated.setdefault(ty, {"bytes": nb, "count": 0})
        h["count"] += 1
    for ty, h in sorted(undonated.items()):
        out.append(Finding(
            rule="donation-miss", severity=Severity.WARN,
            program=program.name,
            message=(f"{h['count']} large input(s) of {ty} "
                     f"({h['bytes']:,} B >= {limit:,} B) never "
                     "donated — a step-chain buffer of this size "
                     "doubles its HBM footprint"),
            key=ty, detail=h))
    return out


register(Rule(
    id="donation-miss", kind="hlo", check=_check_donation,
    doc=("PR 2 added donate_argnums to the fused step chains so the "
         "scan carry aliases its input buffers.  A refactor that "
         "drops the donation (or adds a large undonated buffer) "
         "silently doubles the state footprint; the lowered module "
         "shows it as missing tf.aliasing_output arg attributes.")))


# ---------------------------------------------------------------------
# f64-leak  (x64-enabled hosts tracing f64 into f32 programs)
# ---------------------------------------------------------------------
_F64_RE = re.compile(r"tensor<(?:[0-9x]+x)?f64>")


def _check_f64_leak(program) -> List[Finding]:
    if int(program.meta.get("dtype_bits", 0)) != 32:
        return []                   # only f32-configured programs
    n = len(_F64_RE.findall(program.text))
    if n == 0:
        return []
    return [Finding(
        rule="f64-leak", severity=Severity.WARN,
        program=program.name,
        message=(f"{n} f64 tensor type(s) inside an f32-configured "
                 "program — a host scalar or numpy table traced at "
                 "double precision (2x the bandwidth, and TPUs "
                 "emulate f64); cast at the jit boundary"),
        key="f64", detail={"count": n})]


register(Rule(
    id="f64-leak", kind="hlo", check=_check_f64_leak,
    doc=("The test suite enables jax x64, so an uncast python float "
         "or np.float64 table reaching a trace drags f64 ops into "
         "f32 production programs.  Flags any f64 tensor type in a "
         "program whose configured dtype is f32.")))
