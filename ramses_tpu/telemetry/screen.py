"""The RAMSES-style ``write_screen`` console sink.

One formatting module for every screen line the drivers print — the
per-``ncontrol`` control block (``amr/adaptive_loop.f90:199-214`` +
memory census, previously inlined in ``utils/ops.OpsGuard``) and the
per-step/per-chunk ``verbose`` line (previously ad-hoc ``print()``
calls in each driver).  Routing them here means ``verbose`` is pure
formatting: it no longer forces the per-step slow path — the chunked
fast path reports the same line from its chunk summary.

Everything here is host-side string building over values the caller
already holds; the only device fetch is the amortized conservation
audit the OpsGuard cadence explicitly requests (``audit=True``).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def step_line(sim, dt: Optional[float] = None, chunk: int = 0,
              extra: str = "") -> str:
    """The per-step ``verbose`` line; with ``chunk=n`` it summarizes n
    fused coarse steps from one ``step_chunk`` dispatch."""
    nstep = getattr(sim, "nstep", None)
    t = getattr(sim, "t", None)
    if nstep is None and hasattr(sim, "state"):     # uniform driver
        nstep, t = sim.state.nstep, sim.state.t
    line = f"step {int(nstep):6d}  t={float(t):.6e}"
    if dt is None:
        dt = getattr(sim, "dt_old", None)
    if dt is not None:
        line += f" dt={float(dt):.3e}"
    if getattr(sim, "cell_updates", 0) and hasattr(
            sim, "mus_per_cell_update"):
        line += f" mus/pt={sim.mus_per_cell_update():.4f}"
    if hasattr(sim, "tree"):
        line += f" octs={[sim.tree.noct(l) for l in sim.levels()]}"
    if chunk > 1:
        line += f" chunk={chunk}"
    return line + ((" " + extra) if extra else "")


def control_block(sim, max_rss: float = 0.0,
                  dev_mb: Optional[float] = None,
                  audit: bool = False, extra: str = "") -> str:
    """The reference's per-``ncontrol`` control line
    (``adaptive_loop.f90:199-214`` + ``amr/memory.f90`` census).

    ``audit=True`` adds the mcons/econs conservation line and the
    rt photon budget — both sync device state, so callers amortize
    (OpsGuard's ``cons_every``).  ``dev_mb``: pass a pre-sampled
    device-memory figure to keep this call fetch-free.
    """
    if dev_mb is None:
        from ramses_tpu.utils.ops import device_mb
        dev_mb = device_mb()
    octs = {l: sim.tree.noct(l) for l in sim.levels()} \
        if hasattr(sim, "tree") else {}
    line = (f" Main step={getattr(sim, 'nstep', 0):7d} "
            f"t={getattr(sim, 't', 0.0):13.6e} "
            f"dt={getattr(sim, 'dt_old', 0.0):11.4e} "
            f"mem={max_rss:8.1f}M/{dev_mb:8.1f}M")
    if hasattr(sim, "totals") and audit:
        # conservation audit line (the reference's mcons/econs print,
        # ``amr/update_time.f90`` output block) — amortized: totals()
        # syncs the full device state
        raw = sim.totals()
        if isinstance(raw, dict):          # uniform-grid totals() dicts
            line += f" mcons={float(raw.get('mass', 0.0)):.6e}"
            if "energy" in raw:
                line += f" econs={float(raw['energy']):.6e}"
        else:
            tot = np.asarray(raw)
            ie = getattr(getattr(sim, "cfg", None), "ienergy", None)
            line += f" mcons={tot[0]:.6e}"
            if ie is not None and ie < len(tot):
                line += f" econs={tot[ie]:.6e}"
    if hasattr(sim, "aexp_now") and getattr(sim, "cosmo", None) is not None:
        line += f" a={sim.aexp_now():8.5f}"
    bs = getattr(sim, "balance_stats", None)
    if bs is not None:
        # load-balance observability (the reference's load_balance
        # screen report): per-device cost extrema + rebalance count
        line += (f" lb[max/mean={bs.max_cost:.4g}/{bs.mean_cost:.4g}"
                 f" imb={bs.imbalance:.3f}"
                 f" nreb={getattr(sim, '_rebalance_count', 0)}]")
    rt = getattr(sim, "rt_amr", None) or getattr(sim, "rt", None)
    if rt is not None and hasattr(rt, "rt_stats") and audit:
        # photon budget line (the reference's output_rt_stats,
        # amr/amr_step.f90:467): total photons vs cumulative injected —
        # the conservation ratio drops as gas absorbs
        st = rt.rt_stats(sim)
        line += (f" rt[N={st['photons']:.4e}"
                 f" inj={st['injected']:.4e}"
                 f" ratio={st['ratio']:.4f}]")
    if octs:
        line += f" octs={octs}"
    return line + (" " + extra if extra else "")
