"""SRHD state conversions, EOS, wave speeds.

Reference: ``rhd/`` (own ``umuscl.f90``/``godunov_utils.f90`` with
con→prim recovery and the TM equation of state, SURVEY.md §2.4).

State (units c=1):
  conservative u = [D, S_x, S_y, S_z, τ]        (+ passive D·X)
    D = ρΓ,  S_i = ρ h Γ² v_i,  τ = ρ h Γ² − P − D
  primitive  q = [ρ, v_x, v_y, v_z, P]

EOS through the specific enthalpy h(ρ, P):
  ideal:  h = 1 + γ/(γ−1)·Θ
  tm:     h = 2.5Θ + sqrt(2.25Θ² + 1)   (Taub-Mathews; γ_eff 5/3→4/3)
with Θ = P/ρ.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ramses_tpu.config import Params

NCOMP = 3


@dataclass(frozen=True)
class RhdStatic:
    # class tag the AMR kernels dispatch on (``amr/kernels._physics``)
    physics = "rhd"

    ndim: int = 1
    npassive: int = 0
    gamma: float = 5.0 / 3.0
    eos: str = "ideal"          # ideal | tm
    smallr: float = 1e-10
    smallp: float = 1e-14
    smallc: float = 1e-10       # dtmax-cap floor (c=1 units)
    slope_type: int = 1
    slope_theta: float = 1.5
    courant_factor: float = 0.8
    niter: int = 30             # con→prim Newton iterations
    # trailing-batch layout flag for the AMR oct batches (see
    # ``hydro/muscl._axis`` / ``hydro/core.HydroStatic.trailing_batch``)
    trailing_batch: bool = False

    @property
    def nvar(self) -> int:
        return 5 + self.npassive

    @classmethod
    def from_params(cls, p: Params) -> "RhdStatic":
        h = p.hydro
        raw = p.raw.get("hydro_params", {}) if p.raw else {}
        eos = str(raw.get("eos", "ideal")).strip("'\" ").lower()
        return cls(ndim=p.ndim, npassive=p.npassive, gamma=float(h.gamma),
                   eos=eos, smallr=float(h.smallr),
                   slope_type=int(h.slope_type),
                   slope_theta=float(h.slope_theta),
                   courant_factor=float(h.courant_factor))


def enthalpy(rho, p, cfg: RhdStatic):
    theta = p / jnp.maximum(rho, cfg.smallr)
    if cfg.eos == "tm":
        return 2.5 * theta + jnp.sqrt(2.25 * theta ** 2 + 1.0)
    return 1.0 + cfg.gamma / (cfg.gamma - 1.0) * theta


def sound_speed2(rho, p, cfg: RhdStatic):
    """Relativistic cs² = (∂p/∂e)|_s / h-weighted; ideal: γp/(ρh).
    TM: cs² = Θ(5h−8Θ)/(3h(h−Θ)) (Mignone+2005 eq. for TM)."""
    theta = p / jnp.maximum(rho, cfg.smallr)
    h = enthalpy(rho, p, cfg)
    if cfg.eos == "tm":
        return theta * (5.0 * h - 8.0 * theta) / (
            3.0 * h * jnp.maximum(h - theta, 1e-30))
    return cfg.gamma * theta / jnp.maximum(h, 1e-30)


def prim_to_cons(q, cfg: RhdStatic):
    rho = jnp.maximum(q[0], cfg.smallr)
    v = [q[1 + c] for c in range(NCOMP)]
    p = jnp.maximum(q[4], cfg.smallp)
    v2 = sum(vc * vc for vc in v)
    lor = 1.0 / jnp.sqrt(jnp.maximum(1.0 - v2, 1e-14))
    h = enthalpy(rho, p, cfg)
    D = rho * lor
    w = rho * h * lor ** 2
    comps = [D] + [w * vc for vc in v] + [w - p - D]
    for s in range(cfg.npassive):
        comps.append(D * q[5 + s])
    return jnp.stack(comps)


def cons_to_prim(u, cfg: RhdStatic):
    """Newton recovery of (ρ, v, P) from (D, S, τ).

    Root of f(P) = ρ(P)·h(ρ,P)·Γ(P)² − P − (τ+D) with
    v² = S²/(τ+D+P)², Γ = 1/√(1−v²), ρ = D/Γ — the standard SRHD
    pressure iteration (the rhd godunov_utils recovery), fixed-iteration
    for jit with a bisection-safe clamp.
    """
    D = jnp.maximum(u[0], cfg.smallr)
    S = [u[1 + c] for c in range(NCOMP)]
    tau = u[4]
    S2 = sum(s * s for s in S)
    E = tau + D                              # ρhΓ² − P

    # initial guess: nonrelativistic-ish
    p = jnp.maximum((cfg.gamma - 1.0) * (tau - 0.5 * S2
                                         / jnp.maximum(E, 1e-30)),
                    cfg.smallp)

    def body(i, p):
        """Classic pressure Newton: f(p) = p_eos(ρ, ε) − p with
        f' ≈ v²cs² − 1, where ε = (E+p)(1−v²) − ρ − p per unit ρ.
        Ideal gas: p_eos = (γ−1)ρε.  TM: the exact closure
        p = ρ·ε(ε+2)/(3(1+ε)) (from h = 1+ε+θ in 4θ²−5hθ+h²−1=0)."""
        wtot = E + p
        v2 = jnp.clip(S2 / jnp.maximum(wtot ** 2, 1e-30), 0.0,
                      1.0 - 1e-12)
        lor = 1.0 / jnp.sqrt(1.0 - v2)
        rho = jnp.maximum(D / lor, cfg.smallr)
        eps = jnp.maximum((wtot * (1.0 - v2) - rho - p) / rho, 1e-14)
        if cfg.eos == "tm":
            p_eos = rho * eps * (eps + 2.0) / (3.0 * (1.0 + eps))
        else:
            p_eos = (cfg.gamma - 1.0) * rho * eps
        f = p_eos - p
        cs2 = jnp.clip(sound_speed2(rho, jnp.maximum(p, cfg.smallp), cfg),
                       0.0, 1.0 - 1e-12)
        dfdp = v2 * cs2 - 1.0
        return jnp.maximum(p - f / dfdp, cfg.smallp)

    p = jax.lax.fori_loop(0, cfg.niter, body, p)
    wtot = E + p
    v2 = jnp.clip(S2 / jnp.maximum(wtot ** 2, 1e-30), 0.0, 1.0 - 1e-12)
    lor = 1.0 / jnp.sqrt(1.0 - v2)
    rho = jnp.maximum(D / lor, cfg.smallr)
    v = [s / jnp.maximum(wtot, 1e-30) for s in S]
    comps = [rho] + v + [jnp.maximum(p, cfg.smallp)]
    for sidx in range(cfg.npassive):
        comps.append(u[5 + sidx] / D)
    return jnp.stack(comps)


def theta_of_h(h):
    """Exact θ(h) for the TM EOS: h = 2.5θ + √(2.25θ²+1) ⇒
    4θ² − 5hθ + (h²−1) = 0 ⇒ θ = (5h − √(9h² + 16))/8… check:
    (h−2.5θ)² = 2.25θ²+1 ⇒ h² −5hθ +6.25θ² = 2.25θ²+1 ⇒
    4θ² − 5hθ + (h²−1) = 0, physical (smaller) root."""
    disc = jnp.sqrt(jnp.maximum(25.0 * h * h - 16.0 * (h * h - 1.0), 0.0))
    return (5.0 * h - disc) / 8.0


def lorentz(q):
    v2 = sum(q[1 + c] ** 2 for c in range(NCOMP))
    return 1.0 / jnp.sqrt(jnp.maximum(1.0 - v2, 1e-14))


def flux_along(q, d: int, cfg: RhdStatic):
    """Physical SRHD flux along component d from primitives."""
    u = prim_to_cons(q, cfg)
    vd = q[1 + d]
    p = q[4]
    comps = [u[0] * vd]
    for c in range(NCOMP):
        f = u[1 + c] * vd
        if c == d:
            f = f + p
        comps.append(f)
    comps.append(u[1 + d] - u[0] * vd)       # F(τ) = S_n − D v_n
    for s in range(cfg.npassive):
        comps.append(u[5 + s] * vd)
    return jnp.stack(comps)


def wave_speeds(q, d: int, cfg: RhdStatic):
    """Relativistic characteristic speeds λ± along d (Mignone & Bodo)."""
    rho = jnp.maximum(q[0], cfg.smallr)
    p = jnp.maximum(q[4], cfg.smallp)
    cs2 = jnp.clip(sound_speed2(rho, p, cfg), 1e-16, 1.0 - 1e-12)
    v2 = jnp.clip(sum(q[1 + c] ** 2 for c in range(NCOMP)), 0.0,
                  1.0 - 1e-12)
    vn = q[1 + d]
    den = 1.0 - v2 * cs2
    disc = cs2 * (1.0 - v2) * (1.0 - v2 * cs2
                               - vn * vn * (1.0 - cs2))
    root = jnp.sqrt(jnp.maximum(disc, 0.0))
    lam_p = (vn * (1.0 - cs2) + root) / den
    lam_m = (vn * (1.0 - cs2) - root) / den
    return lam_m, lam_p
