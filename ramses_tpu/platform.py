"""Backend selection helpers.

The deployment image's ``sitecustomize`` registers a TPU-tunnel ("axon")
PJRT plugin in every interpreter and forces ``jax_platforms="axon,cpu"``
through ``jax.config`` — overriding the ``JAX_PLATFORMS`` environment
variable.  Anything that must run on a virtual multi-device CPU mesh
(the reference suite's same-host multi-rank trick,
``tests/run_test_suite.sh:78-82``) has to force the CPU platform back
*before the first backend is instantiated*.  This module is the single
home for that workaround; ``tests/conftest.py`` and
``__graft_entry__.dryrun_multichip`` both use it.
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_cpu_mesh(n_devices: int):
    """Force the CPU backend with ``n_devices`` virtual devices.

    Safe to call more than once with the same count.  Raises if a JAX
    backend was already initialized on a different platform or with
    fewer devices — a loud failure instead of a silently-smaller mesh.
    Returns the first ``n_devices`` devices.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    flag = f"{_COUNT_FLAG}={n_devices}"
    if _COUNT_FLAG in flags:
        flags = re.sub(rf"{_COUNT_FLAG}=\d+", flag, flags)
    else:
        flags = (flags + " " + flag).strip()
    os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")
    devices = jax.devices()
    if devices[0].platform != "cpu":
        raise RuntimeError(
            f"CPU platform could not be forced: backend already "
            f"initialized on {devices[0].platform!r}. Call force_cpu_mesh "
            f"before any other jax use in the process.")
    if len(devices) < n_devices:
        raise RuntimeError(
            f"requested {n_devices} virtual CPU devices but the backend "
            f"has {len(devices)}; it was initialized before XLA_FLAGS "
            f"could be updated.")
    return devices[:n_devices]
