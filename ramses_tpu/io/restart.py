"""Checkpoint-resume: rebuild simulation state from a snapshot directory.

The reference restarts by re-reading its own dump inside ``init_amr`` /
``init_hydro`` / ``init_part`` (``nrestart>0``, SURVEY.md §5.4).  Here the
same files restore the host octree, per-level conservative state, and the
particle set.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from ramses_tpu.io import reader as rdr
from ramses_tpu.io.snapshot import prim_out_to_cons, ref_cell_perm


def restore_tree_state(outdir: str, cfg, levelmin: int, to_cons=None):
    """(tree_levels, u_levels, meta): per-level oct coords and conservative
    cell arrays (our x-slowest flat order) for levels >= levelmin.

    ``to_cons(q_rows)``: output-variable → stored-state conversion;
    defaults to the hydro ``prim_out_to_cons``.  MHD restores pass a
    converter for its extended column set (or identity to get the raw
    output rows)."""
    snap = rdr.load_snapshot(outdir)
    ncpu = len(snap["amr"])
    h = snap["amr"][0].header
    ndim = h["ndim"]
    perm = ref_cell_perm(ndim)
    inv = np.argsort(perm)                  # our off → ref ind

    # concatenate every domain's levels (``init_amr``'s multi-cpu read:
    # each file holds its own contiguous key range, any count merges)
    tree_og: Dict[int, np.ndarray] = {}
    u_lv: Dict[int, np.ndarray] = {}
    for l in sorted({lv for amr in snap["amr"] for lv in amr.levels}):
        if l < levelmin:
            continue
        scale = 2.0 ** (l - 1)
        ogs, qs = [], []
        for amr, hyd in zip(snap["amr"], snap["hydro"]):
            lev = amr.levels.get(l)
            if lev is None or len(lev["xg"]) == 0:
                continue
            ogs.append(np.rint(lev["xg"] * scale - 0.5).astype(np.int64))
            vals = hyd["levels"][l]         # [n, 2^d, nvar] ref order
            qs.append(vals[:, inv])         # our cell order
        if not ogs:
            continue
        tree_og[l] = np.concatenate(ogs)
        q = np.concatenate(qs).reshape(-1, qs[0].shape[2])
        u_lv[l] = (prim_out_to_cons(q, cfg) if to_cons is None
                   else to_cons(q))
    meta = dict(t=h["t"], nstep=h["nstep"], iout=h["iout"],
                aexp=h["aexp"], boxlen=h["boxlen"],
                nlevelmax=h["nlevelmax"], dtold=h["dtold"],
                dtnew=h["dtnew"], info=snap["info"])
    parts = None
    if "part" in snap:
        # concatenate array fields across domains; scalar header
        # entries (ncpu, npart, nstar_tot, …) come from file 1 with the
        # count totals recomputed
        first = snap["part"][0]
        parts = {}
        for k, v in first.items():
            if isinstance(v, np.ndarray):
                parts[k] = np.concatenate([p[k] for p in snap["part"]])
            else:
                parts[k] = v
        if "npart" in first:
            parts["npart"] = sum(int(p["npart"]) for p in snap["part"])
        parts["fields"] = snap["part_fields"]
    return tree_og, u_lv, meta, parts


def restore_particles(parts: dict, ndim: int, nmax: Optional[int] = None):
    """Rebuild a :class:`ParticleSet` from a read particle file.

    ``nmax`` (clamped to the stored count) sets the lane headroom for
    runs that keep creating particles (SF/sinks).  Birth times and
    metallicities round-trip when the file carries the star records
    (``pm/output_part.f90`` optional ``birth_time``/``metallicity``)."""
    import dataclasses

    import jax.numpy as jnp

    from ramses_tpu.pm.particles import ParticleSet
    if parts is None:
        return None
    dims = "xyz"[:ndim]
    x = np.stack([parts[f"position_{d}"] for d in dims], axis=1)
    v = np.stack([parts[f"velocity_{d}"] for d in dims], axis=1)
    nmax = max(nmax or 0, len(x)) or None
    ps = ParticleSet.make(x, v, parts["mass"],
                          idp=parts["identity"].astype(np.int64),
                          family=parts["family"], nmax=nmax)
    pad = ps.n - len(x)
    for key, attr in (("birth_time", "tp"), ("metallicity", "zp")):
        if key in parts:
            ps = dataclasses.replace(ps, **{attr: jnp.asarray(
                np.pad(np.asarray(parts[key], np.float64), (0, pad)),
                getattr(ps, attr).dtype)})
    return ps


def restore_uniform(outdir: str, params, cfg,
                    to_cons=None) -> Tuple[np.ndarray, dict,
                                           Optional[dict]]:
    """Dense [nvar, *sp] conservative state for a single-level run.

    ``to_cons`` overrides the hydro output→conservative conversion for
    other solver families (the SRHD pressure-Newton inverse)."""
    base = [params.amr.nx, params.amr.ny, params.amr.nz][:cfg.ndim]
    if any(b != 1 for b in base) \
            and getattr(cfg, "physics", "hydro") != "hydro":
        # non-cubic support is end-to-end for the hydro family only;
        # the SRHD/MHD drivers build cubic grids (their constructors
        # refuse too — this keeps the restore path equally loud)
        raise NotImplementedError(
            "snapshot restore with nx,ny,nz != 1 is hydro-only "
            f"(got {base})")
    lmin = params.amr.levelmin
    tree_og, u_lv, meta, parts = restore_tree_state(outdir, cfg, lmin,
                                                    to_cons=to_cons)
    if lmin not in u_lv:
        raise ValueError(f"snapshot has no level {lmin} data")
    from ramses_tpu.amr.tree import cell_offsets
    og = tree_og[lmin]
    ndim = cfg.ndim
    n = 1 << lmin
    offs = cell_offsets(ndim)
    cc = (2 * og[:, None, :] + offs[None, :, :]).reshape(-1, ndim)
    dense = np.zeros((cfg.nvar,)
                     + tuple(base[d] * n for d in range(ndim)))
    u = u_lv[lmin]                          # [ncell, nvar]
    idx = tuple(cc[:, d] for d in range(ndim))
    for iv in range(cfg.nvar):
        dense[iv][idx] = u[:, iv]
    return dense, meta, parts
