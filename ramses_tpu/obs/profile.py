"""On-demand device profiling picked up at chunk boundaries.

A consumer arms a job by writing ``profile_request.json`` into its
results dir (``POST /jobs/<id>/profile`` does exactly this); the serve
loop polls the flag at every chunk boundary — the one safe point where
no fused window is in flight — wraps the next N chunks in the existing
``utils/timers.profile_trace`` jax.profiler hook, then writes a
manifest over the trace dir so it shows up as a validated artifact
under ``/jobs/<id>/artifacts``.  An idle worker pays one ``os.path
.isfile`` per chunk; nothing else changes when no request is pending.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

from ramses_tpu.utils.timers import profile_trace

#: flag-file name inside a job's results dir; one request = one capture
PROFILE_FLAG = "profile_request.json"


def request_profile(results_dir: str, chunks: int = 1) -> str:
    """Arm a profile capture of the next ``chunks`` chunk boundaries
    (the filesystem-level equivalent of ``POST /jobs/<id>/profile``).
    Returns the flag path."""
    os.makedirs(results_dir, exist_ok=True)
    flag = os.path.join(results_dir, PROFILE_FLAG)
    tmp = flag + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"chunks": max(1, int(chunks)),
                   "requested_unix": time.time()}, f)
    os.replace(tmp, flag)
    return flag


class ProfileRequestWatcher:
    """Per-job profiling state machine driven from the chunk loop.

    ``poll(telemetry)`` is called after every finished chunk: it opens
    a device trace when a request flag appears, counts armed chunks
    down, and closes/registers the trace dir when they are spent.
    ``_profile_cm`` is the capture hook (``profile_trace`` in
    production) — a staticmethod so tests swap in a fake profiler
    without touching jax.
    """

    _profile_cm = staticmethod(profile_trace)

    def __init__(self, results_dir: str, log=None):
        self.results_dir = results_dir
        self.log = log
        self._cm = None
        self._chunks_left = 0
        self._seq = 0
        self.trace_dir = ""

    @property
    def active(self) -> bool:
        return self._cm is not None

    def poll(self, telemetry=None) -> None:
        """One chunk boundary: pick up a pending request, or advance /
        close an active capture."""
        if self._cm is not None:
            self._chunks_left -= 1
            if self._chunks_left <= 0:
                self._finish(telemetry)
            return
        flag = os.path.join(self.results_dir, PROFILE_FLAG)
        if not os.path.isfile(flag):
            return
        try:
            with open(flag) as f:
                req: Dict[str, Any] = json.load(f) or {}
        except (OSError, ValueError):
            req = {}
        try:
            os.remove(flag)     # consume exactly one request
        except OSError:
            return              # a racing attempt consumed it first
        self._seq += 1
        tdir = os.path.join(self.results_dir,
                            f"profile_{self._seq:04d}")
        try:
            cm = self._profile_cm(tdir)
            cm.__enter__()
        except Exception as e:  # noqa: BLE001 — profiling is optional
            self._event(telemetry, "profile_error", error=repr(e))
            if self.log is not None:
                self.log(f"obs: profile request failed: {e!r}")
            return
        self._cm = cm
        self._chunks_left = max(1, int(req.get("chunks", 1) or 1))
        self.trace_dir = tdir
        self._event(telemetry, "profile_start", trace_dir=tdir,
                    chunks=self._chunks_left)
        if self.log is not None:
            self.log(f"obs: profiling {self._chunks_left} chunk(s) "
                     f"-> {tdir}")

    def stop(self, telemetry=None) -> None:
        """End-of-job safety: close a capture the chunk countdown never
        finished (job completed or errored mid-capture)."""
        if self._cm is not None:
            self._finish(telemetry)

    def _finish(self, telemetry) -> None:
        cm, self._cm = self._cm, None
        try:
            cm.__exit__(None, None, None)
        except Exception as e:  # noqa: BLE001
            self._event(telemetry, "profile_error", error=repr(e),
                        trace_dir=self.trace_dir)
            return
        # manifest over the trace dir: /jobs/<id>/artifacts lists it as
        # a validated artifact like any checkpoint
        try:
            from ramses_tpu.resilience.checkpoint import write_manifest
            write_manifest(self.trace_dir,
                           meta={"kind": "profile",
                                 "captured_unix": time.time()})
        except Exception:       # noqa: BLE001 — listing-only nicety
            pass
        self._event(telemetry, "profile_captured",
                    trace_dir=self.trace_dir)
        if self.log is not None:
            self.log(f"obs: profile captured -> {self.trace_dir}")

    @staticmethod
    def _event(telemetry, kind: str, **fields) -> None:
        if telemetry is not None:
            try:
                telemetry.record_event(kind, **fields)
            except Exception:   # noqa: BLE001
                pass
